"""VERDICT r4 #9 probe: separate K-effect from P-effect in the RS
kernel column-rate spread.  Measures the fused kernel at the two real
schemes plus the two synthetic cross schemes RS(10,3)/RS(8,4):
if column rate tracks K (80 vs 64 contraction rows), the spread is
shape-structural; if it tracks P, it's output-rows-bound."""

import json
import sys

import jax
import jax.numpy as jnp
import numpy as np

from bench import _make_timed
from seaweedfs_tpu.ops import rs_bitmatrix
from seaweedfs_tpu.ops.coder_jax import plane_major
from seaweedfs_tpu.ops.coder_numpy import NumpyCoder
from seaweedfs_tpu.ops.coder_pallas import apply_bitmatrix_pallas

N = 64 * 1024 * 1024
BLOCK = 65536


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def main():
    dev = jax.devices()[0]
    log(f"device: {dev}")
    timed = _make_timed()
    key = jax.random.PRNGKey(0)
    out = {}
    # (k, r): real schemes + synthetic cross probes
    for k, r in ((10, 4), (8, 3), (10, 3), (8, 4), (12, 4), (14, 4)):
        total = k + r
        pm = jnp.asarray(plane_major(
            rs_bitmatrix.parity_bitmatrix(k, total, "cauchy"), r, k),
            jnp.float32)
        data = jax.random.randint(key, (k, N), 0, 256,
                                  dtype=jnp.int32).astype(jnp.uint8)
        jax.block_until_ready(data)
        want = NumpyCoder(k, r, matrix_kind="cauchy").encode(
            np.asarray(data[:, :BLOCK]))
        got = np.asarray(apply_bitmatrix_pallas(
            pm, data[:, :BLOCK], r, k, block_n=BLOCK, mm="int8"))
        assert np.array_equal(got, want), f"RS({k},{r}) wrong"
        dt = timed(apply_bitmatrix_pallas, pm, data, r, k,
                   block_n=BLOCK, mm="int8")
        mbps = data.nbytes / dt / 1e6
        cols = (N / dt) / 1e9
        pct = cols / 6.0 * 100
        log(f"RS({k:2d},{r}) int8: {mbps:8.0f} MB/s  "
            f"{cols:.2f}e9 cols/s  {pct:.0f}% of cap  (8K={8*k}, 8P={8*r})")
        out[f"rs{k}_{r}"] = {"mbps": round(mbps, 1),
                             "cols_e9": round(cols, 2),
                             "pct_cap": round(pct, 1)}
        del data
    print(json.dumps(out))


if __name__ == "__main__":
    main()
