"""Degraded EC reads must fan out in parallel.

The reference launches one goroutine per shard when reconstructing a
missing interval (store_ec.go:322-376), so degraded-read latency is the
slowest single shard fetch — not the sum of up to 13 sequential
round-trips.  These tests inject a per-holder delay into the shard_read
RPC and assert the wall-clock stays near one delay, plus unit-check the
tiered location-cache freshness (store_ec.go:221-229).
"""

import time

import pytest

from seaweedfs_tpu.cluster import rpc
from seaweedfs_tpu.cluster.client import WeedClient
from seaweedfs_tpu.cluster.volume_server import VolumeServer

from test_cluster_ec_distributed import _spread, cluster  # noqa: F401

DELAY = 0.4


def test_loc_ttl_tiers():
    urls = ["h1:1"]
    too_few = {s: urls for s in range(9)}
    incomplete = {s: urls for s in range(12)}
    full = {s: urls for s in range(14)}
    assert VolumeServer._loc_ttl(too_few) == 11.0
    assert VolumeServer._loc_ttl(incomplete) == 7 * 60.0
    assert VolumeServer._loc_ttl(full) == 37 * 60.0
    assert VolumeServer._loc_ttl({}) == 11.0


def test_degraded_read_latency_is_one_fetch(cluster, monkeypatch):  # noqa: F811
    master, servers = cluster
    client = WeedClient(master.url())
    vid, fids = _spread(master, servers, client)
    # Lose shards 0-3 (server 0 keeps only shard 4): a read of any data
    # interval from server 2 (parity-only holder) must reconstruct from
    # 10 sources, 6 of them remote.
    rpc.call_json(f"http://{servers[0].url()}/admin/ec/delete_shards",
                  "POST", {"volume": vid, "shards": [0, 1, 2, 3]})
    for vs in servers:
        vs._send_heartbeat(full=True)
        vs._ec_loc_cache.clear()

    real_call = rpc.call
    fetches = []

    def slow_call(url, *args, **kwargs):
        if "/admin/ec/shard_read" in url:
            fetches.append(url)
            time.sleep(DELAY)
        return real_call(url, *args, **kwargs)

    monkeypatch.setattr(rpc, "call", slow_call)
    t0 = time.monotonic()
    data = rpc.call(f"http://{servers[2].url()}/{fids[0]}")
    elapsed = time.monotonic() - t0
    assert bytes(data) == b"payload-zero"
    remote_fetches = len(fetches)
    assert remote_fetches >= 5, fetches
    serial_floor = remote_fetches * DELAY
    # Parallel fan-out: one delay for the gather (plus scheduling slack);
    # far below the serial sum.
    assert elapsed < min(serial_floor * 0.6, serial_floor - 2 * DELAY), (
        f"degraded read took {elapsed:.2f}s for {remote_fetches} remote "
        f"fetches (serial would be >= {serial_floor:.2f}s)")


def test_failed_reconstruction_drops_location_cache(cluster):  # noqa: F811
    master, servers = cluster
    client = WeedClient(master.url())
    vid, fids = _spread(master, servers, client)
    # Drop 5 shards cluster-wide -> only 9 survive -> reconstruction
    # fails AND the server forgets the now-useless location map so the
    # next read refreshes immediately.
    rpc.call_json(f"http://{servers[0].url()}/admin/ec/delete_shards",
                  "POST", {"volume": vid, "shards": [0, 1, 2, 3, 4]})
    for vs in servers:
        vs._send_heartbeat(full=True)
        vs._ec_loc_cache.clear()
    with pytest.raises(rpc.RpcError):
        rpc.call(f"http://{servers[1].url()}/{fids[0]}")
    assert vid not in servers[1]._ec_loc_cache
