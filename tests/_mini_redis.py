"""In-process mini-RESP2 server for RedisStore tests — the same
no-server-needed pattern as the fake Kafka broker in
test_kafka_queue.py.  Implements just the command set
universal_redis_store.go uses (SET[+EX]/GET/DEL/SADD/SREM/SMEMBERS)
plus AUTH/SELECT/PING, with lazy key expiry."""

from __future__ import annotations

import socket
import threading
import time


class MiniRedis:
    def __init__(self, password: str = ""):
        self.password = password
        self.dbs: dict[int, dict] = {}
        self.expiry: dict[tuple[int, bytes], float] = {}
        self.lock = threading.Lock()
        self.commands_seen: list[list[bytes]] = []
        self._sock = socket.create_server(("127.0.0.1", 0))
        self.port = self._sock.getsockname()[1]
        self._running = True
        threading.Thread(target=self._accept, daemon=True).start()

    def _accept(self):
        while self._running:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _db(self, n: int) -> dict:
        return self.dbs.setdefault(n, {})

    def _serve(self, conn):
        rf = conn.makefile("rb")
        db = 0
        authed = not self.password
        try:
            while True:
                line = rf.readline()
                if not line:
                    return
                assert line[:1] == b"*", line
                nargs = int(line[1:])
                args = []
                for _ in range(nargs):
                    ln = rf.readline()
                    assert ln[:1] == b"$"
                    n = int(ln[1:])
                    args.append(rf.read(n + 2)[:-2])
                cmd = args[0].upper()
                with self.lock:
                    self.commands_seen.append(args)
                    if cmd == b"AUTH":
                        if args[1].decode() == self.password:
                            authed = True
                            conn.sendall(b"+OK\r\n")
                        else:
                            conn.sendall(b"-ERR invalid password\r\n")
                        continue
                    if not authed:
                        conn.sendall(b"-NOAUTH Authentication required."
                                     b"\r\n")
                        continue
                    conn.sendall(self._run(db, cmd, args))
                    if cmd == b"SELECT":
                        db = int(args[1])
        except (OSError, AssertionError, ValueError):
            pass
        finally:
            conn.close()

    def _expired(self, db: int, key: bytes) -> bool:
        exp = self.expiry.get((db, key))
        if exp is not None and time.time() > exp:
            self._db(db).pop(key, None)
            self.expiry.pop((db, key), None)
            return True
        return False

    def _run(self, db: int, cmd: bytes, args: list[bytes]) -> bytes:
        d = self._db(db)
        if cmd == b"PING":
            return b"+PONG\r\n"
        if cmd == b"SELECT":
            return b"+OK\r\n"
        if cmd == b"SET":
            d[args[1]] = args[2]
            self.expiry.pop((db, args[1]), None)
            if len(args) >= 5 and args[3].upper() == b"EX":
                self.expiry[(db, args[1])] = time.time() + int(args[4])
            return b"+OK\r\n"
        if cmd == b"GET":
            if self._expired(db, args[1]):
                return b"$-1\r\n"
            v = d.get(args[1])
            if v is None or isinstance(v, set):
                return b"$-1\r\n"
            return b"$%d\r\n%s\r\n" % (len(v), v)
        if cmd == b"DEL":
            n = 0
            for k in args[1:]:
                if d.pop(k, None) is not None:
                    n += 1
                self.expiry.pop((db, k), None)
            return b":%d\r\n" % n
        if cmd == b"SADD":
            s = d.setdefault(args[1], set())
            n = 0
            for m in args[2:]:
                if m not in s:
                    s.add(m)
                    n += 1
            return b":%d\r\n" % n
        if cmd == b"SREM":
            s = d.get(args[1], set())
            n = 0
            for m in args[2:]:
                if m in s:
                    s.discard(m)
                    n += 1
            return b":%d\r\n" % n
        if cmd == b"SMEMBERS":
            s = d.get(args[1], set())
            out = b"*%d\r\n" % len(s)
            for m in sorted(s):
                out += b"$%d\r\n%s\r\n" % (len(m), m)
            return out
        return b"-ERR unknown command '%s'\r\n" % cmd

    def close(self):
        self._running = False
        try:
            self._sock.close()
        except OSError:
            pass
