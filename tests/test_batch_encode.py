"""Mesh-batched multi-volume EC encode driven through the shell.

`ec.encode -batch` must pull the quiet volumes' .dat/.idx from their
servers, encode MANY volumes in mesh-batched compiled steps (volumes
data-parallel over the 8-device virtual mesh), scatter the 14 shards +
.ecx across the cluster, mount them, delete the originals — and the
shard bytes must be byte-identical to the local single-volume encoder
(`write_ec_files`, the golden-gate layout).

Reference behavior matched: weed/shell/command_ec_encode.go:92-264
(mark readonly → generate → spread → delete), batched per SURVEY §2.3's
"shard scatter after encode" mapping.
"""

import os

import pytest

from seaweedfs_tpu.cluster import rpc
from seaweedfs_tpu.cluster.client import WeedClient
from seaweedfs_tpu.cluster.master import MasterServer
from seaweedfs_tpu.cluster.volume_server import VolumeServer
from seaweedfs_tpu.ec import TOTAL_SHARDS, to_ext
from seaweedfs_tpu.ec.encoder import (write_ec_files,
                                      write_sorted_file_from_idx)
from seaweedfs_tpu.shell import CommandEnv, run_command


@pytest.fixture
def cluster(tmp_path):
    master = MasterServer(volume_size_limit_mb=64,
                          meta_dir=str(tmp_path),
                          # Volume servers here pulse every 60s:
                          # the master's dead-node threshold
                          # (2x its own pulse) must outlast a
                          # slow-machine encode, or the sweep
                          # empties the topology mid-test.
                          pulse_seconds=60)
    master.start()
    servers = []
    for i in range(3):
        d = tmp_path / f"vs{i}"
        d.mkdir()
        vs = VolumeServer(master.url(), [str(d)], pulse_seconds=60)
        vs.start()
        servers.append(vs)
    yield master, servers
    for vs in servers:
        vs.stop()
    master.stop()


def _freshen(servers):
    for vs in servers:
        vs._send_heartbeat(full=True)
        vs._ec_loc_cache.clear()


def _fill_volumes(master, n_volumes=3, objs_per_volume=6):
    client = WeedClient(master.url())
    rpc.call_json(f"{master.url()}/vol/grow?count={n_volumes}", "POST")
    by_vid: dict[int, list] = {}
    i = 0
    while any(len(v) < objs_per_volume
              for v in by_vid.values()) or len(by_vid) < n_volumes:
        payload = f"batch-encode-{i}".encode() * (i % 9 + 1)
        fid = client.upload_data(payload)
        by_vid.setdefault(int(fid.split(",")[0]), []).append(
            (payload, fid))
        i += 1
        if i > 400:
            break
    vids = sorted(by_vid)[:n_volumes]
    return client, {vid: by_vid[vid] for vid in vids}


def test_batch_encode_through_shell(cluster, tmp_path):
    master, servers = cluster
    client, volumes = _fill_volumes(master, n_volumes=3)
    vids = sorted(volumes)
    env = CommandEnv(master.url())
    _freshen(servers)

    # Expected shards: pull each .dat/.idx and run the LOCAL encoder —
    # the batch path must produce byte-identical outputs.
    expect_dir = tmp_path / "expected"
    expect_dir.mkdir()
    expected: dict[int, dict[int, bytes]] = {}
    ecx: dict[int, bytes] = {}
    for vid in vids:
        url = env.volume_locations(vid)[0]
        base = str(expect_dir / str(vid))
        rpc.call_to_file(f"http://{url}/admin/volume_file?volume={vid}"
                         "&ext=.dat", base + ".dat")
        rpc.call_to_file(f"http://{url}/admin/volume_file?volume={vid}"
                         "&ext=.idx", base + ".idx")
        write_ec_files(base)
        write_sorted_file_from_idx(base)
        expected[vid] = {
            s: open(base + to_ext(s), "rb").read()
            for s in range(TOTAL_SHARDS)}
        ecx[vid] = open(base + ".ecx", "rb").read()

    run_command(env, "lock")
    out = run_command(
        env, "ec.encode -volumeId " + ",".join(map(str, vids))
        + " -batch")
    for vid in vids:
        assert f"volume {vid} -> ec shards" in out, out

    _freshen(servers)
    for vid, pairs in volumes.items():
        # Original volume gone everywhere; 14 shards live + mounted.
        assert env.volume_locations(vid) == []
        locs = env.ec_shard_locations(vid)
        assert sorted(locs) == list(range(TOTAL_SHARDS)), \
            f"volume {vid}: {sorted(locs)}"
        # Byte-identity vs the local encoder, shard by shard (+ .ecx).
        for sid in range(TOTAL_SHARDS):
            got = bytes(rpc.call(
                f"http://{locs[sid][0]}/admin/ec/shard_file?"
                f"volume={vid}&shard={sid}"))
            assert got == expected[vid][sid], \
                f"volume {vid} shard {sid} differs from local encode"
        got_ecx = bytes(rpc.call(
            f"http://{locs[0][0]}/admin/ec/shard_file?"
            f"volume={vid}&ext=.ecx"))
        assert got_ecx == ecx[vid]
        # Every object reads back through the EC path.
        for payload, fid in pairs:
            assert bytes(client.download(fid)) == payload
    env.close()


def test_batch_encode_skips_missing_volume(cluster):
    master, servers = cluster
    client, volumes = _fill_volumes(master, n_volumes=1)
    vid = next(iter(volumes))
    env = CommandEnv(master.url())
    _freshen(servers)
    run_command(env, "lock")
    out = run_command(env, f"ec.encode -volumeId 9999,{vid} -batch")
    assert "volume 9999: SKIPPED" in out
    assert f"volume {vid} -> ec shards" in out
    env.close()
