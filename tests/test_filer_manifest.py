"""Chunk manifests + hardlinks (reference weed/filer/filechunk_manifest.go
and filerstore_hardlink.go).

Manifests: huge chunk lists collapse into manifest chunks on write and
resolve lazily on read; deleting the file frees BOTH the manifest blobs
and the inner chunks.  Hardlinks: multiple paths share one KV-backed
content record; writes through any name are visible via all, and the
chunks are freed only when the last link goes.
"""

import json
import time
import urllib.request

import pytest

from seaweedfs_tpu.filer.entry import Attributes, Entry, FileChunk
from seaweedfs_tpu.filer.filechunk_manifest import (
    MANIFEST_BATCH,
    has_chunk_manifest,
    maybe_manifestize,
    resolve_chunk_manifest,
)
from seaweedfs_tpu.filer.filer import Filer
from seaweedfs_tpu.filer.filerstore import MemoryStore, NotFound

from test_filer_server import _req, stack  # noqa: F401


# -- pure manifest algebra ---------------------------------------------------


class _BlobStore:
    """In-memory save/fetch pair standing in for the volume store."""

    def __init__(self):
        self.blobs = {}
        self.n = 0

    def save(self, data: bytes) -> FileChunk:
        self.n += 1
        fid = f"m{self.n}"
        self.blobs[fid] = bytes(data)
        return FileChunk(file_id=fid, offset=0, size=len(data),
                         mtime=time.time_ns())

    def fetch(self, fid: str, cipher_key: str = "") -> bytes:
        return self.blobs[fid]


def _chunks(n, size=10):
    return [FileChunk(file_id=f"c{i}", offset=i * size, size=size,
                      mtime=i + 1) for i in range(n)]


def test_manifestize_roundtrip():
    bs = _BlobStore()
    chunks = _chunks(2500)
    out = maybe_manifestize(bs.save, chunks)
    # 2500 = two full 1000-batches + 500 raw remainder
    manifests = [c for c in out if c.is_chunk_manifest]
    raw = [c for c in out if not c.is_chunk_manifest]
    assert len(manifests) == 2 and len(raw) == 500
    assert manifests[0].offset == 0
    assert manifests[0].size == 1000 * 10
    assert has_chunk_manifest(out)
    data, mchunks = resolve_chunk_manifest(bs.fetch, out)
    assert [c.file_id for c in data] == [c.file_id for c in chunks]
    assert [c.offset for c in data] == [c.offset for c in chunks]
    assert {c.file_id for c in mchunks} == {c.file_id for c in manifests}


def test_manifestize_below_batch_is_noop():
    bs = _BlobStore()
    chunks = _chunks(MANIFEST_BATCH - 1)
    assert maybe_manifestize(bs.save, chunks) == chunks
    assert not bs.blobs


def test_existing_manifests_pass_through():
    bs = _BlobStore()
    level1 = maybe_manifestize(bs.save, _chunks(2000))
    assert all(c.is_chunk_manifest for c in level1)
    # Re-manifestizing never wraps manifest chunks again
    # (doMaybeManifestize only merges data chunks).
    assert maybe_manifestize(bs.save, level1, merge_factor=2) == level1


def test_nested_manifests_resolve():
    bs = _BlobStore()
    level1 = maybe_manifestize(bs.save, _chunks(2000))  # 2 manifests
    # A manifest whose body references other manifests (e.g. replayed
    # by filer.sync) must resolve recursively.
    outer = json.dumps(
        {"chunks": [c.to_dict() for c in level1]}).encode()
    outer_chunk = bs.save(outer)
    outer_chunk.is_chunk_manifest = True
    outer_chunk.offset, outer_chunk.size = 0, 2000 * 10
    data, mchunks = resolve_chunk_manifest(bs.fetch, [outer_chunk])
    assert len(data) == 2000
    assert len(mchunks) == 3  # 1 outer + 2 inner


# -- filer-level hardlinks ---------------------------------------------------


@pytest.fixture
def filer():
    freed = []
    f = Filer(store=MemoryStore(), delete_file_id_fn=freed.extend)
    f.freed = freed
    yield f
    f.close()


def _file(path, fids):
    return Entry(path=path, attributes=Attributes(mode=0o644),
                 chunks=[FileChunk(file_id=fid, offset=i * 4, size=4,
                                   mtime=i + 1)
                         for i, fid in enumerate(fids)])


def test_hardlink_share_and_release(filer):
    filer.create_entry(_file("/a", ["f1", "f2"]))
    link = filer.create_hardlink("/a", "/b")
    assert link.hard_link_id
    a, b = filer.find_entry("/a"), filer.find_entry("/b")
    assert a.hard_link_id == b.hard_link_id
    assert a.hard_link_counter == b.hard_link_counter == 2
    assert [c.file_id for c in b.chunks] == ["f1", "f2"]
    # delete one name: chunks must survive
    filer.delete_entry("/a")
    filer.flush_deletions()
    assert filer.freed == []
    b = filer.find_entry("/b")
    assert b.hard_link_counter == 1
    # delete the last name: chunks freed
    filer.delete_entry("/b")
    filer.flush_deletions()
    assert sorted(filer.freed) == ["f1", "f2"]


def test_hardlink_write_through_any_name(filer):
    filer.create_entry(_file("/a", ["f1"]))
    filer.create_hardlink("/a", "/b")
    # overwrite through /b (open(O_TRUNC) semantics)
    filer.create_entry(_file("/b", ["f9"]))
    a = filer.find_entry("/a")
    assert [c.file_id for c in a.chunks] == ["f9"]
    assert a.hard_link_counter == 2
    filer.flush_deletions()
    assert filer.freed == ["f1"]  # replaced content freed once


def test_hardlink_counts_three_names(filer):
    filer.create_entry(_file("/a", ["f1"]))
    filer.create_hardlink("/a", "/b")
    filer.create_hardlink("/b", "/c")
    assert filer.find_entry("/c").hard_link_counter == 3
    filer.delete_entry("/b")
    filer.delete_entry("/c")
    filer.flush_deletions()
    assert filer.freed == []
    assert filer.find_entry("/a").hard_link_counter == 1


def test_stale_client_counter_cannot_clobber(filer):
    """A client replaying a cached entry (stale hard_link_counter) must
    not overwrite the live link count — the store-side doc is
    authoritative (review finding: stale FUSE chmod after a third link
    would otherwise free shared chunks while /a still exists)."""
    filer.create_entry(_file("/a", ["f1"]))
    filer.create_hardlink("/a", "/b")          # counter 2
    cached = filer.find_entry("/a")            # client caches (counter 2)
    filer.create_hardlink("/a", "/c")          # counter 3
    cached.attributes.mode = 0o600
    filer.create_entry(cached)                 # replay stale entry
    assert filer.find_entry("/b").hard_link_counter == 3
    filer.delete_entry("/b")
    filer.delete_entry("/c")
    filer.flush_deletions()
    assert filer.freed == []                   # /a still holds content
    a = filer.find_entry("/a")
    assert [c.file_id for c in a.chunks] == ["f1"]
    assert a.attributes.mode == 0o600          # the chmod did land


def test_first_link_conversion_emits_event(filer):
    """Converting src to the KV-backed form is a mutation subscribers
    must see — replicas otherwise keep a plain entry and would free
    shared chunks when src is deleted on their side."""
    filer.create_entry(_file("/a", ["f1"]))
    seen = []
    filer.subscribe(lambda ev: seen.append(ev))
    filer.create_hardlink("/a", "/b")
    src_events = [ev for ev in seen
                  if ev.new_entry and ev.new_entry.path == "/a"]
    assert src_events and src_events[-1].new_entry.hard_link_id


def test_hardlink_doc_repair_on_missing_kv(filer):
    """An entry whose KV doc vanished (lost KV plane) must not 500 —
    the next link re-seeds the doc from the entry."""
    filer.create_entry(_file("/a", ["f1"]))
    filer.create_hardlink("/a", "/b")
    hid = filer.find_entry("/a").hard_link_id
    filer.store.kv_delete(Filer._HL_PREFIX + hid)
    link = filer.create_hardlink("/a", "/c")
    # Re-seeded from /a's stored row (counter 1 at conversion time) +1.
    # The true count is unknowable once the doc is lost; the repair
    # restores service rather than 500ing.
    assert link.hard_link_counter == 2
    assert [c.file_id for c in filer.find_entry("/c").chunks] == ["f1"]


def test_hardlink_rejects_directory_and_existing(filer):
    from seaweedfs_tpu.filer.filer import FilerError
    filer.create_entry(Entry(path="/d", is_directory=True))
    filer.create_entry(_file("/a", ["f1"]))
    with pytest.raises(FilerError):
        filer.create_hardlink("/d", "/link")
    with pytest.raises(FilerError):
        filer.create_hardlink("/a", "/d")
    with pytest.raises(NotFound):
        filer.create_hardlink("/missing", "/x")


def test_recursive_delete_releases_links(filer):
    filer.create_entry(_file("/dir/a", ["f1"]))
    filer.create_hardlink("/dir/a", "/keep")
    filer.delete_entry("/dir", recursive=True)
    filer.flush_deletions()
    assert filer.freed == []  # /keep still references the content
    assert [c.file_id for c in filer.find_entry("/keep").chunks] == ["f1"]
    filer.delete_entry("/keep")
    filer.flush_deletions()
    assert filer.freed == ["f1"]


# -- server-level e2e --------------------------------------------------------


def test_server_manifest_roundtrip(stack):  # noqa: F811
    _m, _vs, filer_srv = stack
    # chunk_size=64 -> 1200 chunks -> one 1000-chunk manifest + 200 raw
    body = bytes(range(256)) * 300  # 76,800 bytes
    _req(filer_srv, "/big/manifest.bin", "POST", body).read()
    meta = json.loads(
        _req(filer_srv, "/big/manifest.bin?metadata=true").read())
    chunks = meta["chunks"]
    manifests = [c for c in chunks if c.get("is_chunk_manifest")]
    assert len(manifests) == 1
    assert len(chunks) == 1 + 200
    assert manifests[0]["offset"] == 0
    assert manifests[0]["size"] == 1000 * 64
    # lazy resolution serves the full content and ranges
    with _req(filer_srv, "/big/manifest.bin") as r:
        assert r.read() == body
    with _req(filer_srv, "/big/manifest.bin",
              headers={"Range": "bytes=63900-64100"}) as r:
        assert r.read() == body[63900:64101]
    # deletion frees manifest blob AND inner chunks
    inner_fids = {c["file_id"] for c in chunks if
                  not c.get("is_chunk_manifest")}
    _req(filer_srv, "/big/manifest.bin", "DELETE").read()
    import seaweedfs_tpu.filer.filer as filer_mod  # noqa: F401
    with filer_srv.filer._del_lock:
        # the queue holds (fid, deleting-tenant) pairs
        pending = {fid for fid, _tenant in
                   filer_srv.filer._pending_deletions}
    assert manifests[0]["file_id"] in pending
    assert len(pending) == 1201  # 1000 resolved + 200 raw + 1 manifest
    assert inner_fids <= pending


def test_server_hardlink_over_http(stack):  # noqa: F811
    _m, _vs, filer_srv = stack
    body = b"hardlink content " * 8
    _req(filer_srv, "/hl/src.txt", "POST", body).read()
    out = json.loads(_req(filer_srv, "/hl/dst.txt?hardlink.from=/hl/src.txt",
                          "POST", b"").read())
    assert out["hard_link_id"]
    with _req(filer_srv, "/hl/dst.txt") as r:
        assert r.read() == body
    _req(filer_srv, "/hl/src.txt", "DELETE").read()
    with _req(filer_srv, "/hl/dst.txt") as r:
        assert r.read() == body
    meta = json.loads(_req(filer_srv, "/hl/dst.txt?metadata=true").read())
    assert meta["hard_link_counter"] == 1
    # 404 on a missing source
    with pytest.raises(urllib.request.HTTPError):
        _req(filer_srv, "/hl/x?hardlink.from=/hl/missing", "POST", b"")
