"""Front-door network core (netcore/): the transport matrix.

Every request-handling behavior the PR 5 overload plane pinned —
admission sheds, exemptions, phase budgets, trace propagation — must be
byte-identical under `-transport=threads` (thread-per-connection) and
`-transport=aio` (event loop + bounded worker pool), because the aio
loop hands complete requests to the SAME `_serve_one`.  Plus the rest
of the front door: zero-copy sendfile as the default volume read path
(vs buffered byte-identity, ranges, conditionals, TLS fallback), the
filer chunk cache (singleflight, bounded bytes), the direct
volume→client proxy leg, and small-file packing (shared-needle
roundtrip, sibling-safe deletes, vacuum interaction).
"""

import os
import socket
import threading
import time

import pytest

from seaweedfs_tpu.cluster import rpc
from seaweedfs_tpu.cluster.client import WeedClient
from seaweedfs_tpu.cluster.master import MasterServer
from seaweedfs_tpu.cluster.volume_server import VolumeServer
from seaweedfs_tpu.core import types as t
from seaweedfs_tpu.filer.server import FilerServer
from seaweedfs_tpu.stats.promcheck import validate_exposition
from seaweedfs_tpu.storage.chunk_cache import FilerChunkCache
from seaweedfs_tpu.trace import tracer

pytestmark = pytest.mark.frontdoor

TRANSPORTS = ("threads", "aio")


# -- transport matrix: the overload plane behaves identically ---------------

@pytest.mark.parametrize("transport", TRANSPORTS)
def test_admission_shed_and_exemption(transport):
    """A saturated lane sheds with 429 + Retry-After on BOTH
    transports, and /debug/ surfaces stay admission-exempt (reachable
    while the read lane is pinned)."""
    server = rpc.JsonHttpServer(
        transport=transport,
        admission=rpc.AdmissionControl(1, queue_depth=0,
                                       queue_timeout=0.1))
    gate = threading.Event()
    server.route("GET", "/work", lambda q, b: (gate.wait(5.0),
                                               {"ok": True})[1])
    server.start()
    base = f"http://127.0.0.1:{server.port}"
    results: list = []

    def one():
        try:
            results.append(("ok", rpc.call(f"{base}/work",
                                           timeout=10.0)))
        except rpc.RpcError as e:
            results.append(("shed", e))

    try:
        threads = [threading.Thread(target=one) for _ in range(5)]
        for th in threads:
            th.start()
        time.sleep(0.4)  # one holds the slot; the rest shed
        # Exempt debug surface answers while the lane is pinned.
        snap = rpc.call(f"{base}/debug/conns", timeout=5.0)
        assert snap["transport"] == transport
        gate.set()
        for th in threads:
            th.join()
    finally:
        server.stop()
    sheds = [e for kind, e in results if kind == "shed"]
    oks = [r for kind, r in results if kind == "ok"]
    assert sheds and oks
    for e in sheds:
        assert e.status == 429 and e.retry_after is not None


@pytest.mark.parametrize("transport", TRANSPORTS)
def test_phase_budget_rides_exemplar(transport):
    """The time-attribution plane is transport-independent: a slow
    request's exemplar carries its phase budget on aio exactly as on
    threads (workers run the same `_dispatch`)."""
    from seaweedfs_tpu.stats import phases
    server = rpc.JsonHttpServer(transport=transport)

    def slowop(q, b):
        with phases.phase("disk"):
            time.sleep(0.2)
        time.sleep(0.08)
        return {"ok": True}

    server.route("GET", "/slowop", slowop)
    server.enable_metrics(f"fd_{transport}")
    server.start()
    try:
        assert rpc.call(
            f"http://127.0.0.1:{server.port}/slowop") == {"ok": True}
        ex = server.slo.exemplars()
        assert ex, "0.28s request must exemplar (threshold 0.25)"
        ph = ex[0]["phases"]
        assert 0.15 <= ph["disk"] <= 0.3
        assert ph.get("handler", 0) > 0.04
    finally:
        server.stop()


@pytest.mark.parametrize("transport", TRANSPORTS)
def test_trace_propagation(transport, monkeypatch):
    """An inbound traceparent links the server span to the caller's
    trace on both transports."""
    monkeypatch.setenv("SEAWEEDFS_TPU_TRACES", "1")
    tracer.BUFFER.clear()
    server = rpc.JsonHttpServer(transport=transport)
    server.route("GET", "/traced", lambda q, b: {"ok": True})
    from seaweedfs_tpu.trace import setup_server_tracing
    setup_server_tracing(server, "fdsvc")
    server.start()
    try:
        with tracer.root_span("client.op", "testclient") as root:
            assert rpc.call(
                f"http://127.0.0.1:{server.port}/traced",
                headers={tracer.TRACEPARENT_HEADER: root.traceparent()}
            ) == {"ok": True}
            trace_id = root.trace_id
        spans = tracer.BUFFER.get(trace_id)
        assert spans, "server span missing from the caller's trace"
        srv = [s for s in spans if s["service"] == "fdsvc"]
        assert srv and srv[0]["name"] == "GET /traced"
    finally:
        server.stop()
        tracer.BUFFER.clear()


@pytest.mark.parametrize("transport", TRANSPORTS)
def test_drain_refuses_writes_on_both_transports(transport, tmp_path):
    """PR 5's drain lifecycle under either network core: after drain,
    new writes get 503 + Retry-After while reads keep working."""
    master = MasterServer(volume_size_limit_mb=64,
                          meta_dir=str(tmp_path))
    master.start()
    vs = VolumeServer(master.url(), [str(tmp_path / "vs")],
                      pulse_seconds=60, transport=transport)
    vs.start()
    client = WeedClient(master.url())
    try:
        fid = client.upload_data(b"pre-drain bytes")
        vid = t.parse_file_id(fid)[0]
        # Capture the direct URL first: the drain's goodbye heartbeat
        # unregisters the node from the master immediately.
        loc = client.lookup(vid)[0]["url"]
        url = f"http://{loc}/{fid}"
        vs.drain(grace=1.0)
        assert bytes(rpc.call(url, timeout=5.0)) == b"pre-drain bytes"
        with pytest.raises(rpc.RpcError) as ei:
            rpc.call(url, "POST", b"post-drain write", timeout=5.0)
        assert ei.value.status == 503
        assert ei.value.retry_after is not None
    finally:
        vs.stop()
        master.stop()


@pytest.mark.parametrize("transport", TRANSPORTS)
def test_pipelined_keepalive_requests(transport):
    """Two requests written back-to-back before reading: the aio loop
    must replay buffered leftover bytes after a handoff returns the
    socket (the pipelining path threads get for free)."""
    server = rpc.JsonHttpServer(transport=transport)
    server.route("GET", "/a", lambda q, b: {"n": 1})
    server.route("GET", "/b", lambda q, b: {"n": 2})
    server.start()
    try:
        s = socket.create_connection(("127.0.0.1", server.port),
                                     timeout=5.0)
        s.sendall(b"GET /a HTTP/1.1\r\nHost: x\r\n\r\n"
                  b"GET /b HTTP/1.1\r\nHost: x\r\n\r\n")
        buf = b""
        deadline = time.time() + 5.0
        while buf.count(b"HTTP/1.1 200") < 2 and time.time() < deadline:
            buf += s.recv(65536)
        assert b'{"n": 1}' in buf and b'{"n": 2}' in buf
        s.close()
    finally:
        server.stop()


def test_env_default_transport(monkeypatch):
    """SEAWEEDFS_TPU_TRANSPORT=aio flips every JsonHttpServer that
    doesn't pass transport= explicitly — the whole-suite toggle
    conftest's header advertises."""
    monkeypatch.setenv("SEAWEEDFS_TPU_TRANSPORT", "aio")
    server = rpc.JsonHttpServer()
    server.route("GET", "/t", lambda q, b: {"ok": True})
    server.start()
    try:
        base = f"http://127.0.0.1:{server.port}"
        assert rpc.call(f"{base}/t") == {"ok": True}
        assert rpc.call(f"{base}/debug/conns")["transport"] == "aio"
    finally:
        server.stop()


# -- /debug/conns + the open-connections gauge ------------------------------

@pytest.mark.parametrize("transport", TRANSPORTS)
def test_debug_conns_and_gauge(transport):
    server = rpc.JsonHttpServer(transport=transport)
    server.route("GET", "/t", lambda q, b: {"ok": True})
    reg = server.enable_metrics(f"connrole_{transport}")
    server.start()
    try:
        base = f"http://127.0.0.1:{server.port}"
        assert rpc.call(f"{base}/t") == {"ok": True}
        snap = rpc.call(f"{base}/debug/conns")
        assert snap["transport"] == transport
        assert snap["open"] >= 1  # at least the conn asking
        assert sum(snap["states"].values()) == snap["open"]
        c = snap["conns"][0]
        for k in ("peer", "state", "age_s", "idle_s", "requests"):
            assert k in c, c
        text = reg.expose()
        assert "SeaweedFS_open_connections{" in text
        assert f'role="connrole_{transport}"' in text
        assert validate_exposition(text) == []
    finally:
        server.stop()


# -- zero-copy sendfile as the default volume read path ---------------------

@pytest.fixture()
def needle_cluster(tmp_path):
    master = MasterServer(volume_size_limit_mb=64,
                          meta_dir=str(tmp_path))
    master.start()
    vs = VolumeServer(master.url(), [str(tmp_path / "vs")],
                      pulse_seconds=60)
    vs.start()
    client = WeedClient(master.url())
    try:
        yield master, vs, client
    finally:
        vs.stop()
        master.stop()


def _raw_get(url, headers=None):
    resp, conn = rpc._request(url, "GET", None, 10.0,
                              req_headers=headers)
    try:
        body = resp.read()
    finally:
        rpc._finish(conn, resp)
    return resp.status, dict(resp.headers), body


def test_sendfile_vs_buffered_byte_identity(needle_cluster):
    """The promoted default (sendfile for any whole-needle GET >= 4KB)
    answers byte-for-byte what the buffered path answers — body,
    status, ETag, Content-Length — for whole reads, ranges, and
    conditional requests."""
    _m, vs, client = needle_cluster
    data = os.urandom(48 * 1024)
    fid = client.upload_data(data)
    url = f"{vs.server.url()}/{fid}"
    cases = [
        (None, 200),
        ({"Range": "bytes=0-9"}, 206),
        ({"Range": "bytes=1000-30000"}, 206),
        ({"Range": "bytes=47000-"}, 206),
    ]
    assert vs.sendfile_min == 4096  # promoted default
    results = {}
    for mode, minv in (("sendfile", 4096), ("buffered", 0)):
        vs.sendfile_min = minv
        for hdrs, want_status in cases:
            st, h, body = _raw_get(url, hdrs)
            assert st == want_status, (mode, hdrs, st)
            key = (tuple(sorted((hdrs or {}).items())),)
            results.setdefault(key, []).append(
                (st, body, h.get("etag"), h.get("content-length"),
                 h.get("content-range")))
    for key, pair in results.items():
        assert pair[0] == pair[1], f"sendfile != buffered for {key}"
    # Conditional: If-None-Match on the ETag answers 304 on both paths.
    _st, h, _b = _raw_get(url)
    etag = h["etag"]
    for minv in (4096, 0):
        vs.sendfile_min = minv
        st, _h, body = _raw_get(url, {"If-None-Match": etag})
        assert st == 304 and body == b""


def test_sendfile_small_needle_took_slice_path(needle_cluster,
                                               monkeypatch):
    """8KB — far below any large-object special-casing — now rides
    the zero-copy slice path by default (SENDFILE_MIN is one page)."""
    from seaweedfs_tpu.storage.volume import Volume
    _m, vs, client = needle_cluster
    data = os.urandom(8 * 1024)
    fid = client.upload_data(data)
    vid = t.parse_file_id(fid)[0]
    loc = client.lookup(vid)[0]["url"]
    sliced: list = []
    orig = Volume.read_needle_slice

    def spy(self, *a, **kw):
        sl = orig(self, *a, **kw)
        if sl is not None:
            sliced.append(sl.size)
        return sl

    monkeypatch.setattr(Volume, "read_needle_slice", spy)
    st, _h, body = _raw_get(f"http://{loc}/{fid}")
    assert st == 200 and body == data
    assert sliced == [8 * 1024]


def test_sendfile_tls_falls_back_buffered(tmp_path):
    """A TLS volume server cannot os.sendfile into an SSL socket: the
    response writer must take the buffered loop — same bytes, no
    crash.  (The aio loop likewise diverts TLS conns to threads.)"""
    import subprocess

    from seaweedfs_tpu.utils.config import load_configuration
    from seaweedfs_tpu.utils.security import (install_cluster_tls,
                                              load_server_tls)

    def _openssl(*args):
        subprocess.run(["openssl", *args], check=True,
                       capture_output=True)

    d = tmp_path / "tls"
    d.mkdir()
    try:
        _openssl("req", "-x509", "-newkey", "rsa:2048", "-nodes",
                 "-days", "1", "-keyout", str(d / "ca.key"),
                 "-out", str(d / "ca.crt"), "-subj", "/CN=fd-ca")
    except Exception:
        pytest.skip("openssl unavailable")
    for name in ("server", "client"):
        _openssl("req", "-newkey", "rsa:2048", "-nodes",
                 "-keyout", str(d / f"{name}.key"),
                 "-out", str(d / f"{name}.csr"),
                 "-subj", f"/CN=fd-{name}")
        _openssl("x509", "-req", "-days", "1",
                 "-in", str(d / f"{name}.csr"),
                 "-CA", str(d / "ca.crt"), "-CAkey", str(d / "ca.key"),
                 "-CAcreateserial", "-out", str(d / f"{name}.crt"))
    (tmp_path / "security.toml").write_text(f'''
[grpc]
ca = "{d / 'ca.crt'}"

[grpc.master]
cert = "{d / 'server.crt'}"
key  = "{d / 'server.key'}"

[grpc.volume]
cert = "{d / 'server.crt'}"
key  = "{d / 'server.key'}"

[grpc.client]
cert = "{d / 'client.crt'}"
key  = "{d / 'client.key'}"
''')
    cfg = load_configuration("security", search_paths=[str(tmp_path)])
    assert install_cluster_tls(cfg) is True
    master = MasterServer(
        volume_size_limit_mb=64, meta_dir=str(tmp_path / "m"),
        ssl_context=load_server_tls(cfg, "master"))
    master.start()
    vs = VolumeServer(master.url(), [str(tmp_path / "vs")],
                      pulse_seconds=60, transport="aio",
                      ssl_context=load_server_tls(cfg, "volume"))
    vs.start()
    try:
        client = WeedClient(master.url())
        data = os.urandom(64 * 1024)  # well above sendfile_min
        fid = client.upload_data(data)
        assert bytes(client.download(fid)) == data
    finally:
        vs.stop()
        master.stop()
        rpc.set_client_ssl_context(None)


# -- filer chunk cache: singleflight + bounded bytes ------------------------

def test_chunk_cache_singleflight():
    """N concurrent readers of a cold chunk trigger exactly ONE
    upstream fetch; followers are served the leader's bytes."""
    cache = FilerChunkCache(max_bytes=1 << 20)
    fetches: list = []
    gate = threading.Event()

    def fetch():
        fetches.append(1)
        gate.wait(5.0)
        return b"chunk-bytes" * 100

    out: list = []
    threads = [threading.Thread(
        target=lambda: out.append(cache.get_or_fetch("3,abc", fetch)))
        for _ in range(8)]
    for th in threads:
        th.start()
    time.sleep(0.2)
    gate.set()
    for th in threads:
        th.join()
    assert len(fetches) == 1, f"{len(fetches)} fetches, want 1"
    assert len(out) == 8
    assert all(o == b"chunk-bytes" * 100 for o in out)
    st = cache.stats()
    assert st["hit_bytes"] > 0 and st["miss_bytes"] == len(out[0])


def test_chunk_cache_bounded_bytes_evicts_lru():
    cache = FilerChunkCache(max_bytes=10_000)
    for i in range(8):
        cache.get_or_fetch(f"5,{i:08x}", lambda: bytes(3000))
    st = cache.stats()
    assert st["used_bytes"] <= 10_000
    assert st["evictions"] >= 5
    # The most recent chunk survived; the first was evicted.
    hits0 = st["hit_bytes"]
    cache.get_or_fetch("5,00000007", lambda: bytes(3000))
    assert cache.stats()["hit_bytes"] == hits0 + 3000
    refetched: list = []
    cache.get_or_fetch("5,00000000",
                       lambda: refetched.append(1) or bytes(3000))
    assert refetched


def test_filer_get_populates_chunk_cache(tmp_path):
    """Read-through on the filer chunk path: the second GET of the
    same file is served from cache (hit bytes move, no new fetch)."""
    from seaweedfs_tpu.storage.chunk_cache import CACHE
    master = MasterServer(volume_size_limit_mb=64,
                          meta_dir=str(tmp_path))
    master.start()
    vs = VolumeServer(master.url(), [str(tmp_path / "vs")],
                      pulse_seconds=60)
    vs.start()
    filer = FilerServer(master.url())
    filer.start()
    try:
        base = filer.url()
        payload = os.urandom(30_000)
        rpc.call(base + "/cached.bin", "PUT", payload)
        assert rpc.call(base + "/cached.bin") == payload
        st1 = CACHE.stats()
        assert rpc.call(base + "/cached.bin") == payload
        st2 = CACHE.stats()
        assert st2["hit_bytes"] > st1["hit_bytes"]
        assert st2["miss_bytes"] == st1["miss_bytes"]
        # The debug surface reports the same economics.
        dbg = rpc.call(base + "/debug/cache")
        assert dbg["chunk_cache"]["hit_bytes"] == st2["hit_bytes"]
    finally:
        filer.stop()
        vs.stop()
        master.stop()


# -- small-file packing ------------------------------------------------------

@pytest.fixture()
def packing_stack(tmp_path):
    master = MasterServer(volume_size_limit_mb=64,
                          meta_dir=str(tmp_path))
    master.start()
    # Extra volume slots: TTL'd packs grow their own volume pool
    # beside the plain one (default 7 slots = one growth).
    vs = VolumeServer(master.url(), [str(tmp_path / "vs")],
                      pulse_seconds=60, max_volume_counts=[30])
    vs.start()
    filer = FilerServer(master.url(), pack_threshold=4096,
                        pack_linger=0.05)
    filer.start()
    try:
        yield master, vs, filer
    finally:
        filer.stop()
        vs.stop()
        master.stop()


def _concurrent_puts(base, paths_payloads):
    errs: list = []

    def one(p, d):
        try:
            rpc.call(base + p, "PUT", d)
        except Exception as e:  # noqa: BLE001
            errs.append((p, e))

    threads = [threading.Thread(target=one, args=pp)
               for pp in paths_payloads]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    assert not errs, errs


def test_packed_files_share_needle_and_roundtrip(packing_stack):
    _m, _vs, filer = packing_stack
    base = filer.url()
    payloads = {f"/p{i}.txt": f"tiny-{i}-".encode() * 30
                for i in range(6)}
    _concurrent_puts(base, list(payloads.items()))
    fids = set()
    for p, want in payloads.items():
        e = rpc.call(base + p + "?metadata=true")
        (chunk,) = e["chunks"]
        assert chunk["packed"] is True
        fids.add(chunk["file_id"])
        assert rpc.call(base + p) == want
    assert len(fids) <= 2, f"6 concurrent tiny files used {len(fids)} needles"


def test_packed_delete_leaves_siblings_and_survives_vacuum(
        packing_stack):
    """Deleting one packed file removes only filer metadata; after a
    vacuum pass on the volume the surviving siblings still read back
    (the shared needle was never deleted, so vacuum keeps it)."""
    _m, vs, filer = packing_stack
    base = filer.url()
    payloads = {f"/d{i}.txt": f"del-{i}-".encode() * 40
                for i in range(4)}
    _concurrent_puts(base, list(payloads.items()))
    e = rpc.call(base + "/d0.txt?metadata=true")
    pack_fid = e["chunks"][0]["file_id"]
    rpc.call(base + "/d0.txt", "DELETE")
    time.sleep(0.5)  # deletion queue flush window
    # A non-packed large file deleted alongside DOES free its needle.
    big = os.urandom(20_000)
    rpc.call(base + "/big-del.bin", "PUT", big)
    rpc.call(base + "/big-del.bin", "DELETE")
    vid = t.parse_file_id(pack_fid)[0]
    v = vs.store.find_volume(vid)
    assert v is not None
    from seaweedfs_tpu.storage.vacuum import vacuum
    vacuum(v)
    for p in ("/d1.txt", "/d2.txt", "/d3.txt"):
        assert rpc.call(base + p) == payloads[p], \
            f"{p} lost after sibling delete + vacuum"
    with pytest.raises(rpc.RpcError):
        rpc.call(base + "/d0.txt")


def test_packed_ttl_files_get_ttl_needles(packing_stack):
    """TTL uploads pack separately per ttl value, so whole-needle
    expiry stays correct; the filer entry records ttl_sec."""
    _m, _vs, filer = packing_stack
    base = filer.url()
    # Pre-warm the plain (non-ttl) volume pool so the concurrent
    # assigns below don't race two different-TTL volume growths.
    rpc.call(base + "/warm.bin", "PUT", os.urandom(8192))
    _concurrent_puts(base, [("/t1.txt?ttl=1m", b"ttl-one" * 20),
                            ("/t2.txt?ttl=1m", b"ttl-two" * 20),
                            ("/nt.txt", b"no-ttl" * 20)])
    e1 = rpc.call(base + "/t1.txt?metadata=true")
    e2 = rpc.call(base + "/t2.txt?metadata=true")
    en = rpc.call(base + "/nt.txt?metadata=true")
    assert e1["attributes"]["ttl_sec"] == 60
    assert "ttl_sec" not in en["attributes"] or \
        en["attributes"]["ttl_sec"] == 0
    # ttl files share a pack; the non-ttl file is in a different one.
    assert e1["chunks"][0]["file_id"] == e2["chunks"][0]["file_id"]
    assert en["chunks"][0]["file_id"] != e1["chunks"][0]["file_id"]


def test_oversize_and_cipher_skip_packing(tmp_path):
    _m = MasterServer(volume_size_limit_mb=64, meta_dir=str(tmp_path))
    _m.start()
    vs = VolumeServer(_m.url(), [str(tmp_path / "vs")],
                      pulse_seconds=60)
    vs.start()
    cf = FilerServer(_m.url(), pack_threshold=4096, cipher=True)
    cf.start()
    try:
        base = cf.url()
        rpc.call(base + "/sealed.txt", "PUT", b"cipher small file")
        e = rpc.call(base + "/sealed.txt?metadata=true")
        assert not e["chunks"][0].get("packed")
        assert e["chunks"][0].get("cipher_key")
        assert rpc.call(base + "/sealed.txt") == b"cipher small file"
    finally:
        cf.stop()
        vs.stop()
        _m.stop()


# -- direct volume→client proxy leg -----------------------------------------

def test_large_read_proxies_and_matches(tmp_path):
    """A >= proxy_min single-chunk GET streams through ProxiedBody
    (cache stays cold) and is byte-identical; a small range of the
    same file takes the cached buffered path."""
    from seaweedfs_tpu.storage.chunk_cache import CACHE
    master = MasterServer(volume_size_limit_mb=64,
                          meta_dir=str(tmp_path))
    master.start()
    vs = VolumeServer(master.url(), [str(tmp_path / "vs")],
                      pulse_seconds=60)
    vs.start()
    filer = FilerServer(master.url(), chunk_size=1 << 20)
    filer.start()
    try:
        base = filer.url()
        big = os.urandom(500 * 1024)
        rpc.call(base + "/stream.bin", "PUT", big)
        used0 = CACHE.stats()["used_bytes"]
        assert rpc.call(base + "/stream.bin") == big
        assert CACHE.stats()["used_bytes"] == used0, \
            "proxied big read must not populate the chunk cache"
        # Flow-ledger byte identity on the splice leg: the filer's
        # volume pull is attributed `proxy` and carries the whole
        # chunk; its response leg to the client is `user.read` with
        # exactly the served body — counted inside the splice/sendfile
        # syscall loop, settled briefly to dodge the note-vs-read race.
        from seaweedfs_tpu.stats import flows
        filer_id = base.replace("http://", "")

        def flow(purpose, direction):
            return flows.LEDGER.totals(purpose_=purpose,
                                       direction=direction,
                                       local=filer_id)[0]
        deadline = time.time() + 5.0
        while flow("user.read", "out") != len(big) and \
                time.time() < deadline:
            time.sleep(0.05)
        assert flow("user.read", "out") == len(big), \
            "spliced response leg != served body bytes"
        assert flow("proxy", "in") >= len(big), \
            "filer's volume pull not attributed to `proxy`"
        st, h, body = _raw_get(base + "/stream.bin",
                               {"Range": "bytes=65536-458751"})
        assert st == 206 and body == big[65536:458752]
        assert h["content-range"] == f"bytes 65536-458751/{len(big)}"
        # Sub-proxy_min range: buffered path, cache fills.
        st, _h, body = _raw_get(base + "/stream.bin",
                                {"Range": "bytes=10-99"})
        assert st == 206 and body == big[10:100]
        assert CACHE.stats()["used_bytes"] > used0
        assert rpc.call(base + "/stream.bin") == big  # still identical
    finally:
        filer.stop()
        vs.stop()
        master.stop()
