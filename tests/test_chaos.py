"""Chaos scenarios: writes/reads/rebuilds in flight while fault points
are armed.  Fast and deterministic (tier-1): every failure is injected
through seaweedfs_tpu.fault, never by killing processes or sleeping
out real timeouts."""

import time

import pytest

from seaweedfs_tpu import fault
from seaweedfs_tpu.cluster import resilience, rpc
from seaweedfs_tpu.cluster.client import WeedClient
from seaweedfs_tpu.cluster.master import MasterServer
from seaweedfs_tpu.cluster.volume_server import VolumeServer
from seaweedfs_tpu.parallel import cluster_rebuild

pytestmark = pytest.mark.faults


@pytest.fixture(autouse=True)
def _clean():
    fault.disarm_all()
    resilience.reset_breakers()
    yield
    fault.disarm_all()
    resilience.reset_breakers()


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    """master + 3 volume servers, all one rack so 00x replication can
    place every copy."""
    tmp = tmp_path_factory.mktemp("chaos")
    master = MasterServer(volume_size_limit_mb=16, meta_dir=str(tmp))
    master.start()
    servers = []
    for i in range(3):
        d = tmp / f"vs{i}"
        d.mkdir()
        vs = VolumeServer(master.url(), [str(d)],
                          max_volume_counts=[50], pulse_seconds=60)
        vs.start()
        servers.append(vs)
    yield master, servers
    for vs in servers:
        vs.stop()
    master.stop()


# -- upload during replica death: the re-assign path -------------------------

def test_upload_survives_connect_failures_via_reassign(tmp_path):
    """Acceptance: with rpc.connect armed fail-twice against the only
    volume server, WeedClient.upload still succeeds — each failed PUT
    re-assigns (fresh volume/fid) after a jittered backoff."""
    master = MasterServer(volume_size_limit_mb=16,
                          meta_dir=str(tmp_path))
    master.start()
    vs = VolumeServer(master.url(), [str(tmp_path / "vs")],
                      pulse_seconds=60)
    vs.start()
    try:
        client = WeedClient(master.url())
        client.retry_policy = resilience.RetryPolicy(
            max_attempts=3, base_delay=0.01, max_delay=0.05)
        # Pre-grow the volumes: otherwise the master's own allocation
        # RPCs to the volume server (also riding the faultable client
        # pool) would consume the two armed failures before the
        # client's PUT ever dials.
        client.upload_data(b"warm")
        before = resilience.rpc_retries_total.value(reason="reassign")
        fault.arm("rpc.connect", f"fail*2~{vs.url()}")
        out = client.upload(b"survives the chaos")
        assert client.download(out["fid"]) == b"survives the chaos"
        after = resilience.rpc_retries_total.value(reason="reassign")
        assert after == before + 2   # two failed attempts, two backoffs
        assert not fault.ARMED       # fail*2 exhausted
    finally:
        vs.stop()
        master.stop()


def test_upload_reassigns_past_failed_replication(cluster):
    """A 500 from a failed fan-out is a write failure like any other:
    the client re-assigns and the next attempt lands."""
    _master, _servers = cluster
    client = WeedClient(_master.url())
    client.retry_policy = resilience.RetryPolicy(
        max_attempts=3, base_delay=0.01, max_delay=0.05)
    fault.arm("volume.replicate", "fail*1")
    out = client.upload(b"replicated payload", replication="001")
    assert client.download(out["fid"]) == b"replicated payload"


# -- read during partition: breaker + failover -------------------------------

def test_read_failover_and_breaker_during_partition(cluster):
    """One replica partitioned away (every dial to it fails): reads
    fail over to the healthy replica; after K consecutive failures the
    victim's breaker opens and reads stop paying the dial at all."""
    master, _servers = cluster
    client = WeedClient(master.url())
    fid = client.upload_data(b"partition me", replication="001")
    vid = int(fid.split(",")[0])
    locs = client.lookup(vid)
    assert len(locs) == 2
    victim = locs[0]["url"]
    fault.arm("rpc.connect", f"fail*100~{victim}")
    # Every read succeeds throughout the partition (failover), and the
    # victim's breaker accumulates its consecutive connect failures.
    for _ in range(2 * resilience.BREAKER_THRESHOLD + 2):
        assert client.download(fid) == b"partition me"
    b = resilience.breaker_for(victim)
    assert b.state == "open"
    # Open breaker = fail fast: reads keep succeeding but no longer
    # consume fault hits on the victim (BreakerOpen fires before the
    # dial is even attempted).
    spec = fault.ARMED["rpc.connect"]
    triggered_when_open = spec.triggered
    for _ in range(6):
        assert client.download(fid) == b"partition me"
    assert spec.triggered == triggered_when_open
    # Partition heals: after the cooldown the half-open probe closes
    # the breaker and the victim serves again.
    fault.disarm_all()
    b.cooldown = 0.05
    time.sleep(0.06)
    assert bytes(rpc.call(f"http://{victim}/{fid}")) == b"partition me"
    assert b.state == "closed"


# -- master failover mid-assign ----------------------------------------------

def test_master_failover_mid_assign(cluster):
    """An assign that dies on the wire rotates to the next master seed
    and completes — the client never surfaces the first dead master."""
    master, _servers = cluster
    hostport = master.url().split("://")[-1]
    client = WeedClient([master.url(), master.url()])
    fault.arm("rpc.connect", f"fail*1~{hostport}")
    a = client.assign()
    assert a["fid"]
    assert fault.ARMED == {}  # the one injected failure was consumed


# -- rebuild with a dead shard holder ----------------------------------------

def test_rebuild_fetch_fails_over_past_dead_holder():
    """A shard fetch walks every holder: the first one 'dead' (armed
    fault), the second healthy — the batch must not notice."""
    dead = rpc.JsonHttpServer()
    dead.route("GET", "/admin/ec/shard_file", lambda q, b: b"\x01" * 32)
    dead.start()
    live = rpc.JsonHttpServer()
    live.route("GET", "/admin/ec/shard_file", lambda q, b: b"\x01" * 32)
    live.start()
    try:
        dead_hp = f"127.0.0.1:{dead.port}"
        live_hp = f"127.0.0.1:{live.port}"
        fault.arm("ec.fetch_shard", f"fail*10~{dead_hp}")
        data = cluster_rebuild._fetch_shard(
            [dead_hp, live_hp], 3, 1,
            attempt_timeout=5.0, total_deadline=10.0)
        assert data == b"\x01" * 32
        assert fault.ARMED["ec.fetch_shard"].triggered >= 1
    finally:
        dead.stop()
        live.stop()


def test_rebuild_fetch_bounded_deadline_on_hung_holder():
    """A holder that accepts the connection and then hangs costs one
    per-attempt timeout per round under a total deadline — never the
    old one-600s-hang-per-dead-holder behavior."""
    hung = rpc.JsonHttpServer()
    hung.route("GET", "/admin/ec/shard_file",
               lambda q, b: time.sleep(30) or b"late")
    hung.start()
    try:
        t0 = time.monotonic()
        with pytest.raises(rpc.RpcError) as ei:
            cluster_rebuild._fetch_shard(
                [f"127.0.0.1:{hung.port}"], 3, 1,
                attempt_timeout=0.3, total_deadline=0.5)
        elapsed = time.monotonic() - t0
        assert ei.value.status == 502
        assert elapsed < 5.0
    finally:
        hung.stop()


# -- partial replication leaves zero orphans ---------------------------------

def _get_status(url: str, fid: str) -> int:
    try:
        rpc.call(f"http://{url}/{fid}")
        return 200
    except rpc.RpcError as e:
        return e.status


def test_partial_replication_rolls_back_local_commit(cluster):
    """Acceptance: a failed all-or-fail fan-out deletes the
    locally-committed needle — the 500 the client sees is the whole
    truth, with no orphan left on the primary."""
    master, _servers = cluster
    client = WeedClient(master.url())
    a = client.assign(replication="001")
    fid = a["fid"]
    vid = int(fid.split(",")[0])
    fault.arm("volume.replicate", "fail*1")
    with pytest.raises(rpc.RpcError) as ei:
        rpc.call(f"http://{a['url']}/{fid}", "POST", b"half-landed")
    assert ei.value.status == 500
    assert "replication failed" in ei.value.message
    # Zero orphaned needles anywhere: the primary rolled back its
    # commit, the sibling never stored it (its redirect answers are
    # fine — only a 200 would be an orphan).
    for loc in client.lookup(vid):
        assert _get_status(loc["url"], fid) != 200
    # Disarmed, the same fid writes cleanly everywhere.
    rpc.call(f"http://{a['url']}/{fid}", "POST", b"landed")
    for loc in client.lookup(vid):
        assert bytes(rpc.call(f"http://{loc['url']}/{fid}")) == \
            b"landed"


def test_partial_replication_undoes_committed_siblings(cluster):
    """Three copies, the LAST sibling fails: the sibling that already
    committed gets its copy deleted too — zero orphans on every
    surviving replica."""
    master, servers = cluster
    client = WeedClient(master.url())
    a = client.assign(replication="002")
    fid = a["fid"]
    vid = int(fid.split(",")[0])
    locs = client.lookup(vid)
    assert len(locs) == 3
    siblings = [l["url"] for l in locs if l["url"] != a["url"]]
    # Fail the fan-out to exactly one sibling; the other commits first
    # and must then be rolled back.
    fault.arm("volume.replicate", f"fail*1~{siblings[-1]}")
    with pytest.raises(rpc.RpcError) as ei:
        rpc.call(f"http://{a['url']}/{fid}", "POST", b"three-way")
    assert ei.value.status == 500
    for url in (a["url"], *siblings):
        assert _get_status(url, fid) != 200, f"orphan left on {url}"


def test_failed_overwrite_never_tombstones_prior_version(cluster):
    """Rollback-by-delete applies only to brand-new needles: when the
    failed fan-out was an OVERWRITE of an existing fid, deleting would
    destroy the previous committed version everywhere."""
    master, _servers = cluster
    client = WeedClient(master.url())
    a = client.assign(replication="001")
    fid = a["fid"]
    rpc.call(f"http://{a['url']}/{fid}", "POST", b"version-1")
    fault.arm("volume.replicate", "fail*1")
    with pytest.raises(rpc.RpcError):
        rpc.call(f"http://{a['url']}/{fid}", "POST", b"version-2")
    # The fid must still resolve — a failed update is not a delete.
    out = bytes(rpc.call(f"http://{a['url']}/{fid}"))
    assert out in (b"version-1", b"version-2")


def test_submit_preserves_cipher_key(cluster):
    """submit() passes upload's full result through: a cipher=True
    submit must hand back the one copy of the cipher key."""
    master, _servers = cluster
    client = WeedClient(master.url())
    out = client.submit(b"sealed payload", cipher=True)
    assert out["cipher_key"]
    assert client.download(out["fid"],
                           cipher_key=out["cipher_key"]) == \
        b"sealed payload"


# -- reproducible chaos ------------------------------------------------------

def test_probabilistic_chaos_replays_from_seed(monkeypatch, tmp_path):
    """A @prob chaos run is a pure function of its seed: the same seed
    produces the same injected-failure sequence against live traffic."""
    master = MasterServer(volume_size_limit_mb=16,
                          meta_dir=str(tmp_path))
    master.start()
    vs = VolumeServer(master.url(), [str(tmp_path / "vs")],
                      pulse_seconds=60)
    vs.start()
    try:
        client = WeedClient(master.url())
        fid = client.upload_data(b"seeded chaos")
        url = client.lookup(int(fid.split(",")[0]))[0]["url"]

        def run(seed: str) -> list[int]:
            monkeypatch.setenv("SEAWEEDFS_TPU_FAULTS_SEED", seed)
            fault.arm("volume.read", "status:503@0.5")
            out = []
            for _ in range(24):
                out.append(_get_status(url, fid))
            fault.disarm_all()
            return out

        a, b, c = run("7"), run("7"), run("8")
        assert a == b
        assert set(a) == {200, 503}
        assert a != c
    finally:
        vs.stop()
        master.stop()
