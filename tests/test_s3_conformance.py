"""S3 gateway conformance smoke against a REAL subprocess cluster.

The in-process gateway tests (test_s3api.py) prove protocol details;
this suite proves the shipped artifact: one `weed server -filer=true
-s3=true -s3.config=...` process, started exactly as an operator would,
answering sigv4-signed PUT/GET/HEAD/DELETE/ListObjectsV2 and a
multipart round trip — with anonymous requests refused, because the
-s3.config flag actually reached the gateway."""

import json
import os
import socket
import subprocess
import sys
import time
import urllib.error
import urllib.request
import xml.etree.ElementTree as ET

import pytest

from seaweedfs_tpu.s3api.sigv4 import sign_request

pytestmark = pytest.mark.s3

ACCESS, SECRET = "AKIDEXAMPLE", "wJalrXUtnFEMI/K7MDENG/bPxRkfiEXAMPLE"


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.fixture(scope="module")
def s3_cluster(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("s3conf")
    data_dir = tmp / "data"
    data_dir.mkdir()
    cfg = tmp / "identities.json"
    cfg.write_text(json.dumps({"identities": [{
        "name": "admin",
        "credentials": [{"accessKey": ACCESS, "secretKey": SECRET}],
        "actions": ["Admin", "Read", "Write", "List"]}]}))
    mport, vport, fport, sport = (_free_port() for _ in range(4))
    proc = subprocess.Popen(
        [sys.executable, "-m", "seaweedfs_tpu", "server",
         f"-master.port={mport}", f"-volume.port={vport}",
         "-filer=true", f"-filer.port={fport}",
         "-s3=true", f"-s3.port={sport}", f"-s3.config={cfg}",
         f"-dir={data_dir}", f"-mdir={tmp}"],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    base = f"http://127.0.0.1:{sport}"
    try:
        deadline = time.time() + 30
        while True:
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{mport}/dir/status",
                        timeout=1) as r:
                    up = json.loads(r.read()).get(
                        "topology", {}).get("children")
                if up:
                    # The gateway answers once the filer is up.
                    urllib.request.urlopen(base + "/", timeout=1).read()
                    break
            except urllib.error.HTTPError:
                break  # any HTTP answer means the gateway is serving
            except Exception:
                pass
            if time.time() > deadline:
                raise TimeoutError("s3 cluster did not come up")
            time.sleep(0.2)
        yield base
    finally:
        proc.terminate()
        proc.wait(timeout=10)


def _signed(base: str, method: str, path: str, body: bytes = b"",
            headers: dict | None = None):
    url = base + path
    hdrs = sign_request(method, url, dict(headers or {}), body,
                        ACCESS, SECRET)
    req = urllib.request.Request(url, data=body if body else None,
                                 method=method, headers=hdrs)
    return urllib.request.urlopen(req, timeout=10)


def test_anonymous_is_refused(s3_cluster):
    """-s3.config reached the gateway: unsigned writes are 403s, not
    silently admitted as anonymous-admin."""
    req = urllib.request.Request(s3_cluster + "/conf-bucket",
                                 data=b"", method="PUT")
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(req, timeout=10)
    assert ei.value.code == 403


def test_signed_object_lifecycle(s3_cluster):
    """PUT/GET/HEAD/DELETE + ListObjectsV2, all sigv4-signed."""
    _signed(s3_cluster, "PUT", "/conf-bucket").read()
    body = b"conformance payload " * 64
    with _signed(s3_cluster, "PUT", "/conf-bucket/dir/obj1.bin",
                 body=body) as r:
        assert r.status == 200
    _signed(s3_cluster, "PUT", "/conf-bucket/dir/obj2.bin",
            body=b"two").read()
    with _signed(s3_cluster, "GET", "/conf-bucket/dir/obj1.bin") as r:
        assert r.read() == body
    with _signed(s3_cluster, "HEAD", "/conf-bucket/dir/obj1.bin") as r:
        assert int(r.headers["Content-Length"]) == len(body)
    with _signed(s3_cluster, "GET",
                 "/conf-bucket?list-type=2&prefix=dir/") as r:
        doc = r.read().decode()
    root = ET.fromstring(doc)
    ns = root.tag.split("}")[0] + "}" if "}" in root.tag else ""
    keys = [e.findtext(f"{ns}Key")
            for e in root.findall(f"{ns}Contents")]
    assert sorted(keys) == ["dir/obj1.bin", "dir/obj2.bin"]
    _signed(s3_cluster, "DELETE", "/conf-bucket/dir/obj2.bin").read()
    with pytest.raises(urllib.error.HTTPError) as ei:
        _signed(s3_cluster, "GET", "/conf-bucket/dir/obj2.bin")
    assert ei.value.code == 404
    with _signed(s3_cluster, "GET",
                 "/conf-bucket?list-type=2&prefix=dir/") as r:
        assert b"obj2.bin" not in r.read()


def test_signed_multipart_roundtrip(s3_cluster):
    _signed(s3_cluster, "PUT", "/conf-bucket").read()
    with _signed(s3_cluster, "POST",
                 "/conf-bucket/assembled.bin?uploads") as r:
        doc = r.read().decode()
    root = ET.fromstring(doc)
    ns = root.tag.split("}")[0] + "}" if "}" in root.tag else ""
    upload_id = root.findtext(f"{ns}UploadId")
    assert upload_id
    parts = [b"A" * 700, b"B" * 700, b"C" * 99]
    for i, data in enumerate(parts, start=1):
        with _signed(s3_cluster, "PUT",
                     f"/conf-bucket/assembled.bin?partNumber={i}"
                     f"&uploadId={upload_id}", body=data) as r:
            assert r.status == 200
    complete = b"<CompleteMultipartUpload></CompleteMultipartUpload>"
    _signed(s3_cluster, "POST",
            f"/conf-bucket/assembled.bin?uploadId={upload_id}",
            body=complete).read()
    with _signed(s3_cluster, "GET", "/conf-bucket/assembled.bin") as r:
        assert r.read() == b"".join(parts)
