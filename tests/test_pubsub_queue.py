"""GCP Pub/Sub queue against a fake REST endpoint.

The fake serves the OAuth token endpoint AND the Pub/Sub API on one
local HTTP server; the token handler VERIFIES the RS256 signature of
the service-account JWT with the real public key (libcrypto
DigestVerify), so the whole RFC 7523 grant is exercised
cryptographically, not just structurally.
"""

import base64
import json
import subprocess
import time

import pytest

from seaweedfs_tpu.cluster import rpc
from seaweedfs_tpu.replication.pubsub import (PubSubQueue,
                                              make_service_account_jwt)
from seaweedfs_tpu.utils.cipher import rs256_sign, rs256_verify


@pytest.fixture(scope="module")
def keypair(tmp_path_factory):
    d = tmp_path_factory.mktemp("rsa")
    priv, pub = str(d / "k.pem"), str(d / "p.pem")
    subprocess.run(["openssl", "genpkey", "-algorithm", "RSA",
                    "-pkeyopt", "rsa_keygen_bits:2048", "-out", priv],
                   check=True, capture_output=True)
    subprocess.run(["openssl", "pkey", "-in", priv, "-pubout",
                    "-out", pub], check=True, capture_output=True)
    return open(priv, "rb").read(), open(pub, "rb").read()


def test_rs256_roundtrip(keypair):
    priv, pub = keypair
    sig = rs256_sign(priv, b"payload")
    assert rs256_verify(pub, b"payload", sig)
    assert not rs256_verify(pub, b"payloaD", sig)
    assert not rs256_verify(pub, b"payload", sig[:-1] + b"\x00")


def test_service_account_jwt_shape(keypair):
    priv, pub = keypair
    sa = {"client_email": "svc@proj.iam.gserviceaccount.com",
          "private_key": priv.decode(), "private_key_id": "kid-1"}
    jwt = make_service_account_jwt(sa, "https://oauth2/token", now=1000)
    h, c, s = jwt.split(".")
    pad = lambda x: x + "=" * (-len(x) % 4)  # noqa: E731
    header = json.loads(base64.urlsafe_b64decode(pad(h)))
    claims = json.loads(base64.urlsafe_b64decode(pad(c)))
    assert header == {"alg": "RS256", "typ": "JWT", "kid": "kid-1"}
    assert claims["iss"] == sa["client_email"]
    assert claims["aud"] == "https://oauth2/token"
    assert claims["exp"] == 1000 + 3600
    assert rs256_verify(pub, f"{h}.{c}".encode(),
                        base64.urlsafe_b64decode(pad(s)))


@pytest.fixture
def fake_gcp(keypair):
    """One server: /token (OAuth, signature-verifying) + Pub/Sub v1."""
    _priv, pub = keypair
    srv = rpc.JsonHttpServer("127.0.0.1", 0)
    state = {"messages": [], "acked": [], "tokens": 0,
             "published_with": [], "bad_grants": 0}

    def token(query, body):
        import urllib.parse
        form = dict(urllib.parse.parse_qsl(bytes(body).decode()))
        jwt = form.get("assertion", "")
        h, c, s = jwt.split(".")
        pad = lambda x: x + "=" * (-len(x) % 4)  # noqa: E731
        if not rs256_verify(pub, f"{h}.{c}".encode(),
                            base64.urlsafe_b64decode(pad(s))):
            state["bad_grants"] += 1
            return (401, b'{"error":"invalid_grant"}',
                    {"Content-Type": "application/json"})
        claims = json.loads(base64.urlsafe_b64decode(pad(c)))
        assert claims["aud"].endswith("/token")
        state["tokens"] += 1
        return {"access_token": f"tok-{state['tokens']}",
                "expires_in": 3600, "token_type": "Bearer"}

    def api(path, query, body):
        auth = query.get("_headers", {}).get("authorization", "") \
            if "_headers" in query else None
        doc = json.loads(bytes(body) or b"{}")
        if path.endswith(":publish"):
            state["published_with"].append(auth)
            for m in doc.get("messages", []):
                state["messages"].append(m)
            return {"messageIds": [str(len(state["messages"]))]}
        if path.endswith(":pull"):
            out = [{"ackId": f"a{i}", "message": m}
                   for i, m in enumerate(state["messages"])
                   if f"a{i}" not in state["acked"]]
            return {"receivedMessages": out[:doc.get("maxMessages", 10)]}
        if path.endswith(":acknowledge"):
            state["acked"].extend(doc.get("ackIds", []))
            return {}
        return (404, b"{}", {"Content-Type": "application/json"})

    srv.route("POST", "/token", token)
    srv.pass_headers = True
    srv.prefix_route("POST", "/v1/", api)
    srv.start()
    yield srv, state
    srv.stop()


def _queue(srv, priv) -> PubSubQueue:
    sa = {"client_email": "svc@proj.iam.gserviceaccount.com",
          "private_key": priv.decode(), "private_key_id": "kid-1",
          "token_uri": f"{srv.url()}/token"}
    return PubSubQueue("proj", "events", service_account=sa,
                       endpoint=srv.url())


def test_pubsub_publish_consume_roundtrip(fake_gcp, keypair):
    priv, _pub = keypair
    srv, state = fake_gcp
    q = _queue(srv, priv)
    q.publish("/a.txt", {"op": "create"})
    q.publish("/b.txt", {"op": "delete"})
    assert state["tokens"] == 1  # token cached across calls
    assert state["bad_grants"] == 0
    got = []
    q.consume(lambda k, m: got.append((k, m)))
    assert got == [("/a.txt", {"op": "create"}),
                   ("/b.txt", {"op": "delete"})]
    assert len(state["acked"]) == 2  # acked after delivery
    # messages carry the key attribute + b64 envelope
    m0 = state["messages"][0]
    assert m0["attributes"]["key"] == "/a.txt"
    env = json.loads(base64.b64decode(m0["data"]))
    assert env == {"key": "/a.txt", "message": {"op": "create"}}


def test_pubsub_bearer_token_attached(fake_gcp, keypair):
    priv, _pub = keypair
    srv, state = fake_gcp
    q = _queue(srv, priv)
    q.publish("/x", {"n": 1})
    assert state["published_with"] == ["Bearer tok-1"]


def test_pubsub_spec_routing(fake_gcp, keypair):
    from seaweedfs_tpu.replication.notification import queue_for_spec
    priv, _pub = keypair
    srv, state = fake_gcp
    sa = {"client_email": "svc@proj.iam.gserviceaccount.com",
          "private_key": priv.decode(),
          "token_uri": f"{srv.url()}/token"}
    q = queue_for_spec("pubsub://proj/events", service_account=sa,
                       endpoint=srv.url())
    assert isinstance(q, PubSubQueue)
    q.publish("/via-spec", {"n": 2})
    got = []
    q.consume(lambda k, m: got.append(k))
    assert "/via-spec" in got


def test_pubsub_poison_message_acked(fake_gcp, keypair):
    priv, _pub = keypair
    srv, state = fake_gcp
    state["messages"].append(
        {"data": base64.b64encode(b"not json").decode(),
         "attributes": {}})
    q = _queue(srv, priv)
    q.publish("/good", {"n": 1})
    got = []
    q.consume(lambda k, m: got.append(k))
    assert got == ["/good"]
    assert len(state["acked"]) == 2  # poison acked too, no redelivery
