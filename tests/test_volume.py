"""Volume engine tests: write/read/delete, batching, persistence, vacuum,
idx regeneration, store routing — mirrors the reference's
storage/*_test.go coverage (needle_read_write_test, volume_vacuum_test)."""

import os
import threading

import pytest

from seaweedfs_tpu.core import types as t
from seaweedfs_tpu.core.needle import Needle
from seaweedfs_tpu.storage.needle_map import MemDb, MemoryNeedleMap
from seaweedfs_tpu.storage.store import Store
from seaweedfs_tpu.storage.vacuum import vacuum
from seaweedfs_tpu.storage.volume import NotFoundError, Volume, VolumeError
from seaweedfs_tpu.storage.volume_scanner import (generate_idx_from_dat,
                                                  scan_volume_file)


@pytest.fixture
def vol(tmp_path):
    v = Volume(str(tmp_path), "", 1)
    yield v
    v.close()


def test_write_read_roundtrip(vol):
    n = Needle(cookie=0xCAFE, id=101, data=b"hello volume engine")
    offset, size = vol.write_needle(n)
    assert offset == 8  # right after superblock
    got = vol.read_needle(101)
    assert got.data == b"hello volume engine"
    assert got.cookie == 0xCAFE


def test_cookie_check(vol):
    vol.write_needle(Needle(cookie=0xCAFE, id=1, data=b"x"))
    vol.read_needle(1, cookie=0xCAFE)
    with pytest.raises(VolumeError, match="cookie"):
        vol.read_needle(1, cookie=0xBEEF)


def test_read_missing(vol):
    with pytest.raises(NotFoundError):
        vol.read_needle(999)


def test_delete(vol):
    vol.write_needle(Needle(cookie=1, id=5, data=b"to be deleted"))
    freed = vol.delete_needle(5)
    assert freed > 0
    with pytest.raises(NotFoundError):
        vol.read_needle(5)
    assert vol.delete_needle(5) == 0  # idempotent
    assert vol.deleted_size() > 0


def test_overwrite_supersedes(vol):
    vol.write_needle(Needle(cookie=1, id=9, data=b"v1"))
    vol.write_needle(Needle(cookie=2, id=9, data=b"v2-new"))
    assert vol.read_needle(9).data == b"v2-new"
    assert vol.nm.metrics.deletion_count == 1


def test_concurrent_writes_batched(vol):
    def writer(base):
        for i in range(50):
            vol.write_needle(Needle(cookie=base, id=base * 1000 + i,
                                    data=bytes([base]) * 100))
    threads = [threading.Thread(target=writer, args=(k,)) for k in range(1, 5)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    assert vol.file_count() == 200
    for k in range(1, 5):
        assert vol.read_needle(k * 1000 + 7).data == bytes([k]) * 100


def test_persistence_reload(tmp_path):
    v = Volume(str(tmp_path), "c1", 3)
    for i in range(20):
        v.write_needle(Needle(cookie=i, id=i, data=f"obj{i}".encode()))
    v.delete_needle(7)
    v.close()

    v2 = Volume(str(tmp_path), "c1", 3, create=False)
    assert v2.file_count() == 19
    assert v2.read_needle(11).data == b"obj11"
    with pytest.raises(NotFoundError):
        v2.read_needle(7)
    v2.close()


def test_readonly(vol):
    vol.set_readonly(True)
    with pytest.raises(VolumeError, match="read only"):
        vol.write_needle(Needle(cookie=1, id=1, data=b"x"))
    vol.set_readonly(False)
    vol.write_needle(Needle(cookie=1, id=1, data=b"x"))


def test_scanner_sees_all_records(vol):
    for i in range(5):
        vol.write_needle(Needle(cookie=1, id=i, data=b"d" * (i + 1)))
    vol.delete_needle(2)
    vol.sync()
    records = list(scan_volume_file(vol.file_name() + ".dat"))
    # 5 writes + 1 tombstone marker
    assert len(records) == 6
    assert records[-1][0].size == 0 and records[-1][0].id == 2


def test_generate_idx_from_dat(tmp_path):
    v = Volume(str(tmp_path), "", 4)
    for i in range(10):
        v.write_needle(Needle(cookie=1, id=i, data=f"data{i}".encode()))
    v.delete_needle(3)
    v.sync()
    base = v.file_name()
    v.close()

    regen = str(tmp_path / "regen.idx")
    n = generate_idx_from_dat(base + ".dat", regen)
    assert n == 11  # 10 writes + 1 tombstone
    db = MemDb.from_idx(open(regen, "rb").read())
    assert db.get(3) is None
    assert db.get(5) is not None
    # Regenerated map must agree with the live map.
    with open(base + ".idx", "rb") as f:
        live = MemDb.from_idx(f.read())
    assert live._m == db._m


def test_vacuum_reclaims_space(tmp_path):
    v = Volume(str(tmp_path), "", 5)
    for i in range(30):
        v.write_needle(Needle(cookie=1, id=i, data=b"z" * 500))
    for i in range(0, 30, 2):
        v.delete_needle(i)
    before = v.dat_size()
    rev_before = v.super_block.compaction_revision
    vacuum(v)
    after = v.dat_size()
    assert after < before
    assert v.super_block.compaction_revision == rev_before + 1
    assert v.file_count() == 15
    for i in range(1, 30, 2):
        assert v.read_needle(i).data == b"z" * 500
    for i in range(0, 30, 2):
        with pytest.raises(NotFoundError):
            v.read_needle(i)
    assert v.garbage_ratio() < 0.01
    # Volume still writable after vacuum.
    v.write_needle(Needle(cookie=1, id=100, data=b"post-vacuum"))
    assert v.read_needle(100).data == b"post-vacuum"
    v.close()


def test_needle_map_counters():
    nm = MemoryNeedleMap()
    nm.put(1, 8, 100)
    nm.put(2, 208, 50)
    nm.put(1, 408, 70)  # overwrite
    assert nm.metrics.file_count == 2
    assert nm.metrics.deletion_count == 1
    assert nm.metrics.deletion_byte_count == 100
    nm.delete(2)
    assert nm.metrics.deletion_byte_count == 150
    assert len(nm) == 1
    assert nm.metrics.maximum_file_key == 2


def test_store_routing_and_heartbeat(tmp_path):
    d1, d2 = str(tmp_path / "a"), str(tmp_path / "b")
    store = Store([d1, d2], ip="127.0.0.1", port=8080)
    store.add_volume(1)
    store.add_volume(2, collection="pics", replica_placement="001")
    store.write_needle(1, Needle(cookie=1, id=10, data=b"one"))
    store.write_needle(2, Needle(cookie=1, id=20, data=b"two"))
    assert store.read_needle(2, 20).data == b"two"

    hb = store.collect_heartbeat()
    assert len(hb["volumes"]) == 2
    by_id = {v.id: v for v in hb["volumes"]}
    assert by_id[2].collection == "pics"
    assert by_id[2].replica_placement == 1

    new, deleted = store.drain_deltas()
    assert {v.id for v in new} == {1, 2}
    assert deleted == []

    with pytest.raises(VolumeError):
        store.add_volume(1)  # duplicate
    store.delete_volume(1)
    _, deleted = store.drain_deltas()
    assert [v.id for v in deleted] == [1]
    assert not os.path.exists(os.path.join(d1, "1.dat"))
    store.close()


def test_store_rediscovers_volumes(tmp_path):
    d = str(tmp_path / "disk")
    store = Store([d])
    store.add_volume(7, collection="col")
    store.write_needle(7, Needle(cookie=9, id=1, data=b"persisted"))
    store.close()

    store2 = Store([d])
    assert store2.has_volume(7)
    assert store2.read_needle(7, 1).data == b"persisted"
    store2.close()


def test_vacuum_staging_on_volume(tmp_path):
    """Two-phase staging state lives on the Volume: commit with nothing
    staged fails, compact stages, cleanup abandons, and concurrent
    vacuum() calls from different planes serialize on the volume's
    guard instead of interleaving .cpd/.cpx writes
    (weed/storage/volume_vacuum.go keeps this state on the Volume)."""
    from seaweedfs_tpu.storage.vacuum import (VacuumError, cleanup_compact,
                                              commit_compact, compact)

    v = Volume(str(tmp_path), "", 1)
    for i in range(50):
        v.write_needle(Needle(id=i + 1, cookie=7, data=b"x" * 100))
    for i in range(25):
        v.delete_needle(i + 1)

    with pytest.raises(VacuumError):
        commit_compact(v)  # nothing staged

    compact(v)
    assert v.vacuum_staged is not None
    cleanup_compact(v)  # abandon
    assert v.vacuum_staged is None
    assert not os.path.exists(v.file_name() + ".cpd")
    with pytest.raises(VacuumError):
        commit_compact(v)  # staged snapshot was abandoned

    errs = []

    def worker():
        try:
            vacuum(v)
        except Exception as e:  # noqa: BLE001 — collected for assert
            errs.append(e)

    threads = [threading.Thread(target=worker) for _ in range(4)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    assert errs == []
    for i in range(25, 50):
        assert v.read_needle(i + 1).data == b"x" * 100
    with pytest.raises(NotFoundError):
        v.read_needle(1)
    v.close()
