"""Incremental volume backup / tail.

Reference behaviors: storage/volume_backup.go (BinarySearchByAppendAtNs
:170, IncrementalBackup :65), the VolumeTail RPCs, command/backup.go.
"""

import os
import time

import pytest

from seaweedfs_tpu.core.needle import Needle
from seaweedfs_tpu.storage.volume import Volume
from seaweedfs_tpu.storage.volume_backup import (
    apply_incremental, binary_search_by_append_at_ns,
    last_append_at_ns, read_incremental)


def _vol(tmp_path, vid=3) -> Volume:
    return Volume(str(tmp_path), "", vid, use_worker=False)


def _write(v, nid, data):
    n = Needle(id=nid, cookie=0x99, data=data)
    v.write_needle(n)
    return v.nm.get(nid)


def test_binary_search_cut_offset(tmp_path):
    v = _vol(tmp_path)
    stamps = []
    for i in range(10):
        _write(v, i + 1, f"rec-{i}".encode())
        stamps.append(v.read_needle(i + 1).append_at_ns)
    # Cut strictly after the 5th record.
    cut = binary_search_by_append_at_ns(v, stamps[4])
    off6, _ = v.nm.get(6)
    assert cut == off6
    # Nothing newer -> end of volume.
    assert binary_search_by_append_at_ns(v, stamps[-1]) == v.dat_size()
    # Everything newer -> first record.
    off1, _ = v.nm.get(1)
    assert binary_search_by_append_at_ns(v, 0) == off1
    v.close()


def test_incremental_roundtrip_with_deletes(tmp_path):
    src_dir = tmp_path / "src"
    dst_dir = tmp_path / "dst"
    src_dir.mkdir()
    dst_dir.mkdir()
    v = _vol(src_dir)
    for i in range(5):
        _write(v, i + 1, f"first-{i}".encode())
    v.sync()
    # Seed the backup with a straight file copy (first `weed backup`).
    import shutil
    shutil.copyfile(v.file_name() + ".dat",
                    str(dst_dir / "3.dat"))
    shutil.copyfile(v.file_name() + ".idx",
                    str(dst_dir / "3.idx"))
    since = last_append_at_ns(str(dst_dir / "3.dat"))
    # More writes + a delete on the source.
    for i in range(5, 8):
        _write(v, i + 1, f"second-{i}".encode())
    v.delete_needle(2)
    delta = read_incremental(v, since)
    assert delta
    applied = apply_incremental(str(dst_dir / "3.dat"),
                                str(dst_dir / "3.idx"), delta,
                                v.version)
    assert applied >= 4  # 3 appends + 1 tombstone
    # The backup copy opens as a volume equal to the source.
    b = Volume(str(dst_dir), "", 3, create=False, use_worker=False)
    for i in list(range(5, 8)) + [0, 3, 4]:
        assert b.read_needle(i + 1).data == \
            v.read_needle(i + 1).data
    from seaweedfs_tpu.storage.volume import NotFoundError
    with pytest.raises(NotFoundError):
        b.read_needle(2)  # delete replayed
    # Re-sync with no changes is a no-op.
    since2 = last_append_at_ns(str(dst_dir / "3.dat"))
    assert read_incremental(v, since2) == b""
    b.close()
    v.close()


def test_backup_command_end_to_end(tmp_path):
    """weed backup: full copy then incremental tail via the RPCs."""
    from seaweedfs_tpu.cluster.client import WeedClient
    from seaweedfs_tpu.cluster.master import MasterServer
    from seaweedfs_tpu.cluster.volume_server import VolumeServer
    from seaweedfs_tpu.command import COMMANDS, _load_all, parse_flags
    master = MasterServer(volume_size_limit_mb=64,
                          meta_dir=str(tmp_path / "m"))
    master.start()
    vs = VolumeServer(master.url(), [str(tmp_path / "v")],
                      pulse_seconds=60)
    vs.start()
    try:
        client = WeedClient(master.url())
        fid1 = client.upload_data(b"backup me first")
        vid = int(fid1.split(",")[0])
        _load_all()
        host = master.url().replace("http://", "")
        bdir = str(tmp_path / "backup")
        flags, rest = parse_flags([f"-master={host}",
                                   f"-volumeId={vid}",
                                   f"-dir={bdir}"])
        assert COMMANDS["backup"].run(flags, rest) == 0
        assert os.path.exists(os.path.join(bdir, f"{vid}.dat"))
        # New uploads to the SAME volume, then an incremental run.
        fids = [fid1]
        for i in range(5):
            a = client.assign()
            if int(a["fid"].split(",")[0]) == vid:
                import urllib.request
                urllib.request.urlopen(urllib.request.Request(
                    f"http://{a['url']}/{a['fid']}",
                    data=f"extra-{i}".encode(),
                    method="POST")).read()
                fids.append(a["fid"])
        assert COMMANDS["backup"].run(flags, rest) == 0
        # The local copy serves every fid that landed on this volume.
        b = Volume(bdir, "", vid, create=False, use_worker=False)
        from seaweedfs_tpu.core import types as t
        for fid in fids:
            _vid, key, cookie = t.parse_file_id(fid)
            assert b.read_needle(key, cookie).data
        b.close()
    finally:
        vs.stop()
        master.stop()


def test_delete_before_later_write_replays(tmp_path):
    """Regression: a tombstone appended BEFORE a later live write must
    ride the delta (live-offset binary search alone would cut past it
    and resurrect the deleted needle in the backup)."""
    import shutil
    src_dir = tmp_path / "s"
    dst_dir = tmp_path / "d"
    src_dir.mkdir()
    dst_dir.mkdir()
    v = _vol(src_dir)
    for i in range(4):
        _write(v, i + 1, f"x-{i}".encode())
    v.sync()
    shutil.copyfile(v.file_name() + ".dat", str(dst_dir / "3.dat"))
    shutil.copyfile(v.file_name() + ".idx", str(dst_dir / "3.idx"))
    since = last_append_at_ns(str(dst_dir / "3.dat"))
    v.delete_needle(2)          # tombstone first...
    _write(v, 9, b"later-live")  # ...then a live write
    delta = read_incremental(v, since)
    apply_incremental(str(dst_dir / "3.dat"), str(dst_dir / "3.idx"),
                      delta, v.version)
    b = Volume(str(dst_dir), "", 3, create=False, use_worker=False)
    from seaweedfs_tpu.storage.volume import NotFoundError
    with pytest.raises(NotFoundError):
        b.read_needle(2)
    assert b.read_needle(9).data == b"later-live"
    b.close()
    v.close()


def test_delete_only_interval_replays(tmp_path):
    """A delta window holding ONLY tombstones must still be streamed."""
    import shutil
    src_dir = tmp_path / "s2"
    dst_dir = tmp_path / "d2"
    src_dir.mkdir()
    dst_dir.mkdir()
    v = _vol(src_dir)
    for i in range(3):
        _write(v, i + 1, f"y-{i}".encode())
    v.sync()
    shutil.copyfile(v.file_name() + ".dat", str(dst_dir / "3.dat"))
    shutil.copyfile(v.file_name() + ".idx", str(dst_dir / "3.idx"))
    since = last_append_at_ns(str(dst_dir / "3.dat"))
    v.delete_needle(1)
    v.delete_needle(3)
    delta = read_incremental(v, since)
    assert delta, "delete-only delta must not be empty"
    apply_incremental(str(dst_dir / "3.dat"), str(dst_dir / "3.idx"),
                      delta, v.version)
    b = Volume(str(dst_dir), "", 3, create=False, use_worker=False)
    from seaweedfs_tpu.storage.volume import NotFoundError
    for nid in (1, 3):
        with pytest.raises(NotFoundError):
            b.read_needle(nid)
    assert b.read_needle(2).data == b"y-1"
    # Cursor now covers the tombstones: next delta is empty (no
    # re-fetch loop).
    since2 = last_append_at_ns(str(dst_dir / "3.dat"))
    assert read_incremental(v, since2) == b""
    b.close()
    v.close()
