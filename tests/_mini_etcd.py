"""In-process mini etcd v3 gRPC server for EtcdStore tests: the three
etcdserverpb.KV RPCs (Range/Put/DeleteRange) over a sorted dict —
the mini-RESP/mini-Kafka test pattern for the gRPC world."""

from __future__ import annotations

import bisect
import threading
from concurrent import futures

import grpc

from seaweedfs_tpu.pb import etcd_pb2 as pb


class MiniEtcd:
    def __init__(self):
        self._keys: list[bytes] = []
        self._m: dict[bytes, bytes] = {}
        self._rev = 0
        self._lock = threading.Lock()
        self._server = grpc.server(futures.ThreadPoolExecutor(4))
        unary = grpc.unary_unary_rpc_method_handler
        handlers = {
            "Range": unary(self._range,
                           request_deserializer=pb.RangeRequest.FromString,
                           response_serializer=(
                               pb.RangeResponse.SerializeToString)),
            "Put": unary(self._put,
                         request_deserializer=pb.PutRequest.FromString,
                         response_serializer=(
                             pb.PutResponse.SerializeToString)),
            "DeleteRange": unary(
                self._delete_range,
                request_deserializer=pb.DeleteRangeRequest.FromString,
                response_serializer=(
                    pb.DeleteRangeResponse.SerializeToString)),
        }
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler("etcdserverpb.KV",
                                                  handlers),))
        self.port = self._server.add_insecure_port("127.0.0.1:0")
        self._server.start()

    def _header(self):
        return pb.ResponseHeader(revision=self._rev)

    def _select(self, key: bytes, range_end: bytes) -> list[bytes]:
        if not range_end:
            return [key] if key in self._m else []
        lo = bisect.bisect_left(self._keys, key)
        hi = bisect.bisect_left(self._keys, range_end)
        return self._keys[lo:hi]

    def _range(self, req, ctx):
        with self._lock:
            keys = self._select(req.key, req.range_end)
            if req.sort_order == pb.RangeRequest.DESCEND:
                keys = list(reversed(keys))
            total = len(keys)
            if req.limit:
                keys = keys[:req.limit]
            return pb.RangeResponse(
                header=self._header(),
                kvs=[pb.KeyValue(key=k, value=self._m[k])
                     for k in keys],
                more=total > len(keys), count=total)

    def _put(self, req, ctx):
        with self._lock:
            self._rev += 1
            if req.key not in self._m:
                bisect.insort(self._keys, req.key)
            self._m[req.key] = req.value
            return pb.PutResponse(header=self._header())

    def _delete_range(self, req, ctx):
        with self._lock:
            self._rev += 1
            keys = self._select(req.key, req.range_end)
            for k in list(keys):
                del self._m[k]
                i = bisect.bisect_left(self._keys, k)
                del self._keys[i]
            return pb.DeleteRangeResponse(header=self._header(),
                                          deleted=len(keys))

    def close(self):
        self._server.stop(0.2)
