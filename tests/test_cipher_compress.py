"""Gzip + cipher upload paths (operation lib parity, row §2.7).

Reference behaviors under test:
  - weed/util/cipher.go — AES-256-GCM seal/open
  - weed/operation/upload_content.go — compress-when-it-shrinks,
    Content-Encoding negotiation, cipher uploads with opaque needles
  - weed/server/volume_server_handlers_read.go — stored-gzip needles
    are decompressed for readers that don't accept gzip
  - filer cipher option — chunk keys live only in entry metadata
"""

import gzip
import json
import os

import pytest

from seaweedfs_tpu.cluster import rpc
from seaweedfs_tpu.cluster.client import WeedClient
from seaweedfs_tpu.cluster.master import MasterServer
from seaweedfs_tpu.cluster.volume_server import VolumeServer
from seaweedfs_tpu.utils import cipher
from seaweedfs_tpu.utils.compression import (gzip_data, is_compressable,
                                             maybe_gzip, ungzip_data)

TEXT = b"the quick brown fox jumps over the lazy dog\n" * 200


# -- primitives ------------------------------------------------------------

def test_cipher_roundtrip_and_key_isolation():
    blob, key = cipher.encrypt(TEXT)
    assert blob != TEXT and len(key) == 32
    assert cipher.decrypt(blob, key) == TEXT
    # fresh key every call
    blob2, key2 = cipher.encrypt(TEXT)
    assert key2 != key and blob2 != blob


def test_cipher_rejects_tamper_and_wrong_key():
    blob, key = cipher.encrypt(TEXT)
    bad = blob[:-1] + bytes([blob[-1] ^ 1])
    with pytest.raises(cipher.CipherError):
        cipher.decrypt(bad, key)
    with pytest.raises(cipher.CipherError):
        cipher.decrypt(blob, os.urandom(32))
    with pytest.raises(cipher.CipherError):
        cipher.decrypt(b"short", key)


def test_cipher_empty_payload():
    blob, key = cipher.encrypt(b"")
    assert cipher.decrypt(blob, key) == b""


def test_compressable_heuristic():
    assert is_compressable("a.txt")
    assert is_compressable("a.json")
    assert is_compressable(mime="text/html; charset=utf-8")
    assert is_compressable(mime="application/json")
    assert not is_compressable("a.jpg")
    assert not is_compressable("a.mp4", "video/mp4")
    assert not is_compressable()


def test_maybe_gzip_only_when_it_shrinks():
    z, ok = maybe_gzip(TEXT, "fox.txt")
    assert ok and len(z) < len(TEXT) and ungzip_data(z) == TEXT
    rnd = os.urandom(8192)
    same, ok2 = maybe_gzip(rnd, "noise.txt")
    assert not ok2 and same == rnd
    # non-compressable name: untouched even though it would shrink
    same3, ok3 = maybe_gzip(TEXT, "fox.bin")
    assert not ok3 and same3 == TEXT
    # deterministic output (no mtime) so replicas stay byte-identical
    assert gzip_data(TEXT) == gzip_data(TEXT)


# -- cluster paths ---------------------------------------------------------

@pytest.fixture
def cluster(tmp_path):
    master = MasterServer(volume_size_limit_mb=64, meta_dir=str(tmp_path))
    master.start()
    servers = []
    for i in range(2):
        d = tmp_path / f"vs{i}"
        d.mkdir()
        vs = VolumeServer(master.url(), [str(d)], pulse_seconds=60)
        vs.start()
        servers.append(vs)
    yield master, servers
    for vs in servers:
        vs.stop()
    master.stop()


def test_gzip_upload_transparent_read(cluster):
    master, _ = cluster
    client = WeedClient(master.url())
    r = client.upload(TEXT, name="fox.txt")
    assert r["is_compressed"] and r["size"] == len(TEXT)
    # plain read: server decompresses
    assert client.download(r["fid"]) == TEXT


def test_gzip_passthrough_for_gzip_reader(cluster):
    master, _ = cluster
    client = WeedClient(master.url())
    r = client.upload(TEXT, name="fox.txt")
    vid = r["fid"].split(",")[0]
    locs = client.lookup(int(vid))
    resp, conn = rpc._request(
        f"http://{locs[0]['url']}/{r['fid']}", "GET", None, 10.0,
        req_headers={"Accept-Encoding": "gzip"})
    raw = resp.read()
    rpc._finish(conn, resp)
    assert resp.getheader("content-encoding") == "gzip"
    assert gzip.decompress(raw) == TEXT
    assert len(raw) < len(TEXT)  # the wire bytes stayed compressed


def test_gzip_upload_replicated(cluster):
    """Replicas must store the same compressed bytes + flag: reads from
    EVERY holder decompress correctly."""
    master, servers = cluster
    client = WeedClient(master.url())
    a = client.assign(replication="001")
    from seaweedfs_tpu.utils.compression import gzip_data as gz
    url = f"http://{a['url']}/{a['fid']}?name=fox.txt"
    rpc.call(url, "POST", gz(TEXT),
             headers={"Content-Encoding": "gzip"})
    locs = client.lookup(int(a["fid"].split(",")[0]))
    assert len(locs) == 2
    for loc in locs:
        assert rpc.call(f"http://{loc['url']}/{a['fid']}") == TEXT


def test_cipher_upload_opaque_on_volume_server(cluster):
    master, _ = cluster
    client = WeedClient(master.url())
    r = client.upload(TEXT, name="secret.txt", cipher=True)
    assert r["cipher_key"] and not r["is_compressed"]
    # raw needle bytes are ciphertext, name never reached the server
    raw = client.download(r["fid"])
    assert raw != TEXT and TEXT not in raw
    # holder of the key reads plaintext
    assert client.download(r["fid"], cipher_key=r["cipher_key"]) == TEXT


def test_spoofed_content_encoding_query_param_ignored(cluster):
    """?_content_encoding=gzip in the URL must NOT set the compressed
    flag — reserved underscore keys come from headers only.  A forged
    one would store an unreadable needle on the primary while replicas
    (which strip _ keys) stored it fine."""
    master, _ = cluster
    client = WeedClient(master.url())
    a = client.assign()
    rpc.call(f"http://{a['url']}/{a['fid']}?_content_encoding=gzip",
             "POST", b"plain bytes, not gzip")
    assert client.download(a["fid"]) == b"plain bytes, not gzip"


def test_head_reports_logical_size_for_gzipped_needle(cluster):
    master, _ = cluster
    client = WeedClient(master.url())
    r = client.upload(TEXT, name="fox.txt")
    assert r["is_compressed"]
    locs = client.lookup(int(r["fid"].split(",")[0]))
    url = f"http://{locs[0]['url']}/{r['fid']}"
    resp, conn = rpc._request(url, "HEAD", None, 10.0)
    resp._done = True
    rpc._finish(conn, resp)
    assert int(resp.getheader("content-length")) == len(TEXT)
    # a gzip-accepting HEAD mirrors the gzip-passthrough GET instead
    resp, conn = rpc._request(url, "HEAD", None, 10.0,
                              req_headers={"Accept-Encoding": "gzip"})
    resp._done = True
    rpc._finish(conn, resp)
    assert resp.getheader("content-encoding") == "gzip"
    assert int(resp.getheader("content-length")) < len(TEXT)


def test_mount_honors_filer_cipher(cluster, tmp_path):
    """A WFS pointed at a cipher-enabled filer must seal its chunks
    (wfs.go reads the cipher bit from GetFilerConfiguration)."""
    from seaweedfs_tpu.filer.server import FilerServer
    from seaweedfs_tpu.mount.vfs import WFS
    master, _ = cluster
    fs = FilerServer(master.url(), port=0,
                     store_path=str(tmp_path / "fmnt.db"), cipher=True)
    fs.start()
    try:
        wfs = WFS(fs.url())
        assert wfs.cipher and wfs.writer.cipher
        chunks = wfs.writer.write(TEXT[:1000])
        assert chunks and all(c.cipher_key for c in chunks)
        # sealed on the volume server, opened by the streamer
        client = WeedClient(master.url())
        assert TEXT[:64] not in client.download(chunks[0].file_id)
        assert wfs.streamer.read(chunks) == TEXT[:1000]
    finally:
        fs.stop()


def test_cipher_manifest_blob_is_sealed(cluster, tmp_path):
    """A manifest blob holds every data chunk's key — a cipher filer
    must seal it too, or encryption at rest is defeated for any file
    big enough to manifestize."""
    from seaweedfs_tpu.filer.entry import FileChunk
    from seaweedfs_tpu.filer.server import FilerServer
    master, _ = cluster
    fs = FilerServer(master.url(), port=0,
                     store_path=str(tmp_path / "fm.db"), cipher=True)
    fs.start()
    try:
        fake = [FileChunk(file_id=f"9,{i:x}00000000", offset=i * 10,
                          size=10, mtime=i + 1,
                          cipher_key=os.urandom(32).hex())
                for i in range(1000)]
        out = fs._manifestize(list(fake))
        manifest = [c for c in out if c.is_chunk_manifest]
        assert len(manifest) == 1 and manifest[0].cipher_key
        client = WeedClient(master.url())
        raw = client.download(manifest[0].file_id)
        # the plaintext manifest would contain chunk keys as hex JSON
        assert fake[0].cipher_key.encode() not in raw
        assert b"cipher_key" not in raw
        # the streamer opens it transparently
        resolved = fs.streamer.resolve(out)
        assert [c.file_id for c in resolved] == \
            [c.file_id for c in fake]
    finally:
        fs.stop()


def test_filer_cipher_roundtrip(cluster, tmp_path):
    from seaweedfs_tpu.filer.server import FilerServer
    master, _ = cluster
    fs = FilerServer(master.url(), port=0,
                     store_path=str(tmp_path / "filer.db"),
                     chunk_size=512, cipher=True)
    fs.start()
    try:
        base = fs.url()  # already scheme-qualified
        payload = TEXT[:2000]  # several 512-byte chunks
        rpc.call(f"{base}/docs/secret.txt", "POST", payload)
        # entry metadata carries per-chunk keys
        meta = rpc.call(f"{base}/docs/secret.txt?metadata=true")
        if isinstance(meta, (bytes, bytearray)):
            meta = json.loads(meta)
        chunks = meta.get("chunks", [])
        assert chunks and all(c.get("cipher_key") for c in chunks)
        # chunk needles on the volume server are opaque
        client = WeedClient(master.url())
        raw = client.download(chunks[0]["file_id"])
        assert payload[:len(raw)] != raw and payload[:64] not in raw
        # the filer read path decrypts transparently
        assert rpc.call(f"{base}/docs/secret.txt") == payload
        # ranged read through the decrypting streamer
        resp, conn = rpc._request(f"{base}/docs/secret.txt", "GET",
                                  None, 10.0,
                                  req_headers={"Range": "bytes=100-299"})
        part = resp.read()
        rpc._finish(conn, resp)
        assert part == payload[100:300]
    finally:
        fs.stop()


def test_export_names_gzip_needles(cluster, tmp_path):
    """weed export writes gzip-stored needles under name.gz — the tar
    holds the stored bytes, so the name must say so (export.go)."""
    import tarfile
    from seaweedfs_tpu.command import COMMANDS, _load_all, parse_flags
    master, servers = cluster
    client = WeedClient(master.url())
    r = client.upload(TEXT, name="doc.txt")
    assert r["is_compressed"]
    vid = int(r["fid"].split(",")[0])
    holder = next(vs for vs in servers
                  if vs.store.find_volume(vid) is not None)
    vol_dir = holder.store.find_volume(vid).dir
    holder.store.find_volume(vid).sync()
    _load_all()
    out = tmp_path / "vol.tar"
    flags, rest = parse_flags(
        [f"-dir={vol_dir}", f"-volumeId={vid}", f"-o={out}"])
    assert COMMANDS["export"].run(flags, rest) == 0
    with tarfile.open(out) as tf:
        names = tf.getnames()
        assert "doc.txt.gz" in names
        member = tf.extractfile("doc.txt.gz").read()
    assert gzip.decompress(member) == TEXT
