"""Query engine: SQL parsing, JSON/CSV execution, volume Query RPC,
S3 SelectObjectContent.

Reference behaviors: weed/query/json/query_json.go,
server/volume_grpc_query.go, pb/volume_server.proto:92.
"""

import json
import struct
import urllib.request
import zlib

import pytest

from seaweedfs_tpu.query import parse_select, run_query
from seaweedfs_tpu.query.sql import SqlError


# -- parser ----------------------------------------------------------------

def test_parse_star_and_columns():
    s = parse_select("SELECT * FROM S3Object")
    assert s.columns == [] and s.where is None
    s = parse_select("SELECT s.name, s.age FROM S3Object s")
    assert s.columns == ["name", "age"]


def test_parse_where_tree():
    s = parse_select(
        "SELECT * FROM s WHERE (a = 1 OR b = 'x''y') AND NOT c > 2.5")
    get = lambda col: {"a": 1, "b": "x'y", "c": 1}[col]  # noqa: E731
    assert s.matches(get)
    get2 = lambda col: {"a": 2, "b": "z", "c": 1}[col]  # noqa: E731
    assert not s.matches(get2)


def test_parse_errors():
    for bad in ("SELECT", "SELECT * FROM s WHERE", "DROP TABLE x",
                "SELECT * FROM s WHERE a ~ 1"):
        with pytest.raises(SqlError):
            parse_select(bad)


# -- engine ----------------------------------------------------------------

NDJSON = b"""\
{"name":"ada","age":36,"city":"london","nested":{"lang":"math"}}
{"name":"grace","age":45,"city":"nyc","nested":{"lang":"cobol"}}
{"name":"alan","age":41,"city":"london"}
"""


def test_json_filter_and_projection():
    out = run_query(NDJSON,
                    "SELECT s.name FROM S3Object s "
                    "WHERE s.city = 'london' AND s.age > 36")
    rows = [json.loads(line) for line in out.splitlines()]
    assert rows == [{"name": "alan"}]


def test_json_nested_path_and_null():
    out = run_query(NDJSON, "SELECT name FROM s "
                    "WHERE nested.lang = 'cobol'")
    assert json.loads(out) == {"name": "grace"}
    out = run_query(NDJSON, "SELECT name FROM s "
                    "WHERE nested.lang IS NULL")
    assert json.loads(out) == {"name": "alan"}


def test_json_like_and_or():
    out = run_query(NDJSON, "SELECT name FROM s WHERE "
                    "name LIKE 'a%' OR city = 'nyc'")
    names = [json.loads(x)["name"] for x in out.splitlines()]
    assert names == ["ada", "grace", "alan"]


def test_json_single_doc_and_array():
    doc = json.dumps({"a": 1, "b": 2}).encode()
    assert json.loads(run_query(doc, "SELECT a FROM s")) == {"a": 1}
    arr = json.dumps([{"a": 1}, {"a": 2}]).encode()
    out = [json.loads(x) for x in
           run_query(arr, "SELECT * FROM s WHERE a >= 2").splitlines()]
    assert out == [{"a": 2}]


CSV = b"id,name,score\n1,ada,99\n2,grace,97\n3,alan,85\n"


def test_csv_with_header():
    out = run_query(CSV, "SELECT name FROM s WHERE score >= 97",
                    input_format="csv")
    names = [json.loads(x)["name"] for x in out.splitlines()]
    assert names == ["ada", "grace"]


def test_csv_no_header_ordinals():
    raw = b"1,ada\n2,grace\n"
    out = run_query(raw, "SELECT _2 FROM s WHERE _1 = '2'",
                    input_format="csv", csv_header=False)
    assert json.loads(out) == {"_2": "grace"}


def test_csv_output_format():
    out = run_query(CSV, "SELECT name, score FROM s WHERE score > 90",
                    input_format="csv", output_format="csv")
    assert out.decode().splitlines() == ["ada,99", "grace,97"]


# -- cluster wiring --------------------------------------------------------

@pytest.fixture(scope="module")
def stack(tmp_path_factory):
    from seaweedfs_tpu.cluster.client import WeedClient
    from seaweedfs_tpu.cluster.master import MasterServer
    from seaweedfs_tpu.cluster.volume_server import VolumeServer
    from seaweedfs_tpu.filer.server import FilerServer
    from seaweedfs_tpu.s3api.server import S3ApiServer
    tmp = tmp_path_factory.mktemp("query-stack")
    master = MasterServer(volume_size_limit_mb=64, meta_dir=str(tmp))
    master.start()
    vs = VolumeServer(master.url(), [str(tmp / "vs")], pulse_seconds=60)
    vs.start()
    filer = FilerServer(master.url())
    filer.start()
    s3 = S3ApiServer(filer.url())
    s3.start()
    yield master, vs, filer, s3, WeedClient(master.url())
    s3.stop()
    filer.stop()
    vs.stop()
    master.stop()


def test_volume_server_query_rpc(stack):
    from seaweedfs_tpu.cluster import rpc
    _m, vs, _f, _s3, client = stack
    fid = client.upload_data(NDJSON)
    out = rpc.call(vs.server.url() + "/query", "POST", json.dumps({
        "fid": fid,
        "query": "SELECT s.name FROM S3Object s WHERE s.age > 40",
    }).encode())
    names = sorted(json.loads(x)["name"] for x in out.splitlines())
    assert names == ["alan", "grace"]


def _parse_event_stream(data: bytes) -> dict:
    """Decode AWS event-stream frames -> {event_type: payload}."""
    out = {}
    pos = 0
    while pos < len(data):
        total, hlen = struct.unpack_from(">II", data, pos)
        pc, = struct.unpack_from(">I", data, pos + 8)
        assert pc == zlib.crc32(data[pos:pos + 8])
        headers_raw = data[pos + 12:pos + 12 + hlen]
        payload = data[pos + 12 + hlen:pos + total - 4]
        mc, = struct.unpack_from(">I", data, pos + total - 4)
        assert mc == zlib.crc32(data[pos:pos + total - 4])
        # parse headers for :event-type
        et = None
        hp = 0
        while hp < len(headers_raw):
            nlen = headers_raw[hp]
            name = headers_raw[hp + 1:hp + 1 + nlen].decode()
            assert headers_raw[hp + 1 + nlen] == 7
            vlen, = struct.unpack_from(">H", headers_raw,
                                       hp + 2 + nlen)
            value = headers_raw[hp + 4 + nlen:
                                hp + 4 + nlen + vlen].decode()
            if name == ":event-type":
                et = value
            hp += 4 + nlen + vlen
        out[et] = out.get(et, b"") + payload
        pos += total
    return out


def test_s3_select_object_content(stack):
    _m, _vs, _f, s3, _c = stack
    # create bucket + object
    urllib.request.urlopen(urllib.request.Request(
        s3.url() + "/qbucket", method="PUT")).read()
    urllib.request.urlopen(urllib.request.Request(
        s3.url() + "/qbucket/people.json", data=NDJSON,
        method="PUT")).read()
    req_xml = b"""<?xml version="1.0" encoding="UTF-8"?>
<SelectObjectContentRequest>
  <Expression>SELECT s.name FROM S3Object s WHERE s.age &gt; 40</Expression>
  <ExpressionType>SQL</ExpressionType>
  <InputSerialization><JSON><Type>LINES</Type></JSON></InputSerialization>
  <OutputSerialization><JSON/></OutputSerialization>
</SelectObjectContentRequest>"""
    with urllib.request.urlopen(urllib.request.Request(
            s3.url() + "/qbucket/people.json?select&select-type=2",
            data=req_xml, method="POST")) as resp:
        events = _parse_event_stream(resp.read())
    assert "End" in events and "Stats" in events
    names = sorted(json.loads(x)["name"]
                   for x in events["Records"].splitlines())
    assert names == ["alan", "grace"]


def test_s3_select_csv(stack):
    _m, _vs, _f, s3, _c = stack
    urllib.request.urlopen(urllib.request.Request(
        s3.url() + "/qbucket/scores.csv", data=CSV, method="PUT")).read()
    req_xml = b"""<SelectObjectContentRequest>
  <Expression>SELECT name FROM S3Object WHERE score &gt;= 97</Expression>
  <InputSerialization><CSV><FileHeaderInfo>USE</FileHeaderInfo></CSV></InputSerialization>
  <OutputSerialization><CSV/></OutputSerialization>
</SelectObjectContentRequest>"""
    with urllib.request.urlopen(urllib.request.Request(
            s3.url() + "/qbucket/scores.csv?select&select-type=2",
            data=req_xml, method="POST")) as resp:
        events = _parse_event_stream(resp.read())
    assert events["Records"].decode().splitlines() == ["ada", "grace"]
