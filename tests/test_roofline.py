"""Device roofline plane (stats/roofline.py + the streamed-pipeline
occupancy recorder).

Covers ISSUE 18's acceptance gates: the kernel catalog is closed and
anti-rot tested, the analytic cost model matches the Pallas
CostEstimate algebra exactly, probe_peaks() is disk-cached keyed by
backend/device kind (a tampered cache is believed, proving no
re-probe), achieved fractions land in bounded rings with windowed
sketches, the conservation check pins analytic bytes to
ledger-measured bytes within max(1%, 4KB), PipelineRecorder survives
production duty (bounded overflow, concurrent writers, exact
injected-clock gantt/occupancy/bubble math), sustained occupancy
collapse emits a rate-limited device.slow event, the disarmed path is
a single flag check (the record hook is provably never reached), a
deliberately slow fence is included in the reported kernel wall
(execution-fencing regression), nbytes=0 observations still
materialize the ec_stage_bytes series, and the four new instruments
scrape promcheck-clean on master and volume server of a live cluster
with /debug/device, /cluster/device, healthz, and cluster.roofline
all agreeing."""

import json
import threading
import time

import numpy as np
import pytest

from seaweedfs_tpu.cluster import rpc
from seaweedfs_tpu.cluster.master import MasterServer
from seaweedfs_tpu.cluster.volume_server import VolumeServer
from seaweedfs_tpu.events.journal import JOURNAL
from seaweedfs_tpu.ops import coder_pallas
from seaweedfs_tpu.ops.coder_pallas import PallasCoder
from seaweedfs_tpu.parallel.stream_pipeline import PipelineRecorder
from seaweedfs_tpu.shell import CommandEnv, run_command
from seaweedfs_tpu.stats import metrics, roofline
from seaweedfs_tpu.stats.promcheck import validate_exposition

pytestmark = pytest.mark.roofline


FAKE_PEAKS = {"version": roofline._PEAKS_VERSION, "backend": "fake",
              "device_kind": "fake",
              "matmul_flops": {"int8": 1e9, "bf16": 1e9, "f32": 1e9},
              "membw_bps": 1e8, "h2d_bps": 1e9, "d2h_bps": 1e9,
              "host_stream_bps": 1e9, "probe_seconds": 0.0}


@pytest.fixture
def fake_peaks(monkeypatch):
    """Deterministic peaks: achieved fractions become exact algebra
    instead of hardware-dependent measurements."""
    monkeypatch.setattr(roofline, "_peaks", dict(FAKE_PEAKS))


# -- catalog + cost model ----------------------------------------------------

def test_kernel_catalog_anti_rot():
    """Closed catalog, like events TYPES and flows PURPOSES: exactly
    the documented kernels exist, each validates and has a
    description; anything else raises at the record site."""
    expected = {"encode_kernel", "encode_crc_kernel",
                "reconstruct_kernel", "batch_encode",
                "batch_reconstruct"}
    assert set(roofline.KERNELS) == expected
    for k in roofline.KERNELS:
        assert roofline.validate(k) == k
        assert roofline.KERNELS[k], f"kernel {k} has no description"
    for bad in ("encode", "", "ENCODE_KERNEL", "matmul"):
        with pytest.raises(ValueError):
            roofline.validate(bad)
    ledger = roofline.RooflineLedger()
    with pytest.raises(ValueError):
        ledger.record("matmul", "rs", "int8", out_rows=4, in_rows=10,
                      n=64, seconds=0.1)
    assert roofline.PIPELINE_STAGES == ("stack", "dispatch", "device",
                                        "drain")


def test_cost_model_algebra():
    """The analytic model IS the Pallas CostEstimate algebra: bytes =
    (in+out)*n, macs = 8*out * 8*in * n, CRC folds 8*(in+out)*32*n
    more, flops = 2*macs, everything linear in batch."""
    c = roofline.cost_model(4, 10, 4096)
    assert c["bytes"] == 14 * 4096
    assert c["macs"] == 8 * 4 * 8 * 10 * 4096
    assert c["flops"] == 2 * c["macs"]
    assert c["intensity"] == pytest.approx(c["flops"] / c["bytes"])

    crc = roofline.cost_model(4, 10, 4096, crc=True)
    assert crc["bytes"] == c["bytes"]
    assert crc["macs"] == c["macs"] + 8 * 14 * 32 * 4096

    b = roofline.cost_model(4, 10, 4096, batch=3)
    assert b["bytes"] == 3 * c["bytes"]
    assert b["macs"] == 3 * c["macs"]

    assert roofline.geometry_key(4, 10, 4096) == "4x10x4096"
    assert roofline.geometry_key(4, 10, 4096, batch=8) == "4x10x4096b8"


def test_gf2_work_dense_vs_effective():
    """Paar elimination on a hand case: rows {a,b,c} and {a,b,d} cost
    4 dense XORs but 3 after factoring the shared (a,b) pair; on the
    real rs(10,4) parity bit-matrix elimination must win big (the
    bench's baseline column, arxiv 2108.02692 territory)."""
    m = np.array([[1, 1, 1, 0],
                  [1, 1, 0, 1]], np.uint8)
    assert roofline.dense_gf2_work(m) == 4
    assert roofline.effective_gf2_work(m) == 3
    # A weight-1 row costs zero XORs in both schedules.
    assert roofline.dense_gf2_work(np.eye(4, dtype=np.uint8)) == 0
    assert roofline.effective_gf2_work(np.eye(4, dtype=np.uint8)) == 0

    bm = np.asarray(PallasCoder(10, 4).codec.parity_bitmatrix())
    dense = roofline.dense_gf2_work(bm)
    eff = roofline.effective_gf2_work(bm)
    assert 0 < eff < dense


# -- peak probing ------------------------------------------------------------

def test_probe_peaks_disk_cache(tmp_path, monkeypatch):
    """One real probe writes the cache; a process 'restart' (module
    memo cleared) must read the file back instead of re-probing — a
    tampered sentinel value coming back proves no re-measurement."""
    monkeypatch.setenv("SEAWEEDFS_TPU_ROOFLINE_CACHE", str(tmp_path))
    monkeypatch.setattr(roofline, "_peaks", None)
    doc = roofline.probe_peaks(force=True)
    assert doc["version"] == roofline._PEAKS_VERSION
    assert doc["backend"] not in ("", "none")
    assert doc["matmul_flops"].get("int8", 0) > 0
    assert doc["membw_bps"] > 0
    path = roofline._cache_path(doc["backend"], doc["device_kind"])
    with open(path, encoding="utf-8") as f:
        on_disk = json.load(f)
    assert on_disk["membw_bps"] == doc["membw_bps"]

    on_disk["membw_bps"] = 123456.0
    with open(path, "w", encoding="utf-8") as f:
        json.dump(on_disk, f)
    monkeypatch.setattr(roofline, "_peaks", None)
    assert roofline.probe_peaks()["membw_bps"] == 123456.0
    # The memo serves every later call without touching disk again.
    assert roofline.probe_peaks()["membw_bps"] == 123456.0


def test_roofline_floor(fake_peaks):
    """max(compute floor, bandwidth floor); None when the peak is
    missing or zeroed (a fraction against a made-up peak is noise)."""
    peaks = roofline.probe_peaks()
    assert roofline.roofline_floor_seconds(
        2e9, 1e6, peaks, "int8") == pytest.approx(2.0)
    assert roofline.roofline_floor_seconds(
        1e6, 1e9, peaks, "int8") == pytest.approx(10.0)
    assert roofline.roofline_floor_seconds(
        1e6, 1e6, peaks, "fp4") is None
    assert roofline.roofline_floor_seconds(
        1e6, 1e6, {"matmul_flops": {}, "membw_bps": 0.0},
        "int8") is None


# -- the ledger --------------------------------------------------------------

def test_ledger_ring_bounded_sketches_and_conservation(fake_peaks):
    """300 records: the ring holds the newest 256, the series totals
    stay absolute (heartbeat merge is idempotent), achieved fractions
    are exact against the fake peaks, and conservation flags exactly
    the row whose measured bytes drifted past max(1%, 4KB)."""
    t = [1000.0]
    ledger = roofline.RooflineLedger(clock=lambda: t[0])
    cost = roofline.cost_model(4, 10, 4096)
    floor = roofline.roofline_floor_seconds(
        cost["flops"], cost["bytes"], FAKE_PEAKS, "int8")
    for _ in range(300):
        t[0] += 0.01
        row = ledger.record(
            "encode_kernel", "rs", "int8", out_rows=4, in_rows=10,
            n=4096, seconds=floor * 2, measured_bytes=cost["bytes"])
    assert row["achieved"] == pytest.approx(0.5)
    assert row["geometry"] == "4x10x4096"
    assert len(ledger.recent(1000)) == roofline._RING_MAX

    table = ledger.kernel_table()
    assert len(table) == 1
    assert table[0]["count"] == 300
    assert table[0]["seconds"] == pytest.approx(300 * floor * 2,
                                                rel=1e-3)
    assert table[0]["bytes"] == 300 * cost["bytes"]
    assert table[0]["work"] == 300 * cost["macs"]
    assert table[0]["achieved_p50"] == pytest.approx(0.5, rel=0.15)

    cons = ledger.conservation()
    assert cons["ok"] and cons["checked"] == roofline._RING_MAX

    # Off-by-more-than-tolerance measured bytes: the model drifted.
    ledger.record("encode_kernel", "rs", "int8", out_rows=4,
                  in_rows=10, n=4096, seconds=0.1,
                  measured_bytes=cost["bytes"] * 2)
    cons = ledger.conservation()
    assert not cons["ok"]
    assert cons["violations"][0]["kernel"] == "encode_kernel"

    # An achieved fraction never exceeds 1.0 (a kernel can't beat the
    # roofline; measurement jitter must not report that it did).
    fast = ledger.record("encode_kernel", "rs", "int8", out_rows=4,
                         in_rows=10, n=4096, seconds=floor / 10)
    assert fast["achieved"] == 1.0


def test_real_encode_records_and_conserves(fake_peaks):
    """The PallasCoder call sites feed the process ledger with
    measured bytes equal to the analytic payload — conservation by
    construction, checked against a real (interpret-mode) encode,
    fused-CRC encode, and reconstruct."""
    roofline.LEDGER.reset()
    roofline.set_armed(True)
    try:
        pc = PallasCoder(4, 2)
        data = np.arange(4 * 2048, dtype=np.uint8).reshape(4, 2048)
        parity = np.asarray(pc.encode(data))
        assert parity.shape == (2, 2048)
        pc.encode_with_crc(data)
        shards = {i: data[i] for i in range(4)}
        shards[4] = parity[0]
        pc.reconstruct({k: v for k, v in shards.items() if k != 0},
                       wanted=[0])
        kinds = {r["kernel"] for r in roofline.LEDGER.recent()}
        assert {"encode_kernel", "encode_crc_kernel",
                "reconstruct_kernel"} <= kinds
        cons = roofline.LEDGER.conservation()
        assert cons["ok"], cons["violations"]
        assert cons["checked"] >= 3
    finally:
        roofline.LEDGER.reset()


def test_disarmed_path_is_one_flag_check(monkeypatch):
    """-roofline=false reduces every call site to the ARMED check: a
    booby-trapped record hook proves the accounting code is never
    reached, and the kernels still run."""
    def boom(*a, **k):
        raise AssertionError("roofline hook reached while disarmed")

    monkeypatch.setattr(coder_pallas, "_record_roofline", boom)
    monkeypatch.setattr(roofline.RooflineLedger, "record", boom)
    roofline.set_armed(False)
    try:
        pc = PallasCoder(4, 2)
        data = np.ones((4, 1024), np.uint8)
        out = np.asarray(pc.encode(data))
        assert out.shape == (2, 1024)
        pc.encode_with_crc(data)
    finally:
        roofline.set_armed(True)


def test_fencing_includes_device_wait(fake_peaks, monkeypatch):
    """Execution-fencing regression: when the fence itself takes 50ms
    (modeling in-flight device work at block_until_ready time), the
    recorded kernel wall must include it.  A timer stopped before the
    fence — the async-dispatch flattery bug — fails here."""
    roofline.LEDGER.reset()
    roofline.set_armed(True)
    real_fence = coder_pallas.jax.block_until_ready

    def slow_fence(x):
        time.sleep(0.05)
        return real_fence(x)

    monkeypatch.setattr(coder_pallas.jax, "block_until_ready",
                        slow_fence)
    try:
        PallasCoder(4, 2).encode(np.ones((4, 1024), np.uint8))
        rows = [r for r in roofline.LEDGER.recent()
                if r["kernel"] == "encode_kernel"]
        assert rows, "encode never recorded"
        assert rows[-1]["seconds"] >= 0.05
    finally:
        roofline.LEDGER.reset()


def test_observe_ec_stage_counts_zero_bytes():
    """Satellite fix: nbytes=0 observations must still materialize the
    stage's ec_stage_bytes series (a family that only appears under
    byte-carrying load reads as a counter reset in rate() and silently
    under-counts stages whose first calls are zero-byte)."""
    stage = "zb_regression_stage"
    text0 = "\n".join(metrics.ec_stage_bytes.expose())
    assert f'stage="{stage}"' not in text0
    metrics.observe_ec_stage(stage, 0.001, 0)
    text1 = "\n".join(metrics.ec_stage_bytes.expose())
    assert f'stage="{stage}"' in text1
    assert metrics.ec_stage_bytes.value(stage=stage) == 0.0
    metrics.observe_ec_stage(stage, 0.001, 7)
    assert metrics.ec_stage_bytes.value(stage=stage) == 7.0


# -- PipelineRecorder as production component --------------------------------

def test_recorder_bounded_overflow():
    """Production duty means constant memory: both the event and span
    rings drop the oldest entries past maxlen, and the read side keeps
    computing over whatever survived."""
    rec = PipelineRecorder(maxlen=8)
    for i in range(100):
        rec.record("dispatched", i)
        rec.note_span("device", i, float(i), float(i) + 0.5)
    assert len(rec.events()) == 8
    assert len(rec.spans()) == 8
    assert [s[1] for s in rec.spans()] == list(range(92, 100))
    occ = rec.device_occupancy()
    assert occ["fraction"] is not None
    assert rec.gantt(last=4)[-1]["index"] == 99


def test_recorder_concurrent_writers():
    """Stages run on pool threads plus the main drain loop; concurrent
    note_span/record from 8 writers must never corrupt the rings."""
    rec = PipelineRecorder(maxlen=512)
    errs = []

    def hammer(tid):
        try:
            for i in range(200):
                rec.note_span("device", i, i + tid * 0.01,
                              i + tid * 0.01 + 0.5)
                rec.record("drained", i)
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    threads = [threading.Thread(target=hammer, args=(t,))
               for t in range(8)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    assert not errs
    assert len(rec.spans()) == 512
    assert rec.device_occupancy()["fraction"] is not None
    rec.bubble_attribution()


def test_recorder_gantt_occupancy_bubbles_exact():
    """Injected-clock math, no sleeps: two batches with known spans
    give an exact device-busy fraction, exact per-gap bubble
    attribution naming the starving stage, and index-ordered gantt
    rows that keep the widest interval for a re-noted stage."""
    rec = PipelineRecorder()
    rec.note_span("stack", 0, 0.0, 1.0)
    rec.note_span("dispatch", 0, 1.0, 2.0)
    rec.note_span("device", 0, 2.0, 4.0)
    rec.note_span("drain", 0, 4.0, 5.0)
    rec.note_span("stack", 1, 1.0, 3.0)
    rec.note_span("dispatch", 1, 3.0, 4.0)
    rec.note_span("device", 1, 4.0, 7.0)
    rec.note_span("drain", 1, 7.0, 8.0)

    occ = rec.device_occupancy()
    assert occ["window"] == [0.0, 8.0]
    assert occ["busy_seconds"] == pytest.approx(5.0)   # [2,7] union
    assert occ["fraction"] == pytest.approx(5.0 / 8.0)
    assert occ["stages"]["stack"] == pytest.approx(3.0 / 8.0)

    bub = rec.bubble_attribution()
    # Gaps: [0,2] (stack covers 2s of it, dispatch 1s) and [7,8]
    # (drain covers all 1s).  Starving stage = stack.
    assert bub["bubble_seconds"] == pytest.approx(3.0)
    assert bub["by_stage"]["stack"] == pytest.approx(2.0)
    assert bub["by_stage"]["dispatch"] == pytest.approx(1.0)
    assert bub["by_stage"]["drain"] == pytest.approx(1.0)
    assert bub["starving_stage"] == "stack"

    g = rec.gantt()
    assert [row["index"] for row in g] == [0, 1]
    assert g[0]["stages"]["device"] == [2.0, 4.0]
    # Split stack segments (the pool-wait exclusion pattern) widen.
    rec.note_span("stack", 0, 0.5, 1.5)
    assert rec.gantt()[0]["stages"]["stack"] == [0.0, 1.5]


def test_pipeline_collapse_emits_rate_limited_device_slow():
    """Three consecutive collapsed runs trip the streak and emit ONE
    device.slow (warn); further collapsed runs inside the rate-limit
    window stay silent; a healthy run resets the streak."""
    now = [100.0]
    ledger = roofline.RooflineLedger(clock=lambda: now[0])
    bad = PipelineRecorder()
    bad.note_span("dispatch", 0, 0.0, 9.0)
    bad.note_span("device", 0, 9.0, 10.0)     # 10% busy
    good = PipelineRecorder()
    good.note_span("device", 0, 0.0, 9.0)
    good.note_span("drain", 0, 9.0, 10.0)     # 90% busy

    seq0 = JOURNAL._seq

    def slow_events():
        return [e for e in JOURNAL.snapshot(type_="device.slow")
                if e["seq"] > seq0]

    for _ in range(3):
        ledger.note_pipeline("encode", bad, node="t:0")
    evs = slow_events()
    assert len(evs) == 1
    assert evs[0]["severity"] == "warn"
    assert evs[0]["attrs"]["pipeline"] == "encode"
    assert evs[0]["attrs"]["occupancy"] == pytest.approx(0.1)
    assert evs[0]["attrs"]["starving_stage"] == "dispatch"

    # Still collapsed but inside _EMIT_EVERY: no fresh event.
    ledger.note_pipeline("encode", bad)
    assert len(slow_events()) == 1
    # Past the window: one more.
    now[0] += roofline._EMIT_EVERY + 1.0
    ledger.note_pipeline("encode", bad)
    assert len(slow_events()) == 2

    occ = ledger.occupancy_summary()
    assert occ["any_collapsed"] and occ["collapsed"]["encode"]
    assert occ["latest"]["encode"]["fraction"] == pytest.approx(0.1)
    assert occ["latest"]["encode"]["starving_stage"] == "dispatch"

    ledger.note_pipeline("encode", good)
    occ = ledger.occupancy_summary()
    assert not occ["any_collapsed"]
    assert occ["latest"]["encode"]["fraction"] == pytest.approx(0.9)


# -- live cluster: surfaces + promcheck --------------------------------------

@pytest.fixture
def cluster(tmp_path):
    roofline.LEDGER.reset()
    roofline.set_armed(True)
    master = MasterServer(volume_size_limit_mb=64,
                          meta_dir=str(tmp_path / "meta"),
                          pulse_seconds=60)
    master.start()
    d = tmp_path / "vs0"
    d.mkdir()
    vs = VolumeServer(master.url(), [str(d)], max_volume_counts=[10],
                      pulse_seconds=60)
    vs.start()
    yield master, vs
    vs.stop()
    master.stop()
    roofline.LEDGER.reset()


def _seed_ledger():
    """One real interpret-mode encode plus an injected-clock collapsed
    pipeline folded into the process ledger — the device plane's full
    surface without a heavyweight streamed workload."""
    pc = PallasCoder(4, 2)
    pc.encode(np.ones((4, 2048), np.uint8))
    rec = PipelineRecorder()
    rec.note_span("stack", 0, 0.0, 8.0)
    rec.note_span("dispatch", 0, 8.0, 9.0)
    rec.note_span("device", 0, 9.0, 10.0)
    for _ in range(roofline._COLLAPSE_STREAK):
        roofline.LEDGER.note_pipeline("encode", rec, node="seed:0")


def test_debug_and_cluster_device_surfaces(cluster, tmp_path):
    """The acceptance gate: a recorded encode + collapsed streamed
    pipeline show up on /debug/device (volume AND master), roll up
    through the heartbeat into /cluster/device with a collapse
    warning, mark healthz's device section (warning, never 503-worthy
    by itself), and render through cluster.roofline with -save/-diff
    round-tripping."""
    master, vs = cluster
    _seed_ledger()

    doc = rpc.call(f"http://{vs.url()}/debug/device")
    assert doc["armed"] is True and doc["role"] == "volume"
    kernels = {r["kernel"] for r in doc["kernels"]}
    assert "encode_kernel" in kernels
    assert doc["conservation"]["ok"], doc["conservation"]
    occ = doc["occupancy"]["latest"]["encode"]
    assert occ["fraction"] == pytest.approx(0.1)
    assert occ["starving_stage"] == "stack"
    assert doc["pipelines"][-1]["gantt"], "gantt missing"

    # The role-generic mount answers on the master too.
    mdoc = rpc.call(f"{master.url()}/debug/device")
    assert mdoc["role"] == "master" and "peaks" in mdoc

    vs._send_heartbeat(full=True)
    cdoc = rpc.call(f"{master.url()}/cluster/device")
    assert vs.url() in cdoc["nodes"]
    merged = {r["kernel"] for r in cdoc["kernels"]}
    assert "encode_kernel" in merged
    assert any("collapsed" in w for w in cdoc["warnings"]), cdoc
    row = next(r for r in cdoc["kernels"]
               if r["kernel"] == "encode_kernel")
    assert row["count"] >= 1 and row["bytes"] > 0 and row["work"] > 0
    # ?kernel= filters; an uncataloged name is a loud error.
    fdoc = rpc.call(
        f"{master.url()}/cluster/device?kernel=batch_encode")
    assert all(r["kernel"] == "batch_encode" for r in fdoc["kernels"])
    with pytest.raises(Exception):
        rpc.call(f"{master.url()}/cluster/device?kernel=bogus")

    status, hdoc = rpc.call_status(f"{master.url()}/cluster/healthz")
    assert isinstance(hdoc, dict) and "device" in hdoc
    assert any("collapsed" in w for w in hdoc["device"]["warnings"])
    assert any(r["pipeline"] == "encode"
               for r in hdoc["device"]["occupancy"])
    # Occupancy collapse alone is a warning, not a health problem.
    assert not any("occupancy" in p for p in hdoc["problems"])

    env = CommandEnv(master.url())
    out = run_command(env, "cluster.roofline")
    assert "encode_kernel" in out and "peaks[" in out
    assert "starved by stack" in out
    assert "!!" in out
    save = str(tmp_path / "rl_base.json")
    out = run_command(env, f"cluster.roofline -save {save}")
    assert "kernel rows" in out
    out = run_command(env, f"cluster.roofline -diff {save}")
    assert "no achieved-fraction movement" in out


def test_promcheck_roofline_instruments_all_roles(cluster):
    """Every new instrument scrapes promcheck-clean on master and
    volume server, and the occupancy gauge carries the stage label."""
    master, vs = cluster
    _seed_ledger()
    mtext = bytes(rpc.call(f"{master.url()}/metrics")).decode()
    vtext = bytes(rpc.call(f"http://{vs.url()}/metrics")).decode()
    for text, who in ((mtext, "master"), (vtext, "volume")):
        assert validate_exposition(text) == [], f"{who} scrape dirty"
        for fam in ("SeaweedFS_kernel_seconds_total",
                    "SeaweedFS_kernel_bytes_total",
                    "SeaweedFS_kernel_work_total",
                    "SeaweedFS_device_occupancy"):
            assert fam in text, (who, fam)
    assert 'kernel="encode_kernel"' in vtext
    assert 'stage="device"' in vtext
