"""VolumeServer gRPC maintenance service against a live stack."""

import grpc
import pytest

from seaweedfs_tpu.cluster import rpc
from seaweedfs_tpu.cluster.client import WeedClient
from seaweedfs_tpu.cluster.master import MasterServer
from seaweedfs_tpu.cluster.volume_server import VolumeServer
from seaweedfs_tpu.pb import volume_server_pb2 as pb
from seaweedfs_tpu.pb.volume_grpc import VolumeGrpcServer

SVC = "/volume_server_pb.VolumeServer/"


@pytest.fixture
def stack(tmp_path):
    master = MasterServer(volume_size_limit_mb=64, meta_dir=str(tmp_path))
    master.start()
    vs = VolumeServer(master.url(), [str(tmp_path / "vs")],
                      pulse_seconds=60)
    vs.start()
    g = VolumeGrpcServer(vs, port=0)
    g.start()
    chan = grpc.insecure_channel(g.addr())
    yield master, vs, g, chan
    chan.close()
    g.stop()
    vs.stop()
    master.stop()


def _unary(chan, name, req, resp_cls):
    return chan.unary_unary(
        SVC + name,
        request_serializer=lambda m: m.SerializeToString(),
        response_deserializer=resp_cls.FromString)(req, timeout=15)


def _stream(chan, name, req, resp_cls):
    return chan.unary_stream(
        SVC + name,
        request_serializer=lambda m: m.SerializeToString(),
        response_deserializer=resp_cls.FromString)(req, timeout=15)


def test_vacuum_four_step_over_grpc(stack):
    """The reference master's vacuum orchestration sequence:
    Check -> Compact -> Commit (+ Cleanup) reclaims deleted space."""
    master, vs, _g, chan = stack
    client = WeedClient(master.url())
    fids = [client.upload_data(b"x" * 2000) for _ in range(10)]
    vid = int(fids[0].split(",")[0])
    same = [f for f in fids if int(f.split(",")[0]) == vid]
    for fid in same[: len(same) // 2 + 1]:
        client.delete(fid)
    chk = _unary(chan, "VacuumVolumeCheck",
                 pb.VacuumVolumeCheckRequest(volume_id=vid),
                 pb.VacuumVolumeCheckResponse)
    assert chk.garbage_ratio > 0
    _unary(chan, "VacuumVolumeCompact",
           pb.VacuumVolumeCompactRequest(volume_id=vid),
           pb.VacuumVolumeCompactResponse)
    _unary(chan, "VacuumVolumeCommit",
           pb.VacuumVolumeCommitRequest(volume_id=vid),
           pb.VacuumVolumeCommitResponse)
    _unary(chan, "VacuumVolumeCleanup",
           pb.VacuumVolumeCleanupRequest(volume_id=vid),
           pb.VacuumVolumeCleanupResponse)
    chk2 = _unary(chan, "VacuumVolumeCheck",
                  pb.VacuumVolumeCheckRequest(volume_id=vid),
                  pb.VacuumVolumeCheckResponse)
    assert chk2.garbage_ratio == 0
    # survivors still read back
    for fid in same[len(same) // 2 + 1:]:
        assert client.download(fid) == b"x" * 2000
    # commit without a staged compact is a clean precondition error
    with pytest.raises(grpc.RpcError) as ei:
        _unary(chan, "VacuumVolumeCommit",
               pb.VacuumVolumeCommitRequest(volume_id=vid),
               pb.VacuumVolumeCommitResponse)
    assert ei.value.code() == grpc.StatusCode.FAILED_PRECONDITION


def test_ec_lifecycle_over_grpc(stack):
    master, vs, _g, chan = stack
    client = WeedClient(master.url())
    fid = client.upload_data(b"ec payload " * 50)
    vid = int(fid.split(",")[0])
    _unary(chan, "VolumeEcShardsGenerate",
           pb.VolumeEcShardsGenerateRequest(volume_id=vid),
           pb.VolumeEcShardsGenerateResponse)
    _unary(chan, "VolumeEcShardsMount",
           pb.VolumeEcShardsMountRequest(volume_id=vid,
                                         shard_ids=list(range(14))),
           pb.VolumeEcShardsMountResponse)
    _unary(chan, "VolumeDelete",
           pb.VolumeDeleteRequest(volume_id=vid),
           pb.VolumeDeleteResponse)
    vs._send_heartbeat(full=True)
    # the needle now reads through the EC ladder
    assert client.download(fid) == b"ec payload " * 50
    # stream a shard range over gRPC and compare with the file bytes
    base = vs._volume_base(vid)
    with open(base + ".ec00", "rb") as f:
        expect = f.read(100)
    got = b"".join(r.data for r in _stream(
        chan, "VolumeEcShardRead",
        pb.VolumeEcShardReadRequest(volume_id=vid, shard_id=0,
                                    offset=0, size=100),
        pb.VolumeEcShardReadResponse))
    assert got == expect


def test_copyfile_stream_and_file_status(stack):
    master, vs, _g, chan = stack
    client = WeedClient(master.url())
    fid = client.upload_data(b"copy me " * 100)
    vid = int(fid.split(",")[0])
    vs.store.find_volume(vid).sync()
    st = _unary(chan, "ReadVolumeFileStatus",
                pb.ReadVolumeFileStatusRequest(volume_id=vid),
                pb.ReadVolumeFileStatusResponse)
    assert st.dat_file_size > 0 and st.file_count == 1
    blob = b"".join(r.file_content for r in _stream(
        chan, "CopyFile",
        pb.CopyFileRequest(volume_id=vid, ext=".dat"),
        pb.CopyFileResponse))
    assert len(blob) == st.dat_file_size
    with open(vs.store.find_volume(vid).file_name() + ".dat",
              "rb") as f:
        assert blob == f.read()
    # missing file with ignore flag: empty stream, no error
    out = list(_stream(chan, "CopyFile",
                       pb.CopyFileRequest(volume_id=vid, ext=".vif",
                                          ignore_source_file_not_found=True),
                       pb.CopyFileResponse))
    assert out == []


def test_batch_delete_and_status(stack):
    master, vs, _g, chan = stack
    client = WeedClient(master.url())
    fid = client.upload_data(b"to be deleted")
    out = _unary(chan, "BatchDelete",
                 pb.BatchDeleteRequest(file_ids=[fid, "999,deadbeef01"]),
                 pb.BatchDeleteResponse)
    by_fid = {r.file_id: r for r in out.results}
    assert by_fid[fid].status == 202
    assert by_fid["999,deadbeef01"].status == 404
    with pytest.raises(rpc.RpcError):
        client.download(fid)
    sst = _unary(chan, "VolumeServerStatus",
                 pb.VolumeServerStatusRequest(),
                 pb.VolumeServerStatusResponse)
    assert sst.disk_statuses and sst.disk_statuses[0].all > 0
    # a truly unknown method still answers UNIMPLEMENTED
    with pytest.raises(grpc.RpcError) as ei:
        chan.unary_unary(
            SVC + "NoSuchRpc",
            request_serializer=lambda m: m,
            response_deserializer=lambda b: b)(b"", timeout=5)
    assert ei.value.code() == grpc.StatusCode.UNIMPLEMENTED


def test_mark_readonly_and_configure(stack):
    master, vs, _g, chan = stack
    client = WeedClient(master.url())
    fid = client.upload_data(b"ro test")
    vid = int(fid.split(",")[0])
    _unary(chan, "VolumeMarkReadonly",
           pb.VolumeMarkReadonlyRequest(volume_id=vid),
           pb.VolumeMarkReadonlyResponse)
    st = _unary(chan, "VolumeStatus",
                pb.VolumeStatusRequest(volume_id=vid),
                pb.VolumeStatusResponse)
    assert st.is_read_only
    _unary(chan, "VolumeMarkWritable",
           pb.VolumeMarkWritableRequest(volume_id=vid),
           pb.VolumeMarkWritableResponse)
    cfg = _unary(chan, "VolumeConfigure",
                 pb.VolumeConfigureRequest(volume_id=vid,
                                           replication="001"),
                 pb.VolumeConfigureResponse)
    assert not cfg.error
    v = vs.store.find_volume(vid)
    assert str(v.super_block.replica_placement) == "001"


def test_query_rpc_streams_filtered_stripes(stack):
    """Query (pb/volume_server.proto:92, volume_grpc_query.go): JSON
    lines filtered by (field operand value), selections projected into
    one QueriedStripe per file id — 36/36 RPC parity."""
    master, vs, _g, chan = stack
    client = WeedClient(master.url())
    doc = (b'{"name":"alice","age":31,"city":"zurich"}\n'
           b'{"name":"bob","age":25,"city":"basel"}\n'
           b'{"name":"carol","age":40,"city":"bern"}\n')
    fid = client.upload_data(doc)
    fid2 = client.upload_data(
        b'{"name":"dave","age":50,"city":"geneva"}\n')
    req = pb.QueryRequest(
        selections=["name", "age"],
        from_file_ids=[fid, fid2],
        filter=pb.QueryRequest.Filter(field="age", operand=">",
                                      value="30"),
        input_serialization=pb.QueryRequest.InputSerialization(
            json_input=pb.QueryRequest.InputSerialization.JSONInput(
                type="LINES")))
    stripes = list(_stream(chan, "Query", req, pb.QueriedStripe))
    assert len(stripes) == 2  # one stripe per file id
    # json.ToJson shape: selection names unquoted, values raw.
    assert stripes[0].records == b'{name:"alice",age:31}{name:"carol",age:40}'
    assert stripes[1].records == b'{name:"dave",age:50}'
    # Existence-only filter (empty operand) passes every line with the
    # field; missing file id -> NOT_FOUND.
    req2 = pb.QueryRequest(
        selections=["city"], from_file_ids=[fid],
        filter=pb.QueryRequest.Filter(field="name"))
    (s,) = list(_stream(chan, "Query", req2, pb.QueriedStripe))
    assert s.records.count(b"city:") == 3
    with pytest.raises(grpc.RpcError) as ei:
        list(_stream(chan, "Query", pb.QueryRequest(
            from_file_ids=["999,deadbeef00"]), pb.QueriedStripe))
    assert ei.value.code() == grpc.StatusCode.NOT_FOUND
