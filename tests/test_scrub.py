"""End-to-end data integrity: crash-safe volume recovery (torn tails,
stale/missing .idx), the background scrub's detection + quarantine, and
self-healing repair from replicas and through the TPU EC decode path —
plus the kill -9 chaos test proving zero acknowledged-write loss."""

import glob
import os
import signal
import subprocess
import sys
import threading
import time

import pytest

from seaweedfs_tpu import fault
from seaweedfs_tpu.cluster import rpc
from seaweedfs_tpu.cluster.client import WeedClient
from seaweedfs_tpu.cluster.master import MasterServer
from seaweedfs_tpu.cluster.volume_server import VolumeServer
from seaweedfs_tpu.core import types as t
from seaweedfs_tpu.core.needle import Needle
from seaweedfs_tpu.events import JOURNAL
from seaweedfs_tpu.stats.metrics import needle_repairs_total
from seaweedfs_tpu.storage.volume import (CorruptNeedleError,
                                          NotFoundError, Volume)

pytestmark = pytest.mark.scrub


# -- crash-safe mount --------------------------------------------------------

def _mk_volume(tmp_path, n_needles=5, vid=7):
    v = Volume(str(tmp_path), "", vid, use_worker=False)
    fids = []
    for i in range(n_needles):
        n = Needle(cookie=0x1234 + i, id=100 + i,
                   data=f"needle payload {i} ".encode() * 8)
        v.write_needle(n)
        fids.append((n.id, n.cookie, n.data))
    v.sync()
    return v, fids


def test_torn_tail_is_truncated_on_mount(tmp_path):
    v, fids = _mk_volume(tmp_path)
    base = v.file_name()
    v.close()
    good_size = os.path.getsize(base + ".dat")
    # A kill -9 mid-write: half a record header of garbage at the tail.
    with open(base + ".dat", "ab") as f:
        f.write(b"\xde\xad\xbe\xef" * 3)
    v2 = Volume(str(tmp_path), "", 7, create=False, use_worker=False)
    try:
        assert os.path.getsize(base + ".dat") == good_size
        assert v2.dat_size() == good_size
        for key, cookie, data in fids:
            assert v2.read_needle(key, cookie).data == data
        # The volume is fully writable again: appends land aligned.
        n = Needle(cookie=1, id=999, data=b"post-recovery write")
        v2.write_needle(n)
        assert v2.read_needle(999, 1).data == b"post-recovery write"
    finally:
        v2.close()


def test_lost_idx_tail_entries_are_rejournaled(tmp_path):
    """Crash between the .dat fsync and the .idx append: the record is
    on disk but unindexed — recovery must re-journal it, or an
    acknowledged fsync write would vanish."""
    from seaweedfs_tpu.core import idx as idx_mod
    v, fids = _mk_volume(tmp_path)
    base = v.file_name()
    v.close()
    isize = os.path.getsize(base + ".idx")
    with open(base + ".idx", "r+b") as f:
        f.truncate(isize - 2 * idx_mod.ENTRY_SIZE)  # lose last 2 entries
    v2 = Volume(str(tmp_path), "", 7, create=False, use_worker=False)
    try:
        for key, cookie, data in fids:
            assert v2.read_needle(key, cookie).data == data
        assert v2.file_count() == len(fids)
    finally:
        v2.close()


def test_missing_idx_regenerated_from_dat(tmp_path):
    v, fids = _mk_volume(tmp_path)
    base = v.file_name()
    v.delete_needle(fids[1][0])  # a tombstone must survive the regen
    v.close()
    os.remove(base + ".idx")
    v2 = Volume(str(tmp_path), "", 7, create=False, use_worker=False)
    try:
        assert v2.read_needle(fids[0][0], fids[0][1]).data == fids[0][2]
        with pytest.raises(NotFoundError):
            v2.read_needle(fids[1][0])
    finally:
        v2.close()


def test_stale_idx_beyond_eof_defers_to_scanner(tmp_path):
    """An .idx whose furthest entry points past the .dat EOF is lying:
    the scanner-based regen must win, and the torn .dat tail goes."""
    v, fids = _mk_volume(tmp_path)
    base = v.file_name()
    v.close()
    # Chop the .dat mid-way through the LAST record.
    size = os.path.getsize(base + ".dat")
    with open(base + ".dat", "r+b") as f:
        f.truncate(size - 10)
    v2 = Volume(str(tmp_path), "", 7, create=False, use_worker=False)
    try:
        # Last record is gone (it was torn); the rest must be intact
        # and the index must agree with the data.
        for key, cookie, data in fids[:-1]:
            assert v2.read_needle(key, cookie).data == data
        with pytest.raises(NotFoundError):
            v2.read_needle(fids[-1][0])
        assert v2.dat_size() == os.path.getsize(base + ".dat")
        assert v2.dat_size() % t.NEEDLE_PADDING_SIZE == 0
    finally:
        v2.close()


def test_remount_after_delete_is_idempotent(tmp_path):
    """A volume whose LAST operation was a delete leaves a trailing
    tombstone marker past the furthest write entry: repeated mounts
    must not re-journal it (idx growth) or report phantom recovery."""
    v, fids = _mk_volume(tmp_path)
    base = v.file_name()
    v.delete_needle(fids[-1][0])
    v.close()
    isize = os.path.getsize(base + ".idx")
    seq0 = JOURNAL._seq
    for _ in range(3):
        v2 = Volume(str(tmp_path), "", 7, create=False,
                    use_worker=False)
        v2.close()
        assert os.path.getsize(base + ".idx") == isize
    assert not [ev for ev in JOURNAL.snapshot(type_="volume.recovered")
                if ev["seq"] > seq0]


def test_partial_idx_entry_truncated(tmp_path):
    from seaweedfs_tpu.core import idx as idx_mod
    v, fids = _mk_volume(tmp_path)
    base = v.file_name()
    v.close()
    with open(base + ".idx", "ab") as f:
        f.write(b"\x01\x02\x03")  # torn idx append
    v2 = Volume(str(tmp_path), "", 7, create=False, use_worker=False)
    try:
        assert os.path.getsize(base + ".idx") % idx_mod.ENTRY_SIZE == 0
        for key, cookie, data in fids:
            assert v2.read_needle(key, cookie).data == data
    finally:
        v2.close()


def test_volume_sync_fsyncs_idx_too(tmp_path, monkeypatch):
    v, _fids = _mk_volume(tmp_path)
    try:
        synced = []
        real_fsync = os.fsync

        def spy(fd):
            synced.append(fd)
            return real_fsync(fd)

        monkeypatch.setattr(os, "fsync", spy)
        v.sync()
        assert v._dat.fileno() in synced
        assert v.nm._idx_file.fileno() in synced
    finally:
        v.close()


def test_repair_tickets_survive_restart(tmp_path):
    """A quarantined needle's repair ticket persists: after a server
    restart the volume still reports corrupt (healthz must not lie
    healthy) and repair_needle still closes the ticket."""
    v, fids = _mk_volume(tmp_path)
    key, cookie, data = fids[0]
    assert v.quarantine_needle(key)
    assert v.corrupt_count() == 1
    v.close()
    v2 = Volume(str(tmp_path), "", 7, create=False, use_worker=False)
    try:
        assert v2.corrupt_count() == 1
        assert key in v2.repair_tickets
        n = Needle(cookie=cookie, id=key, data=data)
        v2.repair_needle(n)
        assert v2.corrupt_count() == 0
        assert v2.read_needle(key, cookie).data == data
    finally:
        v2.close()
    v3 = Volume(str(tmp_path), "", 7, create=False, use_worker=False)
    try:
        assert v3.corrupt_count() == 0  # the closed ticket stays closed
    finally:
        v3.close()


# -- .ecc shard checksums ----------------------------------------------------

def test_ecc_sidecar_matches_files_and_detects_flips(tmp_path):
    from seaweedfs_tpu.ec import TOTAL_SHARDS, to_ext
    from seaweedfs_tpu.ec.encoder import (write_ec_files,
                                          write_sorted_file_from_idx)
    from seaweedfs_tpu.ec.integrity import ShardChecksums, file_block_crcs
    v, _fids = _mk_volume(tmp_path, n_needles=20)
    base = v.file_name()
    v.close()
    write_sorted_file_from_idx(base)
    write_ec_files(base)
    ecc = ShardChecksums.load(base)
    for sid in range(TOTAL_SHARDS):
        assert ecc.get(sid) == file_block_crcs(base + to_ext(sid))
        assert ecc.verify_file(sid, base + to_ext(sid)) == []
    # Flip one byte in a parity shard — needle CRCs can't see parity
    # rot, the sidecar must.
    with open(base + to_ext(12), "r+b") as f:
        f.seek(100)
        byte = f.read(1)
        f.seek(100)
        f.write(bytes((byte[0] ^ 0xFF,)))
    assert ecc.verify_file(12, base + to_ext(12)) == [0]


# -- scrub + self-healing in a cluster ---------------------------------------

@pytest.fixture
def cluster(tmp_path):
    master = MasterServer(volume_size_limit_mb=16,
                          meta_dir=str(tmp_path / "meta"),
                          pulse_seconds=60)
    master.start()
    servers = []
    for i in range(2):
        d = tmp_path / f"vs{i}"
        d.mkdir()
        vs = VolumeServer(master.url(), [str(d)],
                          max_volume_counts=[50], pulse_seconds=60)
        vs.start()
        servers.append(vs)
    yield master, servers
    fault.disarm_all()
    for vs in servers:
        vs.stop()
    master.stop()


def _grow_and_corrupt_write(master, collection, replication=""):
    """One volume in `collection`, one clean needle, then one needle
    whose local copy is bit-rotted at write time via the volume.corrupt
    fault point.  Returns (vid, primary_url, corrupt_fid, payload)."""
    rep = f"&replication={replication}" if replication else ""
    rpc.call(f"{master.url()}/vol/grow?count=1"
             f"&collection={collection}{rep}", "POST")
    a1 = rpc.call(f"{master.url()}/dir/assign?"
                  f"collection={collection}{rep}")
    rpc.call(f"http://{a1['url']}/{a1['fid']}", "POST", b"clean needle")
    a2 = rpc.call(f"{master.url()}/dir/assign?"
                  f"collection={collection}{rep}")
    payload = b"soon to be rotten " * 32
    fault.arm("volume.corrupt", "fail*1")
    try:
        rpc.call(f"http://{a2['url']}/{a2['fid']}", "POST", payload)
    finally:
        fault.disarm_all()
    return int(a2["fid"].split(",")[0]), a2["url"], a2["fid"], payload


def _journal_types_since(seq):
    return {ev["type"] for ev in JOURNAL.snapshot()
            if ev["seq"] > seq}


def test_self_healing_replicated_volume(cluster):
    """Acceptance (a): bit-rot on one replica is detected by the scrub,
    quarantined (healthz degraded), then repaired from the healthy
    sibling (healthz healthy again), with events + metrics emitted."""
    master, _servers = cluster
    seq0 = JOURNAL._seq
    vid, url, fid, payload = _grow_and_corrupt_write(
        master, "healrep", replication="001")
    before = needle_repairs_total.value(source="replica")

    # Detection pass (no repair): quarantine + degraded healthz.
    out = rpc.call_json(f"http://{url}/admin/scrub", "POST",
                        {"volume": vid})
    report = next(r for r in out["volumes"]
                  if r["id"] == vid and r["kind"] == "volume")
    assert report["corrupt"] == 1 and report["quarantined"] == 1
    status, doc = rpc.call_status(f"{master.url()}/cluster/healthz")
    assert status == 503 and not doc["healthy"]
    assert any(f"volume {vid}" in p and "corrupt" in p
               for p in doc["problems"]), doc["problems"]
    types = _journal_types_since(seq0)
    assert {"scrub.start", "scrub.finish", "needle.corrupt",
            "volume.quarantine"} <= types

    # Repair pass: the ticket heals from the sibling replica.
    out = rpc.call_json(f"http://{url}/admin/scrub", "POST",
                        {"volume": vid, "repair": True})
    assert out["repaired"] == 1
    assert needle_repairs_total.value(source="replica") == before + 1
    status, doc = rpc.call_status(f"{master.url()}/cluster/healthz")
    assert status == 200 and doc["healthy"], doc["problems"]
    assert "needle.repaired" in _journal_types_since(seq0)
    # The repaired copy serves the original bytes from THIS holder.
    assert bytes(rpc.call(f"http://{url}/{fid}")) == payload


def test_degraded_read_repairs_inline(cluster):
    """A CRC-failing GET triggers the same repair inline and serves the
    repaired bytes — degraded read, not an error."""
    master, _servers = cluster
    vid, url, fid, payload = _grow_and_corrupt_write(
        master, "degread", replication="001")
    before = needle_repairs_total.value(source="replica")
    assert bytes(rpc.call(f"http://{url}/{fid}")) == payload
    assert needle_repairs_total.value(source="replica") == before + 1
    # Healed in place: the next read is a plain local read.
    assert bytes(rpc.call(f"http://{url}/{fid}")) == payload
    assert needle_repairs_total.value(source="replica") == before + 1


def test_unrepairable_corruption_quarantines(cluster):
    """No replica to heal from: the read path answers 500 (never the
    rotten bytes) and the volume reports degraded until repaired."""
    master, _servers = cluster
    vid, url, fid, _payload = _grow_and_corrupt_write(
        master, "noheal")  # replication 000: single copy
    with pytest.raises(rpc.RpcError) as ei:
        rpc.call(f"http://{url}/{fid}")
    assert ei.value.status == 500
    status, doc = rpc.call_status(f"{master.url()}/cluster/healthz")
    assert status == 503
    assert any(f"volume {vid}" in p for p in doc["problems"])
    # The clean needle in the same volume still reads fine.
    st = rpc.call(f"http://{url}/admin/scrub/status")
    row = next(r for r in st["volumes"] if r["id"] == vid)
    assert row["corrupt_count"] == 1


def test_self_healing_ec_volume(cluster):
    """Acceptance (b): bit-rot injected into an EC shard at encode time
    is caught by the shard-checksum scrub and healed through the EC
    decode path (reconstruct from >=10 sibling shards), transitioning
    healthz degraded -> healthy."""
    master, servers = cluster
    seq0 = JOURNAL._seq
    col = "healec"
    rpc.call(f"{master.url()}/vol/grow?count=1&collection={col}",
             "POST")
    a = rpc.call(f"{master.url()}/dir/assign?collection={col}")
    payload = b"erasure coded payload " * 64
    rpc.call(f"http://{a['url']}/{a['fid']}", "POST", payload)
    vid, url = int(a["fid"].split(",")[0]), a["url"]

    fault.arm("volume.corrupt", "fail*1")
    try:
        rpc.call_json(f"http://{url}/admin/ec/generate", "POST",
                      {"volume": vid})
    finally:
        fault.disarm_all()
    rpc.call_json(f"http://{url}/admin/ec/mount", "POST",
                  {"volume": vid})

    before = needle_repairs_total.value(source="ec")
    out = rpc.call_json(f"http://{url}/admin/scrub", "POST",
                        {"volume": vid})
    ec_report = next(r for r in out["volumes"] if r["kind"] == "ec")
    assert ec_report["corrupt"] >= 1 and ec_report["unrepaired"] >= 1
    status, doc = rpc.call_status(f"{master.url()}/cluster/healthz")
    assert status == 503
    assert any(f"ec volume {vid}" in p and "corrupt shard block" in p
               for p in doc["problems"]), doc["problems"]
    assert "needle.corrupt" in _journal_types_since(seq0)

    out = rpc.call_json(f"http://{url}/admin/scrub", "POST",
                        {"volume": vid, "repair": True})
    ec_report = next(r for r in out["volumes"] if r["kind"] == "ec")
    assert ec_report["repaired"] >= 1 and ec_report["unrepaired"] == 0
    assert needle_repairs_total.value(source="ec") > before
    assert "needle.repaired" in _journal_types_since(seq0)
    status, doc = rpc.call_status(f"{master.url()}/cluster/healthz")
    assert status == 200 and doc["healthy"], doc["problems"]

    # Prove the repaired shard bytes are the TRUE bytes: drop the
    # normal volume and read the needle through the EC path.
    vs = next(s for s in servers if s.url() == url)
    vs.store.delete_volume(vid)
    assert bytes(rpc.call(f"http://{url}/{a['fid']}")) == payload
    # A follow-up scrub is clean.
    out = rpc.call_json(f"http://{url}/admin/scrub", "POST",
                        {"volume": vid})
    assert out["corrupt"] == 0


def test_disk_read_fault_surfaces_then_heals(cluster):
    """The disk.read fault point: a one-shot read error on a single-
    copy volume is a 500 (no replica, and transient errors never
    quarantine); the next read — fault exhausted — succeeds."""
    master, _servers = cluster
    col = "diskread"
    rpc.call(f"{master.url()}/vol/grow?count=1&collection={col}",
             "POST")
    a = rpc.call(f"{master.url()}/dir/assign?collection={col}")
    rpc.call(f"http://{a['url']}/{a['fid']}", "POST", b"sector data")
    fault.arm("disk.read", "fail*1")
    with pytest.raises(rpc.RpcError) as ei:
        rpc.call(f"http://{a['url']}/{a['fid']}")
    assert ei.value.status == 500
    assert bytes(rpc.call(f"http://{a['url']}/{a['fid']}")) == \
        b"sector data"


def test_head_returns_needle_checksum(cluster):
    import urllib.request
    master, _servers = cluster
    col = "headcrc"
    rpc.call(f"{master.url()}/vol/grow?count=1&collection={col}",
             "POST")
    a = rpc.call(f"{master.url()}/dir/assign?collection={col}")
    out = rpc.call(f"http://{a['url']}/{a['fid']}", "POST", b"crc me")
    req = urllib.request.Request(f"http://{a['url']}/{a['fid']}",
                                 method="HEAD")
    resp = urllib.request.urlopen(req, timeout=10)
    resp.read()
    assert resp.headers["X-Needle-Checksum"] == out["eTag"]


def test_volume_scrub_and_check_disk_shell_commands(cluster):
    """volume.scrub sweeps on demand; volume.check.disk heals a replica
    whose needle set diverged: a needle one holder NEVER received comes
    back from the healthy sibling, while a tombstone one holder missed
    is propagated as a delete — never resurrected."""
    from seaweedfs_tpu.shell import CommandEnv, run_command
    master, servers = cluster
    col = "checkdisk"
    rpc.call(f"{master.url()}/vol/grow?count=1&collection={col}"
             f"&replication=001", "POST")
    # Needle A: lands on ONE holder only (?type=replicate suppresses
    # the fan-out) — the sibling never saw it.
    a = rpc.call(f"{master.url()}/dir/assign?collection={col}"
                 f"&replication=001")
    rpc.call(f"http://{a['url']}/{a['fid']}?type=replicate", "POST",
             b"diverge me")
    vid = int(a["fid"].split(",")[0])
    # Needle B: replicated everywhere, then deleted on ONE holder only
    # — an acknowledged delete the sibling missed.
    b = rpc.call(f"{master.url()}/dir/assign?collection={col}"
                 f"&replication=001")
    rpc.call(f"http://{b['url']}/{b['fid']}", "POST", b"delete me")
    rpc.call(f"http://{b['url']}/{b['fid']}?type=replicate", "DELETE")
    locs = [loc["url"] for loc in
            rpc.call(f"{master.url()}/dir/lookup?volumeId={vid}"
                     )["locations"]]
    sibling = next(u for u in locs if u != a["url"])
    env = CommandEnv(master.url())
    try:
        env.lock()
        out = run_command(env, "volume.check.disk "
                               f"-volumeId {vid} -n")
        assert "would repair" in out and "would delete" in out
        out = run_command(env, f"volume.check.disk -volumeId {vid}")
        assert "repaired needle" in out
        assert "propagated delete" in out
        # A exists on BOTH holders now; B on NEITHER (delete won).
        assert bytes(rpc.call(
            f"http://{sibling}/{a['fid']}")) == b"diverge me"
        for u in locs:
            try:
                rpc.call(f"http://{u}/{b['fid']}")
                raise AssertionError(f"deleted needle served on {u}")
            except rpc.RpcError as e:
                assert e.status == 404
        out = run_command(env, f"volume.scrub -volumeId {vid}")
        assert f"volume {vid}" in out and "corrupt 0" in out
    finally:
        env.close()


def test_check_disk_never_deletes_healthy_copy_of_quarantined(cluster):
    """A scrub-quarantine tombstone must read as 'this holder needs a
    repair', NOT as an acknowledged delete — propagating it would
    erase the only healthy copies."""
    from seaweedfs_tpu.shell import CommandEnv, run_command
    master, _servers = cluster
    vid, url, fid, payload = _grow_and_corrupt_write(
        master, "quarcheck", replication="001")
    # Detection-only scrub quarantines the rotted copy on `url`.
    rpc.call_json(f"http://{url}/admin/scrub", "POST", {"volume": vid})
    locs = [loc["url"] for loc in
            rpc.call(f"{master.url()}/dir/lookup?volumeId={vid}"
                     )["locations"]]
    sibling = next(u for u in locs if u != url)
    env = CommandEnv(master.url())
    try:
        env.lock()
        out = run_command(env, f"volume.check.disk -volumeId {vid}")
        assert "propagated delete" not in out
        assert "repaired quarantined needle" in out
    finally:
        env.close()
    # The healthy sibling kept its copy, and the quarantined holder
    # was healed from it.
    assert bytes(rpc.call(f"http://{sibling}/{fid}")) == payload
    assert bytes(rpc.call(f"http://{url}/{fid}")) == payload
    status, _doc = rpc.call_status(f"{master.url()}/cluster/healthz")
    assert status == 200


# -- kill -9 chaos: zero acknowledged-write loss -----------------------------

def test_kill9_remount_loses_no_acked_writes(tmp_path):
    """Acceptance: SIGKILL a subprocess volume server mid-upload-burst;
    on remount every ACKNOWLEDGED write is readable and any torn tail
    is truncated (the volume mounts writable, aligned)."""
    master = MasterServer(volume_size_limit_mb=64,
                          meta_dir=str(tmp_path / "meta"),
                          pulse_seconds=60)
    master.start()
    vport = rpc.free_port()
    data = tmp_path / "vsdata"
    data.mkdir()
    proc = subprocess.Popen(
        [sys.executable, "-m", "seaweedfs_tpu", "volume",
         f"-port={vport}", f"-dir={data}", "-max=8",
         f"-mserver=127.0.0.1:{master.server.port}"],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    acked: list[tuple[str, bytes]] = []
    try:
        deadline = time.time() + 60
        while not list(master.topo.leaves()):
            if time.time() > deadline:
                raise TimeoutError("subprocess vs never registered")
            time.sleep(0.2)
        rpc.call(f"{master.url()}/vol/grow?count=2", "POST")
        client = WeedClient(master.url())
        stop = threading.Event()
        lock = threading.Lock()

        def writer(k: int) -> None:
            i = 0
            while not stop.is_set():
                payload = f"worker {k} write {i} ".encode() * 8
                try:
                    fid = client.upload_data(payload)
                except Exception:  # noqa: BLE001 — server died mid-PUT
                    return
                with lock:
                    acked.append((fid, payload))
                i += 1

        threads = [threading.Thread(target=writer, args=(k,))
                   for k in range(4)]
        for th in threads:
            th.start()
        deadline = time.time() + 30
        while len(acked) < 80 and time.time() < deadline:
            time.sleep(0.02)
        os.kill(proc.pid, signal.SIGKILL)  # mid-burst, no warning
        stop.set()
        for th in threads:
            th.join(timeout=30)
        proc.wait(timeout=10)
        assert len(acked) >= 20, "burst never got going"
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10)
        master.stop()

    # Remount the volume files directly: crash-safe mount must yield
    # consistent, readable volumes.
    volumes: dict[int, Volume] = {}
    try:
        for dat in glob.glob(str(data / "*.dat")):
            vid = int(os.path.basename(dat)[:-4])
            v = Volume(str(data), "", vid, create=False,
                       use_worker=False)
            volumes[vid] = v
            # Torn tails truncated: append cursor == file size, aligned.
            assert v.dat_size() == os.path.getsize(dat)
            assert v.dat_size() % t.NEEDLE_PADDING_SIZE == 0
        lost = []
        for fid, payload in acked:
            vid, key, cookie = t.parse_file_id(fid)
            try:
                n = volumes[vid].read_needle(key, cookie)
                if n.data != payload:
                    lost.append((fid, "bytes differ"))
            except Exception as e:  # noqa: BLE001
                lost.append((fid, str(e)))
        assert not lost, \
            f"{len(lost)}/{len(acked)} acked writes lost: {lost[:5]}"
    finally:
        for v in volumes.values():
            v.close()
