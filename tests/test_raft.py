"""Raft consensus + multi-master HA.

Reference behaviors: weed/server/raft_server.go (leader election among
masters), topology/cluster_commands.go (MaxVolumeId state machine),
master_server.go:155 (proxy-to-leader), volume server leader-following
(volume_grpc_client_to_master.go:60-85).
"""

import json
import time

import pytest

from seaweedfs_tpu.cluster import rpc
from seaweedfs_tpu.cluster.master import MasterServer
from seaweedfs_tpu.cluster.raft import LEADER, NotLeader, RaftNode
from seaweedfs_tpu.cluster.volume_server import VolumeServer


def _mk_raft_cluster(n, tmp_path, apply_sink):
    servers = [rpc.JsonHttpServer() for _ in range(n)]
    urls = [s.url() for s in servers]
    nodes = []
    for i, s in enumerate(servers):
        node = RaftNode(
            urls[i], urls,
            apply_fn=lambda cmd, i=i: apply_sink[i].append(cmd),
            state_path=str(tmp_path / f"raft{i}.json"),
            election_timeout=(0.2, 0.4), heartbeat_interval=0.05)
        node.mount(s)
        s.start()
        nodes.append(node)
    for node in nodes:
        node.start()
    return servers, nodes


def _wait_leader(nodes, timeout=10.0, exclude=()):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        leaders = [x for x in nodes
                   if x.state == LEADER and x not in exclude]
        if len(leaders) == 1:
            return leaders[0]
        time.sleep(0.05)
    raise AssertionError("no single leader elected")


def test_raft_elects_single_leader_and_replicates(tmp_path):
    sink = [[], [], []]
    servers, nodes = _mk_raft_cluster(3, tmp_path, sink)
    try:
        leader = _wait_leader(nodes)
        for i in range(5):
            leader.propose({"op": "max_volume_id", "value": i + 1})
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and \
                not all(len(s) == 5 for s in sink):
            time.sleep(0.05)
        assert all([c["value"] for c in s] == [1, 2, 3, 4, 5]
                   for s in sink), sink
        # Followers refuse proposals and name the leader.
        follower = next(x for x in nodes if x is not leader)
        with pytest.raises(NotLeader) as ei:
            follower.propose({"op": "x"})
        assert ei.value.leader == leader.id
    finally:
        for x in nodes:
            x.stop()
        for s in servers:
            s.stop()


def test_raft_leader_failover_preserves_log(tmp_path):
    sink = [[], [], []]
    servers, nodes = _mk_raft_cluster(3, tmp_path, sink)
    try:
        leader = _wait_leader(nodes)
        for i in range(3):
            leader.propose({"v": i})
        # Kill the leader (stop its raft loops AND its HTTP server).
        dead_i = nodes.index(leader)
        leader.stop()
        servers[dead_i].stop()
        survivors = [x for x in nodes if x is not leader]
        new_leader = _wait_leader(survivors, timeout=15)
        assert new_leader is not leader
        # The new leader still has the committed log and extends it.
        new_leader.propose({"v": 99}, timeout=10)
        live_sinks = [sink[nodes.index(x)] for x in survivors]
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and \
                not all(len(s) == 4 for s in live_sinks):
            time.sleep(0.05)
        for s in live_sinks:
            assert [c.get("v") for c in s] == [0, 1, 2, 99]
    finally:
        for x in nodes:
            x.stop()
        for s in servers:
            s.stop()


def test_raft_state_persistence(tmp_path):
    server = rpc.JsonHttpServer()
    applied = []
    node = RaftNode(server.url(), [server.url()], applied.append,
                    state_path=str(tmp_path / "solo.json"),
                    election_timeout=(0.1, 0.2), heartbeat_interval=0.05)
    node.mount(server)
    server.start()
    node.start()
    _wait_leader([node])
    node.propose({"v": 1})
    node.propose({"v": 2})
    node.stop()
    server.stop()
    # Restarted node recovers term and log from disk.
    node2 = RaftNode("http://x", ["http://x"], applied.append,
                     state_path=str(tmp_path / "solo.json"))
    assert [e["cmd"]["v"] for e in node2.log
            if e["cmd"].get("op") != "noop"] == [1, 2]
    assert node2.current_term >= 1


def test_raft_same_term_stepdown_keeps_vote(tmp_path):
    """Election safety: a node that voted in term T and then steps down
    on a same-term AppendEntries must NOT grant a second vote in T."""
    node = RaftNode("http://me", ["http://me", "http://a", "http://b"],
                    apply_fn=lambda cmd: None,
                    state_path=str(tmp_path / "n.json"))
    # Vote for candidate A in term 5.
    out = node._h_request_vote({}, json.dumps(
        {"term": 5, "candidate_id": "http://a",
         "last_log_index": 0, "last_log_term": 0}).encode())
    assert out["vote_granted"]
    # Same-term heartbeat from (split-vote would make this impossible in
    # a healthy cluster, but a candidate steps down the same way).
    node.state = "candidate"
    node._h_append_entries({}, json.dumps(
        {"term": 5, "leader_id": "http://a", "prev_log_index": 0,
         "prev_log_term": 0, "entries": [],
         "leader_commit": 0}).encode())
    assert node.voted_for == "http://a"  # vote survives the step-down
    # A second candidate in the SAME term must be refused.
    out = node._h_request_vote({}, json.dumps(
        {"term": 5, "candidate_id": "http://b",
         "last_log_index": 0, "last_log_term": 0}).encode())
    assert not out["vote_granted"]
    # A HIGHER term clears the vote as usual.
    out = node._h_request_vote({}, json.dumps(
        {"term": 6, "candidate_id": "http://b",
         "last_log_index": 0, "last_log_term": 0}).encode())
    assert out["vote_granted"]


# -- multi-master HA -------------------------------------------------------


@pytest.fixture
def ha_cluster(tmp_path):
    ports = [rpc.free_port() for _ in range(3)]
    urls = [f"http://127.0.0.1:{p}" for p in ports]
    masters = []
    for i, p in enumerate(ports):
        d = tmp_path / f"m{i}"
        d.mkdir()
        m = MasterServer(port=p, volume_size_limit_mb=64,
                         meta_dir=str(d), peers=urls, pulse_seconds=60)
        m.raft.election_timeout = (0.2, 0.4)
        m.raft.heartbeat_interval = 0.05
        m.start()
        masters.append(m)
    vs = VolumeServer(urls, [str(tmp_path / "vs")], pulse_seconds=1)
    vs.start()
    yield masters, vs
    vs.stop()
    for m in masters:
        try:
            m.stop()
        except Exception:  # noqa: BLE001 — some already stopped in-test
            pass


def _wait_master_leader(masters, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        leaders = [m for m in masters if m.raft.state == LEADER]
        if len(leaders) == 1:
            return leaders[0]
        time.sleep(0.05)
    raise AssertionError("no master leader")


def test_master_ha_assign_via_follower(ha_cluster):
    masters, vs = ha_cluster
    leader = _wait_master_leader(masters)
    follower = next(m for m in masters if m is not leader)
    # Wait until the volume server has registered with the leader.
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and \
            not list(leader.topo.leaves()):
        time.sleep(0.1)
    assert list(leader.topo.leaves()), "volume server never registered"
    # Assign through the FOLLOWER: proxied to the leader transparently.
    out = rpc.call(follower.url() + "/dir/assign?count=1")
    assert "fid" in out and out["url"]
    # Cluster status from any node names the same leader.
    s1 = rpc.call(leader.url() + "/cluster/status")
    s2 = rpc.call(follower.url() + "/cluster/status")
    assert s1["leader"] == s2["leader"] == leader.url()
    assert s1["is_leader"] and not s2["is_leader"]


def test_master_ha_volume_id_consensus_across_failover(ha_cluster):
    masters, vs = ha_cluster
    leader = _wait_master_leader(masters)
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and \
            not list(leader.topo.leaves()):
        time.sleep(0.1)
    out1 = rpc.call(leader.url() + "/dir/assign?count=1")
    vid1 = int(out1["fid"].split(",")[0])
    # Kill the leader; a survivor takes over with the id high-water mark.
    leader.stop()
    survivors = [m for m in masters if m is not leader]
    new_leader = _wait_master_leader(survivors, timeout=15)
    # Volume server redials the new leader and re-registers (full beat).
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline and \
            not list(new_leader.topo.leaves()):
        time.sleep(0.2)
    assert list(new_leader.topo.leaves()), \
        "volume server did not follow the new leader"
    # The node row can precede its full beat's volume list: an assign
    # in that window sees zero active volumes on a full store and
    # 406s ("cannot grow") — retry until the re-registration lands.
    deadline = time.monotonic() + 20
    while True:
        try:
            rpc.call(new_leader.url() + "/dir/assign?count=1")
            break
        except rpc.RpcError as e:
            if e.status != 406 or time.monotonic() >= deadline:
                raise
            time.sleep(0.2)
    # Consensus guarantees no id reuse after failover: the new leader's
    # high-water mark covers every id the old leader issued, and a
    # forced grow issues a strictly greater id.
    assert new_leader.topo._max_volume_id >= vid1
    grown_vid = new_leader.topo.next_volume_id()
    assert grown_vid > vid1


# -- snapshot / compaction / membership (round-4) ----------------------------


def _mk_cluster_with(n, tmp_path, apply_sink, **node_kw):
    servers = [rpc.JsonHttpServer() for _ in range(n)]
    urls = [s.url() for s in servers]
    nodes = []
    for i, s in enumerate(servers):
        node = RaftNode(
            urls[i], urls,
            apply_fn=lambda cmd, i=i: apply_sink[i].append(cmd),
            state_path=str(tmp_path / f"raft{i}.json"),
            election_timeout=(0.2, 0.4), heartbeat_interval=0.05,
            **node_kw)
        node.mount(s)
        s.start()
        nodes.append(node)
    for node in nodes:
        node.start()
    return servers, nodes


def test_log_compaction_bounds_journal(tmp_path):
    """After compact_threshold applied entries the log truncates into a
    snapshot; a restart restores the state machine from it."""
    applied = {"v": 0}
    state_path = str(tmp_path / "solo.json")

    def mk():
        return RaftNode(
            "http://127.0.0.1:1", [],
            apply_fn=lambda cmd: applied.__setitem__(
                "v", cmd["value"]),
            snapshot_fn=lambda: {"v": applied["v"]},
            restore_fn=lambda s: applied.__setitem__(
                "v", s.get("v", 0)),
            state_path=state_path, compact_threshold=50,
            election_timeout=(0.1, 0.2), heartbeat_interval=0.05)

    def start_and_lead(node):
        node.start()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and not node.is_leader():
            time.sleep(0.02)
        assert node.is_leader()

    node = mk()
    start_and_lead(node)
    try:
        for i in range(1, 301):
            node.propose({"op": "set", "value": i})
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and node.log_base == 0:
            time.sleep(0.05)
        assert node.log_base > 0, "no compaction happened"
        assert len(node.log) < 300
        import os
        journal_lines = sum(
            1 for _line in open(state_path + ".log"))
        assert journal_lines < 300, "journal not truncated"
        assert applied["v"] == 300
    finally:
        node.stop()
    # Restart: snapshot restores the state machine without the
    # compacted entries.
    applied["v"] = 0
    node2 = mk()
    start_and_lead(node2)
    try:
        node2.propose({"op": "set", "value": 301}, timeout=10)
        assert applied["v"] == 301
        assert node2.log_base > 0
    finally:
        node2.stop()


def test_far_behind_follower_catches_up_via_snapshot(tmp_path):
    """A follower whose needed entries were compacted away receives
    InstallSnapshot and converges."""
    sink = [[], [], []]
    servers, nodes = _mk_cluster_with(
        3, tmp_path, sink,
        snapshot_fn=lambda: {}, restore_fn=lambda s: None,
        compact_threshold=40)
    try:
        leader = _wait_leader(nodes)
        lagger = next(n for n in nodes if n is not leader)
        # Take the lagger offline (crash): stop its threads AND detach
        # its HTTP handler by stopping the server.
        li = nodes.index(lagger)
        servers[li].stop()
        # No PreVote in this implementation: a partitioned node would
        # inflate its term campaigning and depose the healthy leader on
        # reconnect, which is not what this test exercises.  Muzzle its
        # candidacy while "crashed" (in_config gates elections).
        lagger.in_config = False
        for i in range(1, 201):
            leader.propose({"op": "set", "value": i}, timeout=10)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and leader.log_base == 0:
            time.sleep(0.05)
        assert leader.log_base > 0
        # Bring the lagger back.
        lagger.in_config = True
        servers[li].start()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and \
                lagger.log_base < leader.log_base:
            time.sleep(0.05)
        assert lagger.log_base >= 1, "snapshot never installed"
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and \
                lagger.commit_index < leader.commit_index:
            time.sleep(0.05)
        assert lagger.commit_index >= leader.log_base
    finally:
        for n in nodes:
            n.stop()
        for s in servers:
            s.stop()


def test_membership_add_and_remove_server(tmp_path):
    """add_server brings a fresh voter into the cluster (it receives
    the log and counts toward majorities); remove_server takes one
    out and the removed node stops campaigning."""
    sink = [[], [], []]
    servers, nodes = _mk_cluster_with(3, tmp_path, sink)
    extra_sink = []
    s4 = rpc.JsonHttpServer()
    try:
        leader = _wait_leader(nodes)
        leader.propose({"op": "set", "value": 1})

        # New node starts knowing only itself + the leader; the config
        # entry teaches everyone the rest.
        n4 = RaftNode(
            s4.url(), [s4.url(), leader.id],
            apply_fn=extra_sink.append,
            state_path=str(tmp_path / "raft4.json"),
            election_timeout=(0.2, 0.4), heartbeat_interval=0.05)
        n4.mount(s4)
        s4.start()
        n4.start()
        leader.add_server(s4.url())
        assert s4.url() in leader.peers
        leader.propose({"op": "set", "value": 2}, timeout=10)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and not any(
                c.get("value") == 2 for c in extra_sink):
            time.sleep(0.05)
        assert any(c.get("value") == 2 for c in extra_sink), \
            "new server never applied replicated entries"
        # Every node's config now includes the 4th server.
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and not all(
                s4.url() in n.peers or n is n4 for n in nodes):
            time.sleep(0.05)
        assert all(s4.url() in n.peers for n in nodes)

        # Remove it again: it leaves every config and stops electing.
        leader.remove_server(s4.url())
        assert s4.url() not in leader.peers
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and n4.in_config:
            time.sleep(0.05)
        assert not n4.in_config
        with pytest.raises(ValueError):
            leader.remove_server(leader.id)
    finally:
        for n in nodes:
            n.stop()
        for s in servers:
            s.stop()
        try:
            n4.stop()
        except Exception:
            pass
        s4.stop()


def test_master_ha_file_id_sequencer_across_failover(ha_cluster):
    """RaftSequencer (the etcd-sequencer analog): file-id blocks commit
    through the raft log, so a new leader never re-issues ids the old
    leader handed out — even with no heartbeat max_file_key floor."""
    from seaweedfs_tpu.topology.sequence import RaftSequencer
    masters, _vs = ha_cluster
    leader = _wait_master_leader(masters)
    assert isinstance(leader.topo.sequencer, RaftSequencer)
    first = [leader.topo.sequencer.next_file_id() for _ in range(5)]
    assert sorted(set(first)) == first  # strictly increasing, unique
    # fail the leader over
    leader.stop()
    rest = [m for m in masters if m is not leader]
    new_leader = _wait_master_leader(rest)
    second = [new_leader.topo.sequencer.next_file_id()
              for _ in range(5)]
    assert min(second) > max(first), (first, second)
    assert len(set(first + second)) == len(first) + len(second)
