"""Geo active/active: epoch-fenced write leases, delta-compressed
bidirectional shipping, and locality-steered reads.

Two full regions (A and B — master + volume server each) run against
each other: each volume server ships its change logs to the OTHER
region's master (`-replicate.peer`), carries a `-geo.cluster.id`, and
compresses batches (`-replicate.compress`).  Per-volume `.lease`
sidecars key the shipping direction and fence writes by epoch.

The two PR acceptance gates live here:

- `test_split_brain_fencing_gate` — with `wan.partition` armed during
  a forced lease contest, at no point do both clusters ack a write
  for the same volume (a contested lease fails CLOSED with 503 on
  both sides), and a fenced stale-epoch batch is refused with 409.
- `test_partition_heal_converges_fsck_map_equality` — a partition
  strands acked writes on the holder; after heal the backlog drains
  and `volume.fsck -crc -json` returns byte-identical per-volume maps
  through both masters.

Plus the satellites: `wan.reorder` end-to-end (seq-idempotent apply
refuses the gapped batch unacked, then everything converges),
`rlog.compact()` racing an in-flight shipper tick (injected barrier),
locality steering (lag-SLO breach and tenant `home=` hints reorder
/dir/lookup), the `cluster.lease.*` / `cluster.mirror.status -watch`
shell verbs, and the flows cross-assert that compressed ship bytes
land under the `rlog.ship` purpose within budget.
"""

import json
import os
import threading
import time

import pytest

from seaweedfs_tpu import fault
from seaweedfs_tpu.cluster import resilience, rpc
from seaweedfs_tpu.cluster.client import WeedClient
from seaweedfs_tpu.cluster.master import MasterServer
from seaweedfs_tpu.cluster.volume_server import VolumeServer
from seaweedfs_tpu.filer.client import FilerProxy
from seaweedfs_tpu.filer.server import FilerServer
from seaweedfs_tpu.replication import rlog as rl
from seaweedfs_tpu.replication.rlog import ReplicationLog
from seaweedfs_tpu.shell import CommandEnv, run_command
from seaweedfs_tpu.stats import flows as _fl
from seaweedfs_tpu.stats.metrics import replication_resends_total
from seaweedfs_tpu.tenancy.quota import QuotaRule

pytestmark = pytest.mark.geo


@pytest.fixture(autouse=True)
def _clean():
    fault.disarm_all()
    resilience.reset_breakers()
    yield
    fault.disarm_all()
    resilience.reset_breakers()


def _wait(cond, timeout=20.0, msg="condition never held"):
    deadline = time.time() + timeout
    while not cond():
        if time.time() > deadline:
            raise TimeoutError(msg)
        time.sleep(0.05)


# -- the two-region fixture --------------------------------------------------

@pytest.fixture(scope="module")
def geo(tmp_path_factory):
    """Regions A and B, fully active/active: each side's volume server
    ships to the OTHER side's master, compressed, with geo cluster ids
    and lease tables.  Both masters steer reads (peer = the other
    master) with a deliberately tight 50ms lag SLO and a short steer
    cache so steering tests are fast."""
    tmp = tmp_path_factory.mktemp("geo")
    pa = rpc.free_port()
    pb = rpc.free_port()
    while pb == pa:
        pb = rpc.free_port()
    ma = MasterServer(port=pa, volume_size_limit_mb=16,
                      meta_dir=str(tmp / "ma"), pulse_seconds=60,
                      replication_lag_slo=0.05, geo_cluster_id="A",
                      geo_vid_stride=2, geo_vid_offset=1,
                      steer_peer=f"127.0.0.1:{pb}", steer_reads=True,
                      steer_refresh=0.2)
    ma.start()
    mb = MasterServer(port=pb, volume_size_limit_mb=16,
                      meta_dir=str(tmp / "mb"), pulse_seconds=60,
                      replication_lag_slo=0.05, geo_cluster_id="B",
                      geo_vid_stride=2, geo_vid_offset=0,
                      steer_peer=f"127.0.0.1:{pa}", steer_reads=True,
                      steer_refresh=0.2)
    mb.start()
    (tmp / "a").mkdir()
    (tmp / "b").mkdir()
    va = VolumeServer(ma.url(), [str(tmp / "a")],
                      max_volume_counts=[200], pulse_seconds=60,
                      replicate_peer=mb.url(), replicate_interval=0.05,
                      geo_cluster_id="A", replicate_compress=True)
    va.start()
    vb = VolumeServer(mb.url(), [str(tmp / "b")],
                      max_volume_counts=[200], pulse_seconds=60,
                      replicate_peer=ma.url(), replicate_interval=0.05,
                      geo_cluster_id="B", replicate_compress=True)
    vb.start()
    yield ma, va, mb, vb, tmp
    vb.stop()
    va.stop()
    mb.stop()
    ma.stop()


_GEO_COL_N = [0]


def _geo_put(master, vs, data, collection=None):
    """A home-region write: grow-if-new collection, enable the change
    log, ACQUIRE the lease (epoch 1) before the first byte lands, then
    raw POST.  Returns (vid, fid, collection)."""
    if collection is None:
        _GEO_COL_N[0] += 1
        collection = f"geocol{_GEO_COL_N[0]}"
        rpc.call(f"{master.url()}/vol/grow?count=1"
                 f"&collection={collection}", "POST")
    a = rpc.call(f"{master.url()}/dir/assign?collection={collection}")
    vid = int(a["fid"].split(",")[0])
    v = vs.store.find_volume(vid)
    if v.rlog is None:
        v.enable_rlog()
    if vs.leases.get(vid) is None:
        rpc.call_json(f"http://{vs.url()}/admin/lease/acquire",
                      payload={"volume": vid})
    rpc.call(f"http://{a['url']}/{a['fid']}", "POST", data)
    return vid, a["fid"], collection


def _rlog_status(vs, vid):
    doc = rpc.call(f"http://{vs.url()}/debug/replication")
    return (doc.get("rlog") or {}).get(str(vid))


def _wait_shipped(vs, vid, timeout=20.0):
    def ok():
        st = _rlog_status(vs, vid)
        return bool(st) and st["pending"] == 0 and st["last_seq"] > 0
    _wait(ok, timeout, f"volume {vid} never fully shipped: "
                       f"{_rlog_status(vs, vid)}")


# -- bidirectional convergence + forwarding ----------------------------------

def test_bidirectional_compressed_convergence(geo):
    """Both directions at once: A-held volumes ship A->B, B-held ship
    B->A, zlib-compressed, and each region reads the other's writes
    byte-identically.  The receiver's lease table learns the sender's
    lease from the first fenced batch."""
    ma, va, mb, vb, _tmp = geo
    pay_a = b"region A payload " * 64
    vid_a, fid_a, _ = _geo_put(ma, va, pay_a)
    pay_b = b"region B payload " * 64
    vid_b, fid_b, _ = _geo_put(mb, vb, pay_b)
    _wait_shipped(va, vid_a)
    _wait_shipped(vb, vid_b)
    assert WeedClient(mb.url()).download(fid_a) == pay_a
    assert WeedClient(ma.url()).download(fid_b) == pay_b
    # Compression won: the acked wire bytes are the zlib payload.
    for vs in (va, vb):
        sh = vs.shipper.shipped
        assert sh["batches"] >= 1
        assert 0 < sh["wire_bytes"] < sh["raw_bytes"]
    # B learned A's lease from the batch stamp (and vice versa): the
    # mirrored copies are fenced, apply-only, and never ship back.
    _wait(lambda: vb.leases.get(vid_a) is not None, 10)
    _wait(lambda: va.leases.get(vid_b) is not None, 10)
    assert vb.leases.holder(vid_a) == "A"
    assert vb.leases.epoch(vid_a) == 1
    assert not vb.leases.is_holder(vid_a)
    assert not vb.leases.ships(vid_a)
    assert va.leases.holder(vid_b) == "B"
    assert not va.leases.ships(vid_b)


def test_write_at_non_holder_forwards_to_lease_holder(geo):
    """A write landing at the non-holder region never commits there:
    it forwards to the lease holder, commits exactly once, and the
    mirror ships it back."""
    ma, va, mb, vb, _tmp = geo
    v1 = b"forward v1 " * 32
    vid, fid, _col = _geo_put(ma, va, v1)
    _wait_shipped(va, vid)
    va.shipper.paused = True
    try:
        v2 = b"forward v2 " * 32
        out = rpc.call(f"http://{vb.url()}/{fid}", "POST", v2)
        assert out.get("size", 0) > 0
        # Committed at the holder (A) immediately...
        assert WeedClient(ma.url()).download(fid) == v2
        # ...and journaled there: the non-holder (B) did NOT apply it
        # out-of-band — its applied watermark still sits at the
        # pre-forward record.
        st = _rlog_status(va, vid)
        assert st["pending"] >= 1
        wm = vb._replication_watermark(vb.store.find_volume(vid))
        assert wm.value == 1
    finally:
        va.shipper.paused = False
    va.shipper.kick()
    _wait_shipped(va, vid)
    _wait(lambda: WeedClient(mb.url()).download(fid) == v2, 10,
          "forwarded write never shipped back to region B")


# -- wan.reorder end-to-end --------------------------------------------------

def test_wan_reorder_refused_unacked_then_converges(geo):
    """Out-of-order delivery: the `wan.reorder` hook ships batch n+1
    BEFORE batch n.  The receiver's gap check refuses the early batch
    WITHOUT acking (409), the sender's watermark holds, the normal
    loop re-ships in order, and both regions end byte-identical."""
    ma, va, mb, vb, _tmp = geo
    pays = [b"reorder zero " * 40]
    vid, fid0, col = _geo_put(ma, va, pays[0])
    _wait_shipped(va, vid)
    fids = [fid0]
    old_batch = va.shipper.batch_records
    va.shipper.paused = True
    try:
        for i in (1, 2, 3):
            a = rpc.call(f"{ma.url()}/dir/assign?collection={col}")
            assert int(a["fid"].split(",")[0]) == vid
            pay = f"reorder {i} ".encode() * 40
            rpc.call(f"http://{a['url']}/{a['fid']}", "POST", pay)
            fids.append(a["fid"])
            pays.append(pay)
        va.shipper.batch_records = 1  # several batches to reorder
        before = replication_resends_total.value(reason="reorder")
        fault.arm("wan.reorder", "fail*1")
        va.shipper.paused = False
        va.shipper.kick()
        _wait_shipped(va, vid)
        assert replication_resends_total.value(reason="reorder") \
            == before + 1
    finally:
        va.shipper.paused = False
        va.shipper.batch_records = old_batch
        fault.disarm_all()
    # Nothing skipped, nothing double-applied: every record landed.
    bc = WeedClient(mb.url())
    for fid, pay in zip(fids, pays):
        assert bc.download(fid) == pay
    wm = vb._replication_watermark(vb.store.find_volume(vid))
    assert wm.value == _rlog_status(va, vid)["last_seq"]


# -- rlog.compact() vs an in-flight shipper tick -----------------------------

def test_compact_racing_inflight_tick_never_reships_or_skips(
        tmp_path, monkeypatch):
    """The shipper's read-batch / receiver-ack window is lock-free
    against `compact()`.  An injected barrier lands the ack at the
    nastiest instant — after compact rewrote the log, before the file
    swap — and the invariants must hold anyway: the concurrent ack is
    never regressed, no unacked record is dropped (nothing skipped),
    and nothing below the watermark becomes pending again (nothing
    re-shipped)."""
    base = str(tmp_path / "race")
    log = ReplicationLog(base)
    for i in range(6):
        log.append(rl.OP_WRITE, 100 + i, 0, 32)
    log.set_acked(3)
    # The in-flight tick: records 4..6 were read and shipped; the ack
    # has not landed yet when compact starts.
    inflight = log.read_from(log.acked_seq + 1, 100)
    assert [r.seq for r in inflight] == [4, 5, 6]
    in_swap = threading.Event()
    ack_done = threading.Event()
    real_replace = os.replace

    def barriered_replace(src, dst):
        # Barrier only on the compacted-log swap (the watermark file
        # uses os.replace too — an unguarded patch would deadlock the
        # acker against itself).
        if dst.endswith(".rlog") and not in_swap.is_set():
            in_swap.set()
            assert ack_done.wait(10), "acker never ran"
        return real_replace(src, dst)

    monkeypatch.setattr(rl.os, "replace", barriered_replace)

    def acker():
        assert in_swap.wait(10)
        log.set_acked(6)  # the receiver's ack for the in-flight batch
        ack_done.set()

    t = threading.Thread(target=acker)
    t.start()
    dropped = log.compact()
    t.join(15)
    assert not t.is_alive()
    assert dropped == 3, "exactly the pre-ack acked prefix drops"
    assert log.acked_seq == 6, "the concurrent ack must survive"
    # Never re-ship: nothing above the watermark is a data record
    # (only the vacuum marker compact appended).
    tail = log.read_from(log.acked_seq + 1, 100)
    assert all(r.op == rl.OP_VACUUM for r in tail)
    assert log.pending() == len(tail)
    # Never skip: every seq unacked when compact STARTED survived the
    # swap (compact may retain acked records; it must not drop these).
    seqs = {r.seq for r in log.read_from(1, 100)}
    assert {4, 5, 6} <= seqs
    # And the log still works: reopen sees the same durable state.
    log.close()
    log2 = ReplicationLog(base)
    assert log2.acked_seq == 6
    assert {4, 5, 6} <= {r.seq for r in log2.read_from(1, 100)}
    log2.close()


# -- flows cross-assert ------------------------------------------------------

@pytest.mark.flows
def test_compressed_ship_bytes_land_under_rlog_ship_budget(geo):
    """The WAN spend the flow ledger meters for `rlog.ship` is the
    COMPRESSED payload: ledger out-bytes for the shipper's node grow
    by at least the acked wire bytes (and those are smaller than raw),
    and a generous `-flows.budget rlog.ship=...` stays unbreached."""
    ma, va, _mb, _vb, _tmp = geo
    me = va.url()
    _fl.LEDGER.set_budgets(_fl.parse_budgets("rlog.ship=8MB/s"))
    try:
        b0, o0 = _fl.LEDGER.totals(purpose_="rlog.ship",
                                   direction="out", local=me)
        w0 = va.shipper.shipped["wire_bytes"]
        r0 = va.shipper.shipped["raw_bytes"]
        vid, _fid, _col = _geo_put(ma, va,
                                   b"budget geo payload " * 512)
        _wait_shipped(va, vid)
        b1, o1 = _fl.LEDGER.totals(purpose_="rlog.ship",
                                   direction="out", local=me)
        dwire = va.shipper.shipped["wire_bytes"] - w0
        draw = va.shipper.shipped["raw_bytes"] - r0
        assert 0 < dwire < draw, "compression must shrink the batch"
        # The HTTP body carries the compressed stream (plus envelope):
        # at least the wire bytes must be attributed to rlog.ship.
        assert b1 - b0 >= dwire
        assert o1 - o0 >= 1
        st = _fl.LEDGER.budget_status(local=me).get("rlog.ship")
        assert st is not None and st["limit_bps"] > 0
        assert not st["breached"]
    finally:
        _fl.LEDGER.set_budgets({})


# -- THE acceptance gate: split-brain fencing --------------------------------

def test_split_brain_fencing_gate(geo):
    """`wan.partition` armed during a forced lease contest: at no
    point do both clusters ack a write for the same volume.

    1. A mid-partition lease move fails CLOSED (drain timeout, lease
       NOT moved) — the holder keeps committing, the peer keeps
       forwarding.
    2. A contested lease (the demote half of a move landed, the
       acquire never crossed the partition) leaves NO holder: both
       regions refuse writes with 503, nothing commits anywhere.
    3. After heal, the runbook re-fences one holder at a bumped
       epoch; the stranded backlog drains; a stale-epoch batch from
       the fenced identity is refused with 409; a PROPER
       drain-demote-acquire move then succeeds end to end."""
    ma, va, mb, vb, _tmp = geo
    base = b"fence base " * 32
    vid, fid, col = _geo_put(ma, va, base)
    _wait_shipped(va, vid)
    _wait(lambda: vb.leases.get(vid) is not None, 10)

    fault.arm("wan.partition", "fail*1000")
    try:
        # An acked write on the holder that can no longer ship: the
        # drain below can never finish.
        w1 = b"during partition " * 16
        rpc.call(f"http://{va.url()}/{fid}", "POST", w1)
        st, out = rpc.call_status(
            f"http://{va.url()}/admin/lease/move", "POST",
            json.dumps({"volume": vid, "to": "B",
                        "timeout": 0.5}).encode())
        assert st == 503
        assert "NOT moved" in json.dumps(out)
        assert va.leases.is_holder(vid), "a failed move must not demote"
        assert va.leases.epoch(vid) == 1

        # Force the contested mid-move window: A's sidecar says B@2
        # (the demote), but B never heard the acquire (still A@1).
        rpc.call_json(f"http://{va.url()}/admin/lease/acquire",
                      payload={"volume": vid, "cluster_id": "B",
                               "epoch": 2})
        assert not va.leases.is_holder(vid)
        assert not vb.leases.is_holder(vid)
        # The drain attempt's partition failures tripped the per-host
        # breakers; reset so the gate below sees lease verdicts, not
        # breaker fast-fails (the partition itself stays armed).
        resilience.reset_breakers()
        # THE GATE: neither region acks a write now.  Each forwards
        # to the cluster it believes holds the lease; the forward
        # arrives marked geo=fwd at another non-holder and is refused
        # — fail closed, no bouncing, no split brain.
        st_a, _ = rpc.call_status(f"http://{va.url()}/{fid}", "POST",
                                  b"split brain A " * 8)
        st_b, _ = rpc.call_status(f"http://{vb.url()}/{fid}", "POST",
                                  b"split brain B " * 8)
        assert st_a >= 500, f"region A acked a contested write: {st_a}"
        assert st_b >= 500, f"region B acked a contested write: {st_b}"
        # Nothing committed anywhere: A still serves the pre-contest
        # write, B never applied past the shipped base record.
        assert WeedClient(ma.url()).download(fid) == w1
        wm = vb._replication_watermark(vb.store.find_volume(vid))
        assert wm.value == 1
    finally:
        fault.disarm_all()
        resilience.reset_breakers()

    # Heal.  Runbook: the side with stranded acked writes re-fences
    # as holder at an epoch above anything either side saw; the other
    # side fences to match.  The backlog then drains.
    for node in (va, vb):
        rpc.call_json(f"http://{node.url()}/admin/lease/acquire",
                      payload={"volume": vid, "cluster_id": "A",
                               "epoch": 3})
    assert va.leases.is_holder(vid)
    assert not vb.leases.is_holder(vid)
    va.shipper.kick()
    _wait_shipped(va, vid)
    _wait(lambda: WeedClient(mb.url()).download(fid)
          == b"during partition " * 16, 10,
          "stranded partition-era write never reached region B")

    # A batch from the fenced old identity (B@2 < A@3) is refused.
    st, out = rpc.call_status(
        f"http://{vb.url()}/admin/replication/apply", "POST",
        json.dumps({"volume": vid, "collection": col,
                    "cluster_id": "B", "epoch": 2,
                    "records": []}).encode())
    assert st == 409, f"stale-epoch batch admitted: {st} {out}"
    assert "stale" in json.dumps(out)

    # And a PROPER move (drain -> demote@4 -> peer acquire) succeeds.
    out = rpc.call_json(f"http://{va.url()}/admin/lease/move",
                        payload={"volume": vid, "to": "B",
                                 "timeout": 10.0})
    assert out["epoch"] == 4
    assert out["peer_acquired"] is True
    assert vb.leases.is_holder(vid)
    assert not va.leases.is_holder(vid)
    final = b"post-move final " * 16
    rpc.call(f"http://{vb.url()}/{fid}", "POST", final)
    _wait_shipped(vb, vid)
    _wait(lambda: WeedClient(ma.url()).download(fid) == final, 10,
          "post-move write never shipped back to region A")


# -- acceptance: partition + heal => fsck map equality -----------------------

def test_partition_heal_converges_fsck_map_equality(geo):
    """Filer-level proof of byte-identical convergence: writes land
    through a filer on region A, a partition strands one of them,
    heal drains the backlog, and `volume.fsck -crc -json` through
    BOTH masters returns the same per-volume needle map."""
    ma, va, mb, _vb, _tmp = geo
    filer = FilerServer(ma.url())
    filer.start()
    try:
        rpc.call(f"{ma.url()}/vol/grow?count=2", "POST")
        vids = []
        for loc in va.store.locations:
            for v in list(loc.volumes.values()):
                if (v.collection or "") == "":
                    if v.rlog is None:
                        v.enable_rlog()
                    if va.leases.get(v.vid) is None:
                        rpc.call_json(
                            f"http://{va.url()}/admin/lease/acquire",
                            payload={"volume": v.vid})
                    vids.append(v.vid)
        assert vids, "default collection never grew on region A"
        fp = FilerProxy(filer.url())
        fp.put("/geo/one.bin", b"geo fsck one " * 128)
        fault.arm("wan.partition", "fail*1000")
        try:
            fp.put("/geo/two.bin", b"geo fsck two " * 200)
        finally:
            fault.disarm_all()
            resilience.reset_breakers()
        va.shipper.kick()
        _wait(lambda: all((_rlog_status(va, vid) or
                           {"pending": 0})["pending"] == 0
                          for vid in vids), 20,
              "backlog never drained after heal")
        env_a = CommandEnv(ma.url(), filer_url=filer.url())
        env_b = CommandEnv(mb.url(), filer_url=filer.url())
        try:
            fa = json.loads(run_command(env_a,
                                        "volume.fsck -crc -json"))
            fb = json.loads(run_command(env_b,
                                        "volume.fsck -crc -json"))
        finally:
            env_a.close()
            env_b.close()
        assert fa["volumes"] == fb["volumes"], \
            "regions diverged after partition + heal"
    finally:
        filer.stop()


# -- locality-steered reads --------------------------------------------------

def test_locality_steering_on_lag_and_tenant_home(geo):
    """/dir/lookup reordering: a B-held volume read through region A
    serves the local mirrored replica while it is in-SLO, steers to
    region B's replica when the mirror lag breaches the SLO, recovers
    when the mirror catches up, and honors a tenant `home=` hint even
    in-SLO.  Clients already re-lookup on 429/503, so this is
    lookup-time only."""
    ma, va, mb, vb, _tmp = geo
    pay = b"steer me " * 64
    vid, fid, _col = _geo_put(mb, vb, pay)
    _wait_shipped(vb, vid)
    _wait(lambda: rpc.call_status(
        f"{ma.url()}/dir/lookup?volumeId={vid}")[0] == 200, 10,
        "region A never learned the mirrored replica")
    vb._send_heartbeat(full=True)
    time.sleep(0.25)  # let region A's steer caches refresh to lag=0
    doc = rpc.call(f"{ma.url()}/dir/lookup?volumeId={vid}")
    assert doc["locations"][0]["url"] == va.url(), \
        "in-SLO read must stay local"

    vb.shipper.paused = True
    try:
        rpc.call(f"http://{vb.url()}/{fid}", "POST",
                 b"stale now " * 64)

        def lag_breached():
            vb._send_heartbeat(full=True)
            rows = {int(r["volume"]): r for r in rpc.call(
                f"{mb.url()}/cluster/mirror").get("volumes", [])}
            row = rows.get(vid)
            return bool(row) and \
                float(row.get("lag_seconds", 0) or 0) > 0.05
        _wait(lag_breached, 10, "lag never breached the SLO")
        time.sleep(0.25)  # region A's cached peer-mirror row expires
        doc = rpc.call(f"{ma.url()}/dir/lookup?volumeId={vid}")
        assert doc["locations"][0]["url"] == vb.url(), \
            "out-of-SLO read must steer to the fresh replica"
        assert any(loc["url"] == va.url()
                   for loc in doc["locations"]), \
            "steering reorders, it must not drop the local replica"
    finally:
        vb.shipper.paused = False
    vb.shipper.kick()
    _wait_shipped(vb, vid)
    vb._send_heartbeat(full=True)
    time.sleep(0.3)
    doc = rpc.call(f"{ma.url()}/dir/lookup?volumeId={vid}")
    assert doc["locations"][0]["url"] == va.url(), \
        "recovered mirror must un-steer"

    # Tenant home hint: pinned-to-B tenants read B even in-SLO.
    ma.tenant_policy.rules.append(
        QuotaRule(tenant="geo-steer-bob", home="B"))
    try:
        doc = rpc.call(f"{ma.url()}/dir/lookup?volumeId={vid}"
                       f"&tenant=geo-steer-bob")
        assert doc["locations"][0]["url"] == vb.url()
        doc = rpc.call(f"{ma.url()}/dir/lookup?volumeId={vid}")
        assert doc["locations"][0]["url"] == va.url()
    finally:
        ma.tenant_policy.rules = [
            r for r in ma.tenant_policy.rules
            if r.tenant != "geo-steer-bob"]


# -- shell verbs + rollup surfaces -------------------------------------------

def test_shell_lease_verbs_and_surfaces(geo):
    """cluster.lease.ls / cluster.lease.move / cluster.mirror.status
    -watch, plus the lease rollups in /cluster/mirror and
    /cluster/healthz."""
    ma, va, mb, vb, _tmp = geo
    vid, _fid, _col = _geo_put(ma, va, b"shell lease " * 32)
    _wait_shipped(va, vid)
    va._send_heartbeat(full=True)
    env = CommandEnv(ma.url())
    try:
        out = run_command(env, "cluster.lease.ls")
        assert "this cluster: A" in out
        assert str(vid) in out
        assert "HOLDER" in out and "EPOCH" in out
        out = run_command(env, "cluster.mirror.status")
        assert "cluster: A" in out
        assert "LEASE" in out
        assert "A@e1" in out
        # -watch with a poll budget returns (no endless loop to ^C).
        out = run_command(env, "cluster.mirror.status -watch "
                               "-interval 0.05 -count 1")
        assert "LEASE" in out
        # The move verb requires the operator lock, drains, then
        # hands the lease to B at epoch 2.
        run_command(env, "lock")
        out = run_command(env, f"cluster.lease.move -volume {vid} "
                               f"-to B")
        assert "moved to cluster B at epoch 2" in out
        run_command(env, "unlock")
    finally:
        env.close()
    assert vb.leases.is_holder(vid)
    assert not va.leases.is_holder(vid)
    assert va.leases.epoch(vid) == 2
    # healthz: info-only geo lease counters under the replication
    # section (a remote-held lease is a fact, not a problem).
    va._send_heartbeat(full=True)
    _status, doc = rpc.call_status(f"{ma.url()}/cluster/healthz")
    repl = doc["replication"]
    assert repl["cluster_id"] == "A"
    assert repl["leases"]["volumes"] >= 1
    assert repl["leases"]["moving"] == 0
