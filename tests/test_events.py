"""Cluster event journal + health rollup: the static event-type
catalog, the bounded ring, /debug/events filtering, the master's
/cluster/healthz + /cluster/events aggregation, events.ls /
cluster.check, and the anti-rot smoke test proving EVERY cataloged
event type is emitted through its real code path (with a trace id
linking it to /debug/traces when tracing is on)."""

import json
import os
import threading
import time

import pytest

from seaweedfs_tpu import events, fault
from seaweedfs_tpu.cluster import resilience, rpc
from seaweedfs_tpu.cluster.client import WeedClient
from seaweedfs_tpu.cluster.master import MasterServer
from seaweedfs_tpu.cluster.volume_server import VolumeServer
from seaweedfs_tpu.events import JOURNAL, TYPES, EventJournal
from seaweedfs_tpu.replication import ReplicationShipper
from seaweedfs_tpu.shell import CommandEnv, run_command
from seaweedfs_tpu.stats.promcheck import validate_exposition
from seaweedfs_tpu.trace import root_span


# -- journal unit tests ------------------------------------------------------

def test_unknown_type_and_severity_raise():
    j = EventJournal(capacity=8)
    with pytest.raises(ValueError):
        j.emit("no.such.event")
    with pytest.raises(ValueError):
        j.emit("volume.grow", severity="catastrophic")
    assert j.emitted == 0


def test_ring_is_bounded_and_wrap_retains_newest():
    j = EventJournal(capacity=4)
    # The hot-path contract: the ring is a bounded deque — an unbounded
    # journal would grow without limit on a long-lived server.
    assert j._ring.maxlen == 4
    for i in range(10):
        j.emit("volume.grow", count=i)
    got = [ev["attrs"]["count"] for ev in j.snapshot()]
    assert got == [6, 7, 8, 9]          # newest retained, oldest gone
    assert j.emitted == 10 and j.dropped == 6


def test_concurrent_emit_from_threads():
    j = EventJournal(capacity=10000)
    n_threads, per_thread = 8, 200

    def worker(k):
        for i in range(per_thread):
            j.emit("fault.injected", severity="warn", thread=k, i=i)

    threads = [threading.Thread(target=worker, args=(k,))
               for k in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    evs = j.snapshot()
    assert len(evs) == n_threads * per_thread == j.emitted
    seqs = [ev["seq"] for ev in evs]
    assert len(set(seqs)) == len(seqs)  # seq is unique under races


def test_snapshot_filters_and_limit():
    j = EventJournal(capacity=64)
    j.emit("volume.grow", count=1)
    time.sleep(0.01)
    cut = time.time()
    j.emit("volume.vacuum", vid=3)
    j.emit("heartbeat.lost", severity="warn", node="a:1")
    assert [e["type"] for e in j.snapshot(type_="volume.vacuum")] == \
        ["volume.vacuum"]
    assert [e["type"] for e in j.snapshot(severity="warn")] == \
        ["heartbeat.lost"]
    assert all(e["ts"] >= cut for e in j.snapshot(since=cut))
    assert len(j.snapshot(since=cut)) == 2
    assert [e["type"] for e in j.snapshot(limit=1)] == \
        ["heartbeat.lost"]  # limit keeps the newest


def test_jsonl_sink(tmp_path):
    j = EventJournal(capacity=8)
    path = str(tmp_path / "events.jsonl")
    j.set_sink(path)
    j.emit("volume.grow", count=2)
    j.emit("tier.move", vid=9, direction="upload")
    lines = [json.loads(ln) for ln in
             open(path).read().strip().split("\n")]
    assert [ev["type"] for ev in lines] == ["volume.grow", "tier.move"]
    assert lines[1]["attrs"]["vid"] == 9


def test_jsonl_sink_size_rotation(tmp_path, monkeypatch):
    """-events.file.max_mb rotates the sink (path -> path.1 -> ...)
    keeping -events.file.keep rotated generations; the live file always
    holds the newest events."""
    monkeypatch.setenv("SEAWEEDFS_TPU_EVENTS_FILE_MAX_MB", "0.0002")
    monkeypatch.setenv("SEAWEEDFS_TPU_EVENTS_FILE_KEEP", "2")
    j = EventJournal(capacity=8)
    path = str(tmp_path / "events.jsonl")
    j.set_sink(path)  # re-resolves the rotation env on next write
    for i in range(40):
        j.emit("volume.grow", count=i, pad="x" * 64)
    assert os.path.exists(path + ".1")
    assert os.path.exists(path + ".2")
    assert not os.path.exists(path + ".3"), "keep=2 must bound the chain"
    last = json.loads(open(path).read().strip().split("\n")[-1])
    assert last["attrs"]["count"] == 39
    # Rotation disabled (no max): one ever-growing file, no .1 sibling.
    monkeypatch.delenv("SEAWEEDFS_TPU_EVENTS_FILE_MAX_MB")
    plain = str(tmp_path / "plain.jsonl")
    j.set_sink(plain)
    for i in range(40):
        j.emit("volume.grow", count=i, pad="x" * 64)
    assert len(open(plain).read().strip().split("\n")) == 40
    assert not os.path.exists(plain + ".1")


def test_event_carries_active_trace_id():
    j = EventJournal(capacity=8)
    with root_span("unit.op", "test") as sp:
        ev = j.emit("volume.grow", count=1)
        assert ev["trace_id"] == sp.trace_id != ""
    assert j.emit("volume.grow", count=2)["trace_id"] == ""


# -- /debug/events endpoint --------------------------------------------------

def test_debug_events_endpoint_filters(monkeypatch):
    server = rpc.JsonHttpServer()
    events.setup_event_routes(server)
    server.start()
    base = f"http://127.0.0.1:{server.port}/debug/events"
    marker = os.urandom(4).hex()
    try:
        JOURNAL.emit("volume.grow", marker=marker)
        cut = time.time()
        JOURNAL.emit("heartbeat.lost", severity="warn", node="x:1",
                     marker=marker)
        out = rpc.call(f"{base}?type=volume.grow")
        assert out["token"] == JOURNAL.token
        assert all(e["type"] == "volume.grow" for e in out["events"])
        assert any(e["attrs"].get("marker") == marker
                   for e in out["events"])
        out = rpc.call(f"{base}?since={cut}&severity=warn")
        assert any(e["attrs"].get("marker") == marker
                   for e in out["events"])
        assert all(e["severity"] == "warn" and e["ts"] >= cut
                   for e in out["events"])
        with pytest.raises(rpc.RpcError) as ei:
            rpc.call(f"{base}?type=bogus.type")
        assert ei.value.status == 400
        out = rpc.call(f"{base}?limit=1")
        assert len(out["events"]) == 1
    finally:
        server.stop()


def test_debug_events_kill_switch(monkeypatch):
    monkeypatch.setenv("SEAWEEDFS_TPU_EVENTS", "0")
    server = rpc.JsonHttpServer()
    events.setup_event_routes(server)
    server.start()
    try:
        with pytest.raises(rpc.RpcError) as ei:
            rpc.call(f"http://127.0.0.1:{server.port}/debug/events")
        assert ei.value.status == 404
    finally:
        server.stop()


# -- satellites: sysstats fallback + glog -v ---------------------------------

def test_memory_status_falls_back_off_linux(monkeypatch):
    """No /proc/self/status (macOS): RSS must come from getrusage, not
    silently read zero."""
    import builtins
    real_open = builtins.open

    def fake_open(path, *a, **k):
        if path == "/proc/self/status":
            raise OSError("no procfs")
        return real_open(path, *a, **k)

    monkeypatch.setattr(builtins, "open", fake_open)
    from seaweedfs_tpu.stats.sysstats import memory_status
    assert memory_status()["rss"] > 0


def test_cli_v_flag_configures_glog(monkeypatch):
    from seaweedfs_tpu.command import main
    from seaweedfs_tpu.utils import glog
    old = glog._verbosity
    try:
        assert main(["version", "-v", "2"]) == 0
        assert glog._verbosity == 2
        assert glog.v(2).on and not glog.v(3).on
        # Without the flag the WEED_V env applies instead of being
        # clobbered back to 0.
        monkeypatch.setenv("WEED_V", "1")
        assert main(["version"]) == 0
        assert glog._verbosity == 1
    finally:
        glog._verbosity = old


# -- mini-cluster: every event type through its real code path ---------------

@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    """Raft master (so elections are real) + two volume servers + a
    stub EC peer, with tracing recording on so every event can carry a
    trace id."""
    saved = {k: os.environ.get(k)
             for k in ("SEAWEEDFS_TPU_TRACES", "SEAWEEDFS_TPU_TRACE")}
    os.environ["SEAWEEDFS_TPU_TRACES"] = "1"
    os.environ.pop("SEAWEEDFS_TPU_TRACE", None)
    tmp = tmp_path_factory.mktemp("events-smoke")
    port = rpc.free_port()
    master = MasterServer(port=port, volume_size_limit_mb=16,
                          meta_dir=str(tmp / "meta"),
                          pulse_seconds=60,
                          peers=[f"http://127.0.0.1:{port}"])
    master.start()
    deadline = time.time() + 15
    while not master.is_leader():
        if time.time() > deadline:
            raise TimeoutError("single-node raft never elected")
        time.sleep(0.05)
    servers = []
    for i in range(2):
        d = tmp / f"vs{i}"
        d.mkdir()
        vs = VolumeServer(master.url(), [str(d)],
                          max_volume_counts=[200], pulse_seconds=60)
        vs.start()
        servers.append(vs)
    stub = rpc.JsonHttpServer()
    stub.route("GET", "/ping", lambda q, b: {"pong": True})
    stub.start()
    client = WeedClient(master.url())
    yield master, servers, stub, client, tmp
    stub.stop()
    for vs in servers:
        vs.stop()
    master.stop()
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v


_COLLECTION_N = [0]


def _new_volume(cl, prefix: str, replication: str = ""):
    """One fresh volume with a needle in it; returns (vid, holder_url,
    fid).  Uses /vol/grow?count=1 so each driver costs one volume, not
    a 7-volume layout growth."""
    master, _servers, _stub, client, _tmp = cl
    _COLLECTION_N[0] += 1
    col = f"{prefix}{_COLLECTION_N[0]}"
    rep = f"&replication={replication}" if replication else ""
    rpc.call(f"{master.url()}/vol/grow?count=1&collection={col}{rep}",
             "POST")
    a = rpc.call(f"{master.url()}/dir/assign?collection={col}{rep}")
    rpc.call(f"http://{a['url']}/{a['fid']}", "POST",
             b"event journal payload " * 64)
    return int(a["fid"].split(",")[0]), a["url"], a["fid"]


def _drive_volume_assign(cl):
    master, _s, _st, _c, _t = cl
    _COLLECTION_N[0] += 1
    rpc.call(f"{master.url()}/dir/assign?"
             f"collection=assigncol{_COLLECTION_N[0]}")


def _drive_volume_grow(cl):
    _drive_volume_assign(cl)  # an assign with no writable volume grows


def _drive_volume_readonly(cl):
    vid, url, _fid = _new_volume(cl, "rocol")
    rpc.call_json(f"http://{url}/admin/readonly", "POST",
                  {"volume": vid, "readonly": True})


def _drive_volume_vacuum(cl):
    _master, _s, _st, client, _t = cl
    vid, url, fid = _new_volume(cl, "vaccol")
    rpc.call(f"http://{url}/{fid}", "DELETE")
    rpc.call_json(f"http://{url}/admin/vacuum", "POST", {"volume": vid})


def _drive_heartbeat_lost(cl):
    master, servers, _st, _c, _t = cl
    vs = servers[1]
    dn = next(d for d in master.topo.leaves() if d.url() == vs.url())
    dn.last_seen = 0.0
    master._sweep_dead_nodes()
    vs._send_heartbeat(full=True)  # restore for later drivers


def _drive_heartbeat_recovered(cl):
    _drive_heartbeat_lost(cl)  # re-registration after a sweep death


def _drive_leader_elect(cl):
    master, _s, _st, _c, _t = cl
    raft = master.raft
    with raft._lock:
        raft._become_follower(raft.current_term + 1, None)
    deadline = time.time() + 15
    while not master.is_leader():
        if time.time() > deadline:
            raise TimeoutError("raft never re-elected")
        time.sleep(0.05)


def _drive_leader_stepdown(cl):
    _drive_leader_elect(cl)  # the forced step-down emits it


def _drive_ec_encode(cl):
    vid, url, _fid = _new_volume(cl, "eccol")
    rpc.call_json(f"http://{url}/admin/ec/generate", "POST",
                  {"volume": vid})


def _drive_ec_rebuild(cl):
    vid, url, _fid = _new_volume(cl, "ecrcol")
    rpc.call_json(f"http://{url}/admin/ec/generate", "POST",
                  {"volume": vid})
    # Real shard loss: two of the 14 shard files gone, then rebuild.
    rpc.call_json(f"http://{url}/admin/ec/delete_shards", "POST",
                  {"volume": vid, "shards": [3, 7]})
    out = rpc.call_json(f"http://{url}/admin/ec/rebuild", "POST",
                        {"volume": vid})
    assert sorted(out["rebuilt_shards"]) == [3, 7]


def _drive_ec_repair_local(cl):
    """Degraded read of an LRC volume with a lost shard: the interval
    reconstructs from the shard's locality group (5 reads) and the
    server journals the local repair."""
    vid, url, fid = _new_volume(cl, "lrccol")
    rpc.call_json(f"http://{url}/admin/ec/generate", "POST",
                  {"volume": vid, "codec": "lrc"})
    rpc.call_json(f"http://{url}/admin/ec/mount", "POST",
                  {"volume": vid})
    rpc.call_json(f"http://{url}/admin/delete_volume", "POST",
                  {"volume": vid})
    # The test needle sits at the head of the .dat -> shard 0.
    rpc.call_json(f"http://{url}/admin/ec/delete_shards", "POST",
                  {"volume": vid, "shards": [0]})
    assert rpc.call(f"http://{url}/{fid}")
    # Heal the volume so the healthz rollup tests that follow see a
    # healthy cluster again.
    rpc.call_json(f"http://{url}/admin/ec/rebuild", "POST",
                  {"volume": vid})
    rpc.call_json(f"http://{url}/admin/ec/mount", "POST",
                  {"volume": vid})


def _drive_breaker_open(cl):
    _m, _s, stub, _c, _t = cl
    hostport = f"127.0.0.1:{stub.port}"
    fault.arm("rpc.connect", f"fail~{hostport}")
    try:
        with root_span("drive.breaker_open", "test"):
            for _ in range(resilience.BREAKER_THRESHOLD):
                with pytest.raises(ConnectionError):
                    rpc.call(f"http://{hostport}/ping")
        assert resilience.breaker_for(hostport).state == "open"
    finally:
        fault.disarm_all()
        resilience.reset_breakers()


def _drive_breaker_half_open(cl):
    b = resilience.CircuitBreaker(threshold=1, cooldown=0.05,
                                  host="probe.test:1")
    with root_span("drive.breaker_half_open", "test"):
        b.record_failure()
        time.sleep(0.06)
        assert b.allow()             # the half-open probe
        assert b.state == "half-open"


def _drive_breaker_close(cl):
    b = resilience.CircuitBreaker(threshold=1, cooldown=0.05,
                                  host="close.test:1")
    with root_span("drive.breaker_close", "test"):
        b.record_failure()
        time.sleep(0.06)
        assert b.allow()
        b.record_success()
        assert b.state == "closed"


def _drive_replication_rollback(cl):
    master, _s, _st, _c, _t = cl
    _COLLECTION_N[0] += 1
    a = rpc.call(f"{master.url()}/dir/assign?replication=001"
                 f"&collection=repcol{_COLLECTION_N[0]}")
    fault.arm("volume.replicate", "fail*1")
    try:
        with pytest.raises(rpc.RpcError) as ei:
            rpc.call(f"http://{a['url']}/{a['fid']}", "POST", b"x")
        assert ei.value.status == 500
    finally:
        fault.disarm_all()


def _drive_fault_injected(cl):
    _m, _s, _st, client, _t = cl
    _vid, url, fid = _new_volume(cl, "faultcol")
    fault.arm("volume.read", "status:500*1")
    try:
        with pytest.raises(rpc.RpcError):
            rpc.call(f"http://{url}/{fid}")
    finally:
        fault.disarm_all()


def _drive_tier_move(cl):
    _m, _s, _st, _c, tmp = cl
    vid, url, _fid = _new_volume(cl, "tiercol")
    rpc.call_json(f"http://{url}/admin/readonly", "POST",
                  {"volume": vid, "readonly": True})
    rpc.call_json(f"http://{url}/admin/tier_upload", "POST",
                  {"volume": vid, "dest": f"local://{tmp}/tier"})
    rpc.call_json(f"http://{url}/admin/tier_download", "POST",
                  {"volume": vid})


def _drive_scrub(cl):
    vid, url, _fid = _new_volume(cl, "scrubcol")
    rpc.call_json(f"http://{url}/admin/scrub", "POST",
                  {"volume": vid})


def _corrupt_needle_volume(cl, prefix: str):
    """One single-copy volume whose only needle was bit-rotted at
    write time via the volume.corrupt fault point."""
    master, _servers, _stub, _client, _tmp = cl
    _COLLECTION_N[0] += 1
    col = f"{prefix}{_COLLECTION_N[0]}"
    rpc.call(f"{master.url()}/vol/grow?count=1&collection={col}",
             "POST")
    a = rpc.call(f"{master.url()}/dir/assign?collection={col}")
    fault.arm("volume.corrupt", "fail*1")
    try:
        rpc.call(f"http://{a['url']}/{a['fid']}", "POST",
                 b"rotten payload " * 16)
    finally:
        fault.disarm_all()
    return int(a["fid"].split(",")[0]), a["url"]


def _drive_needle_corrupt(cl):
    vid, url = _corrupt_needle_volume(cl, "rotcol")
    # The scrub detects the rot (and quarantines: no replica exists).
    rpc.call_json(f"http://{url}/admin/scrub", "POST",
                  {"volume": vid})
    # Clean up so the corrupt volume doesn't hold healthz degraded
    # for the later health tests.
    rpc.call_json(f"http://{url}/admin/delete_volume", "POST",
                  {"volume": vid})


def _drive_volume_quarantine(cl):
    _drive_needle_corrupt(cl)  # detection quarantines single copies


def _drive_needle_repaired(cl):
    """EC decode self-healing: a shard bit-rotted at encode time is
    caught by the .ecc scrub and reconstructed from >=10 siblings."""
    vid, url, _fid = _new_volume(cl, "echeal")
    fault.arm("volume.corrupt", "fail*1")
    try:
        rpc.call_json(f"http://{url}/admin/ec/generate", "POST",
                      {"volume": vid})
    finally:
        fault.disarm_all()
    rpc.call_json(f"http://{url}/admin/ec/mount", "POST",
                  {"volume": vid})
    out = rpc.call_json(f"http://{url}/admin/scrub", "POST",
                        {"volume": vid, "repair": True})
    assert out["repaired"] >= 1, out


def _drive_volume_recovered(cl):
    """Torn-tail crash recovery through the real mount path."""
    _m, servers, _st, _c, _t = cl
    vid, url, _fid = _new_volume(cl, "reccol")
    vs = next(s for s in servers if s.url() == url)
    base = vs.store.find_volume(vid).file_name()
    rpc.call_json(f"http://{url}/admin/unmount", "POST",
                  {"volume": vid})
    with open(base + ".dat", "ab") as f:
        f.write(b"\xba\xad\xf0\x0d" * 5)  # torn trailing record
    rpc.call_json(f"http://{url}/admin/mount", "POST",
                  {"volume": vid})


def _drive_node_drain(cl):
    """Graceful lifecycle through the real path: a throwaway volume
    server drains over HTTP (node.draining emitted by the server) and
    goodbyes the master (node.drained emitted by its /heartbeat
    handler, which unregisters the node immediately)."""
    master, _s, _st, _c, tmp = cl
    _COLLECTION_N[0] += 1
    d = tmp / f"drainvs{_COLLECTION_N[0]}"
    d.mkdir()
    vs = VolumeServer(master.url(), [str(d)], max_volume_counts=[5],
                      pulse_seconds=60)
    vs.start()
    try:
        out = rpc.call_json(f"http://{vs.url()}/admin/drain", "POST",
                            {"grace": 2.0}, timeout=15.0)
        assert out["draining"], out
        assert all(dn.url() != vs.url()
                   for dn in master.topo.leaves()), \
            "goodbye did not unregister the drained node"
    finally:
        vs.stop()


def _drive_disk_low(cl):
    """Reserve breach: free space below an absurd reserve flips the
    server's volumes readonly and journals disk.low; restoring the
    reserve undoes the flips (only OURS) so later drivers see the
    fixture unchanged."""
    _m, servers, _st, _c, _t = cl
    vs = servers[0]
    try:
        vs.store.disk_reserve_bytes = 1 << 60
        with root_span("drive.disk_low", "test"):
            vs.store.check_disk_reserve()
        assert vs.store.low_disk_dirs
    finally:
        vs.store.disk_reserve_bytes = 0
        vs.store.check_disk_reserve()  # reset: flips ours back
    assert not vs.store.low_disk_dirs


def _drive_disk_full(cl):
    """Injected ENOSPC during a needle append: the handler 500s, the
    volume journals disk.full and flips readonly; the volume is then
    deleted so it cannot degrade later healthz checks."""
    vid, url, fid = _new_volume(cl, "fullcol")
    fault.arm("disk.full", "fail*1")
    try:
        with pytest.raises(rpc.RpcError) as ei:
            rpc.call(f"http://{url}/{fid}", "POST", b"x" * 256)
        assert ei.value.status == 500
    finally:
        fault.disarm_all()
    rpc.call_json(f"http://{url}/admin/delete_volume", "POST",
                  {"volume": vid})


def _drive_server_shed(cl):
    """Overload shed through the real admission gate: a 1-slot,
    0-queue server sheds the second of two concurrent requests with
    429 and journals one server.shed episode."""
    server = rpc.JsonHttpServer(
        admission=rpc.AdmissionControl(1, queue_depth=0,
                                       queue_timeout=0.1))
    server.route("GET", "/slow",
                 lambda q, b: (time.sleep(0.4), {"ok": True})[1])
    server.start()
    statuses = []

    def call_slow():
        try:
            rpc.call(f"http://127.0.0.1:{server.port}/slow",
                     timeout=5.0)
            statuses.append(200)
        except rpc.RpcError as e:
            statuses.append(e.status)
    try:
        threads = [threading.Thread(target=call_slow)
                   for _ in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert 429 in statuses and 200 in statuses, statuses
    finally:
        server.stop()


def _drive_slo_burn(cl):
    """Fast burn through the real engine (stats/slo.py): a tracker
    with a declared availability objective watches 100% of its
    data-plane requests fail — burn rate 1000x the budget over both
    windows — and emits slo.burn exactly once per episode."""
    from seaweedfs_tpu.stats.slo import SloTracker
    tr = SloTracker("driver", node="slo.test:1", clock=lambda: 1000.0)
    tr.set_objectives(availability=0.999)
    for _ in range(20):
        tr.observe("/needle", "GET", 500, 0.001)
    state = tr.burn_state()
    assert state["fast_burn"], state
    # Same episode: no second event (the flip emits, the state doesn't).
    before = events.events_total.value(type="slo.burn")
    tr.burn_state()
    assert events.events_total.value(type="slo.burn") == before


def _drive_replication_ship(cl):
    """Ship/ack/lag through the real shipper: a self-mirror (shipper on
    the holding server pointed at its OWN master — safe, because the
    receive side applies with journal=False so nothing ships back)
    tails the volume's change log, observes the lag episode, posts the
    batch to /admin/replication/apply, and advances the watermark on
    the ack."""
    master, servers, _st, _c, _t = cl
    vid, url, fid = _new_volume(cl, "mirrorcol")
    vs = next(s for s in servers if s.url() == url)
    v = vs.store.find_volume(vid)
    v.enable_rlog()
    # Journaled write: the _new_volume write predates the change log.
    rpc.call(f"http://{url}/{fid}", "POST", b"mirrored payload " * 16)
    sh = ReplicationShipper(vs.store, master.url(), node=url,
                            collections=v.collection)
    with root_span("drive.replication_ship", "test"):
        sh.tick()
    assert v.rlog.pending() == 0, v.rlog.status()


def _drive_replication_cutover(cl):
    """Verified failover through the real shell command: a throwaway
    volume server with a shipper (self-paired; zero volumes, so it is
    trivially caught up) is drained, waited on, and paused by
    cluster.mirror.cutover under the shell lock."""
    master, _s, _st, _c, tmp = cl
    _COLLECTION_N[0] += 1
    d = tmp / f"cutvs{_COLLECTION_N[0]}"
    d.mkdir()
    vs = VolumeServer(master.url(), [str(d)], max_volume_counts=[5],
                      pulse_seconds=60, replicate_peer=master.url())
    vs.start()
    env = CommandEnv(master.url())
    try:
        env.lock()
        with root_span("drive.replication_cutover", "test"):
            out = run_command(
                env, "cluster.mirror.cutover -grace 1 -timeout 15")
        assert "cutover complete" in out
        assert vs.shipper.paused
    finally:
        env.close()
        vs.stop()


def _drive_lifecycle_tier(cl):
    """Policy-driven tiering through the real daemon: a min-age rule
    matches the fresh single-copy volume on the next scan and the
    daemon drives readonly + tier_upload on its holder."""
    from seaweedfs_tpu.lifecycle import LifecycleDaemon, Rule
    from seaweedfs_tpu.lifecycle.policy import Policy
    master, servers, _st, _c, tmp = cl
    vid, url, _fid = _new_volume(cl, "lccol")
    # The daemon reads modified_at from heartbeat state; push one.
    next(s for s in servers
         if s.url() == url)._send_heartbeat(full=True)
    time.sleep(0.05)  # the int modified_at must be strictly in the past
    col = next(dn.volumes[vid].collection
               for dn in master.topo.leaves() if vid in dn.volumes)
    policy = Policy([Rule(collection=col, action="tier",
                          dest=f"local://{tmp}/lctier", min_age=0.001)])
    daemon = LifecycleDaemon(master, policy, interval=3600)
    with root_span("drive.lifecycle_tier", "test"):
        out = daemon.scan_once()
    assert vid in out["tiered"], out


def _drive_lifecycle_promote(cl):
    """Auto-promotion through the real holder-side path: tier a volume,
    then run the promotion worker directly (the scheduler just wraps it
    in a thread + dedup guard)."""
    _m, servers, _st, _c, tmp = cl
    vid, url, _fid = _new_volume(cl, "promcol")
    rpc.call_json(f"http://{url}/admin/readonly", "POST",
                  {"volume": vid, "readonly": True})
    rpc.call_json(f"http://{url}/admin/tier_upload", "POST",
                  {"volume": vid, "dest": f"local://{tmp}/promtier"})
    vs = next(s for s in servers if s.url() == url)
    with root_span("drive.lifecycle_promote", "test"):
        vs._promote_volume(vid)
    assert vs.store.find_volume(vid).remote_file is None


def _drive_volume_expired(cl):
    """Whole-volume TTL retirement through the real sweeper: a 1-minute
    TTL volume, the expiry clock pushed past TTL + grace, one
    _lifecycle_tick on the holder."""
    from seaweedfs_tpu.storage import expiry
    master, servers, _st, _c, _t = cl
    _COLLECTION_N[0] += 1
    col = f"expcol{_COLLECTION_N[0]}"
    rpc.call(f"{master.url()}/vol/grow?count=1&collection={col}"
             f"&ttl=1m", "POST")
    a = rpc.call(f"{master.url()}/dir/assign?collection={col}&ttl=1m")
    rpc.call(f"http://{a['url']}/{a['fid']}", "POST",
             b"short-lived payload " * 8)
    vid = int(a["fid"].split(",")[0])
    vs = next(s for s in servers if s.url() == a["url"])
    expiry.set_clock(lambda: time.time() + 600.0)
    try:
        with root_span("drive.volume_expired", "test"):
            vs._lifecycle_tick()
    finally:
        expiry.reset_clock()
    assert vs.store.find_volume(vid) is None


def _drive_quota_exceeded(cl):
    """A hard stored-usage quota breach through the real assign path:
    install a rule on the live master, seed its rollup as a heartbeat
    would, and watch the assign reject 403."""
    from seaweedfs_tpu.tenancy.quota import QuotaRule
    master, _s, _st, _c, _t = cl
    master.tenant_policy.rules.append(
        QuotaRule(tenant="evquota", max_bytes=1))
    master.usage_rollup.update_node(
        "evnode:0", [{"tenant": "evquota", "collection": "evcol",
                      "bytes": 4096, "objects": 1}])
    master._last_quota_emit.pop("evquota", None)  # defeat the 5s dedup
    try:
        with pytest.raises(rpc.RpcError) as ei:
            rpc.call(f"{master.url()}/dir/assign",
                     headers={"X-Weed-Tenant": "evquota"})
        assert ei.value.status == 403
    finally:
        master.tenant_policy.rules.pop()
        master.usage_rollup.update_node("evnode:0", [])


def _drive_tenant_throttled(cl):
    """An over-rate tenant through the real admission throttle: a
    fresh AdmissionControl with a 1 rps rule sheds within one burst."""
    from seaweedfs_tpu.tenancy.quota import QuotaPolicy, QuotaRule
    adm = rpc.AdmissionControl(
        0, tenant_policy=QuotaPolicy(
            [QuotaRule(tenant="evflood", max_rps=1.0)]))
    adm._last_throttle_emit.pop("evflood", None)
    retry = 0.0
    for _ in range(50):
        retry = adm.throttle("evflood")
        if retry > 0.0:
            break
    assert retry > 0.0, "1 rps bucket never throttled a 50-call burst"


def _drive_flows_budget(cl):
    """An over-budget purpose through the real ledger pacing path: a
    1 B/s repair.fetch ceiling with a zero sustain window breaches on
    the first megabyte noted."""
    from seaweedfs_tpu.stats import flows as _fl
    _fl.LEDGER.set_budgets({"repair.fetch": 1.0}, sustain=0.0)
    try:
        _fl.LEDGER.note("repair.fetch", "in", 1 << 20,
                        peer="evpeer:0", peer_role="volume",
                        local="evflows:0")
    finally:
        _fl.LEDGER.set_budgets({})


def _lease_vs(cl, cluster_id="A"):
    """Throwaway geo volume server (its own -geo.cluster.id + a
    self-pair shipper, like the cutover driver) hosting one volume —
    the lease emit sites live on the geo-enabled write/apply paths."""
    master, _s, _st, _c, tmp = cl
    _COLLECTION_N[0] += 1
    d = tmp / f"geovs{_COLLECTION_N[0]}"
    d.mkdir()
    vs = VolumeServer(master.url(), [str(d)], max_volume_counts=[5],
                      pulse_seconds=60, replicate_peer=master.url(),
                      geo_cluster_id=cluster_id)
    vs.start()
    vid = 9000 + _COLLECTION_N[0]
    vs.store.add_volume(vid, f"geocol{_COLLECTION_N[0]}", "000", "")
    return vs, vid


def _drive_lease_acquire(cl):
    """Acquire through the real handler: the node fences itself in as
    the volume's holder at epoch 1."""
    vs, vid = _lease_vs(cl)
    try:
        with root_span("drive.lease_acquire", "test"):
            out = rpc.call_json(
                f"http://{vs.url()}/admin/lease/acquire",
                payload={"volume": vid})
        assert out["holder_is_local"] and out["epoch"] == 1, out
    finally:
        vs.stop()


def _drive_lease_move(cl):
    """Transfer through the real handler: drain (trivially empty rlog),
    demote-first to cluster B at epoch 2."""
    vs, vid = _lease_vs(cl)
    try:
        rpc.call_json(f"http://{vs.url()}/admin/lease/acquire",
                      payload={"volume": vid})
        with root_span("drive.lease_move", "test"):
            out = rpc.call_json(
                f"http://{vs.url()}/admin/lease/move",
                payload={"volume": vid, "to": "B"})
        assert out["epoch"] == 2, out
        assert not vs.leases.is_holder(vid)
    finally:
        vs.stop()


def _drive_lease_fence(cl):
    """Fence through the real apply path: a batch stamped with a stale
    epoch is refused 409 and journaled."""
    vs, vid = _lease_vs(cl)
    try:
        rpc.call_json(f"http://{vs.url()}/admin/lease/acquire",
                      payload={"volume": vid})
        with root_span("drive.lease_fence", "test"):
            status, out = rpc.call_status(
                f"http://{vs.url()}/admin/replication/apply", "POST",
                json.dumps({"volume": vid, "cluster_id": "STALE",
                            "epoch": 0, "records": []}).encode())
        assert status == 409, (status, out)
    finally:
        vs.stop()


def _drive_device_slow(cl):
    """Collapse through the real ledger path: three consecutive
    streamed runs whose device-occupancy fraction sits at 10% (device
    busy 1s of a 10s window, starved by dispatch) trip the streak and
    emit through note_pipeline's own rate-limited site."""
    from seaweedfs_tpu.parallel.stream_pipeline import PipelineRecorder
    from seaweedfs_tpu.stats.roofline import RooflineLedger
    ledger = RooflineLedger(clock=lambda: 100.0)
    rec = PipelineRecorder(clock=lambda: 0.0)
    rec.note_span("dispatch", 0, 0.0, 9.0)
    rec.note_span("device", 0, 9.0, 10.0)
    for _ in range(3):
        ledger.note_pipeline("encode", rec, node="evdev:0")


def _shard_master():
    """Unstarted master with the metadata-HA plane armed and two fake
    filers registered via the real heartbeat handler (handlers work
    without start(); the fake URLs refuse connections fast, which the
    best-effort acquire/demote pushes tolerate by design)."""
    from seaweedfs_tpu.cluster.master import MasterServer
    m = MasterServer(port=0, filer_shards=2)
    a, b = "http://127.0.0.1:1", "http://127.0.0.1:2"
    for u in (a, b):
        m._filer_heartbeat({}, json.dumps({"url": u,
                                           "shards": {}}).encode())
    return m, a, b


def _drive_shard_promote(cl):
    """Failover through the real sweep: the primary misses its pulses,
    the most-caught-up live follower is promoted at epoch+1."""
    m, _a, _b = _shard_master()
    dead = m._shard_map[0]["primary"]
    m._filers[dead]["last_seen"] = 0.0
    with root_span("drive.shard_promote", "test"):
        m._sweep_dead_filers()
    assert m._shard_map[0]["primary"] != dead


def _drive_shard_move(cl):
    m, a, b = _shard_master()
    old = m._shard_map[0]["primary"]
    target = b if old == a else a
    # The fake old primary refuses its demote push, and a move away
    # from an unreachable primary fails CLOSED while its lease may
    # still be live — age it past the 3-pulse TTL so the move lands.
    m._filers[old]["last_seen"] = 0.0
    with root_span("drive.shard_move", "test"):
        out = m._filer_shard_move(
            {}, json.dumps({"shard": 0, "to": target}).encode())
    assert out["moved"] and out["primary"] == target


def _drive_shard_fence(cl, tmp_path=None):
    """A durable epoch raise on the filer-side plane — the moment a
    stale primary's pushes become refusable."""
    import tempfile
    from seaweedfs_tpu.filer.metaha import ShardPlane
    plane = ShardPlane(None, tempfile.mkdtemp(),
                       "http://127.0.0.1:3")
    with root_span("drive.shard_fence", "test"):
        assert plane._fence(0, 1)


def _drive_repair_converge(cl):
    """Real autopilot convergence: a 001 volume loses one of its two
    holders to the dead-node sweep, and run_now() re-replicates it to
    a freshly started third server through /admin/volume/receive —
    emitting repair.plan, repair.start and repair.finish."""
    master, servers, _st, _c, tmp = cl
    _vid, _url, _fid = _new_volume(cl, "repcol", replication="001")
    vs3 = None
    dead = None
    try:
        d = tmp / f"vs-repair-{int(time.time() * 1000)}"
        d.mkdir()
        vs3 = VolumeServer(master.url(), [str(d)],
                           max_volume_counts=[200], pulse_seconds=60)
        vs3.start()
        deadline = time.time() + 10
        while vs3.url() not in {n.url()
                                for n in master.topo.leaves()}:
            if time.time() > deadline:
                raise TimeoutError("third server never registered")
            time.sleep(0.05)
        dead = servers[1]
        dn = next(n for n in master.topo.leaves()
                  if n.url() == dead.url())
        dn.last_seen = 0.0
        master._sweep_dead_nodes()
        out = master.repair.run_now(kinds=["replicate"])
        assert any(r["outcome"] == "ok" for r in out["results"]), out
    finally:
        if dead is not None:
            dead._send_heartbeat(full=True)  # restore for later drivers
        if vs3 is not None:
            vs3.stop()
            gone = next((n for n in master.topo.leaves()
                         if n.url() == vs3.url()), None)
            if gone is not None:
                master.topo.unregister_data_node(gone)
                master._hb_known.discard(vs3.url())


def _drive_repair_cancel(cl):
    """A queued repair whose deficit heals (the holder returns before
    the executor picks it up) is canceled by the reconcile pass."""
    m = MasterServer(port=0)
    vol = {"id": 7001, "collection": "rc", "size": 0, "file_count": 0,
           "replica_placement": 1}
    m._heartbeat({}, json.dumps(
        {"ip": "127.0.0.1", "port": 7101, "volumes": [vol]}).encode())
    m.repair._degraded_since[("replicate", 7001)] = 0.0
    m.repair.reconcile()
    assert any(t.vid == 7001 for t in m.repair._queue)
    m._heartbeat({}, json.dumps(
        {"ip": "127.0.0.1", "port": 7102, "volumes": [vol]}).encode())
    with root_span("drive.repair_cancel", "test"):
        m.repair.reconcile()
    assert not m.repair._queue


DRIVERS = {
    "volume.assign": _drive_volume_assign,
    "volume.grow": _drive_volume_grow,
    "volume.readonly": _drive_volume_readonly,
    "volume.vacuum": _drive_volume_vacuum,
    "heartbeat.lost": _drive_heartbeat_lost,
    "heartbeat.recovered": _drive_heartbeat_recovered,
    "leader.elect": _drive_leader_elect,
    "leader.stepdown": _drive_leader_stepdown,
    "ec.encode.start": _drive_ec_encode,
    "ec.encode.finish": _drive_ec_encode,
    "ec.rebuild.start": _drive_ec_rebuild,
    "ec.rebuild.finish": _drive_ec_rebuild,
    "ec.repair.local": _drive_ec_repair_local,
    "breaker.open": _drive_breaker_open,
    "breaker.half_open": _drive_breaker_half_open,
    "breaker.close": _drive_breaker_close,
    "replication.rollback": _drive_replication_rollback,
    "fault.injected": _drive_fault_injected,
    "tier.move": _drive_tier_move,
    "scrub.start": _drive_scrub,
    "scrub.finish": _drive_scrub,
    "needle.corrupt": _drive_needle_corrupt,
    "needle.repaired": _drive_needle_repaired,
    "volume.quarantine": _drive_volume_quarantine,
    "volume.recovered": _drive_volume_recovered,
    "node.draining": _drive_node_drain,
    "node.drained": _drive_node_drain,
    "disk.low": _drive_disk_low,
    "disk.full": _drive_disk_full,
    "server.shed": _drive_server_shed,
    "slo.burn": _drive_slo_burn,
    "replication.ship": _drive_replication_ship,
    "replication.ack": _drive_replication_ship,
    "replication.lag": _drive_replication_ship,
    "replication.cutover": _drive_replication_cutover,
    "lifecycle.tier": _drive_lifecycle_tier,
    "lifecycle.promote": _drive_lifecycle_promote,
    "volume.expired": _drive_volume_expired,
    "quota.exceeded": _drive_quota_exceeded,
    "tenant.throttled": _drive_tenant_throttled,
    "flows.budget": _drive_flows_budget,
    "lease.acquire": _drive_lease_acquire,
    "lease.move": _drive_lease_move,
    "lease.fence": _drive_lease_fence,
    "device.slow": _drive_device_slow,
    "shard.promote": _drive_shard_promote,
    "shard.move": _drive_shard_move,
    "shard.fence": _drive_shard_fence,
    "repair.plan": _drive_repair_converge,
    "repair.start": _drive_repair_converge,
    "repair.finish": _drive_repair_converge,
    "repair.cancel": _drive_repair_cancel,
}


def test_driver_catalog_matches_registry():
    """Adding an event type without an emission driver (or vice versa)
    fails here: the catalog and the smoke suite move in lockstep."""
    assert set(DRIVERS) == set(TYPES)
    # Deliberate churn: growing the catalog must touch this number so
    # the diff shows the new types were consciously added (18 from the
    # journal's introduction + 6 data-integrity types + 5 overload/
    # lifecycle types + 1 codec type: ec.repair.local + 1 SLO type:
    # slo.burn + 4 cross-cluster mirror types: replication.ship/ack/
    # lag/cutover + 3 data-lifecycle types: lifecycle.tier/promote +
    # volume.expired + 2 tenancy types: quota.exceeded +
    # tenant.throttled + 1 wire-flow type: flows.budget + 3 geo lease
    # types: lease.acquire/move/fence + 1 device roofline type:
    # device.slow + 3 filer metadata-HA types: shard.promote/move/
    # fence + 4 durability-autopilot types: repair.plan/start/finish/
    # cancel).
    assert len(TYPES) == 52


@pytest.mark.parametrize("etype", sorted(TYPES))
def test_every_event_type_is_emitted(cluster, etype):
    """Drive the real code path hosting each event's emit site, observe
    the event land in the journal with a non-empty trace id (tracing is
    on for this cluster).  An emit site that code motion orphaned shows
    up as zero new events."""
    before_seq = JOURNAL._seq
    before = events.events_total.value(type=etype)
    DRIVERS[etype](cluster)
    after = events.events_total.value(type=etype)
    assert after > before, f"event type {etype} never emitted"
    fresh = [ev for ev in JOURNAL.snapshot(type_=etype)
             if ev["seq"] > before_seq]
    assert fresh, f"no fresh {etype} event in the ring"
    for ev in fresh:
        assert ev["trace_id"], \
            f"{etype} emitted without a trace id: {ev}"
        assert ev["severity"] in events.SEVERITIES


# -- health rollup -----------------------------------------------------------

def test_healthz_degraded_then_repaired(cluster):
    """The acceptance flow: a mounted EC volume loses shards ->
    /cluster/healthz turns 503 and cluster.check names the degraded
    volume; after repair both report healthy."""
    master, servers, _st, _c, _t = cluster
    vid, url, _fid = _new_volume(cluster, "healthec")
    rpc.call_json(f"http://{url}/admin/ec/generate", "POST",
                  {"volume": vid})
    rpc.call_json(f"http://{url}/admin/ec/mount", "POST",
                  {"volume": vid})
    status, doc = rpc.call_status(f"{master.url()}/cluster/healthz")
    assert status == 200 and doc["healthy"], doc["problems"]

    rpc.call_json(f"http://{url}/admin/ec/delete_shards", "POST",
                  {"volume": vid, "shards": [2, 5]})
    status, doc = rpc.call_status(f"{master.url()}/cluster/healthz")
    assert status == 503 and not doc["healthy"]
    assert any(f"ec volume {vid}" in p and "degraded" in p
               for p in doc["problems"]), doc["problems"]
    row = next(v for v in doc["ec_volumes"] if v["id"] == vid)
    assert row["missing"] == [2, 5] and row["present"] == 12
    # Node rows carry the heartbeat-fed disk status.
    assert any(d.get("percent_used") is not None
               for n in doc["nodes"] for d in n["disks"])

    env = CommandEnv(master.url())
    try:
        out = run_command(env, "cluster.check")
        assert "UNHEALTHY" in out
        assert f"ec volume {vid}" in out and "degraded" in out

        # Repair: rebuild the lost shards and remount.
        rpc.call_json(f"http://{url}/admin/ec/rebuild", "POST",
                      {"volume": vid})
        rpc.call_json(f"http://{url}/admin/ec/mount", "POST",
                      {"volume": vid})
        status, doc = rpc.call_status(
            f"{master.url()}/cluster/healthz")
        assert status == 200 and doc["healthy"], doc["problems"]
        out = run_command(env, "cluster.check")
        assert out.startswith("HEALTHY")
    finally:
        env.close()


def test_events_ls_and_cluster_aggregation(cluster):
    master, _s, _st, _c, _t = cluster
    env = CommandEnv(master.url())
    try:
        out = run_command(env, "events.ls -limit 500")
        assert "volume.assign" in out and "heartbeat.lost" in out
        out = run_command(env, "events.ls -types")
        for t in TYPES:
            assert t in out
        out = run_command(env, "events.ls -type volume.grow")
        lines = [ln for ln in out.splitlines()[1:] if ln.strip()]
        assert lines and all("volume.grow" in ln for ln in lines)
        with pytest.raises(Exception):
            run_command(env, "events.ls -type bogus")
    finally:
        env.close()
    # Master-side aggregation endpoint (single timeline, deduplicated).
    out = rpc.call(f"{master.url()}/cluster/events?limit=1000")
    assert out["servers_reached"] >= 1
    types = {e["type"] for e in out["events"]}
    assert "volume.assign" in types and "ec.encode.finish" in types
    ts = [e["ts"] for e in out["events"]]
    assert ts == sorted(ts)  # one merged, ordered timeline


def test_node_health_gauge_and_live_scrapes_validate(cluster):
    """Every live role's /metrics carries the events counter and passes
    the promtool-style validator after the full smoke drove real
    traffic through it."""
    master, servers, _st, _c, _t = cluster
    mtext = rpc.call(f"{master.url()}/metrics").decode()
    assert "SeaweedFS_events_total" in mtext
    assert 'SeaweedFS_node_health{node="' in mtext
    assert "SeaweedFS_node_health" in mtext
    for vs in servers:
        vtext = rpc.call(f"http://{vs.url()}/metrics").decode()
        assert "SeaweedFS_disk_percent_used" in vtext
        assert "SeaweedFS_disk_all_bytes" in vtext
        assert "SeaweedFS_disk_used_bytes" in vtext
        assert validate_exposition(vtext) == [], vs.url()
    assert validate_exposition(mtext) == []


# -- cross-process aggregation -----------------------------------------------

def test_cluster_events_aggregates_across_processes(tmp_path):
    """A volume server in a SEPARATE process: its journal entries are
    only reachable over HTTP, so /cluster/events must pull and merge
    them — in-process sharing can't fake this one."""
    import subprocess
    import sys

    master = MasterServer(volume_size_limit_mb=16,
                          meta_dir=str(tmp_path / "meta"),
                          pulse_seconds=60)
    master.start()
    vport = rpc.free_port()
    data = tmp_path / "vsdata"
    data.mkdir()
    proc = subprocess.Popen(
        [sys.executable, "-m", "seaweedfs_tpu", "volume",
         f"-port={vport}", f"-dir={data}", "-max=8",
         f"-mserver=127.0.0.1:{master.server.port}"],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    try:
        deadline = time.time() + 30
        while not list(master.topo.leaves()):
            if time.time() > deadline:
                raise TimeoutError("subprocess volume server never "
                                   "registered")
            time.sleep(0.2)
        rpc.call(f"{master.url()}/vol/grow?count=1", "POST")
        vol_list = rpc.call(f"{master.url()}/vol/list")
        node = vol_list["topology"]["data_centers"][0]["racks"][0][
            "nodes"][0]
        vid = node["volumes"][0]["id"]
        # Emit an event INSIDE the subprocess (its own journal).
        rpc.call_json(f"http://127.0.0.1:{vport}/admin/readonly",
                      "POST", {"volume": vid, "readonly": True})
        out = rpc.call(f"{master.url()}/cluster/events"
                       f"?type=volume.readonly")
        assert any(e["node"] == f"127.0.0.1:{vport}"
                   and e["attrs"].get("vid") == vid
                   for e in out["events"]), out
        assert out["servers_reached"] >= 2
        # The master's own journal contributes too: one timeline.
        out = rpc.call(f"{master.url()}/cluster/events?limit=1000")
        types = {e["type"] for e in out["events"]}
        assert "heartbeat.recovered" in types
        assert "volume.assign" in types
    finally:
        proc.terminate()
        proc.wait(timeout=10)
        master.stop()
