"""Mesh-batched multi-volume EC rebuild driven through the shell.

The production entry point (`ec.rebuild -batch`) must gather survivor
shards from their volume-server holders, rebuild every volume's missing
shards in mesh-batched compiled steps (volumes data-parallel over the
8-device virtual mesh), scatter the rebuilt shards back onto cluster
nodes, and mount them — byte-identical to the originals.

Reference behavior being matched: weed/shell/command_ec_rebuild.go:57
(one volume at a time on one node) — here batched per SURVEY §2.3's
mapping of multi-volume rebuild onto the `vol` mesh axis.
"""

import pytest

from seaweedfs_tpu.cluster import rpc
from seaweedfs_tpu.cluster.client import WeedClient
from seaweedfs_tpu.cluster.master import MasterServer
from seaweedfs_tpu.cluster.volume_server import VolumeServer
from seaweedfs_tpu.parallel import cluster_rebuild
from seaweedfs_tpu.shell import CommandEnv, run_command


@pytest.fixture
def cluster(tmp_path):
    master = MasterServer(volume_size_limit_mb=64,
                          meta_dir=str(tmp_path),
                          # Volume servers here pulse every 60s:
                          # the master's dead-node threshold
                          # (2x its own pulse) must outlast a
                          # slow-machine encode, or the sweep
                          # empties the topology mid-test.
                          pulse_seconds=60)
    master.start()
    servers = []
    for i in range(3):
        d = tmp_path / f"vs{i}"
        d.mkdir()
        vs = VolumeServer(master.url(), [str(d)], pulse_seconds=60)
        vs.start()
        servers.append(vs)
    yield master, servers
    for vs in servers:
        vs.stop()
    master.stop()


def _freshen(servers):
    for vs in servers:
        vs._send_heartbeat(full=True)
        vs._ec_loc_cache.clear()


def _make_ec_volumes(master, servers, n_volumes=3, objs_per_volume=6):
    """Grow volumes, upload into each, EC-encode and spread 5/5/4.
    Returns {vid: [(payload, fid), ...]}."""
    client = WeedClient(master.url())
    rpc.call_json(f"{master.url()}/vol/grow?count={n_volumes}", "POST")
    by_vid: dict[int, list] = {}
    i = 0
    while any(len(v) < objs_per_volume
              for v in by_vid.values()) or len(by_vid) < n_volumes:
        payload = f"batch-rebuild-{i}".encode() * (i % 7 + 1)
        fid = client.upload_data(payload)
        by_vid.setdefault(int(fid.split(",")[0]), []).append(
            (payload, fid))
        i += 1
        if i > 400:
            break
    vids = sorted(by_vid)[:n_volumes]
    spread = [(servers[0], [0, 1, 2, 3, 4]),
              (servers[1], [5, 6, 7, 8, 9]),
              (servers[2], [10, 11, 12, 13])]
    for vid in vids:
        src = client.lookup(vid)[0]["url"]
        rpc.call_json(f"http://{src}/admin/ec/generate", "POST",
                      {"volume": vid})
        for vs, shards in spread:
            if vs.url() != src:
                rpc.call_json(f"http://{vs.url()}/admin/ec/copy_shard",
                              "POST", {"volume": vid, "source": src,
                                       "shards": shards,
                                       "copy_ecx": True})
        for vs, shards in spread:
            rpc.call_json(f"http://{vs.url()}/admin/ec/mount", "POST",
                          {"volume": vid})
            drop = [s for s in range(14) if s not in shards]
            rpc.call_json(f"http://{vs.url()}/admin/ec/delete_shards",
                          "POST", {"volume": vid, "shards": drop})
        rpc.call_json(f"http://{src}/admin/delete_volume", "POST",
                      {"volume": vid})
    _freshen(servers)
    return client, {vid: by_vid[vid] for vid in vids}


def _shard_bytes(server_url, vid, sid) -> bytes:
    return bytes(rpc.call(
        f"http://{server_url}/admin/ec/shard_file?volume={vid}"
        f"&shard={sid}"))


def _holder_of(env, vid, sid) -> str:
    return env.ec_shard_locations(vid)[sid][0]


def test_batch_rebuild_through_shell(cluster):
    master, servers = cluster
    client, volumes = _make_ec_volumes(master, servers, n_volumes=3)
    vids = sorted(volumes)
    env = CommandEnv(master.url())

    # Capture originals, then lose shards: two volumes lose the SAME
    # set (one mesh group, V=2) and the third a different set (second
    # group) — exercises signature grouping and multi-step batching.
    lost = {vids[0]: [0, 3], vids[1]: [0, 3], vids[2]: [12, 13]}
    originals = {}
    for vid, sids in lost.items():
        for sid in sids:
            holder = _holder_of(env, vid, sid)
            originals[(vid, sid)] = _shard_bytes(holder, vid, sid)
            rpc.call_json(f"http://{holder}/admin/ec/delete_shards",
                          "POST", {"volume": vid, "shards": [sid]})
    _freshen(servers)
    for vid, sids in lost.items():
        present = set(env.ec_shard_locations(vid))
        assert all(s not in present for s in sids)

    run_command(env, "lock")
    out = run_command(env, "ec.rebuild -batch")
    for vid in vids:
        assert f"volume {vid}: rebuilt shards" in out

    _freshen(servers)
    for vid, sids in lost.items():
        locs = env.ec_shard_locations(vid)
        assert sorted(locs) == list(range(14)), \
            f"volume {vid} shards incomplete: {sorted(locs)}"
        for sid in sids:
            rebuilt = _shard_bytes(locs[sid][0], vid, sid)
            assert rebuilt == originals[(vid, sid)], \
                f"volume {vid} shard {sid} not byte-identical"

    # Every object still reads back through the rebuilt cluster.
    for vid, pairs in volumes.items():
        for payload, fid in pairs:
            assert bytes(client.download(fid)) == payload
    env.close()


def test_batch_rebuild_fails_over_flaky_holders(cluster):
    """One dead/flaky holder must not fail the batch: every shard fetch
    walks all holders (store_ec.go:264-320) and retries transient
    errors (round-2/3 verdict weak spot #7)."""
    master, servers = cluster
    client, volumes = _make_ec_volumes(master, servers, n_volumes=2)
    vids = sorted(volumes)
    real_env = CommandEnv(master.url())
    originals = {}
    for vid in vids:
        holder = _holder_of(real_env, vid, 1)
        originals[vid] = _shard_bytes(holder, vid, 1)
        rpc.call_json(f"http://{holder}/admin/ec/delete_shards",
                      "POST", {"volume": vid, "shards": [1]})
    _freshen(servers)

    class FlakyEnv:
        """Delegates to the real env but reports a dead node as the
        FIRST holder of every shard."""

        def __getattr__(self, name):
            return getattr(real_env, name)

        def ec_shard_locations(self, vid):
            locs = real_env.ec_shard_locations(vid)
            return {sid: ["127.0.0.1:1"] + urls
                    for sid, urls in locs.items()}

    from seaweedfs_tpu.parallel import cluster_rebuild
    out = cluster_rebuild.batch_rebuild(FlakyEnv())
    assert all(f"volume {vid}: rebuilt shards" in "\n".join(out)
               for vid in vids), out
    _freshen(servers)
    for vid in vids:
        locs = real_env.ec_shard_locations(vid)
        assert sorted(locs) == list(range(14))
        assert _shard_bytes(locs[1][0], vid, 1) == originals[vid]
    real_env.close()


def test_fetch_shard_exhausts_holders_with_clear_error():
    from seaweedfs_tpu.parallel.cluster_rebuild import _fetch_shard
    with pytest.raises(rpc.RpcError) as ei:
        _fetch_shard(["127.0.0.1:1", "127.0.0.1:2"], 7, 3)
    assert "7.3 unreachable on any holder" in ei.value.message


def test_batch_rebuild_skips_unrecoverable(cluster):
    master, servers = cluster
    client, volumes = _make_ec_volumes(master, servers, n_volumes=1)
    vid = next(iter(volumes))
    env = CommandEnv(master.url())
    # Lose 5 shards -> only 9 survive -> must be skipped, not crash.
    for sid in [0, 1, 2, 3, 4]:
        holder = _holder_of(env, vid, sid)
        rpc.call_json(f"http://{holder}/admin/ec/delete_shards", "POST",
                      {"volume": vid, "shards": [sid]})
    _freshen(servers)
    run_command(env, "lock")
    out = run_command(env, "ec.rebuild -batch")
    assert "SKIPPED" in out and str(vid) in out
    env.close()


def test_batch_rebuild_nothing_to_do(cluster):
    master, servers = cluster
    client, volumes = _make_ec_volumes(master, servers, n_volumes=1)
    env = CommandEnv(master.url())
    run_command(env, "lock")
    assert run_command(env, "ec.rebuild -batch") == "nothing to rebuild"
    env.close()


def test_plan_rebuilds_groups_by_signature(cluster):
    master, servers = cluster
    client, volumes = _make_ec_volumes(master, servers, n_volumes=3)
    vids = sorted(volumes)
    env = CommandEnv(master.url())
    lost = {vids[0]: [1], vids[1]: [1], vids[2]: [13]}
    for vid, sids in lost.items():
        for sid in sids:
            holder = _holder_of(env, vid, sid)
            rpc.call_json(f"http://{holder}/admin/ec/delete_shards",
                          "POST", {"volume": vid, "shards": [sid]})
    _freshen(servers)
    plan = cluster_rebuild.plan_rebuilds(env)
    assert len(plan.groups) == 2
    sig_two = [vs for vs in plan.groups.values() if len(vs) == 2]
    assert len(sig_two) == 1
    assert {v for v, _ in sig_two[0]} == {vids[0], vids[1]}
    assert not plan.skipped
    env.close()
