"""Image handling: EXIF orientation fix + on-the-fly resize.

Reference behaviors: weed/images/orientation.go (fix on JPEG upload),
resizing.go (?width=&height=&mode= on reads).
"""

import io
import urllib.request

import pytest

from seaweedfs_tpu.images import (HAS_PIL, fix_jpeg_orientation,
                                  resized)

pytestmark = pytest.mark.skipif(not HAS_PIL, reason="PIL unavailable")


def _jpeg(width=64, height=32, orientation=None) -> bytes:
    from PIL import Image
    img = Image.new("RGB", (width, height), (200, 30, 30))
    # Asymmetry so rotation is observable.
    for x in range(width // 2):
        for y in range(height):
            img.putpixel((x, y), (30, 30, 200))
    out = io.BytesIO()
    if orientation:
        exif = Image.Exif()
        exif[0x0112] = orientation
        img.save(out, format="JPEG", exif=exif)
    else:
        img.save(out, format="JPEG")
    return out.getvalue()


def test_orientation_fix_rotates_and_strips():
    from PIL import Image
    data = _jpeg(64, 32, orientation=6)  # 90° CW needed
    fixed = fix_jpeg_orientation(data)
    img = Image.open(io.BytesIO(fixed))
    assert img.size == (32, 64)  # rotated
    assert img.getexif().get(0x0112, 1) == 1  # tag gone/neutral


def test_orientation_noop_for_upright_and_non_jpeg():
    data = _jpeg(64, 32)
    assert fix_jpeg_orientation(data) == data
    assert fix_jpeg_orientation(b"not an image") == b"not an image"


def test_resize_modes():
    from PIL import Image
    data = _jpeg(100, 50)
    out, mime = resized(data, width=50)  # aspect preserved
    assert mime == "image/jpeg"
    assert Image.open(io.BytesIO(out)).size == (50, 25)
    out, _ = resized(data, width=40, height=40, mode="fill")
    assert Image.open(io.BytesIO(out)).size == (40, 40)
    out, _ = resized(data, width=40, height=40, mode="fit")
    assert Image.open(io.BytesIO(out)).size == (40, 40)
    # Non-image data passes through untouched.
    raw, mime = resized(b"plain text", width=10)
    assert raw == b"plain text" and mime == ""


def test_volume_server_image_pipeline(tmp_path):
    """Upload a rotated JPEG, read it back resized through the cluster."""
    from PIL import Image

    from seaweedfs_tpu.cluster.client import WeedClient
    from seaweedfs_tpu.cluster.master import MasterServer
    from seaweedfs_tpu.cluster.volume_server import VolumeServer
    master = MasterServer(volume_size_limit_mb=64,
                          meta_dir=str(tmp_path))
    master.start()
    vs = VolumeServer(master.url(), [str(tmp_path / "vs")],
                      pulse_seconds=60)
    vs.start()
    try:
        client = WeedClient(master.url())
        a = client.assign()
        fid = a["fid"]
        data = _jpeg(64, 32, orientation=6)
        req = urllib.request.Request(
            f"http://{a['url']}/{fid}?mime=image/jpeg", data=data,
            method="POST")
        urllib.request.urlopen(req).read()
        # Orientation was fixed at write time: stored bytes are 32x64.
        stored = client.download(fid)
        assert Image.open(io.BytesIO(stored)).size == (32, 64)
        # Resize on read.
        with urllib.request.urlopen(
                f"http://{a['url']}/{fid}?width=16") as r:
            assert r.headers["Content-Type"] == "image/jpeg"
            assert Image.open(io.BytesIO(r.read())).size == (16, 32)
    finally:
        vs.stop()
        master.stop()
