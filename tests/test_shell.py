"""Shell command suite against an in-process cluster.

Mirrors the reference's shell tests (weed/shell/command_ec_test.go,
command_volume_balance_test.go) but runs the real command implementations
against live master + volume servers, like §3.3's lifecycle.
"""

import pytest

from seaweedfs_tpu.cluster import rpc
from seaweedfs_tpu.cluster.client import WeedClient
from seaweedfs_tpu.cluster.master import MasterServer
from seaweedfs_tpu.cluster.volume_server import VolumeServer
from seaweedfs_tpu.shell import CommandEnv, ShellError, run_command


@pytest.fixture
def cluster(tmp_path):
    master = MasterServer(volume_size_limit_mb=64, meta_dir=str(tmp_path))
    master.start()
    servers = []
    for i in range(3):
        d = tmp_path / f"vs{i}"
        d.mkdir()
        vs = VolumeServer(master.url(), [str(d)], pulse_seconds=60)
        vs.start()
        servers.append(vs)
    yield master, servers
    for vs in servers:
        vs.stop()
    master.stop()


@pytest.fixture
def env(cluster):
    master, _servers = cluster
    e = CommandEnv(master.url())
    yield e
    e.close()


def _freshen(servers):
    for vs in servers:
        vs._send_heartbeat(full=True)
        vs._ec_loc_cache.clear()


def _upload_some(master, n=20):
    """Returns (client, vid, [(payload, fid), ...]) for one volume."""
    client = WeedClient(master.url())
    pairs = [(f"shell-payload-{i}".encode(),
              client.upload_data(f"shell-payload-{i}".encode()))
             for i in range(n)]
    vid = int(pairs[0][1].split(",")[0])
    return client, vid, [(p, f) for p, f in pairs
                         if int(f.split(",")[0]) == vid]


def test_lock_required(env):
    with pytest.raises(ShellError, match="lock"):
        run_command(env, "ec.encode -volumeId 1")


def test_help_lists_commands(env):
    out = run_command(env, "help")
    for name in ("ec.encode", "ec.rebuild", "ec.balance", "ec.decode",
                 "volume.balance", "volume.fix.replication", "lock"):
        assert name in out


def test_volume_list(cluster, env):
    master, servers = cluster
    _client, vid, _fids = _upload_some(master)
    _freshen(servers)
    out = run_command(env, "volume.list")
    assert f"volume id:{vid}" in out
    assert "DataNode" in out


def test_ec_encode_balance_rebuild_decode_lifecycle(cluster, env):
    master, servers = cluster
    client, vid, fids = _upload_some(master)
    _freshen(servers)
    run_command(env, "lock")

    # encode: volume becomes 14 shards spread over the 3 servers.
    out = run_command(env, f"ec.encode -volumeId {vid}")
    assert f"volume {vid}" in out
    _freshen(servers)
    shard_map = env.ec_shard_locations(vid)
    assert sorted(shard_map) == list(range(14))
    # original volume gone everywhere
    for vs in servers:
        assert vs.store.find_volume(vid) is None
    # reads still work through any server
    for payload, fid in fids[:3]:
        data = rpc.call(f"http://{servers[0].url()}/{fid}")
        assert bytes(data) == payload

    # balance: shard counts stay within 1 of each other.
    run_command(env, "ec.balance")
    _freshen(servers)
    counts = {vs.url(): 0 for vs in servers}
    for sid, urls in env.ec_shard_locations(vid).items():
        assert len(urls) == 1, f"shard {sid} duplicated"
        counts[urls[0]] += 1
    assert max(counts.values()) - min(counts.values()) <= 1

    # lose two shards, rebuild restores all 14.
    victim = servers[0]
    have = sorted(sid for sid, urls in env.ec_shard_locations(vid).items()
                  if victim.url() in urls)
    drop = have[:2]
    rpc.call_json(f"http://{victim.url()}/admin/ec/delete_shards", "POST",
                  {"volume": vid, "shards": drop})
    _freshen(servers)
    assert len(env.ec_shard_locations(vid)) == 14 - len(drop)
    out = run_command(env, f"ec.rebuild -volumeId {vid}")
    assert "rebuilt" in out
    _freshen(servers)
    assert sorted(env.ec_shard_locations(vid)) == list(range(14))

    # decode: back to a normal volume; all payloads intact.
    out = run_command(env, f"ec.decode -volumeId {vid}")
    assert "decoded" in out
    _freshen(servers)
    assert env.ec_shard_locations(vid) == {}
    locs = env.volume_locations(vid)
    assert len(locs) == 1
    client.cache.forget(vid)
    for payload, fid in fids:
        assert client.download(fid) == payload


def test_volume_balance_and_move(cluster, env):
    master, servers = cluster
    client = WeedClient(master.url())
    # Grow several volumes; they all land via weighted placement, then
    # balance evens them out.
    rpc.call_json(f"{master.url()}/vol/grow?count=6", payload={})
    _freshen(servers)
    run_command(env, "lock")
    run_command(env, "volume.balance")
    _freshen(servers)
    counts = [len(n["volumes"]) for n in env.data_nodes()]
    assert max(counts) - min(counts) <= 1

    # move one volume explicitly and read through the new location.
    fid = client.upload_data(b"move-me")
    vid = int(fid.split(",")[0])
    src = env.volume_locations(vid)[0]
    dst = next(n["url"] for n in env.data_nodes() if n["url"] != src)
    # target may already hold a replica; pick a fresh vid if so
    run_command(env,
                f"volume.move -volumeId {vid} -source {src} -target {dst}")
    _freshen(servers)
    client.cache.forget(vid)
    assert client.download(fid) == b"move-me"
    assert dst in env.volume_locations(vid)
    assert src not in env.volume_locations(vid)


def test_volume_fix_replication(cluster, env):
    master, servers = cluster
    client = WeedClient(master.url())
    a = client.assign(replication="001")
    fid = a["fid"]
    rpc.call(f"http://{a['url']}/{fid}", "POST", b"replicated-data")
    vid = int(fid.split(",")[0])
    _freshen(servers)
    locs = env.volume_locations(vid)
    assert len(locs) == 2
    # Kill one replica.
    dead = locs[1]
    env.vs_call(dead, "/admin/delete_volume", {"volume": vid})
    _freshen(servers)
    assert len(env.volume_locations(vid)) == 1
    run_command(env, "lock")
    out = run_command(env, "volume.fix.replication")
    assert f"volume {vid}" in out
    _freshen(servers)
    assert len(env.volume_locations(vid)) == 2


def test_collection_commands(cluster, env):
    master, servers = cluster
    client = WeedClient(master.url())
    client.upload_data(b"x", collection="photos")
    _freshen(servers)
    out = run_command(env, "collection.list")
    assert "photos" in out
    run_command(env, "lock")
    out = run_command(env, "collection.delete -collection photos")
    assert "photos" in out


def test_evacuate(cluster, env):
    master, servers = cluster
    client, vid, fids = _upload_some(master, n=5)
    _freshen(servers)
    node = env.volume_locations(vid)[0]
    run_command(env, "lock")
    out = run_command(env, f"volumeServer.evacuate -node {node}")
    assert "->" in out
    _freshen(servers)
    assert node not in env.volume_locations(vid)
    client.cache.forget(vid)
    payload, fid = fids[0]
    assert client.download(fid) == payload
