"""In-process mini Cassandra: CQL binary protocol v4 frames
(STARTUP→READY, QUERY→RESULT) over a sorted (directory, name) dict,
dispatching on the store's five exact statement texts."""

from __future__ import annotations

import socket
import struct
import threading

from seaweedfs_tpu.filer.cassandra_store import (OP_ERROR, OP_QUERY,
                                                 OP_READY, OP_RESULT,
                                                 OP_STARTUP,
                                                 RESULT_ROWS,
                                                 RESULT_VOID,
                                                 CassandraStore)


class MiniCassandra:
    def __init__(self):
        # (directory, name) -> meta bytes
        self.rows: dict[tuple[str, str], bytes] = {}
        self.lock = threading.Lock()
        self.queries_seen: list[str] = []
        self._sock = socket.create_server(("127.0.0.1", 0))
        self.port = self._sock.getsockname()[1]
        self._running = True
        threading.Thread(target=self._accept, daemon=True).start()

    def _accept(self):
        while self._running:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    @staticmethod
    def _recv_exact(conn, n):
        out = bytearray()
        while len(out) < n:
            piece = conn.recv(n - len(out))
            if not piece:
                return None
            out += piece
        return bytes(out)

    def _serve(self, conn):
        try:
            while True:
                hdr = self._recv_exact(conn, 9)
                if hdr is None:
                    return
                ver, _fl, stream, op, length = struct.unpack(">BBhBi",
                                                             hdr)
                body = self._recv_exact(conn, length) if length else b""
                if body is None:
                    return
                if op == OP_STARTUP:
                    out_op, out = OP_READY, b""
                elif op == OP_QUERY:
                    out_op, out = self._query(body)
                else:
                    out_op = OP_ERROR
                    msg = b"bad opcode"
                    out = struct.pack(">iH", 0x000A, len(msg)) + msg
                conn.sendall(struct.pack(">BBhBi", 0x84, 0, stream,
                                         out_op, len(out)) + out)
        except OSError:
            pass
        finally:
            conn.close()

    @staticmethod
    def _void():
        return OP_RESULT, struct.pack(">i", RESULT_VOID)

    @staticmethod
    def _rows_result(cols: list[str], rows: list[list[bytes]]):
        # flags=1 (global table spec), varchar columns.
        body = struct.pack(">ii", RESULT_ROWS, 0)[:4]
        meta = struct.pack(">ii", 0x0001, len(cols))
        for part in (b"ks", b"filemeta"):
            meta += struct.pack(">H", len(part)) + part
        for c in cols:
            cb = c.encode()
            meta += struct.pack(">H", len(cb)) + cb
            meta += struct.pack(">H", 0x000D)  # varchar
        body = struct.pack(">i", RESULT_ROWS) + meta
        body += struct.pack(">i", len(rows))
        for row in rows:
            for cell in row:
                if cell is None:
                    body += struct.pack(">i", -1)
                else:
                    body += struct.pack(">i", len(cell)) + cell
        return OP_RESULT, body

    def _query(self, body: bytes):
        n = struct.unpack_from(">i", body)[0]
        cql = body[4:4 + n].decode()
        i = 4 + n
        _consistency, flags = struct.unpack_from(">HB", body, i)
        i += 3
        values: list[bytes] = []
        if flags & 0x01:
            count = struct.unpack_from(">H", body, i)[0]
            i += 2
            for _ in range(count):
                ln = struct.unpack_from(">i", body, i)[0]
                i += 4
                values.append(body[i:i + ln] if ln >= 0 else b"")
                i += max(ln, 0)
        with self.lock:
            self.queries_seen.append(cql)
            return self._dispatch(cql, values)

    def _dispatch(self, cql: str, v: list[bytes]):
        s = CassandraStore
        if cql.startswith("USE"):
            return self._void()
        if cql == s.SQL_INSERT:
            d, name = v[0].decode(), v[1].decode()
            self.rows[(d, name)] = v[2]
            return self._void()
        if cql == s.SQL_FIND:
            d, name = v[0].decode(), v[1].decode()
            meta = self.rows.get((d, name))
            if meta is None:
                return self._rows_result(["meta"], [])
            return self._rows_result(["meta"], [[meta]])
        if cql == s.SQL_DELETE:
            self.rows.pop((v[0].decode(), v[1].decode()), None)
            return self._void()
        if cql == s.SQL_DELETE_DIR:
            d = v[0].decode()
            for k in [k for k in self.rows if k[0] == d]:
                del self.rows[k]
            return self._void()
        if cql in (s.SQL_LIST_EXCLUSIVE, s.SQL_LIST_INCLUSIVE):
            d, start = v[0].decode(), v[1].decode()
            limit = struct.unpack(">i", v[2])[0]
            keep = sorted(
                (name, meta) for (dd, name), meta in self.rows.items()
                if dd == d and (
                    name >= start if cql == s.SQL_LIST_INCLUSIVE
                    else name > start))
            keep = keep[:limit]
            return self._rows_result(
                ["name", "meta"],
                [[name.encode(), meta] for name, meta in keep])
        msg = f"unknown statement: {cql}".encode()
        return OP_ERROR, struct.pack(">iH", 0x2000, len(msg)) + msg

    def close(self):
        self._running = False
        try:
            self._sock.close()
        except OSError:
            pass
