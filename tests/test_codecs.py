"""Pluggable erasure codecs: registry, LRC(10,2,2) algebra, the
repair-bandwidth planner, minimal-read rebuilds, codec-agnostic
scrub/.ecc integrity, and the cluster acceptance flow (encode with
-codec lrc, survive losses, rebuild with <= 6 shard reads asserted via
SeaweedFS_ec_repair_read_bytes_total and the planner report).

Property tests: EVERY registered codec round-trips against the
NumpyCoder reference under randomized erasure patterns up to its
declared tolerance, and raises cleanly one past what the code can
express.
"""

import itertools
import os
import random

import numpy as np
import pytest

from seaweedfs_tpu.codecs import (Codec, codec_names, get_codec,
                                  rs_codec, solve_decode)
from seaweedfs_tpu.ops import gf256
from seaweedfs_tpu.ops.erasure import new_coder

pytestmark = pytest.mark.codecs

RNG = np.random.default_rng(7)


def _all_shards(codec: Codec, data: np.ndarray) -> np.ndarray:
    return np.concatenate(
        [data, gf256.mat_mul(codec.parity_matrix(), data)], axis=0)


def _roundtrip(codec: Codec, shards: np.ndarray, missing) -> None:
    present = tuple(s for s in range(codec.total_shards)
                    if s not in missing)
    mat, used = codec.decode_matrix(present, tuple(missing))
    rec = gf256.mat_mul(mat, shards[list(used)])
    assert np.array_equal(rec, shards[list(missing)]), missing


# -- registry ---------------------------------------------------------------

def test_registry_has_rs_and_lrc():
    assert {"rs", "lrc"} <= set(codec_names())
    rs = get_codec("rs")
    assert (rs.data_shards, rs.parity_shards, rs.tolerance) == (10, 4, 4)
    lrc = get_codec("lrc")
    assert (lrc.data_shards, lrc.parity_shards) == (10, 4)
    assert lrc.total_shards == rs.total_shards == 14
    assert len(lrc.locality) == 2
    with pytest.raises(ValueError, match="unknown erasure codec"):
        get_codec("nope")
    # None / empty resolve to the wire-compatible default.
    assert get_codec(None).name == "rs"


def test_rs_codec_matches_gf256_reference():
    """The registered rs codec IS the klauspost construction: same
    parity matrix, same decode matrices, same first-k survivor
    selection — the wire-compat invariant."""
    rs = get_codec("rs")
    assert np.array_equal(rs.parity_matrix(),
                          gf256.parity_matrix(10, 14))
    present = tuple(s for s in range(14) if s not in (0, 13))
    mat, used = rs.decode_matrix(present, (0, 13))
    ref_mat, ref_used = gf256.decode_matrix(10, 14, list(present),
                                            wanted=[0, 13])
    assert list(used) == ref_used
    assert np.array_equal(mat, ref_mat)


def test_lrc_local_groups_and_repair_costs():
    lrc = get_codec("lrc")
    assert lrc.local_group(0).members == (0, 1, 2, 3, 4, 10)
    assert lrc.local_group(7).members == (5, 6, 7, 8, 9, 11)
    assert lrc.local_group(12) is None
    for sid in range(12):
        assert lrc.min_repair_reads(sid) == 5
    for sid in (12, 13):
        assert lrc.min_repair_reads(sid) == 10
    assert all(get_codec("rs").min_repair_reads(s) == 10
               for s in range(14))


def test_lrc_repair_plan_prefers_local_group():
    lrc = get_codec("lrc")
    plan = lrc.repair_plan(tuple(range(1, 14)), [0])
    assert plan[0].local and set(plan[0].reads) == {1, 2, 3, 4, 10}
    # A global parity loss has no locality group: 10-read re-encode.
    plan = lrc.repair_plan(tuple(range(13)), [13])
    assert not plan[0].local and len(plan[0].reads) == 10
    # Local parity of group B from its data members.
    plan = lrc.repair_plan(tuple(s for s in range(14) if s != 11), [11])
    assert plan[0].local and set(plan[0].reads) == {5, 6, 7, 8, 9}


# -- exhaustive / randomized algebra ----------------------------------------

def test_lrc_survives_every_loss_up_to_tolerance_exhaustively():
    """All C(14,1) + C(14,2) + C(14,3) = 469 erasure patterns decode:
    the 'survives loss of any 2 shards' acceptance criterion with a
    margin (the Cauchy construction is maximally recoverable at 3)."""
    lrc = get_codec("lrc")
    data = RNG.integers(0, 256, (10, 48), dtype=np.uint8)
    shards = _all_shards(lrc, data)
    for k in (1, 2, 3):
        for missing in itertools.combinations(range(14), k):
            _roundtrip(lrc, shards, list(missing))


def test_lrc_structured_four_loss_one_per_group_plus_globals():
    """The acceptance pattern: any 1 loss per local group + BOTH
    global parities (4 losses) still decodes via the local XORs."""
    lrc = get_codec("lrc")
    data = RNG.integers(0, 256, (10, 32), dtype=np.uint8)
    shards = _all_shards(lrc, data)
    for a in (0, 1, 2, 3, 4, 10):
        for b in (5, 6, 7, 8, 9, 11):
            _roundtrip(lrc, shards, [a, b, 12, 13])


def test_lrc_raises_cleanly_past_what_the_code_expresses():
    lrc = get_codec("lrc")
    # 4 data shards of one group exceed the group's 1 local + 2 global
    # equations: undecodable, and the solver says so instead of
    # returning garbage.
    present = tuple(s for s in range(14) if s not in (0, 1, 2, 3))
    with pytest.raises(ValueError, match="unrecoverable"):
        lrc.decode_matrix(present, (0, 1, 2, 3))
    with pytest.raises(ValueError, match="unrecoverable"):
        lrc.repair_plan(present, [0, 1, 2, 3])
    # 3 same-group data + the group's local parity (4 losses).
    present = tuple(s for s in range(14) if s not in (5, 6, 7, 11))
    with pytest.raises(ValueError, match="unrecoverable"):
        lrc.decode_matrix(present, (5, 6, 7, 11))


@pytest.mark.parametrize("name", sorted({"rs", "lrc"}))
def test_every_registered_codec_roundtrips_against_numpy_reference(name):
    """The satellite property test: randomized erasures up to the
    codec's tolerance round-trip through the NumpyCoder reference
    backend, and one past the tolerance either round-trips (patterns
    the code can still express) or raises ValueError — never silent
    corruption."""
    codec = get_codec(name)
    coder = new_coder(backend="numpy", codec=name)
    rng = random.Random(99)
    data = RNG.integers(0, 256, (codec.data_shards, 96), dtype=np.uint8)
    shards = np.asarray(coder.encode_all(data))
    assert coder.verify(shards)
    for _ in range(40):
        k = rng.randint(1, codec.tolerance)
        missing = sorted(rng.sample(range(codec.total_shards), k))
        have = {s: shards[s] for s in range(codec.total_shards)
                if s not in missing}
        rec = coder.reconstruct(have)
        for m in missing:
            assert np.array_equal(np.asarray(rec[m]), shards[m]), \
                (name, missing)
    # One past the tolerance: must decode correctly or raise cleanly.
    for _ in range(40):
        missing = sorted(rng.sample(range(codec.total_shards),
                                    codec.tolerance + 1))
        have = {s: shards[s] for s in range(codec.total_shards)
                if s not in missing}
        try:
            rec = coder.reconstruct(have)
        except ValueError:
            continue
        for m in missing:
            assert np.array_equal(np.asarray(rec[m]), shards[m]), \
                (name, missing)


def test_device_backends_match_numpy_reference_for_lrc():
    """Same bytes out of every backend — the bit-matmul lowering of
    the LRC matrices is semantics-preserving."""
    data = RNG.integers(0, 256, (10, 4096), dtype=np.uint8)
    ref = new_coder(backend="numpy", codec="lrc")
    want = np.asarray(ref.encode_all(data))
    for backend in ("jax", "pallas"):
        coder = new_coder(backend=backend, codec="lrc")
        got = np.asarray(coder.encode_all(data))
        assert np.array_equal(got, want), backend
        have = {s: want[s] for s in range(14) if s not in (4, 9)}
        rec = coder.reconstruct(have)
        assert np.array_equal(np.asarray(rec[4]), want[4])
        assert np.array_equal(np.asarray(rec[9]), want[9])


def test_lrc_bitmatrix_sibling_module():
    """ops/lrc_bitmatrix mirrors rs_bitmatrix's API for the lrc codec."""
    from seaweedfs_tpu.ops import lrc_bitmatrix, rs_bitmatrix
    pb = lrc_bitmatrix.parity_bitmatrix()
    assert pb.shape == (8 * 4, 8 * 10)
    assert np.array_equal(
        pb, rs_bitmatrix.expand_bitmatrix(
            get_codec("lrc").parity_matrix()))
    bmat, used = lrc_bitmatrix.decode_bitmatrix(tuple(range(1, 14)), (0,))
    assert set(used) == {1, 2, 3, 4, 10}
    assert bmat.shape == (8, 8 * 5)


def test_solver_minimal_support_and_rs_equivalence():
    """The generic solver drops survivors the algebra doesn't need and
    reproduces klauspost's subshard selection for MDS codes."""
    rs = rs_codec(10, 4)
    mat, used = solve_decode(np.asarray(rs.matrix), tuple(range(1, 14)),
                             (0,))
    ref_mat, ref_used = gf256.decode_matrix(10, 14, list(range(1, 14)),
                                            wanted=[0])
    assert list(used) == ref_used
    assert np.array_equal(mat, ref_mat)
    # Ad-hoc RS schemes (bench table) still construct.
    for k, m in ((16, 4), (8, 3)):
        c = rs_codec(k, m)
        assert c.total_shards == k + m


# -- file pipeline: encode/rebuild/scrub with the lrc codec -----------------

LARGE, SMALL = 10000, 100  # the reference test's shrunken block sizes


@pytest.fixture(scope="module")
def lrc_base(tmp_path_factory):
    """A real volume with random needles, encoded to LRC shards with
    the numpy reference backend."""
    from seaweedfs_tpu.core.needle import Needle
    from seaweedfs_tpu.ec.encoder import (write_ec_files,
                                          write_sorted_file_from_idx)
    from seaweedfs_tpu.storage.volume import Volume
    tmp = tmp_path_factory.mktemp("lrcvol")
    v = Volume(str(tmp), "", 1)
    rng = random.Random(21)
    payloads = {}
    for i in range(1, 81):
        data = bytes(rng.randrange(256)
                     for _ in range(rng.randrange(1, 700)))
        payloads[i] = data
        n = Needle(cookie=0x1234, id=i, data=data)
        n.append_at_ns = i
        v.write_needle(n)
    v.sync()
    base = v.file_name()
    v.close()
    write_sorted_file_from_idx(base)
    write_ec_files(base, coder=new_coder(backend="numpy", codec="lrc"),
                   large_block_size=LARGE, small_block_size=SMALL,
                   chunk_size=SMALL)
    return base, payloads


def _open_lrc(base, **kw):
    from seaweedfs_tpu.ec.volume import EcVolume
    return EcVolume(base, coder=new_coder(backend="numpy", codec="lrc"),
                    large_block_size=LARGE, small_block_size=SMALL, **kw)


def test_lrc_vif_records_codec(lrc_base):
    base, _ = lrc_base
    from seaweedfs_tpu.ec.volume_info import ec_codec_name
    assert ec_codec_name(base) == "lrc"


def test_lrc_volume_detects_codec_from_vif(lrc_base):
    """EcVolume with no explicit coder picks the lrc matrices from the
    .vif — the end-to-end codec-id thread."""
    from seaweedfs_tpu.ec.volume import EcVolume
    base, payloads = lrc_base
    ev = EcVolume(base, large_block_size=LARGE, small_block_size=SMALL)
    try:
        assert ev.codec.name == "lrc"
        n = ev.read_needle(5)
        assert n.data == payloads[5]
    finally:
        ev.close()


def test_lrc_every_needle_reads_back(lrc_base):
    base, payloads = lrc_base
    ev = _open_lrc(base)
    try:
        for nid, want in payloads.items():
            assert ev.read_needle(nid).data == want
    finally:
        ev.close()


def test_lrc_degraded_read_uses_local_group_reads(lrc_base, tmp_path):
    """Lose one shard per local group: every needle still reads, and
    the reconstruction reads 5 shards per missing interval (asserted
    via SeaweedFS_ec_repair_read_bytes_total{codec="lrc"})."""
    import shutil
    from seaweedfs_tpu.ec import to_ext
    from seaweedfs_tpu.stats.metrics import ec_repair_read_bytes_total
    base, payloads = lrc_base
    dst = str(tmp_path / "v")
    for sid in range(14):
        if sid in (2, 7):
            continue
        shutil.copyfile(base + to_ext(sid), dst + to_ext(sid))
    for ext in (".ecx", ".vif"):
        shutil.copyfile(base + ext, dst + ext)
    ev = _open_lrc(dst)
    try:
        before = ec_repair_read_bytes_total.value(codec="lrc")
        for nid, want in payloads.items():
            assert ev.read_needle(nid).data == want
        read = ec_repair_read_bytes_total.value(codec="lrc") - before
        # Each interval on a lost shard reconstructs from EXACTLY its
        # 5-shard locality group; RS(10,4) would read 10 interval
        # copies.  Predict the byte count from the layout math and
        # require equality — the provably-fewer-reads acceptance.
        expected = 0
        for nid in payloads:
            _off, _size, intervals = ev.locate_needle(nid)
            for iv in intervals:
                sid, _o = iv.to_shard_id_and_offset(LARGE, SMALL)
                if sid in (2, 7):
                    expected += 5 * iv.size
        assert expected > 0 and read == expected
    finally:
        ev.close()


def test_lrc_rebuild_reads_local_group_and_is_byte_identical(
        lrc_base, tmp_path):
    """rebuild_ec_files on an lrc volume: the missing in-group shard
    is regenerated byte-identically while reading only its 5-shard
    local group (satellite: codec-derived shard counts + planner)."""
    import shutil
    from seaweedfs_tpu.ec import to_ext
    from seaweedfs_tpu.ec.encoder import rebuild_ec_files
    from seaweedfs_tpu.stats.metrics import ec_repair_read_bytes_total
    base, _ = lrc_base
    dst = str(tmp_path / "v")
    for sid in range(14):
        if sid == 8:
            continue
        shutil.copyfile(base + to_ext(sid), dst + to_ext(sid))
    for ext in (".ecx", ".vif"):
        shutil.copyfile(base + ext, dst + ext)
    shard_size = os.path.getsize(base + to_ext(0))
    before = ec_repair_read_bytes_total.value(codec="lrc")
    # No coder passed: codec comes from the .vif.
    rebuilt = rebuild_ec_files(dst, coder=new_coder(backend="numpy",
                                                    codec="lrc"))
    read = ec_repair_read_bytes_total.value(codec="lrc") - before
    assert rebuilt == [8]
    assert read == 5 * shard_size  # local group, not 10 survivors
    with open(base + to_ext(8), "rb") as a, \
            open(dst + to_ext(8), "rb") as b:
        assert a.read() == b.read()


def test_lrc_rebuild_updates_ecc_sidecar_for_scrub(lrc_base, tmp_path):
    """Scrub/.ecc satellite: the sidecar written for lrc volumes has
    one CRC list per codec shard (not an RS-shaped 14 by accident but
    derived), survives a rebuild, and the scrub verifier finds zero
    corruption on clean shards + flags a real flip."""
    import shutil
    from seaweedfs_tpu.ec import to_ext
    from seaweedfs_tpu.ec.integrity import ShardChecksums
    base, _ = lrc_base
    codec = get_codec("lrc")
    ecc = ShardChecksums.load(base)
    assert sorted(ecc.shards) == list(range(codec.total_shards))
    for sid in range(codec.total_shards):
        assert ecc.verify_file(sid, base + to_ext(sid)) == []
    # Flip a byte in a parity shard copy: scrub math flags exactly it.
    dst = str(tmp_path / "v")
    for sid in range(14):
        shutil.copyfile(base + to_ext(sid), dst + to_ext(sid))
    for ext in (".ecx", ".vif", ".ecc"):
        shutil.copyfile(base + ext, dst + ext)
    with open(dst + to_ext(11), "r+b") as f:
        f.seek(3)
        b = f.read(1)
        f.seek(3)
        f.write(bytes([b[0] ^ 0xFF]))
    ecc2 = ShardChecksums.load(dst)
    assert ecc2.verify_file(11, dst + to_ext(11)) == [0]
    assert ecc2.verify_file(10, dst + to_ext(10)) == []


def test_rebuild_plan_is_codec_aware_for_mixed_clusters():
    """The satellite fix: plan_rebuilds derives shard counts from each
    volume's codec, so a mixed-codec cluster can't mis-plan."""
    from seaweedfs_tpu.parallel.cluster_rebuild import (plan_rebuilds,
                                                       plan_repair_reads)

    class Env:
        def __init__(self):
            self.codecs = {1: "rs", 2: "lrc"}
            self.locs = {
                1: {s: ["h1:80"] for s in range(14) if s != 3},
                2: {s: ["h2:80"] for s in range(14) if s != 3},
            }

        def data_nodes(self):
            return [{"url": "h:80", "ec_shards": [
                {"id": vid, "shard_bits": 0} for vid in self.locs]}]

        def ec_shard_locations(self, vid):
            return self.locs[vid]

        def ec_codec(self, vid):
            return self.codecs[vid]

    plan = plan_rebuilds(Env())
    assert len(plan.groups) == 2 and not plan.skipped
    keys = sorted(plan.groups)
    # Same survivor signature, different codec -> separate groups.
    assert [k[0] for k in keys] == ["lrc", "rs"]
    rs_report = plan_repair_reads(get_codec("rs"), keys[1][1], [3])
    lrc_report = plan_repair_reads(get_codec("lrc"), keys[0][1], [3])
    assert rs_report["planned_read_shards"] == 10
    assert lrc_report["planned_read_shards"] == 5
    assert lrc_report["local_repairs"] == 1
    assert lrc_report["rs_read_shards"] == 10


# -- cluster acceptance: -codec lrc end to end ------------------------------


@pytest.fixture
def cluster(tmp_path):
    from seaweedfs_tpu.cluster.master import MasterServer
    from seaweedfs_tpu.cluster.volume_server import VolumeServer
    master = MasterServer(volume_size_limit_mb=64, meta_dir=str(tmp_path),
                          pulse_seconds=60)
    master.start()
    servers = []
    for i in range(3):
        d = tmp_path / f"vs{i}"
        d.mkdir()
        vs = VolumeServer(master.url(), [str(d)], pulse_seconds=60)
        vs.start()
        servers.append(vs)
    yield master, servers
    for vs in servers:
        vs.stop()
    master.stop()


def _freshen(servers):
    for vs in servers:
        vs._send_heartbeat(full=True)
        vs._ec_loc_cache.clear()


def test_cluster_lrc_acceptance(cluster):
    """ISSUE acceptance: a cluster volume encoded with `ec.encode
    -codec lrc` survives loss of any 2 shards (and 1 per local group +
    both globals), and a single-shard rebuild provably reads <= 6
    shards — asserted via SeaweedFS_ec_repair_read_bytes_total and the
    planner report — vs 10 for RS."""
    from seaweedfs_tpu.cluster import rpc
    from seaweedfs_tpu.cluster.client import WeedClient
    from seaweedfs_tpu.ec import to_ext
    from seaweedfs_tpu.shell import CommandEnv, run_command
    from seaweedfs_tpu.stats.metrics import ec_repair_read_bytes_total
    master, servers = cluster
    client = WeedClient(master.url())
    pairs = [(f"lrc-payload-{i}".encode(),
              client.upload_data(f"lrc-payload-{i}".encode()))
             for i in range(24)]
    vid = int(pairs[0][1].split(",")[0])
    pairs = [(p, f) for p, f in pairs if int(f.split(",")[0]) == vid]
    _freshen(servers)
    env = CommandEnv(master.url())
    try:
        run_command(env, "lock")
        out = run_command(env, f"ec.encode -volumeId {vid} -codec lrc")
        assert f"volume {vid}" in out
        _freshen(servers)
        # Codec id is threaded end to end: .vif -> heartbeat ->
        # master lookup -> shell view.
        assert env.ec_codec(vid) == "lrc"
        assert sorted(env.ec_shard_locations(vid)) == list(range(14))
        for vs in servers:
            assert vs.store.find_volume(vid) is None
        for payload, fid in pairs[:3]:
            assert bytes(rpc.call(
                f"http://{servers[0].url()}/{fid}")) == payload

        def holders_of(sid):
            return env.ec_shard_locations(vid)[sid]

        def drop(shards):
            for sid in shards:
                for url in holders_of(sid):
                    rpc.call_json(f"http://{url}/admin/ec/delete_shards",
                                  "POST", {"volume": vid,
                                           "shards": [sid]})
            _freshen(servers)

        def heal():
            out = run_command(env, f"ec.rebuild -volumeId {vid} -batch")
            _freshen(servers)
            assert sorted(env.ec_shard_locations(vid)) == \
                list(range(14))
            return out

        # Loss of 2 shards (one per group): every payload still reads.
        drop([1, 6])
        for payload, fid in pairs:
            assert bytes(rpc.call(
                f"http://{servers[1].url()}/{fid}")) == payload
        heal()

        # Structured 4-loss: 1 per local group + BOTH globals.
        drop([4, 9, 12, 13])
        for payload, fid in pairs[:5]:
            assert bytes(rpc.call(
                f"http://{servers[2].url()}/{fid}")) == payload
        heal()

        # Single-shard rebuild provably reads <= 6 shards (5 actual).
        url0 = holders_of(3)[0]
        shard_size = os.path.getsize(os.path.join(
            next(loc.directory for vs in servers
                 if vs.url() == url0 for loc in vs.store.locations),
            f"{vid}{to_ext(3)}"))
        drop([3])
        before = ec_repair_read_bytes_total.value(codec="lrc")
        out = heal()
        read = ec_repair_read_bytes_total.value(codec="lrc") - before
        assert "read 5 shards vs 10 for RS" in out
        assert read == 5 * shard_size <= 6 * shard_size
        # The repair-bandwidth counter is on the volume server scrape.
        scrape = bytes(rpc.call(
            f"http://{servers[0].url()}/metrics")).decode()
        assert "SeaweedFS_ec_repair_read_bytes_total" in scrape
        # ... and an RS volume in the same cluster reads 10.
        for payload, fid in pairs:
            assert bytes(rpc.call(
                f"http://{servers[0].url()}/{fid}")) == payload
    finally:
        env.close()


def test_cluster_rs_volumes_untouched_beside_lrc(cluster):
    """Acceptance guard: existing RS volumes still encode, report
    codec rs, and rebuild with the classic 10-survivor read set."""
    from seaweedfs_tpu.cluster import rpc
    from seaweedfs_tpu.cluster.client import WeedClient
    from seaweedfs_tpu.shell import CommandEnv, run_command
    master, servers = cluster
    client = WeedClient(master.url())
    fid = client.upload_data(b"rs-control-payload")
    vid = int(fid.split(",")[0])
    _freshen(servers)
    env = CommandEnv(master.url())
    try:
        run_command(env, "lock")
        run_command(env, f"ec.encode -volumeId {vid}")
        _freshen(servers)
        assert env.ec_codec(vid) == "rs"
        assert sorted(env.ec_shard_locations(vid)) == list(range(14))
        sid, urls = next(iter(env.ec_shard_locations(vid).items()))
        for url in urls:
            rpc.call_json(f"http://{url}/admin/ec/delete_shards",
                          "POST", {"volume": vid, "shards": [sid]})
        _freshen(servers)
        out = run_command(env, f"ec.rebuild -volumeId {vid} -batch")
        assert "rebuilt" in out and "vs 10 for RS" not in out
        _freshen(servers)
        assert sorted(env.ec_shard_locations(vid)) == list(range(14))
        assert bytes(rpc.call(
            f"http://{servers[0].url()}/{fid}")) == b"rs-control-payload"
    finally:
        env.close()


def test_codec_lookup_failure_skips_volume_instead_of_guessing_rs():
    """A transient master failure while resolving a volume's codec must
    SKIP the volume, never plan it as rs — decoding LRC shards with RS
    matrices would scatter corrupt bytes cluster-wide."""
    from seaweedfs_tpu.parallel.cluster_rebuild import plan_rebuilds

    class Env:
        def data_nodes(self):
            # /vol/list payload without codec ids (stale master).
            return [{"url": "h:80",
                     "ec_shards": [{"id": 5, "shard_bits": 0}]}]

        def ec_shard_locations(self, vid):
            return {s: ["h:80"] for s in range(13)}

        def ec_codec(self, vid):
            raise ConnectionError("master lookup 503")

    plan = plan_rebuilds(Env())
    assert not plan.groups
    assert plan.skipped and "cannot determine codec" in plan.skipped[0][1]


def test_plan_rebuilds_reads_codec_from_vol_list_payload():
    """The /vol/list ec_shards entries carry the codec: planning does
    not fall back to per-volume lookups when the payload has it."""
    from seaweedfs_tpu.parallel.cluster_rebuild import plan_rebuilds

    class Env:
        def data_nodes(self):
            return [{"url": "h:80", "ec_shards": [
                {"id": 5, "shard_bits": 0, "codec": "lrc"}]}]

        def ec_shard_locations(self, vid):
            return {s: ["h:80"] for s in range(13)}

        def ec_codec(self, vid):
            raise AssertionError("per-volume lookup should not run")

    plan = plan_rebuilds(Env())
    assert list(plan.groups) == [("lrc", tuple(range(13)), (13,))]


def test_unrecoverable_pattern_is_skipped_not_misplanned():
    from seaweedfs_tpu.parallel.cluster_rebuild import plan_rebuilds

    class Env:
        def data_nodes(self):
            return [{"url": "h:80",
                     "ec_shards": [{"id": 9, "shard_bits": 0}]}]

        def ec_shard_locations(self, vid):
            return {s: ["h:80"] for s in range(14)
                    if s not in (0, 1, 2, 3)}

        def ec_codec(self, vid):
            return "lrc"

    plan = plan_rebuilds(Env())
    assert not plan.groups
    assert plan.skipped and "unrecoverable" in plan.skipped[0][1]
