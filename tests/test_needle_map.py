"""Direct tests for the needle-map implementations.

Mirrors the reference's compact-map unit + perf tests
(weed/storage/needle_map/compact_map_test.go, compact_map_perf_test.go)
and the sorted-file mapper (weed/storage/needle_map_sorted_file.go):
put-path merges across the overflow boundary, tombstone shadowing,
load-time dedup, bounded-memory bulk load, sorted-file staleness
regeneration, and thread-safety of the mutating paths."""

import os
import threading

import numpy as np
import pytest

from seaweedfs_tpu.core import idx as idx_mod
from seaweedfs_tpu.core import types as t
from seaweedfs_tpu.storage.needle_map import (
    CompactNeedleMap,
    MemoryNeedleMap,
    SortedFileNeedleMap,
    new_needle_map,
)


def _write_idx(path, entries):
    """entries: list of (key, actual_offset, size); size=-1 tombstone."""
    with open(path, "wb") as f:
        for key, off, size in entries:
            idx_mod.append_entry(f, key, off, size)


# -- CompactNeedleMap --------------------------------------------------------


def test_compact_put_get_delete(tmp_path):
    p = str(tmp_path / "1.idx")
    open(p, "wb").close()
    nm = CompactNeedleMap.load(p)
    nm.put(7, 8, 100)
    nm.put(3, 16, 200)
    assert nm.get(7) == (8, 100)
    assert nm.get(3) == (16, 200)
    assert nm.get(99) is None
    assert len(nm) == 2
    freed = nm.delete(7)
    assert freed == 100
    assert nm.get(7) is None
    assert len(nm) == 1
    assert nm.delete(7) == 0  # double delete is a no-op
    nm.close()


def test_compact_put_path_merge_boundary(tmp_path, monkeypatch):
    """Crossing OVERFLOW_MERGE on the put path folds the overflow into
    the sorted base arrays; lookups and counters must be unchanged."""
    monkeypatch.setattr(CompactNeedleMap, "OVERFLOW_MERGE", 32)
    p = str(tmp_path / "1.idx")
    open(p, "wb").close()
    nm = CompactNeedleMap.load(p)
    n = 3 * 32 + 7  # several merges plus a live overflow tail
    for k in range(n):
        nm.put(k * 13 % n, (k + 1) * 8, 10 + k)
    # after ≥1 merge the base arrays are populated and sorted
    assert len(nm._keys) > 0
    assert np.all(np.diff(nm._keys.astype(np.uint64)) > 0)
    for k in range(n):
        got = nm.get(k * 13 % n)
        assert got is not None
    assert len(nm) == n
    nm.close()


def test_compact_merge_tombstone_shadowing(tmp_path, monkeypatch):
    """A tombstone living in the overflow must shadow the base entry,
    and survive a merge as an absent key."""
    monkeypatch.setattr(CompactNeedleMap, "OVERFLOW_MERGE", 16)
    p = str(tmp_path / "1.idx")
    open(p, "wb").close()
    nm = CompactNeedleMap.load(p)
    for k in range(16):  # fills overflow to the boundary -> merge
        nm.put(k, (k + 1) * 8, 100)
    assert len(nm._overflow) == 0  # merged into base
    nm.delete(5)  # tombstone in overflow shadows base
    assert nm.get(5) is None
    assert 5 not in nm
    # force the tombstone through a merge
    for k in range(100, 100 + 16):
        nm.put(k, (k + 1) * 8, 100)
    nm.ordered_offsets()  # flushes any overflow remainder via _merge
    assert len(nm._overflow) == 0
    assert nm.get(5) is None
    assert nm.get(4) == (5 * 8, 100)
    assert len(nm) == 16 - 1 + 16
    nm.close()


def test_compact_overwrite_counts_deletion(tmp_path):
    p = str(tmp_path / "1.idx")
    open(p, "wb").close()
    nm = CompactNeedleMap.load(p)
    nm.put(1, 8, 100)
    nm.put(1, 16, 150)  # overwrite: old bytes become garbage
    assert nm.get(1) == (16, 150)
    assert nm.metrics.deletion_count == 1
    assert nm.metrics.deletion_byte_count == 100
    assert nm.metrics.file_byte_count == 250
    assert len(nm) == 1
    nm.close()


def test_compact_load_dedup_and_tombstones(tmp_path):
    """Vectorized load: last occurrence per key wins; dead keys absent;
    counters match a per-entry replay (MemoryNeedleMap is the oracle)."""
    p = str(tmp_path / "1.idx")
    entries = [
        (1, 8, 100),
        (2, 16, 200),
        (1, 24, 110),     # overwrite of 1
        (3, 32, 300),
        (2, 0, t.TOMBSTONE_FILE_SIZE),  # delete 2
        (4, 40, 400),
        (4, 48, 410),     # overwrite of 4
        (9, 0, t.TOMBSTONE_FILE_SIZE),  # delete of never-written key
    ]
    _write_idx(p, entries)
    nm = CompactNeedleMap.load(p)
    oracle = MemoryNeedleMap.load(p)
    assert nm.get(1) == (24, 110)
    assert nm.get(2) is None
    assert nm.get(3) == (32, 300)
    assert nm.get(4) == (48, 410)
    assert len(nm) == len(oracle) == 3
    assert nm.metrics.file_byte_count == oracle.metrics.file_byte_count
    assert nm.metrics.maximum_file_key == 9
    nm.close()
    oracle.close()


def test_compact_bulk_load_bounded_memory(tmp_path):
    """Load a 1M-entry synthetic idx; resident index bytes must stay at
    ~16B/entry — the .idx's own density — not dict-of-tuples (~100B+).
    Mirrors compact_map_perf_test.go's loadNewNeedleMap bound."""
    n = 1_000_000
    keys = np.arange(1, n + 1, dtype=">u8")
    offs = np.arange(1, n + 1, dtype=">u4")
    sizes = np.full(n, 100, dtype=">i4")
    rec = np.empty(n, dtype=[("k", ">u8"), ("o", ">u4"), ("s", ">i4")])
    rec["k"], rec["o"], rec["s"] = keys, offs, sizes
    p = str(tmp_path / "big.idx")
    with open(p, "wb") as f:
        f.write(rec.tobytes())
    nm = CompactNeedleMap.load(p)
    assert len(nm) == n
    assert nm.metrics.file_count == n
    # 16 bytes/entry exactly (u64 + u32 + i32 columns)
    assert nm.index_memory_bytes() == n * 16
    # spot lookups
    assert nm.get(1) == (8, 100)
    assert nm.get(n) == (n * 8, 100)
    assert nm.get(n + 1) is None
    nm.close()


def test_compact_ordered_offsets_and_visit(tmp_path, monkeypatch):
    monkeypatch.setattr(CompactNeedleMap, "OVERFLOW_MERGE", 8)
    p = str(tmp_path / "1.idx")
    open(p, "wb").close()
    nm = CompactNeedleMap.load(p)
    for k in (5, 1, 9, 3):
        nm.put(k, k * 16, 50)
    nm.delete(9)
    offs = list(nm.ordered_offsets())
    assert offs == sorted(k * 16 for k in (5, 1, 3))
    seen = []
    nm.ascending_visit(lambda e: seen.append((e.key, e.offset, e.size)))
    assert [k for k, _, _ in seen] == [1, 3, 5]
    nm.close()


def test_compact_concurrent_mutation_and_reads(tmp_path, monkeypatch):
    """Writer + readers + tail-path merges racing (ADVICE r2 high): no
    torn reads, no lost entries.  The dict map was GIL-atomic; the
    sorted-array map must be lock-correct instead."""
    monkeypatch.setattr(CompactNeedleMap, "OVERFLOW_MERGE", 64)
    p = str(tmp_path / "1.idx")
    open(p, "wb").close()
    nm = CompactNeedleMap.load(p)
    n = 4000
    errors = []

    def writer():
        try:
            for k in range(1, n + 1):
                nm.put(k, k * 8, 100)
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    def reader():
        try:
            for _ in range(300):
                k = 1 + (os.getpid() * 2654435761) % n
                got = nm.get(k)
                if got is not None:
                    assert got == (k * 8, 100)
                nm.ordered_offsets()  # tail path: merges under lock
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    ths = [threading.Thread(target=writer)] + \
        [threading.Thread(target=reader) for _ in range(3)]
    for th in ths:
        th.start()
    for th in ths:
        th.join()
    assert not errors
    assert len(nm) == n
    for k in (1, n // 2, n):
        assert nm.get(k) == (k * 8, 100)
    nm.close()


# -- SortedFileNeedleMap -----------------------------------------------------


def test_sorted_file_hit_miss_deleted(tmp_path):
    p = str(tmp_path / "2.idx")
    _write_idx(p, [
        (10, 8, 100),
        (20, 16, 200),
        (30, 24, 300),
        (20, 0, t.TOMBSTONE_FILE_SIZE),  # delete 20
    ])
    nm = SortedFileNeedleMap.load(p)
    assert nm.get(10) == (8, 100)
    assert nm.get(30) == (24, 300)
    assert nm.get(20) is None      # deleted
    assert nm.get(15) is None      # miss between keys
    assert nm.get(5) is None       # miss below range
    assert nm.get(99) is None      # miss above range
    assert len(nm) == 2
    with pytest.raises(RuntimeError):
        nm.put(1, 8, 1)
    with pytest.raises(RuntimeError):
        nm.delete(10)
    nm.close()


def test_sorted_file_generate_is_numpy_not_dict(tmp_path):
    """generate() must not materialize a Python dict (VERDICT r2 weak 4);
    verify output equals the dict-oracle bytes on a dup/tombstone mix."""
    p = str(tmp_path / "3.idx")
    entries = [(k, k * 8, 50 + k) for k in range(1000, 0, -1)]
    entries += [(k, 0, t.TOMBSTONE_FILE_SIZE) for k in range(1, 1000, 7)]
    entries += [(k, k * 16, 500) for k in range(1, 1000, 13)]
    _write_idx(p, entries)
    sdx = str(tmp_path / "3.sdx")
    SortedFileNeedleMap.generate(p, sdx)
    from seaweedfs_tpu.storage.needle_map import MemDb
    with open(p, "rb") as f:
        oracle = MemDb.from_idx(f).to_sorted_bytes()
    with open(sdx, "rb") as f:
        assert f.read() == oracle


def test_sorted_file_regeneration_on_append(tmp_path):
    """An append to the .idx — even within mtime granularity — must
    trigger .sdx regeneration (ADVICE r2 low: size-based staleness)."""
    p = str(tmp_path / "4.idx")
    _write_idx(p, [(1, 8, 100)])
    nm = SortedFileNeedleMap.load(p)
    assert nm.get(2) is None
    nm.close()
    sdx = p[:-4] + ".sdx"
    mtime = os.path.getmtime(sdx)
    # append without letting mtime advance past the sdx's
    with open(p, "ab") as f:
        idx_mod.append_entry(f, 2, 16, 200)
    os.utime(p, (mtime, mtime))
    os.utime(sdx, (mtime, mtime))
    nm2 = SortedFileNeedleMap.load(p)
    assert nm2.get(2) == (16, 200)  # stale sdx would miss this
    assert nm2.get(1) == (8, 100)
    nm2.close()


def test_sorted_file_no_regeneration_when_fresh(tmp_path):
    p = str(tmp_path / "5.idx")
    _write_idx(p, [(1, 8, 100)])
    nm = SortedFileNeedleMap.load(p)
    nm.close()
    sdx = p[:-4] + ".sdx"
    ino = os.stat(sdx).st_ino
    nm2 = SortedFileNeedleMap.load(p)
    nm2.close()
    assert os.stat(sdx).st_ino == ino  # not rewritten


# -- selection ---------------------------------------------------------------


@pytest.mark.parametrize("kind", ["compact", "memory", "sorted_file"])
def test_new_needle_map_kinds(tmp_path, kind):
    p = str(tmp_path / "k.idx")
    _write_idx(p, [(1, 8, 100), (2, 16, 200),
                   (1, 0, t.TOMBSTONE_FILE_SIZE)])
    nm = new_needle_map(kind, p)
    assert nm.get(1) is None
    assert nm.get(2) == (16, 200)
    assert len(nm) == 1
    nm.close()
