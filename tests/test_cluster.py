"""In-process cluster integration tests: master + N volume servers + client.

The reference tests distribution logic with in-process fakes; its servers
are all just structs (SURVEY §4) — same here: real HTTP servers on
localhost ports, one process.
"""

import time

import pytest

from seaweedfs_tpu.cluster import rpc
from seaweedfs_tpu.cluster.client import WeedClient
from seaweedfs_tpu.cluster.master import MasterServer
from seaweedfs_tpu.cluster.volume_server import VolumeServer


@pytest.fixture
def cluster(tmp_path):
    master = MasterServer(volume_size_limit_mb=64,
                          meta_dir=str(tmp_path))
    master.start()
    servers = []
    for i in range(3):
        d = tmp_path / f"vs{i}"
        d.mkdir()
        vs = VolumeServer(master.url(), [str(d)],
                          rack=f"rack{i % 2}", pulse_seconds=60)
        vs.start()
        servers.append(vs)
    yield master, servers
    for vs in servers:
        vs.stop()
    master.stop()


def test_upload_download_delete(cluster):
    master, servers = cluster
    client = WeedClient(master.url())
    fid = client.upload_data(b"hello cluster", name="greeting.txt")
    assert "," in fid
    assert client.download(fid) == b"hello cluster"
    client.delete(fid)
    with pytest.raises(rpc.RpcError) as ei:
        client.download(fid)
    assert ei.value.status == 404


def test_many_uploads_spread(cluster):
    master, servers = cluster
    client = WeedClient(master.url())
    fids = [client.upload_data(f"obj-{i}".encode()) for i in range(50)]
    assert len({f.split(",")[0] for f in fids}) > 1  # multiple volumes
    for i, fid in enumerate(fids):
        assert client.download(fid) == f"obj-{i}".encode()


def test_replicated_write_and_failover(cluster):
    master, servers = cluster
    client = WeedClient(master.url())
    fid = client.upload_data(b"replicated!", replication="001")
    vid = int(fid.split(",")[0])
    # Both replicas must hold the bytes.
    locs = client.lookup(vid)
    assert len(locs) == 2
    for loc in locs:
        out = rpc.call(f"http://{loc['url']}/{fid}")
        assert bytes(out) == b"replicated!"
    # Kill the first replica server; read must fail over to the other.
    victim_url = locs[0]["url"]
    victim = next(vs for vs in servers if vs.url() == victim_url)
    victim.stop()
    client.cache._m.clear()
    # master may still list the dead node; client retries the live one.
    assert client.download(fid) == b"replicated!"


def test_lookup_unknown_volume(cluster):
    master, _ = cluster
    with pytest.raises(rpc.RpcError) as ei:
        rpc.call(f"{master.url()}/dir/lookup?volumeId=999")
    assert ei.value.status == 404


def test_heartbeat_registers_topology(cluster):
    master, servers = cluster
    status = rpc.call(f"{master.url()}/dir/status")
    topo = status["topology"]
    dc = topo["children"][0]
    racks = {r["id"] for r in dc["children"]}
    assert racks == {"rack0", "rack1"}
    nodes = sum(len(r["children"]) for r in dc["children"])
    assert nodes == 3


def test_vacuum_via_master(cluster):
    master, servers = cluster
    client = WeedClient(master.url())
    fids = [client.upload_data(b"x" * 2000) for _ in range(30)]
    for fid in fids[:20]:
        client.delete(fid)
    out = rpc.call_json(f"{master.url()}/vol/vacuum?garbageThreshold=0.1",
                        "POST", {})
    assert out["vacuumed"]
    for fid in fids[20:]:
        assert client.download(fid) == b"x" * 2000


def test_collection_lifecycle(cluster):
    master, servers = cluster
    client = WeedClient(master.url())
    fid = client.upload_data(b"in-collection", collection="photos")
    assert client.download(fid) == b"in-collection"
    cols = rpc.call(f"{master.url()}/col/list")
    assert "photos" in cols["collections"]
    rpc.call_json(f"{master.url()}/col/delete?collection=photos", "POST", {})
    cols = rpc.call(f"{master.url()}/col/list")
    assert "photos" not in cols["collections"]


def test_ec_lifecycle_over_cluster(cluster, tmp_path):
    """ec.encode equivalent: generate shards, spread them, mount, read back
    through the EC path, survive shard deletion."""
    master, servers = cluster
    client = WeedClient(master.url())
    # Fill one volume on a known server.
    fid = client.upload_data(b"ec-payload-0")
    vid = int(fid.split(",")[0])
    fids = [fid] + [client.upload_data(f"ec-payload-{i}".encode())
                    for i in range(1, 20)]
    fids = [f for f in fids if int(f.split(",")[0]) == vid]
    src = client.lookup(vid)[0]["url"]
    src_vs = next(vs for vs in servers if vs.url() == src)

    # 1. generate shards on the source
    rpc.call_json(f"http://{src}/admin/ec/generate", "POST",
                  {"volume": vid})
    # 2. spread a few shards to another server
    dst_vs = next(vs for vs in servers if vs.url() != src)
    rpc.call_json(f"http://{dst_vs.url()}/admin/ec/copy_shard", "POST",
                  {"volume": vid, "source": src,
                   "shards": [10, 11, 12, 13], "copy_ecx": True})
    # 3. mount on both
    rpc.call_json(f"http://{src}/admin/ec/mount", "POST", {"volume": vid})
    out = rpc.call_json(f"http://{dst_vs.url()}/admin/ec/mount", "POST",
                        {"volume": vid})
    assert out["shards"] == [10, 11, 12, 13]
    # 4. delete the original volume; reads must go through EC shards now
    rpc.call_json(f"http://{src}/admin/delete_volume", "POST",
                  {"volume": vid})
    for i, f in enumerate(fids):
        data = rpc.call(f"http://{src}/{f}")
        assert bytes(data) == b"ec-payload-0" if i == 0 else True
    # 5. source loses data shards 0-3 -> degraded reads via local survivors
    rpc.call_json(f"http://{src}/admin/ec/delete_shards", "POST",
                  {"volume": vid, "shards": [10, 11, 12, 13]})
    data = rpc.call(f"http://{src}/{fids[0]}")
    assert bytes(data) == b"ec-payload-0"
    # 6. master learned the shard layout via heartbeats
    lookup = rpc.call(f"{master.url()}/dir/lookup?volumeId={vid}")
    assert "ecShards" in lookup


def test_replicated_write_fails_when_sibling_down(cluster):
    """All-or-fail fan-out (store_replicate.go): a write to a
    replicated volume must ERROR when a sibling replica is down, so
    the client knows the copy count wasn't met — never a silent
    under-replication."""
    master, servers = cluster
    client = WeedClient(master.url())
    fid = client.upload_data(b"seed", replication="001")
    vid = int(fid.split(",")[0])
    locs = client.lookup(vid)
    assert len(locs) == 2
    victim = next(vs for vs in servers if vs.url() == locs[1]["url"])
    victim.stop()
    # direct POST to the surviving holder on the same volume
    survivor = locs[0]["url"]
    key = 0x7777
    with pytest.raises(rpc.RpcError) as ei:
        rpc.call(f"http://{survivor}/{vid},{key:x}00000001",
                 "POST", b"must not half-land")
    assert ei.value.status == 500
    assert "replication failed" in ei.value.message
