"""Master admin-script cron (reference weed/server/master_server.go:187-263
startAdminScripts): maintenance shell commands run unattended on the
leader, wrapped in lock/unlock."""

import time

import pytest

from seaweedfs_tpu.cluster import rpc
from seaweedfs_tpu.cluster.client import WeedClient
from seaweedfs_tpu.cluster.master import MasterServer
from seaweedfs_tpu.cluster.volume_server import VolumeServer


@pytest.fixture
def cluster(tmp_path):
    master = MasterServer(
        volume_size_limit_mb=64, meta_dir=str(tmp_path),
        admin_scripts="volume.list\nec.encode -volumeId={vid}",
        admin_script_interval=3600)  # fired manually in tests
    master.start()
    vs = VolumeServer(master.url(), [str(tmp_path / "vs")],
                      pulse_seconds=60)
    vs.start()
    yield master, vs
    vs.stop()
    master.stop()


def test_cron_round_runs_scripts_with_lock(cluster):
    master, vs = cluster
    client = WeedClient(master.url())
    fid = client.upload_data(b"cron-me")
    vid = int(fid.split(",")[0])
    master.admin_scripts = [
        "volume.list", f"ec.encode -volumeId={vid} -force"]
    runs = master.run_admin_scripts()
    lines = [line for _ts, line, _ok, _out in runs]
    assert lines[0] == "lock" and lines[-1] == "unlock"
    assert all(ok for _ts, line, ok, out in runs), runs
    # The EC encode actually happened: shards exist, needle still reads.
    vs._send_heartbeat(full=True)
    locs = rpc.call(f"{master.url()}/dir/lookup?volumeId={vid}")
    assert len(locs.get("ecShards", {})) == 14
    assert bytes(client.download(fid)) == b"cron-me"
    assert master.admin_script_runs  # history recorded


def test_cron_records_failures_and_continues(cluster):
    master, _vs = cluster
    master.admin_scripts = ["definitely.not.a.command", "volume.list"]
    runs = master.run_admin_scripts()
    by_line = {line: ok for _ts, line, ok, _out in runs}
    assert by_line["definitely.not.a.command"] is False
    assert by_line["volume.list"] is True  # later scripts still ran


def test_cron_aborts_round_when_lock_held(cluster):
    """An operator holding the exclusive lease must stop the whole
    round — running maintenance concurrently with their session is the
    race the lock exists to prevent (review finding)."""
    from seaweedfs_tpu.shell import CommandEnv, run_command
    master, _vs = cluster
    operator = CommandEnv(master.url())
    run_command(operator, "lock")
    try:
        master.admin_scripts = ["volume.list"]
        runs = master.run_admin_scripts()
        lines = [line for _ts, line, _ok, _out in runs]
        assert lines == ["lock"]  # aborted before any script
        assert runs[0][2] is False
    finally:
        run_command(operator, "unlock")
        operator.close()
    # With the lease released the next round goes through.
    runs = master.run_admin_scripts()
    assert [line for _ts, line, ok, _out in runs if ok][:2] == \
        ["lock", "volume.list"]


def test_cron_thread_fires_on_interval(tmp_path):
    master = MasterServer(
        volume_size_limit_mb=64, meta_dir=str(tmp_path / "m2"),
        admin_scripts="volume.list", admin_script_interval=0.2)
    master.start()
    try:
        deadline = time.time() + 10
        while time.time() < deadline and not master.admin_script_runs:
            time.sleep(0.1)
        assert master.admin_script_runs, "cron never fired"
        assert any(line == "volume.list" and ok
                   for _ts, line, ok, _out in master.admin_script_runs)
    finally:
        master.stop()
