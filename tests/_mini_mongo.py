"""In-process mini MongoDB server: OP_MSG framing + the command subset
the filer store uses (update/find/delete/createIndexes) over the
store's own BSON codec — the mini-RESP pattern for the mongo wire."""

from __future__ import annotations

import socket
import struct
import threading

from seaweedfs_tpu.filer.mongo_store import OP_MSG, bson_decode, bson_encode

_HDR = struct.Struct("<iiii")


class MiniMongo:
    def __init__(self):
        # (db, collection) -> list of docs {directory, name, meta}
        self.collections: dict[tuple, list[dict]] = {}
        self.lock = threading.Lock()
        self.commands_seen: list[dict] = []
        self._sock = socket.create_server(("127.0.0.1", 0))
        self.port = self._sock.getsockname()[1]
        self._running = True
        threading.Thread(target=self._accept, daemon=True).start()

    def _accept(self):
        while self._running:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _recv_exact(self, conn, n):
        out = bytearray()
        while len(out) < n:
            piece = conn.recv(n - len(out))
            if not piece:
                return None
            out += piece
        return bytes(out)

    def _serve(self, conn):
        try:
            while True:
                hdr = self._recv_exact(conn, 16)
                if hdr is None:
                    return
                length, rid, _rto, opcode = _HDR.unpack(hdr)
                payload = self._recv_exact(conn, length - 16)
                if payload is None or opcode != OP_MSG:
                    return
                doc, _ = bson_decode(payload, 5)
                with self.lock:
                    self.commands_seen.append(doc)
                    reply = self._run(doc)
                body = b"\x00\x00\x00\x00" + b"\x00" + bson_encode(reply)
                conn.sendall(_HDR.pack(16 + len(body), 1, rid, OP_MSG)
                             + body)
        except OSError:
            pass
        finally:
            conn.close()

    def _coll(self, doc, cmd) -> list[dict]:
        return self.collections.setdefault((doc["$db"], doc[cmd]), [])

    @staticmethod
    def _matches(d: dict, q: dict) -> bool:
        for k, cond in q.items():
            if isinstance(cond, dict):
                got = d.get(k, "")
                for op, val in cond.items():
                    if op == "$gt" and not got > val:
                        return False
                    if op == "$gte" and not got >= val:
                        return False
            elif d.get(k) != cond:
                return False
        return True

    def _run(self, doc: dict) -> dict:
        if "createIndexes" in doc:
            return {"ok": 1.0}
        if "update" in doc:
            coll = self._coll(doc, "update")
            n = 0
            for u in doc["updates"]:
                q, setter = u["q"], u["u"]["$set"]
                hit = next((d for d in coll
                            if self._matches(d, q)), None)
                if hit is not None:
                    hit.update(setter)
                elif u.get("upsert"):
                    coll.append({**q, **setter})
                n += 1
            return {"ok": 1.0, "n": n}
        if "find" in doc:
            coll = self._coll(doc, "find")
            hits = [d for d in coll
                    if self._matches(d, doc.get("filter", {}))]
            for field, order in (doc.get("sort") or {}).items():
                hits.sort(key=lambda d: d.get(field, ""),
                          reverse=order < 0)
            limit = doc.get("limit", 0)
            if limit:
                hits = hits[:limit]
            return {"ok": 1.0,
                    "cursor": {"id": 0,
                               "ns": f"{doc['$db']}.{doc['find']}",
                               "firstBatch": [dict(h) for h in hits]}}
        if "delete" in doc:
            coll = self._coll(doc, "delete")
            n = 0
            for dd in doc["deletes"]:
                q, limit = dd["q"], dd.get("limit", 0)
                keep = []
                for d in coll:
                    if self._matches(d, q) and (limit == 0 or n < limit):
                        n += 1
                    else:
                        keep.append(d)
                coll[:] = keep
            return {"ok": 1.0, "n": n}
        return {"ok": 0.0, "errmsg": f"unknown command {list(doc)[0]}"}

    def close(self):
        self._running = False
        try:
            self._sock.close()
        except OSError:
            pass
