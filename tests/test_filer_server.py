"""Filer HTTP server against a live master + volume server.

Covers auto-chunked uploads, range reads, listings, rename, recursive
delete, and chunk GC (reference: weed/server/filer_server_handlers_*).
"""

import urllib.request

import pytest

from seaweedfs_tpu.cluster import rpc
from seaweedfs_tpu.cluster.master import MasterServer
from seaweedfs_tpu.cluster.volume_server import VolumeServer
from seaweedfs_tpu.filer.server import FilerServer


@pytest.fixture(scope="module")
def stack(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("filer-stack")
    master = MasterServer(volume_size_limit_mb=64, meta_dir=str(tmp))
    master.start()
    vs = VolumeServer(master.url(), [str(tmp / "vs")], pulse_seconds=60)
    vs.start()
    filer = FilerServer(master.url(), chunk_size=64)  # tiny: force chunking
    filer.start()
    yield master, vs, filer
    filer.stop()
    vs.stop()
    master.stop()


def _url(filer, path):
    return f"{filer.url()}{path}"


def _req(filer, path, method="GET", data=None, headers=None):
    req = urllib.request.Request(_url(filer, path), data=data,
                                 method=method, headers=headers or {})
    return urllib.request.urlopen(req, timeout=10)


def test_upload_download_roundtrip(stack):
    _m, _vs, filer = stack
    body = b"hello filer world " * 40  # 720B -> 12 chunks of 64
    with _req(filer, "/docs/hello.txt", "POST", body,
              {"Content-Type": "text/plain"}) as resp:
        import json
        meta = json.load(resp)
    assert meta["size"] == len(body)
    with _req(filer, "/docs/hello.txt") as resp:
        assert resp.read() == body
        assert resp.headers["Content-Type"] == "text/plain"


def test_range_read(stack):
    _m, _vs, filer = stack
    body = bytes(range(256)) * 4  # 1024B across 16 chunks
    _req(filer, "/range.bin", "POST", body).read()
    with _req(filer, "/range.bin", headers={"Range": "bytes=100-299"}) as r:
        assert r.status == 206
        assert r.read() == body[100:300]
        assert r.headers["Content-Range"] == "bytes 100-299/1024"
    with _req(filer, "/range.bin", headers={"Range": "bytes=-50"}) as r:
        assert r.read() == body[-50:]
    with _req(filer, "/range.bin", headers={"Range": "bytes=1000-"}) as r:
        assert r.read() == body[1000:]


def test_directory_listing_and_metadata(stack):
    _m, _vs, filer = stack
    for name in ("a.txt", "b.txt", "c.txt"):
        _req(filer, f"/listdir/{name}", "POST", b"x").read()
    import json
    with _req(filer, "/listdir/") as resp:
        listing = json.load(resp)
    assert [e["name"] for e in listing["entries"]] == \
        ["a.txt", "b.txt", "c.txt"]
    with _req(filer, "/listdir/?limit=1&lastFileName=a.txt") as resp:
        listing = json.load(resp)
    assert [e["name"] for e in listing["entries"]] == ["b.txt"]
    with _req(filer, "/listdir/a.txt?metadata=true") as resp:
        meta = json.load(resp)
    assert meta["path"] == "/listdir/a.txt"
    assert meta["chunks"][0]["size"] == 1


def test_rename(stack):
    _m, _vs, filer = stack
    _req(filer, "/mv/src.txt", "POST", b"move-payload").read()
    _req(filer, "/mv/src.txt?mv.to=/mv/dst.txt", "POST", b"").read()
    with _req(filer, "/mv/dst.txt") as resp:
        assert resp.read() == b"move-payload"
    with pytest.raises(urllib.error.HTTPError) as ei:
        _req(filer, "/mv/src.txt")
    assert ei.value.code == 404


def test_delete_and_chunk_gc(stack):
    _m, _vs, filer = stack
    _req(filer, "/gc/file.bin", "POST", b"Z" * 200).read()
    import json
    with _req(filer, "/gc/file.bin?metadata=true") as resp:
        fids = [c["file_id"] for c in json.load(resp)["chunks"]]
    assert fids
    _req(filer, "/gc/file.bin", "DELETE").read()
    with pytest.raises(urllib.error.HTTPError):
        _req(filer, "/gc/file.bin")
    filer.filer.flush_deletions()
    # blobs must be gone from the volume server
    for fid in fids:
        with pytest.raises(rpc.RpcError):
            rpc.call(f"http://{filer.client.lookup(int(fid.split(',')[0]))[0]['url']}/{fid}")


def test_delete_dir_requires_recursive(stack):
    _m, _vs, filer = stack
    _req(filer, "/deldir/x", "POST", b"1").read()
    with pytest.raises(urllib.error.HTTPError) as ei:
        _req(filer, "/deldir", "DELETE")
    assert ei.value.code == 400
    _req(filer, "/deldir?recursive=true", "DELETE").read()
    with pytest.raises(urllib.error.HTTPError):
        _req(filer, "/deldir/x")


def test_overwrite_gcs_old_chunks(stack):
    _m, _vs, filer = stack
    import json
    _req(filer, "/ow.bin", "POST", b"old" * 50).read()
    with _req(filer, "/ow.bin?metadata=true") as resp:
        old_fids = {c["file_id"] for c in json.load(resp)["chunks"]}
    _req(filer, "/ow.bin", "POST", b"new-content").read()
    with _req(filer, "/ow.bin") as resp:
        assert resp.read() == b"new-content"
    filer.filer.flush_deletions()
    with _req(filer, "/ow.bin?metadata=true") as resp:
        new_fids = {c["file_id"] for c in json.load(resp)["chunks"]}
    assert not (old_fids & new_fids)


def test_meta_subscribe(stack):
    _m, _vs, filer = stack
    import json
    with _req(filer, "/.meta/subscribe?since_ns=0") as resp:
        before = json.load(resp)
    _req(filer, "/subevent.txt", "POST", b"ping").read()
    with _req(filer, f"/.meta/subscribe?since_ns={before['last_ns']}") as r:
        after = json.load(r)
    paths = [e["new_entry"]["path"] for e in after["events"]
             if e["new_entry"]]
    assert "/subevent.txt" in paths


def test_head_and_bad_ranges(stack):
    _m, _vs, filer = stack
    body = b"H" * 500
    _req(filer, "/head.bin", "POST", body).read()
    with _req(filer, "/head.bin", "HEAD") as r:
        assert r.read() == b""
        assert r.headers["Content-Length"] == "500"
    # unparseable / multi-range headers serve the full body (RFC 7233)
    for bad in ("bytes=abc-", "bytes=0-1,5-6", "chars=0-5"):
        with _req(filer, "/head.bin", headers={"Range": bad}) as r:
            assert r.status == 200
            assert r.read() == body


def test_upload_to_root_rejected(stack):
    _m, _vs, filer = stack
    with pytest.raises(urllib.error.HTTPError) as ei:
        _req(filer, "/", "POST", b"data")
    assert ei.value.code == 400


def test_mkdir_on_file_conflict(stack):
    _m, _vs, filer = stack
    _req(filer, "/conf.txt", "POST", b"f").read()
    with pytest.raises(urllib.error.HTTPError) as ei:
        _req(filer, "/conf.txt?mkdir=true", "POST", b"")
    assert ei.value.code == 409


def test_mv_under_itself_rejected(stack):
    _m, _vs, filer = stack
    _req(filer, "/selfdir/f", "POST", b"1").read()
    with pytest.raises(urllib.error.HTTPError) as ei:
        _req(filer, "/selfdir?mv.to=/selfdir/sub", "POST", b"")
    assert ei.value.code == 400


def test_mkdir(stack):
    _m, _vs, filer = stack
    import json
    _req(filer, "/made/dir?mkdir=true", "POST", b"").read()
    with _req(filer, "/made/dir?metadata=true") as resp:
        assert json.load(resp)["is_directory"] is True
