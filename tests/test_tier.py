"""Storage backends + volume tiering.

Reference behaviors: weed/storage/backend/ (BackendStorage registry,
disk + S3 tier), volume_tier.go (.vif sidecar, remote reads),
server/volume_grpc_tier_upload.go/_download.go, shell
volume.tier.upload/download.
"""

import json
import os
import urllib.request

import pytest

from seaweedfs_tpu.core.needle import Needle
from seaweedfs_tpu.storage.backend import (LocalDirBackend, S3Backend,
                                           backend_for_spec)
from seaweedfs_tpu.storage.tier import (load_vif, move_dat_from_remote,
                                        move_dat_to_remote,
                                        open_remote_volume)
from seaweedfs_tpu.storage.volume import Volume, VolumeError


# -- backends ---------------------------------------------------------------

def test_local_backend_roundtrip(tmp_path):
    b = backend_for_spec(f"local://{tmp_path}/tier")
    assert isinstance(b, LocalDirBackend)
    src = tmp_path / "src.bin"
    src.write_bytes(bytes(range(256)) * 16)
    assert b.upload_file("v1.dat", str(src)) == 4096
    assert b.read_range("v1.dat", 256, 10) == bytes(range(10))
    dst = tmp_path / "back.bin"
    b.download_file("v1.dat", str(dst))
    assert dst.read_bytes() == src.read_bytes()
    b.delete("v1.dat")
    with pytest.raises(FileNotFoundError):
        b.read_range("v1.dat", 0, 1)


def test_remote_file_block_cache(tmp_path):
    b = LocalDirBackend(str(tmp_path / "t"))
    payload = os.urandom(3 * 1024 * 1024 + 123)
    src = tmp_path / "big.bin"
    src.write_bytes(payload)
    b.upload_file("big", str(src))
    rf = b.open_file("big", len(payload))
    # cross-block read
    assert rf.pread(100, (1 << 20) - 50) == payload[(1 << 20) - 50:
                                                   (1 << 20) + 50]
    # tail + beyond-EOF clamp
    assert rf.pread(1 << 20, len(payload) - 10) == payload[-10:]
    assert rf.pread(10, len(payload) + 5) == b""
    assert rf.size() == len(payload)


def _make_volume(tmp_path, n_needles=20) -> Volume:
    v = Volume(str(tmp_path), "", 7, use_worker=False)
    for i in range(n_needles):
        n = Needle(id=i + 1, cookie=0x1234 + i,
                   data=f"needle-{i}".encode() * 10)
        v.write_needle(n)
    return v


# -- tier move --------------------------------------------------------------

def test_tier_upload_remote_reads_and_download(tmp_path):
    v = _make_volume(tmp_path)
    before = {i + 1: v.read_needle(i + 1).data for i in range(20)}
    with pytest.raises(VolumeError):
        move_dat_to_remote(v, f"local://{tmp_path}/remote")  # not RO
    v.set_readonly()
    info = move_dat_to_remote(v, f"local://{tmp_path}/remote")
    assert not os.path.exists(v.file_name() + ".dat")  # dat moved away
    assert load_vif(v.file_name())["files"][0]["key"] == info["files"][0]["key"]
    # Reads proxy through the remote backend.
    for nid, data in before.items():
        assert v.read_needle(nid).data == data
    # Writes are rejected on a tiered volume.
    with pytest.raises(VolumeError):
        v.write_needle(Needle(id=999, cookie=1, data=b"x"))
    # Bring it back.
    move_dat_from_remote(v)
    assert os.path.exists(v.file_name() + ".dat")
    assert not os.path.exists(v.file_name() + ".vif")
    for nid, data in before.items():
        assert v.read_needle(nid).data == data
    v.close()


def test_open_remote_volume_after_restart(tmp_path):
    v = _make_volume(tmp_path)
    v.set_readonly()
    move_dat_to_remote(v, f"local://{tmp_path}/remote")
    v.close()
    # Fresh process: only .idx + .vif are local.
    v2 = open_remote_volume(str(tmp_path), "", 7)
    assert v2.readonly and v2.remote_file is not None
    assert v2.read_needle(5).data == b"needle-4" * 10
    assert v2.file_count() == 20
    v2.close()


def test_store_discovers_tiered_volume(tmp_path):
    from seaweedfs_tpu.storage.store import Store
    v = _make_volume(tmp_path)
    v.set_readonly()
    move_dat_to_remote(v, f"local://{tmp_path}/remote")
    v.close()
    store = Store([str(tmp_path)])
    try:
        found = store.find_volume(7)
        assert found is not None and found.remote_file is not None
        assert found.read_needle(3).data == b"needle-2" * 10
    finally:
        store.close()


# -- S3 backend against our own gateway ------------------------------------

@pytest.fixture(scope="module")
def s3_stack(tmp_path_factory):
    from seaweedfs_tpu.cluster.master import MasterServer
    from seaweedfs_tpu.cluster.volume_server import VolumeServer
    from seaweedfs_tpu.filer.server import FilerServer
    from seaweedfs_tpu.s3api.server import S3ApiServer
    tmp = tmp_path_factory.mktemp("tier-s3")
    master = MasterServer(volume_size_limit_mb=64, meta_dir=str(tmp))
    master.start()
    vs = VolumeServer(master.url(), [str(tmp / "vs")], pulse_seconds=60)
    vs.start()
    filer = FilerServer(master.url())
    filer.start()
    s3 = S3ApiServer(filer.url())
    s3.start()
    urllib.request.urlopen(urllib.request.Request(
        s3.url() + "/tier-bucket", method="PUT")).read()
    yield master, vs, s3
    s3.stop()
    filer.stop()
    vs.stop()
    master.stop()


def test_s3_backend_tier_roundtrip(tmp_path, s3_stack):
    _m, _vs, s3 = s3_stack
    host = s3.url().replace("http://", "")
    v = _make_volume(tmp_path)
    before = {i + 1: v.read_needle(i + 1).data for i in range(20)}
    v.set_readonly()
    move_dat_to_remote(v, f"s3://{host}/tier-bucket/tiered")
    # The object is visible through the S3 API itself.
    with urllib.request.urlopen(
            s3.url() + "/tier-bucket?list-type=2&prefix=tiered/") as r:
        assert b"7.dat" in r.read()
    for nid, data in before.items():
        assert v.read_needle(nid).data == data
    move_dat_from_remote(v)
    for nid, data in before.items():
        assert v.read_needle(nid).data == data
    v.close()


def test_tier_rpcs_and_shell(tmp_path, s3_stack):
    """Full path: upload data -> readonly -> volume.tier.upload shell
    command -> read through cluster -> volume.tier.download."""
    from seaweedfs_tpu.cluster import rpc
    from seaweedfs_tpu.cluster.client import WeedClient
    master, vs, s3 = s3_stack
    client = WeedClient(master.url())
    fid = client.upload_data(b"tiered object data", collection="")
    vid = int(fid.split(",")[0])
    node = vs.server.url().replace("http://", "")
    rpc.call_json(f"http://{node}/admin/readonly",
                  payload={"volume": vid, "readonly": True})
    host = s3.url().replace("http://", "")
    out = rpc.call_json(f"http://{node}/admin/tier_upload", payload={
        "volume": vid, "dest": f"s3://{host}/tier-bucket/rpc"})
    assert out["remote"]["file_size"] > 0
    # Read the needle through the normal cluster path (remote-backed).
    assert client.download(fid) == b"tiered object data"
    rpc.call_json(f"http://{node}/admin/tier_download",
                  payload={"volume": vid})
    assert client.download(fid) == b"tiered object data"


def test_keep_local_reload_stays_remote(tmp_path):
    """A .vif marks the remote copy authoritative: restart must load the
    volume remote-backed + readonly even when keep_local left a .dat."""
    from seaweedfs_tpu.storage.store import Store
    v = _make_volume(tmp_path)
    v.set_readonly()
    move_dat_to_remote(v, f"local://{tmp_path}/remote", keep_local=True)
    v.close()
    assert os.path.exists(os.path.join(str(tmp_path), "7.dat"))
    store = Store([str(tmp_path)])
    try:
        found = store.find_volume(7)
        assert found.remote_file is not None and found.readonly
        with pytest.raises(VolumeError):
            from seaweedfs_tpu.core.needle import Needle as _N
            found.write_needle(_N(id=999, cookie=1, data=b"x"))
    finally:
        store.close()
