"""volume.configure.replication + fs.meta.notify + notification.toml.

Reference: weed/shell/command_volume_configure_replication.go,
command_fs_meta_notify.go, notification/configuration.go.
"""

import json
import os

import pytest

from seaweedfs_tpu.cluster import rpc
from seaweedfs_tpu.cluster.client import WeedClient
from seaweedfs_tpu.cluster.master import MasterServer
from seaweedfs_tpu.cluster.volume_server import VolumeServer
from seaweedfs_tpu.core.super_block import SuperBlock
from seaweedfs_tpu.filer.server import FilerServer
from seaweedfs_tpu.shell import CommandEnv, run_command


@pytest.fixture
def cluster(tmp_path):
    master = MasterServer(volume_size_limit_mb=64, meta_dir=str(tmp_path))
    master.start()
    servers = []
    for i in range(2):
        d = tmp_path / f"vs{i}"
        d.mkdir()
        vs = VolumeServer(master.url(), [str(d)], pulse_seconds=60)
        vs.start()
        servers.append(vs)
    yield master, servers
    for vs in servers:
        vs.stop()
    master.stop()


def test_volume_configure_replication(cluster, tmp_path):
    master, servers = cluster
    client = WeedClient(master.url())
    fid = client.upload_data(b"data", name="a.txt")  # replication 000
    vid = int(fid.split(",")[0])
    env = CommandEnv(master.url())
    run_command(env, "lock")
    out = run_command(env,
                      f"volume.configure.replication -volumeId {vid} "
                      f"-replication 001")
    assert "configured 001" in out
    # superblock byte rewritten on disk
    holder = next(vs for vs in servers
                  if vs.store.find_volume(vid) is not None)
    v = holder.store.find_volume(vid)
    assert str(v.super_block.replica_placement) == "001"
    with open(v.file_name() + ".dat", "rb") as f:
        sb = SuperBlock.from_bytes(f.read(8))
    assert str(sb.replica_placement) == "001"
    # master re-registered it under the new placement
    lookup = rpc.call(f"{master.url()}/vol/list")
    found = [vv for dc in lookup["topology"]["data_centers"]
             for rack in dc["racks"] for n in rack["nodes"]
             for vv in n["volumes"] if vv["id"] == vid]
    assert found and all(
        vv["replica_placement"] == sb.replica_placement.to_byte()
        for vv in found)
    # fix.replication now creates the second copy
    out = run_command(env, "volume.fix.replication")
    assert "copied" in out
    import time
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        client.cache.forget(vid)
        if len(client.lookup(vid)) == 2:
            break
        time.sleep(0.1)
    assert len(client.lookup(vid)) == 2, out
    # the data reads back from either replica
    assert client.download(fid) == b"data"
    # idempotent: nothing left to change
    import pytest as _pt
    from seaweedfs_tpu.shell.env import ShellError
    with _pt.raises(ShellError, match="no volume"):
        run_command(env,
                    f"volume.configure.replication -volumeId {vid} "
                    f"-replication 001")
    run_command(env, "unlock")


def test_fs_meta_notify_bootstraps_queue(cluster, tmp_path):
    master, _ = cluster
    fs = FilerServer(master.url(), port=0,
                     store_path=str(tmp_path / "f.db"))
    fs.start()
    try:
        base = fs.url()
        rpc.call(f"{base}/boot/a.txt", "POST", b"one")
        rpc.call(f"{base}/boot/sub/b.txt", "POST", b"two")
        env = CommandEnv(master.url(), filer_url=base)
        spool = tmp_path / "notify" / "spool.jsonl"
        out = run_command(env,
                          f"fs.meta.notify -queue=file://{spool} /boot")
        assert "notified" in out
        lines = [json.loads(ln) for ln in
                 open(spool).read().splitlines()]
        keys = {ln["key"] for ln in lines}
        assert {"/boot/a.txt", "/boot/sub", "/boot/sub/b.txt"} <= keys
        ev = next(ln["message"] for ln in lines
                  if ln["key"] == "/boot/a.txt")
        assert ev["new_entry"]["path"] == "/boot/a.txt"
        assert ev["old_entry"] is None
        # the events drive a replicator like live ones do
        from seaweedfs_tpu.replication.notification import FileQueue
        from seaweedfs_tpu.replication.replicator import Replicator
        from seaweedfs_tpu.replication.sink import LocalSink
        repl = Replicator(base, "/boot",
                          LocalSink(str(tmp_path / "mirror")))
        FileQueue(str(spool)).consume(
            lambda k, m: repl.replicate(m))
        assert open(tmp_path / "mirror" / "a.txt", "rb").read() == \
            b"one"
        assert open(tmp_path / "mirror" / "sub" / "b.txt",
                    "rb").read() == b"two"
    finally:
        fs.stop()


def test_filer_wires_notification_toml(cluster, tmp_path, monkeypatch):
    master, _ = cluster
    conf_dir = tmp_path / "conf"
    conf_dir.mkdir()
    spool_dir = tmp_path / "nspool"
    (conf_dir / "notification.toml").write_text(
        f'[notification.file_queue]\nenabled = true\n'
        f'dir = "{spool_dir}"\n')
    import seaweedfs_tpu.utils.config as cfgmod
    monkeypatch.setattr(cfgmod, "SEARCH_PATHS", [str(conf_dir)])
    fs = FilerServer(master.url(), port=0,
                     store_path=str(tmp_path / "f2.db"))
    fs.start()
    try:
        from seaweedfs_tpu.replication.notification import FileQueue
        assert isinstance(fs.filer.notification_queue, FileQueue)
        rpc.call(f"{fs.url()}/nq/x.txt", "POST", b"payload")
        got = []
        FileQueue(str(spool_dir / "events.jsonl")).consume(
            lambda k, m: got.append(k))
        assert "/nq/x.txt" in got
    finally:
        fs.stop()
