"""Distributed tracing: traceparent propagation, span buffer, sampling,
/debug/traces endpoint, shell commands, and the tier-1 smoke-check that
one filer write produces one retrievable multi-span trace.

Also covers the EC stage histograms (execution-fenced device timings
from the Pallas coder feeding SeaweedFS_ec_stage_seconds on /metrics).
"""

import os
import time
import urllib.request

import numpy as np
import pytest

from seaweedfs_tpu.trace import tracer


# -- traceparent codec ------------------------------------------------------

def test_traceparent_roundtrip():
    sp = tracer.Span("ab" * 16, "", "op", "svc", "server", True)
    parsed = tracer.parse_traceparent(sp.traceparent())
    assert parsed == ("ab" * 16, sp.span_id, True)


@pytest.mark.parametrize("bad", [
    "", "00-xyz", "00-" + "0" * 32 + "-" + "1" * 16 + "-01",
    "00-" + "a" * 32 + "-" + "0" * 16 + "-01",
    "00-" + "a" * 31 + "-" + "b" * 16 + "-01",
    "ff-" + "a" * 32 + "-" + "b" * 16 + "-01",
    "00-" + "g" * 32 + "-" + "b" * 16 + "-01",
])
def test_traceparent_malformed(bad):
    assert tracer.parse_traceparent(bad) is None


# -- buffer bounds ----------------------------------------------------------

def test_buffer_evicts_oldest_trace():
    buf = tracer.TraceBuffer(max_traces=4)
    for i in range(6):
        sp = tracer.Span(f"{i:032x}", "", "op", "svc", "server", True)
        sp.duration = 0.001
        buf.record(sp)
    assert len(buf.summaries(0)) == 4
    assert buf.dropped == 2
    assert buf.get(f"{0:032x}") is None
    assert buf.get(f"{5:032x}") is not None


def test_buffer_caps_spans_per_trace():
    buf = tracer.TraceBuffer(max_spans=3)
    for _ in range(5):
        buf.record(tracer.Span("c" * 32, "", "op", "svc", "server", True))
    assert len(buf.get("c" * 32)) == 3


# -- sampling + slow trigger ------------------------------------------------

@pytest.fixture
def trace_env():
    saved = {k: os.environ.get(k) for k in
             ("SEAWEEDFS_TPU_TRACE", "SEAWEEDFS_TPU_TRACE_SAMPLE",
              "SEAWEEDFS_TPU_TRACE_SLOW_MS", "SEAWEEDFS_TPU_TRACES")}
    tracer.BUFFER.clear()
    yield
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    tracer.BUFFER.clear()


def test_unsampled_fast_request_not_recorded(trace_env):
    os.environ["SEAWEEDFS_TPU_TRACE_SAMPLE"] = "0"
    sp = tracer.begin_server_span("svc", "GET", "/x", "")
    tracer.end_server_span(sp, 200)
    assert tracer.BUFFER.get(sp.trace_id) is None


def test_slow_request_recorded_despite_sampling(trace_env):
    os.environ["SEAWEEDFS_TPU_TRACE_SAMPLE"] = "0"
    os.environ["SEAWEEDFS_TPU_TRACE_SLOW_MS"] = "5"
    sp = tracer.begin_server_span("svc", "GET", "/slow", "")
    time.sleep(0.02)
    tracer.end_server_span(sp, 200)
    spans = tracer.BUFFER.get(sp.trace_id)
    assert spans and spans[0]["name"] == "GET /slow"


def test_disabled_records_nothing(trace_env):
    os.environ["SEAWEEDFS_TPU_TRACE"] = "0"
    assert tracer.begin_server_span("svc", "GET", "/x", "") is None
    with tracer.span("child") as sp:
        assert sp is tracer.NOOP


def test_span_nesting_parent_links(trace_env):
    root = tracer.begin_server_span("svc", "POST", "/f", "")
    with tracer.span("outer") as outer:
        with tracer.span("inner", k="v") as inner:
            assert inner.trace_id == root.trace_id
            assert inner.parent_id == outer.span_id
        assert outer.parent_id == root.span_id
    # propagated context parents the downstream server span
    downstream = tracer.begin_server_span(
        "svc2", "POST", "/g", root.traceparent())
    assert downstream.trace_id == root.trace_id
    assert downstream.parent_id == root.span_id
    tracer.end_server_span(downstream, 200)
    tracer.end_server_span(root, 200)
    spans = tracer.BUFFER.get(root.trace_id)
    assert {s["name"] for s in spans} == \
        {"POST /f", "outer", "inner", "POST /g"}
    inner_d = next(s for s in spans if s["name"] == "inner")
    assert inner_d["attrs"] == {"k": "v"}


def test_span_error_status(trace_env):
    root = tracer.begin_server_span("svc", "GET", "/e", "")
    with pytest.raises(ValueError):
        with tracer.span("boom"):
            raise ValueError("nope")
    tracer.end_server_span(root, 500)
    spans = tracer.BUFFER.get(root.trace_id)
    assert all(s["status"] == "error" for s in spans)


# -- live stack smoke (tier-1 trace smoke-check) ----------------------------

@pytest.fixture(scope="module")
def traced_stack(tmp_path_factory):
    """master + 2 volume servers (2-replica default) + filer, with the
    /debug/traces endpoint enabled — env must be set BEFORE servers are
    constructed, since the route mounts at construction (like pprof)."""
    saved = os.environ.get("SEAWEEDFS_TPU_TRACES")
    os.environ["SEAWEEDFS_TPU_TRACES"] = "1"
    from seaweedfs_tpu.cluster.master import MasterServer
    from seaweedfs_tpu.cluster.volume_server import VolumeServer
    from seaweedfs_tpu.filer.server import FilerServer
    tracer.BUFFER.clear()
    tmp = tmp_path_factory.mktemp("trace-stack")
    master = MasterServer(volume_size_limit_mb=64, meta_dir=str(tmp),
                          default_replication="001")
    master.start()
    vs1 = VolumeServer(master.url(), [str(tmp / "v1")], pulse_seconds=60)
    vs1.start()
    vs2 = VolumeServer(master.url(), [str(tmp / "v2")], pulse_seconds=60)
    vs2.start()
    filer = FilerServer(master.url())
    filer.start()
    yield master, vs1, vs2, filer
    filer.stop()
    vs2.stop()
    vs1.stop()
    master.stop()
    if saved is None:
        os.environ.pop("SEAWEEDFS_TPU_TRACES", None)
    else:
        os.environ["SEAWEEDFS_TPU_TRACES"] = saved
    tracer.BUFFER.clear()


def _get_json(url: str) -> dict:
    import json
    with urllib.request.urlopen(url) as r:
        return json.load(r)


def test_filer_write_produces_multi_span_trace(traced_stack):
    """Acceptance: a single filer write against a 2-replica volume
    yields one trace with >= 4 spans across >= 2 services (filer server
    span -> volume write span -> replica fan-out spans), consistent
    trace id, resolvable parent links."""
    _master, _v1, _v2, filer = traced_stack
    tracer.BUFFER.clear()
    from seaweedfs_tpu.filer.client import FilerProxy
    FilerProxy(filer.url()).put("/traced/hello.txt", b"trace me" * 100)

    out = _get_json(filer.url() + "/debug/traces")
    roots = [t for t in out["traces"] if "filer" in t["services"]
             and "POST /traced/hello.txt" in t["root"]]
    assert roots, f"no filer write trace in {out['traces']}"
    summary = roots[0]
    detail = _get_json(
        filer.url() + f"/debug/traces?trace={summary['trace_id']}")
    spans = detail["spans"]
    assert len(spans) >= 4
    assert all(s["trace_id"] == summary["trace_id"] for s in spans)
    services = {s["service"] for s in spans}
    assert {"filer", "volumeServer"} <= services
    # every non-root parent link resolves inside the trace
    ids = {s["span_id"] for s in spans}
    for s in spans:
        if s["parent_id"]:
            assert s["parent_id"] in ids, s
    # the replication fan-out is visible: a replicate span plus the
    # replica's own server span (type=replicate POST)
    names = [s["name"] for s in spans]
    assert "volume.replicate" in names
    assert "filer.write.chunks" in names
    replicas = [s for s in spans if s["service"] == "volumeServer"
                and s["name"].startswith("POST /")]
    assert len(replicas) >= 2  # primary write + >=1 fan-out write


def test_read_redirect_lookup_is_traced(traced_stack):
    """A GET landing on the wrong volume server spans its master lookup
    (volume.loc_lookup) before the 301."""
    master, vs1, vs2, _filer = traced_stack
    from seaweedfs_tpu.cluster import rpc
    from seaweedfs_tpu.cluster.client import WeedClient
    client = WeedClient(master.url())
    fid = client.upload_data(b"single copy", replication="000")
    locs = client.lookup(int(fid.split(",")[0]))
    holder = locs[0]["url"]
    other = vs2 if vs1.url() == holder else vs1
    tracer.BUFFER.clear()
    assert bytes(rpc.call(f"http://{other.url()}/{fid}")) \
        == b"single copy"
    names = [s["name"] for t in tracer.BUFFER.summaries(0)
             for s in tracer.BUFFER.get(t["trace_id"])]
    assert "volume.loc_lookup" in names


def test_trace_shell_commands(traced_stack):
    master, _v1, _v2, filer = traced_stack
    from seaweedfs_tpu.filer.client import FilerProxy
    from seaweedfs_tpu.shell import CommandEnv, run_command
    FilerProxy(filer.url()).put("/traced/shell.txt", b"shell trace")
    env = CommandEnv(master.url(), filer_url=filer.url())
    try:
        listing = run_command(env, "trace.ls")
        assert "TRACE" in listing
        line = next(ln for ln in listing.splitlines()[1:]
                    if "/traced/shell.txt" in ln)
        trace_id = line.split()[0]
        tree = run_command(env, f"trace.get {trace_id}")
        assert "filer" in tree and "volumeServer" in tree
        assert "volume.replicate" in tree
    finally:
        env.close()


def test_traces_endpoint_404_unknown_trace(traced_stack):
    _m, _v1, _v2, filer = traced_stack
    from seaweedfs_tpu.cluster import rpc
    with pytest.raises(rpc.RpcError) as ei:
        rpc.call(filer.url() + "/debug/traces?trace=" + "d" * 32)
    assert ei.value.status == 404


def test_traces_route_gated_like_pprof(tmp_path):
    """Without SEAWEEDFS_TPU_TRACES the endpoint must not exist."""
    saved = os.environ.pop("SEAWEEDFS_TPU_TRACES", None)
    try:
        from seaweedfs_tpu.cluster import rpc
        from seaweedfs_tpu.cluster.master import MasterServer
        m = MasterServer(volume_size_limit_mb=64,
                         meta_dir=str(tmp_path))
        m.start()
        try:
            with pytest.raises(rpc.RpcError) as ei:
                rpc.call(m.url() + "/debug/traces")
            assert ei.value.status == 404
        finally:
            m.stop()
    finally:
        if saved is not None:
            os.environ["SEAWEEDFS_TPU_TRACES"] = saved


def test_grpc_facade_extracts_traceparent(trace_env, tmp_path):
    """The gRPC master facade bypasses the HTTP middleware, so it must
    extract the traceparent metadata itself: an Assign made inside an
    active span yields a master server span parented under it."""
    pytest.importorskip("grpc")
    # recording is consumer-gated; force it for this in-process reader
    os.environ["SEAWEEDFS_TPU_TRACE"] = "1"
    from seaweedfs_tpu.cluster.client import WeedClient
    from seaweedfs_tpu.cluster.master import MasterServer
    from seaweedfs_tpu.cluster.volume_server import VolumeServer
    from seaweedfs_tpu.pb.master_grpc import MasterGrpcServer
    master = MasterServer(volume_size_limit_mb=64,
                          meta_dir=str(tmp_path))
    master.start()
    vs = VolumeServer(master.url(), [str(tmp_path / "vs")],
                      pulse_seconds=60)
    vs.start()
    # default port convention (http + 10000) — what use_grpc dials
    g = MasterGrpcServer(master)
    g.start()
    client = WeedClient(master.url(), use_grpc=True)
    try:
        tracer.BUFFER.clear()
        root = tracer.begin_server_span("test", "POST", "/entry", "")
        client.assign()
        tracer.end_server_span(root, 200)
        spans = tracer.BUFFER.get(root.trace_id)
        grpc_span = next(s for s in spans
                         if s["name"] == "GRPC /master_pb.Seaweed/Assign")
        assert grpc_span["service"] == "master"
        assert grpc_span["parent_id"] == root.span_id
    finally:
        client.close()
        g.stop()
        vs.stop()
        master.stop()


def test_cli_trace_flags_set_env(trace_env):
    """-debug.traces / -trace.sample / -trace.slowMs / -trace=false on
    any server command map onto the tracer's env knobs."""
    os.environ.pop("SEAWEEDFS_TPU_TRACES", None)
    from seaweedfs_tpu.command import main
    assert main(["version", "-debug.traces", "-trace.sample=0.25",
                 "-trace.slowMs=100", "-trace=false"]) == 0
    assert os.environ.get("SEAWEEDFS_TPU_TRACES") == "1"
    assert os.environ.get("SEAWEEDFS_TPU_TRACE") == "0"
    assert tracer.sample_rate() == 0.25
    assert tracer.slow_threshold_seconds() == 0.1
    assert not tracer.enabled()


# -- EC stage histograms ----------------------------------------------------

def test_ec_stage_histogram_records_fenced_device_time(traced_stack):
    """An EC reconstruct run records execution-fenced device time into
    the *_ec_stage_seconds histogram, visible on a volume server's
    /metrics scrape."""
    from seaweedfs_tpu.ops.coder_pallas import PallasCoder
    coder = PallasCoder(interpret=True)
    rng = np.random.default_rng(7)
    data = rng.integers(0, 256, size=(10, 4096), dtype=np.uint8)
    shards = np.asarray(coder.encode_all(data))
    present = {i: shards[i] for i in range(2, 14)}  # lose shards 0,1
    rec = coder.reconstruct(present, wanted=[0, 1])
    assert np.array_equal(np.asarray(rec[0]), shards[0])

    _m, vs1, _v2, _f = traced_stack
    with urllib.request.urlopen(
            vs1.server.url() + "/metrics") as r:
        text = r.read().decode()
    assert "SeaweedFS_ec_stage_seconds" in text
    assert 'stage="encode_kernel"' in text
    assert 'stage="reconstruct_kernel"' in text
    assert "SeaweedFS_ec_stage_bytes_total" in text
