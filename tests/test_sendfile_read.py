"""Zero-copy needle GET path: Volume.read_needle_slice + os.sendfile
(reference parity: volume_server_handlers_read.go serves needle bytes
after a CRC check — same check here, without a userspace payload copy)."""

import os
import time

import pytest

from seaweedfs_tpu.cluster import rpc
from seaweedfs_tpu.cluster.client import WeedClient
from seaweedfs_tpu.cluster.master import MasterServer
from seaweedfs_tpu.cluster.volume_server import VolumeServer
from seaweedfs_tpu.core.needle import Needle
from seaweedfs_tpu.stats import flows
from seaweedfs_tpu.storage.volume import NotFoundError, Volume, VolumeError


BIG = os.urandom(512 * 1024)


def test_read_needle_slice_verifies_and_serves(tmp_path):
    v = Volume(str(tmp_path), "", 1)
    v.write_needle(Needle(id=7, cookie=0xABC, data=BIG))
    sl = v.read_needle_slice(7, 0xABC, min_size=1024)
    assert sl is not None
    with sl:
        assert sl.size == len(BIG)
        got = b""
        while True:
            piece = sl.read(100_000)
            if not piece:
                break
            got += piece
    assert got == BIG
    # small needles fall back to the parse path
    v.write_needle(Needle(id=8, cookie=1, data=b"tiny"))
    assert v.read_needle_slice(8, 1, min_size=1024) is None
    # wrong cookie refused, absent/deleted raise like read_needle
    with pytest.raises(VolumeError):
        v.read_needle_slice(7, 0xDEF, min_size=1024)
    with pytest.raises(NotFoundError):
        v.read_needle_slice(999, None, min_size=1024)
    v.close()


def test_read_needle_slice_detects_corruption(tmp_path):
    v = Volume(str(tmp_path), "", 1)
    off, _size = v.write_needle(Needle(id=7, cookie=1, data=BIG))
    # Flip one payload byte on disk: the streamed CRC must catch it.
    with open(v.file_name() + ".dat", "r+b") as f:
        f.seek(off + 16 + 4 + 1000)
        b = f.read(1)
        f.seek(off + 16 + 4 + 1000)
        f.write(bytes((b[0] ^ 0xFF,)))
    with pytest.raises(VolumeError, match="CRC"):
        v.read_needle_slice(7, 1, min_size=1024)
    v.close()


def test_large_get_end_to_end_sendfile(tmp_path):
    """Upload > SENDFILE_MIN through a live cluster, read it back via
    the HTTP plane (exercises NeedleSlice.sendfile_to on a real
    socket), and confirm compressed uploads still take the parse path."""
    master = MasterServer(volume_size_limit_mb=64, meta_dir=str(tmp_path))
    master.start()
    d = tmp_path / "v"
    d.mkdir()
    vs = VolumeServer(master.url(), [str(d)], pulse_seconds=60)
    vs.start()
    try:
        client = WeedClient(master.url())
        fid = client.upload_data(BIG)
        out = rpc.call(f"http://{vs.url()}/{fid}")
        assert bytes(out) == BIG
        # Flow-ledger byte identity: the sendfile bytes never transit
        # userspace, so the server's user.read response leg must carry
        # the syscall-returned totals — exactly the served body.  (The
        # note lands on the serving thread right after os.sendfile
        # returns; settle briefly so the assert can't race it.)
        def served():
            return flows.LEDGER.totals(purpose_="user.read",
                                       direction="out",
                                       local=vs.url())[0]
        deadline = time.time() + 5.0
        while served() != len(BIG) and time.time() < deadline:
            time.sleep(0.05)
        assert served() == len(BIG), \
            "sendfile response leg != served body bytes"
        assert flows.LEDGER.totals(purpose_="user.read",
                                   direction="in")[0] == len(BIG)
        # a compressible payload stored gzipped must still round-trip
        # (slice path declines compressed needles)
        text = (b"the quick brown fox " * 40_000)  # > SENDFILE_MIN
        fid2 = client.upload(text, name="a.txt")["fid"]
        assert client.download(fid2) == text
    finally:
        vs.stop()
        master.stop()


def test_range_reads_on_volume_server(tmp_path):
    """processRangeRequest parity (weed/server/common.go:233 via
    volume_server_handlers_read.go:255-264): single ranges serve 206 +
    Content-Range on both the parse path (small needles) and the
    zero-copy sendfile path (large needles); suffix form works;
    multi-range is ignored (whole body, RFC 7233 MAY); a range past
    the end answers 416."""
    import urllib.request

    master = MasterServer(volume_size_limit_mb=64, meta_dir=str(tmp_path))
    master.start()
    d = tmp_path / "v"
    d.mkdir()
    vs = VolumeServer(master.url(), [str(d)], pulse_seconds=60)
    vs.start()
    try:
        client = WeedClient(master.url())
        small = bytes(range(256)) * 4          # parse path
        big = BIG                              # sendfile path (512KB)
        fid_s = client.upload_data(small)
        fid_b = client.upload_data(big)

        def get(fid, rng=None):
            req = urllib.request.Request(
                f"http://{vs.url()}/{fid}",
                headers={"Range": rng} if rng else {})
            try:
                with urllib.request.urlopen(req, timeout=10) as r:
                    return r.status, dict(r.headers), r.read()
            except urllib.error.HTTPError as e:
                return e.code, dict(e.headers), b""

        for fid, payload in ((fid_s, small), (fid_b, big)):
            st, hdrs, body = get(fid, "bytes=10-99")
            assert st == 206 and body == payload[10:100]
            assert hdrs["Content-Range"] == \
                f"bytes 10-99/{len(payload)}"
            st, _h, body = get(fid, "bytes=-100")    # suffix form
            assert st == 206 and body == payload[-100:]
            st, _h, body = get(fid, f"bytes={len(payload) - 1}-")
            assert st == 206 and body == payload[-1:]
            st, _h, body = get(fid, "bytes=0-5,10-15")  # multi: whole
            assert st == 200 and body == payload
            st, _h, _b = get(fid, f"bytes={len(payload) + 5}-")
            assert st == 416
            st, hdrs, body = get(fid)                # no range
            assert st == 200 and body == payload
            assert hdrs.get("Accept-Ranges") == "bytes"
    finally:
        vs.stop()
        master.stop()


def test_parse_byte_range_edge_cases():
    """Reversed/negative ranges are unsatisfiable and ignored (Go's
    parseRange rejects start > end); ranges against an empty body
    answer 416 except the always-satisfiable suffix form."""
    import pytest as _pytest

    from seaweedfs_tpu.cluster.rpc import parse_byte_range

    assert parse_byte_range("bytes=50-20", 100) is None
    assert parse_byte_range("bytes=5--10", 100) is None
    assert parse_byte_range("bytes=-100", 0) is None
    for rng in ("bytes=0-", "bytes=5-"):
        with _pytest.raises(rpc.RpcError) as ei:
            parse_byte_range(rng, 0)
        assert ei.value.status == 416


def test_conditional_get_etag_last_modified(tmp_path):
    """volume_server_handlers_read.go:113-129 parity: ETag is the
    quoted checksum hex, If-None-Match answers 304, Last-Modified +
    If-Modified-Since answer 304, needle mime/name drive Content-Type
    and Content-Disposition (?dl=true switches to attachment) — on
    both the parse path and the zero-copy path."""
    import urllib.request

    master = MasterServer(volume_size_limit_mb=64, meta_dir=str(tmp_path))
    master.start()
    d = tmp_path / "v"
    d.mkdir()
    vs = VolumeServer(master.url(), [str(d)], pulse_seconds=60)
    vs.start()
    try:
        client = WeedClient(master.url())
        cases = [
            client.upload(b"small payload" * 10, name="doc.pdf",
                          mime="application/pdf", compress=False)["fid"],
            client.upload(os.urandom(300_000), name="big.bin",
                          mime="image/png", compress=False)["fid"],
        ]

        def get(fid, headers=None, q=""):
            req = urllib.request.Request(
                f"http://{vs.url()}/{fid}{q}", headers=headers or {})
            try:
                with urllib.request.urlopen(req, timeout=10) as r:
                    return r.status, dict(r.headers), r.read()
            except urllib.error.HTTPError as e:
                return e.code, dict(e.headers), b""

        for fid in cases:
            st, hdrs, body = get(fid)
            assert st == 200
            etag = hdrs["ETag"]
            assert etag.startswith('"') and len(etag) == 10
            assert "Last-Modified" in hdrs
            assert 'filename="' in hdrs["Content-Disposition"]
            assert hdrs["Content-Disposition"].startswith("inline")
            assert hdrs["Content-Type"] in ("application/pdf",
                                            "image/png")
            # If-None-Match -> 304
            st, _h, body = get(fid, {"If-None-Match": etag})
            assert st == 304 and body == b""
            # If-Modified-Since (now) -> 304
            st, _h, _b = get(
                fid, {"If-Modified-Since": hdrs["Last-Modified"]})
            assert st == 304
            # stale If-Modified-Since -> 200
            st, _h, _b = get(fid, {
                "If-Modified-Since":
                "Mon, 01 Jan 1990 00:00:00 GMT"})
            assert st == 200
            # ?dl=true -> attachment
            st, hdrs, _b = get(fid, q="?dl=true")
            assert hdrs["Content-Disposition"].startswith("attachment")
    finally:
        vs.stop()
        master.stop()


def test_read_redirect_non_local_volume(tmp_path):
    """-read.redirect parity (volume.go:79, default true;
    GetOrHeadHandler:62-83): a GET against a server that doesn't host
    the volume answers 301 to a current holder; with
    read_redirect=False it answers 404 like before."""
    import urllib.request

    master = MasterServer(volume_size_limit_mb=64, meta_dir=str(tmp_path))
    master.start()
    servers = []
    for i, redirect in enumerate((True, False)):
        d = tmp_path / f"v{i}"
        d.mkdir()
        vs = VolumeServer(master.url(), [str(d)], pulse_seconds=60,
                          read_redirect=redirect)
        vs.start()
        servers.append(vs)
    try:
        client = WeedClient(master.url())
        # Fill until both servers host at least one volume, then pick
        # a fid hosted ONLY on one server.
        fids = [client.upload_data(f"rr-{i}".encode() * 10)
                for i in range(60)]
        by_server: dict[str, str] = {}
        for fid in fids:
            vid = int(fid.split(",")[0])
            locs = client.lookup(vid)
            if len(locs) == 1:
                by_server.setdefault(locs[0]["url"], fid)
        a_url = servers[0].url()
        b_url = servers[1].url()
        foreign = by_server.get(b_url)  # hosted on B, ask A
        assert foreign is not None, by_server
        # A (redirect on) 301s to B; urllib follows and gets the data.
        req = urllib.request.Request(f"http://{a_url}/{foreign}")
        with urllib.request.urlopen(req, timeout=10) as r:
            assert r.status == 200
            assert r.url.startswith(f"http://{b_url}/")
            assert r.read().startswith(b"rr-")
        # Raw: the response really is a 301 with Location.
        class NoRedirect(urllib.request.HTTPRedirectHandler):
            def redirect_request(self, *a, **kw):
                return None
        opener = urllib.request.build_opener(NoRedirect)
        try:
            opener.open(f"http://{a_url}/{foreign}", timeout=10)
            raise AssertionError("expected 301")
        except urllib.error.HTTPError as e:
            assert e.code == 301
            assert e.headers["Location"] == f"http://{b_url}/{foreign}"
        # B (redirect off) answers 404 for A's volumes.
        local = by_server.get(a_url)
        if local is not None:
            try:
                opener.open(f"http://{b_url}/{local}", timeout=10)
                raise AssertionError("expected 404")
            except urllib.error.HTTPError as e:
                assert e.code == 404
    finally:
        for vs in servers:
            vs.stop()
        master.stop()
