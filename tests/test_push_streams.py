"""Streaming push channels (replacing poll loops — round-2/3 verdict
missing #8 / weak #6): the filer meta tail is a long-lived NDJSON
stream (SubscribeMetadata analog) and the master pushes volume-location
deltas over /cluster/watch (KeepConnected analog)."""

import json
import time
import urllib.request

import pytest

from seaweedfs_tpu.cluster.master import MasterServer
from seaweedfs_tpu.cluster.volume_server import VolumeServer
from seaweedfs_tpu.filer.client import FilerProxy
from seaweedfs_tpu.filer.meta_aggregator import MetaAggregator
from seaweedfs_tpu.filer.server import FilerServer


@pytest.fixture
def stack(tmp_path):
    master = MasterServer(volume_size_limit_mb=64, meta_dir=str(tmp_path))
    master.start()
    vs = VolumeServer(master.url(), [str(tmp_path / "vs")],
                      pulse_seconds=60)
    vs.start()
    filer = FilerServer(master.url())
    filer.start()
    yield master, vs, filer
    filer.stop()
    vs.stop()
    master.stop()


def _put(filer, path, data=b"x"):
    urllib.request.urlopen(urllib.request.Request(
        f"{filer.url()}{path}", data=data, method="POST"),
        timeout=30).read()


def test_meta_tail_pushes_without_polling(stack):
    _m, _vs, filer = stack
    _put(filer, "/pre/existing.txt", b"replayed")
    proxy = FilerProxy(filer.url())
    resp, events = proxy.meta_stream(since_ns=0)
    got: list[dict] = []
    import threading
    done = threading.Event()

    def consume():
        for d in events:
            got.append(d)
            if any((e.get("new_entry") or {}).get("path")
                   == "/live/pushed.txt" for e in got):
                done.set()
                return

    t = threading.Thread(target=consume, daemon=True)
    t.start()
    # The replay part arrives first...
    deadline = time.time() + 5
    while time.time() < deadline and not any(
            (e.get("new_entry") or {}).get("path") == "/pre/existing.txt"
            for e in got):
        time.sleep(0.05)
    assert any((e.get("new_entry") or {}).get("path")
               == "/pre/existing.txt" for e in got), got
    # ...then a LIVE mutation is pushed promptly (no poll interval).
    t0 = time.time()
    _put(filer, "/live/pushed.txt", b"now")
    assert done.wait(5), "live event never arrived on the stream"
    latency = time.time() - t0
    assert latency < 2.0, f"push took {latency:.2f}s — looks like polling"
    resp.close()


def test_meta_tail_cursor_only_for_excluded(stack):
    _m, _vs, filer = stack
    sig = filer.filer.signature
    proxy = FilerProxy(filer.url())
    resp, events = proxy.meta_stream(since_ns=0, exclude_signature=sig)
    _put(filer, "/excluded/by-signature.txt")
    deadline = time.time() + 5
    cursor_docs = []
    for d in events:
        cursor_docs.append(d)
        if d.get("_cursor_only"):
            break
        if time.time() > deadline:
            break
    assert any(d.get("_cursor_only") and d["ts_ns"] > 0
               for d in cursor_docs), cursor_docs
    assert not any(d.get("new_entry") for d in cursor_docs)
    resp.close()


def test_meta_aggregator_streams_peer_events(stack):
    _m, _vs, filer = stack
    agg = MetaAggregator([filer.url()])
    seen = []
    agg.subscribe(lambda peer, ev: seen.append((peer, ev)))
    agg.start()
    try:
        t0 = time.time()
        _put(filer, "/agg/streamed.txt", b"hi")
        deadline = time.time() + 5
        while time.time() < deadline and not any(
                ev.new_entry and ev.new_entry.path == "/agg/streamed.txt"
                for _p, ev in seen):
            time.sleep(0.05)
        assert any(ev.new_entry and
                   ev.new_entry.path == "/agg/streamed.txt"
                   for _p, ev in seen)
        assert time.time() - t0 < 2.0  # pushed, not polled
        assert agg._offsets[filer.url()] > 0
    finally:
        agg.stop()


def test_meta_tail_paged_replay_of_large_journal(stack):
    """Replay pages through the journal in bounded reads (no full-
    journal buffering, no log lock held across the history — review
    finding), then hands off to live push with no gap."""
    from seaweedfs_tpu.filer.entry import Attributes, Entry
    _m, _vs, filer = stack
    n = 2500  # > 2 replay pages of 1000
    for i in range(n):
        filer.filer.create_entry(Entry(
            path=f"/bulk/f{i:05d}", attributes=Attributes(mtime=1.0)))
    proxy = FilerProxy(filer.url())
    resp, events = proxy.meta_stream(since_ns=0)
    seen_paths = set()
    for d in events:
        p = (d.get("new_entry") or {}).get("path", "")
        if p.startswith("/bulk/f"):
            seen_paths.add(p)
        if len(seen_paths) == n:
            break
    assert len(seen_paths) == n
    # live handoff still works after the long replay
    _put(filer, "/bulk/live.txt", b"x")
    got_live = False
    deadline = time.time() + 5
    for d in events:
        if (d.get("new_entry") or {}).get("path") == "/bulk/live.txt":
            got_live = True
            break
        if time.time() > deadline:
            break
    assert got_live
    resp.close()


def test_cluster_watch_snapshot_and_delta(stack):
    master, vs, filer = stack
    # Ensure at least one volume exists for the snapshot.
    _put(filer, "/watch/seed.txt", b"s")
    vs._send_heartbeat(full=True)
    resp = urllib.request.urlopen(f"{master.url()}/cluster/watch",
                                  timeout=30)
    docs = []
    # initial snapshot: the node's current vids
    line = resp.readline()
    while line is not None and line.strip():
        docs.append(json.loads(line))
        if docs[-1].get("new_vids"):
            break
        line = resp.readline()
    assert docs and docs[-1]["url"] == vs.url()
    assert docs[-1]["new_vids"]
    resp.close()


def test_client_cache_invalidated_on_push(stack):
    master, vs, filer = stack
    _put(filer, "/inv/obj.txt", b"z")
    vs._send_heartbeat(full=True)
    client = filer.client  # FilerServer's WeedClient runs the watcher
    # Prime the cache.
    vids = sorted(set(vs.store.locations[0].volumes))
    vid = vids[0]
    assert client.lookup(vid)
    assert client.cache.get(vid) is not None
    # Deleting the volume makes the next heartbeat report it gone; the
    # master pushes the delta and the watcher drops the cache entry —
    # long before the 60s TTL.
    from seaweedfs_tpu.cluster import rpc
    rpc.call_json(f"http://{vs.url()}/admin/delete_volume", "POST",
                  {"volume": vid})
    vs._send_heartbeat(full=True)
    deadline = time.time() + 10
    while time.time() < deadline and client.cache.get(vid) is not None:
        time.sleep(0.1)
    assert client.cache.get(vid) is None, \
        "vid cache entry survived a location push"
