"""JAX / Pallas coders must be byte-identical to the numpy oracle."""

import numpy as np
import pytest

from seaweedfs_tpu.ops.coder_jax import JaxCoder
from seaweedfs_tpu.ops.coder_numpy import NumpyCoder
from seaweedfs_tpu.ops.coder_pallas import BLOCK_N, PallasCoder
from seaweedfs_tpu.ops.erasure import new_coder


def _rand(k, n, seed=0):
    return np.random.default_rng(seed).integers(0, 256, (k, n)).astype(np.uint8)


@pytest.fixture(scope="module")
def oracle():
    return NumpyCoder(10, 4)


@pytest.mark.parametrize("n", [128, 1000, 8192])
def test_jax_encode_matches_numpy(oracle, n):
    data = _rand(10, n, n)
    jc = JaxCoder(10, 4)
    assert np.array_equal(np.asarray(jc.encode(data)), oracle.encode(data))


def test_jax_reconstruct_matches_numpy(oracle):
    data = _rand(10, 2048, 7)
    shards = oracle.encode_all(data)
    jc = JaxCoder(10, 4)
    for lost in [(0, 1, 2, 3), (10, 11, 12, 13), (2, 7, 11, 13), (5,)]:
        have = {i: shards[i] for i in range(14) if i not in lost}
        rec = jc.reconstruct(have)
        assert set(rec) == set(lost)
        for i in lost:
            assert np.array_equal(np.asarray(rec[i]), shards[i])


def test_jax_alt_scheme(oracle):
    data = _rand(16, 512, 3)
    jc = JaxCoder(16, 4)
    nc = NumpyCoder(16, 4)
    assert np.array_equal(np.asarray(jc.encode(data)), nc.encode(data))


def test_pallas_encode_matches_numpy(oracle):
    # Exercise both exact-multiple and ragged n (padding path).
    for n in (BLOCK_N, BLOCK_N * 2, 5000):
        data = _rand(10, n, n)
        pc = PallasCoder(10, 4)  # interpret mode on CPU
        assert np.array_equal(np.asarray(pc.encode(data)), oracle.encode(data))


def test_pallas_reconstruct_matches(oracle):
    data = _rand(10, BLOCK_N, 11)
    shards = oracle.encode_all(data)
    pc = PallasCoder(10, 4)
    lost = (1, 6, 10, 12)
    have = {i: shards[i] for i in range(14) if i not in lost}
    rec = pc.reconstruct(have)
    for i in lost:
        assert np.array_equal(np.asarray(rec[i]), shards[i])


def test_backend_selection(monkeypatch):
    monkeypatch.setenv("SEAWEEDFS_TPU_CODER", "numpy")
    assert isinstance(new_coder(), NumpyCoder)
    monkeypatch.setenv("SEAWEEDFS_TPU_CODER", "jax")
    assert isinstance(new_coder(), JaxCoder)
    monkeypatch.setenv("SEAWEEDFS_TPU_CODER", "bogus")
    with pytest.raises(ValueError):
        new_coder()


def test_cross_backend_byte_identity():
    """All three backends produce identical shard bytes (compat invariant)."""
    data = _rand(10, 1024, 99)
    outs = []
    for b in ("numpy", "jax", "pallas"):
        c = new_coder(backend=b)
        outs.append(np.asarray(c.encode(data)))
    assert np.array_equal(outs[0], outs[1])
    assert np.array_equal(outs[0], outs[2])
