"""Filer layer: chunk algebra, store conformance, namespace core.

Mirrors the reference's pure-function test style for the chunk model
(weed/filer/filechunks_test.go) and per-backend store conformance
(filer/leveldb/leveldb_store_test.go).
"""

import time

import pytest

from seaweedfs_tpu.filer import (Attributes, Entry, FileChunk, Filer,
                                 FilerError, MemoryStore, SqliteStore,
                                 compact_file_chunks,
                                 non_overlapping_visible_intervals,
                                 read_chunk_views, total_size)
from seaweedfs_tpu.filer.filerstore import NotFound, iterate_tree


def C(fid, offset, size, mtime):
    return FileChunk(file_id=fid, offset=offset, size=size, mtime=mtime)


# -- chunk algebra (filechunks_test.go scenarios) ---------------------------

class TestVisibleIntervals:
    def test_append_only(self):
        vis = non_overlapping_visible_intervals(
            [C("a", 0, 100, 1), C("b", 100, 100, 2)])
        assert [(v.start, v.stop, v.file_id) for v in vis] == \
            [(0, 100, "a"), (100, 200, "b")]

    def test_full_overwrite(self):
        vis = non_overlapping_visible_intervals(
            [C("a", 0, 100, 1), C("b", 0, 100, 2)])
        assert [(v.start, v.stop, v.file_id) for v in vis] == \
            [(0, 100, "b")]

    def test_partial_tail_overwrite(self):
        vis = non_overlapping_visible_intervals(
            [C("a", 0, 100, 1), C("b", 50, 100, 2)])
        assert [(v.start, v.stop, v.file_id) for v in vis] == \
            [(0, 50, "a"), (50, 150, "b")]

    def test_hole_punch_middle(self):
        vis = non_overlapping_visible_intervals(
            [C("a", 0, 300, 1), C("b", 100, 100, 2)])
        assert [(v.start, v.stop, v.file_id, v.chunk_offset)
                for v in vis] == \
            [(0, 100, "a", 0), (100, 200, "b", 0), (200, 300, "a", 200)]

    def test_older_chunk_arrives_later_in_list(self):
        # List order must not matter — only mtime does.
        vis = non_overlapping_visible_intervals(
            [C("b", 0, 100, 2), C("a", 0, 200, 1)])
        assert [(v.start, v.stop, v.file_id) for v in vis] == \
            [(0, 100, "b"), (100, 200, "a")]

    def test_interleaved_writes(self):
        vis = non_overlapping_visible_intervals([
            C("a", 0, 100, 1), C("b", 50, 100, 2), C("c", 25, 50, 3)])
        assert [(v.start, v.stop, v.file_id) for v in vis] == \
            [(0, 25, "a"), (25, 75, "c"), (75, 150, "b")]

    def test_random_writes_against_oracle(self):
        import random
        rng = random.Random(42)
        for _trial in range(50):
            file_len = 1000
            oracle = [None] * file_len
            chunks = []
            for mtime in range(1, 16):
                off = rng.randrange(0, file_len - 10)
                size = rng.randrange(1, file_len - off)
                fid = f"f{mtime}"
                chunks.append(C(fid, off, size, mtime))
                for i in range(off, off + size):
                    oracle[i] = fid
            rng.shuffle(chunks)
            vis = non_overlapping_visible_intervals(chunks)
            # disjoint + sorted
            for u, v in zip(vis, vis[1:]):
                assert u.stop <= v.start
            got = [None] * file_len
            for v in vis:
                for i in range(v.start, min(v.stop, file_len)):
                    got[i] = v.file_id
            assert got == oracle


class TestReadViews:
    def test_view_clipping(self):
        chunks = [C("a", 0, 100, 1), C("b", 100, 100, 2)]
        views = read_chunk_views(chunks, 50, 100)
        assert [(v.file_id, v.offset_in_chunk, v.size, v.logical_offset)
                for v in views] == [("a", 50, 50, 50), ("b", 0, 50, 100)]

    def test_view_inside_remnant(self):
        # overwrite middle, then read from the tail remnant: the
        # offset_in_chunk must account for the clipped head.
        chunks = [C("a", 0, 300, 1), C("b", 100, 100, 2)]
        views = read_chunk_views(chunks, 250, 50)
        assert [(v.file_id, v.offset_in_chunk, v.size) for v in views] == \
            [("a", 250, 50)]


def test_compact_chunks():
    chunks = [C("a", 0, 100, 1), C("b", 0, 50, 2), C("c", 50, 50, 3)]
    compacted, garbage = compact_file_chunks(chunks)
    assert {c.file_id for c in compacted} == {"b", "c"}
    assert {c.file_id for c in garbage} == {"a"}


def test_total_size():
    assert total_size([]) == 0
    assert total_size([C("a", 0, 100, 1), C("b", 50, 100, 2)]) == 150


# -- store conformance -------------------------------------------------------

@pytest.fixture(params=["memory", "sqlite", "sqlite-file", "ordered_kv",
                        "sharded_kv", "redis", "sql-mysql",
                        "sql-postgres", "etcd", "elastic", "mongodb",
                        "cassandra"])
def store(request, tmp_path):
    mini = None
    if request.param == "memory":
        s = MemoryStore()
    elif request.param == "sqlite":
        s = SqliteStore()
    elif request.param == "ordered_kv":
        from seaweedfs_tpu.filer.ordered_kv import OrderedKvStore
        s = OrderedKvStore(str(tmp_path / "okv"))
    elif request.param == "sharded_kv":
        from seaweedfs_tpu.filer.ordered_kv import ShardedKvStore
        s = ShardedKvStore(str(tmp_path / "skv"), shards=4)
    elif request.param == "redis":
        from seaweedfs_tpu.filer.redis_store import RedisStore
        from _mini_redis import MiniRedis
        mini = MiniRedis()
        s = RedisStore("127.0.0.1", mini.port)
    elif request.param == "sql-mysql":
        from seaweedfs_tpu.filer.abstract_sql import (
            MysqlDialect, sqlite_validating_store)
        s = sqlite_validating_store(MysqlDialect())
    elif request.param == "sql-postgres":
        from seaweedfs_tpu.filer.abstract_sql import (
            PostgresDialect, sqlite_validating_store)
        s = sqlite_validating_store(PostgresDialect())
    elif request.param == "etcd":
        from seaweedfs_tpu.filer.etcd_store import EtcdStore
        from _mini_etcd import MiniEtcd
        mini = MiniEtcd()
        s = EtcdStore(f"127.0.0.1:{mini.port}")
    elif request.param == "elastic":
        from seaweedfs_tpu.filer.elastic_store import ElasticStore
        from _mini_es import MiniEs
        mini = MiniEs()
        s = ElasticStore(mini.url())
    elif request.param == "mongodb":
        from seaweedfs_tpu.filer.mongo_store import MongoStore
        from _mini_mongo import MiniMongo
        mini = MiniMongo()
        s = MongoStore("127.0.0.1", mini.port)
    elif request.param == "cassandra":
        from seaweedfs_tpu.filer.cassandra_store import CassandraStore
        from _mini_cassandra import MiniCassandra
        mini = MiniCassandra()
        s = CassandraStore("127.0.0.1", mini.port)
    else:
        s = SqliteStore(str(tmp_path / "filer.db"))
    yield s
    s.close()
    if mini is not None:
        mini.close()


class TestStoreConformance:
    def test_insert_find_delete(self, store):
        e = Entry(path="/a/b/c.txt", attributes=Attributes(mtime=1.0))
        store.insert_entry(e)
        got = store.find_entry("/a/b/c.txt")
        assert got.path == "/a/b/c.txt"
        assert got.attributes.mtime == 1.0
        store.delete_entry("/a/b/c.txt")
        with pytest.raises(NotFound):
            store.find_entry("/a/b/c.txt")

    def test_find_missing(self, store):
        with pytest.raises(NotFound):
            store.find_entry("/nope")

    def test_update_overwrites(self, store):
        store.insert_entry(Entry(path="/x", attributes=Attributes(uid=1)))
        store.update_entry(Entry(path="/x", attributes=Attributes(uid=2)))
        assert store.find_entry("/x").attributes.uid == 2

    def test_listing_order_and_pagination(self, store):
        names = ["a.txt", "b.txt", "c.txt", "d.txt"]
        for n in names:
            store.insert_entry(Entry(path=f"/dir/{n}"))
        store.insert_entry(Entry(path="/dir/sub", is_directory=True))
        store.insert_entry(Entry(path="/dir/sub/nested.txt"))
        got = store.list_directory_entries("/dir", "", True, 100)
        assert [e.name for e in got] == names + ["sub"]
        # pagination: resume after b.txt
        got = store.list_directory_entries("/dir", "b.txt", False, 2)
        assert [e.name for e in got] == ["c.txt", "d.txt"]
        # inclusive start
        got = store.list_directory_entries("/dir", "b.txt", True, 2)
        assert [e.name for e in got] == ["b.txt", "c.txt"]

    def test_delete_folder_children(self, store):
        store.insert_entry(Entry(path="/d", is_directory=True))
        store.insert_entry(Entry(path="/d/x"))
        store.insert_entry(Entry(path="/d/sub", is_directory=True))
        store.insert_entry(Entry(path="/d/sub/y"))
        store.insert_entry(Entry(path="/dz"))  # sibling, must survive
        store.delete_folder_children("/d")
        assert store.find_entry("/d") is not None
        assert store.find_entry("/dz") is not None
        with pytest.raises(NotFound):
            store.find_entry("/d/x")
        with pytest.raises(NotFound):
            store.find_entry("/d/sub/y")

    def test_delete_folder_children_like_metachars(self, store):
        # '_' in SQL LIKE matches any char: /a_b must not delete /axb's.
        store.insert_entry(Entry(path="/a_b", is_directory=True))
        store.insert_entry(Entry(path="/a_b/gone"))
        store.insert_entry(Entry(path="/axb", is_directory=True))
        store.insert_entry(Entry(path="/axb/kept"))
        store.insert_entry(Entry(path="/axb/sub", is_directory=True))
        store.insert_entry(Entry(path="/axb/sub/kept2"))
        store.delete_folder_children("/a_b")
        assert store.find_entry("/axb/kept") is not None
        assert store.find_entry("/axb/sub/kept2") is not None
        with pytest.raises(NotFound):
            store.find_entry("/a_b/gone")

    def test_chunks_roundtrip(self, store):
        e = Entry(path="/f", chunks=[C("3,abc123", 0, 10, 5)])
        store.insert_entry(e)
        got = store.find_entry("/f")
        assert got.chunks[0].file_id == "3,abc123"
        assert got.chunks[0].size == 10

    def test_kv(self, store):
        assert store.kv_get("k") is None
        store.kv_put("k", b"v1")
        assert store.kv_get("k") == b"v1"
        store.kv_put("k", b"v2")
        assert store.kv_get("k") == b"v2"

    def test_iterate_tree(self, store):
        for p in ("/t/a", "/t/b/c", "/t/b/d"):
            d = p.rsplit("/", 1)[0]
            parts = d.split("/")
            for i in range(2, len(parts) + 1):
                store.insert_entry(Entry(path="/".join(parts[:i]),
                                         is_directory=True))
            store.insert_entry(Entry(path=p))
        paths = {e.path for e in iterate_tree(store, "/t")}
        assert paths == {"/t", "/t/a", "/t/b", "/t/b/c", "/t/b/d"}


# -- filer core --------------------------------------------------------------

class TestFiler:
    def test_create_makes_parents(self):
        f = Filer()
        f.create_entry(Entry(path="/a/b/c/file.txt"))
        assert f.find_entry("/a").is_directory
        assert f.find_entry("/a/b/c").is_directory
        assert not f.find_entry("/a/b/c/file.txt").is_directory
        f.close()

    def test_overwrite_queues_old_chunks(self):
        deleted = []
        f = Filer(delete_file_id_fn=deleted.extend)
        f.create_entry(Entry(path="/f", chunks=[C("1,aa", 0, 10, 1)]))
        f.create_entry(Entry(path="/f", chunks=[C("1,bb", 0, 20, 2)]))
        f.flush_deletions()
        assert deleted == ["1,aa"]
        f.close()

    def test_delete_recursive_collects_chunks(self):
        deleted = []
        f = Filer(delete_file_id_fn=deleted.extend)
        f.create_entry(Entry(path="/d/x", chunks=[C("1,x", 0, 1, 1)]))
        f.create_entry(Entry(path="/d/sub/y", chunks=[C("1,y", 0, 1, 1)]))
        with pytest.raises(FilerError):
            f.delete_entry("/d")  # non-empty, not recursive
        f.delete_entry("/d", recursive=True)
        f.flush_deletions()
        assert sorted(deleted) == ["1,x", "1,y"]
        assert not f.exists("/d")
        assert not f.exists("/d/sub/y")
        f.close()

    def test_o_excl(self):
        f = Filer()
        f.create_entry(Entry(path="/f"))
        with pytest.raises(FilerError):
            f.create_entry(Entry(path="/f"), o_excl=True)
        f.close()

    def test_file_dir_conflict(self):
        f = Filer()
        f.create_entry(Entry(path="/x"))
        with pytest.raises(FilerError):
            f.create_entry(Entry(path="/x/y"))  # /x is a file
        f.close()

    def test_rename_file_and_tree(self):
        f = Filer()
        f.create_entry(Entry(path="/old/deep/f1", chunks=[C("1,a", 0, 5, 1)]))
        f.create_entry(Entry(path="/old/f2"))
        f.rename("/old", "/new")
        assert f.find_entry("/new/deep/f1").chunks[0].file_id == "1,a"
        assert f.exists("/new/f2")
        assert not f.exists("/old")
        f.close()

    def test_rename_refuses_move_under_itself(self):
        f = Filer()
        f.create_entry(Entry(path="/d/x"))
        with pytest.raises(FilerError):
            f.rename("/d", "/d/sub")
        with pytest.raises(FilerError):
            f.rename("/d", "/d")
        assert f.exists("/d/x")
        f.close()

    def test_rename_refuses_overwrite(self):
        f = Filer()
        f.create_entry(Entry(path="/a"))
        f.create_entry(Entry(path="/b"))
        with pytest.raises(FilerError):
            f.rename("/a", "/b")
        f.close()

    def test_ttl_expiry(self):
        deleted = []
        f = Filer(delete_file_id_fn=deleted.extend)
        e = Entry(path="/tmp/x", chunks=[C("1,t", 0, 1, 1)],
                  attributes=Attributes(ttl_sec=1,
                                        crtime=time.time() - 10))
        f.create_entry(e)
        assert not f.exists("/tmp/x")  # expired on read
        f.flush_deletions()
        assert deleted == ["1,t"]
        f.close()

    def test_listing_skips_expired(self):
        f = Filer()
        f.create_entry(Entry(path="/d/live"))
        f.create_entry(Entry(
            path="/d/dead",
            attributes=Attributes(ttl_sec=1, crtime=time.time() - 10)))
        names = [e.name for e in f.list_entries("/d")]
        assert names == ["live"]
        f.close()

    def test_listing_refills_page_after_expiry(self):
        # expired entries inside a page must not truncate pagination.
        f = Filer()
        expired = Attributes(ttl_sec=1, crtime=time.time() - 10)
        for i in range(4):
            f.create_entry(Entry(path=f"/p/a{i}", attributes=expired))
        for i in range(3):
            f.create_entry(Entry(path=f"/p/z{i}"))
        got = f.list_entries("/p", limit=3)
        assert [e.name for e in got] == ["z0", "z1", "z2"]
        f.close()

    def test_subscribe_replay_and_tail(self):
        f = Filer()
        f.create_entry(Entry(path="/one"))
        events = []
        unsub = f.subscribe(lambda ev: events.append(ev))
        # replayed /one (and its parent creations)
        assert any(ev.new_entry and ev.new_entry.path == "/one"
                   for ev in events)
        n = len(events)
        f.create_entry(Entry(path="/two"))
        assert len(events) > n
        assert events[-1].new_entry.path == "/two"
        unsub()
        f.create_entry(Entry(path="/three"))
        assert events[-1].new_entry.path == "/two"
        f.close()

    def test_sqlite_backed_filer(self, tmp_path):
        db = str(tmp_path / "meta.db")
        f = Filer(store=SqliteStore(db))
        f.create_entry(Entry(path="/persist/me",
                             chunks=[C("2,zz", 0, 7, 1)]))
        f.close()
        f2 = Filer(store=SqliteStore(db))
        assert f2.find_entry("/persist/me").chunks[0].file_id == "2,zz"
        f2.close()
