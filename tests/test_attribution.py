"""Time-attribution plane: per-request phase budgets (stats/phases.py),
lock-contention metering (stats/contention.py), the always-on
continuous profiler (utils/pprof.py), and cluster.profile merging.

The load-bearing invariants:

- a slow request's exemplar carries a phase budget whose non-queue
  phases sum to (approximately all of) its measured wall;
- admission-queue wait is attributed to the `queue` phase;
- a contended MeteredLock records the wait in the histogram AND the
  waiting request's `lock` phase, while /debug/locks names the holder
  and waiters with stacks;
- the disarmed/uncontended metered fast path stays cheap (the fault-
  registry stance: zero-cost when off);
- `?window=` profiles answer instantly from the ring, `?seconds=` is
  validated and clamped;
- every new instrument survives a promcheck-gated live scrape on all
  three roles;
- cluster.profile merges collapsed stacks from >= 2 distinct nodes of
  a real subprocess cluster.
"""

import os
import threading
import time

import pytest

from seaweedfs_tpu.cluster import rpc
from seaweedfs_tpu.stats import contention, phases
from seaweedfs_tpu.stats.promcheck import validate_exposition

pytestmark = pytest.mark.attribution


@pytest.fixture(scope="module", autouse=True)
def _stop_continuous_profiler():
    """The continuous profiler is a process-wide singleton: left
    running it would keep sampling (and allocating) through every
    LATER test module, skewing timing- and tracemalloc-sensitive
    tests elsewhere in the suite."""
    yield
    from seaweedfs_tpu.utils import pprof
    if pprof.PROFILER is not None:
        pprof.PROFILER.stop()


# -- phase ledger ------------------------------------------------------------

def test_phase_ledger_sums_to_wall_on_slow_request():
    """The budget invariant: named phases + the handler residual cover
    the dispatch wall, and the budget rides the /debug/slow exemplar."""
    server = rpc.JsonHttpServer()

    def slowop(q, b):
        with phases.phase("disk"):
            time.sleep(0.12)
        with phases.phase("rpc_downstream"):
            time.sleep(0.08)
        time.sleep(0.08)  # handler residual
        return {"ok": True}

    server.route("GET", "/slowop", slowop)
    server.enable_metrics("phasetest")
    server.start()
    try:
        assert rpc.call(f"http://127.0.0.1:{server.port}/slowop") == \
            {"ok": True}
        ex = server.slo.exemplars()
        assert ex, "a 0.28s request must exemplar (threshold 0.25)"
        ph = ex[0]["phases"]
        wall = ex[0]["seconds"]
        covered = sum(v for k, v in ph.items() if k != "queue")
        assert covered >= 0.9 * wall
        assert covered <= wall + 0.01
        assert 0.10 <= ph["disk"] <= 0.16
        assert 0.06 <= ph["rpc_downstream"] <= 0.12
        assert 0.06 <= ph["handler"] <= 0.14
        # The live phase sketches feed the labeled gauge.
        vals = server.slo.phase_gauge_values()
        assert ("phasetest", "/slowop", "disk", "0.99") in vals
        # ... and /debug/slo exposes them as JSON.
        snap = server.slo.snapshot()
        assert "disk" in snap["phases"]["/slowop"]
    finally:
        server.stop()


def test_queue_phase_measures_admission_wait():
    """A request that waited in the admission queue shows that wait as
    its `queue` phase — slow-because-queued must not read as
    slow-because-handler."""
    server = rpc.JsonHttpServer(
        admission=rpc.AdmissionControl(1, queue_depth=4,
                                       queue_timeout=5.0))
    server.route("GET", "/work",
                 lambda q, b: (time.sleep(0.3), {"ok": True})[1])
    server.enable_metrics("queuetest")
    server.start()
    try:
        threads = [threading.Thread(
            target=lambda: rpc.call(
                f"http://127.0.0.1:{server.port}/work", timeout=10.0))
            for _ in range(2)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        queued = [e for e in server.slo.exemplars()
                  if e["phases"].get("queue", 0.0) > 0.2]
        assert queued, server.slo.exemplars()
        # Its handler time is still the real 0.3s, separately named.
        assert queued[0]["phases"]["handler"] >= 0.25
    finally:
        server.stop()


def test_phases_disabled_kill_switch(monkeypatch):
    monkeypatch.setattr(phases, "ENABLED", False)
    server = rpc.JsonHttpServer()
    server.route("GET", "/slowop",
                 lambda q, b: (time.sleep(0.3), {"ok": True})[1])
    server.enable_metrics("killtest")
    server.start()
    try:
        rpc.call(f"http://127.0.0.1:{server.port}/slowop")
        ex = server.slo.exemplars()
        assert ex and "phases" not in ex[0]
    finally:
        server.stop()


def test_phase_context_is_noop_without_ledger():
    """Instrumented code outside any request (background daemons,
    tests) pays one thread-local read and records nothing."""
    assert phases.active() is None
    with phases.phase("disk"):
        pass
    assert phases.active() is None


# -- lock-contention metering ------------------------------------------------

def test_contended_lock_records_wait_and_debug_locks_names_holder():
    lk = contention.MeteredLock("test.contended")

    def holder():
        with lk:
            time.sleep(0.25)

    th = threading.Thread(target=holder, name="holder-thread")
    th.start()
    time.sleep(0.05)

    def waiter():
        with lk:
            pass

    tw = threading.Thread(target=waiter, name="waiter-thread")
    tw.start()
    time.sleep(0.05)
    # While held + waited on: the snapshot names both, with stacks.
    snaps = [s for s in contention.snapshot_all()
             if s["lock"] == "test.contended"]
    assert snaps and snaps[0]["holder"]["thread"] == "holder-thread"
    assert any("holder" in line for line in
               snaps[0]["holder"]["stack"])
    assert any(w.get("thread") == "waiter-thread"
               for w in snaps[0]["waiters"])
    th.join()
    tw.join()
    # The contended wait landed in the histogram (~0.2s bucket range).
    text = "\n".join(contention.lock_wait_seconds.expose())
    assert 'lock="test.contended"' in text
    assert lk.contended >= 1
    assert contention.lock_wait_seconds.count(
        lock="test.contended") >= 1
    assert contention.lock_hold_seconds.count(
        lock="test.contended") >= 1


def test_contended_lock_wait_feeds_the_request_lock_phase():
    """A request blocked on a metered lock shows the wait as `lock` in
    its exemplar — the lock histogram and the phase budget agree."""
    lk = contention.MeteredLock("test.reqlock")
    server = rpc.JsonHttpServer()

    def locked_op(q, b):
        with lk:
            time.sleep(0.01)
        return {"ok": True}

    server.route("GET", "/locked", locked_op)
    server.enable_metrics("lockphase")
    server.start()
    release = threading.Event()

    def hog():
        with lk:
            release.wait(2.0)

    th = threading.Thread(target=hog)
    th.start()
    time.sleep(0.05)
    try:
        done = threading.Event()

        def call():
            rpc.call(f"http://127.0.0.1:{server.port}/locked",
                     timeout=10.0)
            done.set()

        tc = threading.Thread(target=call)
        tc.start()
        time.sleep(0.3)
        release.set()
        tc.join()
        assert done.is_set()
        ex = server.slo.exemplars()
        assert ex, "the lock-blocked request must exemplar"
        assert ex[0]["phases"]["lock"] >= 0.2
    finally:
        release.set()
        th.join()
        server.stop()


def test_disarmed_metered_lock_is_cheap(monkeypatch):
    """The fault-registry stance: disarmed metering must be one global
    check in front of the raw lock — bounded absolute overhead, no
    histogram traffic."""
    n = 20000
    raw = threading.Lock()
    t0 = time.perf_counter()
    for _ in range(n):
        with raw:
            pass
    raw_cycle = (time.perf_counter() - t0) / n

    monkeypatch.setattr(contention, "ENABLED", False)
    lk = contention.MeteredLock("test.disarmed")
    t0 = time.perf_counter()
    for _ in range(n):
        with lk:
            pass
    disarmed_cycle = (time.perf_counter() - t0) / n
    assert lk.acquired == 0          # no armed bookkeeping ran
    # Absolute bound (generous for CI): a couple of µs per cycle, and
    # nothing observed into the histograms.
    assert disarmed_cycle < max(20 * raw_cycle, 10e-6)
    assert contention.lock_wait_seconds.count(
        lock="test.disarmed") == 0
    assert contention.lock_hold_seconds.count(
        lock="test.disarmed") == 0


def test_armed_uncontended_fast_path_bounded(monkeypatch):
    """Armed but uncontended: try-acquire + holder bookkeeping + one
    hold observation — still microseconds, never a wait-histogram
    touch."""
    monkeypatch.setattr(contention, "ENABLED", True)
    lk = contention.MeteredLock("test.uncontended")
    n = 5000
    t0 = time.perf_counter()
    for _ in range(n):
        with lk:
            pass
    cycle = (time.perf_counter() - t0) / n
    assert cycle < 50e-6
    assert lk.acquired == n and lk.contended == 0
    # The wait histogram is never touched by uncontended acquires;
    # holds are observed (hold_observe_min defaults to 0).
    assert contention.lock_wait_seconds.count(
        lock="test.uncontended") == 0
    assert contention.lock_hold_seconds.count(
        lock="test.uncontended") == n


def test_metered_rlock_reentrancy():
    import threading as th
    lk = contention.MeteredLock("test.rlock", th.RLock())
    with lk:
        with lk:
            assert lk.locked()
    assert not lk.locked()
    # Hold measured outermost-to-outermost: exactly one observation.
    text = "\n".join(contention.lock_hold_seconds.expose())
    assert 'lock="test.rlock"' in text


# -- debug surfaces ----------------------------------------------------------

def _mk_stack(tmp_path):
    os.environ["SEAWEEDFS_TPU_PPROF"] = "1"
    from seaweedfs_tpu.cluster.master import MasterServer
    from seaweedfs_tpu.cluster.volume_server import VolumeServer
    from seaweedfs_tpu.filer.server import FilerServer
    master = MasterServer(volume_size_limit_mb=64,
                          meta_dir=str(tmp_path))
    master.start()
    vs = VolumeServer(master.url(), [str(tmp_path / "vs")],
                      pulse_seconds=60)
    vs.start()
    filer = FilerServer(master.url())
    filer.start()
    return master, vs, filer


def test_debug_locks_and_promcheck_all_roles(tmp_path):
    """Live-scrape gate: /debug/locks answers on every role and every
    new instrument (phase gauge, lock histograms, runnable gauge)
    survives promcheck on master, volume server, and filer."""
    master, vs, filer = _mk_stack(tmp_path)
    try:
        import urllib.request
        # Traffic so phase sketches and lock holds have data.
        urllib.request.urlopen(urllib.request.Request(
            f"{filer.url()}/f.txt", data=b"x" * 2048, method="POST"),
            timeout=30).read()
        urllib.request.urlopen(f"{filer.url()}/f.txt",
                               timeout=30).read()
        for base in (master.url(), f"http://{vs.url()}"):
            locks = rpc.call(f"{base}/debug/locks")
            assert locks["metering"] is True
            names = {row["lock"] for row in locks["locks"]}
            assert "rpc.pool" in names  # client plane is shared
        # volume server saw a write -> its write lock is registered
        vs_locks = rpc.call(f"http://{vs.url()}/debug/locks")
        names = {row["lock"] for row in vs_locks["locks"]}
        assert "volume.write" in names
        scrapes = {
            "master": rpc.call(f"{master.url()}/metrics").decode(),
            "volume": rpc.call(f"http://{vs.url()}/metrics").decode(),
            "filer": filer.metrics_registry.expose(),
        }
        for role, text in scrapes.items():
            probs = validate_exposition(text)
            assert not probs, (role, probs[:5])
            assert "SeaweedFS_lock_wait_seconds" in text, role
            assert "SeaweedFS_lock_hold_seconds" in text, role
            assert "SeaweedFS_runnable_threads" in text, role
            assert "SeaweedFS_request_phase_seconds" in text, role
        # The volume server's scrape carries real hold samples for the
        # write path (value present, histogram well-formed per above).
        assert 'lock="volume.write"' in scrapes["volume"]
    finally:
        filer.stop()
        vs.stop()
        master.stop()
        os.environ.pop("SEAWEEDFS_TPU_PPROF", None)


def test_profile_window_serves_instantly_and_profile_is_exempt(
        tmp_path):
    """?window= answers from the always-on ring without sampling, and
    a profile of a saturated server is admission-exempt — profiling
    must work exactly when the lanes are full."""
    os.environ["SEAWEEDFS_TPU_PPROF_WINDOW"] = "0.3"
    server = rpc.JsonHttpServer(
        admission=rpc.AdmissionControl(1, queue_depth=0,
                                       queue_timeout=0.1))
    os.environ["SEAWEEDFS_TPU_PPROF"] = "1"
    try:
        from seaweedfs_tpu.utils import pprof
        pprof.enable_pprof_routes(server)
        prof = pprof.ensure_continuous_profiler()
        release = threading.Event()
        server.route("GET", "/hog",
                     lambda q, b: (release.wait(10.0), {"ok": 1})[1])
        server.start()
        base = f"http://127.0.0.1:{server.port}"
        hog = threading.Thread(
            target=lambda: rpc.call(f"{base}/hog", timeout=30.0))
        hog.start()
        time.sleep(0.6)  # lane now occupied; ring has >= 1 window
        try:
            t0 = time.perf_counter()
            body = rpc.call(f"{base}/debug/pprof/profile?window=5")
            elapsed = time.perf_counter() - t0
            assert elapsed < 1.0, "ring reads must not sample"
            assert b"samples" in body
            assert prof.running
        finally:
            release.set()
            hog.join()
    finally:
        server.stop()
        os.environ.pop("SEAWEEDFS_TPU_PPROF", None)
        os.environ.pop("SEAWEEDFS_TPU_PPROF_WINDOW", None)


def test_runtime_attribution_toggle():
    """POST /debug/attribution?enabled=0|1 arms/disarms the whole
    plane restart-free — the overhead bench's A/B lever and the
    operator's rule-it-out switch."""
    server = rpc.JsonHttpServer()
    contention.setup_contention_routes(server)
    server.route("GET", "/slowop",
                 lambda q, b: (time.sleep(0.3), {"ok": True})[1])
    server.enable_metrics("toggletest")
    server.start()
    base = f"http://127.0.0.1:{server.port}"
    try:
        out = rpc.call(f"{base}/debug/attribution?enabled=0", "POST")
        assert out["phases"] is False and out["lock_meter"] is False
        assert not phases.ENABLED and not contention.ENABLED
        rpc.call(f"{base}/slowop")
        assert "phases" not in server.slo.exemplars()[0]
        out = rpc.call(f"{base}/debug/attribution?enabled=1", "POST")
        assert out["phases"] is True and out["lock_meter"] is True
        rpc.call(f"{base}/slowop")
        assert "phases" in server.slo.exemplars()[0]
        locks = rpc.call(f"{base}/debug/locks")
        assert locks["metering"] is True
    finally:
        server.stop()
        contention.set_plane_enabled(True)


# -- cluster.profile ---------------------------------------------------------

def test_cluster_profile_merges_across_subprocess_cluster(tmp_path):
    """The acceptance shape: a real 3-node subprocess cluster (master
    + 2 volume servers), one cluster.profile, merged collapsed stacks
    with frames from >= 2 distinct nodes, written via -o."""
    import subprocess
    import sys
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               SEAWEEDFS_TPU_PPROF="1",
               SEAWEEDFS_TPU_PPROF_WINDOW="1")
    procs = []
    mport = rpc.free_port()
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    def spawn(args):
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "seaweedfs_tpu"] + args, env=env,
            cwd=repo, stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL))

    spawn(["master", f"-port={mport}", f"-mdir={tmp_path}/meta"])
    vports = []
    for i in range(2):
        vport = rpc.free_port()
        os.makedirs(f"{tmp_path}/vs{i}")
        spawn(["volume", f"-port={vport}", f"-dir={tmp_path}/vs{i}",
               "-max=10", f"-mserver=127.0.0.1:{mport}"])
        vports.append(vport)
    try:
        deadline = time.time() + 60
        want = [f"http://127.0.0.1:{p}" for p in [mport] + vports]
        for url in want:
            while True:
                try:
                    rpc.call_status(f"{url}/debug/locks", timeout=2.0)
                    break
                except Exception:  # noqa: BLE001 — still starting
                    if time.time() > deadline:
                        raise TimeoutError(f"{url} never came up") \
                            from None
                    time.sleep(0.2)
        from seaweedfs_tpu.shell.command_profile import (
            ClusterProfile, merge_cluster_profile, parse_collapsed,
            strip_node_frames)
        merged, nodes = merge_cluster_profile(want, seconds=0.5)
        assert len(nodes) == 3
        prefixes = {s.split(";", 1)[0] for s in merged}
        assert len([p for p in prefixes if p.startswith("node:")]) >= 2
        # Through the shell command with -o, against the master env.
        from seaweedfs_tpu.shell.env import CommandEnv
        out_file = tmp_path / "cluster.collapsed"
        cenv = CommandEnv(f"http://127.0.0.1:{mport}")
        text = ClusterProfile().do(
            ["-seconds", "0.5", "-o", str(out_file)], cenv)
        assert "node(s)" in text
        saved = parse_collapsed(out_file.read_text())
        assert saved, "collapsed output must round-trip"
        node_frames = {s.split(";", 1)[0] for s in saved}
        assert len([p for p in node_frames
                    if p.startswith("node:")]) >= 2
        # -diff against itself: near-zero movement, command succeeds.
        diff_text = ClusterProfile().do(
            ["-window", "2", "-diff", str(out_file)], cenv)
        assert "DELTA" in diff_text or "no stack-share" in diff_text
        assert strip_node_frames(saved)
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
