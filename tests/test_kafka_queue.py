"""Kafka wire-protocol queue against an in-process fake broker.

The fake broker speaks real framed Kafka over TCP (Metadata v1,
Produce v3, Fetch v4) and stores the record batches it receives, so the
client is exercised through actual sockets and the actual byte formats.
The record-batch encoder is additionally pinned by a golden-bytes test
derived from the protocol spec, so encode/decode aren't just verified
against each other.
"""

import json
import socket
import struct
import threading

import pytest

from seaweedfs_tpu.core.crc import crc32c
from seaweedfs_tpu.replication.kafka import (KafkaQueue,
                                             decode_record_batches,
                                             encode_record_batch)


# -- record batch format ----------------------------------------------------

def test_record_batch_golden_bytes():
    """Spec-derived expected bytes for one record (key=b'k', value=b'v'):
    KIP-98 record batch v2 layout, computed by hand here with plain
    struct packing — independent of the library's writer helpers."""
    got = encode_record_batch([(b"k", b"v")])
    # record: attrs(0) tsDelta(0) offDelta(0) keyLen(1) 'k' valLen(1)
    # 'v' headers(0) — varints are zigzag, so 1 encodes as 0x02
    record = bytes([0, 0x00, 0x00, 0x02, ord("k"), 0x02, ord("v"), 0x00])
    body = (struct.pack(">h", 0)            # attributes
            + struct.pack(">i", 0)          # lastOffsetDelta
            + struct.pack(">q", 0)          # baseTimestamp
            + struct.pack(">q", 0)          # maxTimestamp
            + struct.pack(">q", -1)         # producerId
            + struct.pack(">h", -1)         # producerEpoch
            + struct.pack(">i", -1)         # baseSequence
            + struct.pack(">i", 1)          # record count
            + bytes([len(record) << 1])     # record length varint
            + record)
    expect = (struct.pack(">q", 0)                    # baseOffset
              + struct.pack(">i", 9 + len(body))      # batchLength
              + struct.pack(">i", -1)                 # leaderEpoch
              + bytes([2])                            # magic
              + struct.pack(">I", crc32c(body))       # CRC32-C
              + body)
    assert got == expect


def test_record_batch_roundtrip_multi():
    recs = [(b"a", b"v1"), (None, b"v2"), (b"c" * 200, b"v" * 5000)]
    buf = encode_record_batch(recs, base_ts_ms=123)
    out = decode_record_batches(buf)
    assert [(k, v) for _o, k, v in out] == recs
    assert [o for o, _k, _v in out] == [0, 1, 2]


def test_record_batch_crc_tamper_detected():
    buf = bytearray(encode_record_batch([(b"k", b"v")]))
    buf[-1] ^= 1
    with pytest.raises(ValueError, match="CRC"):
        decode_record_batches(bytes(buf))


def test_truncated_tail_batch_ignored():
    full = encode_record_batch([(b"k", b"v1")])
    partial = encode_record_batch([(b"k", b"v2")])[:-3]
    out = decode_record_batches(full + partial)
    assert [(k, v) for _o, k, v in out] == [(b"k", b"v1")]


# -- fake broker ------------------------------------------------------------

class FakeBroker:
    """In-memory Kafka speaking Metadata v1 / Produce v3 / Fetch v4 /
    ListOffsets v1 over real TCP; N partitions, partition 0 exposed via
    the legacy single-partition attributes the older tests use."""

    def __init__(self, n_partitions: int = 1):
        self.sock = socket.socket()
        self.sock.bind(("127.0.0.1", 0))
        self.sock.listen(8)
        self.port = self.sock.getsockname()[1]
        self.n_partitions = n_partitions
        # per-partition stores; partition 0 aliased by legacy attrs
        self.plogs = {p: [] for p in range(n_partitions)}
        self.pbases = {p: [] for p in range(n_partitions)}
        self.pnext = {p: 0 for p in range(n_partitions)}
        self.plog_start = {p: 0 for p in range(n_partitions)}
        self.produce_count = 0
        self._stop = False
        threading.Thread(target=self._serve, daemon=True).start()

    # legacy single-partition views (partition 0)
    @property
    def log(self):
        return self.plogs[0]

    @property
    def base_offsets(self):
        return self.pbases[0]

    @property
    def next_offset(self):
        return self.pnext[0]

    @next_offset.setter
    def next_offset(self, v):
        self.pnext[0] = v

    @property
    def log_start(self):
        return self.plog_start[0]

    @log_start.setter
    def log_start(self, v):
        self.plog_start[0] = v

    def _serve(self):
        while not self._stop:
            try:
                conn, _ = self.sock.accept()
            except OSError:
                return
            threading.Thread(target=self._client, args=(conn,),
                             daemon=True).start()

    def _client(self, conn):
        try:
            while True:
                head = self._read(conn, 4)
                if not head:
                    return
                (size,) = struct.unpack(">i", head)
                req = self._read(conn, size)
                api, ver, corr = struct.unpack(">hhi", req[:8])
                (cid_len,) = struct.unpack(">h", req[8:10])
                body = req[10 + cid_len:]
                if api == 3:
                    resp = self._metadata(ver)
                elif api == 0:
                    resp = self._produce(body)
                elif api == 1:
                    resp = self._fetch(body)
                elif api == 2:
                    resp = self._list_offsets(body)
                else:
                    return
                out = struct.pack(">i", corr) + resp
                conn.sendall(struct.pack(">i", len(out)) + out)
        except (ConnectionError, OSError):
            pass
        finally:
            conn.close()

    @staticmethod
    def _read(conn, n):
        out = b""
        while len(out) < n:
            piece = conn.recv(n - len(out))
            if not piece:
                return b""
            out += piece
        return out

    @staticmethod
    def _str(s):
        raw = s.encode()
        return struct.pack(">h", len(raw)) + raw

    def _metadata(self, ver):
        b = b""
        b += struct.pack(">i", 1)                      # 1 broker
        b += struct.pack(">i", 7)                      # node id
        b += self._str("127.0.0.1")
        b += struct.pack(">i", self.port)
        b += struct.pack(">h", -1)                     # rack (null)
        b += struct.pack(">i", 7)                      # controller
        b += struct.pack(">i", 1)                      # 1 topic
        b += struct.pack(">h", 0)                      # no error
        b += self._str("events")
        b += bytes([0])                                # not internal
        b += struct.pack(">i", self.n_partitions)
        for pid in range(self.n_partitions):
            b += struct.pack(">h", 0)
            b += struct.pack(">i", pid)
            b += struct.pack(">i", 7)                  # leader = us
            b += struct.pack(">i", 1) + struct.pack(">i", 7)  # replicas
            b += struct.pack(">i", 1) + struct.pack(">i", 7)  # isr
        return b

    def _produce(self, body):
        # transactional_id, acks, timeout, 1 topic, name, 1 part, id, batch
        off = 0
        (tid_len,) = struct.unpack_from(">h", body, off)
        off += 2 + max(0, tid_len)
        off += 2 + 4 + 4   # acks, timeout, topic count
        (tlen,) = struct.unpack_from(">h", body, off)
        off += 2 + tlen
        off += 4           # partition count
        (pid,) = struct.unpack_from(">i", body, off)
        off += 4
        (blen,) = struct.unpack_from(">i", body, off)
        off += 4
        batch = bytearray(body[off:off + blen])
        n_records = len(decode_record_batches(bytes(batch)))
        base = self.pnext[pid]
        batch[0:8] = struct.pack(">q", base)  # broker assigns offsets
        self.plogs[pid].append(bytes(batch))
        self.pbases[pid].append(base)
        self.pnext[pid] += n_records
        self.produce_count += 1
        resp = struct.pack(">i", 1) + self._str("events")
        resp += struct.pack(">i", 1)
        resp += struct.pack(">i", 0)          # partition
        resp += struct.pack(">h", 0)          # no error
        resp += struct.pack(">q", base)       # base offset
        resp += struct.pack(">q", -1)         # log append time
        resp += struct.pack(">i", 0)          # throttle
        return resp

    def _fetch(self, body):
        # replica, max_wait, min_bytes, max_bytes, isolation,
        # topics(1), name, parts(1), id, fetch_offset, part_max
        off = 4 + 4 + 4 + 4 + 1 + 4
        (tlen,) = struct.unpack_from(">h", body, off)
        off += 2 + tlen + 4
        (pid,) = struct.unpack_from(">i", body, off)
        off += 4
        (fetch_offset,) = struct.unpack_from(">q", body, off)
        if fetch_offset < self.plog_start[pid]:
            resp = struct.pack(">i", 0)
            resp += struct.pack(">i", 1) + self._str("events")
            resp += struct.pack(">i", 1)
            resp += struct.pack(">i", pid)
            resp += struct.pack(">h", 1)      # OFFSET_OUT_OF_RANGE
            resp += struct.pack(">q", -1) + struct.pack(">q", -1)
            resp += struct.pack(">i", 0)
            resp += struct.pack(">i", 0)
            return resp
        # include the batch containing fetch_offset (broker semantics:
        # return from the containing batch onward)
        records = b"".join(
            batch for batch, base in zip(self.plogs[pid],
                                         self.pbases[pid])
            if base + len(decode_record_batches(batch)) > fetch_offset)
        resp = struct.pack(">i", 0)           # throttle
        resp += struct.pack(">i", 1) + self._str("events")
        resp += struct.pack(">i", 1)
        resp += struct.pack(">i", pid)        # partition
        resp += struct.pack(">h", 0)          # no error
        resp += struct.pack(">q", self.pnext[pid])  # high watermark
        resp += struct.pack(">q", self.pnext[pid])  # last stable
        resp += struct.pack(">i", 0)          # aborted txns
        resp += struct.pack(">i", len(records)) + records
        return resp

    def _list_offsets(self, body):
        off = 4 + 4
        (tlen,) = struct.unpack_from(">h", body, off)
        off += 2 + tlen + 4
        (pid,) = struct.unpack_from(">i", body, off)
        resp = struct.pack(">i", 1) + self._str("events")
        resp += struct.pack(">i", 1)
        resp += struct.pack(">i", pid)        # partition
        resp += struct.pack(">h", 0)          # no error
        resp += struct.pack(">q", -1)         # timestamp
        resp += struct.pack(">q", self.plog_start[pid])
        return resp

    def close(self):
        self._stop = True
        try:
            self.sock.close()
        except OSError:
            pass


@pytest.fixture
def broker():
    b = FakeBroker()
    yield b
    b.close()


def test_kafka_publish_consume_roundtrip(broker, tmp_path):
    q = KafkaQueue(f"127.0.0.1:{broker.port}", "events",
                   offset_path=str(tmp_path / "off"))
    q.publish("/a.txt", {"op": "create"})
    q.publish("/b.txt", {"op": "delete"})
    assert broker.produce_count == 2
    got = []
    q.consume(lambda k, m: got.append((k, m)))
    assert got == [("/a.txt", {"op": "create"}),
                   ("/b.txt", {"op": "delete"})]
    # checkpoint: a fresh consumer instance resumes past delivered msgs
    q2 = KafkaQueue(f"127.0.0.1:{broker.port}", "events",
                    offset_path=str(tmp_path / "off"))
    q2.publish("/c.txt", {"op": "create"})
    got2 = []
    q2.consume(lambda k, m: got2.append(k))
    assert got2 == ["/c.txt"]
    q.close()
    q2.close()


def test_kafka_queue_spec(broker):
    from seaweedfs_tpu.replication.notification import queue_for_spec
    q = queue_for_spec(f"kafka://127.0.0.1:{broker.port}/events")
    assert isinstance(q, KafkaQueue) and q.topic == "events"
    q.publish("/x", {"n": 1})
    got = []
    q.consume(lambda k, m: got.append((k, m)))
    assert got == [("/x", {"n": 1})]
    q.close()


def test_kafka_poison_record_skipped(broker):
    """A record without the envelope advances the offset instead of
    wedging every future consume."""
    q = KafkaQueue(f"127.0.0.1:{broker.port}", "events")
    batch = encode_record_batch([(b"k", b"not json at all")])
    broker.log.append(batch)
    broker.base_offsets.append(broker.next_offset)
    broker.next_offset += 1
    q.publish("/good", {"n": 2})
    got = []
    q.consume(lambda k, m: got.append(k))
    assert got == ["/good"]
    q.close()


def test_kafka_offset_out_of_range_resets_to_log_start(broker):
    """Retention truncated below the checkpoint: the consumer must
    resume from the earliest retained offset, not raise forever."""
    q = KafkaQueue(f"127.0.0.1:{broker.port}", "events")
    q.publish("/old", {"n": 0})
    q.publish("/new", {"n": 1})
    # simulate retention reaping the first batch
    broker.log.pop(0)
    broker.base_offsets.pop(0)
    broker.log_start = 1
    got = []
    q.consume(lambda k, m: got.append(k))   # offset 0 -> err 1 -> reset
    assert got == ["/new"]
    q.close()


def test_tombstone_record_decoded_as_none(broker):
    """Null-value records (compacted-topic deletes) decode to
    value=None and are skipped by consume without wedging."""
    import struct as _s
    from seaweedfs_tpu.replication.kafka import (_w_varint, _w_i8,
                                                 _w_i16, _w_i32,
                                                 _w_i64)
    # hand-build a batch with one tombstone record (value length -1)
    rec = bytearray()
    _w_i8(rec, 0)
    _w_varint(rec, 0)
    _w_varint(rec, 0)
    _w_varint(rec, 1)
    rec += b"k"
    _w_varint(rec, -1)        # null value
    _w_varint(rec, 0)
    body = bytearray()
    _w_i16(body, 0)
    _w_i32(body, 0)
    _w_i64(body, 0)
    _w_i64(body, 0)
    _w_i64(body, -1)
    _w_i16(body, -1)
    _w_i32(body, -1)
    _w_i32(body, 1)
    _w_varint(body, len(rec))
    body += rec
    batch = bytearray()
    _w_i64(batch, 0)
    _w_i32(batch, 9 + len(body))
    _w_i32(batch, -1)
    _w_i8(batch, 2)
    batch += _s.pack(">I", crc32c(bytes(body)))
    batch += body
    out = decode_record_batches(bytes(batch))
    assert out == [(0, b"k", None)]
    # consume skips it and continues to real messages
    broker.log.append(bytes(batch))
    broker.base_offsets.append(broker.next_offset)
    broker.next_offset += 1
    q = KafkaQueue(f"127.0.0.1:{broker.port}", "events")
    q.publish("/after-tombstone", {"n": 1})
    got = []
    q.consume(lambda k, m: got.append(k))
    assert got == ["/after-tombstone"]
    q.close()


def test_gzip_compressed_batch_from_foreign_producer():
    """codec=1 (gzip) batches decode via stdlib; snappy still refuses."""
    import gzip as _gzip
    import struct as _s
    from seaweedfs_tpu.replication.kafka import (_w_varint, _w_i8,
                                                 _w_i16, _w_i32,
                                                 _w_i64)
    rec = bytearray()
    _w_i8(rec, 0)
    _w_varint(rec, 0)
    _w_varint(rec, 0)
    _w_varint(rec, 2)
    rec += b"kk"
    _w_varint(rec, 5)
    rec += b"value"
    _w_varint(rec, 0)
    framed = bytearray()
    _w_varint(framed, len(rec))
    framed += rec

    def build(codec, records_blob):
        body = bytearray()
        _w_i16(body, codec)
        _w_i32(body, 0)
        _w_i64(body, 0)
        _w_i64(body, 0)
        _w_i64(body, -1)
        _w_i16(body, -1)
        _w_i32(body, -1)
        _w_i32(body, 1)
        body += records_blob
        batch = bytearray()
        _w_i64(batch, 0)
        _w_i32(batch, 9 + len(body))
        _w_i32(batch, -1)
        _w_i8(batch, 2)
        batch += _s.pack(">I", crc32c(bytes(body)))
        batch += body
        return bytes(batch)

    out = decode_record_batches(build(1, _gzip.compress(bytes(framed))))
    assert out == [(0, b"kk", b"value")]
    with pytest.raises(ValueError, match="codec 2"):
        decode_record_batches(build(2, bytes(framed)))


def test_kafka_multi_partition_publish_and_drain(tmp_path):
    """Keys route to partitions by CRC32-C; consume drains ALL
    partitions (the old client silently ignored everything but 0)."""
    broker = FakeBroker(n_partitions=4)
    try:
        q = KafkaQueue(f"127.0.0.1:{broker.port}", "events",
                       offset_path=str(tmp_path / "off.json"))
        keys = [f"/dir/file-{i}.txt" for i in range(40)]
        for k in keys:
            q.publish(k, {"k": k})
        used = {p for p in range(4) if broker.plogs[p]}
        assert len(used) > 1, "hash routing never left partition 0"
        got = []
        q.consume(lambda k, m: got.append(k))
        assert sorted(got) == sorted(keys)
        # same key always lands on the same partition (ordering)
        q.publish(keys[0], {"k": "again"})
        target = [p for p in range(4)
                  if any(b"again" in blob for blob in broker.plogs[p])]
        from seaweedfs_tpu.core.crc import crc32c as _crc
        assert target == [_crc(keys[0].encode()) % 4]
        # per-partition offsets persisted as JSON; a new consumer
        # resumes cleanly
        q2 = KafkaQueue(f"127.0.0.1:{broker.port}", "events",
                        offset_path=str(tmp_path / "off.json"))
        got2 = []
        q2.consume(lambda k, m: got2.append(k))
        assert got2 == [keys[0]]
        q.close()
        q2.close()
    finally:
        broker.close()
