"""Native C++ coder + CRC must match the pure-Python oracles byte-for-byte.

Skipped when native/libseaweed_native.so hasn't been built.
"""

import numpy as np
import pytest

from seaweedfs_tpu.core.crc import _crc32c_py
from seaweedfs_tpu.ops.coder_numpy import NumpyCoder
from seaweedfs_tpu.utils import native as native_mod

pytestmark = pytest.mark.skipif(native_mod.load() is None,
                                reason="native library not built")


def test_native_crc_matches_python():
    lib = native_mod.load()
    fn = native_mod.crc32c_fn(lib)
    assert fn(b"123456789") == 0xE3069283
    rng = np.random.default_rng(0)
    for size in (0, 1, 7, 8, 9, 1000, 4096):
        data = rng.integers(0, 256, size).astype(np.uint8).tobytes()
        assert fn(data) == _crc32c_py(data), size
    # incremental
    data = rng.integers(0, 256, 1000).astype(np.uint8).tobytes()
    assert fn(data[500:], fn(data[:500])) == fn(data)


def test_native_coder_matches_numpy():
    from seaweedfs_tpu.ops.coder_native import NativeCoder
    nc, oc = NativeCoder(10, 4), NumpyCoder(10, 4)
    data = np.random.default_rng(1).integers(
        0, 256, (10, 12345)).astype(np.uint8)
    assert np.array_equal(nc.encode(data), oc.encode(data))
    shards = oc.encode_all(data)
    lost = (1, 6, 10, 13)
    have = {i: shards[i] for i in range(14) if i not in lost}
    rec = nc.reconstruct(have)
    for sid in lost:
        assert np.array_equal(rec[sid], shards[sid])
    assert nc.verify(shards)


def test_native_alt_scheme():
    from seaweedfs_tpu.ops.coder_native import NativeCoder
    nc, oc = NativeCoder(8, 3), NumpyCoder(8, 3)
    data = np.random.default_rng(2).integers(
        0, 256, (8, 4096)).astype(np.uint8)
    assert np.array_equal(nc.encode(data), oc.encode(data))


def test_native_sanitizer_harness():
    """SURVEY §5: sanitizer builds for the C++ host kernels.  Builds
    the standalone harness with -fsanitize=address,undefined and runs
    it (crc vectors, gf_mul_add/gf_mix vs scalar reference at
    tail-stressing lengths)."""
    import os
    import shutil
    import subprocess
    if shutil.which("g++") is None:
        pytest.skip("g++ not available")
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run(["make", "asan-test"],
                         cwd=os.path.join(root, "native"),
                         capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "native sanitizer harness OK" in out.stdout
