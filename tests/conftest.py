"""Test configuration: hermetic 8-device virtual CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding/collective logic is
exercised on a virtual CPU mesh exactly as the driver's `dryrun_multichip`
does.  Two things make the suite hermetic:

1. JAX_PLATFORMS / XLA_FLAGS are forced (not defaulted — the environment
   ships JAX_PLATFORMS=axon for the real chip) before jax initializes.
2. The `axon` PJRT plugin (registered by sitecustomize at interpreter
   startup) is dropped from jax's backend-factory registry; otherwise
   jax.devices() would dial the TPU tunnel from every test process, which
   both serializes on the single chip grant and hangs when the tunnel is
   busy.  Tests must never depend on the real chip.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

try:
    import jax
    import jax._src.xla_bridge as _xb

    _xb._backend_factories.pop("axon", None)
    # sitecustomize imported jax before this conftest ran, so the
    # jax_platforms config already latched "axon"; point it back at cpu.
    jax.config.update("jax_platforms", "cpu")
except Exception:
    pass
