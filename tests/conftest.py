"""Test configuration: hermetic 8-device virtual CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding/collective logic is
exercised on a virtual CPU mesh exactly as the driver's `dryrun_multichip`
does.  force_cpu also unregisters the axon TPU plugin that sitecustomize
installs, so pytest never dials the TPU tunnel (which would serialize on
the single chip grant and hang while it's held).  Tests must never depend
on the real chip.
"""

import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from seaweedfs_tpu.utils.jaxenv import force_cpu  # noqa: E402

force_cpu(device_count=8)


def pytest_report_header(config):
    """Session-scoped transport toggle: SEAWEEDFS_TPU_TRANSPORT=aio
    runs every in-process AND subprocess JsonHttpServer in the suite on
    the netcore event loop (cluster/rpc.py default_transport); unset or
    "threads" is the thread-per-connection baseline.  Surfaced in the
    header so a CI log always says which transport a run exercised."""
    t = os.environ.get("SEAWEEDFS_TPU_TRANSPORT", "") or "threads"
    return f"seaweedfs_tpu transport: {t}"


@pytest.fixture(autouse=True)
def _hermetic_resilience_state():
    """Per-host circuit breakers are process-global and keyed by
    host:port; free_port() can re-issue a port a previous test drove
    into the open state.  Start every test with clean breakers (and
    leave no armed fault points behind) so failure-handling tests stay
    order-independent.  The filer chunk cache is process-global and
    keyed by fid — a fresh cluster in the next test could mint a
    colliding fid, so it resets too."""
    from seaweedfs_tpu import fault
    from seaweedfs_tpu.cluster import resilience
    from seaweedfs_tpu.stats import flows
    from seaweedfs_tpu.storage import chunk_cache
    resilience.reset_breakers()
    chunk_cache.CACHE.reset()
    # The wire-flow ledger is process-global; rows from one test's
    # cluster must not leak into the next test's conservation math.
    flows.LEDGER.reset()
    yield
    fault.disarm_all()
    resilience.reset_breakers()
    chunk_cache.CACHE.reset()
    # Tests that shrink the shared cache (streaming-memory bounds) must
    # not leak the smaller budget into the next test.
    chunk_cache.CACHE.max_bytes = chunk_cache.FilerChunkCache().max_bytes
