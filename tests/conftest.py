"""Test configuration: hermetic 8-device virtual CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding/collective logic is
exercised on a virtual CPU mesh exactly as the driver's `dryrun_multichip`
does.  force_cpu also unregisters the axon TPU plugin that sitecustomize
installs, so pytest never dials the TPU tunnel (which would serialize on
the single chip grant and hang while it's held).  Tests must never depend
on the real chip.
"""

import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from seaweedfs_tpu.utils.jaxenv import force_cpu  # noqa: E402

force_cpu(device_count=8)


@pytest.fixture(autouse=True)
def _hermetic_resilience_state():
    """Per-host circuit breakers are process-global and keyed by
    host:port; free_port() can re-issue a port a previous test drove
    into the open state.  Start every test with clean breakers (and
    leave no armed fault points behind) so failure-handling tests stay
    order-independent."""
    from seaweedfs_tpu import fault
    from seaweedfs_tpu.cluster import resilience
    resilience.reset_breakers()
    yield
    fault.disarm_all()
    resilience.reset_breakers()
