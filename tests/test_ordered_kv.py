"""Durability and engine semantics of the embedded ordered-KV store
(seaweedfs_tpu/filer/ordered_kv.py — the leveldb-analog default store;
conformance with the FilerStore contract is covered by the parametric
suite in test_filer.py::TestStoreConformance)."""

import os

import pytest

from seaweedfs_tpu.filer.entry import Attributes, Entry
from seaweedfs_tpu.filer.filerstore import store_for_path
from seaweedfs_tpu.filer.ordered_kv import OrderedKv, OrderedKvStore


def test_reopen_recovers_from_wal(tmp_path):
    kv = OrderedKv(str(tmp_path))
    kv.put(b"a", b"1")
    kv.put(b"b", b"2")
    kv.put(b"a", b"3")
    kv.delete(b"b")
    kv.close()
    kv2 = OrderedKv(str(tmp_path))
    assert kv2.get(b"a") == b"3"
    assert kv2.get(b"b") is None
    kv2.close()


def test_reopen_without_close_simulates_crash(tmp_path):
    kv = OrderedKv(str(tmp_path))
    for i in range(100):
        kv.put(f"k{i:03d}".encode(), b"v" * 10)
    # no close(): the WAL was flushed per append, a crashed process
    # leaves exactly these bytes behind
    kv2 = OrderedKv(str(tmp_path))
    assert kv2.get(b"k099") == b"v" * 10
    assert len(kv2.scan(b"", b"\xff")) == 100
    kv2.close()
    kv.close()


def test_torn_tail_is_dropped(tmp_path):
    kv = OrderedKv(str(tmp_path))
    kv.put(b"good", b"yes")
    kv.close()
    with open(tmp_path / "kv.wal", "ab") as f:
        f.write(b"\x13\x37garbage-torn-record")
    kv2 = OrderedKv(str(tmp_path))
    assert kv2.get(b"good") == b"yes"
    # and the torn bytes are gone so new appends stay parseable
    kv2.put(b"after", b"tear")
    kv2.close()
    kv3 = OrderedKv(str(tmp_path))
    assert kv3.get(b"after") == b"tear"
    kv3.close()


def test_compaction_snapshot_and_reopen(tmp_path):
    kv = OrderedKv(str(tmp_path), compact_min_bytes=1)
    for i in range(50):
        kv.put(b"key", f"value-{i}".encode())  # 49 dead versions
    kv.put(b"other", b"x")
    kv.compact()
    assert os.path.getsize(tmp_path / "kv.wal") == 0
    assert os.path.getsize(tmp_path / "kv.snap") > 0
    kv.put(b"post", b"snap")
    kv.close()
    kv2 = OrderedKv(str(tmp_path))
    assert kv2.get(b"key") == b"value-49"
    assert kv2.get(b"other") == b"x"
    assert kv2.get(b"post") == b"snap"
    kv2.close()


def test_scan_range_and_limit(tmp_path):
    kv = OrderedKv(str(tmp_path))
    for ch in "fbdace":
        kv.put(ch.encode(), ch.upper().encode())
    rows = kv.scan(b"b", b"e")
    assert [k for k, _ in rows] == [b"b", b"c", b"d"]
    assert [k for k, _ in kv.scan(b"", b"\xff", limit=2)] == [b"a", b"b"]
    kv.close()


def test_store_reopen_keeps_namespace(tmp_path):
    d = str(tmp_path / "fstore")
    s = OrderedKvStore(d)
    for name in ("a.txt", "b.txt"):
        s.insert_entry(Entry(path=f"/docs/{name}",
                             attributes=Attributes(mtime=1.0)))
    s.kv_put("checkpoint", b"123")
    s.close()
    s2 = OrderedKvStore(d)
    assert s2.find_entry("/docs/a.txt").path == "/docs/a.txt"
    assert [e.name for e in
            s2.list_directory_entries("/docs", "", True, 10)] == \
        ["a.txt", "b.txt"]
    assert s2.kv_get("checkpoint") == b"123"
    s2.close()


def test_sibling_prefix_not_deleted(tmp_path):
    """/ab must survive delete_folder_children(/a) — the range-bound
    subtlety the key layout is designed around."""
    s = OrderedKvStore(str(tmp_path / "s"))
    s.insert_entry(Entry(path="/a/x", attributes=Attributes()))
    s.insert_entry(Entry(path="/a/sub/y", attributes=Attributes()))
    s.insert_entry(Entry(path="/ab", attributes=Attributes()))
    s.delete_folder_children("/a")
    assert s.find_entry("/ab")
    for gone in ("/a/x", "/a/sub/y"):
        try:
            s.find_entry(gone)
            raise AssertionError(f"{gone} survived")
        except Exception:
            pass
    s.close()


def test_bisect_fallback_engine(tmp_path, monkeypatch):
    """Without sortedcontainers the store falls back to the bisect
    index and behaves identically (incl. durability)."""
    import seaweedfs_tpu.filer.ordered_kv as okv
    monkeypatch.setattr(okv, "SortedDict", None)
    kv = okv.OrderedKv(str(tmp_path))
    assert isinstance(kv._m, okv._BisectDict)
    for ch in "dbca":
        kv.put(ch.encode(), ch.encode())
    kv.put(b"b", b"B2")
    kv.delete(b"c")
    assert [k for k, _ in kv.scan(b"", b"\xff")] == [b"a", b"b", b"d"]
    assert kv.get(b"b") == b"B2"
    kv.delete_range(b"a", b"b")
    kv.compact()
    kv.close()
    kv2 = okv.OrderedKv(str(tmp_path))
    assert [k for k, _ in kv2.scan(b"", b"\xff")] == [b"b", b"d"]
    kv2.close()


def test_store_for_path_existing_file_never_shadowed(tmp_path):
    """An extensionless path holding a sqlite store from a previous
    run must keep opening as sqlite, not be shadowed by a new
    ordered-kv directory."""
    from seaweedfs_tpu.filer.filerstore import SqliteStore
    p = str(tmp_path / "filermeta")
    old = SqliteStore(p)
    old.insert_entry(Entry(path="/legacy.txt",
                           attributes=Attributes(mtime=1.0)))
    old.close()
    s = store_for_path(p)
    assert s.name == "sqlite"
    assert s.find_entry("/legacy.txt").path == "/legacy.txt"
    s.close()


def test_store_for_path_picks_ordered_kv_for_directories(tmp_path):
    d = tmp_path / "metadir"
    d.mkdir()
    s = store_for_path(str(d))
    assert isinstance(s, OrderedKvStore)
    s.close()
    s2 = store_for_path(str(tmp_path / "filer.db"))
    assert s2.name == "sqlite"
    s2.close()


# -- sharded store (leveldb2 analog) ----------------------------------------

def test_sharded_kv_persistence_and_spread(tmp_path):
    from seaweedfs_tpu.filer.ordered_kv import ShardedKvStore
    d = str(tmp_path / "skv")
    s = ShardedKvStore(d, shards=4)
    for i in range(64):
        s.insert_entry(Entry(path=f"/dir{i}/f.txt",
                             attributes=Attributes(mtime=float(i))))
    # dir-hash routing spreads entries across more than one shard
    used = {id(s._shard(f"/dir{i}/f.txt")) for i in range(64)}
    assert len(used) > 1
    # and every dir's children land on that dir's OWN shard
    for i in range(8):
        sh = s._shard(f"/dir{i}/f.txt")
        assert sh.list_directory_entries(f"/dir{i}", "", False, 10)
    s.close()
    # reopen: everything still there, through the same dir-hash routing
    s2 = ShardedKvStore(d, shards=4)
    for i in range(64):
        assert s2.find_entry(f"/dir{i}/f.txt").attributes.mtime == float(i)
    s2.close()


def test_sharded_kv_subtree_delete_spans_shards(tmp_path):
    from seaweedfs_tpu.filer.ordered_kv import ShardedKvStore
    s = ShardedKvStore(str(tmp_path / "skv2"), shards=4)
    # build a subtree whose levels hash to different shards
    paths = [f"/root/a{i}/b{j}/leaf.txt" for i in range(4)
             for j in range(4)]
    for p in paths:
        s.insert_entry(Entry(path=p, attributes=Attributes(mtime=1.0)))
    s.insert_entry(Entry(path="/rootx/outside.txt",
                         attributes=Attributes(mtime=2.0)))
    s.delete_folder_children("/root")
    from seaweedfs_tpu.filer.filerstore import NotFound
    for p in paths:
        with pytest.raises(NotFound):
            s.find_entry(p)
    # sibling prefix /rootx survives the /root range delete
    assert s.find_entry("/rootx/outside.txt").attributes.mtime == 2.0
    s.close()
