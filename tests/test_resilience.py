"""Unified RPC resilience policy: RetryPolicy classification/backoff
and the per-host circuit breaker, plus their wiring into the rpc
client pool and /metrics."""

import threading
import time

import pytest

from seaweedfs_tpu.cluster import resilience, rpc
from seaweedfs_tpu.cluster.resilience import (BreakerOpen, CircuitBreaker,
                                              ConnectError, RetryPolicy)


@pytest.fixture(autouse=True)
def _clean_breakers():
    resilience.reset_breakers()
    yield
    resilience.reset_breakers()


# -- RetryPolicy -------------------------------------------------------------

def test_backoff_full_jitter_bounds():
    p = RetryPolicy(base_delay=0.1, max_delay=1.0)
    for attempt in range(8):
        cap = min(1.0, 0.1 * 2 ** attempt)
        for _ in range(20):
            d = p.backoff(attempt)
            assert 0.0 <= d <= cap


def test_retries_connect_errors_even_non_idempotent():
    calls = []

    def fn(attempt, timeout):
        calls.append(attempt)
        if len(calls) < 3:
            raise ConnectError("dial failed")
        return "ok"

    p = RetryPolicy(max_attempts=3, base_delay=0.001, max_delay=0.002)
    assert p.run(fn, idempotent=False) == "ok"
    assert calls == [0, 1, 2]


def test_non_idempotent_never_retries_after_send():
    """Once bytes may have hit the wire (a plain ConnectionError), a
    non-idempotent body must not be re-sent."""
    calls = []

    def fn(attempt, timeout):
        calls.append(attempt)
        raise ConnectionResetError("mid-exchange")

    p = RetryPolicy(max_attempts=3, base_delay=0.001)
    with pytest.raises(ConnectionResetError):
        p.run(fn, idempotent=False)
    assert calls == [0]
    # The same failure IS retried when the call is idempotent.
    calls.clear()
    with pytest.raises(ConnectionResetError):
        p.run(fn, idempotent=True)
    assert calls == [0, 1, 2]


def test_5xx_retried_only_when_idempotent():
    calls = []

    def fn(attempt, timeout):
        calls.append(attempt)
        raise rpc.RpcError(503, "unavailable")

    p = RetryPolicy(max_attempts=2, base_delay=0.001)
    with pytest.raises(rpc.RpcError):
        p.run(fn, idempotent=False)
    assert calls == [0]
    calls.clear()
    with pytest.raises(rpc.RpcError):
        p.run(fn, idempotent=True)
    assert calls == [0, 1]


def test_4xx_never_retried():
    calls = []

    def fn(attempt, timeout):
        calls.append(attempt)
        raise rpc.RpcError(404, "not found")

    with pytest.raises(rpc.RpcError):
        RetryPolicy(max_attempts=3, base_delay=0.001).run(fn)
    assert calls == [0]


def test_total_deadline_bounds_attempts_and_timeout():
    """Per-attempt timeout is clipped to what remains of the total
    deadline, and the loop stops once the budget is spent."""
    seen = []

    def fn(attempt, timeout):
        seen.append(timeout)
        time.sleep(0.05)
        raise ConnectError("down")

    p = RetryPolicy(max_attempts=50, base_delay=0.0, max_delay=0.0,
                    per_attempt_timeout=10.0, total_deadline=0.2)
    t0 = time.monotonic()
    with pytest.raises(ConnectError):
        p.run(fn)
    assert time.monotonic() - t0 < 2.0
    assert len(seen) < 50          # deadline cut the attempt loop
    assert all(t <= 10.0 for t in seen)
    assert seen[0] <= 0.2 + 0.01   # clipped to the remaining budget


def test_retry_counter_increments():
    before = resilience.rpc_retries_total.value(reason="connect")

    def fn(attempt, timeout):
        if attempt == 0:
            raise ConnectError("dial")
        return "ok"

    RetryPolicy(max_attempts=2, base_delay=0.001).run(fn)
    after = resilience.rpc_retries_total.value(reason="connect")
    assert after == before + 1


# -- CircuitBreaker ----------------------------------------------------------

def test_breaker_opens_after_threshold_and_half_open_probe():
    b = CircuitBreaker(threshold=3, cooldown=0.1)
    assert b.state == "closed"
    for _ in range(2):
        b.record_failure()
    assert b.state == "closed" and b.allow()
    b.record_failure()
    assert b.state == "open"
    assert not b.allow()
    time.sleep(0.12)
    assert b.allow()               # the half-open probe
    assert b.state == "half-open"
    assert not b.allow()           # only ONE probe at a time
    b.record_success()
    assert b.state == "closed" and b.allow()


def test_breaker_half_open_failure_reopens():
    b = CircuitBreaker(threshold=1, cooldown=0.05)
    b.record_failure()
    assert b.state == "open"
    time.sleep(0.06)
    assert b.allow()
    b.record_failure()             # probe failed
    assert b.state == "open"
    assert not b.allow()


def test_breaker_success_resets_consecutive_count():
    b = CircuitBreaker(threshold=3, cooldown=1.0)
    b.record_failure()
    b.record_failure()
    b.record_success()
    b.record_failure()
    b.record_failure()
    assert b.state == "closed"     # never 3 consecutive


def test_breaker_disabled_with_zero_threshold():
    b = CircuitBreaker(threshold=0, cooldown=0.01)
    for _ in range(10):
        b.record_failure()
    assert b.allow()


def test_breaker_thread_safety_smoke():
    b = CircuitBreaker(threshold=5, cooldown=0.01)

    def churn():
        for i in range(500):
            b.allow()
            (b.record_failure if i % 3 else b.record_success)()

    threads = [threading.Thread(target=churn) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert b.state in ("closed", "half-open", "open")


# -- pool integration --------------------------------------------------------

def test_pool_connect_failures_open_breaker_and_fail_fast():
    """Dial failures to a dead host open its breaker; once open, the
    acquire fails fast with BreakerOpen (no socket work at all)."""
    port = rpc.free_port()  # nothing listens here
    url = f"http://127.0.0.1:{port}/x"
    for _ in range(resilience.BREAKER_THRESHOLD):
        with pytest.raises(ConnectionError):
            rpc.call(url, timeout=2.0)
    b = resilience.breaker_for(f"127.0.0.1:{port}")
    assert b.state == "open"
    t0 = time.monotonic()
    with pytest.raises(BreakerOpen):
        rpc.call(url, timeout=30.0)
    assert time.monotonic() - t0 < 0.5


def test_pool_dial_failure_is_connect_error():
    port = rpc.free_port()
    with pytest.raises(ConnectError):
        rpc.call(f"http://127.0.0.1:{port}/x", timeout=2.0)


def test_success_closes_breaker_again():
    server = rpc.JsonHttpServer()
    server.route("GET", "/ok", lambda q, b: {"ok": True})
    server.start()
    try:
        hostport = f"127.0.0.1:{server.port}"
        b = resilience.breaker_for(hostport)
        for _ in range(resilience.BREAKER_THRESHOLD):
            b.record_failure()
        assert b.state == "open"
        b.cooldown = 0.01
        time.sleep(0.02)
        assert rpc.call(f"http://{hostport}/ok") == {"ok": True}
        assert b.state == "closed"
    finally:
        server.stop()


def test_resilience_metrics_on_scrape():
    server = rpc.JsonHttpServer()
    reg = server.enable_metrics("testrole")
    text = reg.expose()
    assert "SeaweedFS_rpc_retries_total" in text
    assert "SeaweedFS_rpc_breaker_state" in text
    assert "SeaweedFS_faults_injected_total" in text
    # Registering twice (two servers sharing a registry) must not
    # duplicate the exposition blocks.
    server2 = rpc.JsonHttpServer()
    server2.enable_metrics("testrole2", registry=reg,
                           serve_route=False)
    text = reg.expose()
    assert text.count(
        "# TYPE SeaweedFS_rpc_retries_total counter") == 1


# -- overload protection (429 / Retry-After) ---------------------------------

class _StatusErr(Exception):
    def __init__(self, status, retry_after=None):
        super().__init__(f"status {status}")
        self.status = status
        self.retry_after = retry_after


def test_429_shed_retried_even_non_idempotent(monkeypatch):
    """An admission shed is refused BEFORE the handler runs, so a 429
    is always safe to retry — even for a non-idempotent body (unlike
    5xx answers, where the server may have executed the request)."""
    monkeypatch.setattr(resilience.time, "sleep", lambda s: None)
    calls = []

    def fn(attempt, timeout):
        calls.append(attempt)
        if len(calls) < 3:
            raise _StatusErr(429)
        return "ok"

    p = RetryPolicy(max_attempts=3, base_delay=0.001)
    assert p.run(fn, idempotent=False) == "ok"
    assert calls == [0, 1, 2]


def test_retry_after_is_backoff_floor_capped_at_attempt_budget(
        monkeypatch):
    """The server's Retry-After pacing hint floors the jittered
    backoff, but a hostile/buggy value is capped at the per-attempt
    timeout so it can never park the client."""
    slept = []
    monkeypatch.setattr(resilience.time, "sleep", slept.append)

    def fail_with(ra):
        calls = []

        def fn(attempt, timeout):
            calls.append(attempt)
            raise _StatusErr(429, retry_after=ra)
        with pytest.raises(_StatusErr):
            RetryPolicy(max_attempts=2, base_delay=0.0001,
                        max_delay=0.001,
                        per_attempt_timeout=0.5).run(fn)

    fail_with(0.3)
    assert slept and slept[-1] >= 0.3  # floor honored
    slept.clear()
    fail_with(999.0)
    assert slept and slept[-1] <= 0.5  # capped at per-attempt budget


def test_rpc_call_parses_retry_after_header():
    server = rpc.JsonHttpServer()
    server.route("GET", "/shedme", lambda q, b: (
        429, {"error": "overloaded"}, {"Retry-After": "2.5"}))
    server.start()
    try:
        with pytest.raises(rpc.RpcError) as ei:
            rpc.call(f"http://127.0.0.1:{server.port}/shedme")
        assert ei.value.status == 429
        assert ei.value.retry_after == 2.5
    finally:
        server.stop()


def test_breaker_treats_429_like_503():
    """Deliberate shedding from a LIVE process must never open the
    breaker: a 429 (like a 503) records success, while real 5xx
    answers keep counting toward opening it."""
    server = rpc.JsonHttpServer()
    server.route("GET", "/shed", lambda q, b: (429, {"error": "busy"}))
    server.route("GET", "/sick", lambda q, b: (500, {"error": "ill"}))
    server.start()
    hostport = f"127.0.0.1:{server.port}"
    try:
        for _ in range(resilience.BREAKER_THRESHOLD + 2):
            with pytest.raises(rpc.RpcError):
                rpc.call(f"http://{hostport}/shed")
        assert resilience.breaker_for(hostport).state == "closed"
        for _ in range(resilience.BREAKER_THRESHOLD):
            with pytest.raises(rpc.RpcError):
                rpc.call(f"http://{hostport}/sick")
        assert resilience.breaker_for(hostport).state == "open"
    finally:
        server.stop()
