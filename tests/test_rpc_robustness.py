"""RPC-plane robustness: truncated transfers, over-long lines, chunked
streaming.

Covers the failure modes the reference's net/http handles for free
(IncompleteRead on early close, 414/431 on over-long lines) that a
hand-rolled HTTP plane must reproduce explicitly."""

import socket
import threading

import pytest

from seaweedfs_tpu.cluster import rpc


def _raw_server(script):
    """One-shot raw-socket server: accepts one connection, runs
    script(conn), closes.  Returns (port, thread)."""
    srv = socket.create_server(("127.0.0.1", 0))
    port = srv.getsockname()[1]

    def run():
        conn, _ = srv.accept()
        try:
            script(conn)
        finally:
            conn.close()
            srv.close()

    th = threading.Thread(target=run, daemon=True)
    th.start()
    return port, th


def _drain_request(conn):
    buf = b""
    while b"\r\n\r\n" not in buf:
        data = conn.recv(65536)
        if not data:
            return buf
        buf += data
    return buf


def test_early_close_with_content_length_raises():
    """A peer that dies mid-body must surface an error, not a short
    'successful' read (ADVICE r2 medium)."""
    def script(conn):
        _drain_request(conn)
        conn.sendall(b"HTTP/1.1 200 OK\r\n"
                     b"Content-Length: 100\r\n\r\n"
                     b"only-ten-b")  # 10 of 100 bytes, then close

    port, _ = _raw_server(script)
    with pytest.raises(ConnectionError):
        rpc.call(f"http://127.0.0.1:{port}/x", timeout=5.0)


def test_early_close_to_file_raises(tmp_path):
    def script(conn):
        _drain_request(conn)
        conn.sendall(b"HTTP/1.1 200 OK\r\n"
                     b"Content-Length: 1048576\r\n\r\n" + b"x" * 1000)

    port, _ = _raw_server(script)
    dest = tmp_path / "out.bin"
    with pytest.raises(ConnectionError):
        rpc.call_to_file(f"http://127.0.0.1:{port}/x", str(dest),
                         timeout=5.0)


def test_chunked_body_streams_incrementally(tmp_path):
    """call_to_file must stream a chunked upstream in bounded reads, and
    reassemble the exact payload."""
    payload = bytes(range(256)) * 512  # 128KB
    def script(conn):
        _drain_request(conn)
        head = (b"HTTP/1.1 200 OK\r\n"
                b"Transfer-Encoding: chunked\r\n"
                b"Connection: close\r\n\r\n")
        conn.sendall(head)
        for i in range(0, len(payload), 7001):  # awkward chunk sizes
            chunk = payload[i:i + 7001]
            conn.sendall(hex(len(chunk))[2:].encode() + b"\r\n" +
                         chunk + b"\r\n")
        conn.sendall(b"0\r\n\r\n")

    port, _ = _raw_server(script)
    dest = tmp_path / "out.bin"
    n = rpc.call_to_file(f"http://127.0.0.1:{port}/x", str(dest),
                         timeout=5.0)
    assert n == len(payload)
    assert dest.read_bytes() == payload


def test_chunked_read_honors_requested_size():
    def script(conn):
        _drain_request(conn)
        conn.sendall(b"HTTP/1.1 200 OK\r\n"
                     b"Transfer-Encoding: chunked\r\n"
                     b"Connection: close\r\n\r\n"
                     b"10\r\n" + b"a" * 16 + b"\r\n"
                     b"10\r\n" + b"b" * 16 + b"\r\n"
                     b"0\r\n\r\n")

    port, _ = _raw_server(script)
    resp, conn = rpc._request(f"http://127.0.0.1:{port}/x", "GET", None,
                              5.0)
    try:
        assert resp.read(4) == b"aaaa"
        assert resp.read(20) == b"a" * 12 + b"b" * 8
        assert resp.read() == b"b" * 8
        assert resp.read() == b""
    finally:
        conn.close()


def test_chunked_early_close_raises():
    def script(conn):
        _drain_request(conn)
        conn.sendall(b"HTTP/1.1 200 OK\r\n"
                     b"Transfer-Encoding: chunked\r\n\r\n"
                     b"100\r\n" + b"x" * 16)  # promises 256, sends 16

    port, _ = _raw_server(script)
    with pytest.raises(ConnectionError):
        rpc.call(f"http://127.0.0.1:{port}/x", timeout=5.0)


def test_server_rejects_overlong_request_line():
    server = rpc.JsonHttpServer()
    server.route("GET", "/ok", lambda q, b: {"ok": True})
    server.start()
    try:
        with socket.create_connection(("127.0.0.1", server.port),
                                      timeout=5.0) as s:
            s.sendall(b"GET /" + b"a" * 70000 + b" HTTP/1.1\r\n\r\n")
            data = s.recv(65536)
        assert b"414" in data.split(b"\r\n", 1)[0]
    finally:
        server.stop()


def test_server_rejects_overlong_header():
    server = rpc.JsonHttpServer()
    server.route("GET", "/ok", lambda q, b: {"ok": True})
    server.start()
    try:
        with socket.create_connection(("127.0.0.1", server.port),
                                      timeout=5.0) as s:
            s.sendall(b"GET /ok HTTP/1.1\r\nX-Big: " + b"a" * 70000 +
                      b"\r\n\r\n")
            data = s.recv(65536)
        assert b"431" in data.split(b"\r\n", 1)[0]
    finally:
        server.stop()


def test_server_ignores_truncated_request():
    """EOF mid-headers must not route a half-request."""
    hits = []
    server = rpc.JsonHttpServer()
    server.route("POST", "/mutate", lambda q, b: hits.append(1) or {})
    server.start()
    try:
        with socket.create_connection(("127.0.0.1", server.port),
                                      timeout=5.0) as s:
            s.sendall(b"POST /mutate HTTP/1.1\r\nContent-Le")
        # connection closed mid-headers; give the server a beat
        import time
        time.sleep(0.1)
        assert hits == []
    finally:
        server.stop()
