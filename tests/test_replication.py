"""Replication: sinks, notification queues, replicator, filer.sync.

Reference behaviors: weed/replication/replicator.go (event -> sink),
sink/localsink + filersink + s3sink, notification queues, and
command/filer_sync.go (active-active sync with loop prevention and
offset checkpoints).
"""

import json
import os
import time
import urllib.request

import pytest

from seaweedfs_tpu.cluster.master import MasterServer
from seaweedfs_tpu.cluster.volume_server import VolumeServer
from seaweedfs_tpu.filer.client import FilerProxy
from seaweedfs_tpu.filer.server import FilerServer
from seaweedfs_tpu.replication import (FileQueue, FilerSyncWorker,
                                       LocalSink, MemoryQueue, Replicator,
                                       sync_once)
from seaweedfs_tpu.replication.sink import sink_for_spec


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("repl")
    master = MasterServer(volume_size_limit_mb=64, meta_dir=str(tmp))
    master.start()
    vs = VolumeServer(master.url(), [str(tmp / "vs")], pulse_seconds=60)
    vs.start()
    fa = FilerServer(master.url())
    fa.start()
    fb = FilerServer(master.url())
    fb.start()
    yield master, fa, fb
    fb.stop()
    fa.stop()
    vs.stop()
    master.stop()


# -- queues ----------------------------------------------------------------

def test_memory_queue_roundtrip():
    q = MemoryQueue()
    q.publish("/a", {"n": 1})
    q.publish("/b", {"n": 2})
    got = []
    q.consume(lambda k, m: got.append((k, m["n"])))
    assert got == [("/a", 1), ("/b", 2)]
    assert len(q) == 0


def test_file_queue_resumes_offset(tmp_path):
    path = str(tmp_path / "spool.jsonl")
    q = FileQueue(path)
    q.publish("/x", {"n": 1})
    q.publish("/y", {"n": 2})
    got = []
    q.consume(lambda k, m: got.append(k))
    assert got == ["/x", "/y"]
    # New consumer instance resumes past the checkpoint.
    q2 = FileQueue(path)
    q2.publish("/z", {"n": 3})
    got2 = []
    q2.consume(lambda k, m: got2.append(k))
    assert got2 == ["/z"]


def test_filer_publishes_to_queue(cluster):
    _m, fa, _fb = cluster
    q = MemoryQueue()
    fa.filer.notification_queue = q
    try:
        FilerProxy(fa.url()).put("/nq/f.txt", b"data")
        keys = []
        q.consume(lambda k, m: keys.append(k))
        assert "/nq/f.txt" in keys
    finally:
        fa.filer.notification_queue = None


# -- sinks + replicator ----------------------------------------------------

def test_local_sink_replication(cluster, tmp_path):
    _m, fa, _fb = cluster
    pa = FilerProxy(fa.url())
    pa.put("/repl/src/one.txt", b"payload-1")
    pa.put("/repl/src/sub/two.txt", b"payload-2")
    sink = LocalSink(str(tmp_path / "mirror"))
    repl = Replicator(fa.url(), "/repl/src", sink)
    for ev in pa.meta_events(0, prefix="/repl/src")["events"]:
        repl.replicate(ev)
    root = tmp_path / "mirror"
    assert (root / "one.txt").read_bytes() == b"payload-1"
    assert (root / "sub" / "two.txt").read_bytes() == b"payload-2"
    # Deletes propagate too.
    off = pa.meta_info()["last_ns"]
    pa.delete("/repl/src/one.txt")
    for ev in pa.meta_events(off, prefix="/repl/src")["events"]:
        repl.replicate(ev)
    assert not (root / "one.txt").exists()
    assert (root / "sub" / "two.txt").exists()


def test_local_sink_rejects_escaping_keys(tmp_path):
    sink = LocalSink(str(tmp_path / "jail"))
    with pytest.raises(ValueError):
        sink.create_entry("../escape.txt", {}, b"x")


def test_filer_sink_spec(cluster):
    _m, fa, fb = cluster
    pa, pb = FilerProxy(fa.url()), FilerProxy(fb.url())
    pa.put("/fsink/data.bin", bytes(range(100)))
    host = fb.url().replace("http://", "")
    sink = sink_for_spec(f"filer://{host}/fsink-mirror")
    repl = Replicator(fa.url(), "/fsink", sink)
    for ev in pa.meta_events(0, prefix="/fsink")["events"]:
        repl.replicate(ev)
    with pb.get("/fsink-mirror/data.bin") as resp:
        assert resp.read() == bytes(range(100))


# -- filer.sync ------------------------------------------------------------

def test_sync_once_and_loop_prevention(cluster):
    _m, fa, fb = cluster
    pa, pb = FilerProxy(fa.url()), FilerProxy(fb.url())
    pa.put("/sync/a-file.txt", b"from-a")
    n1 = sync_once(fa.url(), fb.url(), "/sync", "/sync")
    assert n1 >= 1
    with pb.get("/sync/a-file.txt") as resp:
        assert resp.read() == b"from-a"
    # Replayed events on B carry A's signature; syncing B->A must skip
    # them (loop breaker) and a-file must not bounce back as a new event.
    n2 = sync_once(fb.url(), fa.url(), "/sync", "/sync")
    n3 = sync_once(fa.url(), fb.url(), "/sync", "/sync")
    assert n3 == 0  # steady state: nothing new to apply
    # Offset checkpoint persisted in target KV.
    sig_a = pa.meta_info()["signature"]
    assert pb.kv_get(f"sync.offset.{sig_a:x}") is not None


def test_bidirectional_sync_worker(cluster):
    _m, fa, fb = cluster
    pa, pb = FilerProxy(fa.url()), FilerProxy(fb.url())
    worker = FilerSyncWorker(fa.url(), fb.url(), "/bidi", "/bidi",
                             interval=0.1)
    worker.start()
    try:
        pa.put("/bidi/from-a.txt", b"AAA")
        pb.put("/bidi/from-b.txt", b"BBB")
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            try:
                with pb.get("/bidi/from-a.txt") as r1, \
                        pa.get("/bidi/from-b.txt") as r2:
                    assert r1.read() == b"AAA"
                    assert r2.read() == b"BBB"
                break
            except Exception:
                time.sleep(0.2)
        else:
            pytest.fail("bidirectional sync did not converge")
    finally:
        worker.stop()


# -- filer.copy CLI --------------------------------------------------------

def test_filer_copy_command(cluster, tmp_path):
    from seaweedfs_tpu.command import COMMANDS, _load_all, parse_flags
    _m, fa, _fb = cluster
    src = tmp_path / "tree"
    (src / "sub").mkdir(parents=True)
    (src / "root.txt").write_bytes(b"r")
    (src / "sub" / "leaf.txt").write_bytes(b"l")
    _load_all()
    host = fa.url().replace("http://", "")
    flags, rest = parse_flags([f"-filer={host}", str(src), "/copied/"])
    assert COMMANDS["filer.copy"].run(flags, rest) == 0
    p = FilerProxy(fa.url())
    with p.get("/copied/tree/root.txt") as r:
        assert r.read() == b"r"
    with p.get("/copied/tree/sub/leaf.txt") as r:
        assert r.read() == b"l"
