"""Replication: sinks, notification queues, and the replicator pump.

Reference behaviors: weed/replication/replicator.go (event -> sink),
sink/localsink + filersink + s3sink, and notification queues.  The
cross-cluster mirror (change-log shipper) is covered by tests/test_dr.py.
"""

import pytest

from seaweedfs_tpu.cluster.master import MasterServer
from seaweedfs_tpu.cluster.volume_server import VolumeServer
from seaweedfs_tpu.filer.client import FilerProxy
from seaweedfs_tpu.filer.server import FilerServer
from seaweedfs_tpu.replication import (FileQueue, LocalSink, MemoryQueue,
                                       Replicator)
from seaweedfs_tpu.replication.sink import sink_for_spec


def test_filer_event_plane_is_quarantined():
    """The filer-event replication port (replicator/sink/notification)
    is deliberately OUT of the package's supported surface: `__all__`
    pins exactly the live change-log mirror + geo lease plane, while
    the legacy names stay importable through lazy `__getattr__` (this
    file exercises them above).  Growing `__all__` — or wiring the
    quarantined modules into a server role — must consciously touch
    this pin."""
    import seaweedfs_tpu.replication as repl
    assert repl.__all__ == ["LeaseTable", "ReplicationLog",
                            "ReplicationShipper", "VolumeLease",
                            "Watermark"]
    # Lazy quarantine: importing the package in a fresh process does
    # NOT import the legacy modules as a side effect (checked in a
    # subprocess so this test can't disturb the shared module cache —
    # this very file imported them eagerly above)...
    import subprocess
    import sys
    out = subprocess.run(
        [sys.executable, "-c",
         "import sys; import seaweedfs_tpu.replication as r; "
         "bad = [m for m in sys.modules if m.endswith(("
         "'.replicator', '.sink', '.notification'))]; "
         "assert not bad, bad; "
         "assert r.Replicator is not None; "  # lazy resolve works
         "print('quarantine-ok')"],
        capture_output=True, text=True, timeout=60)
    assert "quarantine-ok" in out.stdout, (out.stdout, out.stderr)
    # ...and unknown names still raise through the lazy hook.
    with pytest.raises(AttributeError):
        repl.NoSuchName  # noqa: B018


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("repl")
    master = MasterServer(volume_size_limit_mb=64, meta_dir=str(tmp))
    master.start()
    vs = VolumeServer(master.url(), [str(tmp / "vs")], pulse_seconds=60)
    vs.start()
    fa = FilerServer(master.url())
    fa.start()
    fb = FilerServer(master.url())
    fb.start()
    yield master, fa, fb
    fb.stop()
    fa.stop()
    vs.stop()
    master.stop()


# -- queues ----------------------------------------------------------------

def test_memory_queue_roundtrip():
    q = MemoryQueue()
    q.publish("/a", {"n": 1})
    q.publish("/b", {"n": 2})
    got = []
    q.consume(lambda k, m: got.append((k, m["n"])))
    assert got == [("/a", 1), ("/b", 2)]
    assert len(q) == 0


def test_file_queue_resumes_offset(tmp_path):
    path = str(tmp_path / "spool.jsonl")
    q = FileQueue(path)
    q.publish("/x", {"n": 1})
    q.publish("/y", {"n": 2})
    got = []
    q.consume(lambda k, m: got.append(k))
    assert got == ["/x", "/y"]
    # New consumer instance resumes past the checkpoint.
    q2 = FileQueue(path)
    q2.publish("/z", {"n": 3})
    got2 = []
    q2.consume(lambda k, m: got2.append(k))
    assert got2 == ["/z"]


def test_filer_publishes_to_queue(cluster):
    _m, fa, _fb = cluster
    q = MemoryQueue()
    fa.filer.notification_queue = q
    try:
        FilerProxy(fa.url()).put("/nq/f.txt", b"data")
        keys = []
        q.consume(lambda k, m: keys.append(k))
        assert "/nq/f.txt" in keys
    finally:
        fa.filer.notification_queue = None


# -- sinks + replicator ----------------------------------------------------

def test_local_sink_replication(cluster, tmp_path):
    _m, fa, _fb = cluster
    pa = FilerProxy(fa.url())
    pa.put("/repl/src/one.txt", b"payload-1")
    pa.put("/repl/src/sub/two.txt", b"payload-2")
    sink = LocalSink(str(tmp_path / "mirror"))
    repl = Replicator(fa.url(), "/repl/src", sink)
    for ev in pa.meta_events(0, prefix="/repl/src")["events"]:
        repl.replicate(ev)
    root = tmp_path / "mirror"
    assert (root / "one.txt").read_bytes() == b"payload-1"
    assert (root / "sub" / "two.txt").read_bytes() == b"payload-2"
    # Deletes propagate too.
    off = pa.meta_info()["last_ns"]
    pa.delete("/repl/src/one.txt")
    for ev in pa.meta_events(off, prefix="/repl/src")["events"]:
        repl.replicate(ev)
    assert not (root / "one.txt").exists()
    assert (root / "sub" / "two.txt").exists()


def test_local_sink_rejects_escaping_keys(tmp_path):
    sink = LocalSink(str(tmp_path / "jail"))
    with pytest.raises(ValueError):
        sink.create_entry("../escape.txt", {}, b"x")


def test_filer_sink_spec(cluster):
    _m, fa, fb = cluster
    pa, pb = FilerProxy(fa.url()), FilerProxy(fb.url())
    pa.put("/fsink/data.bin", bytes(range(100)))
    host = fb.url().replace("http://", "")
    sink = sink_for_spec(f"filer://{host}/fsink-mirror")
    repl = Replicator(fa.url(), "/fsink", sink)
    for ev in pa.meta_events(0, prefix="/fsink")["events"]:
        repl.replicate(ev)
    with pb.get("/fsink-mirror/data.bin") as resp:
        assert resp.read() == bytes(range(100))


# -- filer.copy CLI --------------------------------------------------------

def test_filer_copy_command(cluster, tmp_path):
    from seaweedfs_tpu.command import COMMANDS, _load_all, parse_flags
    _m, fa, _fb = cluster
    src = tmp_path / "tree"
    (src / "sub").mkdir(parents=True)
    (src / "root.txt").write_bytes(b"r")
    (src / "sub" / "leaf.txt").write_bytes(b"l")
    _load_all()
    host = fa.url().replace("http://", "")
    flags, rest = parse_flags([f"-filer={host}", str(src), "/copied/"])
    assert COMMANDS["filer.copy"].run(flags, rest) == 0
    p = FilerProxy(fa.url())
    with p.get("/copied/tree/root.txt") as r:
        assert r.read() == b"r"
    with p.get("/copied/tree/sub/leaf.txt") as r:
        assert r.read() == b"l"
