"""Mount layer: dirty-page intervals, meta cache, WFS, real FUSE mount.

Reference behaviors: weed/filesys/dirty_page_interval.go (interval
algebra), meta_cache/ (cache + subscription invalidation), wfs.go /
file.go / dir.go (node ops).  The kernel FUSE test runs only where
/dev/fuse is usable.
"""

import errno
import os
import time

import pytest

from seaweedfs_tpu.mount import ContinuousIntervals, WFS
from seaweedfs_tpu.mount.vfs import FuseError


# -- dirty page intervals --------------------------------------------------

def test_intervals_basic_merge():
    iv = ContinuousIntervals()
    iv.add(0, b"aaaa")
    iv.add(4, b"bbbb")
    assert iv.pop_all() == [(0, b"aaaabbbb")]


def test_intervals_overwrite_newest_wins():
    iv = ContinuousIntervals()
    iv.add(0, b"aaaaaaaaaa")
    iv.add(3, b"BBB")
    assert iv.pop_all() == [(0, b"aaaBBBaaaa")]


def test_intervals_split_and_partial_overlap():
    iv = ContinuousIntervals()
    iv.add(0, b"xxxx")        # 0-4
    iv.add(8, b"yyyy")        # 8-12
    iv.add(2, b"ZZZZZZZZ")    # 2-10 covers the gap + both edges
    assert iv.pop_all() == [(0, b"xxZZZZZZZZyy")]


def test_intervals_read_overlay():
    iv = ContinuousIntervals()
    iv.add(5, b"hello")
    assert iv.read(0, 20) == [(5, b"hello")]
    assert iv.read(6, 2) == [(6, b"el")]
    assert iv.read(10, 5) == []
    assert iv.max_end() == 10


def test_intervals_disjoint_stay_separate():
    iv = ContinuousIntervals()
    iv.add(0, b"aa")
    iv.add(10, b"bb")
    assert iv.pop_all() == [(0, b"aa"), (10, b"bb")]


# -- WFS over a live stack -------------------------------------------------

@pytest.fixture(scope="module")
def stack(tmp_path_factory):
    from seaweedfs_tpu.cluster.master import MasterServer
    from seaweedfs_tpu.cluster.volume_server import VolumeServer
    from seaweedfs_tpu.filer.server import FilerServer
    tmp = tmp_path_factory.mktemp("mount-stack")
    master = MasterServer(volume_size_limit_mb=64, meta_dir=str(tmp))
    master.start()
    vs = VolumeServer(master.url(), [str(tmp / "vs")], pulse_seconds=60)
    vs.start()
    filer = FilerServer(master.url())
    filer.start()
    yield master, vs, filer
    filer.stop()
    vs.stop()
    master.stop()


@pytest.fixture
def wfs(stack):
    _m, _vs, filer = stack
    w = WFS(filer.url(), chunk_size=64)  # tiny chunks: force multi-chunk
    w.start()
    yield w
    w.stop()


def test_wfs_create_write_read(wfs):
    fh = wfs.create("/hello.txt")
    data = b"hello mounted world " * 20  # 400B -> several 64B chunks
    assert wfs.write(fh, data, 0) == len(data)
    # Read-your-writes before flush (dirty overlay).
    assert wfs.read(fh, len(data), 0) == data
    wfs.release(fh)
    # Reopen: content came back from the blob store.
    fh2 = wfs.open("/hello.txt")
    assert wfs.read(fh2, 4096, 0) == data
    st = wfs.getattr("/hello.txt")
    assert st["st_size"] == len(data)
    wfs.release(fh2)


def test_wfs_random_overwrite_and_sparse(wfs):
    fh = wfs.create("/rw.bin")
    wfs.write(fh, b"A" * 100, 0)
    wfs.release(fh)
    fh = wfs.open("/rw.bin")
    wfs.write(fh, b"B" * 10, 45)  # overwrite the middle
    wfs.write(fh, b"C" * 5, 200)  # sparse extension
    wfs.release(fh)
    fh = wfs.open("/rw.bin")
    got = wfs.read(fh, 4096, 0)
    wfs.release(fh)
    assert got[:45] == b"A" * 45
    assert got[45:55] == b"B" * 10
    assert got[55:100] == b"A" * 45
    assert got[100:200] == b"\0" * 100  # hole reads as zeros
    assert got[200:205] == b"C" * 5
    assert len(got) == 205


def test_wfs_truncate(wfs):
    fh = wfs.create("/trunc.txt")
    wfs.write(fh, b"0123456789", 0)
    wfs.release(fh)
    wfs.truncate("/trunc.txt", 4)
    fh = wfs.open("/trunc.txt")
    assert wfs.read(fh, 100, 0) == b"0123"
    wfs.release(fh)
    wfs.truncate("/trunc.txt", 8)  # grow with zeros
    fh = wfs.open("/trunc.txt")
    assert wfs.read(fh, 100, 0) == b"0123\0\0\0\0"
    wfs.release(fh)


def test_wfs_dirs_and_rename(wfs):
    wfs.mkdir("/d1")
    wfs.mkdir("/d1/d2")
    fh = wfs.create("/d1/d2/f.txt")
    wfs.write(fh, b"content", 0)
    wfs.release(fh)
    assert "d2" in wfs.readdir("/d1")
    assert wfs.readdir("/d1/d2") == ["f.txt"]
    with pytest.raises(FuseError) as ei:
        wfs.rmdir("/d1")
    assert ei.value.errno == errno.ENOTEMPTY
    wfs.rename("/d1/d2/f.txt", "/d1/g.txt")
    assert wfs.readdir("/d1/d2") == []
    fh = wfs.open("/d1/g.txt")
    assert wfs.read(fh, 100, 0) == b"content"
    wfs.release(fh)
    wfs.rmdir("/d1/d2")
    with pytest.raises(FuseError):
        wfs.readdir("/d1/d2")


def test_wfs_unlink_and_enoent(wfs):
    fh = wfs.create("/gone.txt")
    wfs.release(fh)
    wfs.unlink("/gone.txt")
    with pytest.raises(FuseError) as ei:
        wfs.open("/gone.txt")
    assert ei.value.errno == errno.ENOENT


def test_wfs_symlink_xattr_chmod(wfs):
    fh = wfs.create("/target.txt")
    wfs.release(fh)
    wfs.symlink("/target.txt", "/link")
    assert wfs.readlink("/link") == "/target.txt"
    wfs.chmod("/target.txt", 0o600)
    assert wfs.getattr("/target.txt")["st_mode"] & 0o777 == 0o600
    wfs.setxattr("/target.txt", "user.tag", b"v1")
    assert wfs.getxattr("/target.txt", "user.tag") == b"v1"
    assert wfs.listxattr("/target.txt") == ["user.tag"]
    wfs.removexattr("/target.txt", "user.tag")
    with pytest.raises(FuseError):
        wfs.getxattr("/target.txt", "user.tag")


def test_wfs_meta_cache_sees_external_changes(stack, wfs):
    """A file written through the filer HTTP API (not the mount) shows
    up via the subscription-fed meta cache."""
    _m, _vs, filer = stack
    from seaweedfs_tpu.filer.client import FilerProxy
    FilerProxy(filer.url()).put("/external.txt", b"outside write")
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        try:
            fh = wfs.open("/external.txt")
            break
        except FuseError:
            time.sleep(0.1)
    else:
        pytest.fail("external file never appeared through meta cache")
    assert wfs.read(fh, 100, 0) == b"outside write"
    wfs.release(fh)


# -- real kernel mount (gated) ---------------------------------------------

def _fuse_usable():
    try:
        return os.access("/dev/fuse", os.R_OK | os.W_OK)
    except OSError:
        return False


@pytest.mark.skipif(not _fuse_usable(), reason="/dev/fuse not usable")
def test_real_fuse_mount(stack, tmp_path):
    from seaweedfs_tpu.mount.fuse_ll import FuseMount
    _m, _vs, filer = stack
    mp = tmp_path / "mnt"
    mp.mkdir()
    w = WFS(filer.url(), filer_dir="/fusetest", chunk_size=256)
    fm = FuseMount(w, str(mp))
    fm.mount_background()
    try:
        # Plain POSIX IO through the kernel.
        p = mp / "kernel.txt"
        body = b"written through the kernel\n" * 50
        with open(p, "wb") as f:
            f.write(body)
        assert p.read_bytes() == body
        assert p.stat().st_size == len(body)
        (mp / "subdir").mkdir()
        os.rename(p, mp / "subdir" / "moved.txt")
        assert sorted(os.listdir(mp)) == ["subdir"]
        assert (mp / "subdir" / "moved.txt").read_bytes() == body
        # The file exists in the filer namespace under /fusetest.
        from seaweedfs_tpu.filer.client import FilerProxy
        meta = FilerProxy(filer.url()).meta("/fusetest/subdir/moved.txt")
        assert meta is not None
        os.remove(mp / "subdir" / "moved.txt")
        os.rmdir(mp / "subdir")
        assert os.listdir(mp) == []
    finally:
        fm.unmount()


@pytest.mark.skipif(not _fuse_usable(), reason="/dev/fuse not usable")
def test_real_fuse_hardlink(stack, tmp_path):
    """`ln` through the kernel: both names resolve the shared content
    (filerstore_hardlink.go indirection), surviving rm of one name."""
    from seaweedfs_tpu.mount.fuse_ll import FuseMount
    _m, _vs, filer = stack
    mp = tmp_path / "mnt_ln"
    mp.mkdir()
    w = WFS(filer.url(), filer_dir="/fuselink", chunk_size=256)
    fm = FuseMount(w, str(mp))
    fm.mount_background()
    try:
        a = mp / "orig.txt"
        a.write_bytes(b"shared content " * 40)
        os.link(a, mp / "alias.txt")
        assert (mp / "alias.txt").read_bytes() == a.read_bytes()
        st = os.stat(a)
        assert st.st_nlink == 2
        os.remove(a)
        assert (mp / "alias.txt").read_bytes() == \
            b"shared content " * 40
    finally:
        fm.unmount()


@pytest.mark.skipif(not _fuse_usable(), reason="/dev/fuse not usable")
def test_real_fuse_cipher_mount(stack, tmp_path):
    """A kernel mount of a cipher-enabled filer seals chunks: plaintext
    through the OS, ciphertext on the volume server."""
    from seaweedfs_tpu.cluster.client import WeedClient
    from seaweedfs_tpu.filer.client import FilerProxy
    from seaweedfs_tpu.filer.server import FilerServer
    from seaweedfs_tpu.mount.fuse_ll import FuseMount
    master, _vs, _filer = stack
    cfs = FilerServer(master.url(), chunk_size=512, cipher=True)
    cfs.start()
    mp = tmp_path / "mnt_ci"
    mp.mkdir()
    w = WFS(cfs.url(), filer_dir="/cipher", chunk_size=512)
    assert w.cipher, "mount must adopt the filer's cipher bit"
    fm = FuseMount(w, str(mp))
    fm.mount_background()
    try:
        secret = b"top secret material " * 60  # > 1 chunk
        (mp / "s.bin").write_bytes(secret)
        assert (mp / "s.bin").read_bytes() == secret
        meta = FilerProxy(cfs.url()).meta("/cipher/s.bin")
        chunks = meta["chunks"]
        assert chunks and all(c.get("cipher_key") for c in chunks)
        raw = WeedClient(master.url()).download(chunks[0]["file_id"])
        assert secret[:64] not in raw
    finally:
        fm.unmount()
        cfs.stop()
