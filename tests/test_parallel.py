"""Mesh-sharded codec tests on the virtual 8-device CPU mesh."""

import numpy as np
import pytest

import jax

from seaweedfs_tpu.ops.coder_numpy import NumpyCoder
from seaweedfs_tpu.parallel.mesh import make_mesh
from seaweedfs_tpu.parallel.sharded_codec import (all_to_all_reconstruct,
                                                  batched_encode,
                                                  batched_reconstruct)


@pytest.fixture(scope="module")
def oracle():
    return NumpyCoder(10, 4)


def _volumes(v, n, seed=0):
    return np.random.default_rng(seed).integers(
        0, 256, (v, 10, n)).astype(np.uint8)


def test_eight_devices_available():
    assert len(jax.devices()) == 8


def test_batched_encode_matches_oracle(oracle):
    data = _volumes(8, 512)
    mesh = make_mesh(8, vol_axis=4)  # 4-way volumes x 2-way columns
    parity = np.asarray(batched_encode(data, mesh))
    for i in range(8):
        assert np.array_equal(parity[i], oracle.encode(data[i])), i


def test_batched_encode_no_mesh(oracle):
    data = _volumes(3, 256, 1)
    parity = np.asarray(batched_encode(data))
    for i in range(3):
        assert np.array_equal(parity[i], oracle.encode(data[i]))


def test_batched_reconstruct(oracle):
    v, n = 8, 640
    data = _volumes(v, n, 2)
    lost = (0, 3, 11, 13)
    present = tuple(s for s in range(14) if s not in lost)
    used = present[:10]
    mesh = make_mesh(8, vol_axis=8)
    shards = np.stack([oracle.encode_all(data[i]) for i in range(v)])
    stacked = shards[:, list(used), :]
    rec = np.asarray(batched_reconstruct(stacked, present, lost, mesh))
    for i in range(v):
        for j, sid in enumerate(lost):
            assert np.array_equal(rec[i, j], shards[i, sid]), (i, sid)


def test_all_to_all_reconstruct(oracle):
    """Shard-major layout resharded over ICI (all_to_all) then decoded."""
    v, n = 4, 512
    data = _volumes(v, n, 3)
    lost = (2, 7, 10, 12)
    present = tuple(s for s in range(14) if s not in lost)
    used = present[:10]
    mesh = make_mesh(8, vol_axis=4)  # col axis = 2 chips hold 5 shards each
    shards = np.stack([oracle.encode_all(data[i]) for i in range(v)])
    stacked = shards[:, list(used), :]
    rec = np.asarray(all_to_all_reconstruct(stacked, present, lost, mesh))
    assert rec.shape == (v, 4, n)
    for i in range(v):
        for j, sid in enumerate(lost):
            assert np.array_equal(rec[i, j], shards[i, sid]), (i, sid)


def test_all_to_all_validates_divisibility(oracle):
    mesh = make_mesh(8, vol_axis=2)  # col axis = 4; 10 % 4 != 0
    data = _volumes(2, 512, 4)
    with pytest.raises(ValueError, match="divide"):
        all_to_all_reconstruct(data, tuple(range(10)), (10,), mesh)


def test_batched_reconstruct_wrong_stack_width(oracle):
    data = _volumes(2, 128, 5)  # 10 rows but claim 11 survivors
    with pytest.raises(ValueError, match="survivor rows"):
        batched_reconstruct(data[:, :9], tuple(range(10)), (10,), None)


def test_ring_reconstruct_matches_oracle(oracle):
    """Ring reduce-scatter of partial GF(2) products (ppermute)."""
    from seaweedfs_tpu.parallel.sharded_codec import ring_reconstruct
    v, n = 4, 512
    data = _volumes(v, n, 5)
    lost = (1, 6, 11, 13)
    present = tuple(s for s in range(14) if s not in lost)
    used = present[:10]
    mesh = make_mesh(8, vol_axis=4)  # ring axis D=2, 5 rows per chip
    shards = np.stack([oracle.encode_all(data[i]) for i in range(v)])
    stacked = shards[:, list(used), :]
    rec = np.asarray(ring_reconstruct(stacked, present, lost, mesh))
    assert rec.shape == (v, 4, n)
    for i in range(v):
        for j, sid in enumerate(lost):
            assert np.array_equal(rec[i, j], shards[i, sid]), (i, sid)


def test_ring_reconstruct_single_lost_shard(oracle):
    """W=1 — the common ec.rebuild case where the ring's W·N traffic
    beats all_to_all's (K/D)·N."""
    from seaweedfs_tpu.parallel.sharded_codec import ring_reconstruct
    v, n = 4, 640
    data = _volumes(v, n, 6)
    lost = (4,)
    present = tuple(s for s in range(14) if s != 4)
    used = present[:10]
    mesh = make_mesh(8, vol_axis=4)
    shards = np.stack([oracle.encode_all(data[i]) for i in range(v)])
    stacked = shards[:, list(used), :]
    rec = np.asarray(ring_reconstruct(stacked, present, lost, mesh))
    for i in range(v):
        assert np.array_equal(rec[i, 0], shards[i, 4])


def test_ring_reconstruct_deeper_ring(oracle):
    """D=5 ring (2 rows/chip): more hops, same answer."""
    from seaweedfs_tpu.parallel.mesh import make_mesh as mk
    from seaweedfs_tpu.parallel.sharded_codec import ring_reconstruct
    import jax.sharding
    devs = np.array(jax.devices()[:5]).reshape(1, 5)
    mesh = jax.sharding.Mesh(devs, ("vol", "col"))
    v, n = 1, 500
    data = _volumes(v, n, 7)
    lost = (0, 13)
    present = tuple(s for s in range(14) if s not in lost)
    used = present[:10]
    shards = np.stack([oracle.encode_all(data[i]) for i in range(v)])
    stacked = shards[:, list(used), :]
    rec = np.asarray(ring_reconstruct(stacked, present, lost, mesh))
    for j, sid in enumerate(lost):
        assert np.array_equal(rec[0, j], shards[0, sid])


def test_ring_reconstruct_validates_divisibility():
    from seaweedfs_tpu.parallel.sharded_codec import ring_reconstruct
    mesh = make_mesh(8, vol_axis=2)  # col axis = 4; 10 % 4 != 0
    data = np.zeros((2, 10, 512), np.uint8)
    with pytest.raises(ValueError):
        ring_reconstruct(data, tuple(range(10)), (10,), mesh)
