"""fs.*/bucket.* shell commands, volume.fsck, leave, and JWT security.

Reference behaviors: weed/shell/command_fs_*.go, command_bucket_*.go,
command_volume_fsck.go, command_volume_server_leave.go,
security/jwt.go + guard.go (write-path JWT).
"""

import time

import pytest

from seaweedfs_tpu.cluster import rpc
from seaweedfs_tpu.cluster.client import WeedClient
from seaweedfs_tpu.cluster.master import MasterServer
from seaweedfs_tpu.cluster.volume_server import VolumeServer
from seaweedfs_tpu.filer.client import FilerProxy
from seaweedfs_tpu.filer.server import FilerServer
from seaweedfs_tpu.shell.commands import run_command
from seaweedfs_tpu.shell.env import CommandEnv, ShellError


@pytest.fixture(scope="module")
def stack(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("shellfs")
    master = MasterServer(volume_size_limit_mb=64, meta_dir=str(tmp))
    master.start()
    vs = VolumeServer(master.url(), [str(tmp / "vs")], pulse_seconds=60)
    vs.start()
    filer = FilerServer(master.url())
    filer.start()
    env = CommandEnv(master.url(), filer_url=filer.url())
    yield master, vs, filer, env
    filer.stop()
    vs.stop()
    master.stop()


def test_fs_commands_roundtrip(stack, tmp_path):
    _m, _vs, filer, env = stack
    p = FilerProxy(filer.url())
    p.put("/shelltest/docs/a.txt", b"alpha content")
    p.put("/shelltest/docs/deep/b.txt", b"beta")
    assert run_command(env, "fs.pwd") == "/"
    run_command(env, "fs.cd /shelltest")
    assert run_command(env, "fs.pwd") == "/shelltest"
    assert "docs/" in run_command(env, "fs.ls")
    assert "a.txt" in run_command(env, "fs.ls docs")
    du = run_command(env, "fs.du")
    assert "17 bytes" in du and "2 files" in du
    assert run_command(env, "fs.cat docs/a.txt") == "alpha content"
    tree = run_command(env, "fs.tree")
    assert "a.txt" in tree and "deep/" in tree and "b.txt" in tree
    run_command(env, "fs.mkdir sub")
    run_command(env, "fs.mv docs/a.txt sub/renamed.txt")
    assert run_command(env, "fs.cat sub/renamed.txt") == "alpha content"
    run_command(env, "fs.rm -r sub")
    with pytest.raises(ShellError):
        run_command(env, "fs.cat sub/renamed.txt")
    meta = run_command(env, "fs.meta.cat docs/deep/b.txt")
    assert '"chunks"' in meta
    # meta save / load into a new subtree
    out = tmp_path / "meta.jsonl"
    msg = run_command(env, f"fs.meta.save -o={out} /shelltest")
    assert "saved" in msg
    run_command(env, "fs.rm -r /shelltest/docs")
    loaded = run_command(env, f"fs.meta.load {out}")
    assert "loaded" in loaded
    assert run_command(env, "fs.cat /shelltest/docs/deep/b.txt") == \
        "beta"


def test_bucket_commands(stack):
    _m, _vs, _f, env = stack
    run_command(env, "bucket.create -name shop")
    assert "shop" in run_command(env, "bucket.list")
    run_command(env, "lock")
    run_command(env, "bucket.delete -name shop")
    run_command(env, "unlock")
    assert "shop" not in run_command(env, "bucket.list")


def test_volume_fsck(stack):
    _m, vs, filer, env = stack
    FilerProxy(filer.url()).put("/fsck/ok.txt", b"fine " * 100)
    out = run_command(env, "volume.fsck")
    assert "0 missing" in out


def test_jwt_secured_cluster(tmp_path):
    key = "test-signing-key"
    master = MasterServer(volume_size_limit_mb=64,
                          meta_dir=str(tmp_path / "m"),
                          jwt_signing_key=key)
    master.start()
    vs = VolumeServer(master.url(), [str(tmp_path / "v")],
                      pulse_seconds=60, jwt_signing_key=key)
    vs.start()
    try:
        client = WeedClient(master.url())
        a = client.assign()
        assert a.get("auth"), "secured master must mint a jwt"
        # Write WITHOUT the token -> 401.
        with pytest.raises(rpc.RpcError) as ei:
            rpc.call(f"http://{a['url']}/{a['fid']}", "POST", b"nope")
        assert ei.value.status == 401
        # Wrong-fid token -> 401 too.
        from seaweedfs_tpu.utils.security import gen_jwt
        bad = gen_jwt(key, 10, "9,deadbeef")
        with pytest.raises(rpc.RpcError) as ei:
            rpc.call(f"http://{a['url']}/{a['fid']}?jwt={bad}",
                     "POST", b"nope")
        assert ei.value.status == 401
        # The client flow attaches tokens transparently (write+delete).
        fid = client.upload_data(b"secured payload")
        assert client.download(fid) == b"secured payload"
        client.delete(fid)
        with pytest.raises(rpc.RpcError):
            client.download(fid)
        # Reads stay public (the reference guards only writes by
        # default).
        fid2 = client.upload_data(b"again")
        assert rpc.call(f"http://{a['url']}/{fid2}") == b"again"
        # type=replicate is NOT an auth bypass: replicated writes carry
        # the original jwt and are re-verified (store_replicate.go
        # forwards the JWT; replicas still run the auth check).
        with pytest.raises(rpc.RpcError) as ei:
            rpc.call(f"http://{a['url']}/{a['fid']}?type=replicate",
                     "POST", b"nope")
        assert ei.value.status == 401
        with pytest.raises(rpc.RpcError) as ei:
            rpc.call(f"http://{a['url']}/{a['fid']}?type=replicate",
                     "DELETE")
        assert ei.value.status == 401
    finally:
        vs.stop()
        master.stop()


def test_volume_server_leave(tmp_path):
    master = MasterServer(volume_size_limit_mb=64,
                          meta_dir=str(tmp_path / "m"), pulse_seconds=1)
    master.start()
    vs = VolumeServer(master.url(), [str(tmp_path / "v")],
                      pulse_seconds=1)
    vs.start()
    try:
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and \
                not list(master.topo.leaves()):
            time.sleep(0.1)
        assert list(master.topo.leaves())
        env = CommandEnv(master.url())
        run_command(env, "lock")
        node = vs.server.url().replace("http://", "")
        out = run_command(env, f"volumeServer.leave -node {node}")
        assert "leaving" in out
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline and \
                list(master.topo.leaves()):
            time.sleep(0.2)
        assert not list(master.topo.leaves()), \
            "master never drained the leaving server"
    finally:
        vs.stop()
        master.stop()
