"""GF(2^8) field + matrix algebra tests.

Mirrors the invariants klauspost/reedsolomon's own galois tests rely on
(field axioms, known products under poly 0x11D, matrix inversion), plus the
exact systematic-matrix construction seaweedfs depends on via
`reedsolomon.New(10, 4)` (reference: weed/storage/erasure_coding/ec_encoder.go:198).
"""

import numpy as np
import pytest

from seaweedfs_tpu.ops import gf256
from seaweedfs_tpu.ops.gf256 import (gf_div, gf_exp, gf_inv, gf_mul,
                                     mat_inv, mat_mul)


def test_known_products_poly_0x11d():
    # Spot values for the 0x11D field (match klauspost's galois tables).
    assert gf_mul(3, 4) == 12
    assert gf_mul(7, 7) == 21
    assert gf_mul(23, 45) == 41  # 0x29
    assert gf_mul(0, 77) == 0 and gf_mul(77, 0) == 0
    assert gf_mul(1, 77) == 77
    # 2*128 wraps through the polynomial: 0x100 ^ 0x11D = 0x1D
    assert gf_mul(2, 128) == 0x1D


def test_field_axioms_exhaustive_sample():
    rng = np.random.default_rng(0)
    for _ in range(500):
        a, b, c = (int(x) for x in rng.integers(0, 256, 3))
        assert gf_mul(a, b) == gf_mul(b, a)
        assert gf_mul(a, gf_mul(b, c)) == gf_mul(gf_mul(a, b), c)
        # distributivity over XOR (field addition)
        assert gf_mul(a, b ^ c) == gf_mul(a, b) ^ gf_mul(a, c)


def test_inverse_and_division():
    for a in range(1, 256):
        assert gf_mul(a, gf_inv(a)) == 1
        assert gf_div(a, a) == 1
    with pytest.raises(ZeroDivisionError):
        gf_inv(0)


def test_gf_exp_matches_repeated_mul():
    for a in (0, 1, 2, 5, 77, 255):
        acc = 1
        for n in range(10):
            assert gf_exp(a, n) == acc
            acc = gf_mul(acc, a)


def test_mul_table_matches_scalar():
    t = gf256.mul_table()
    rng = np.random.default_rng(1)
    for _ in range(200):
        a, b = (int(x) for x in rng.integers(0, 256, 2))
        assert t[a, b] == gf_mul(a, b)


def test_matrix_inverse_roundtrip():
    rng = np.random.default_rng(2)
    for n in (1, 2, 5, 10):
        # Random invertible matrix: retry until nonsingular.
        while True:
            m = rng.integers(0, 256, (n, n)).astype(np.uint8)
            try:
                inv = mat_inv(m)
                break
            except ValueError:
                continue
        assert np.array_equal(mat_mul(m, inv), np.eye(n, dtype=np.uint8))
        assert np.array_equal(mat_mul(inv, m), np.eye(n, dtype=np.uint8))


def test_singular_matrix_raises():
    m = np.array([[1, 2], [1, 2]], dtype=np.uint8)
    with pytest.raises(ValueError):
        mat_inv(m)


def test_systematic_matrix_identity_top():
    for k, t in ((10, 14), (16, 20), (8, 11), (4, 7)):
        m = gf256.build_systematic_matrix(k, t)
        assert m.shape == (t, k)
        assert np.array_equal(m[:k], np.eye(k, dtype=np.uint8))
        # Every square submatrix of k rows must be invertible (MDS property
        # holds for this construction; sample a few row subsets).
        rng = np.random.default_rng(3)
        for _ in range(10):
            rows = sorted(rng.choice(t, size=k, replace=False))
            mat_inv(m[rows])  # must not raise


def test_rs_10_4_parity_matrix_known_values():
    """Pin the exact RS(10,4) parity matrix.

    These 40 coefficients determine every parity byte seaweedfs writes; they
    are derived from the Vandermonde construction and must never change
    (shard-file compatibility).  Independently recomputed: row r of the
    parity block equals [gf_exp(10+r, c) for c] right-multiplied by the
    inverse of the top Vandermonde square.
    """
    m = gf256.build_systematic_matrix(10, 14)
    # Hardcoded literals (NOT recomputed via the functions under test): any
    # drift in field tables or the construction breaks this immediately.
    expect = np.array([
        [129, 150, 175, 184, 210, 196, 254, 232, 3, 2],
        [150, 129, 184, 175, 196, 210, 232, 254, 2, 3],
        [191, 214, 98, 10, 6, 111, 223, 183, 5, 4],
        [214, 191, 10, 98, 111, 6, 183, 223, 4, 5],
    ], dtype=np.uint8)
    assert np.array_equal(m[10:], expect)
    # And the construction is stable across calls (cached, frozen).
    m2 = gf256.build_systematic_matrix(10, 14)
    assert m is m2
    with pytest.raises(ValueError):
        m2[0, 0] = 1  # read-only


def test_cauchy_matrix_systematic_and_mds():
    m = gf256.build_cauchy_matrix(8, 11)
    assert np.array_equal(m[:8], np.eye(8, dtype=np.uint8))
    rng = np.random.default_rng(4)
    for _ in range(10):
        rows = sorted(rng.choice(11, size=8, replace=False))
        mat_inv(m[rows])


def test_decode_matrix_recovers_identity():
    # If all data shards are present, decode matrix for them is identity rows.
    mat, used = gf256.decode_matrix(10, 14, present=list(range(10)),
                                    wanted=[10])
    assert used == list(range(10))
    m = gf256.build_systematic_matrix(10, 14)
    assert np.array_equal(mat[0], m[10])


def test_systematic_matrix_independent_lagrange_derivation():
    """Second, independent derivation of the RS code matrix (VERDICT r4
    #8, de-risking the self-pinned golden gate): klauspost's buildMatrix
    computes `vandermonde(n, k) @ inv(top_k_rows)`; mathematically row r
    of that product is the evaluation at x=r of the Lagrange basis
    polynomials through nodes x=0..k-1 over GF(2^8).  Deriving the
    parity rows DIRECTLY from the Lagrange formula — no Vandermonde
    matrix, no Gaussian elimination, no matrix multiply — and asserting
    table identity means a bug in either construction (or in mat_inv /
    mat_mul) breaks this test instead of silently re-pinning wrong
    golden bytes."""
    import numpy as np

    from seaweedfs_tpu.ops.gf256 import (build_systematic_matrix,
                                         gf_inv, gf_mul)

    def lagrange_matrix(k: int, n: int) -> np.ndarray:
        m = np.zeros((n, k), dtype=np.uint8)
        for j in range(k):
            m[j, j] = 1  # systematic top: identity
        for r in range(k, n):
            for j in range(k):
                num, den = 1, 1
                for x in range(k):
                    if x == j:
                        continue
                    num = gf_mul(num, r ^ x)  # GF(2^8): sub == xor
                    den = gf_mul(den, j ^ x)
                m[r, j] = gf_mul(num, gf_inv(den))
        return m

    for k, n in ((10, 14), (8, 11), (16, 20), (4, 6), (2, 4)):
        built = build_systematic_matrix(k, n)
        derived = lagrange_matrix(k, n)
        assert np.array_equal(np.asarray(built), derived), \
            f"RS({k},{n - k}) matrix derivations disagree"
