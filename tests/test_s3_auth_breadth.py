"""S3 auth breadth: signature v2 (header + presigned), presigned v4,
POST-policy uploads, filer-backed IAM.

Reference: weed/s3api/auth_signature_v2.go, s3api/policy/,
auth_credentials.go.
"""

import base64
import json
import time
import urllib.error
import urllib.parse
import urllib.request

import pytest

from seaweedfs_tpu.cluster.master import MasterServer
from seaweedfs_tpu.cluster.volume_server import VolumeServer
from seaweedfs_tpu.filer.server import FilerServer
from seaweedfs_tpu.s3api import Identity, S3ApiServer
from seaweedfs_tpu.s3api.auth import (
    AuthError,
    IdentityAccessManagement,
    canonical_string_v2,
    compute_signature_v4,
    derive_signing_key,
    signature_v2,
)
from seaweedfs_tpu.s3api.policy import PostPolicy, parse_multipart_form

ACCESS, SECRET = "V2ACCESSKEY", "v2-secret-key"
IDENT = Identity("alice", ACCESS, SECRET, ["Admin"])


@pytest.fixture
def iam():
    return IdentityAccessManagement([IDENT])


# -- signature v2 ------------------------------------------------------------


def _v2_sign(method, path, raw_query, headers):
    date_field = "" if "x-amz-date" in headers else headers.get("date", "")
    return signature_v2(SECRET, canonical_string_v2(
        method, path, raw_query, headers, date_field))


def test_v2_header_auth_roundtrip(iam):
    headers = {"date": time.strftime("%a, %d %b %Y %H:%M:%S GMT",
                                     time.gmtime()),
               "content-type": "text/plain"}
    sig = _v2_sign("PUT", "/bkt/key.txt", "", headers)
    headers["authorization"] = f"AWS {ACCESS}:{sig}"
    ident = iam.authenticate("PUT", "/bkt/key.txt", "", headers, b"x")
    assert ident.name == "alice"
    # Tampering with the path breaks it.
    with pytest.raises(AuthError):
        iam.authenticate("PUT", "/bkt/other.txt", "", headers, b"x")


def test_v2_subresource_in_canonical_string(iam):
    """?uploads participates in the canonical resource; ?prefix does
    not (resourceList whitelist)."""
    headers = {"date": "Mon, 01 Jan 2024 00:00:00 GMT"}
    sig = _v2_sign("POST", "/bkt/key", "uploads", headers)
    h = dict(headers, authorization=f"AWS {ACCESS}:{sig}")
    assert iam.authenticate("POST", "/bkt/key", "uploads", h, b"")
    # The same signature is NOT valid without the subresource...
    with pytest.raises(AuthError):
        iam.authenticate("POST", "/bkt/key", "", h, b"")
    # ...but non-whitelisted params don't affect it.
    assert iam.authenticate("POST", "/bkt/key", "uploads&prefix=zz",
                            h, b"")


def test_v2_presigned(iam):
    expires = int(time.time()) + 60
    sig = signature_v2(SECRET, canonical_string_v2(
        "GET", "/bkt/file.bin", "", {}, str(expires)))
    q = urllib.parse.urlencode({"AWSAccessKeyId": ACCESS,
                                "Expires": str(expires),
                                "Signature": sig})
    assert iam.authenticate("GET", "/bkt/file.bin", q, {}, b"")
    # Expired link.
    old = int(time.time()) - 10
    sig_old = signature_v2(SECRET, canonical_string_v2(
        "GET", "/bkt/file.bin", "", {}, str(old)))
    q_old = urllib.parse.urlencode({"AWSAccessKeyId": ACCESS,
                                    "Expires": str(old),
                                    "Signature": sig_old})
    with pytest.raises(AuthError) as ei:
        iam.authenticate("GET", "/bkt/file.bin", q_old, {}, b"")
    assert "expired" in str(ei.value)


def test_v4_presigned(iam):
    now = time.gmtime()
    amz_date = time.strftime("%Y%m%dT%H%M%SZ", now)
    scope = f"{time.strftime('%Y%m%d', now)}/us-east-1/s3/aws4_request"
    params = [("X-Amz-Algorithm", "AWS4-HMAC-SHA256"),
              ("X-Amz-Credential", f"{ACCESS}/{scope}"),
              ("X-Amz-Date", amz_date),
              ("X-Amz-Expires", "300"),
              ("X-Amz-SignedHeaders", "host")]
    raw = urllib.parse.urlencode(params)
    headers = {"host": "s3.example:8333"}
    sig = compute_signature_v4("GET", "/bkt/obj", raw, headers,
                               ["host"], "UNSIGNED-PAYLOAD", amz_date,
                               scope, SECRET)
    full = raw + "&" + urllib.parse.urlencode({"X-Amz-Signature": sig})
    assert iam.authenticate("GET", "/bkt/obj", full, headers, b"")
    with pytest.raises(AuthError):
        bad = raw + "&X-Amz-Signature=" + "0" * 64
        iam.authenticate("GET", "/bkt/obj", bad, headers, b"")


def test_v4_presigned_long_lived_link(iam):
    """The whole point of presigning: a link used 20 minutes after
    signing is VALID while X-Amz-Expires allows it — only the
    expiry governs age, not the header-auth skew window."""
    signed_at = time.gmtime(time.time() - 20 * 60)
    amz_date = time.strftime("%Y%m%dT%H%M%SZ", signed_at)
    scope = (f"{time.strftime('%Y%m%d', signed_at)}"
             "/us-east-1/s3/aws4_request")
    params = [("X-Amz-Algorithm", "AWS4-HMAC-SHA256"),
              ("X-Amz-Credential", f"{ACCESS}/{scope}"),
              ("X-Amz-Date", amz_date),
              ("X-Amz-Expires", "3600"),
              ("X-Amz-SignedHeaders", "host")]
    raw = urllib.parse.urlencode(params)
    headers = {"host": "h"}
    sig = compute_signature_v4("GET", "/b/k", raw, headers, ["host"],
                               "UNSIGNED-PAYLOAD", amz_date, scope,
                               SECRET)
    full = raw + "&" + urllib.parse.urlencode({"X-Amz-Signature": sig})
    assert iam.authenticate("GET", "/b/k", full, headers, b"")
    # ...but past its declared expiry it dies.
    bad = [(k, ("60" if k == "X-Amz-Expires" else v))
           for k, v in params]
    raw2 = urllib.parse.urlencode(bad)
    sig2 = compute_signature_v4("GET", "/b/k", raw2, headers, ["host"],
                                "UNSIGNED-PAYLOAD", amz_date, scope,
                                SECRET)
    with pytest.raises(AuthError) as ei:
        iam.authenticate(
            "GET", "/b/k",
            raw2 + "&" + urllib.parse.urlencode(
                {"X-Amz-Signature": sig2}), headers, b"")
    assert "expired" in str(ei.value)
    # Malformed Expires is a clean 400, not a 500.
    with pytest.raises(AuthError) as ei:
        iam.authenticate(
            "GET", "/b/k",
            raw.replace("X-Amz-Expires=3600", "X-Amz-Expires=abc")
            + "&X-Amz-Signature=" + sig, headers, b"")
    assert ei.value.status == 400


def test_iam_fail_closed():
    iam = IdentityAccessManagement([])
    iam.fail_closed = True
    with pytest.raises(AuthError) as ei:
        iam.authenticate("GET", "/", "", {}, b"")
    assert ei.value.status == 503
    with pytest.raises(AuthError):
        iam.authenticate_policy({"policy": "x"})


# -- POST policy -------------------------------------------------------------


def _policy_b64(conditions, expires_in=120):
    doc = {"expiration": time.strftime(
        "%Y-%m-%dT%H:%M:%S.000Z", time.gmtime(time.time() + expires_in)),
        "conditions": conditions}
    return base64.b64encode(json.dumps(doc).encode()).decode()


def test_policy_signature_v2_and_conditions(iam):
    policy = _policy_b64([{"bucket": "pics"},
                          ["starts-with", "$key", "user/"],
                          ["content-length-range", 1, 1024]])
    form = {"policy": policy, "AWSAccessKeyId": ACCESS,
            "Signature": signature_v2(SECRET, policy),
            "key": "user/cat.jpg", "bucket": "pics"}
    assert iam.authenticate_policy(form).name == "alice"
    pol = PostPolicy.parse(policy)
    pol.check(form, 512)
    with pytest.raises(AuthError):  # over the size range
        pol.check(form, 4096)
    with pytest.raises(AuthError):  # key prefix violated
        pol.check(dict(form, key="other/cat.jpg"), 512)
    with pytest.raises(AuthError):  # field not covered by the policy
        pol.check(dict(form, acl="public-read"), 512)
    with pytest.raises(AuthError):  # bad signature
        iam.authenticate_policy(dict(form, Signature="AAAA"))


def test_policy_signature_v4(iam):
    policy = _policy_b64([{"bucket": "pics"}])
    now = time.gmtime()
    scope = f"{time.strftime('%Y%m%d', now)}/us-east-1/s3/aws4_request"
    key = derive_signing_key(SECRET, time.strftime("%Y%m%d", now),
                             "us-east-1")
    import hashlib
    import hmac as hmac_mod
    sig = hmac_mod.new(key, policy.encode(), hashlib.sha256).hexdigest()
    form = {"policy": policy, "X-Amz-Credential": f"{ACCESS}/{scope}",
            "X-Amz-Signature": sig, "bucket": "pics"}
    assert iam.authenticate_policy(form).name == "alice"


def test_policy_checks_final_key_after_filename_substitution(stack):
    """${filename} substitutes BEFORE the policy runs, so a malicious
    filename cannot escape the signed key prefix."""
    master, vs, filer = stack
    s3 = S3ApiServer(filer.url(), identities=[IDENT])
    s3.start()
    try:
        headers = {"Date": time.strftime("%a, %d %b %Y %H:%M:%S GMT",
                                         time.gmtime())}
        sig = _v2_sign("PUT", "/polbkt", "",
                       {k.lower(): v for k, v in headers.items()})
        urllib.request.urlopen(urllib.request.Request(
            f"{s3.url()}/polbkt", method="PUT",
            headers=dict(headers,
                         Authorization=f"AWS {ACCESS}:{sig}")),
            timeout=30).read()
        policy = _policy_b64([{"bucket": "polbkt"},
                              ["eq", "$key", "safe/exact.txt"]])
        fields = {"key": "safe/${filename}", "bucket": "polbkt",
                  "policy": policy, "AWSAccessKeyId": ACCESS,
                  "Signature": signature_v2(SECRET, policy)}
        # filename that makes the FINAL key violate the eq condition
        body, ctype = _form_body(fields, b"x", filename="evil.txt")
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(f"{s3.url()}/polbkt", body, ctype)
        assert ei.value.code == 403
        # the sanctioned filename passes
        body, ctype = _form_body(fields, b"ok", filename="exact.txt")
        with _post(f"{s3.url()}/polbkt", body, ctype) as r:
            assert r.status == 204
    finally:
        s3.stop()


def test_unknown_policy_operator_rejected():
    policy = _policy_b64([["starts-with ", "$key", "x"]])  # typo'd op
    with pytest.raises(AuthError) as ei:
        PostPolicy.parse(policy).check({"key": "xyz"}, 1)
    assert ei.value.status == 400


def test_expired_policy_rejected(iam):
    policy = _policy_b64([{"bucket": "b"}], expires_in=-5)
    with pytest.raises(AuthError) as ei:
        PostPolicy.parse(policy).check({"bucket": "b"}, 1)
    assert "expired" in str(ei.value)


def test_multipart_preserves_trailing_newlines():
    """File content ending in newlines must round-trip byte-exact —
    the framing CRLF belongs to the boundary, not the content
    (review finding: text files were silently truncated)."""
    boundary = "bnd"
    payload = b"line1\nline2\n\r\n\r\n"  # hostile trailing bytes
    body = (b"--bnd\r\n"
            b'Content-Disposition: form-data; name="key"\r\n\r\n'
            b"k\r\n"
            b"--bnd\r\n"
            b'Content-Disposition: form-data; name="file"; '
            b'filename="t.txt"\r\n\r\n'
            + payload +
            b"\r\n--bnd--\r\n")
    fields, _n, fbytes, _ct = parse_multipart_form(
        body, f"multipart/form-data; boundary={boundary}")
    assert fbytes == payload
    assert fields["key"] == "k"


def test_multipart_form_parser():
    boundary = "xyzBOUNDARYxyz"
    body = (
        f"--{boundary}\r\n"
        'Content-Disposition: form-data; name="key"\r\n\r\n'
        "docs/${filename}\r\n"
        f"--{boundary}\r\n"
        'Content-Disposition: form-data; name="policy"\r\n\r\n'
        "cG9saWN5\r\n"
        f"--{boundary}\r\n"
        'Content-Disposition: form-data; name="file"; '
        'filename="report.pdf"\r\n'
        "Content-Type: application/pdf\r\n\r\n"
        "PDFBYTES\x00MORE\r\n"
        f"--{boundary}--\r\n").encode("latin-1")
    fields, fname, fbytes, fctype = parse_multipart_form(
        body, f"multipart/form-data; boundary={boundary}")
    assert fields["key"] == "docs/${filename}"
    assert fields["policy"] == "cG9saWN5"
    assert fname == "report.pdf"
    assert fbytes == b"PDFBYTES\x00MORE"
    assert fctype == "application/pdf"
    assert "Content-Type" not in fields  # file part != form field


# -- e2e: browser POST upload + filer-backed IAM ----------------------------


@pytest.fixture(scope="module")
def stack(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("s3-auth-stack")
    master = MasterServer(volume_size_limit_mb=64, meta_dir=str(tmp))
    master.start()
    vs = VolumeServer(master.url(), [str(tmp / "vs")], pulse_seconds=60)
    vs.start()
    filer = FilerServer(master.url())
    filer.start()
    yield master, vs, filer
    filer.stop()
    vs.stop()
    master.stop()


def _form_body(fields: dict, file_bytes: bytes,
               filename="up.bin") -> tuple[bytes, str]:
    boundary = "testBoundary123"
    parts = []
    for k, v in fields.items():
        parts.append(f"--{boundary}\r\nContent-Disposition: form-data; "
                     f'name="{k}"\r\n\r\n{v}\r\n')
    parts.append(f"--{boundary}\r\nContent-Disposition: form-data; "
                 f'name="file"; filename="{filename}"\r\n'
                 "Content-Type: application/octet-stream\r\n\r\n")
    body = "".join(parts).encode() + file_bytes + \
        f"\r\n--{boundary}--\r\n".encode()
    return body, f"multipart/form-data; boundary={boundary}"


def _post(url, body, ctype):
    req = urllib.request.Request(url, data=body, method="POST",
                                 headers={"Content-Type": ctype})
    return urllib.request.urlopen(req, timeout=30)


def test_post_policy_upload_e2e(stack):
    master, vs, filer = stack
    s3 = S3ApiServer(filer.url(), identities=[IDENT])
    s3.start()
    try:
        # create the bucket with sigv2 header auth — exercises v2 over
        # the real wire too
        headers = {"Date": time.strftime("%a, %d %b %Y %H:%M:%S GMT",
                                         time.gmtime())}
        sig = _v2_sign("PUT", "/postbkt", "",
                       {k.lower(): v for k, v in headers.items()})
        req = urllib.request.Request(
            f"{s3.url()}/postbkt", method="PUT",
            headers=dict(headers, Authorization=f"AWS {ACCESS}:{sig}"))
        urllib.request.urlopen(req, timeout=30).read()

        policy = _policy_b64([{"bucket": "postbkt"},
                              ["starts-with", "$key", "in/"],
                              ["content-length-range", 0, 65536]])
        fields = {"key": "in/${filename}", "bucket": "postbkt",
                  "policy": policy, "AWSAccessKeyId": ACCESS,
                  "Signature": signature_v2(SECRET, policy),
                  "success_action_status": "201"}
        payload = b"browser upload bytes " * 99
        body, ctype = _form_body(fields, payload, filename="pic.jpg")
        with _post(f"{s3.url()}/postbkt", body, ctype) as r:
            assert r.status == 201
            assert b"<Key>in/pic.jpg</Key>" in r.read()
        # The object is readable through the filer namespace.
        with urllib.request.urlopen(
                f"{filer.url()}/buckets/postbkt/in/pic.jpg",
                timeout=30) as r:
            assert r.read() == payload
        # A form with a field the policy doesn't cover is rejected.
        bad_fields = dict(fields, acl="public-read")
        body, ctype = _form_body(bad_fields, b"x")
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(f"{s3.url()}/postbkt", body, ctype)
        assert ei.value.code == 403
    finally:
        s3.stop()


def test_filer_backed_iam_hot_reload(stack):
    master, vs, filer = stack
    cfg = {"identities": [{
        "name": "filer-admin",
        "credentials": [{"accessKey": "FILERKEY",
                         "secretKey": "filersecret"}],
        "actions": ["Admin"]}]}
    req = urllib.request.Request(
        f"{filer.url()}/etc/iam/identity.json",
        data=json.dumps(cfg).encode(), method="POST")
    urllib.request.urlopen(req, timeout=30).read()

    s3 = S3ApiServer(filer.url(), iam_refresh_seconds=0.2)
    s3.start()
    try:
        assert s3.iam.enabled
        assert "FILERKEY" in s3.iam.identities
        # Unauthenticated requests are rejected now.
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"{s3.url()}/", timeout=30)
        assert ei.value.code == 403
        # Update the config through the filer: the gateway hot-reloads.
        cfg["identities"][0]["credentials"][0]["accessKey"] = "ROTATED"
        req = urllib.request.Request(
            f"{filer.url()}/etc/iam/identity.json",
            data=json.dumps(cfg).encode(), method="POST")
        urllib.request.urlopen(req, timeout=30).read()
        deadline = time.time() + 5
        while time.time() < deadline and \
                "ROTATED" not in s3.iam.identities:
            time.sleep(0.1)
        assert "ROTATED" in s3.iam.identities
        assert "FILERKEY" not in s3.iam.identities
        # Deleting the config revokes the loaded identities (back to
        # the pre-config anonymous state) — it must not leave stale
        # keys working forever.
        urllib.request.urlopen(urllib.request.Request(
            f"{filer.url()}/etc/iam/identity.json", method="DELETE"),
            timeout=30).read()
        deadline = time.time() + 5
        while time.time() < deadline and s3.iam.identities:
            time.sleep(0.1)
        assert not s3.iam.identities
        assert not s3.iam.fail_closed
    finally:
        s3.stop()
