"""Profiling hooks + status UIs (reference weed/util/grace/pprof.go,
server/*_ui): /debug/pprof handlers (opt-in) and HTML status pages."""

import threading
import time
import urllib.request

import pytest

from seaweedfs_tpu.cluster.master import MasterServer
from seaweedfs_tpu.cluster.volume_server import VolumeServer
from seaweedfs_tpu.filer.server import FilerServer


@pytest.fixture(scope="module", autouse=True)
def _stop_continuous_profiler():
    """Mounting pprof routes starts the process-wide continuous
    profiler; stop it on module exit so its 19Hz sampling (and its
    traced allocations) can't skew later test modules."""
    yield
    from seaweedfs_tpu.utils.pprof import PROFILER
    if PROFILER is not None:
        PROFILER.stop()


@pytest.fixture(scope="module")
def stack(tmp_path_factory):
    import os
    os.environ["SEAWEEDFS_TPU_PPROF"] = "1"
    tmp = tmp_path_factory.mktemp("ui-stack")
    master = MasterServer(volume_size_limit_mb=64, meta_dir=str(tmp))
    master.start()
    vs = VolumeServer(master.url(), [str(tmp / "vs")], pulse_seconds=60)
    vs.start()
    filer = FilerServer(master.url())
    filer.start()
    yield master, vs, filer
    filer.stop()
    vs.stop()
    master.stop()
    os.environ.pop("SEAWEEDFS_TPU_PPROF", None)


def _get(url):
    with urllib.request.urlopen(url, timeout=30) as r:
        return r.status, r.read(), r.headers.get("Content-Type", "")


def test_status_uis(stack):
    master, vs, filer = stack
    urllib.request.urlopen(urllib.request.Request(
        f"{filer.url()}/seed.txt", data=b"x", method="POST"),
        timeout=30).read()
    vs._send_heartbeat(full=True)
    st, body, ctype = _get(f"{master.url()}/ui")
    assert st == 200 and ctype.startswith("text/html")
    assert vs.url().encode() in body  # topology table shows the node
    st, body, ctype = _get(f"http://{vs.url()}/ui")
    assert st == 200 and b"Volume server" in body
    assert b"rw" in body  # at least one volume row
    st, body, ctype = _get(f"{filer.url()}/.ui")
    assert st == 200 and b"Filer" in body


def test_ui_escapes_hostile_names(tmp_path):
    """Client-controlled strings (collection, rack names) render inert
    — a hostile name must not script the operator's browser."""
    import os
    os.environ["SEAWEEDFS_TPU_PPROF"] = "1"
    master = MasterServer(volume_size_limit_mb=64,
                          meta_dir=str(tmp_path))
    master.start()
    vs = VolumeServer(master.url(), [str(tmp_path / "vs")],
                      pulse_seconds=60,
                      rack="<script>alert(1)</script>")
    vs.start()
    try:
        st, body, _ = _get(f"{master.url()}/ui")
        assert st == 200
        assert b"<script>alert(1)</script>" not in body
        assert b"&lt;script&gt;" in body
        st, body, _ = _get(f"http://{vs.url()}/ui")
        assert b"<script>alert(1)</script>" not in body
    finally:
        vs.stop()
        master.stop()


def test_pprof_threads_and_heap(stack):
    import tracemalloc
    master, _vs, _filer = stack
    st, body, _ = _get(f"{master.url()}/debug/pprof/threads")
    assert st == 200
    assert b"http:" in body or b"MainThread" in body  # real stacks
    try:
        st, body, _ = _get(f"{master.url()}/debug/pprof/heap")
        assert st == 200  # first call starts tracemalloc
        st, body, _ = _get(f"{master.url()}/debug/pprof/heap")
        assert st == 200 and b"traced:" in body
    finally:
        # ?stop=true turns allocation tracing back off (review finding:
        # it must not tax the process forever).
        st, body, _ = _get(f"{master.url()}/debug/pprof/heap?stop=true")
        assert st == 200
        assert not tracemalloc.is_tracing()


def test_pprof_profile_samples_other_threads(stack):
    """The CPU sampler must see work on OTHER threads — per-thread
    cProfile showed an idle process no matter the load (review
    finding)."""
    _m, vs, _f = stack
    stop = threading.Event()

    def very_recognizable_busy_loop():
        while not stop.is_set():
            sum(i * i for i in range(1000))

    t = threading.Thread(target=very_recognizable_busy_loop,
                         daemon=True)
    t.start()
    try:
        st, body, _ = _get(
            f"http://{vs.url()}/debug/pprof/profile?seconds=0.5")
    finally:
        stop.set()
        t.join()
    assert st == 200
    assert b"samples" in body
    assert b"very_recognizable_busy_loop" in body


def test_pprof_routes_absent_without_optin(tmp_path):
    import os
    assert os.environ.get("SEAWEEDFS_TPU_PPROF") != "1" or True
    saved = os.environ.pop("SEAWEEDFS_TPU_PPROF", None)
    try:
        master = MasterServer(volume_size_limit_mb=64,
                              meta_dir=str(tmp_path))
        master.start()
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(f"{master.url()}/debug/pprof/threads")
            assert ei.value.code == 404
        finally:
            master.stop()
    finally:
        if saved is not None:
            os.environ["SEAWEEDFS_TPU_PPROF"] = saved


import urllib.error  # noqa: E402


def _get_status(url):
    try:
        with urllib.request.urlopen(url, timeout=30) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def test_profile_seconds_validation(stack):
    """Satellite: ?seconds= must 400 on unparseable/NaN/inf and clamp
    negative/zero/huge into [0.1, 30] instead of looping oddly."""
    master, _vs, _filer = stack
    base = f"{master.url()}/debug/pprof/profile"
    for bad in ("abc", "NaN", "nan", "inf", "-inf", "1e999"):
        st, body = _get_status(f"{base}?seconds={bad}")
        assert st == 400, (bad, st, body)
    # Clamped low: returns fast with a tiny live sample.
    t0 = time.time()
    st, body = _get_status(f"{base}?seconds=-5")
    assert st == 200 and time.time() - t0 < 2.0
    st, body = _get_status(f"{base}?seconds=0")
    assert st == 200
    # Measured rate is reported in the header line, not the nominal.
    assert b"Hz measured" in body
    st, body = _get_status(f"{master.url()}/debug/pprof/heap?top=xyz")
    assert st == 400


def test_heap_start_stop_race_serialized(stack):
    """Satellite: concurrent /debug/pprof/heap start/snapshot/stop
    calls race tracemalloc's process-global world switch — they must
    serialize behind the handler lock, never 500."""
    import tracemalloc
    master, _vs, _filer = stack
    base = f"{master.url()}/debug/pprof/heap"
    statuses = []
    lock = threading.Lock()

    def hammer(i):
        for j in range(6):
            url = base + ("?stop=true" if (i + j) % 3 == 0 else "")
            st, _ = _get_status(url)
            with lock:
                statuses.append(st)

    threads = [threading.Thread(target=hammer, args=(i,))
               for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    try:
        assert all(st == 200 for st in statuses), statuses
    finally:
        _get_status(base + "?stop=true")
        assert not tracemalloc.is_tracing()


def test_sample_stacks_rate_drift_compensated():
    """Satellite: the sampler schedules ticks on an absolute grid, so
    collection cost no longer erodes the delivered rate; callers get
    the measured elapsed to report real Hz."""
    from seaweedfs_tpu.utils.pprof import sample_stacks
    stop = threading.Event()
    threads = [threading.Thread(
        target=lambda: stop.wait(5.0), daemon=True)
        for _ in range(24)]  # many threads = real collection cost
    for t in threads:
        t.start()
    try:
        counts, samples, elapsed = sample_stacks(0.5, hz=80.0)
    finally:
        stop.set()
    assert counts and samples > 0
    measured = samples / elapsed
    # Old behavior: sleep(interval) AFTER collecting -> delivered rate
    # = 1/(interval + cost), well under 80 with 24 threads.  The grid
    # scheduler holds it near nominal (CI-tolerant band).
    assert measured > 55.0, f"measured only {measured:.1f}Hz"
    assert elapsed == pytest.approx(0.5, abs=0.1)


def test_cpuprofile_flag_writes_collapsed_stacks(tmp_path):
    """-cpuprofile on any subcommand samples ALL threads and dumps
    flamegraph-compatible collapsed stacks at exit
    (grace.SetupProfiling analog)."""
    import subprocess
    import sys
    out = tmp_path / "cpu.stacks"
    subprocess.run(
        [sys.executable, "-c",
         "from seaweedfs_tpu.utils.jaxenv import force_cpu; force_cpu()\n"
         "import sys, runpy, time\n"
         f"sys.argv=['weed','version','-cpuprofile={out}']\n"
         "try: runpy.run_module('seaweedfs_tpu', run_name='__main__')\n"
         "except SystemExit: pass\n"
         "t=time.monotonic()\n"
         "while time.monotonic()-t < 1.5: sum(i*i for i in range(1000))"],
        check=True, capture_output=True, timeout=120,
        cwd="/root/repo")
    assert out.exists()
    text = out.read_text()
    assert text.strip(), "no samples recorded"
    # collapsed-stack lines: frame;frame;... count
    line = text.splitlines()[0]
    assert ";" in line or "(" in line
    assert line.rsplit(" ", 1)[1].isdigit()
