"""Metadata plane HA: sharded filer fleet with crash-safe
log-replicated shards, epoch-fenced failover, and shard-map-aware
clients.

The namespace shards on the first path component; each shard primary
frames every acked mutation into a CRC-framed `.mlog` journal
(replication/rlog.py FramedLog), fsyncs it, and semi-sync-replicates
it to in-sync followers BEFORE the 200.  The master owns the shard
map (filers register and heartbeat like volume servers) and promotes
the most-caught-up follower at epoch+1 when a primary dies.

The PR acceptance gates live here:

- `test_kill_primary_mid_storm_zero_acked_op_loss` — a shard primary
  is killed (kill -9 analog: no demote, no goodbye pulse) in the
  middle of a create/rename storm; the master promotes a follower,
  shard-map-aware clients converge on it, and EVERY op acked before
  the kill is still present after the failover.
- `test_partition_during_move_no_dual_primary_ack` — `wan.partition`
  armed against the old primary while the master moves the shard: at
  no point do two filers ack writes for the shard (the partitioned
  side fails closed when its lease TTL lapses; its pushes are fenced
  by epoch), and after heal the trees converge equal on every
  replica.
- torn-mlog restart — a crash mid-append tears the journal tail; the
  reopen truncates exactly the torn frame and the seq chain resumes.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from seaweedfs_tpu import fault
from seaweedfs_tpu.cluster import resilience, rpc
from seaweedfs_tpu.cluster.master import MasterServer
from seaweedfs_tpu.cluster.volume_server import VolumeServer
from seaweedfs_tpu.filer import Filer, MemoryStore
from seaweedfs_tpu.filer.client import (FilerProxy, ShardedFilerClient)
from seaweedfs_tpu.filer.meta_aggregator import ShardMetaAggregator
from seaweedfs_tpu.filer.metaha import (ShardPlane, ShardWriteError,
                                        shard_key, shard_of)
from seaweedfs_tpu.filer.server import FilerServer
from seaweedfs_tpu.replication.rlog import FramedLog
from seaweedfs_tpu.stats.promcheck import validate_exposition

pytestmark = pytest.mark.metaha


@pytest.fixture(autouse=True)
def _clean():
    fault.disarm_all()
    resilience.reset_breakers()
    yield
    fault.disarm_all()
    resilience.reset_breakers()


def _wait(cond, timeout=20.0, msg="condition never held"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.05)
    raise AssertionError(msg)


# -- shard keying ------------------------------------------------------------

def test_shard_key_first_path_component():
    assert shard_key("/a/b/c") == "a"
    assert shard_key("/a") == "a"
    assert shard_key("a/b") == "a"
    assert shard_key("/") == ""
    # A rename inside one top-level tree is single-shard by
    # construction — the whole subtree hashes on the same component.
    for n in (1, 2, 7, 64):
        assert shard_of("/proj/deep/file", n) == shard_of("/proj", n)
        assert 0 <= shard_of("/proj", n) < n


def _dir_for_shard(k: int, num_shards: int) -> str:
    """A top-level directory name that hashes to shard `k`."""
    i = 0
    while True:
        name = f"d{k}x{i}"
        if shard_of("/" + name, num_shards) == k:
            return name
        i += 1


# -- FramedLog: the shard `.mlog` -------------------------------------------

def test_framed_log_append_read_restart(tmp_path):
    path = str(tmp_path / "s.mlog")
    log = FramedLog(path)
    for i in range(5):
        assert log.append(1, {"op": "set", "n": i}) == i + 1
    log.sync()
    assert [r["n"] for _s, _e, r in log.read_from(3)] == [2, 3, 4]
    log.close()
    # Restart: seqs, epoch, and payloads all recover from the file.
    log2 = FramedLog(path)
    assert (log2.first_seq, log2.last_seq, log2.last_epoch) == (1, 5, 1)
    assert log2.append(2, {"op": "set", "n": 5}) == 6
    assert log2.read_from(6) == [(6, 2, {"op": "set", "n": 5})]
    log2.close()


def test_framed_log_torn_tail_truncated_on_restart(tmp_path):
    """THE torn-mlog gate: a kill -9 mid-append leaves a half-written
    frame; reopen drops exactly that frame — every fsync'd (acked)
    record survives and the seq chain resumes where it stopped."""
    path = str(tmp_path / "torn.mlog")
    log = FramedLog(path)
    for i in range(8):
        log.append(3, {"op": "set", "n": i})
    log.sync()
    log.close()
    with open(path, "ab") as f:           # torn frame: header only,
        f.write(b"\x00" * 9)              # no payload, no CRC
    log2 = FramedLog(path)
    assert log2.last_seq == 8
    assert [r["n"] for _s, _e, r in log2.read_from(1)] == list(range(8))
    assert log2.append(3, {"op": "set", "n": 8}) == 9
    log2.close()
    # CRC-bad full frame (bit rot in the tail) is also stepped over.
    data = open(path, "rb").read()
    with open(path, "wb") as f:
        f.write(data[:-1] + bytes([data[-1] ^ 0xFF]))
    log3 = FramedLog(path)
    assert log3.last_seq == 8
    log3.close()


def test_framed_log_seq_gap_raises_and_follower_passthrough(tmp_path):
    log = FramedLog(str(tmp_path / "gap.mlog"))
    assert log.append(1, {"n": 0}, seq=7) == 7  # follower bootstrap:
    assert log.first_seq == 7                   # any starting seq
    assert log.append(1, {"n": 1}, seq=8) == 8
    with pytest.raises(ValueError):
        log.append(1, {"n": 9}, seq=10)         # gap: refuse
    assert log.last_seq == 8
    log.close()


def test_framed_log_truncate_from_returns_newest_first(tmp_path):
    log = FramedLog(str(tmp_path / "cut.mlog"))
    for i in range(6):
        log.append(1, {"n": i})
    dropped = log.truncate_from(4)
    assert [r["n"] for _s, _e, r in dropped] == [5, 4, 3]
    assert log.last_seq == 3
    assert log.append(2, {"n": 99}) == 4  # chain resumes at the cut
    log.close()


# -- ShardPlane: fencing, idempotency, semi-sync ----------------------------

def _plane(tmp_path, url="http://127.0.0.1:1"):
    f = Filer(store=MemoryStore())
    plane = ShardPlane(f, str(tmp_path / "ha"), url, pulse_seconds=5.0)
    plane.num_shards = 2
    return f, plane


def test_apply_record_fences_stale_epochs_durably(tmp_path):
    f, plane = _plane(tmp_path)
    st, _ = plane.apply_record(0, 2, 1, {"op": "kv", "key": "a",
                                         "val": None})
    assert st == 200
    # A push from a deposed primary at the old epoch is refused.
    st, doc = plane.apply_record(0, 1, 2, {"op": "kv", "key": "b",
                                           "val": None})
    assert st == 409 and doc["current"] == 2
    # The fence survives a restart (shard_epochs.json is durable,
    # written BEFORE any record at the new epoch is accepted).
    plane.stop()
    f2, plane2 = _plane(tmp_path)
    st, _ = plane2.apply_record(0, 1, 2, {"op": "kv", "key": "b",
                                          "val": None})
    assert st == 409
    plane2.stop()
    f.close()
    f2.close()


def test_apply_record_idempotent_and_gap_refused(tmp_path):
    f, plane = _plane(tmp_path)
    rec = {"op": "set", "entry": {"path": "/x/a", "is_directory": True}}
    assert plane.apply_record(0, 1, 1, rec)[0] == 200
    st, doc = plane.apply_record(0, 1, 1, rec)  # replay: no-op, acked
    assert st == 200 and doc["dup"]
    st, doc = plane.apply_record(0, 1, 5, rec)  # gap: refused unacked
    assert st == 409 and "gap" in doc["error"]
    assert plane.log_for(0).last_seq == 1
    plane.stop()
    f.close()


def test_primary_fails_closed_without_master_contact(tmp_path):
    """No master contact, no acks: the lease-TTL half of the
    no-dual-primary guarantee (the epoch fence is the other)."""
    f, plane = _plane(tmp_path)
    shard = shard_of("/solo", 2)
    plane.acquire(shard, 1, followers=[])
    verdict = plane.gate("/solo/file")    # lease never renewed
    assert verdict is not None and verdict[0] == 503
    assert "lease" in verdict[1]["error"]
    plane.note_master_contact()           # a pulse landed: acks resume
    assert plane.gate("/solo/file") is None
    plane.stop()
    f.close()


def test_semi_sync_refuses_when_no_follower_acks(tmp_path):
    """The zero-acked-op-loss bar: with followers configured but none
    reachable, the primary journals locally then REFUSES the ack —
    an acked op always exists on at least two disks."""
    f, plane = _plane(tmp_path)
    shard = shard_of("/twod", 2)
    plane.acquire(shard, 1,
                  followers=["http://127.0.0.1:9"])  # nothing there
    plane.note_master_contact()
    with pytest.raises(ShardWriteError) as ei:
        plane.on_op({"op": "set",
                     "entry": {"path": "/twod", "is_directory": True}},
                    "/twod")
    assert ei.value.status == 503
    assert "no in-sync follower" in ei.value.doc["error"]
    plane.stop()
    f.close()


# -- the fleet ---------------------------------------------------------------

SHARDS = 2
PULSE = 0.4


def _start_fleet(tmp, n_filers=3):
    (tmp / "master").mkdir(exist_ok=True)
    master = MasterServer(volume_size_limit_mb=64,
                          meta_dir=str(tmp / "master"),
                          pulse_seconds=PULSE, filer_shards=SHARDS)
    master.start()
    vs = VolumeServer(master.url(), [str(tmp / "vs")], pulse_seconds=60)
    vs.start()
    filers = []
    for i in range(n_filers):
        fs = FilerServer(master.url(), pulse_seconds=PULSE,
                         ha_dir=str(tmp / f"ha{i}"))
        fs.start()
        filers.append(fs)
    _wait(lambda: all(fs.shards.armed and
                      len(fs.shards.map) == SHARDS for fs in filers),
          msg="shard map never armed on every filer")
    return master, vs, filers


def _stop_fleet(master, vs, filers):
    fault.disarm_all()
    resilience.reset_breakers()
    for fs in filers:
        try:
            fs.stop()
        except Exception:  # noqa: BLE001 — hard-killed mid-test
            pass
    vs.stop()
    master.stop()


def _hard_kill(fs: FilerServer) -> None:
    """kill -9 analog: the process vanishes — no demote, no goodbye
    pulse, journals exactly as the last fsync left them."""
    fs._hb_stop.set()
    fs.server.stop()
    fs.filer.shard_sink = None
    fs.shards.stop()


def _primary_of(master, shard: int) -> str:
    doc = rpc.call(master.url() + "/cluster/filer/shards")
    return (doc["shards"].get(str(shard)) or {}).get("primary")


def _by_url(filers, url):
    return next(fs for fs in filers if fs.url() == url)


def _wait_insync(filers, master, shard: int, n: int = 1):
    def ok():
        url = _primary_of(master, shard)
        if not url:
            return False
        try:
            fs = _by_url(filers, url)
        except StopIteration:
            return False
        return len(fs.shards._insync.get(shard, ())) >= n
    _wait(ok, msg=f"shard {shard} never reached {n} in-sync followers")


def _tree(fs: FilerServer, path: str) -> dict:
    """Recursive {path: is_directory} snapshot straight off the local
    store — reads are ungated, so this sees exactly what replicated."""
    out = {}
    try:
        entries = fs.filer.list_entries(path, "", False, 10_000)
    except Exception:  # noqa: BLE001 — dir not replicated (yet)
        return out
    for e in entries:
        out[e.path] = e.is_directory
        if e.is_directory:
            out.update(_tree(fs, e.path))
    return out


def test_fleet_routes_replicates_and_hints(tmp_path):
    master, vs, filers = _start_fleet(tmp_path)
    try:
        for k in range(SHARDS):
            _wait_insync(filers, master, k)
        cl = ShardedFilerClient(master.url(), map_ttl=0.2)
        d = _dir_for_shard(0, SHARDS)
        cl.mkdir(f"/{d}")
        cl.mkdir(f"/{d}/inner")
        cl.rename(f"/{d}/inner", f"/{d}/moved")
        shard = shard_of(f"/{d}", SHARDS)
        primary = _primary_of(master, shard)
        # 409 wrong-shard from a non-primary carries the primary hint.
        other = next(fs for fs in filers if fs.url() != primary)
        req = urllib.request.Request(other.url() + f"/{d}/nope",
                                     data=b"x", method="POST")
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=10)
        assert ei.value.code == 409
        hint = json.loads(ei.value.read())
        assert hint["error"] == "wrong shard"
        assert hint["primary"] == primary and hint["shard"] == shard
        # Cross-shard rename is refused up front (400, not a partial
        # delete+create split across two histories).
        d1 = _dir_for_shard(1, SHARDS)
        cl.mkdir(f"/{d1}")
        with pytest.raises((rpc.RpcError,
                            urllib.error.HTTPError)) as ei:
            FilerProxy(primary).rename(f"/{d}", f"/{d1}/stolen")
        assert getattr(ei.value, "status", getattr(ei.value, "code",
                                                   0)) == 400
        # Semi-sync: the acked rename is already on every follower's
        # journal; the store catches up within a tailer beat.
        pfs = _by_url(filers, primary)
        want = pfs.shards.log_for(shard).last_seq
        followers = [fs for fs in filers if fs.url() != primary]
        _wait(lambda: all(
            fs.shards.log_for(shard).watermark.value >= want
            for fs in followers), msg="followers never leveled")
        for fs in followers:
            t = _tree(fs, f"/{d}")
            assert f"/{d}/moved" in t and f"/{d}/inner" not in t
        # The fleet shows up in the master's health rollup.
        hz = rpc.call(master.url() + "/cluster/healthz")
        assert {r["url"] for r in hz["filers"]["nodes"]} == \
            {fs.url() for fs in filers}
        assert not [p for p in hz["problems"] if "filer" in p]
    finally:
        _stop_fleet(master, vs, filers)


def test_kill_primary_mid_storm_zero_acked_op_loss(tmp_path):
    """THE failover gate: kill -9 the shard primary mid create/rename
    storm.  Every op acked before the kill survives the promotion,
    the most-caught-up follower takes over at epoch+1, and the
    shard-map-aware client converges without surfacing the death."""
    master, vs, filers = _start_fleet(tmp_path)
    try:
        shard = 0
        d = _dir_for_shard(shard, SHARDS)
        _wait_insync(filers, master, shard, n=2)
        old_primary = _primary_of(master, shard)
        old_epoch = rpc.call(master.url() + "/cluster/filer/shards")[
            "shards"][str(shard)]["epoch"]
        cl = ShardedFilerClient(master.url(), map_ttl=0.2,
                                contested_deadline=20.0)
        cl.mkdir(f"/{d}")
        acked: list[tuple[str, str]] = []  # (kind, path) in ack order
        for i in range(10):
            cl.mkdir(f"/{d}/pre{i}")
            acked.append(("dir", f"/{d}/pre{i}"))
        cl.rename(f"/{d}/pre0", f"/{d}/ren0")
        acked[0] = ("dir", f"/{d}/ren0")
        _hard_kill(_by_url(filers, old_primary))
        # The storm keeps going THROUGH the failover: the client eats
        # the contested 503s (old primary gone, promotion in flight)
        # and lands every op on the promoted follower.
        for i in range(10):
            cl.mkdir(f"/{d}/post{i}")
            acked.append(("dir", f"/{d}/post{i}"))
        cl.rename(f"/{d}/post0", f"/{d}/renp")
        acked[10] = ("dir", f"/{d}/renp")
        new_primary = _primary_of(master, shard)
        assert new_primary and new_primary != old_primary
        new_epoch = rpc.call(master.url() + "/cluster/filer/shards")[
            "shards"][str(shard)]["epoch"]
        assert new_epoch > old_epoch, "promotion must bump the fence"
        # ZERO acked-op loss: every ack is visible on the new primary.
        t = _tree(_by_url(filers, new_primary), f"/{d}")
        for _kind, path in acked:
            assert path in t, f"acked {path} lost across failover"
        assert f"/{d}/pre0" not in t and f"/{d}/post0" not in t
        # The surviving follower converges on the same tree.
        live = [fs for fs in filers
                if fs.url() not in (old_primary, new_primary)]
        want = _by_url(filers,
                       new_primary).shards.log_for(shard).last_seq
        _wait(lambda: all(
            fs.shards.log_for(shard).watermark.value >= want
            for fs in live), msg="survivor follower never leveled")
        for fs in live:
            assert _tree(fs, f"/{d}") == t
    finally:
        _stop_fleet(master, vs, filers)


def test_partition_during_move_no_dual_primary_ack(tmp_path):
    """THE split-brain gate: `wan.partition` cuts the old primary off
    mid shard-move.  The partitioned side fails CLOSED when its lease
    TTL lapses (never acks in the dark), its late pushes are fenced by
    epoch, the promoted side acks — and after heal every replica's
    tree is equal."""
    master, vs, filers = _start_fleet(tmp_path)
    try:
        shard = 1
        d = _dir_for_shard(shard, SHARDS)
        _wait_insync(filers, master, shard, n=2)
        cl = ShardedFilerClient(master.url(), map_ttl=0.2,
                                contested_deadline=20.0)
        cl.mkdir(f"/{d}")
        cl.mkdir(f"/{d}/base")
        a_url = _primary_of(master, shard)
        a = _by_url(filers, a_url)
        b = next(fs for fs in filers if fs.url() != a_url)
        fault.arm("wan.partition", f"fail*100000~{a_url}")
        try:
            move_body = json.dumps({"shard": shard,
                                    "to": b.url()}).encode()
            # A move while the old primary's lease may still be live
            # behind the partition fails CLOSED — transferring now
            # could produce two acking primaries (the geo lease-move
            # stance).
            st, doc = rpc.call_status(
                master.url() + "/cluster/filer/shards/move", "POST",
                move_body)
            assert st == 503 and "NOT moved" in json.dumps(doc)
            assert _primary_of(master, shard) == a_url
            # A's pulses die behind the partition; its lease TTL
            # (3 pulses) lapses and it stops acking — in the dark,
            # fail closed.
            _wait(lambda: a.shards.gate(f"/{d}/x") is not None,
                  msg="partitioned primary never failed closed")
            st = a.shards.gate(f"/{d}/x")
            assert st[0] == 503 and "lease" in st[1]["error"]
            # Once the master has seen the TTL out, the move goes
            # through (the sweep may promote on its own first — the
            # retry then transfers from that interim primary).
            def try_move():
                s, mdoc = rpc.call_status(
                    master.url() + "/cluster/filer/shards/move",
                    "POST", move_body)
                return s == 200 and (mdoc.get("moved") or
                                     mdoc.get("already"))
            _wait(try_move, msg="move never cleared the lease TTL")
            assert _primary_of(master, shard) == b.url()
            moved_epoch = rpc.call(
                master.url() + "/cluster/filer/shards")["shards"][
                str(shard)]["epoch"]
            # NO DUAL ACK: a write straight at A is refused...
            with pytest.raises((rpc.RpcError, OSError)) as ei:
                FilerProxy(a_url).mkdir(f"/{d}/brainA")
            assert getattr(ei.value, "status", 503) >= 500
            # ...while the promoted primary acks through the client
            # (B's in-sync pushes to A die on the partition too; the
            # third filer acks the semi-sync write).
            cl.refresh_map(force=True)
            cl.mkdir(f"/{d}/during")
            # A late push at A's old epoch is fenced with 409 by the
            # promoted primary — the other half of the guarantee.
            st, fdoc = rpc.call_status(
                b.url() + "/.meta/shard/apply", "POST",
                json.dumps({"shard": shard, "epoch": moved_epoch - 1,
                            "seq": 1,
                            "record": {"op": "kv", "key": "z",
                                       "val": None}}).encode())
            assert st == 409 and "stale epoch" in fdoc["error"]
        finally:
            fault.disarm_all()
            resilience.reset_breakers()
        # Heal: A heartbeats again, adopts the moved map as a
        # follower, and its tailer levels it with the new history.
        _wait(lambda: a.shards.role(shard) == "follower",
              msg="healed primary never demoted itself")
        want = b.shards.log_for(shard).last_seq
        _wait(lambda: all(
            fs.shards.log_for(shard).watermark.value >= want
            for fs in filers if fs is not b),
            msg="healed fleet never leveled")
        trees = [_tree(fs, f"/{d}") for fs in filers]
        assert trees[0] == trees[1] == trees[2]
        assert f"/{d}/during" in trees[0]
        assert f"/{d}/brainA" not in trees[0]
    finally:
        _stop_fleet(master, vs, filers)


def test_shard_subscribe_resumes_by_seq_across_fleet(tmp_path):
    """Cluster-wide (shard, seq) subscription: exact resume positions
    that survive because seqs ARE the replicated history."""
    master, vs, filers = _start_fleet(tmp_path, n_filers=2)
    try:
        for k in range(SHARDS):
            _wait_insync(filers, master, k)
        cl = ShardedFilerClient(master.url(), map_ttl=0.2)
        dirs = [_dir_for_shard(k, SHARDS) for k in range(SHARDS)]
        for d in dirs:
            cl.mkdir(f"/{d}")
        recs, cursors = cl.poll_events()
        made = {r["record"]["entry"]["path"] for r in recs
                if r["record"].get("op") == "set"}
        assert {f"/{d}" for d in dirs} <= made
        assert set(cursors) == set(range(SHARDS))
        # Resume: only records past the cursor come back, from every
        # shard's own primary.
        for d in dirs:
            cl.mkdir(f"/{d}/next")
        recs2, cursors2 = cl.poll_events(cursors)
        paths2 = {r["record"]["entry"]["path"] for r in recs2
                  if r["record"].get("op") == "set"}
        assert paths2 == {f"/{d}/next" for d in dirs}
        assert all(cursors2[k] > cursors[k] for k in cursors)
        # The aggregator rides the same cursors on a thread.
        agg = ShardMetaAggregator(master.url())
        seen = []
        agg.subscribe(lambda s, q, r: seen.append((s, q,
                                                   r.get("op"))))
        agg.start(cursors2)
        cl.mkdir(f"/{dirs[0]}/live")
        _wait(lambda: any(op == "set" for _s, _q, op in seen),
              msg="aggregator never saw the live op")
        agg.stop()
    finally:
        _stop_fleet(master, vs, filers)


def test_shard_metrics_promcheck(tmp_path):
    master, vs, filers = _start_fleet(tmp_path, n_filers=2)
    try:
        for k in range(SHARDS):
            _wait_insync(filers, master, k)
        cl = ShardedFilerClient(master.url(), map_ttl=0.2)
        d = _dir_for_shard(0, SHARDS)
        cl.mkdir(f"/{d}")
        cl.mkdir(f"/{d}/one")
        text = "\n".join(fs.metrics_registry.expose()
                         for fs in filers)
        for fam in ("SeaweedFS_filer_shard_journal_records_total",
                    "SeaweedFS_filer_shard_apply_total"):
            assert fam in text, f"{fam} missing from the exposition"
        for fs in filers:
            t = fs.metrics_registry.expose()
            assert validate_exposition(t) == [], \
                validate_exposition(t)[:5]
    finally:
        _stop_fleet(master, vs, filers)
