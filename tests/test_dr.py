"""Disaster recovery: the durable per-volume change log (`.rlog`),
cross-cluster active/passive mirroring, and verified failover.

Three layers, matching the replication plane's own structure:

- `.rlog` / `.rwm` unit tests — crash-safe append/recover semantics
  (torn tail, CRC-bad tail, rotten head, vacuum compaction, watermark
  monotonicity) on a bare tmpdir, no servers.
- A two-cluster `mirror` fixture (primary = single-node-raft master +
  volume server with `-replicate.peer`; standby = plain master +
  volume server) driving the real shipper: byte-identical convergence,
  tombstone propagation (a delete must never resurrect), duplicate
  delivery, WAN partition + heal, the master's lag SLO in
  /cluster/healthz, raft leader failover with records in flight,
  `volume.fsck -crc -json` convergence proof, the cluster.mirror.*
  shell verbs, and promcheck-gated metrics.
- Function-scoped chaos: restart both sides mid-backlog (shipping
  resumes exactly from the durable watermarks) and
  `cluster.mirror.cutover` under live client load with zero
  client-visible errors and zero acked-write loss.
"""

import json
import os
import threading
import time

import pytest

from seaweedfs_tpu import fault
from seaweedfs_tpu.cluster import resilience, rpc
from seaweedfs_tpu.cluster.client import WeedClient
from seaweedfs_tpu.cluster.master import MasterServer
from seaweedfs_tpu.cluster.volume_server import VolumeServer
from seaweedfs_tpu.core import types as t
from seaweedfs_tpu.replication import rlog as rl
from seaweedfs_tpu.replication.rlog import (LogRecord, RECORD_SIZE,
                                            ReplicationLog, Watermark)
from seaweedfs_tpu.shell import CommandEnv, run_command
from seaweedfs_tpu.stats.metrics import replication_resends_total
from seaweedfs_tpu.stats.promcheck import validate_exposition

pytestmark = pytest.mark.dr


@pytest.fixture(autouse=True)
def _clean():
    fault.disarm_all()
    resilience.reset_breakers()
    yield
    fault.disarm_all()
    resilience.reset_breakers()


def _wait(cond, timeout=20.0, msg="condition never held"):
    deadline = time.time() + timeout
    while not cond():
        if time.time() > deadline:
            raise TimeoutError(msg)
        time.sleep(0.05)


# -- change-log unit tests ---------------------------------------------------

def test_record_roundtrip_and_crc_gate():
    rec = LogRecord(7, rl.OP_WRITE, 0xDEADBEEF, 1234, 77, 999_000)
    buf = rec.to_bytes()
    assert len(buf) == RECORD_SIZE == 40
    assert LogRecord.from_bytes(buf) == rec
    # One flipped byte anywhere must fail the CRC gate.
    assert LogRecord.from_bytes(buf[:-1] + bytes([buf[-1] ^ 1])) is None
    assert LogRecord.from_bytes(bytes([buf[0] ^ 0x80]) + buf[1:]) is None
    # A short buffer is a torn tail, not an exception.
    assert LogRecord.from_bytes(buf[:RECORD_SIZE - 1]) is None


def test_append_read_reopen_resume(tmp_path):
    base = str(tmp_path / "7")
    log = ReplicationLog(base)
    for i in range(5):
        assert log.append(rl.OP_WRITE, 100 + i, 9, 64) == i + 1
    recs = log.read_from(1, 100)
    assert [r.seq for r in recs] == [1, 2, 3, 4, 5]
    assert [r.needle_id for r in recs] == [100, 101, 102, 103, 104]
    # Arithmetic seek: start mid-log, bounded batch.
    assert [r.seq for r in log.read_from(3, 2)] == [3, 4]
    log.close()
    log2 = ReplicationLog(base)
    assert (log2.first_seq, log2.last_seq) == (1, 5)
    assert log2.append(rl.OP_DELETE, 100, 0, 0) == 6
    log2.close()


def test_torn_partial_tail_truncated_on_open(tmp_path):
    base = str(tmp_path / "8")
    log = ReplicationLog(base)
    for i in range(3):
        log.append(rl.OP_WRITE, i, 0, 10)
    log.close()
    with open(base + ".rlog", "ab") as f:
        f.write(b"\xfe" * 17)  # crash mid-append: a partial record
    log2 = ReplicationLog(base)
    assert log2.last_seq == 3
    assert [r.seq for r in log2.read_from(1, 10)] == [1, 2, 3]
    assert os.path.getsize(base + ".rlog") == 3 * RECORD_SIZE
    log2.close()


def test_crc_bad_tail_stepped_back_over(tmp_path):
    base = str(tmp_path / "9")
    log = ReplicationLog(base)
    for i in range(3):
        log.append(rl.OP_WRITE, i, 0, 10)
    log.close()
    with open(base + ".rlog", "r+b") as f:  # rot inside the LAST record
        f.seek(2 * RECORD_SIZE + 5)
        f.write(b"\xff")
    log2 = ReplicationLog(base)
    assert log2.last_seq == 2, "CRC-bad tail record must be dropped"
    assert log2.append(rl.OP_WRITE, 9, 0, 10) == 3
    log2.close()


def test_rotten_head_resets_and_resumes_from_watermark(tmp_path):
    base = str(tmp_path / "10")
    log = ReplicationLog(base)
    for i in range(3):
        log.append(rl.OP_WRITE, i, 0, 10)
    log.set_acked(2)
    log.close()
    with open(base + ".rlog", "r+b") as f:  # head record rots
        f.seek(3)
        f.write(b"\xff")
    log2 = ReplicationLog(base)
    # Broken seq arithmetic -> full reset; the seq chain resumes from
    # the durable acked watermark, so already-acked seqs never recur.
    assert log2.first_seq == 0
    assert log2.last_seq == 2 == log2.acked_seq
    assert log2.append(rl.OP_WRITE, 9, 0, 10) == 3
    log2.close()


def test_missing_log_resumes_seq_from_watermark(tmp_path):
    base = str(tmp_path / "11")
    log = ReplicationLog(base)
    for i in range(3):
        log.append(rl.OP_WRITE, i, 0, 10)
    log.set_acked(3)
    log.close()
    os.remove(base + ".rlog")
    log2 = ReplicationLog(base)
    assert log2.last_seq == 3 and log2.pending() == 0
    assert log2.append(rl.OP_WRITE, 9, 0, 10) == 4
    log2.close()


def test_compact_drops_acked_prefix_keeps_seq_chain(tmp_path):
    base = str(tmp_path / "12")
    log = ReplicationLog(base)
    for i in range(5):
        log.append(rl.OP_WRITE, i, 0, 10)
    log.set_acked(3)
    assert log.compact() == 3
    recs = log.read_from(1, 100)  # clamps to first_seq
    assert [r.seq for r in recs] == [4, 5, 6]
    assert recs[-1].op == rl.OP_VACUUM
    assert (log.first_seq, log.last_seq) == (4, 6)
    assert log.pending() == 3
    log.close()
    # The compacted file alone still carries the chain.
    log2 = ReplicationLog(base)
    assert (log2.first_seq, log2.last_seq) == (4, 6)
    assert log2.append(rl.OP_WRITE, 9, 0, 10) == 7
    # Fully-acked log: compaction leaves just the vacuum record.
    log2.set_acked(7)
    log2.compact()
    recs = log2.read_from(1, 100)
    assert len(recs) == 1 and recs[0].op == rl.OP_VACUUM
    assert recs[0].seq == 8 == log2.last_seq
    log2.close()


def test_watermark_is_monotonic_and_durable(tmp_path):
    path = str(tmp_path / "13.rwm")
    wm = Watermark(path)
    wm.set(5)
    wm.set(3)  # regression is a no-op: acks never move backwards
    assert wm.value == 5
    assert Watermark(path).value == 5  # survives reopen
    wm.remove()
    assert Watermark(path).value == 0


# -- two-cluster mirror ------------------------------------------------------

@pytest.fixture(scope="module")
def mirror(tmp_path_factory):
    """Primary (single-node-raft master + shipper-bearing volume
    server) mirroring into a standby (plain master + volume server).
    The lag SLO is deliberately tight (50ms) so breach tests are
    fast; shipping at 50ms ticks keeps steady-state lag under it."""
    tmp = tmp_path_factory.mktemp("mirror")
    sb_master = MasterServer(volume_size_limit_mb=16,
                             meta_dir=str(tmp / "sbmeta"),
                             pulse_seconds=60)
    sb_master.start()
    (tmp / "sb").mkdir()
    sb_vs = VolumeServer(sb_master.url(), [str(tmp / "sb")],
                         max_volume_counts=[200], pulse_seconds=60)
    sb_vs.start()
    pport = rpc.free_port()
    pr_master = MasterServer(port=pport, volume_size_limit_mb=16,
                             meta_dir=str(tmp / "prmeta"),
                             pulse_seconds=60,
                             peers=[f"http://127.0.0.1:{pport}"],
                             replication_lag_slo=0.05)
    pr_master.start()
    _wait(pr_master.is_leader, 15, "single-node raft never elected")
    (tmp / "pr").mkdir()
    pr_vs = VolumeServer(pr_master.url(), [str(tmp / "pr")],
                         max_volume_counts=[200], pulse_seconds=60,
                         replicate_peer=sb_master.url(),
                         replicate_interval=0.05)
    pr_vs.start()
    yield pr_master, pr_vs, sb_master, sb_vs, tmp
    pr_vs.stop()
    pr_master.stop()
    sb_vs.stop()
    sb_master.stop()


_COL_N = [0]


def _put(mir, data, collection=None):
    """Journaled write on the primary: grow-if-new collection, enable
    the change log BEFORE the write lands (a write that precedes the
    log's creation has nothing to ship), raw POST.  Returns (vid, fid,
    collection)."""
    pr_master, pr_vs = mir[0], mir[1]
    if collection is None:
        _COL_N[0] += 1
        collection = f"drcol{_COL_N[0]}"
        rpc.call(f"{pr_master.url()}/vol/grow?count=1"
                 f"&collection={collection}", "POST")
    a = rpc.call(f"{pr_master.url()}/dir/assign?collection={collection}")
    vid = int(a["fid"].split(",")[0])
    v = pr_vs.store.find_volume(vid)
    if v.rlog is None:
        v.enable_rlog()
    rpc.call(f"http://{a['url']}/{a['fid']}", "POST", data)
    return vid, a["fid"], collection


def _rlog_status(vs, vid):
    doc = rpc.call(f"http://{vs.url()}/debug/replication")
    return (doc.get("rlog") or {}).get(str(vid))


def _wait_shipped(vs, vid, timeout=20.0):
    def ok():
        st = _rlog_status(vs, vid)
        return bool(st) and st["pending"] == 0 and st["last_seq"] > 0
    _wait(ok, timeout, f"volume {vid} never fully shipped: "
                       f"{_rlog_status(vs, vid)}")


def test_mirror_converges_byte_identical(mirror):
    pr_master, pr_vs, sb_master, _sb_vs, _tmp = mirror
    payloads = [f"mirror payload {i} ".encode() * 32 for i in range(3)]
    vid, fid0, col = _put(mirror, payloads[0])
    fids = [fid0]
    for p in payloads[1:]:
        fids.append(_put(mirror, p, collection=col)[1])
    _wait_shipped(pr_vs, vid)
    sbc = WeedClient(sb_master.url())
    for fid, p in zip(fids, payloads):
        assert sbc.download(fid) == p
    # The standby holds the volume under the same id + collection.
    st = _rlog_status(pr_vs, vid)
    assert st["acked_seq"] == st["last_seq"] >= len(payloads)


def test_tombstone_propagates_and_never_resurrects(mirror):
    _pm, pr_vs, sb_master, sb_vs, _tmp = mirror
    vid, fid, col = _put(mirror, b"doomed needle " * 16)
    _wait_shipped(pr_vs, vid)
    sbc = WeedClient(sb_master.url())
    assert sbc.download(fid)
    rpc.call(f"http://{pr_vs.url()}/{fid}", "DELETE")
    _wait_shipped(pr_vs, vid)
    with pytest.raises(rpc.RpcError) as ei:
        sbc.download(fid)
    assert ei.value.status == 404
    # Replay the WHOLE already-acked log at the standby: every record
    # is behind its applied watermark, so nothing applies and the
    # tombstone holds — a delete must never resurrect.
    v = pr_vs.store.find_volume(vid)
    recs = v.rlog.read_from(1, 1000)
    body = {"volume": vid, "collection": col, "version": v.version,
            "replication": "000", "ttl": "",
            "records": [{"seq": r.seq, "op": r.op,
                         "needle_id": r.needle_id, "cookie": r.cookie,
                         "size": r.size, "ts_ns": r.ts_ns,
                         "blob": None} for r in recs]}
    out = rpc.call_json(f"http://{sb_vs.url()}/admin/replication/apply",
                        "POST", body)
    assert out["applied"] == 0 and out["skipped"] == len(recs)
    with pytest.raises(rpc.RpcError):
        sbc.download(fid)


def test_journal_commit_points_and_quarantine_stays_local(mirror):
    """The volume journals at the needle commit points (write +
    delete carry the needle id/cookie), while scrub quarantine — local
    hygiene whose remote copy is healthy — must NOT journal: shipping
    a quarantine as a delete would destroy the standby's good copy."""
    _pm, pr_vs, _sbm, _sbv, _tmp = mirror
    vid, fid, col = _put(mirror, b"journaled write " * 16)
    v = pr_vs.store.find_volume(vid)
    _vid, key, cookie = t.parse_file_id(fid)
    recs = v.rlog.read_from(1, 100)
    assert any(r.op == rl.OP_WRITE and r.needle_id == key
               and r.cookie == cookie and r.size > 0 for r in recs)
    rpc.call(f"http://{pr_vs.url()}/{fid}", "DELETE")
    recs = v.rlog.read_from(1, 100)
    assert recs[-1].op == rl.OP_DELETE and recs[-1].needle_id == key
    # A second, live needle to quarantine.
    _vid2, fid2, _c = _put(mirror, b"healthy elsewhere " * 16,
                           collection=col)
    _wait_shipped(pr_vs, vid)
    last = v.rlog.last_seq
    _vid2, key2, _ck2 = t.parse_file_id(fid2)
    assert v.quarantine_needle(key2)
    assert v.rlog.last_seq == last, \
        "quarantine must not journal a cross-cluster tombstone"
    # Cleanup: drop the quarantined volume so /cluster/healthz stays
    # clean for the SLO test below.
    rpc.call_json(f"http://{pr_vs.url()}/admin/delete_volume", "POST",
                  {"volume": vid})
    pr_vs._send_heartbeat(full=True)


def test_duplicate_delivery_is_a_noop(mirror):
    _pm, pr_vs, sb_master, _sbv, _tmp = mirror
    before = replication_resends_total.value(reason="duplicate")
    fault.arm("wan.duplicate", "fail*1")
    payload = b"delivered twice, stored once " * 8
    vid, fid, _col = _put(mirror, payload)
    _wait_shipped(pr_vs, vid)
    assert replication_resends_total.value(reason="duplicate") \
        == before + 1, "the injected duplicate send never happened"
    assert WeedClient(sb_master.url()).download(fid) == payload


def test_partition_holds_watermark_then_heals(mirror):
    _pm, pr_vs, sb_master, _sbv, _tmp = mirror
    # Enough charges that the hold outlives retries; once the WAN
    # breaker opens, sends fail fast without consuming charges.
    fault.arm("wan.partition", "fail*1000")
    payload = b"written during the partition " * 8
    vid, fid, _col = _put(mirror, payload)
    sh = pr_vs.shipper
    _wait(lambda: sh.lag_view()["volumes"]
          .get(str(vid), {}).get("lag_seq", 0) > 0, 10,
          "partition never showed up as lag")
    time.sleep(0.2)  # several ticks: the watermark must hold
    st = _rlog_status(pr_vs, vid)
    assert st["pending"] > 0 and st["acked_seq"] == 0
    fault.disarm_all()
    resilience.reset_breakers()  # the hold opened the WAN breaker
    sh.kick()
    _wait_shipped(pr_vs, vid)
    assert WeedClient(sb_master.url()).download(fid) == payload
    assert sh.lag_view()["volumes"][str(vid)]["lag_seq"] == 0


def test_healthz_degrades_on_lag_slo_breach_and_recovers(mirror):
    pr_master, pr_vs, _sbm, _sbv, _tmp = mirror
    sh = pr_vs.shipper
    vid, _fid, col = _put(mirror, b"slo probe " * 8)
    _wait_shipped(pr_vs, vid)
    pr_vs._send_heartbeat(full=True)
    status, doc = rpc.call_status(f"{pr_master.url()}/cluster/healthz")
    assert status == 200, doc.get("problems")
    assert doc["replication"]["lag_slo"] == 0.05
    sh.paused = True  # WAN maintenance window: journaling continues
    try:
        _put(mirror, b"stuck behind the pause " * 8, collection=col)
        # The paused shipper still OBSERVES lag each tick — pausing
        # shipping must never pause the alarm about it.
        _wait(lambda: sh.lag_view()["volumes"]
              .get(str(vid), {}).get("lag_seconds", 0.0) > 0.05, 10,
              "paused shipper stopped observing lag")
        pr_vs._send_heartbeat(full=True)
        status, doc = rpc.call_status(
            f"{pr_master.url()}/cluster/healthz")
        assert status == 503
        assert any("replication lag" in p and "exceeds SLO" in p
                   for p in doc["problems"]), doc["problems"]
    finally:
        sh.paused = False
        sh.kick()
    _wait_shipped(pr_vs, vid)
    pr_vs._send_heartbeat(full=True)
    status, doc = rpc.call_status(f"{pr_master.url()}/cluster/healthz")
    assert status == 200, doc.get("problems")


def test_raft_leader_failover_with_records_in_flight(mirror):
    """Leadership churn on the primary's master while unshipped
    records sit in the change log: the shipper (volume-server-owned,
    peer-master-addressed) must not lose or skip anything."""
    pr_master, pr_vs, sb_master, _sbv, _tmp = mirror
    fault.arm("wan.partition", "fail*1000")
    payload = b"survives the election " * 8
    vid, fid, _col = _put(mirror, payload)
    raft = pr_master.raft
    with raft._lock:
        raft._become_follower(raft.current_term + 1, None)
    _wait(pr_master.is_leader, 15, "raft never re-elected")
    fault.disarm_all()
    resilience.reset_breakers()
    pr_vs.shipper.kick()
    _wait_shipped(pr_vs, vid)
    assert WeedClient(sb_master.url()).download(fid) == payload
    st = _rlog_status(pr_vs, vid)
    assert st["acked_seq"] == st["last_seq"] > 0


def test_fsck_crc_json_proves_cross_cluster_convergence(mirror):
    """The machine-checkable convergence proof from the README
    runbook: `volume.fsck -crc -json` run against EACH cluster's
    master (same filer namespace) emits a node-address-free checksum
    map; converged clusters compare equal."""
    from seaweedfs_tpu.filer.client import FilerProxy
    from seaweedfs_tpu.filer.server import FilerServer
    pr_master, pr_vs, sb_master, _sbv, _tmp = mirror
    filer = FilerServer(pr_master.url())
    filer.start()
    env_pr = env_sb = None
    try:
        # The filer writes into the default collection: pre-grow and
        # journal-enable so its chunks mirror from the first byte.
        rpc.call(f"{pr_master.url()}/vol/grow?count=2", "POST")
        for loc in pr_vs.store.locations:
            for v in list(loc.volumes.values()):
                if v.rlog is None:
                    v.enable_rlog()
        fp = FilerProxy(filer.url())
        fp.put("/dr/a.txt", b"alpha " * 200)
        fp.put("/dr/deep/b.txt", b"beta " * 333)

        def all_acked():
            doc = rpc.call(f"http://{pr_vs.url()}/debug/replication")
            rlogs = doc.get("rlog") or {}
            return rlogs and all(st["pending"] == 0
                                 for st in rlogs.values())
        _wait(all_acked, 20, "filer chunks never finished shipping")
        env_pr = CommandEnv(pr_master.url(), filer_url=filer.url())
        env_sb = CommandEnv(sb_master.url(), filer_url=filer.url())
        doc_pr = json.loads(run_command(env_pr,
                                        "volume.fsck -crc -json"))
        doc_sb = json.loads(run_command(env_sb,
                                        "volume.fsck -crc -json"))
        assert doc_pr["verdict"] == "ok", doc_pr
        assert doc_sb["verdict"] == "ok", doc_sb
        assert doc_pr["checked"] > 0
        assert doc_pr["volumes"] == doc_sb["volumes"]
    finally:
        for env in (env_pr, env_sb):
            if env is not None:
                env.close()
        filer.stop()


def test_mirror_shell_status_pause_resume(mirror):
    pr_master, pr_vs, sb_master, _sbv, _tmp = mirror
    pr_vs._send_heartbeat(full=True)
    env = CommandEnv(pr_master.url())
    try:
        out = run_command(env, "cluster.mirror.status")
        assert "peer(s):" in out and sb_master.url() in out
        assert "lag SLO: 0.05s" in out
        run_command(env, "cluster.mirror.pause")
        assert pr_vs.shipper.paused
        pr_vs._send_heartbeat(full=True)  # pause state rides heartbeats
        assert "paused:" in run_command(env, "cluster.mirror.status")
        run_command(env, "cluster.mirror.resume")
        assert not pr_vs.shipper.paused
        doc = rpc.call(f"{pr_master.url()}/cluster/mirror")
        assert doc["paired"] and sb_master.url() in doc["peers"]
    finally:
        env.close()


def test_replication_metrics_promcheck(mirror):
    _pm, pr_vs, _sbm, _sbv, _tmp = mirror
    fault.arm("wan.duplicate", "fail*1")  # materialize the resend series
    vid, _fid, _col = _put(mirror, b"promcheck traffic " * 32)
    _wait_shipped(pr_vs, vid)
    text = rpc.call(f"http://{pr_vs.url()}/metrics").decode()
    for fam in ("SeaweedFS_replication_shipped_bytes_total",
                "SeaweedFS_replication_resends_total",
                "SeaweedFS_replication_lag_seconds_total",
                "SeaweedFS_replication_lag_seconds"):
        assert fam in text, f"{fam} missing from /metrics"
    assert validate_exposition(text) == [], validate_exposition(text)[:5]


# -- function-scoped chaos: restarts + cutover under load --------------------

def test_restart_both_sides_resumes_from_watermarks(tmp_path):
    """Standby dies mid-backlog, comes back on the same port + dir:
    the `.rap` applied watermark no-ops any re-shipped prefix.  Then
    the primary restarts: the volume re-enables its change log from
    the sidecar on mount and the shipper resumes from the durable
    `.rwm` — nothing is lost, nothing re-ships."""
    sb_master = MasterServer(volume_size_limit_mb=16,
                             meta_dir=str(tmp_path / "sbmeta"),
                             pulse_seconds=60)
    sb_master.start()
    (tmp_path / "sb").mkdir()
    sb_port = rpc.free_port()

    def new_sb_vs():
        return VolumeServer(sb_master.url(), [str(tmp_path / "sb")],
                            port=sb_port, max_volume_counts=[50],
                            pulse_seconds=60)
    pr_master = MasterServer(volume_size_limit_mb=16,
                             meta_dir=str(tmp_path / "prmeta"),
                             pulse_seconds=60)
    pr_master.start()
    (tmp_path / "pr").mkdir()
    pr_port = rpc.free_port()

    def new_pr_vs():
        return VolumeServer(pr_master.url(), [str(tmp_path / "pr")],
                            port=pr_port, max_volume_counts=[50],
                            pulse_seconds=60,
                            replicate_peer=sb_master.url(),
                            replicate_interval=0.05)
    sb_vs = new_sb_vs()
    sb_vs.start()
    pr_vs = new_pr_vs()
    pr_vs.start()
    live = [pr_vs, sb_vs]
    try:
        rpc.call(f"{pr_master.url()}/vol/grow?count=1"
                 "&collection=restart", "POST")
        payloads = {}

        def put(data):
            a = rpc.call(f"{pr_master.url()}/dir/assign"
                         "?collection=restart")
            vid = int(a["fid"].split(",")[0])
            v = live[0].store.find_volume(vid)
            if v.rlog is None:
                v.enable_rlog()
            rpc.call(f"http://{a['url']}/{a['fid']}", "POST", data)
            payloads[a["fid"]] = data
            return vid

        vid = put(b"before the outage " * 16)
        _wait_shipped(pr_vs, vid)
        # Standby goes away; acked writes keep landing on the primary.
        sb_vs.stop()
        for i in range(3):
            put(f"during the outage {i} ".encode() * 16)
        v = pr_vs.store.find_volume(vid)
        _wait(lambda: v.rlog.pending() >= 3, 10)
        time.sleep(0.2)
        assert v.rlog.pending() >= 3, "watermark must hold while down"
        # Standby returns on the same port + dir and catches up.
        sb_vs = new_sb_vs()
        live[1] = sb_vs
        sb_vs.start()
        resilience.reset_breakers()  # the outage opened the breaker
        pr_vs.shipper.kick()
        _wait_shipped(pr_vs, vid, timeout=30)
        acked_before_restart = v.rlog.acked_seq
        # Primary restarts: same dir, same peer.
        pr_vs.stop()
        pr_vs = new_pr_vs()
        live[0] = pr_vs
        pr_vs.start()
        v = pr_vs.store.find_volume(vid)
        assert v.rlog is not None, \
            "mount must re-enable the change log from the sidecar"
        assert v.rlog.acked_seq == acked_before_restart
        assert v.rlog.pending() == 0, "nothing may re-ship after ack"
        vid2 = put(b"after the restart " * 16)
        assert vid2 == vid
        _wait_shipped(pr_vs, vid, timeout=30)
        sbc = WeedClient(sb_master.url())
        for fid, data in payloads.items():
            assert sbc.download(fid) == data
    finally:
        for s in live:
            try:
                s.stop()
            except Exception:  # noqa: BLE001 — already stopped
                pass
        pr_master.stop()
        sb_master.stop()


def test_cutover_under_load_zero_client_visible_errors(tmp_path):
    """The acceptance drill: live writers during cluster.mirror.cutover
    see zero errors (failing over to the standby master when the
    drained primary refuses them), and every write EITHER cluster
    acked is readable from the standby afterwards — zero acked-write
    loss."""
    sb_master = MasterServer(volume_size_limit_mb=16,
                             meta_dir=str(tmp_path / "sbmeta"),
                             pulse_seconds=60)
    sb_master.start()
    (tmp_path / "sb").mkdir()
    sb_vs = VolumeServer(sb_master.url(), [str(tmp_path / "sb")],
                         max_volume_counts=[50], pulse_seconds=60)
    sb_vs.start()
    pr_master = MasterServer(volume_size_limit_mb=16,
                             meta_dir=str(tmp_path / "prmeta"),
                             pulse_seconds=60)
    pr_master.start()
    (tmp_path / "pr").mkdir()
    pr_vs = VolumeServer(pr_master.url(), [str(tmp_path / "pr")],
                         max_volume_counts=[50], pulse_seconds=60,
                         replicate_peer=sb_master.url(),
                         replicate_interval=0.05)
    pr_vs.start()
    env = None
    stop = threading.Event()
    th = None
    try:
        # Pre-grow + journal-enable the load collection so every
        # writer needle is shipped from the first byte.
        rpc.call(f"{pr_master.url()}/vol/grow?count=1&collection=cut",
                 "POST")
        a = rpc.call(f"{pr_master.url()}/dir/assign?collection=cut")
        pr_vs.store.find_volume(
            int(a["fid"].split(",")[0])).enable_rlog()
        rpc.call(f"http://{a['url']}/{a['fid']}", "POST",
                 b"cutover seed")
        acked, errors = [], []

        def writer():
            pc = WeedClient(pr_master.url())
            sc = WeedClient(sb_master.url())
            i = 0
            while not stop.is_set():
                data = f"cutover payload {i} ".encode() * 8
                i += 1
                try:
                    # Failover clients write to a standby-local
                    # collection: each cluster allocates needle keys
                    # independently, so mixing both write paths into
                    # one mirrored volume would collide.
                    try:
                        fid = pc.upload_data(data, collection="cut")
                    except Exception:  # noqa: BLE001 — drained away
                        fid = sc.upload_data(data, collection="cutsb")
                    acked.append((fid, data))
                except Exception as e:  # noqa: BLE001
                    errors.append(repr(e))
                time.sleep(0.005)

        th = threading.Thread(target=writer, daemon=True)
        th.start()
        time.sleep(0.4)  # some primary-acked traffic first
        env = CommandEnv(pr_master.url())
        run_command(env, "lock")
        out = run_command(env,
                          "cluster.mirror.cutover -grace 1 -timeout 30")
        time.sleep(0.3)  # post-cutover writes keep flowing (standby)
        stop.set()
        th.join(timeout=15)
        assert not th.is_alive()
        assert "cutover complete" in out
        assert pr_vs.shipper.paused, \
            "cutover must quiesce the old primary's shipper"
        assert errors == [], errors[:3]
        assert len(acked) > 5
        # Zero acked-write loss: EVERY acked write — landed on the
        # primary before/during the drain or on the standby after —
        # reads back byte-identical from the standby cluster.
        sbc = WeedClient(sb_master.url())
        for fid, data in acked:
            assert sbc.download(fid) == data
    finally:
        stop.set()
        if th is not None:
            th.join(timeout=15)
        if env is not None:
            env.close()
        for s in (pr_vs, sb_vs):
            try:
                s.stop()
            except Exception:  # noqa: BLE001 — drained/stopped
                pass
        pr_master.stop()
        sb_master.stop()
