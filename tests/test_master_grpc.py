"""gRPC control plane: the wire-compatible master_pb.Seaweed service.

Clients speak raw grpc channels with the protoc-generated messages —
exactly what a ported `weed`-style gRPC client would send — and the
facade bridges to the same master internals as the JSON plane.
"""

import json
import threading
import time

import grpc
import pytest

from seaweedfs_tpu.cluster import rpc
from seaweedfs_tpu.cluster.client import WeedClient
from seaweedfs_tpu.cluster.master import MasterServer
from seaweedfs_tpu.cluster.volume_server import VolumeServer
from seaweedfs_tpu.pb import master_pb2 as pb
from seaweedfs_tpu.pb.master_grpc import MasterGrpcServer

SVC = "/master_pb.Seaweed/"


@pytest.fixture
def stack(tmp_path):
    master = MasterServer(volume_size_limit_mb=64, meta_dir=str(tmp_path))
    master.start()
    vs = VolumeServer(master.url(), [str(tmp_path / "vs")],
                      pulse_seconds=60)
    vs.start()
    g = MasterGrpcServer(master, port=0)
    g.start()
    chan = grpc.insecure_channel(g.addr())
    yield master, vs, g, chan
    chan.close()
    g.stop()
    vs.stop()
    master.stop()


def _unary(chan, name, req, resp_cls):
    fn = chan.unary_unary(
        SVC + name,
        request_serializer=lambda m: m.SerializeToString(),
        response_deserializer=resp_cls.FromString)
    return fn(req, timeout=10)


def test_assign_lookup_roundtrip(stack):
    _m, _vs, _g, chan = stack
    out = _unary(chan, "Assign",
                 pb.AssignRequest(count=1, replication="000"),
                 pb.AssignResponse)
    assert out.fid and out.url and not out.error
    # upload through the HTTP data plane with the gRPC-assigned fid
    rpc.call(f"http://{out.url}/{out.fid}", "POST", b"grpc-assigned")
    vid = out.fid.split(",")[0]
    lk = _unary(chan, "LookupVolume",
                pb.LookupVolumeRequest(volume_ids=[vid]),
                pb.LookupVolumeResponse)
    assert len(lk.volume_id_locations) == 1
    locs = lk.volume_id_locations[0].locations
    assert any(loc.url == out.url for loc in locs)
    assert rpc.call(f"http://{locs[0].url}/{out.fid}") == \
        b"grpc-assigned"
    # unknown volume -> per-entry error, not a transport failure
    lk2 = _unary(chan, "LookupVolume",
                 pb.LookupVolumeRequest(volume_ids=["9999"]),
                 pb.LookupVolumeResponse)
    assert lk2.volume_id_locations[0].error


def test_statistics_and_configuration(stack):
    master, vs, _g, chan = stack
    client = WeedClient(master.url())
    client.upload_data(b"x" * 1000)
    vs.store.find_volume(1).sync()
    vs._send_heartbeat(full=True)  # counters ride heartbeats
    st = _unary(chan, "Statistics", pb.StatisticsRequest(),
                pb.StatisticsResponse)
    assert st.file_count >= 1 and st.used_size > 0
    cfg = _unary(chan, "GetMasterConfiguration",
                 pb.GetMasterConfigurationRequest(),
                 pb.GetMasterConfigurationResponse)
    assert cfg.leader == master.url()


def test_volume_list_topology(stack):
    master, vs, _g, chan = stack
    WeedClient(master.url()).upload_data(b"vols")
    vl = _unary(chan, "VolumeList", pb.VolumeListRequest(),
                pb.VolumeListResponse)
    nodes = [dn for dc in vl.topology_info.data_center_infos
             for rack in dc.rack_infos for dn in rack.data_node_infos]
    assert any(dn.id == vs.url() and dn.volume_infos for dn in nodes)
    assert vl.volume_size_limit_mb == 64


def test_grpc_heartbeat_registers_volume_server(stack):
    """A 'Go-style' volume server registering over gRPC SendHeartbeat
    lands in the same topology the JSON plane serves."""
    master, _vs, _g, chan = stack
    hb = pb.Heartbeat(
        ip="10.9.9.9", port=18080, public_url="10.9.9.9:18080",
        max_volume_count=5, data_center="dc9", rack="r9",
        has_no_volumes=True,
        volumes=[pb.VolumeInformationMessage(
            id=77, size=123, collection="", file_count=1,
            replica_placement=0, version=3)])
    stream = chan.stream_stream(
        SVC + "SendHeartbeat",
        request_serializer=lambda m: m.SerializeToString(),
        response_deserializer=pb.HeartbeatResponse.FromString)
    responses = stream(iter([hb]), timeout=10)
    first = next(iter(responses))
    assert first.volume_size_limit == 64 << 20
    # visible through the JSON lookup path
    out = rpc.call(f"{master.url()}/dir/lookup?volumeId=77")
    assert out["locations"][0]["url"] == "10.9.9.9:18080"


def test_keep_connected_pushes_locations(stack):
    master, vs, _g, chan = stack
    WeedClient(master.url()).upload_data(b"watch me")
    stream = chan.stream_stream(
        SVC + "KeepConnected",
        request_serializer=lambda m: m.SerializeToString(),
        response_deserializer=pb.VolumeLocation.FromString)
    got = []
    done = threading.Event()

    def consume():
        try:
            for loc in stream(iter([pb.KeepConnectedRequest(
                    name="test-client")]), timeout=15):
                got.append(loc)
                done.set()
                return
        except grpc.RpcError:
            pass

    t = threading.Thread(target=consume, daemon=True)
    t.start()
    assert done.wait(10), "no VolumeLocation pushed"
    assert got[0].url == vs.url() and got[0].new_vids


def test_collections_and_admin_lease(stack):
    master, _vs, _g, chan = stack
    WeedClient(master.url()).upload_data(b"c", collection="grpccol")
    cl = _unary(chan, "CollectionList", pb.CollectionListRequest(),
                pb.CollectionListResponse)
    assert any(c.name == "grpccol" for c in cl.collections)
    lease = _unary(chan, "LeaseAdminToken",
                   pb.LeaseAdminTokenRequest(lock_name="grpc-shell"),
                   pb.LeaseAdminTokenResponse)
    assert lease.token
    # a second caller is refused while held
    with pytest.raises(grpc.RpcError) as ei:
        _unary(chan, "LeaseAdminToken",
               pb.LeaseAdminTokenRequest(lock_name="intruder"),
               pb.LeaseAdminTokenResponse)
    assert ei.value.code() == grpc.StatusCode.ABORTED
    _unary(chan, "ReleaseAdminToken",
           pb.ReleaseAdminTokenRequest(previous_token=lease.token),
           pb.ReleaseAdminTokenResponse)


def test_grpc_incremental_ec_shard_heartbeat(stack):
    """Delta-only EC heartbeats (new_ec_shards / deleted_ec_shards)
    register and unregister shard bits without a full sync."""
    master, _vs, _g, chan = stack
    stream = chan.stream_stream(
        SVC + "SendHeartbeat",
        request_serializer=lambda m: m.SerializeToString(),
        response_deserializer=pb.HeartbeatResponse.FromString)
    hb0 = pb.Heartbeat(ip="10.8.8.8", port=18081,
                       public_url="10.8.8.8:18081",
                       max_volume_count=5, has_no_volumes=True)
    hb_add = pb.Heartbeat(
        ip="10.8.8.8", port=18081, public_url="10.8.8.8:18081",
        max_volume_count=5,
        new_ec_shards=[pb.VolumeEcShardInformationMessage(
            id=88, ec_index_bits=0b111)])
    hb_del = pb.Heartbeat(
        ip="10.8.8.8", port=18081, public_url="10.8.8.8:18081",
        max_volume_count=5,
        deleted_ec_shards=[pb.VolumeEcShardInformationMessage(
            id=88, ec_index_bits=0b111)])
    for _ in stream(iter([hb0, hb_add]), timeout=10):
        pass
    ec = _unary(chan, "LookupEcVolume",
                pb.LookupEcVolumeRequest(volume_id=88),
                pb.LookupEcVolumeResponse)
    assert {e.shard_id for e in ec.shard_id_locations} == {0, 1, 2}
    assert ec.shard_id_locations[0].locations[0].url == \
        "10.8.8.8:18081"
    for _ in stream(iter([hb_del]), timeout=10):
        pass
    with pytest.raises(grpc.RpcError) as ei:
        _unary(chan, "LookupEcVolume",
               pb.LookupEcVolumeRequest(volume_id=88),
               pb.LookupEcVolumeResponse)
    assert ei.value.code() == grpc.StatusCode.NOT_FOUND


def test_lookup_malformed_id_is_per_entry_error(stack):
    _m, _vs, _g, chan = stack
    lk = _unary(chan, "LookupVolume",
                pb.LookupVolumeRequest(volume_ids=["not-a-vid"]),
                pb.LookupVolumeResponse)
    assert lk.volume_id_locations[0].error


def test_weedclient_grpc_transport(tmp_path):
    """WeedClient(use_grpc=True): assign/lookup ride master_pb.Seaweed
    on the conventional port (+10000) and operate the SAME live master
    state as the JSON plane — uploads through the gRPC transport read
    back through the HTTP one.  Stopping the gRPC plane breaks the
    client, proving the traffic actually rides it."""
    from seaweedfs_tpu.cluster.client import WeedClient

    master = MasterServer(volume_size_limit_mb=64,
                          meta_dir=str(tmp_path))
    master.start()
    vs = VolumeServer(master.url(), [str(tmp_path / "vs")],
                      pulse_seconds=60)
    vs.start()
    g = MasterGrpcServer(master)  # http port + 10000
    g.start()
    try:
        gclient = WeedClient(master.url(), use_grpc=True)
        assert gclient._grpc is not None
        fid = gclient.upload_data(b"over grpc")
        assert gclient.download(fid) == b"over grpc"
        # Same state via the plain JSON client.
        jclient = WeedClient(master.url(), use_grpc=False)
        assert jclient.download(fid) == b"over grpc"
        # Kill the gRPC plane: a fresh gRPC client must fail fast,
        # proving assigns do not silently fall back to JSON.
        g.stop()
        broken = WeedClient(master.url(), use_grpc=True)
        with pytest.raises(Exception):
            broken._grpc._assign(
                broken._grpc.pb.AssignRequest(count=1), timeout=2)
    finally:
        vs.stop()
        master.stop()


def test_weedclient_env_selects_grpc(tmp_path, monkeypatch):
    from seaweedfs_tpu.cluster.client import WeedClient
    monkeypatch.setenv("WEED_INTERNAL_GRPC", "1")
    c = WeedClient("http://127.0.0.1:59999")
    assert c._grpc is not None
    monkeypatch.delenv("WEED_INTERNAL_GRPC")
    c2 = WeedClient("http://127.0.0.1:59999")
    assert c2._grpc is None
