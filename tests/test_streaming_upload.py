"""Streaming request bodies: a PUT larger than any RAM budget flows
through the S3/filer write path in O(chunk) memory (reference:
filer_server_handlers_write_autochunk.go:188 uploadReaderToChunks).

The e2e tests upload from a generator reader (the client never holds
the body either) and assert the server process's Python allocation
peak stays a small fraction of the body size via tracemalloc.
"""

import hashlib
import io
import json
import tracemalloc
import urllib.request

import pytest

from seaweedfs_tpu.cluster import rpc
from seaweedfs_tpu.cluster.master import MasterServer
from seaweedfs_tpu.cluster.volume_server import VolumeServer
from seaweedfs_tpu.filer.server import FilerServer
from seaweedfs_tpu.s3api.server import S3ApiServer, _AwsChunkedReader

MB = 1 << 20


class PatternReader:
    """Deterministic pseudo-random byte stream of a given size, never
    materialized; also hashes what it hands out."""

    def __init__(self, total: int, seed: int = 7):
        self.left = total
        self._block = bytes((seed * i * 2654435761 >> 3) & 0xFF
                            for i in range(65536))
        self.md5 = hashlib.md5()

    def read(self, n: int = -1) -> bytes:
        if n < 0 or n > self.left:
            n = self.left
        out = (self._block * (n // len(self._block) + 1))[:n]
        self.left -= n
        self.md5.update(out)
        return out


# -- BodyReader unit ---------------------------------------------------------


def _reader(data: bytes, length=None, chunked=False):
    return rpc.BodyReader(io.BufferedReader(io.BytesIO(data)),
                          length, chunked)


def test_body_reader_exact_reads():
    r = _reader(b"abcdefghij", length=10)
    assert r.length == 10
    assert r.read(4) == b"abcd"
    assert r.read() == b"efghij"
    assert r.read(5) == b""


def test_body_reader_truncation_raises():
    r = _reader(b"abc", length=10)
    with pytest.raises(ConnectionError):
        r.read()
    assert r.truncated


def test_body_reader_chunked():
    wire = b"4\r\nwiki\r\n5\r\npedia\r\n0\r\n\r\n"
    r = _reader(wire, chunked=True)
    assert r.length is None
    assert r.read(6) == b"wikipe"
    assert r.read() == b"dia"
    assert r.read() == b""


def test_aws_chunked_reader():
    framed = (b"5;chunk-signature=deadbeef\r\nhello\r\n"
              b"6\r\n world\r\n"
              b"0\r\n\r\n")
    r = _AwsChunkedReader(_reader(framed, length=len(framed)), 11)
    assert r.length == 11
    assert r.read(3) == b"hel"
    assert r.read() == b"lo world"
    assert r.read() == b""


def test_aws_chunked_declared_length_mismatch():
    """x-amz-decoded-content-length must match the decoded payload —
    a mismatch errors instead of storing a truncated object (review
    finding)."""
    framed = b"5;sig=x\r\nhello\r\n0\r\n\r\n"
    over = _AwsChunkedReader(_reader(framed, length=len(framed)), 3)
    with pytest.raises(ConnectionError):
        over.read()  # actual payload exceeds the declared 3
    under = _AwsChunkedReader(_reader(framed, length=len(framed)), 9)
    with pytest.raises(ConnectionError):
        under.read()  # terminator arrives before the declared 9


# -- e2e with RSS assertion --------------------------------------------------


@pytest.fixture(scope="module")
def stack(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("stream-stack")
    master = MasterServer(volume_size_limit_mb=256, meta_dir=str(tmp))
    master.start()
    vs = VolumeServer(master.url(), [str(tmp / "vs")], pulse_seconds=60)
    vs.start()
    filer = FilerServer(master.url(), chunk_size=MB)
    filer.start()
    s3 = S3ApiServer(filer.url())
    s3.start()
    yield master, vs, filer, s3
    s3.stop()
    filer.stop()
    vs.stop()
    master.stop()


def _upload(url: str, total: int, chunked=False) -> str:
    src = PatternReader(total)
    req = urllib.request.Request(url, data=src, method="PUT")
    if chunked:
        req.add_header("Transfer-Encoding", "chunked")
    else:
        req.add_header("Content-Length", str(total))
    with urllib.request.urlopen(req, timeout=300) as resp:
        resp.read()
    assert src.left == 0
    return src.md5.hexdigest()


def _check_stored(filer, path: str, total: int, md5_hex: str):
    meta = json.loads(urllib.request.urlopen(
        f"{filer.url()}{path}?metadata=true", timeout=30).read())
    from seaweedfs_tpu.filer.entry import FileChunk
    from seaweedfs_tpu.filer.filechunks import total_size
    chunks = [FileChunk.from_dict(c) for c in meta["chunks"]]
    assert total_size(chunks) == total
    # Hash the content back via bounded Range reads.
    md5 = hashlib.md5()
    pos = 0
    while pos < total:
        hi = min(pos + 4 * MB, total) - 1
        req = urllib.request.Request(
            f"{filer.url()}{path}", headers={"Range": f"bytes={pos}-{hi}"})
        with urllib.request.urlopen(req, timeout=60) as r:
            md5.update(r.read())
        pos = hi + 1
    assert md5.hexdigest() == md5_hex


def test_filer_put_streams_with_bounded_memory(stack):
    _m, _vs, filer, _s3 = stack
    total = 48 * MB
    tracemalloc.start()
    md5_hex = _upload(f"{filer.url()}/stream/big.bin", total)
    _cur, peak = tracemalloc.get_traced_memory()
    tracemalloc.stop()
    assert peak < total // 3, (
        f"upload of {total >> 20}MB peaked at {peak >> 20}MB of Python "
        f"allocations — the body is being buffered, not streamed")
    _check_stored(filer, "/stream/big.bin", total, md5_hex)


def test_filer_chunked_te_put_streams(stack):
    _m, _vs, filer, _s3 = stack
    total = 32 * MB
    tracemalloc.start()
    md5_hex = _upload(f"{filer.url()}/stream/chunked.bin", total,
                      chunked=True)
    _cur, peak = tracemalloc.get_traced_memory()
    tracemalloc.stop()
    # Peak is per-hop pipeline overhead (~1MB buffers x copies), not a
    # function of body size.
    assert peak < total // 2
    _check_stored(filer, "/stream/chunked.bin", total, md5_hex)


def test_s3_put_object_streams(stack):
    _m, _vs, filer, s3 = stack
    urllib.request.urlopen(urllib.request.Request(
        f"{s3.url()}/streambucket", method="PUT"), timeout=30).read()
    total = 48 * MB
    tracemalloc.start()
    md5_hex = _upload(f"{s3.url()}/streambucket/big.obj", total)
    _cur, peak = tracemalloc.get_traced_memory()
    tracemalloc.stop()
    # Three hops (client->s3->filer->volume) of ~1MB pipeline buffers;
    # far below the body size, and independent of it.
    assert peak < total // 3, (
        f"S3 PUT of {total >> 20}MB peaked at {peak >> 20}MB — buffered")
    _check_stored(filer, f"/buckets/streambucket/big.obj", total, md5_hex)


def test_client_death_mid_upload_frees_chunks(stack, monkeypatch):
    """A client that dies mid-PUT must not leak the chunks already
    uploaded: the filer's rollback deletes what landed."""
    import socket as sock_mod
    _m, _vs, filer, _s3 = stack
    deleted: list[str] = []
    orig = filer._delete_file_ids
    monkeypatch.setattr(
        filer, "_delete_file_ids",
        lambda fids: (deleted.extend(fids), orig(fids)) and None)
    host, port = filer.server.host, filer.server.port
    s = sock_mod.create_connection((host, port))
    s.sendall(b"PUT /stream/dead.bin HTTP/1.1\r\n"
              b"Host: x\r\nContent-Length: 50000000\r\n\r\n")
    s.sendall(b"x" * (3 * MB))  # a few chunks land...
    s.close()                   # ...then the client dies
    import time as _t
    deadline = _t.time() + 10
    while _t.time() < deadline and not deleted:
        _t.sleep(0.1)
    assert deleted, "partial upload's chunks were not rolled back"
    # And the entry never appeared.
    with pytest.raises(urllib.request.HTTPError):
        urllib.request.urlopen(f"{filer.url()}/stream/dead.bin",
                               timeout=10)


def test_filer_get_streams_with_bounded_memory(stack):
    """Reads are symmetric with writes: a whole-file GET flows through
    ChunkRangeReader in 1MB pieces — never a whole-body buffer in the
    filer (StreamContent, filer/stream.go)."""
    _m, _vs, filer, _s3 = stack
    total = 48 * MB
    md5_hex = _upload(f"{filer.url()}/stream/rbig.bin", total)
    # Peak memory must track the (bounded) chunk cache, not the file:
    # shrink the cache so a buffered body would stand out.
    filer.streamer.cache.reset()
    filer.streamer.cache.configure(4 * MB)
    tracemalloc.start()
    md5 = hashlib.md5()
    with urllib.request.urlopen(f"{filer.url()}/stream/rbig.bin",
                                timeout=300) as resp:
        assert int(resp.headers["Content-Length"]) == total
        while True:
            piece = resp.read(1 << 20)
            if not piece:
                break
            md5.update(piece)
    _cur, peak = tracemalloc.get_traced_memory()
    tracemalloc.stop()
    assert md5.hexdigest() == md5_hex
    # File-size-independent bound: 4MB cache + in-flight 1MB pieces +
    # the in-process test client's own buffers.  The buffered-body
    # failure mode measures O(file) (~60MB here).
    assert peak < 24 * MB, (
        f"GET of {total >> 20}MB peaked at {peak >> 20}MB of Python "
        f"allocations with a 4MB chunk cache — the body is being "
        f"buffered, not streamed")


def test_s3_get_object_streams(stack):
    """The filer->S3 chain stays O(MB): gateway proxies the filer's
    already-streaming response."""
    _m, _vs, filer, s3 = stack
    total = 32 * MB
    _upload(f"{s3.url()}/strbkt", 0)  # create bucket (empty PUT)
    md5_hex = _upload(f"{s3.url()}/strbkt/big.obj", total)
    filer.streamer.cache.reset()
    filer.streamer.cache.configure(4 * MB)
    tracemalloc.start()
    md5 = hashlib.md5()
    with urllib.request.urlopen(f"{s3.url()}/strbkt/big.obj",
                                timeout=300) as resp:
        while True:
            piece = resp.read(1 << 20)
            if not piece:
                break
            md5.update(piece)
    _cur, peak = tracemalloc.get_traced_memory()
    tracemalloc.stop()
    assert md5.hexdigest() == md5_hex
    # gateway + filer + test client all in-process: a wider bound, but
    # still far below the O(file) buffered failure mode
    assert peak < 32 * MB


def test_get_unfetchable_chunk_is_clean_500(stack):
    """First-piece priming: when a chunk can't be fetched the client
    gets a clean 500 — never a 200 with a truncated body."""
    _m, _vs, filer, _s3 = stack
    _upload(f"{filer.url()}/stream/dead.bin", 2 * MB)
    # corrupt the entry to reference a nonexistent volume
    e = filer.filer.find_entry("/stream/dead.bin")
    e2 = e.clone()
    for c in e2.chunks:
        c.file_id = "999," + c.file_id.split(",")[1]
    filer.filer.store.update_entry(e2)
    try:
        urllib.request.urlopen(f"{filer.url()}/stream/dead.bin",
                               timeout=30)
        raise AssertionError("expected HTTPError")
    except urllib.error.HTTPError as err:
        assert err.code in (404, 500)  # clean error, nothing streamed


def test_streamed_sparse_gap_reads_zeros(stack):
    """iter_content's gap handling: a hole between chunks streams as
    zeros, byte-identical with the buffered read() path."""
    from seaweedfs_tpu.filer.entry import Attributes, Entry, FileChunk
    _m, _vs, filer, _s3 = stack
    # one real chunk at offset 3MB; bytes [0,3MB) are a hole
    body = b"Z" * (MB // 2)
    req = urllib.request.Request(f"{filer.url()}/stream/seed2.bin",
                                 data=body, method="PUT")
    urllib.request.urlopen(req, timeout=30).read()
    seeded = filer.filer.find_entry("/stream/seed2.bin")
    sparse = Entry(path="/stream/sparse.bin",
                   attributes=Attributes(mtime=1.0),
                   chunks=[FileChunk(
                       file_id=seeded.chunks[0].file_id,
                       offset=3 * MB, size=len(body), mtime=2)])
    filer.filer.create_entry(sparse)
    with urllib.request.urlopen(f"{filer.url()}/stream/sparse.bin",
                                timeout=30) as resp:
        got = resp.read()
    assert len(got) == 3 * MB + len(body)
    assert got[:3 * MB] == bytes(3 * MB)
    assert got[3 * MB:] == body
