"""Streaming request bodies: a PUT larger than any RAM budget flows
through the S3/filer write path in O(chunk) memory (reference:
filer_server_handlers_write_autochunk.go:188 uploadReaderToChunks).

The e2e tests upload from a generator reader (the client never holds
the body either) and assert the server process's Python allocation
peak stays a small fraction of the body size via tracemalloc.
"""

import hashlib
import io
import json
import tracemalloc
import urllib.request

import pytest

from seaweedfs_tpu.cluster import rpc
from seaweedfs_tpu.cluster.master import MasterServer
from seaweedfs_tpu.cluster.volume_server import VolumeServer
from seaweedfs_tpu.filer.server import FilerServer
from seaweedfs_tpu.s3api.server import S3ApiServer, _AwsChunkedReader

MB = 1 << 20


class PatternReader:
    """Deterministic pseudo-random byte stream of a given size, never
    materialized; also hashes what it hands out."""

    def __init__(self, total: int, seed: int = 7):
        self.left = total
        self._block = bytes((seed * i * 2654435761 >> 3) & 0xFF
                            for i in range(65536))
        self.md5 = hashlib.md5()

    def read(self, n: int = -1) -> bytes:
        if n < 0 or n > self.left:
            n = self.left
        out = (self._block * (n // len(self._block) + 1))[:n]
        self.left -= n
        self.md5.update(out)
        return out


# -- BodyReader unit ---------------------------------------------------------


def _reader(data: bytes, length=None, chunked=False):
    return rpc.BodyReader(io.BufferedReader(io.BytesIO(data)),
                          length, chunked)


def test_body_reader_exact_reads():
    r = _reader(b"abcdefghij", length=10)
    assert r.length == 10
    assert r.read(4) == b"abcd"
    assert r.read() == b"efghij"
    assert r.read(5) == b""


def test_body_reader_truncation_raises():
    r = _reader(b"abc", length=10)
    with pytest.raises(ConnectionError):
        r.read()
    assert r.truncated


def test_body_reader_chunked():
    wire = b"4\r\nwiki\r\n5\r\npedia\r\n0\r\n\r\n"
    r = _reader(wire, chunked=True)
    assert r.length is None
    assert r.read(6) == b"wikipe"
    assert r.read() == b"dia"
    assert r.read() == b""


def test_aws_chunked_reader():
    framed = (b"5;chunk-signature=deadbeef\r\nhello\r\n"
              b"6\r\n world\r\n"
              b"0\r\n\r\n")
    r = _AwsChunkedReader(_reader(framed, length=len(framed)), 11)
    assert r.length == 11
    assert r.read(3) == b"hel"
    assert r.read() == b"lo world"
    assert r.read() == b""


def test_aws_chunked_declared_length_mismatch():
    """x-amz-decoded-content-length must match the decoded payload —
    a mismatch errors instead of storing a truncated object (review
    finding)."""
    framed = b"5;sig=x\r\nhello\r\n0\r\n\r\n"
    over = _AwsChunkedReader(_reader(framed, length=len(framed)), 3)
    with pytest.raises(ConnectionError):
        over.read()  # actual payload exceeds the declared 3
    under = _AwsChunkedReader(_reader(framed, length=len(framed)), 9)
    with pytest.raises(ConnectionError):
        under.read()  # terminator arrives before the declared 9


# -- e2e with RSS assertion --------------------------------------------------


@pytest.fixture(scope="module")
def stack(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("stream-stack")
    master = MasterServer(volume_size_limit_mb=256, meta_dir=str(tmp))
    master.start()
    vs = VolumeServer(master.url(), [str(tmp / "vs")], pulse_seconds=60)
    vs.start()
    filer = FilerServer(master.url(), chunk_size=MB)
    filer.start()
    s3 = S3ApiServer(filer.url())
    s3.start()
    yield master, vs, filer, s3
    s3.stop()
    filer.stop()
    vs.stop()
    master.stop()


def _upload(url: str, total: int, chunked=False) -> str:
    src = PatternReader(total)
    req = urllib.request.Request(url, data=src, method="PUT")
    if chunked:
        req.add_header("Transfer-Encoding", "chunked")
    else:
        req.add_header("Content-Length", str(total))
    with urllib.request.urlopen(req, timeout=300) as resp:
        resp.read()
    assert src.left == 0
    return src.md5.hexdigest()


def _check_stored(filer, path: str, total: int, md5_hex: str):
    meta = json.loads(urllib.request.urlopen(
        f"{filer.url()}{path}?metadata=true", timeout=30).read())
    from seaweedfs_tpu.filer.entry import FileChunk
    from seaweedfs_tpu.filer.filechunks import total_size
    chunks = [FileChunk.from_dict(c) for c in meta["chunks"]]
    assert total_size(chunks) == total
    # Hash the content back via bounded Range reads.
    md5 = hashlib.md5()
    pos = 0
    while pos < total:
        hi = min(pos + 4 * MB, total) - 1
        req = urllib.request.Request(
            f"{filer.url()}{path}", headers={"Range": f"bytes={pos}-{hi}"})
        with urllib.request.urlopen(req, timeout=60) as r:
            md5.update(r.read())
        pos = hi + 1
    assert md5.hexdigest() == md5_hex


def test_filer_put_streams_with_bounded_memory(stack):
    _m, _vs, filer, _s3 = stack
    total = 48 * MB
    tracemalloc.start()
    md5_hex = _upload(f"{filer.url()}/stream/big.bin", total)
    _cur, peak = tracemalloc.get_traced_memory()
    tracemalloc.stop()
    assert peak < total // 3, (
        f"upload of {total >> 20}MB peaked at {peak >> 20}MB of Python "
        f"allocations — the body is being buffered, not streamed")
    _check_stored(filer, "/stream/big.bin", total, md5_hex)


def test_filer_chunked_te_put_streams(stack):
    _m, _vs, filer, _s3 = stack
    total = 32 * MB
    tracemalloc.start()
    md5_hex = _upload(f"{filer.url()}/stream/chunked.bin", total,
                      chunked=True)
    _cur, peak = tracemalloc.get_traced_memory()
    tracemalloc.stop()
    # Peak is per-hop pipeline overhead (~1MB buffers x copies), not a
    # function of body size.
    assert peak < total // 2
    _check_stored(filer, "/stream/chunked.bin", total, md5_hex)


def test_s3_put_object_streams(stack):
    _m, _vs, filer, s3 = stack
    urllib.request.urlopen(urllib.request.Request(
        f"{s3.url()}/streambucket", method="PUT"), timeout=30).read()
    total = 48 * MB
    tracemalloc.start()
    md5_hex = _upload(f"{s3.url()}/streambucket/big.obj", total)
    _cur, peak = tracemalloc.get_traced_memory()
    tracemalloc.stop()
    # Three hops (client->s3->filer->volume) of ~1MB pipeline buffers;
    # far below the body size, and independent of it.
    assert peak < total // 3, (
        f"S3 PUT of {total >> 20}MB peaked at {peak >> 20}MB — buffered")
    _check_stored(filer, f"/buckets/streambucket/big.obj", total, md5_hex)


def test_client_death_mid_upload_frees_chunks(stack, monkeypatch):
    """A client that dies mid-PUT must not leak the chunks already
    uploaded: the filer's rollback deletes what landed."""
    import socket as sock_mod
    _m, _vs, filer, _s3 = stack
    deleted: list[str] = []
    orig = filer._delete_file_ids
    monkeypatch.setattr(
        filer, "_delete_file_ids",
        lambda fids: (deleted.extend(fids), orig(fids)) and None)
    host, port = filer.server.host, filer.server.port
    s = sock_mod.create_connection((host, port))
    s.sendall(b"PUT /stream/dead.bin HTTP/1.1\r\n"
              b"Host: x\r\nContent-Length: 50000000\r\n\r\n")
    s.sendall(b"x" * (3 * MB))  # a few chunks land...
    s.close()                   # ...then the client dies
    import time as _t
    deadline = _t.time() + 10
    while _t.time() < deadline and not deleted:
        _t.sleep(0.1)
    assert deleted, "partial upload's chunks were not rolled back"
    # And the entry never appeared.
    with pytest.raises(urllib.request.HTTPError):
        urllib.request.urlopen(f"{filer.url()}/stream/dead.bin",
                               timeout=10)
