"""Streamed EC pipeline (ISSUE 8 / ROADMAP 1): byte-identity of the
overlapped encode path, bit-exactness of the kernel-fused `.ecc`
CRC32-C sidecar, the overlap regression (injected clock, no sleeps),
and the zero-collectives property of the shard_map batch step.

Marker: ecpipe (tier-1).
"""

from __future__ import annotations

import itertools
import json
import os
import re
import threading

import numpy as np
import pytest

from seaweedfs_tpu.core.crc import crc32c
from seaweedfs_tpu.ec import SMALL_BLOCK_SIZE, to_ext
from seaweedfs_tpu.ec.encoder import (write_ec_files,
                                      write_sorted_file_from_idx)
from seaweedfs_tpu.ec.integrity import ShardChecksums, file_block_crcs
from seaweedfs_tpu.ops import crc_fold
from seaweedfs_tpu.ops.coder_numpy import NumpyCoder
from seaweedfs_tpu.ops.coder_pallas import PallasCoder
from seaweedfs_tpu.parallel.stream_pipeline import (PipelineRecorder,
                                                    run_pipeline)

pytestmark = pytest.mark.ecpipe

BLOCK = SMALL_BLOCK_SIZE


@pytest.fixture(autouse=True)
def _force_fused(monkeypatch):
    """The fused-CRC default is platform-gated (ON only on TPU, see
    crc_fold.fused_crc_enabled) — force it on so this suite exercises
    the fused paths on the CPU test mesh too."""
    monkeypatch.setenv("SEAWEEDFS_TPU_EC_FUSED_CRC", "1")


# ---------------------------------------------------------------------------
# crc_fold algebra and the fused kernel
# ---------------------------------------------------------------------------

def test_crc_fold_matches_reference_blocks():
    rng = np.random.default_rng(0)
    tile, block = 512, 4096
    rows = rng.integers(0, 256, (3, 3 * block), dtype=np.uint8)
    parts = crc_fold.tile_partials_np(rows, tile, block)
    for r in range(rows.shape[0]):
        got = crc_fold.block_crcs_from_partials(
            parts[r], rows.shape[1], tile, block)
        want = [crc32c(rows[r, b * block:(b + 1) * block].tobytes())
                for b in range(3)]
        assert got == want
    dev = np.asarray(crc_fold.block_crcs_jnp(rows, tile, block))
    assert dev.dtype == np.uint32
    assert [list(map(int, dev[r])) for r in range(3)] == \
        [[crc32c(rows[r, b * block:(b + 1) * block].tobytes())
          for b in range(3)] for r in range(3)]


def test_fused_accumulator_final_partial_block():
    """feed_tiles for the aligned body + feed_bytes for a ragged tail
    must reproduce BlockCrcAccumulator.finalize() bit for bit,
    including the final partial block."""
    rng = np.random.default_rng(1)
    tile, block = 512, 4096
    body = rng.integers(0, 256, (1, 2 * block), dtype=np.uint8)
    tail = rng.integers(0, 256, block // 3, dtype=np.uint8).tobytes()
    parts = crc_fold.tile_partials_np(body, tile, block)
    acc = crc_fold.FusedCrcAccumulator(tile, block)
    acc.feed_tiles(parts[0], 2 * block)
    acc.feed_bytes(tail)
    want = [crc32c(body[0, :block].tobytes()),
            crc32c(body[0, block:].tobytes()), crc32c(tail)]
    assert acc.finalize() == want
    # tiles after a pending tail must refuse (never silently misalign)
    acc2 = crc_fold.FusedCrcAccumulator(tile, block)
    acc2.feed_bytes(b"x")
    with pytest.raises(ValueError):
        acc2.feed_tiles(parts[0], block)


@pytest.mark.parametrize("codec", ["rs", "lrc"])
@pytest.mark.parametrize("mm", ["bf16", "int8"])
def test_fused_kernel_crcs_bit_exact(codec, mm):
    """The Pallas kernel's second output folds to the exact crc32c of
    every `.ecc` block of every shard row — data and parity — with a
    ragged tail handled by the CPU fallback."""
    rng = np.random.default_rng(2)
    n = 2 * BLOCK + 4096  # two full blocks + a partial tail
    data = rng.integers(0, 256, (10, n), dtype=np.uint8)
    coder = PallasCoder(block_n=4096, mm=mm, codec=codec)
    assert coder.fused_crc_ok
    parity, parts = coder.encode_with_crc(data)
    parity, parts = np.asarray(parity), np.asarray(parts)
    assert np.array_equal(parity, NumpyCoder(codec=codec).encode(data))
    rows = np.concatenate([data, parity], axis=0)
    for r in range(rows.shape[0]):
        acc = crc_fold.FusedCrcAccumulator(coder.block_n)
        acc.feed_tiles(parts[r], 2 * BLOCK)
        acc.feed_bytes(rows[r, 2 * BLOCK:].tobytes())
        want = [crc32c(rows[r, b * BLOCK:(b + 1) * BLOCK].tobytes())
                for b in range(2)] + [crc32c(rows[r, 2 * BLOCK:]
                                             .tobytes())]
        assert acc.finalize() == want, f"row {r}"


def test_int8_mm_correctness_gate():
    """Satellite: int8 is the on-TPU serving default (BENCH tuned it
    fastest) — gate it against the NumpyCoder oracle for encode AND
    reconstruct, rs and lrc."""
    rng = np.random.default_rng(3)
    data = rng.integers(0, 256, (10, 8192), dtype=np.uint8)
    for codec in ("rs", "lrc"):
        oracle = NumpyCoder(codec=codec)
        c8 = PallasCoder(mm="int8", codec=codec)
        assert np.array_equal(np.asarray(c8.encode(data)),
                              oracle.encode(data))
        full = np.asarray(c8.encode_all(data))
        lost = (2, 11)
        have = {s: full[s] for s in range(full.shape[0])
                if s not in lost}
        got = c8.reconstruct(have, wanted=list(lost))
        for s in lost:
            assert np.array_equal(np.asarray(got[s]), full[s]), \
                (codec, s)


def test_int8_is_on_tpu_default(monkeypatch):
    from seaweedfs_tpu.ops import coder_pallas
    monkeypatch.delenv("SEAWEEDFS_TPU_MM", raising=False)
    monkeypatch.setattr(coder_pallas, "_on_tpu", lambda: True)
    assert PallasCoder(interpret=True).mm == "int8"
    monkeypatch.setattr(coder_pallas, "_on_tpu", lambda: False)
    assert PallasCoder(interpret=True).mm == "bf16"
    monkeypatch.setenv("SEAWEEDFS_TPU_MM", "bf16")
    monkeypatch.setattr(coder_pallas, "_on_tpu", lambda: True)
    assert PallasCoder(interpret=True).mm == "bf16"


def test_write_ec_files_fused_matches_cpu_sidecar(tmp_path):
    """write_ec_files with the fused coder produces byte-identical
    shards AND a bit-identical `.ecc` to the CPU-accumulator path."""
    rng = np.random.default_rng(4)
    base_f = str(tmp_path / "1")
    base_c = str(tmp_path / "2")
    payload = rng.integers(0, 256, 2 * 1024 * 1024 + 999,
                           dtype=np.uint8).tobytes()
    for b in (base_f, base_c):
        with open(b + ".dat", "wb") as f:
            f.write(payload)
        with open(b + ".idx", "wb") as f:
            f.write(b"")
    write_ec_files(base_f, coder=PallasCoder(block_n=4096),
                   chunk_size=BLOCK)
    write_ec_files(base_c, coder=NumpyCoder(), chunk_size=BLOCK)
    ecc_f = ShardChecksums.load(base_f)
    ecc_c = ShardChecksums.load(base_c)
    for sid in range(14):
        assert open(base_f + to_ext(sid), "rb").read() == \
            open(base_c + to_ext(sid), "rb").read()
        assert ecc_f.get(sid) == ecc_c.get(sid) == \
            file_block_crcs(base_f + to_ext(sid))


# ---------------------------------------------------------------------------
# Overlap regression — injected clock, structural, no sleeps
# ---------------------------------------------------------------------------

def test_pipeline_issues_next_h2d_before_prev_device_completes():
    """The streamed pipeline must dispatch chunk k+1 BEFORE chunk k's
    device step completes.  The fake device enforces it structurally:
    draining chunk k BLOCKS until dispatch(k+1) has been recorded —
    a serialized pipeline would deadlock here (bounded by timeout),
    the streamed one sails through."""
    counter = itertools.count()
    rec = PipelineRecorder(clock=lambda: next(counter))
    n_items = 6
    drained = []

    def drain(handle):
        if handle < n_items - 1:
            assert rec.wait_for("dispatched", handle + 1, timeout=30.0), \
                f"next H2D never issued while chunk {handle} in flight"
        drained.append(handle)

    n = run_pipeline(range(n_items), dispatch=lambda x: x, drain=drain,
                     depth=2, recorder=rec)
    assert n == n_items and drained == list(range(n_items))
    # Injected-clock ordering: the overlap is visible in the recorded
    # sequence numbers, not just in the absence of deadlock.
    for k in range(n_items - 1):
        assert rec.first_time("dispatched", k + 1) < \
            rec.first_time("drained", k)


def test_pipeline_depth0_is_serialized():
    counter = itertools.count()
    rec = PipelineRecorder(clock=lambda: next(counter))
    run_pipeline(range(3), dispatch=lambda x: x, drain=lambda h: None,
                 depth=0, recorder=rec)
    for k in range(2):
        assert rec.first_time("drained", k) < \
            rec.first_time("dispatched", k + 1)


def test_pipeline_error_paths_no_deadlock():
    with pytest.raises(RuntimeError, match="boom"):
        run_pipeline(range(100), dispatch=lambda x: x,
                     drain=lambda h: (_ for _ in ()).throw(
                         RuntimeError("boom")), depth=2)

    def gen():
        yield 1
        raise ValueError("genfail")
    with pytest.raises(ValueError, match="genfail"):
        run_pipeline(gen(), dispatch=lambda x: x,
                     drain=lambda h: None, depth=2)
    with pytest.raises(ZeroDivisionError):
        run_pipeline(range(10), dispatch=lambda x: 1 // 0,
                     drain=lambda h: None, depth=2)
    # Threads must not leak after error unwinds.
    assert not [t for t in threading.enumerate()
                if t.name.startswith("ecpipe-")]


def test_scatter_byte_budget_caps_inflight():
    from seaweedfs_tpu.parallel.cluster_encode import _ByteBudget
    b = _ByteBudget(100)
    t1 = b.acquire(60)
    holder = {}

    def second():
        holder["taken"] = b.acquire(60)  # must block until release

    th = threading.Thread(target=second, daemon=True)
    th.start()
    th.join(timeout=0.2)
    assert th.is_alive() and "taken" not in holder
    b.release(t1)
    th.join(timeout=5.0)
    assert holder["taken"] == 60
    b.release(holder["taken"])
    # An oversized request is clamped, never deadlocks alone.
    big = b.acquire(10 ** 9)
    assert big == 100
    b.release(big)


def test_batch_encode_refuses_bad_chunk_size_before_freeze():
    """The chunk_size guard must reject every value _chunk_reader would
    choke on mid-stream — including in-range non-divisors of the large
    block — BEFORE any replica is frozen (env untouched: None works)."""
    from seaweedfs_tpu.ec import LARGE_BLOCK_SIZE, SMALL_BLOCK_SIZE
    from seaweedfs_tpu.parallel.cluster_encode import batch_encode
    for bad in (SMALL_BLOCK_SIZE // 2, LARGE_BLOCK_SIZE * 2,
                3 * SMALL_BLOCK_SIZE):  # in range, !| large block
        with pytest.raises(ValueError):
            batch_encode(None, [], chunk_size=bad)


# ---------------------------------------------------------------------------
# shard_map batch step: zero collectives
# ---------------------------------------------------------------------------

def test_shard_map_batch_encode_zero_collectives():
    from seaweedfs_tpu.parallel.cluster_rebuild import make_mesh
    from seaweedfs_tpu.parallel.sharded_codec import assert_no_collectives

    mesh = make_mesh()
    hlo = assert_no_collectives(
        mesh, 4,
        (mesh.shape["vol"] * 2, 10, mesh.shape["col"] * 4096))
    assert hlo  # compiled and clean


# ---------------------------------------------------------------------------
# Wire-level: streamed batch encode golden equivalence + pushed .ecc
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    from seaweedfs_tpu.cluster.master import MasterServer
    from seaweedfs_tpu.cluster.volume_server import VolumeServer
    tmp_path = tmp_path_factory.mktemp("ecpipe")
    master = MasterServer(volume_size_limit_mb=64,
                          meta_dir=str(tmp_path), pulse_seconds=60)
    master.start()
    servers = []
    for i in range(3):
        d = tmp_path / f"vs{i}"
        d.mkdir()
        vs = VolumeServer(master.url(), [str(d)], pulse_seconds=60)
        vs.start()
        servers.append(vs)
    yield master, servers, tmp_path
    for vs in servers:
        vs.stop()
    master.stop()


def _freshen(servers):
    for vs in servers:
        vs._send_heartbeat(full=True)
        vs._ec_loc_cache.clear()


def _fill_ragged_volumes(master, n_volumes=2):
    """Volumes with deliberately unequal sizes so the streamed pipeline
    sees ragged tails: one volume runs out of chunks before the other
    (`active` shrinks mid-stream)."""
    from seaweedfs_tpu.cluster import rpc
    from seaweedfs_tpu.cluster.client import WeedClient
    client = WeedClient(master.url())
    rpc.call_json(f"{master.url()}/vol/grow?count={n_volumes}", "POST")
    rng = np.random.default_rng(7)
    by_vid: dict[int, int] = {}
    i = 0
    while len(by_vid) < n_volumes or min(by_vid.values()) < 4:
        payload = rng.integers(0, 256, 64 * 1024 + i * 37,
                               dtype=np.uint8).tobytes()
        fid = client.upload_data(payload)
        vid = int(fid.split(",")[0])
        by_vid[vid] = by_vid.get(vid, 0) + 1
        i += 1
        if i > 200:
            break
    return sorted(by_vid)[:n_volumes]


@pytest.mark.parametrize("codec", ["rs", "lrc"])
def test_streamed_batch_encode_golden(cluster, codec, tmp_path):
    """The overlapped pipeline's shard files AND holder `.ecc` sidecars
    are byte-identical to the seed `write_ec_files` golden layout plus
    the CPU crc32c reference — for ragged volume tails and both
    codecs.  Also proves receive_shard accepted the kernel-pushed CRCs
    (each holder's sidecar entry equals the reference without it ever
    reading the payload: the entries predate the shard push)."""
    from seaweedfs_tpu.cluster import rpc
    from seaweedfs_tpu.codecs import get_codec
    from seaweedfs_tpu.parallel.cluster_encode import batch_encode
    from seaweedfs_tpu.shell import CommandEnv

    master, servers, _ = cluster
    vids = _fill_ragged_volumes(master)
    env = CommandEnv(master.url())
    _freshen(servers)
    total = get_codec(codec).total_shards

    expect_dir = tmp_path / f"expected_{codec}"
    expect_dir.mkdir()
    expected: dict[int, dict[int, bytes]] = {}
    for vid in vids:
        url = env.volume_locations(vid)[0]
        base = str(expect_dir / str(vid))
        rpc.call_to_file(f"http://{url}/admin/volume_file?volume={vid}"
                         "&ext=.dat", base + ".dat")
        rpc.call_to_file(f"http://{url}/admin/volume_file?volume={vid}"
                         "&ext=.idx", base + ".idx")
        write_ec_files(base, coder=NumpyCoder(codec=codec),
                       codec=codec)
        write_sorted_file_from_idx(base)
        expected[vid] = {s: open(base + to_ext(s), "rb").read()
                         for s in range(total)}

    out = batch_encode(env, vids, chunk_size=BLOCK, codec=codec)
    for vid in vids:
        assert any(f"volume {vid} -> ec shards" in line
                   for line in out), out

    _freshen(servers)
    for vid in vids:
        locs = env.ec_shard_locations(vid)
        assert sorted(locs) == list(range(total))
        for sid in range(total):
            got = bytes(rpc.call(
                f"http://{locs[sid][0]}/admin/ec/shard_file?"
                f"volume={vid}&shard={sid}"))
            assert got == expected[vid][sid], (vid, sid)
    # Holder-side `.ecc`: every holder's sidecar entry for every local
    # shard file equals the CPU crc32c reference of its bytes, bit for
    # bit (filesystem walk of the fixture dirs — no server internals).
    _master, servers, base_tmp = cluster
    found = 0
    for root, _dirs, files in os.walk(base_tmp):
        for fname in files:
            m = re.match(r"^(\d+)\.ec(\d\d)$", fname)
            if not m or int(m.group(1)) not in vids:
                continue
            base = os.path.join(root, m.group(1))
            sid = int(m.group(2))
            ecc = ShardChecksums.load(base)
            want = file_block_crcs(os.path.join(root, fname))
            assert ecc.get(sid) == want, (base, sid)
            found += 1
    assert found >= total * len(vids)


def test_streamed_batch_rebuild_pushes_device_ecc(cluster):
    """Kill one shard of an encoded volume, batch-rebuild it, and
    check the new holder's `.ecc` entry matches the CPU crc32c of the
    rebuilt file byte-for-byte AND the rebuilt bytes are identical to
    the originals — the CRC fragment rode the scatter."""
    from seaweedfs_tpu.cluster import rpc
    from seaweedfs_tpu.parallel.cluster_rebuild import batch_rebuild
    from seaweedfs_tpu.shell import CommandEnv

    master, servers, base_tmp = cluster
    env = CommandEnv(master.url())
    _freshen(servers)
    vids = sorted({
        int(m.group(1))
        for root, _d, files in os.walk(base_tmp)
        for f in files
        for m in [re.match(r"^(\d+)\.ec03$", f)] if m})
    assert vids, "no encoded volumes (runs after the golden test)"
    vid = vids[0]
    holder = env.ec_shard_locations(vid)[3][0]
    original = bytes(rpc.call(
        f"http://{holder}/admin/ec/shard_file?volume={vid}&shard=3"))
    rpc.call_json(f"http://{holder}/admin/ec/delete_shards", "POST",
                  {"volume": vid, "shards": [3]})
    _freshen(servers)
    assert 3 not in env.ec_shard_locations(vid)

    out = batch_rebuild(env, [vid])
    assert any("rebuilt shards [3]" in line for line in out), out
    _freshen(servers)
    locs = env.ec_shard_locations(vid)
    assert 3 in locs
    rebuilt = bytes(rpc.call(
        f"http://{locs[3][0]}/admin/ec/shard_file?volume={vid}"
        "&shard=3"))
    assert rebuilt == original
    for root, _dirs, files in os.walk(base_tmp):
        if f"{vid}.ec03" in files:
            base = os.path.join(root, str(vid))
            crcs = ShardChecksums.load(base).get(3)
            if crcs is not None:
                assert crcs == file_block_crcs(base + ".ec03")
                return
    pytest.fail("rebuilt shard's .ecc entry not found")


def test_receive_ecc_endpoint_validation(cluster):
    from seaweedfs_tpu.cluster import rpc
    master, servers, _ = cluster
    url = servers[0].url()
    good = {"block": BLOCK, "shards": {"0": ["0a0b0c0d"]}}
    r = rpc.call(f"http://{url}/admin/ec/receive_ecc?volume=9999",
                 "POST", json.dumps(good).encode())
    assert r["merged"] is True
    with pytest.raises(rpc.RpcError) as ei:
        rpc.call(f"http://{url}/admin/ec/receive_ecc?volume=9999",
                 "POST", json.dumps(
                     {"block": BLOCK, "shards": {"99": ["00000000"]}}
                 ).encode())
    assert ei.value.status == 400
    with pytest.raises(rpc.RpcError) as ei:
        rpc.call(f"http://{url}/admin/ec/receive_ecc?volume=9999",
                 "POST", b"not json")
    assert ei.value.status == 400
    # Wrong shapes must 400, not 500 — and a bare hex string must not
    # be char-iterated into bogus one-digit CRCs.
    for bad in ({"block": BLOCK, "shards": []},
                {"block": BLOCK, "shards": "0a0b0c0d"},
                {"block": BLOCK, "shards": {"0": "0a0b0c0d"}},
                # >32-bit / negative values can never equal a
                # recomputed crc32c — merged, they'd make the first
                # scrub quarantine a healthy shard.
                {"block": BLOCK, "shards": {"0": ["1aabbccdd"]}},
                {"block": BLOCK, "shards": {"0": ["-1"]}}):
        with pytest.raises(rpc.RpcError) as ei:
            rpc.call(f"http://{url}/admin/ec/receive_ecc?volume=9999",
                     "POST", json.dumps(bad).encode())
        assert ei.value.status == 400, bad
    # Existing entries survive a merge of other shards.
    more = {"block": BLOCK, "shards": {"1": ["11111111"]}}
    rpc.call(f"http://{url}/admin/ec/receive_ecc?volume=9999",
             "POST", json.dumps(more).encode())
    base = servers[0]._volume_base(9999)
    ecc = ShardChecksums.load(base)
    assert ecc.get(0) == [0x0a0b0c0d] and ecc.get(1) == [0x11111111]


def test_receive_shard_stale_ecc_refingerprinted(cluster):
    """receive_shard only trusts a `.ecc` entry that receive_ecc
    shipped for THIS push (the pending map).  A stale sidecar entry
    left by a prior encode generation — same padded shard size, so the
    block count matches — must be re-fingerprinted from the pushed
    body, or the first scrub would quarantine a healthy shard."""
    from seaweedfs_tpu.cluster import rpc
    master, servers, _ = cluster
    vs = servers[0]
    url = vs.url()
    vid = 9998
    body = bytes(np.random.default_rng(7).integers(
        0, 256, BLOCK, dtype=np.uint8))
    true_crc = crc32c(body)
    stale = (true_crc + 1) & 0xFFFFFFFF

    # A prior generation's entry: in the sidecar, NOT pending.
    rpc.call(f"http://{url}/admin/ec/receive_ecc?volume={vid}", "POST",
             json.dumps({"block": BLOCK,
                         "shards": {"3": [f"{stale:08x}"]}}).encode())
    vs._ec_pending_ecc.clear()  # the pushing encoder is long gone
    rpc.call(f"http://{url}/admin/ec/receive_shard?volume={vid}"
             "&shard=3", "POST", body)
    base = vs._volume_base(vid)
    assert ShardChecksums.load(base).get(3) == [true_crc]

    # Fresh fragment for this push: consumed from the pending map and
    # trusted verbatim — it describes the INTENDED bytes, so a CRC that
    # differs from the wire body is exactly what makes push corruption
    # scrub-detectable (no CPU re-fingerprint overwrites it).
    intended = (true_crc ^ 0xDEADBEEF) & 0xFFFFFFFF
    rpc.call(f"http://{url}/admin/ec/receive_ecc?volume={vid}", "POST",
             json.dumps({"block": BLOCK,
                         "shards": {"4": [f"{intended:08x}"]}}).encode())
    rpc.call(f"http://{url}/admin/ec/receive_shard?volume={vid}"
             "&shard=4", "POST", body)
    assert ShardChecksums.load(base).get(4) == [intended]
    assert vid not in vs._ec_pending_ecc  # consumed, not leaked

    # An EXPIRED pending entry (its shard push failed long ago, and a
    # later generation's push happens to match the block count) must
    # not be trusted either: fingerprint wins.
    from seaweedfs_tpu.cluster import volume_server as vs_mod
    rpc.call(f"http://{url}/admin/ec/receive_ecc?volume={vid}", "POST",
             json.dumps({"block": BLOCK,
                         "shards": {"5": [f"{stale:08x}"]}}).encode())
    old_ttl = vs_mod._PENDING_ECC_TTL
    vs_mod._PENDING_ECC_TTL = 0.0
    try:
        rpc.call(f"http://{url}/admin/ec/receive_shard?volume={vid}"
                 "&shard=5", "POST", body)
    finally:
        vs_mod._PENDING_ECC_TTL = old_ttl
    assert ShardChecksums.load(base).get(5) == [true_crc]
