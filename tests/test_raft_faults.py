"""Raft fault injection: partitions, leader kills mid-operation,
InstallSnapshot racing appends, membership churn under load.

The reference trusts a battle-tested library
(weed/server/raft_server.go vendoring chrislusf/raft); a from-scratch
raft earns trust through adversarial schedules (VERDICT r4 #4).  Every
test asserts the two safety properties that matter to the master:
no committed entry is ever lost or reordered, and file ids / volume
ids stay unique+monotonic across every failover schedule.

Partitioning uses the RaftNode.transport seam: a blocked link raises
like a dead TCP connection, in BOTH directions.
"""

import threading
import time

import pytest

from seaweedfs_tpu.cluster import rpc
from seaweedfs_tpu.cluster.master import MasterServer
from seaweedfs_tpu.cluster.raft import LEADER, NotLeader, RaftNode


class Net:
    """Bidirectional partition fabric over the transport seam."""

    def __init__(self):
        self.cut: set[frozenset] = set()

    def isolate(self, node_id: str, others: list[str]) -> None:
        for o in others:
            if o != node_id:
                self.cut.add(frozenset((node_id, o)))

    def heal(self) -> None:
        self.cut.clear()

    def transport_for(self, node_id: str):
        def call(url: str, *a, **kw):
            target = url.split("/raft/")[0]
            if frozenset((node_id, target)) in self.cut:
                raise ConnectionError(
                    f"partitioned: {node_id} -/-> {target}")
            return rpc.call_json(url, *a, **kw)
        return call


def _mk_cluster(n, tmp_path, sinks, net: Net | None = None,
                compact_threshold: int = 1000):
    servers = [rpc.JsonHttpServer() for _ in range(n)]
    urls = [s.url() for s in servers]
    nodes = []
    for i, s in enumerate(servers):
        node = RaftNode(
            urls[i], urls,
            apply_fn=lambda cmd, i=i: sinks[i].append(cmd),
            state_path=str(tmp_path / f"raft{i}.json"),
            election_timeout=(0.25, 0.5), heartbeat_interval=0.06,
            compact_threshold=compact_threshold)
        if net is not None:
            node.transport = net.transport_for(urls[i])
        node.mount(s)
        s.start()
        nodes.append(node)
    for node in nodes:
        node.start()
    return servers, urls, nodes


def _wait_leader(nodes, timeout=20.0, exclude=()):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        leaders = [x for x in nodes
                   if x.state == LEADER and x not in exclude]
        if len(leaders) == 1:
            return leaders[0]
        time.sleep(0.03)
    raise AssertionError("no single leader")


def _wait_converged(sinks, n_entries, nodes=None, timeout=20.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if all(len(s) >= n_entries for s in sinks):
            return
        time.sleep(0.03)
    raise AssertionError(
        f"sinks never reached {n_entries}: {[len(s) for s in sinks]}")


def _vals(sink):
    return [c.get("v") for c in sink if "v" in c]


def _teardown(nodes, servers):
    for x in nodes:
        x.stop()
    for s in servers:
        s.stop()


def test_partitioned_leader_cannot_commit_and_steps_down(tmp_path):
    """Classic partition: the old leader in the minority must never
    commit; the majority side elects and commits; after heal the old
    leader steps down and converges WITHOUT losing the majority's
    committed entries."""
    net = Net()
    sinks = [[], [], []]
    servers, urls, nodes = _mk_cluster(3, tmp_path, sinks, net)
    try:
        leader = _wait_leader(nodes)
        leader.propose({"v": 0})
        _wait_converged(sinks, 1)
        net.isolate(leader.id, urls)
        # Minority leader: this proposal must NOT commit anywhere.
        with pytest.raises((TimeoutError, NotLeader)):
            leader.propose({"v": "lost"}, timeout=1.5)
        majority = [x for x in nodes if x is not leader]
        new_leader = _wait_leader(majority, exclude=(leader,))
        for i in range(1, 4):
            new_leader.propose({"v": i})
        maj_sinks = [sinks[nodes.index(x)] for x in majority]
        _wait_converged(maj_sinks, 4)
        net.heal()
        # Old leader rejoins as follower and converges.
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and \
                (leader.state == LEADER or len(sinks[nodes.index(leader)]) < 4):
            time.sleep(0.05)
        assert leader.state != LEADER
        _wait_converged(sinks, 4)
        for s in sinks:
            assert _vals(s)[:4] == [0, 1, 2, 3]
            assert "lost" not in _vals(s)
    finally:
        _teardown(nodes, servers)


def test_no_commit_without_quorum(tmp_path):
    net = Net()
    sinks = [[], [], []]
    servers, urls, nodes = _mk_cluster(3, tmp_path, sinks, net)
    try:
        leader = _wait_leader(nodes)
        leader.propose({"v": 0})
        _wait_converged(sinks, 1)
        for u in urls:  # full partition: every link cut
            net.isolate(u, urls)
        with pytest.raises((TimeoutError, NotLeader)):
            leader.propose({"v": "never"}, timeout=1.5)
        time.sleep(0.5)
        for s in sinks:
            assert "never" not in _vals(s)
        net.heal()
        nl = _wait_leader(nodes)
        nl.propose({"v": 1}, timeout=10)
        _wait_converged(sinks, 2)
        for s in sinks:
            assert _vals(s)[:2] in ([0, 1], [0, "never"])  # see below
        # "never" may commit after heal ONLY if the old leader retained
        # leadership and its entry replicated — that is legal raft
        # (uncommitted != must-be-lost).  What is illegal is loss of a
        # committed entry or divergence between sinks:
        assert len({tuple(map(str, _vals(s)[:2])) for s in sinks}) == 1
    finally:
        _teardown(nodes, servers)


def test_partition_heal_cycles_converge_identically(tmp_path):
    """Repeated partition/heal churn with proposals in between: all
    state machines end byte-identical, committed prefix preserved."""
    net = Net()
    sinks = [[], [], []]
    servers, urls, nodes = _mk_cluster(3, tmp_path, sinks, net)
    try:
        seq = 0
        committed: list[int] = []
        for cycle in range(3):
            leader = _wait_leader(nodes, timeout=15)
            for _ in range(3):
                try:
                    leader.propose({"v": seq}, timeout=5)
                    committed.append(seq)
                except (TimeoutError, NotLeader):
                    pass
                seq += 1
            victim = leader if cycle % 2 == 0 else \
                next(x for x in nodes if x is not leader)
            net.isolate(victim.id, urls)
            time.sleep(0.6)
            net.heal()
        # Post-heal election churn can depose the leader between the
        # wait and the propose; re-resolve and retry like a client.
        for _attempt in range(5):
            leader = _wait_leader(nodes, timeout=15)
            try:
                leader.propose({"v": "fin"}, timeout=10)
                break
            except (TimeoutError, NotLeader):
                time.sleep(0.2)
        else:
            raise AssertionError("fin never committed")
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            tails = [_vals(s) for s in sinks]
            if all(t and t[-1] == "fin" for t in tails) and \
                    len({tuple(map(str, t)) for t in tails}) == 1:
                break
            time.sleep(0.05)
        tails = [_vals(s) for s in sinks]
        assert len({tuple(map(str, t)) for t in tails}) == 1, tails
        # Every entry acknowledged committed is present, in order.
        final = tails[0]
        it = iter(final)
        for v in committed:
            assert v in final, (v, final)
        pos = [final.index(v) for v in committed]
        assert pos == sorted(pos)
    finally:
        _teardown(nodes, servers)


def test_install_snapshot_races_live_appends(tmp_path):
    """A follower cut off past the compaction horizon receives
    InstallSnapshot WHILE the leader keeps appending: the follower must
    converge to the exact applied sequence with no gap or repeat at the
    snapshot/log seam."""
    net = Net()
    sinks = [[], [], []]
    servers, urls, nodes = _mk_cluster(3, tmp_path, sinks, net,
                                       compact_threshold=30)
    try:
        leader = _wait_leader(nodes)
        follower = next(x for x in nodes if x is not leader)
        fi = nodes.index(follower)
        net.isolate(follower.id, urls)
        # Push far past the compaction threshold while it's dark.
        for i in range(80):
            leader.propose({"v": i}, timeout=5)
        live = [s for j, s in enumerate(sinks) if j != fi]
        _wait_converged(live, 80)
        assert leader.log_base > 0, "compaction never happened"
        # Heal and keep appending concurrently.
        stop = threading.Event()
        appended = []

        def hammer():
            i = 80
            while not stop.is_set():
                try:
                    leader.propose({"v": i}, timeout=5)
                    appended.append(i)
                    i += 1
                except (TimeoutError, NotLeader):
                    return
                time.sleep(0.005)

        th = threading.Thread(target=hammer, daemon=True)
        th.start()
        net.heal()
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline and \
                follower.last_applied < 80:
            time.sleep(0.05)
        stop.set()
        th.join(timeout=5)
        total = 80 + len(appended)
        live = [s for j, s in enumerate(sinks) if j != fi]
        _wait_converged(live, total, timeout=15)
        # Follower convergence is by applied INDEX: entries up to the
        # snapshot horizon arrive via restore (no apply_fn call), the
        # rest via the apply loop.
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline and \
                follower.last_applied < leader.last_applied:
            time.sleep(0.05)
        assert follower.last_applied == leader.last_applied
        lv = _vals(sinks[nodes.index(leader)])
        assert lv == list(range(total))
        # The follower's sink is a clean SUFFIX of the sequence — no
        # gap and no repeat at the snapshot/log seam.
        fv = _vals(sinks[fi])
        assert fv == lv[len(lv) - len(fv):], (fv[:5], len(fv))
    finally:
        _teardown(nodes, servers)


def test_membership_change_under_load(tmp_path):
    """add_server then remove_server while proposals flow: no committed
    loss, the joiner converges, the removed node stops participating."""
    net = Net()
    sinks = [[], [], [], []]
    servers, urls, nodes = _mk_cluster(3, tmp_path, sinks[:3], net)
    # A fourth node, initially outside the cluster.
    s4 = rpc.JsonHttpServer()
    n4 = RaftNode(s4.url(), [s4.url()],
                  apply_fn=sinks[3].append,
                  state_path=str(tmp_path / "raft3.json"),
                  election_timeout=(0.2, 0.4), heartbeat_interval=0.05)
    n4.in_config = False  # waits to be added
    n4.transport = net.transport_for(s4.url())
    n4.mount(s4)
    s4.start()
    n4.start()
    try:
        leader = _wait_leader(nodes)
        stop = threading.Event()
        acked = []

        def load():
            i = 0
            while not stop.is_set():
                try:
                    leader.propose({"v": i}, timeout=5)
                    acked.append(i)
                except (TimeoutError, NotLeader):
                    return
                i += 1
                time.sleep(0.004)

        th = threading.Thread(target=load, daemon=True)
        th.start()
        time.sleep(0.2)
        leader.add_server(s4.url(), timeout=10)
        time.sleep(0.4)
        victim = next(x for x in nodes if x is not leader)
        leader.remove_server(victim.id, timeout=10)
        time.sleep(0.4)
        stop.set()
        th.join(timeout=5)
        assert len(acked) > 20, "load generator barely ran"
        # Every acked entry lands, in order, on leader + joiner.
        deadline = time.monotonic() + 10
        li = nodes.index(leader)
        while time.monotonic() < deadline and (
                len(_vals(sinks[3])) < len(acked)
                or len(_vals(sinks[li])) < len(acked)):
            time.sleep(0.05)
        for sink in (sinks[li], sinks[3]):
            vals = _vals(sink)
            assert vals[:len(acked)] == acked[:len(vals)] or \
                vals == acked, (len(vals), len(acked))
        assert not victim.in_config
    finally:
        n4.stop()
        s4.stop()
        _teardown(nodes, servers)


def test_partitioned_candidate_term_inflation_rejoin(tmp_path):
    """An isolated node campaigns repeatedly and inflates its term; on
    heal the cluster absorbs the higher term (one new election at most)
    without losing committed entries."""
    net = Net()
    sinks = [[], [], []]
    servers, urls, nodes = _mk_cluster(3, tmp_path, sinks, net)
    try:
        leader = _wait_leader(nodes)
        for i in range(3):
            leader.propose({"v": i})
        _wait_converged(sinks, 3)
        outsider = next(x for x in nodes if x is not leader)
        net.isolate(outsider.id, urls)
        time.sleep(1.5)  # several election timeouts of term churn
        assert outsider.current_term > leader.current_term
        net.heal()
        nl = _wait_leader(nodes, timeout=15)
        nl.propose({"v": 3}, timeout=10)
        _wait_converged(sinks, 4)
        for s in sinks:
            assert _vals(s)[:4] == [0, 1, 2, 3]
    finally:
        _teardown(nodes, servers)


# -- master-level schedules (leader kill mid-operation) ----------------------

from seaweedfs_tpu.cluster.volume_server import VolumeServer  # noqa: E402


@pytest.fixture
def ha_cluster(tmp_path):
    ports = [rpc.free_port() for _ in range(3)]
    urls = [f"http://127.0.0.1:{p}" for p in ports]
    masters = []
    for i, p in enumerate(ports):
        d = tmp_path / f"m{i}"
        d.mkdir()
        m = MasterServer(port=p, volume_size_limit_mb=64,
                         meta_dir=str(d), peers=urls, pulse_seconds=60)
        m.raft.election_timeout = (0.2, 0.4)
        m.raft.heartbeat_interval = 0.05
        m.start()
        masters.append(m)
    vs = VolumeServer(urls, [str(tmp_path / "vs")], pulse_seconds=1)
    vs.start()
    yield masters, vs
    vs.stop()
    for m in masters:
        try:
            m.stop()
        except Exception:  # noqa: BLE001 — some stopped in-test
            pass


def _wait_master_leader(masters, timeout=20.0, exclude=()):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        leaders = [m for m in masters
                   if m.raft.state == LEADER and m not in exclude]
        if len(leaders) == 1 and list(leaders[0].topo.leaves()):
            return leaders[0]
        time.sleep(0.05)
    raise AssertionError("no master leader with a registered node")


def _assign_any(masters):
    """Assign via whichever master answers (clients retry seeds)."""
    last = None
    for m in masters:
        try:
            out = rpc.call(m.url() + "/dir/assign?count=1", timeout=3)
            if "fid" in out:
                return out["fid"]
            last = rpc.RpcError(500, str(out))
        except Exception as e:  # noqa: BLE001
            last = e
    raise last


def test_leader_kill_during_sequencer_advance(ha_cluster):
    """Clients hammer /dir/assign while the leader is killed mid-run:
    every fid issued across the failover must be UNIQUE — the raft-
    replicated sequencer must never re-issue a file-id block."""
    masters, vs = ha_cluster
    leader = _wait_master_leader(masters)
    fids: list[str] = []
    fids_lock = threading.Lock()
    stop = threading.Event()

    def worker():
        while not stop.is_set():
            try:
                fid = _assign_any(masters)
            except Exception:  # noqa: BLE001 — failover window
                time.sleep(0.05)
                continue
            with fids_lock:
                fids.append(fid)

    threads = [threading.Thread(target=worker, daemon=True)
               for _ in range(4)]
    for th in threads:
        th.start()
    time.sleep(0.5)
    leader.stop()  # kill mid-hammer
    survivors = [m for m in masters if m is not leader]
    _wait_master_leader(survivors, exclude=(leader,))
    time.sleep(1.0)  # keep assigning against the new leader
    stop.set()
    for th in threads:
        th.join(timeout=5)
    assert len(fids) > 50, "assign load barely ran"
    keys = [f.split(",")[1][:-8] for f in fids]
    assert len(set(fids)) == len(fids), "duplicate fid issued"
    assert len(set(keys)) == len(keys), "file-id key re-issued"
    # Monotonic issuance: keys are hex of a raft-backed counter.
    nums = [int(k, 16) for k in keys]
    assert len(set(nums)) == len(nums)


def test_leader_kill_during_volume_growth(ha_cluster):
    """Kill the leader while /vol/grow allocations are in flight: the
    new leader must keep volume ids unique (raft MaxVolumeId ceiling),
    and assigns keep working on the grown topology."""
    masters, vs = ha_cluster
    leader = _wait_master_leader(masters)
    stop = threading.Event()
    errors: list[str] = []

    def grower():
        while not stop.is_set():
            for m in masters:
                try:
                    rpc.call_json(m.url() + "/vol/grow?count=1", "POST",
                                  timeout=3)
                    break
                except Exception:  # noqa: BLE001 — failover window
                    continue
            time.sleep(0.05)

    th = threading.Thread(target=grower, daemon=True)
    th.start()
    time.sleep(0.4)
    leader.stop()
    survivors = [m for m in masters if m is not leader]
    new_leader = _wait_master_leader(survivors, exclude=(leader,))
    time.sleep(1.0)
    stop.set()
    th.join(timeout=5)
    # Force registrations current, then check uniqueness.
    vs._send_heartbeat(full=True)
    time.sleep(0.3)
    vids = [v.id for dn in new_leader.topo.leaves()
            for v in dn.volumes.values()]
    assert len(vids) == len(set(vids)), f"duplicate volume id: {vids}"
    assert len(vids) >= 2
    fid = _assign_any(survivors)
    assert "," in fid


def test_exactly_once_apply_across_leader_kill(tmp_path):
    """Propose, ack, kill the leader immediately: survivors apply every
    committed entry EXACTLY once — no duplicate application after the
    new leader's term begins."""
    sinks = [[], [], []]
    servers, urls, nodes = _mk_cluster(3, tmp_path, sinks)
    try:
        leader = _wait_leader(nodes)
        for i in range(10):
            leader.propose({"v": i})
        li = nodes.index(leader)
        leader.stop()
        servers[li].stop()
        survivors = [x for x in nodes if x is not leader]
        nl = _wait_leader(survivors, timeout=15, exclude=(leader,))
        nl.propose({"v": 10}, timeout=10)
        live = [sinks[nodes.index(x)] for x in survivors]
        _wait_converged(live, 11)
        for s in live:
            vals = _vals(s)
            assert vals == list(range(11)), vals  # once each, in order
    finally:
        _teardown(nodes, servers)


def test_divergent_uncommitted_log_truncated_on_rejoin(tmp_path):
    """The §5.3 conflict case: an isolated leader accumulates
    uncommitted entries at indexes the majority fills differently;
    after heal its log truncates to the majority's — its own divergent
    tail disappears, the committed majority entries survive."""
    net = Net()
    sinks = [[], [], []]
    servers, urls, nodes = _mk_cluster(3, tmp_path, sinks, net)
    try:
        leader = _wait_leader(nodes)
        leader.propose({"v": "base"})
        _wait_converged(sinks, 1)
        net.isolate(leader.id, urls)
        # Uncommitted divergent tail on the isolated leader.
        for tag in ("dead-a", "dead-b"):
            try:
                leader.propose({"v": tag}, timeout=0.8)
            except (TimeoutError, NotLeader):
                pass
        majority = [x for x in nodes if x is not leader]
        nl = _wait_leader(majority, exclude=(leader,))
        for i in range(3):
            nl.propose({"v": i}, timeout=5)
        maj_sinks = [sinks[nodes.index(x)] for x in majority]
        _wait_converged(maj_sinks, 4)
        net.heal()
        old_sink = sinks[nodes.index(leader)]
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and len(_vals(old_sink)) < 4:
            time.sleep(0.05)
        vals = _vals(old_sink)
        assert vals[:4] == ["base", 0, 1, 2], vals
        assert "dead-a" not in vals and "dead-b" not in vals
        # And the divergent entries are gone from its LOG, not just
        # unapplied (truncation, §5.3).
        logged = [e["cmd"].get("v") for e in leader.log
                  if "v" in e.get("cmd", {})]
        assert "dead-a" not in logged and "dead-b" not in logged
    finally:
        _teardown(nodes, servers)
