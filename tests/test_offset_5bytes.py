"""5-byte offset flavor: the reference's 5BytesOffset build tag
(weed/storage/types/offset_5bytes.go:9-16) as a config-selected
process flavor — 17-byte .idx records, 8TB max volume.

Boundary coverage writes a needle PAST the 32GB 4-byte cap using a
sparse .dat (truncate + append), so the test exercises real >32-bit
offset units without 32GB of disk."""

import os

import pytest

from seaweedfs_tpu.core import idx as idx_mod
from seaweedfs_tpu.core import types as t
from seaweedfs_tpu.core.needle import Needle


@pytest.fixture
def five_byte_flavor():
    t.set_offset_flavor(5)
    yield
    t.set_offset_flavor(4)


def test_offset_codec_roundtrip_5bytes(five_byte_flavor):
    assert t.OFFSET_SIZE == 5
    assert t.NEEDLE_MAP_ENTRY_SIZE == 17
    assert t.MAX_POSSIBLE_VOLUME_SIZE == 8 << 40  # 8TB
    for actual in (0, 8, 32 << 30, (32 << 30) + 8, (8 << 40) - 8):
        b = t.offset_to_bytes(actual)
        assert len(b) == 5
        assert t.offset_from_bytes(b) == actual
    # Layout matches OffsetToBytes: 4 BE low bytes then the high byte.
    units = (40 << 30) // 8  # > 2^32 units? no — > 2^32 BYTES: check
    b = t.offset_to_bytes(40 << 30)
    assert b[4] == (((40 << 30) // 8) >> 32) & 0xFF
    assert b[:4] == (((40 << 30) // 8) & 0xFFFFFFFF).to_bytes(4, "big")


def test_offset_4byte_layout_unchanged():
    assert t.OFFSET_SIZE == 4
    b = t.offset_to_bytes(1 << 20)
    assert len(b) == 4
    assert t.offset_from_bytes(b) == 1 << 20


def test_idx_entries_17_bytes_roundtrip(five_byte_flavor, tmp_path):
    p = tmp_path / "x.idx"
    big = (33 << 30)  # past the 32GB 4-byte cap
    with open(p, "wb") as f:
        idx_mod.append_entry(f, 7, 4096, 100)
        idx_mod.append_entry(f, 8, big, 200)
    assert os.path.getsize(p) == 2 * 17
    with open(p, "rb") as f:
        entries = list(idx_mod.iter_index(f))
    assert [(e.key, e.offset, e.size) for e in entries] == \
        [(7, 4096, 100), (8, big, 200)]


@pytest.mark.parametrize("kind", ["compact", "memory", "sorted_file"])
def test_needle_maps_past_32gb(five_byte_flavor, tmp_path, kind):
    from seaweedfs_tpu.storage.needle_map import new_needle_map
    p = str(tmp_path / "v.idx")
    big = (100 << 30) + 4096  # ~100GB offset
    with open(p, "wb") as f:
        idx_mod.append_entry(f, 1, 4096, 50)
        idx_mod.append_entry(f, 2, big, 60)
        idx_mod.append_entry(f, 3, big + 4096, 70)
        idx_mod.append_entry(f, 3, 0, t.TOMBSTONE_FILE_SIZE)  # delete
    nm = new_needle_map(kind, p)
    assert nm.get(1) == (4096, 50)
    assert nm.get(2) == (big, 60)
    assert nm.get(3) is None
    assert len(nm) == 2
    nm.close()


def test_volume_needle_past_32gb_sparse(five_byte_flavor, tmp_path):
    """End-to-end: a needle written at a >32GB offset (sparse file)
    round-trips through Volume write/read and survives reopen."""
    from seaweedfs_tpu.storage.volume import Volume
    v = Volume(str(tmp_path), "", 1)
    v.write_needle(Needle(id=1, cookie=5, data=b"low"))
    # Fake a huge volume: push the append cursor past 32GB (sparse).
    with v._lock:
        v._dat.seek(0, os.SEEK_END)
        target = (33 << 30)
        v._dat.truncate(target)
        v._dat.seek(0, os.SEEK_END)
        v._append_at = target
    off, _sz = v.write_needle(Needle(id=2, cookie=5, data=b"high" * 100))
    assert off >= 33 << 30
    assert v.read_needle(2).data == b"high" * 100
    assert v.read_needle(1).data == b"low"
    v.close()
    # Reopen: the .idx replay must resolve the >32GB offset.
    v2 = Volume(str(tmp_path), "", 1, create=False)
    assert v2.read_needle(2).data == b"high" * 100
    assert v2.read_needle(1).data == b"low"
    v2.close()


def test_4byte_volume_caps_at_32gb(tmp_path):
    from seaweedfs_tpu.storage.volume import Volume, VolumeError
    v = Volume(str(tmp_path), "", 1)
    with v._lock:
        v._dat.seek(0, os.SEEK_END)
        v._dat.truncate(33 << 30)
        v._append_at = 33 << 30
    with pytest.raises(VolumeError, match="max size"):
        v.write_needle(Needle(id=1, cookie=1, data=b"x"))
    v.close()


def test_cli_flag_selects_flavor(tmp_path, monkeypatch):
    from seaweedfs_tpu.command import main
    # `weed version -offsetBytes=5` flips the process flavor.
    try:
        main(["version", "-offsetBytes=5"])
        assert t.OFFSET_SIZE == 5
    finally:
        t.set_offset_flavor(4)
