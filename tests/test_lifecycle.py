"""Data-lifecycle plane: the declarative policy engine, the read-through
remote block cache (bounded bytes + singleflight), the master-side
lifecycle daemon (idle-cold tiering), auto-promotion of hot tiered
volumes, TTL expiry that actually deletes data (vacuum + whole-volume
retirement + near-expiry layout steering), and the kill -9 crash
windows around tier upload/download (a volume is always fully local or
fully remote on remount)."""

import os
import signal
import subprocess
import sys
import textwrap
import threading
import time

import pytest

from seaweedfs_tpu.cluster import rpc
from seaweedfs_tpu.cluster.client import WeedClient
from seaweedfs_tpu.cluster.master import MasterServer
from seaweedfs_tpu.cluster.volume_server import VolumeServer
from seaweedfs_tpu.core.needle import Needle
from seaweedfs_tpu.core.ttl import TTL
from seaweedfs_tpu.lifecycle import (LifecycleDaemon, Policy, PolicyError,
                                     Rule, load_rules, parse_duration,
                                     parse_rules_text)
from seaweedfs_tpu.storage import expiry
from seaweedfs_tpu.storage.backend import LocalDirBackend
from seaweedfs_tpu.storage.remote_cache import CACHE, RemoteBlockCache
from seaweedfs_tpu.storage.tier import (load_vif, move_dat_to_remote,
                                        open_remote_volume)
from seaweedfs_tpu.storage.vacuum import vacuum
from seaweedfs_tpu.storage.volume import (NotFoundError, Volume,
                                          VolumeError)

pytestmark = pytest.mark.lifecycle


@pytest.fixture(autouse=True)
def _clean_lifecycle_state():
    yield
    expiry.reset_clock()
    CACHE.reset()


# -- policy engine -----------------------------------------------------------

def test_parse_duration_units():
    assert parse_duration("90s") == 90.0
    assert parse_duration("10m") == 600.0
    assert parse_duration("2h") == 7200.0
    assert parse_duration("30d") == 30 * 86400.0
    assert parse_duration("1w") == 604800.0
    assert parse_duration("45") == 45.0           # bare seconds
    assert parse_duration("1.5m") == 90.0
    for bad in ("", "10x", "m", "-5s", "1 0m"):
        with pytest.raises(PolicyError):
            parse_duration(bad)


def test_line_grammar_and_first_match_wins():
    p = parse_rules_text(textwrap.dedent("""\
        # comments and blank lines are fine

        logs    tier   dest=local:///cold  idle=10m
        logs    tier   dest=local:///never  age=99d   # shadowed
        pics    tier   dest=s3://h:1/b/frozen  age=30d  fullness=0.8
        scratch expire
        *       expire
    """))
    assert len(p) == 5
    r = p.tier_rule_for("logs")
    assert (r.dest, r.idle_for) == ("local:///cold", 600.0)
    r = p.tier_rule_for("pics")
    assert (r.min_age, r.fullness) == (30 * 86400.0, 0.8)
    assert p.tier_rule_for("other") is None
    # expire: the exact rule wins over the wildcard, both match.
    assert p.expire_rule_for("scratch").collection == "scratch"
    assert p.expire_rule_for("anything").collection == "*"


def test_toml_rules_and_load_dispatch(tmp_path):
    toml = tmp_path / "rules.toml"
    toml.write_text(textwrap.dedent("""\
        [[rule]]
        collection = "logs"
        action = "tier"
        dest = "local:///cold"
        idle = "10m"

        [[rule]]
        collection = "*"
        action = "expire"
    """))
    p = load_rules(str(toml))
    assert p.tier_rule_for("logs").idle_for == 600.0
    assert p.expire_rule_for("x") is not None
    txt = tmp_path / "rules.txt"
    txt.write_text("logs tier dest=local:///cold idle=10m\n")
    assert len(load_rules(str(txt))) == 1


@pytest.mark.parametrize("bad,msg", [
    ("logs tier idle=10m", "dest"),                     # no destination
    ("logs tier dest=local:///c", "at least one"),      # unconditional
    ("logs tier dest=local:///c fullness=1.5", "fullness"),
    ("logs expire idle=10m", "no conditions"),
    ("logs tier dest=local:///c shade=1", "unknown rule keys"),
    ("logs freeze", "unknown lifecycle action"),
    ("logs", "want"),
    ("logs tier dest", "bad token"),
])
def test_rule_validation_errors(bad, msg):
    with pytest.raises(PolicyError, match=msg):
        parse_rules_text(bad)


# -- remote block cache ------------------------------------------------------

def _backend_with_object(tmp_path, name: str, nbytes: int):
    b = LocalDirBackend(str(tmp_path / name))
    payload = os.urandom(nbytes)
    src = tmp_path / f"{name}.src"
    src.write_bytes(payload)
    b.upload_file("obj", str(src))
    return b, payload


def test_cache_bounded_bytes_lru(tmp_path):
    b, payload = _backend_with_object(tmp_path, "lru", 5 << 20)
    c = RemoteBlockCache(max_bytes=2 << 20)  # room for 2 blocks
    for idx in range(5):
        blk, hit = c.get_block(b, "obj", idx, idx << 20,
                               min(1 << 20, len(payload) - (idx << 20)))
        assert not hit
        assert blk == payload[idx << 20:(idx + 1) << 20]
    assert c.used_bytes() <= 2 << 20
    assert c.evictions == 3
    # Newest block cached, oldest evicted.
    _, hit = c.get_block(b, "obj", 4, 4 << 20, 1 << 20)
    assert hit
    _, hit = c.get_block(b, "obj", 0, 0, 1 << 20)
    assert not hit


def test_cache_singleflight_one_backend_fetch(tmp_path):
    b, payload = _backend_with_object(tmp_path, "sf", 1 << 20)
    c = RemoteBlockCache(max_bytes=8 << 20)
    fetches = [0]
    gate = threading.Event()
    real = b.read_range

    def slow_read(key, offset, size):
        fetches[0] += 1
        gate.wait(5.0)
        return real(key, offset, size)

    b.read_range = slow_read
    results = []

    def reader():
        results.append(c.get_block(b, "obj", 0, 0, 1 << 20))

    threads = [threading.Thread(target=reader) for _ in range(6)]
    for t in threads:
        t.start()
    time.sleep(0.1)   # let every follower queue up behind the leader
    gate.set()
    for t in threads:
        t.join(10.0)
    assert fetches[0] == 1, "singleflight must collapse to ONE fetch"
    assert len(results) == 6
    assert all(blk == payload for blk, _hit in results)
    assert sum(1 for _b, hit in results if not hit) == 1


def test_cache_leader_failure_elects_new_leader(tmp_path):
    b, payload = _backend_with_object(tmp_path, "fail", 1 << 20)
    c = RemoteBlockCache(max_bytes=8 << 20)
    real = b.read_range
    calls = [0]

    def flaky(key, offset, size):
        calls[0] += 1
        if calls[0] == 1:
            raise ConnectionResetError("wan died")
        return real(key, offset, size)

    b.read_range = flaky
    with pytest.raises(ConnectionResetError):
        c.get_block(b, "obj", 0, 0, 1 << 20)
    # The failed leader must not poison the block: the next reader
    # becomes the leader and succeeds.
    blk, hit = c.get_block(b, "obj", 0, 0, 1 << 20)
    assert blk == payload and not hit


def test_cache_drop_file_and_hits_window(tmp_path):
    b, _ = _backend_with_object(tmp_path, "drop", 1 << 20)
    c = RemoteBlockCache(max_bytes=8 << 20)
    c.get_block(b, "obj", 0, 0, 1 << 20)
    c.record_read(b.spec, "obj", now=100.0)
    c.record_read(b.spec, "obj", now=130.0)
    c.record_read(b.spec, "obj", now=159.0)
    assert c.hits_in_window(b.spec, "obj", 60.0, now=160.0) == 3
    assert c.hits_in_window(b.spec, "obj", 25.0, now=160.0) == 1
    assert c.hits_in_window(b.spec, "other", 60.0, now=160.0) == 0
    c.drop_file(b.spec, "obj")
    assert c.used_bytes() == 0
    assert c.hits_in_window(b.spec, "obj", 60.0, now=160.0) == 0
    _, hit = c.get_block(b, "obj", 0, 0, 1 << 20)
    assert not hit  # invalidated


# -- expiry decisions --------------------------------------------------------

def _ttl_needle(nid: int, ttl: str | None, written_at: int) -> Needle:
    n = Needle(id=nid, cookie=1, data=b"payload " * 8)
    if ttl:
        n.set_ttl(TTL.parse(ttl))
    n.set_last_modified(written_at)
    return n


def test_needle_expiry_per_needle_and_superblock():
    t0 = 1_000_000
    n = _ttl_needle(1, "1m", t0)
    assert not expiry.needle_expired(n, None, at=t0 + 59)
    assert expiry.needle_expired(n, None, at=t0 + 61)
    # Superblock TTL applies when the needle has none of its own.
    bare = _ttl_needle(2, None, t0)
    assert not expiry.needle_expired(bare, None, at=t0 + 10**9)
    assert expiry.needle_expired(bare, TTL.parse("1m"), at=t0 + 61)
    # Per-needle TTL wins over a longer superblock TTL.
    assert expiry.needle_expired(n, TTL.parse("1h"), at=t0 + 61)


def test_volume_expiry_and_near_expiry():
    ttl = TTL.parse("10m")
    t0 = 1_000_000.0
    assert not expiry.volume_expired(ttl, t0, at=t0 + 599)
    assert expiry.volume_expired(ttl, t0, at=t0 + 601)
    assert not expiry.volume_expired(ttl, t0, grace=60, at=t0 + 650)
    assert expiry.volume_expired(ttl, t0, grace=60, at=t0 + 661)
    assert not expiry.volume_expired(ttl, 0, at=t0)  # never written
    assert not expiry.volume_near_expiry(ttl, t0, at=t0 + 299)
    assert expiry.volume_near_expiry(ttl, t0, at=t0 + 301)
    assert not expiry.volume_near_expiry(TTL.parse(""), t0, at=t0 + 1e9)


def test_read_expired_needle_is_404_and_vacuum_reclaims(tmp_path):
    v = Volume(str(tmp_path), "", 11, ttl=TTL.parse("1m"),
               use_worker=False)
    now = int(time.time())
    for i in range(8):
        v.write_needle(_ttl_needle(i + 1, "1m", now))
    keeper = Needle(id=99, cookie=1, data=b"no ttl flag " * 4)
    keeper.set_last_modified(now)
    v.write_needle(keeper)
    assert v.read_needle(1).data == b"payload " * 8
    before_dat = v.dat_size()
    expiry.set_clock(lambda: now + 120.0)
    # Expired needle: 404 with an expiry reason, not data.
    with pytest.raises(NotFoundError, match="expired"):
        v.read_needle(1)
    # Vacuum treats expired needles as dead and reclaims the bytes.
    vacuum(v)
    assert v.vacuum_expired_count == 9  # superblock TTL covers id=99
    assert v.dat_size() < before_dat
    assert v.file_count() == 0
    v.close()


def test_vacuum_keeps_unexpired_ttl_needles(tmp_path):
    v = Volume(str(tmp_path), "", 12, use_worker=False)
    now = int(time.time())
    v.write_needle(_ttl_needle(1, "1m", now))       # will expire
    v.write_needle(_ttl_needle(2, "1h", now))       # still live
    expiry.set_clock(lambda: now + 120.0)
    vacuum(v)
    assert v.vacuum_expired_count == 1
    with pytest.raises(NotFoundError):
        v.read_needle(1)
    assert v.read_needle(2).data == b"payload " * 8
    v.close()


def test_layout_steers_writes_off_near_expiry_volumes():
    from seaweedfs_tpu.core.replica_placement import ReplicaPlacement
    from seaweedfs_tpu.storage.store import VolumeInfo
    from seaweedfs_tpu.topology.node import DataNode
    from seaweedfs_tpu.topology.volume_layout import VolumeLayout
    layout = VolumeLayout(ReplicaPlacement.parse("000"),
                          TTL.parse("10m"), 1 << 30)
    dn = DataNode("n1", "127.0.0.1", 8080)
    now = int(time.time())
    fresh = VolumeInfo(id=1, collection="c", size=0, file_count=0,
                       delete_count=0, deleted_byte_count=0,
                       read_only=False, replica_placement=0,
                       ttl=TTL.parse("10m").to_uint32(),
                       compact_revision=0, modified_at=now)
    layout.register_volume(fresh, dn)
    assert 1 in layout.writables
    # Past half the TTL since the newest write: no new assignments.
    stale = VolumeInfo(id=1, collection="c", size=0, file_count=0,
                       delete_count=0, deleted_byte_count=0,
                       read_only=False, replica_placement=0,
                       ttl=TTL.parse("10m").to_uint32(),
                       compact_revision=0, modified_at=now - 400)
    layout.register_volume(stale, dn)
    assert 1 not in layout.writables


# -- the lifecycle daemon + E2E acceptance -----------------------------------

@pytest.fixture(scope="module")
def lc_cluster(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("lifecycle")
    master = MasterServer(volume_size_limit_mb=16, meta_dir=str(tmp),
                          pulse_seconds=60)
    master.start()
    d = tmp / "vs0"
    d.mkdir()
    vs = VolumeServer(master.url(), [str(d)], pulse_seconds=60,
                      tier_promote_hits=3, tier_promote_window=60.0)
    vs.start()
    client = WeedClient(master.url())
    yield master, vs, client, tmp
    vs.stop()
    master.stop()


_COL_N = [0]


def _fresh_volume(cl, prefix: str, ttl: str = ""):
    master, vs, _client, _tmp = cl
    _COL_N[0] += 1
    col = f"{prefix}{_COL_N[0]}"
    q = f"&ttl={ttl}" if ttl else ""
    rpc.call(f"{master.url()}/vol/grow?count=1&collection={col}{q}",
             "POST")
    a = rpc.call(f"{master.url()}/dir/assign?collection={col}{q}")
    payload = f"{col} payload ".encode() * 64
    rpc.call(f"http://{a['url']}/{a['fid']}", "POST", payload)
    vs._send_heartbeat(full=True)  # the daemon reads heartbeat state
    return int(a["fid"].split(",")[0]), col, a["fid"], payload


def test_e2e_idle_tiering_cache_and_promotion(lc_cluster):
    """The acceptance path: an idle-rule daemon tiers a cold volume
    with zero read unavailability; a read burst makes hit-bytes beat
    miss-bytes on the re-reads; sustained hits auto-promote the volume
    back to local disk."""
    master, vs, client, tmp = lc_cluster
    vid, col, fid, payload = _fresh_volume(lc_cluster, "cold")
    policy = Policy([Rule(collection=col, action="tier",
                          dest=f"local://{tmp}/cold-tier",
                          idle_for=0.05)])
    daemon = LifecycleDaemon(master, policy, interval=3600, mbps=0)
    # Scan 1 only observes: an idle decision needs a read baseline.
    out = daemon.scan_once()
    assert out["tiered"] == [] and out["errors"] == []
    time.sleep(0.3)  # idle_for elapses with no reads and no writes
    out = daemon.scan_once()
    assert out["tiered"] == [vid], out
    assert daemon.status()["actions"]["tier_ok"] >= 1
    v = vs.store.find_volume(vid)
    assert v.remote_file is not None and v.readonly
    assert not os.path.exists(v.file_name() + ".dat")

    # Zero read unavailability + cache accounting: pass 1 misses, the
    # re-read passes are served from cache, so hit bytes pull ahead.
    s0 = CACHE.stats()
    for _ in range(3):
        assert client.download(fid) == payload
    s1 = CACHE.stats()
    hit_d = s1["hit_bytes"] - s0["hit_bytes"]
    miss_d = s1["miss_bytes"] - s0["miss_bytes"]
    assert miss_d > 0 and hit_d > miss_d, (hit_d, miss_d)

    # 3 reads inside the window >= tier_promote_hits: the holder's
    # lifecycle tick schedules the download back to local.
    assert CACHE.hits_in_window(v.remote_file.backend.spec,
                                v.remote_file.key, 60.0) >= 3
    vs._lifecycle_tick()
    deadline = time.time() + 15
    while vs.store.find_volume(vid).remote_file is not None:
        assert time.time() < deadline, "promotion never completed"
        time.sleep(0.05)
    v = vs.store.find_volume(vid)
    assert os.path.exists(v.file_name() + ".dat")
    assert not os.path.exists(v.file_name() + ".vif")
    assert client.download(fid) == payload  # local again, same bytes


def test_e2e_ttl_expiry_vacuum_and_volume_retirement(lc_cluster):
    """Short-TTL acceptance: expired needles 404 with an expiry reason,
    the daemon's expire rule vacuums the bytes away, and once the whole
    volume is past TTL + grace the holder retires it entirely."""
    master, vs, client, _tmp = lc_cluster
    vid, col, fid, payload = _fresh_volume(lc_cluster, "scratch",
                                           ttl="1m")
    assert client.download(fid) == payload  # live before expiry
    v = vs.store.find_volume(vid)
    assert v.super_block.ttl.minutes() == 1
    before_dat = v.dat_size()

    base_now = time.time()
    expiry.set_clock(lambda: base_now + 90.0)  # past the 60s TTL
    try:
        with pytest.raises(rpc.RpcError) as ei:
            client.download(fid)
        assert ei.value.status == 404
        # The expire rule drives vacuum; the bytes physically vanish.
        daemon = LifecycleDaemon(
            master, Policy([Rule(collection=col, action="expire")]),
            interval=3600)
        out = daemon.scan_once()
        assert vid in out["vacuumed"], out
        assert vs.store.find_volume(vid).dat_size() < before_dat
        assert vs.store.find_volume(vid).file_count() == 0
        # Fully past TTL + grace: the sweeper deletes the volume whole.
        expiry.set_clock(lambda: base_now + 600.0)
        vs._lifecycle_tick()
        assert vs.store.find_volume(vid) is None
    finally:
        expiry.reset_clock()


def test_daemon_requires_single_holder_and_skips_tiered():
    """_consider must refuse to tier replicated volumes (the remote
    object would be shared state under two holders) and never re-tier
    an already-tiered one."""

    class VInfo:
        collection = "c"
        tiered = False
        ttl = 0
        modified_at = 1.0
        size = 100

    class DN:
        def url(self):
            return "127.0.0.1:1"

    class Topo:
        volume_size_limit = 1000

        def leaves(self):
            return []

    class M:
        topo = Topo()

        def is_leader(self):
            return True

    daemon = LifecycleDaemon(
        M(), Policy([Rule(collection="*", action="tier",
                          dest="local:///t", min_age=0.0001)]),
        interval=3600)
    tiered = []
    daemon._tier_one = lambda dn, vid, vinfo, rule, out: tiered.append(
        vid)
    out = {"tiered": [], "vacuumed": [], "errors": []}
    dn = DN()
    # Two holders: refused.
    daemon._consider(dn, 1, VInfo(), {1: [dn, dn]}, None, None, out)
    assert tiered == []
    # Single holder: tiered.
    daemon._consider(dn, 1, VInfo(), {1: [dn]}, None, None, out)
    assert tiered == [1]
    # Already tiered: skipped.
    vi = VInfo()
    vi.tiered = True
    daemon._consider(dn, 2, vi, {2: [dn]}, None, None, out)
    assert tiered == [1]


def test_daemon_unreachable_holder_degrades_scan_not_master():
    """A dead holder costs the scan an error entry; the daemon keeps
    going and the error is visible in status()."""

    class DN:
        def __init__(self):
            self.volumes = {}

        def url(self):
            return "127.0.0.1:1"  # nothing listens here

    class Topo:
        volume_size_limit = 1000

        def __init__(self, dn):
            self._dn = dn

        def leaves(self):
            return [self._dn]

    class M:
        def __init__(self, dn):
            self.topo = Topo(dn)

        def is_leader(self):
            return True

    dn = DN()
    daemon = LifecycleDaemon(
        M(dn), Policy([Rule(collection="*", action="tier",
                            dest="local:///t", min_age=0.0001)]),
        interval=3600)
    daemon._policy_retry.max_attempts = 1
    class VInfo:
        collection = ""
        tiered = False
        ttl = 0
        modified_at = 1.0
        size = 10
    dn.volumes = {5: VInfo()}
    out = daemon.scan_once()
    assert out["tiered"] == []
    assert out["errors"] and out["errors"][0]["volume"] == 5
    assert daemon.status()["actions"]["tier_error"] == 1


# -- kill -9 crash windows ---------------------------------------------------

def _make_local_volume(dir_: str, vid: int, n: int = 30) -> bytes:
    v = Volume(dir_, "", vid, use_worker=False)
    for i in range(n):
        v.write_needle(Needle(id=i + 1, cookie=7,
                              data=f"needle-{i} ".encode() * 40))
    v.sync()
    v.close()
    return b""


def _run_child(script: str, tmp_path, *args) -> int:
    path = tmp_path / "child.py"
    path.write_text(textwrap.dedent(script))
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=os.path.dirname(os.path.dirname(
                   os.path.abspath(__file__))))
    proc = subprocess.run(
        [sys.executable, str(path), *map(str, args)],
        capture_output=True, timeout=120, env=env)
    return proc.returncode


def test_kill9_during_tier_upload_leaves_volume_fully_local(tmp_path):
    """SIGKILL mid-upload: the remote object is torn, but no .vif was
    published — on remount the volume is fully local and readable, and
    a re-run of the tier upload succeeds over the leftover object."""
    data_dir = tmp_path / "data"
    data_dir.mkdir()
    remote_dir = tmp_path / "remote"
    _make_local_volume(str(data_dir), 21)
    rc = _run_child("""\
        import os, signal, sys
        from seaweedfs_tpu.storage.backend import LocalDirBackend
        from seaweedfs_tpu.storage import tier
        from seaweedfs_tpu.storage.volume import Volume

        data_dir, remote_dir = sys.argv[1], sys.argv[2]

        def half_then_die(self, key, path):
            data = open(path, "rb").read()
            with open(self._p(key), "wb") as f:
                f.write(data[: len(data) // 2])
                f.flush()
                os.fsync(f.fileno())
            os.kill(os.getpid(), signal.SIGKILL)

        LocalDirBackend.upload_file = half_then_die
        v = Volume(data_dir, "", 21, create=False, use_worker=False)
        v.set_readonly()
        tier.move_dat_to_remote(v, "local://" + remote_dir)
    """, tmp_path, data_dir, remote_dir)
    assert rc == -signal.SIGKILL
    # The torn half-object exists remotely, but nothing points at it.
    assert os.path.exists(remote_dir / "21.dat")
    assert not os.path.exists(data_dir / "21.vif")
    assert os.path.exists(data_dir / "21.dat")
    from seaweedfs_tpu.storage.store import Store
    store = Store([str(data_dir)])
    try:
        v = store.find_volume(21)
        assert v is not None and v.remote_file is None  # fully local
        assert v.read_needle(3).data == b"needle-2 " * 40
        # Re-tiering over the leftover partial object succeeds.
        v.set_readonly()
        move_dat_to_remote(v, f"local://{remote_dir}")
        assert v.read_needle(3).data == b"needle-2 " * 40
    finally:
        store.close()


def test_kill9_during_tier_download_leaves_volume_fully_remote(
        tmp_path):
    """SIGKILL mid-download: the temp download dies with the process —
    on remount the .vif still rules, the volume is fully remote and
    readable, and no torn .dat shadows the intact remote copy."""
    data_dir = tmp_path / "data"
    data_dir.mkdir()
    remote_dir = tmp_path / "remote"
    _make_local_volume(str(data_dir), 22)
    v = Volume(str(data_dir), "", 22, create=False, use_worker=False)
    v.set_readonly()
    move_dat_to_remote(v, f"local://{remote_dir}")
    v.close()
    rc = _run_child("""\
        import os, signal, sys
        from seaweedfs_tpu.storage.backend import LocalDirBackend
        from seaweedfs_tpu.storage import tier

        data_dir = sys.argv[1]

        def half_then_die(self, key, path):
            data = open(self._p(key), "rb").read()
            with open(path, "wb") as f:
                f.write(data[: len(data) // 2])
                f.flush()
                os.fsync(f.fileno())
            os.kill(os.getpid(), signal.SIGKILL)

        LocalDirBackend.download_file = half_then_die
        v = tier.open_remote_volume(data_dir, "", 22)
        tier.move_dat_from_remote(v)
    """, tmp_path, data_dir)
    assert rc == -signal.SIGKILL
    assert not os.path.exists(data_dir / "22.dat")  # torn temp != .dat
    assert os.path.exists(data_dir / "22.vif")
    from seaweedfs_tpu.storage.store import Store
    store = Store([str(data_dir)])
    try:
        v = store.find_volume(22)
        assert v is not None and v.remote_file is not None
        assert v.read_needle(5).data == b"needle-4 " * 40
    finally:
        store.close()


def test_truncated_download_never_replaces_dat(tmp_path):
    """A download that comes back short (fault, not crash) must raise
    and leave the volume remote — never swap a torn .dat live."""
    data_dir = tmp_path / "data"
    data_dir.mkdir()
    remote_dir = tmp_path / "remote"
    _make_local_volume(str(data_dir), 23)
    v = Volume(str(data_dir), "", 23, create=False, use_worker=False)
    v.set_readonly()
    move_dat_to_remote(v, f"local://{remote_dir}")

    real = LocalDirBackend.download_file

    def short(self, key, path):
        real(self, key, path)
        with open(path, "r+b") as f:
            f.truncate(100)
        return 100

    LocalDirBackend.download_file = short
    try:
        from seaweedfs_tpu.storage.tier import move_dat_from_remote
        with pytest.raises(VolumeError, match="got 100 bytes"):
            move_dat_from_remote(v)
    finally:
        LocalDirBackend.download_file = real
    assert not os.path.exists(data_dir / "23.dat")
    assert not os.path.exists(data_dir / "23.dat.tmpdl")
    assert v.remote_file is not None
    assert v.read_needle(2).data == b"needle-1 " * 40
    v.close()


def test_scrub_skips_tiered_volumes(tmp_path):
    """The backend owns a tiered volume's integrity: scrub must not
    ranged-GET the whole .dat back over the WAN every sweep."""
    from seaweedfs_tpu.storage.scrub import ScrubDaemon
    from seaweedfs_tpu.storage.store import Store
    data_dir = tmp_path / "data"
    data_dir.mkdir()
    _make_local_volume(str(data_dir), 24, n=5)
    _make_local_volume(str(data_dir), 25, n=5)
    store = Store([str(data_dir)])
    try:
        v = store.find_volume(24)
        v.set_readonly()
        move_dat_to_remote(v, f"local://{tmp_path}/remote")
        reads = [0]
        real = LocalDirBackend.read_range

        def counting(self, key, offset, size):
            reads[0] += 1
            return real(self, key, offset, size)

        LocalDirBackend.read_range = counting
        try:
            out = ScrubDaemon(store, ec_volumes={}).scrub_all()
        finally:
            LocalDirBackend.read_range = real
        assert reads[0] == 0, "scrub fetched remote bytes"
        scanned = [r["id"] for r in out["volumes"]]
        assert 25 in scanned and 24 not in scanned
    finally:
        store.close()


def test_open_remote_volume_mounts_without_dat(tmp_path):
    """Startup with only .idx + .vif on disk (the .dat lives remotely):
    the volume mounts remote-backed and serves reads; modified_at rides
    the .vif so TTL decisions survive the round trip."""
    data_dir = tmp_path / "data"
    data_dir.mkdir()
    _make_local_volume(str(data_dir), 26, n=6)
    v = Volume(str(data_dir), "", 26, create=False, use_worker=False)
    v.set_readonly()
    before = int(v.modified_at)
    move_dat_to_remote(v, f"local://{tmp_path}/remote")
    v.close()
    assert not os.path.exists(data_dir / "26.dat")
    v2 = open_remote_volume(str(data_dir), "", 26)
    try:
        assert v2.readonly and v2.remote_file is not None
        assert v2.read_needle(6).data == b"needle-5 " * 40
        assert int(v2.modified_at) == before
        assert load_vif(v2.file_name())["files"][0]["modified_at"] == \
            before
    finally:
        v2.close()


def test_shell_verbs_and_metrics_exposition(lc_cluster):
    """`cluster.lifecycle` / `volume.tier.status` render live state,
    `cluster.lifecycle run` drives a synchronous scan, and the tier
    instruments ride the volume server's /metrics scrape."""
    from seaweedfs_tpu.shell import CommandEnv, run_command
    master, vs, _client, _tmp = lc_cluster
    _fresh_volume(lc_cluster, "shellcol")
    env = CommandEnv(master.url())
    out = run_command(env, "cluster.lifecycle")
    assert "enabled" in out and "rules" in out
    out = run_command(env, "cluster.lifecycle run")
    assert "scan complete" in out
    out = run_command(env, "volume.tier.status")
    assert "NODE" in out and "VOL" in out
    assert vs.url() in out
    assert "cache @" in out
    body = rpc.call(f"http://{vs.url()}/metrics")
    text = body.decode() if isinstance(body, bytes) else str(body)
    for name in ("SeaweedFS_tier_cache_hit_bytes_total",
                 "SeaweedFS_tier_cache_miss_bytes_total",
                 "SeaweedFS_tier_moved_bytes_total",
                 "SeaweedFS_ttl_expired_bytes_total",
                 "SeaweedFS_lifecycle_actions_total"):
        assert name in text, f"{name} missing from /metrics"
