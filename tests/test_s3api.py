"""S3 gateway: protocol, auth, multipart, listings.

Mirrors the reference's s3api tests (auto_signature_v4_test.go,
auth_credentials_test.go) plus integration-style object tests
(test/s3/basic) against the live filer+volume+master stack.
"""

import hashlib
import json
import time
import urllib.error
import urllib.parse
import urllib.request
import xml.etree.ElementTree as ET

import pytest

from seaweedfs_tpu.cluster.master import MasterServer
from seaweedfs_tpu.cluster.volume_server import VolumeServer
from seaweedfs_tpu.filer.server import FilerServer
from seaweedfs_tpu.s3api import Identity, S3ApiServer
from seaweedfs_tpu.s3api.auth import compute_signature_v4

ACCESS, SECRET = "AKIDEXAMPLE", "wJalrXUtnFEMI/K7MDENG/bPxRkfiEXAMPLE"
RO_ACCESS, RO_SECRET = "READONLYKEY", "readonlysecret"


def test_sigv4_canonical_request_matches_aws_doc_example():
    """The canonical request for the worked GET-object example in AWS's
    SigV4 documentation (examplebucket, 2013-05-24) must hash to the
    documented value — this pins header canonicalization, URI encoding,
    and the blank-line layout exactly."""
    empty_hash = hashlib.sha256(b"").hexdigest()
    headers = {
        "host": "examplebucket.s3.amazonaws.com",
        "range": "bytes=0-9",
        "x-amz-content-sha256": empty_hash,
        "x-amz-date": "20130524T000000Z",
    }
    signed = ["host", "range", "x-amz-content-sha256", "x-amz-date"]
    from seaweedfs_tpu.s3api.auth import canonical_query, canonical_uri
    canon_headers = "".join(
        f"{h}:{' '.join(headers[h].split())}\n" for h in signed)
    cr = "\n".join(["GET", canonical_uri("/test.txt"),
                    canonical_query(""), canon_headers,
                    ";".join(signed), empty_hash])
    assert hashlib.sha256(cr.encode()).hexdigest() == (
        "7344ae5b7ee6c3e7e6b0fe0640412a37625d1fbfff95c48bbb2dc43964946972")


def test_sigv4_key_derivation_chain():
    """derive_signing_key must be the published 4-step HMAC cascade,
    checked against an independent step-by-step computation."""
    import hmac as hmac_mod

    from seaweedfs_tpu.s3api.auth import derive_signing_key

    def step(key, msg):
        return hmac_mod.new(key, msg.encode(), hashlib.sha256).digest()

    secret, date, region, service = "topsecret", "20250101", "us-west-2", "s3"
    expect = step(step(step(step(("AWS4" + secret).encode(), date),
                            region), service), "aws4_request")
    assert derive_signing_key(secret, date, region, service) == expect


def test_sigv4_signature_detects_tampering():
    """Any mutation of method/path/query/headers/payload/secret changes
    the signature (the property the verifier relies on)."""
    base = dict(
        method="GET", path="/test.txt", raw_query="a=1&b=2",
        headers={"host": "h", "x-amz-date": "20250101T000000Z"},
        signed_headers=["host", "x-amz-date"],
        payload_hash=hashlib.sha256(b"body").hexdigest(),
        amz_date="20250101T000000Z",
        scope="20250101/us-east-1/s3/aws4_request",
        secret_key="s3cr3t")
    ref = compute_signature_v4(**base)
    assert compute_signature_v4(**base) == ref  # deterministic
    for field, val in [("method", "PUT"), ("path", "/test2.txt"),
                      ("raw_query", "a=1&b=3"),
                      ("payload_hash", hashlib.sha256(b"x").hexdigest()),
                      ("secret_key", "other")]:
        mutated = {**base, field: val}
        assert compute_signature_v4(**mutated) != ref, field


class S3Client:
    """Minimal sig-v4-signing S3 client for tests."""

    def __init__(self, endpoint, access="", secret=""):
        self.endpoint = endpoint.rstrip("/")
        self.access, self.secret = access, secret
        self.host = endpoint.split("//", 1)[1]

    def request(self, method, path, query="", body=b"", headers=None):
        headers = dict(headers or {})
        url = f"{self.endpoint}{urllib.parse.quote(path)}"
        if query:
            url += f"?{query}"
        if self.access:
            now = time.gmtime()
            amz_date = time.strftime("%Y%m%dT%H%M%SZ", now)
            date = time.strftime("%Y%m%d", now)
            scope = f"{date}/us-east-1/s3/aws4_request"
            payload_hash = hashlib.sha256(body).hexdigest()
            headers["host"] = self.host
            headers["x-amz-date"] = amz_date
            headers["x-amz-content-sha256"] = payload_hash
            signed = sorted(k.lower() for k in headers)
            sig = compute_signature_v4(
                method, path, query, {k.lower(): v
                                      for k, v in headers.items()},
                signed, payload_hash, amz_date, scope, self.secret)
            headers["Authorization"] = (
                "AWS4-HMAC-SHA256 "
                f"Credential={self.access}/{scope},"
                f"SignedHeaders={';'.join(signed)},Signature={sig}")
        req = urllib.request.Request(url, data=body or None,
                                     method=method, headers=headers)
        return urllib.request.urlopen(req, timeout=30)

    def xml(self, method, path, query="", body=b"", headers=None):
        with self.request(method, path, query, body, headers) as r:
            return ET.fromstring(r.read())


def _strip_ns(root):
    for el in root.iter():
        el.tag = el.tag.split("}", 1)[-1]
    return root


@pytest.fixture(scope="module")
def stack(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("s3-stack")
    master = MasterServer(volume_size_limit_mb=64, meta_dir=str(tmp))
    master.start()
    vs = VolumeServer(master.url(), [str(tmp / "vs")], pulse_seconds=60)
    vs.start()
    filer = FilerServer(master.url(), chunk_size=256)
    filer.start()
    s3 = S3ApiServer(filer.url(), identities=[
        Identity("admin", ACCESS, SECRET, ["Admin"]),
        Identity("reader", RO_ACCESS, RO_SECRET, ["Read", "List"]),
    ])
    s3.start()
    client = S3Client(s3.url(), ACCESS, SECRET)
    yield master, vs, filer, s3, client
    s3.stop()
    filer.stop()
    vs.stop()
    master.stop()


def test_bucket_lifecycle(stack):
    *_rest, client = stack
    client.request("PUT", "/lifebucket").read()
    root = _strip_ns(client.xml("GET", "/"))
    names = [b.findtext("Name") for b in root.iter("Bucket")]
    assert "lifebucket" in names
    client.request("HEAD", "/lifebucket").read()
    with client.request("DELETE", "/lifebucket") as r:
        assert r.status == 204
    with pytest.raises(urllib.error.HTTPError) as ei:
        client.request("HEAD", "/nonexistent-bucket")
    assert ei.value.code == 404


def test_object_crud_and_range(stack):
    *_rest, client = stack
    client.request("PUT", "/objbucket").read()
    body = b"0123456789" * 100  # 1000B -> 4 chunks of 256
    with client.request("PUT", "/objbucket/dir/key.bin", body=body) as r:
        etag = r.headers["ETag"]
    # PUT's ETag must match what GET/HEAD serve afterwards (sync
    # clients use it for change detection).
    with client.request("HEAD", "/objbucket/dir/key.bin") as r:
        assert r.headers["ETag"] == etag
    with client.request("GET", "/objbucket/dir/key.bin") as r:
        assert r.read() == body
    with client.request("GET", "/objbucket/dir/key.bin",
                        headers={"Range": "bytes=10-19"}) as r:
        assert r.status == 206
        assert r.read() == body[10:20]
    with client.request("DELETE", "/objbucket/dir/key.bin") as r:
        assert r.status == 204
    with pytest.raises(urllib.error.HTTPError) as ei:
        client.request("GET", "/objbucket/dir/key.bin")
    assert ei.value.code == 404


def test_copy_object(stack):
    *_rest, client = stack
    client.request("PUT", "/copybucket").read()
    client.request("PUT", "/copybucket/src.txt", body=b"copy-me").read()
    client.xml("PUT", "/copybucket/dst.txt",
               headers={"x-amz-copy-source": "/copybucket/src.txt"})
    with client.request("GET", "/copybucket/dst.txt") as r:
        assert r.read() == b"copy-me"
    # deleting the copy must not corrupt the source
    client.request("DELETE", "/copybucket/dst.txt").read()
    with client.request("GET", "/copybucket/src.txt") as r:
        assert r.read() == b"copy-me"


def test_list_objects_v2_prefix_delimiter(stack):
    *_rest, client = stack
    client.request("PUT", "/listbucket").read()
    for key in ("a/one.txt", "a/two.txt", "a/sub/three.txt", "b/four.txt",
                "top.txt"):
        client.request("PUT", f"/listbucket/{key}", body=b"x").read()
    root = _strip_ns(client.xml("GET", "/listbucket",
                                "list-type=2"))
    keys = [c.findtext("Key") for c in root.iter("Contents")]
    assert keys == ["a/one.txt", "a/sub/three.txt", "a/two.txt",
                    "b/four.txt", "top.txt"]
    # prefix
    root = _strip_ns(client.xml("GET", "/listbucket",
                                "list-type=2&prefix=a%2F"))
    keys = [c.findtext("Key") for c in root.iter("Contents")]
    assert keys == ["a/one.txt", "a/sub/three.txt", "a/two.txt"]
    # delimiter groups common prefixes
    root = _strip_ns(client.xml("GET", "/listbucket",
                                "list-type=2&delimiter=%2F"))
    keys = [c.findtext("Key") for c in root.iter("Contents")]
    prefixes = [p.findtext("Prefix")
                for p in root.iter("CommonPrefixes")]
    assert keys == ["top.txt"]
    assert prefixes == ["a/", "b/"]
    # pagination
    root = _strip_ns(client.xml("GET", "/listbucket",
                                "list-type=2&max-keys=2"))
    assert root.findtext("IsTruncated") == "true"
    token = root.findtext("NextContinuationToken")
    root = _strip_ns(client.xml(
        "GET", "/listbucket",
        "list-type=2&max-keys=10&continuation-token="
        + urllib.parse.quote(token)))
    keys2 = [c.findtext("Key") for c in root.iter("Contents")]
    assert keys2 == ["a/two.txt", "b/four.txt", "top.txt"]


def test_multipart_upload(stack):
    *_rest, filer, _s3, client = stack[2], stack[3], stack[4]
    client.request("PUT", "/mpbucket").read()
    root = _strip_ns(client.xml("POST", "/mpbucket/assembled.bin",
                                "uploads",
                                headers={"Content-Type": "video/mp4"}))
    upload_id = root.findtext("UploadId")
    assert upload_id
    parts = [b"A" * 600, b"B" * 600, b"C" * 100]
    for i, data in enumerate(parts, start=1):
        client.request("PUT", "/mpbucket/assembled.bin",
                       f"partNumber={i}&uploadId={upload_id}",
                       body=data).read()
    complete = b"<CompleteMultipartUpload></CompleteMultipartUpload>"
    client.xml("POST", "/mpbucket/assembled.bin",
               f"uploadId={upload_id}", body=complete)
    with client.request("GET", "/mpbucket/assembled.bin") as r:
        assert r.read() == b"".join(parts)
        assert r.headers["Content-Type"] == "video/mp4"
    # parts metadata cleaned up; chunks still alive (just read them)
    filer_srv = filer
    filer_srv.filer.flush_deletions()
    with client.request("GET", "/mpbucket/assembled.bin") as r:
        assert r.read() == b"".join(parts)


def test_multipart_complete_respects_part_list(stack):
    *_rest, client = stack
    client.request("PUT", "/plistbucket").read()
    root = _strip_ns(client.xml("POST", "/plistbucket/sel.bin", "uploads"))
    uid = root.findtext("UploadId")
    for i, data in [(1, b"one"), (2, b"two"), (3, b"three")]:
        client.request("PUT", "/plistbucket/sel.bin",
                       f"partNumber={i}&uploadId={uid}", body=data).read()
    # Complete with only parts 1 and 2: part 3 must be excluded.
    body = (b"<CompleteMultipartUpload>"
            b"<Part><PartNumber>1</PartNumber></Part>"
            b"<Part><PartNumber>2</PartNumber></Part>"
            b"</CompleteMultipartUpload>")
    client.xml("POST", "/plistbucket/sel.bin", f"uploadId={uid}",
               body=body)
    with client.request("GET", "/plistbucket/sel.bin") as r:
        assert r.read() == b"onetwo"


def test_multipart_complete_empty_fails(stack):
    *_rest, client = stack
    client.request("PUT", "/emptybucket").read()
    root = _strip_ns(client.xml("POST", "/emptybucket/none.bin",
                                "uploads"))
    uid = root.findtext("UploadId")
    with pytest.raises(urllib.error.HTTPError) as ei:
        client.request("POST", "/emptybucket/none.bin",
                       f"uploadId={uid}",
                       body=b"<CompleteMultipartUpload/>")
    assert ei.value.code == 400


def test_aws_chunked_decode():
    import io

    from seaweedfs_tpu.s3api.server import _AwsChunkedReader
    framed = (b"5;chunk-signature=abc\r\nhello\r\n"
              b"7;chunk-signature=def\r\n world!\r\n"
              b"0;chunk-signature=end\r\n\r\n")
    r = _AwsChunkedReader(io.BytesIO(framed), 12)
    assert r.read() == b"hello world!"
    # Malformed/unframed input must error, never 200 as a silently
    # truncated or mis-stored object.
    bad = _AwsChunkedReader(io.BytesIO(b"not-chunked-at-all"), None)
    with pytest.raises(ConnectionError):
        bad.read()
    torn = _AwsChunkedReader(io.BytesIO(b"5;sig=x\r\nhel"), None)
    with pytest.raises(ConnectionError):
        torn.read()


def test_head_object_content_length(stack):
    *_rest, client = stack
    client.request("PUT", "/headbucket").read()
    client.request("PUT", "/headbucket/obj", body=b"Q" * 777).read()
    with client.request("HEAD", "/headbucket/obj") as r:
        assert r.headers["Content-Length"] == "777"
        assert r.read() == b""


def test_delete_bucket_clears_pending_uploads(stack):
    *_rest, client = stack
    client.request("PUT", "/pendbucket").read()
    root = _strip_ns(client.xml("POST", "/pendbucket/dangling", "uploads"))
    client.request("PUT", "/pendbucket/dangling",
                   f"partNumber=1&uploadId={root.findtext('UploadId')}",
                   body=b"p").read()
    client.request("DELETE", "/pendbucket").read()
    client.request("PUT", "/pendbucket").read()
    uploads = _strip_ns(client.xml("GET", "/pendbucket", "uploads"))
    assert list(uploads.iter("Upload")) == []
    client.request("DELETE", "/pendbucket").read()


def test_multipart_abort(stack):
    *_rest, client = stack
    client.request("PUT", "/abortbucket").read()
    root = _strip_ns(client.xml("POST", "/abortbucket/x.bin", "uploads"))
    upload_id = root.findtext("UploadId")
    client.request("PUT", "/abortbucket/x.bin",
                   f"partNumber=1&uploadId={upload_id}",
                   body=b"zzz").read()
    with client.request("DELETE", "/abortbucket/x.bin",
                        f"uploadId={upload_id}") as r:
        assert r.status == 204
    with pytest.raises(urllib.error.HTTPError):
        client.request("GET", "/abortbucket/x.bin")


def test_delete_multiple(stack):
    *_rest, client = stack
    client.request("PUT", "/multibucket").read()
    for k in ("k1", "k2", "k3"):
        client.request("PUT", f"/multibucket/{k}", body=b"d").read()
    body = (b"<Delete><Object><Key>k1</Key></Object>"
            b"<Object><Key>k3</Key></Object></Delete>")
    root = _strip_ns(client.xml("POST", "/multibucket", "delete",
                                body=body))
    deleted = [d.findtext("Key") for d in root.iter("Deleted")]
    assert sorted(deleted) == ["k1", "k3"]
    root = _strip_ns(client.xml("GET", "/multibucket", "list-type=2"))
    keys = [c.findtext("Key") for c in root.iter("Contents")]
    assert keys == ["k2"]


def test_tagging(stack):
    *_rest, client = stack
    client.request("PUT", "/tagbucket").read()
    client.request("PUT", "/tagbucket/obj", body=b"t").read()
    tags = (b"<Tagging><TagSet><Tag><Key>env</Key>"
            b"<Value>prod</Value></Tag></TagSet></Tagging>")
    client.request("PUT", "/tagbucket/obj", "tagging", body=tags).read()
    root = _strip_ns(client.xml("GET", "/tagbucket/obj", "tagging"))
    got = {t.findtext("Key"): t.findtext("Value")
           for t in root.iter("Tag")}
    assert got == {"env": "prod"}
    client.request("DELETE", "/tagbucket/obj", "tagging").read()
    root = _strip_ns(client.xml("GET", "/tagbucket/obj", "tagging"))
    assert list(root.iter("Tag")) == []
    # object data untouched by tagging ops
    with client.request("GET", "/tagbucket/obj") as r:
        assert r.read() == b"t"


def test_auth_rejections(stack):
    _m, _vs, _f, s3, admin = stack
    # bad secret
    bad = S3Client(s3.url(), ACCESS, "wrong-secret")
    with pytest.raises(urllib.error.HTTPError) as ei:
        bad.request("GET", "/")
    assert ei.value.code == 403
    # unknown key
    unknown = S3Client(s3.url(), "NOSUCHKEY", "x")
    with pytest.raises(urllib.error.HTTPError) as ei:
        unknown.request("GET", "/")
    assert ei.value.code == 403
    # no auth header at all
    anon = S3Client(s3.url())
    with pytest.raises(urllib.error.HTTPError) as ei:
        anon.request("GET", "/")
    assert ei.value.code == 403


def test_readonly_identity(stack):
    _m, _vs, _f, s3, admin = stack
    admin.request("PUT", "/robucket").read()
    admin.request("PUT", "/robucket/data", body=b"ro").read()
    ro = S3Client(s3.url(), RO_ACCESS, RO_SECRET)
    with ro.request("GET", "/robucket/data") as r:
        assert r.read() == b"ro"
    root = _strip_ns(ro.xml("GET", "/robucket", "list-type=2"))
    assert [c.findtext("Key") for c in root.iter("Contents")] == ["data"]
    with pytest.raises(urllib.error.HTTPError) as ei:
        ro.request("PUT", "/robucket/new", body=b"nope")
    assert ei.value.code == 403
    with pytest.raises(urllib.error.HTTPError) as ei:
        ro.request("DELETE", "/robucket/data")
    assert ei.value.code == 403


# -- regression tests for review findings ------------------------------------

def test_copy_requires_read_on_source_bucket(stack):
    """Write on the destination must not grant read of the source
    (s3api_object_copy_handlers.go checks both ends)."""
    *_rest, s3, client = stack[:4] + (stack[4],)
    scoped_id = Identity("scoped", "SCOPEDKEY", "scopedsecret",
                         ["Read:mine", "Write:mine", "List"])
    s3.iam.identities[scoped_id.access_key] = scoped_id
    client.request("PUT", "/privatebkt").read()
    client.request("PUT", "/mine").read()
    client.request("PUT", "/privatebkt/secret.txt", body=b"top secret").read()
    scoped = S3Client(s3.url(), "SCOPEDKEY", "scopedsecret")
    with pytest.raises(urllib.error.HTTPError) as ei:
        scoped.request("PUT", "/mine/stolen",
                       headers={"x-amz-copy-source": "/privatebkt/secret.txt"})
    assert ei.value.code == 403
    # and with read rights on the source it succeeds
    client.request("PUT", "/mine/ok",
                   headers={"x-amz-copy-source": "/privatebkt/secret.txt"}
                   ).read()
    with client.request("GET", "/mine/ok") as r:
        assert r.read() == b"top secret"


def test_list_pagination_dot_vs_slash_order(stack):
    """'a.txt' sorts before 'a/x' in S3 key order ('.' < '/'); paginated
    listing must not skip either."""
    *_rest, client = stack
    client.request("PUT", "/orderbkt").read()
    client.request("PUT", "/orderbkt/a.txt", body=b"1").read()
    client.request("PUT", "/orderbkt/a/x", body=b"2").read()
    client.request("PUT", "/orderbkt/b.txt", body=b"3").read()
    keys, token = [], ""
    for _ in range(10):
        q = "list-type=2&max-keys=1"
        if token:
            q += f"&continuation-token={urllib.parse.quote(token)}"
        root = _strip_ns(client.xml("GET", "/orderbkt", query=q))
        keys += [c.findtext("Key") for c in root.iter("Contents")]
        if root.findtext("IsTruncated") != "true":
            break
        token = root.findtext("NextContinuationToken")
    assert keys == ["a.txt", "a/x", "b.txt"]


def test_upload_part_unknown_upload_id(stack):
    *_rest, client = stack
    client.request("PUT", "/mpbkt").read()
    with pytest.raises(urllib.error.HTTPError) as ei:
        client.request("PUT", "/mpbkt/obj",
                       query="partNumber=1&uploadId=deadbeef", body=b"x")
    assert ei.value.code == 404


def test_get_and_head_carry_etag(stack):
    *_rest, client = stack
    client.request("PUT", "/etagbkt").read()
    client.request("PUT", "/etagbkt/f.bin", body=b"etag me").read()
    with client.request("GET", "/etagbkt/f.bin") as r:
        get_etag = r.headers.get("ETag")
    with client.request("HEAD", "/etagbkt/f.bin") as r:
        head_etag = r.headers.get("ETag")
    assert get_etag and get_etag == head_etag


def test_bucket_name_validation(stack):
    *_rest, client = stack
    for bad in ("/.uploads", "/UPPER", "/ab", "/-bad", "/bad-"):
        with pytest.raises(urllib.error.HTTPError) as ei:
            client.request("PUT", bad)
        assert ei.value.code == 400, bad
    client.request("PUT", "/valid-name.ok").read()


def test_sigv4_replay_window(stack):
    """Requests with an x-amz-date outside +/-15min are rejected
    (RequestTimeTooSkewed, like the reference's auth window)."""
    *_rest, s3, client = stack[:4] + (stack[4],)

    class StaleClient(S3Client):
        def request(self, method, path, query="", body=b"",
                    headers=None):
            headers = dict(headers or {})
            stale = time.gmtime(time.time() - 3600)
            amz_date = time.strftime("%Y%m%dT%H%M%SZ", stale)
            date = time.strftime("%Y%m%d", stale)
            scope = f"{date}/us-east-1/s3/aws4_request"
            payload_hash = hashlib.sha256(body).hexdigest()
            headers["host"] = self.host
            headers["x-amz-date"] = amz_date
            headers["x-amz-content-sha256"] = payload_hash
            signed = sorted(k.lower() for k in headers)
            sig = compute_signature_v4(
                method, path, query,
                {k.lower(): v for k, v in headers.items()}, signed,
                payload_hash, amz_date, scope, self.secret)
            headers["Authorization"] = (
                "AWS4-HMAC-SHA256 "
                f"Credential={self.access}/{scope},"
                f"SignedHeaders={';'.join(signed)},Signature={sig}")
            req = urllib.request.Request(
                f"{self.endpoint}{urllib.parse.quote(path)}",
                data=body or None, method=method, headers=headers)
            return urllib.request.urlopen(req, timeout=30)

    stale = StaleClient(s3.url(), ACCESS, SECRET)
    with pytest.raises(urllib.error.HTTPError) as ei:
        stale.request("GET", "/")
    assert ei.value.code == 403
    assert b"RequestTimeTooSkewed" in ei.value.read()


def test_delimiter_common_prefixes_count_toward_max_keys(stack):
    *_rest, client = stack
    client.request("PUT", "/delimbkt").read()
    for d in ("p1", "p2", "p3"):
        client.request("PUT", f"/delimbkt/{d}/f", body=b"x").read()
    root = _strip_ns(client.xml(
        "GET", "/delimbkt", query="list-type=2&delimiter=/&max-keys=2"))
    prefixes = [p.findtext("Prefix")
                for p in root.iter("CommonPrefixes")]
    assert prefixes == ["p1/", "p2/"]
    assert root.findtext("IsTruncated") == "true"
    token = root.findtext("NextContinuationToken")
    root2 = _strip_ns(client.xml(
        "GET", "/delimbkt",
        query="list-type=2&delimiter=/&max-keys=2&continuation-token="
              + urllib.parse.quote(token)))
    prefixes2 = [p.findtext("Prefix")
                 for p in root2.iter("CommonPrefixes")]
    assert prefixes2 == ["p3/"]
    assert root2.findtext("IsTruncated") == "false"


def test_exactly_full_page_not_truncated(stack):
    *_rest, client = stack
    client.request("PUT", "/fullpagebkt").read()
    for i in range(3):
        client.request("PUT", f"/fullpagebkt/k{i}", body=b"x").read()
    root = _strip_ns(client.xml("GET", "/fullpagebkt",
                                query="list-type=2&max-keys=3"))
    assert len(list(root.iter("Contents"))) == 3
    assert root.findtext("IsTruncated") == "false"
