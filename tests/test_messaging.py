"""Messaging broker: topics, publish/subscribe, placement, durability.

Reference behaviors: weed/messaging/broker/ (partitioned topic logs on
filer files, replay-then-tail subscribe, consistent-hash placement,
redirects), pb/messaging.proto's 6 RPC shapes.
"""

import threading
import time

import pytest

from seaweedfs_tpu.messaging import HashRing, MessagingClient
from seaweedfs_tpu.messaging.broker import MessageBroker


# -- hash ring --------------------------------------------------------------

def test_hash_ring_stability():
    ring = HashRing(["a", "b", "c"])
    keys = [f"t/{i}" for i in range(200)]
    before = {k: ring.locate(k) for k in keys}
    ring.add("d")
    after = {k: ring.locate(k) for k in keys}
    moved = sum(1 for k in keys if before[k] != after[k])
    # Adding one of four members moves roughly 1/4 of keys, not all.
    assert 0 < moved < 120
    # Keys that moved went to the new member.
    assert all(after[k] == "d" for k in keys if before[k] != after[k])
    ring.remove("d")
    assert {k: ring.locate(k) for k in keys} == before


# -- broker stack -----------------------------------------------------------

@pytest.fixture(scope="module")
def stack(tmp_path_factory):
    from seaweedfs_tpu.cluster.master import MasterServer
    from seaweedfs_tpu.cluster.volume_server import VolumeServer
    from seaweedfs_tpu.filer.server import FilerServer
    tmp = tmp_path_factory.mktemp("msg-stack")
    master = MasterServer(volume_size_limit_mb=64, meta_dir=str(tmp))
    master.start()
    vs = VolumeServer(master.url(), [str(tmp / "vs")], pulse_seconds=60)
    vs.start()
    filer = FilerServer(master.url())
    filer.start()
    yield master, vs, filer
    filer.stop()
    vs.stop()
    master.stop()


@pytest.fixture
def broker(stack):
    _m, _vs, filer = stack
    b = MessageBroker(filer.url())
    b.start()
    yield b
    b.stop()


def test_configure_publish_fetch_roundtrip(broker):
    c = MessagingClient(broker.url())
    cfg = c.configure_topic("chat", "room1", partition_count=2)
    assert cfg["partition_count"] == 2
    assert c.topic_config("chat", "room1")["partition_count"] == 2
    out = c.publish("chat", "room1", b"hello world", key="user-1")
    p = out["partition"]
    msgs = c.fetch("chat", "room1", p)["messages"]
    assert [m["value"] for m in msgs] == [b"hello world"]
    assert msgs[0]["key"] == "user-1"
    # Same key -> same partition (ordering per key).
    out2 = c.publish("chat", "room1", b"second", key="user-1")
    assert out2["partition"] == p
    msgs = c.fetch("chat", "room1", p)["messages"]
    assert [m["value"] for m in msgs] == [b"hello world", b"second"]


def test_fetch_since_offset_tailing(broker):
    c = MessagingClient(broker.url())
    c.configure_topic("chat", "tail", partition_count=1)
    ts = []
    for i in range(5):
        ts.append(c.publish("chat", "tail", f"m{i}", key="k")["ts_ns"])
    out = c.fetch("chat", "tail", 0, since_ns=ts[2])
    assert [m["value"] for m in out["messages"]] == ["m3", "m4"]
    assert out["last_ns"] == ts[4]
    # Nothing new: empty page, offset stable.
    out2 = c.fetch("chat", "tail", 0, since_ns=out["last_ns"])
    assert out2["messages"] == []


def test_messages_survive_broker_restart(stack):
    """Messages are durable in the filer: a new broker replays them
    (the filer IS the log)."""
    _m, _vs, filer = stack
    b1 = MessageBroker(filer.url())
    b1.start()
    c1 = MessagingClient(b1.url())
    c1.configure_topic("dur", "events", partition_count=1)
    for i in range(3):
        c1.publish("dur", "events", f"e{i}", key="k")
    b1.stop()  # flushes tail segments to the filer
    b2 = MessageBroker(filer.url())
    b2.start()
    try:
        msgs = MessagingClient(b2.url()).fetch("dur", "events", 0)
        assert [m["value"] for m in msgs["messages"]] == \
            ["e0", "e1", "e2"]
    finally:
        b2.stop()


def test_two_brokers_placement_and_redirect(stack):
    _m, _vs, filer = stack
    b1 = MessageBroker(filer.url())
    b2 = MessageBroker(filer.url())
    b1.start()
    b2.start()
    try:
        c = MessagingClient(b1.url())
        # 32 partitions: with 8, consistent hashing over two
        # random-port broker urls lands ALL partitions on one broker
        # ~0.8% of runs — an inherent flake, not a placement bug.
        c.configure_topic("multi", "t", partition_count=32)
        # Both brokers agree on placement for every partition.
        for p in range(32):
            o1 = b1._owner_of("multi", "t", p)
            o2 = b2._owner_of("multi", "t", p)
            assert o1 == o2
        owners = {b1._owner_of("multi", "t", p) for p in range(32)}
        assert owners == {b1.url(), b2.url()}  # spread over both
        # Publishing through the "wrong" broker redirects transparently.
        for i in range(16):
            c.publish("multi", "t", f"m{i}", key=f"k{i}")
        total = 0
        for p in range(32):
            total += len(c.fetch("multi", "t", p)["messages"])
        assert total == 16
        # find_broker agrees with where messages actually landed.
        from seaweedfs_tpu.cluster import rpc
        fb = rpc.call(b2.url() + "/find_broker?namespace=multi&topic=t"
                      "&partition=3")
        assert fb["broker"] in (b1.url(), b2.url())
    finally:
        b1.stop()
        b2.stop()


def test_streaming_subscribe_tail(broker):
    c = MessagingClient(broker.url())
    c.configure_topic("live", "s", partition_count=1)
    got = []
    stop = threading.Event()
    t = threading.Thread(
        target=lambda: c.subscribe(
            "live", "s", 0, got.append, poll_interval=0.05,
            stop_check=stop.is_set),
        daemon=True)
    t.start()
    for i in range(4):
        c.publish("live", "s", f"ev{i}", key="k")
        time.sleep(0.05)
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline and len(got) < 4:
        time.sleep(0.05)
    stop.set()
    t.join(timeout=3)
    assert [m["value"] for m in got] == ["ev0", "ev1", "ev2", "ev3"]


def test_delete_topic(broker):
    c = MessagingClient(broker.url())
    c.configure_topic("gone", "t", partition_count=1)
    c.publish("gone", "t", "x", key="k")
    c.delete_topic("gone", "t")
    from seaweedfs_tpu.cluster import rpc
    with pytest.raises(rpc.RpcError):
        c.topic_config("gone", "t")


# -- gRPC plane (messaging_pb.SeaweedMessaging) -----------------------------

def test_messaging_grpc_publish_subscribe(broker):
    import grpc
    from seaweedfs_tpu.pb import messaging_pb2 as pb
    from seaweedfs_tpu.pb.messaging_grpc import MessagingGrpcServer
    g = MessagingGrpcServer(broker, port=0)
    g.start()
    chan = grpc.insecure_channel(g.addr())
    SVC = "/messaging_pb.SeaweedMessaging/"
    try:
        unary = lambda name, req, resp: chan.unary_unary(  # noqa: E731
            SVC + name,
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=resp.FromString)(req, timeout=10)
        unary("ConfigureTopic",
              pb.ConfigureTopicRequest(
                  namespace="chat", topic="grpc",
                  configuration=pb.TopicConfiguration(
                      partition_count=1)),
              pb.ConfigureTopicResponse)
        cfg = unary("GetTopicConfiguration",
                    pb.GetTopicConfigurationRequest(namespace="chat",
                                                    topic="grpc"),
                    pb.GetTopicConfigurationResponse)
        assert cfg.configuration.partition_count == 1
        fb = unary("FindBroker",
                   pb.FindBrokerRequest(namespace="chat",
                                        topic="grpc"),
                   pb.FindBrokerResponse)
        assert fb.broker == broker.url()
        # bidi publish: init then two messages
        pub = chan.stream_stream(
            SVC + "Publish",
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=pb.PublishResponse.FromString)
        reqs = [
            pb.PublishRequest(init=pb.PublishRequest.InitMessage(
                namespace="chat", topic="grpc", partition=0)),
            pb.PublishRequest(data=pb.Message(key=b"k1",
                                              value=b"hello grpc")),
            pb.PublishRequest(data=pb.Message(key=b"k2",
                                              value=b"second")),
            pb.PublishRequest(data=pb.Message(is_close=True)),
        ]
        out = list(pub(iter(reqs), timeout=10))
        assert out[0].config.partition_count == 1
        assert out[-1].is_closed
        # bidi subscribe from EARLIEST sees both messages
        sub = chan.stream_stream(
            SVC + "Subscribe",
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=pb.BrokerMessage.FromString)
        init = pb.SubscriberMessage(
            init=pb.SubscriberMessage.InitMessage(
                namespace="chat", topic="grpc", partition=0,
                startPosition=(
                    pb.SubscriberMessage.InitMessage.EARLIEST)))
        got = []
        for msg in sub(iter([init]), timeout=10):
            got.append(msg.data)
            if len(got) == 2:
                break
        assert [(m.key, m.value) for m in got] == \
            [(b"k1", b"hello grpc"), (b"k2", b"second")]
    finally:
        chan.close()
        g.stop()
