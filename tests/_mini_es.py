"""In-process mini Elasticsearch REST server for ElasticStore tests:
_doc CRUD, term/range _search with Name sort, wildcard multi-index —
the mini-RESP pattern over the repo's own JsonHttpServer."""

from __future__ import annotations

import fnmatch
import json
import threading

from seaweedfs_tpu.cluster import rpc


class MiniEs:
    def __init__(self):
        self.indices: dict[str, dict[str, dict]] = {}
        self._lock = threading.Lock()
        self._srv = rpc.JsonHttpServer()
        self._srv.prefix_route("PUT", "/", self._put)
        self._srv.prefix_route("GET", "/", self._get)
        self._srv.prefix_route("DELETE", "/", self._delete)
        self._srv.prefix_route("POST", "/", self._post)
        self._srv.start()
        self.port = self._srv.port

    def url(self) -> str:
        return self._srv.url()

    @staticmethod
    def _doc_path(path: str):
        parts = path.strip("/").split("/")
        if len(parts) == 3 and parts[1] == "_doc":
            return parts[0], parts[2]
        return None

    def _put(self, path: str, query: dict, body: bytes):
        dp = self._doc_path(path)
        if dp is None:  # index creation
            with self._lock:
                self.indices.setdefault(path.strip("/"), {})
            return {"acknowledged": True}
        index, doc_id = dp
        with self._lock:
            self.indices.setdefault(index, {})[doc_id] = \
                json.loads(body)
        return {"result": "updated", "_id": doc_id}

    def _get(self, path: str, query: dict, body: bytes):
        dp = self._doc_path(path)
        if path.startswith("/_cat/indices"):
            with self._lock:
                return (200, json.dumps(
                    [{"index": name} for name in self.indices]).encode(),
                    {"Content-Type": "application/json"})
        if dp is None:
            raise rpc.RpcError(400, "bad path")
        index, doc_id = dp
        with self._lock:
            doc = self.indices.get(index, {}).get(doc_id)
        if doc is None:
            raise rpc.RpcError(404, json.dumps({"found": False}))
        return {"found": True, "_id": doc_id, "_source": doc}

    def _delete(self, path: str, query: dict, body: bytes):
        dp = self._doc_path(path)
        with self._lock:
            if dp is None:  # whole index
                self.indices.pop(path.strip("/"), None)
                return {"acknowledged": True}
            index, doc_id = dp
            existed = self.indices.get(index, {}).pop(doc_id, None)
        if existed is None:
            raise rpc.RpcError(404, json.dumps({"result": "not_found"}))
        return {"result": "deleted"}

    def _post(self, path: str, query: dict, body: bytes):
        parts = path.strip("/").split("/")
        if len(parts) == 2 and parts[1] == "_search":
            return self._search(parts[0], json.loads(body or b"{}"))
        raise rpc.RpcError(400, f"bad path {path}")

    def _search(self, index_pat: str, req: dict):
        q = req.get("query", {})
        term = {}
        range_filter = {}
        if "term" in q:
            term = q["term"]
        elif "bool" in q:
            for m in q["bool"].get("must", []):
                term.update(m.get("term", {}))
            for f in q["bool"].get("filter", []):
                range_filter.update(f.get("range", {}))
        with self._lock:
            docs = []
            for name, idx in self.indices.items():
                if fnmatch.fnmatchcase(name, index_pat):
                    docs.extend(idx.values())
        def field_of(doc, name):
            # ES keyword subfield: "Name.keyword" reads the raw value
            return doc.get(name[:-8] if name.endswith(".keyword")
                           else name, "")

        hits = []
        for doc in docs:
            ok = all(doc.get(k) == v for k, v in term.items())
            for field, cond in range_filter.items():
                for op, val in cond.items():
                    got = field_of(doc, field)
                    ok = ok and {"gt": got > val, "gte": got >= val,
                                 "lt": got < val,
                                 "lte": got <= val}[op]
            if ok:
                hits.append(doc)
        for sort in req.get("sort", []):
            for field, order in sort.items():
                hits.sort(key=lambda d: field_of(d, field),
                          reverse=order == "desc")
        size = req.get("size", 10)
        return {"hits": {"hits": [{"_source": d}
                                  for d in hits[:size]]}}

    def close(self):
        self._srv.stop()
