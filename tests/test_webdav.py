"""WebDAV gateway protocol tests (reference: weed/server/webdav_server.go;
the reference leans on x/net/webdav's own tests — here the verb set is
exercised over HTTP against the live filer+volume+master stack).
"""

import urllib.error
import urllib.parse
import urllib.request
import xml.etree.ElementTree as ET

import pytest

from seaweedfs_tpu.cluster.master import MasterServer
from seaweedfs_tpu.cluster.volume_server import VolumeServer
from seaweedfs_tpu.filer.server import FilerServer
from seaweedfs_tpu.webdav import WebDavServer

DAV = "{DAV:}"


@pytest.fixture(scope="module")
def dav(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("dav-stack")
    master = MasterServer(volume_size_limit_mb=64, meta_dir=str(tmp))
    master.start()
    vs = VolumeServer(master.url(), [str(tmp / "vs")], pulse_seconds=60)
    vs.start()
    filer = FilerServer(master.url(), chunk_size=512)
    filer.start()
    srv = WebDavServer(filer.url())
    srv.start()
    yield srv
    srv.stop()
    filer.stop()
    vs.stop()
    master.stop()


def req(dav_srv, method, path, body=None, headers=None, expect=None):
    r = urllib.request.Request(dav_srv.url() + path, data=body,
                               method=method, headers=headers or {})
    try:
        resp = urllib.request.urlopen(r, timeout=10)
        status, data = resp.status, resp.read()
        hdrs = dict(resp.headers)
    except urllib.error.HTTPError as e:
        status, data, hdrs = e.code, e.read(), dict(e.headers)
    if expect is not None:
        assert status == expect, f"{method} {path}: {status} {data[:200]}"
    return status, data, hdrs


def test_options_advertises_dav(dav):
    _, _, hdrs = req(dav, "OPTIONS", "/", expect=200)
    assert hdrs.get("DAV") == "1,2"
    assert "PROPFIND" in hdrs.get("Allow", "")


def test_mkcol_put_get_propfind(dav):
    req(dav, "MKCOL", "/docs", expect=201)
    req(dav, "PUT", "/docs/a.txt", body=b"alpha", expect=201)
    req(dav, "PUT", "/docs/a.txt", body=b"alpha2", expect=204)  # overwrite
    _, data, _ = req(dav, "GET", "/docs/a.txt", expect=200)
    assert data == b"alpha2"
    _, _, hdrs = req(dav, "HEAD", "/docs/a.txt", expect=200)
    assert hdrs["Content-Length"] == "6"
    # PROPFIND depth 1 on the collection
    status, body, _ = req(dav, "PROPFIND", "/docs",
                          headers={"Depth": "1"}, expect=207)
    ms = ET.fromstring(body)
    hrefs = [r.findtext(f"{DAV}href") for r in ms.findall(f"{DAV}response")]
    assert "/docs/" in hrefs and "/docs/a.txt" in hrefs
    # the file response carries a contentlength prop
    for r in ms.findall(f"{DAV}response"):
        if r.findtext(f"{DAV}href") == "/docs/a.txt":
            assert r.find(
                f"{DAV}propstat/{DAV}prop/{DAV}getcontentlength"
            ).text == "6"


def test_propfind_depth0_and_missing(dav):
    req(dav, "MKCOL", "/d0", expect=201)
    req(dav, "PUT", "/d0/x", body=b"x", expect=201)
    _, body, _ = req(dav, "PROPFIND", "/d0",
                     headers={"Depth": "0"}, expect=207)
    ms = ET.fromstring(body)
    assert len(ms.findall(f"{DAV}response")) == 1
    req(dav, "PROPFIND", "/missing-path", expect=404)


def test_mkcol_conflict_and_exists(dav):
    req(dav, "MKCOL", "/no/parent/here", expect=409)
    req(dav, "MKCOL", "/dupdir", expect=201)
    req(dav, "MKCOL", "/dupdir", expect=405)


def test_move_and_copy(dav):
    req(dav, "MKCOL", "/mv", expect=201)
    req(dav, "PUT", "/mv/src.txt", body=b"move-me", expect=201)
    req(dav, "MOVE", "/mv/src.txt",
        headers={"Destination": dav.url() + "/mv/dst.txt"}, expect=201)
    req(dav, "GET", "/mv/src.txt", expect=404)
    _, data, _ = req(dav, "GET", "/mv/dst.txt", expect=200)
    assert data == b"move-me"
    # COPY leaves the source in place
    req(dav, "COPY", "/mv/dst.txt",
        headers={"Destination": dav.url() + "/mv/copy.txt"}, expect=201)
    _, d1, _ = req(dav, "GET", "/mv/dst.txt", expect=200)
    _, d2, _ = req(dav, "GET", "/mv/copy.txt", expect=200)
    assert d1 == d2 == b"move-me"
    # Overwrite: F refuses when destination exists
    req(dav, "PUT", "/mv/exists.txt", body=b"old", expect=201)
    req(dav, "COPY", "/mv/dst.txt",
        headers={"Destination": dav.url() + "/mv/exists.txt",
                 "Overwrite": "F"}, expect=412)
    req(dav, "COPY", "/mv/dst.txt",
        headers={"Destination": dav.url() + "/mv/exists.txt"}, expect=204)


def test_delete_recursive(dav):
    req(dav, "MKCOL", "/deltree", expect=201)
    req(dav, "PUT", "/deltree/f1", body=b"1", expect=201)
    req(dav, "PUT", "/deltree/f2", body=b"2", expect=201)
    req(dav, "DELETE", "/deltree", expect=204)
    req(dav, "GET", "/deltree/f1", expect=404)
    req(dav, "DELETE", "/deltree", expect=404)


def test_lock_unlock(dav):
    req(dav, "PUT", "/locked.txt", body=b"L", expect=201)
    status, body, hdrs = req(dav, "LOCK", "/locked.txt", expect=200)
    token = hdrs.get("Lock-Token", "")
    assert token.startswith("<opaquelocktoken:")
    assert b"lockdiscovery" in body
    req(dav, "UNLOCK", "/locked.txt",
        headers={"Lock-Token": token}, expect=204)


def test_proppatch_echoes_ok(dav):
    req(dav, "PUT", "/pp.txt", body=b"p", expect=201)
    status, body, _ = req(
        dav, "PROPPATCH", "/pp.txt",
        body=b'<?xml version="1.0"?><D:propertyupdate xmlns:D="DAV:">'
             b'<D:set><D:prop><D:displayname>x</D:displayname></D:prop>'
             b'</D:set></D:propertyupdate>', expect=207)
    assert b"200 OK" in body


def test_range_get(dav):
    req(dav, "PUT", "/range.bin", body=b"0123456789" * 100, expect=201)
    status, data, hdrs = req(dav, "GET", "/range.bin",
                             headers={"Range": "bytes=10-19"}, expect=206)
    assert data == b"0123456789"
