"""Wire-flow attribution plane (stats/flows.py + the rpc choke point).

Covers ISSUE 16's acceptance gates: the purpose catalog is closed and
anti-rot tested, bytes counted by a sender match the receiver within
1% on every paired (link, purpose) cell of a live multi-node cluster
(including the zero-copy sendfile and splice legs), an EC rebuild's
traffic lands under ec.gather/ec.scatter — never user.* — a budget
breach produces the flows.budget event plus a healthz WARNING (never a
503), the legacy per-subsystem byte counters cross-check against the
ledger, and SeaweedFS_wire_bytes_total scrapes promcheck-clean on all
three roles."""

import time

import pytest

from seaweedfs_tpu import fault
from seaweedfs_tpu.cluster import rpc
from seaweedfs_tpu.cluster.client import WeedClient
from seaweedfs_tpu.cluster.master import MasterServer
from seaweedfs_tpu.cluster.volume_server import VolumeServer
from seaweedfs_tpu.events.journal import JOURNAL
from seaweedfs_tpu.filer.server import FilerServer
from seaweedfs_tpu.stats import flows
from seaweedfs_tpu.stats.metrics import ec_repair_read_bytes_total
from seaweedfs_tpu.stats.promcheck import validate_exposition
from seaweedfs_tpu.shell import CommandEnv, run_command

pytestmark = pytest.mark.flows


def _wait(cond, timeout=20.0, msg="condition never held"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return
        time.sleep(0.1)
    raise AssertionError(msg)


# -- catalog + ledger units --------------------------------------------------

def test_purpose_catalog_anti_rot():
    """The catalog is CLOSED: exactly the documented purposes exist,
    each validates, tags, and notes cleanly; anything else raises
    loudly at the call site (like the event catalog)."""
    expected = {"user.read", "user.write", "replicate.fanout",
                "ec.gather", "ec.scatter", "repair.fetch", "rlog.ship",
                "tier.up", "tier.down", "proxy", "control"}
    assert set(flows.PURPOSES) == expected
    led = flows.FlowLedger()
    for p in flows.PURPOSES:
        assert flows.validate(p) == p
        assert flows.tag(p) == {flows.PURPOSE_HEADER: p}
        assert flows.PURPOSES[p], f"purpose {p} has no description"
        led.note(p, "out", 10, peer="x:1", peer_role="volume",
                 local="me:0")
    assert led.totals()[0] == 10 * len(expected)
    for bad in ("user.delete", "gossip", "", "USER.READ"):
        with pytest.raises(ValueError):
            flows.validate(bad)
        with pytest.raises(ValueError):
            flows.tag(bad)
        with pytest.raises(ValueError):
            led.note(bad, "out", 1, local="me:0")
    with pytest.raises(ValueError):
        led.note("user.read", "sideways", 1, local="me:0")


def test_resolve_heuristics_and_header_priority():
    """A valid explicit header always wins; without one, replication
    POSTs, control-plane paths, and plain GET/PUT fall out of the
    method+path heuristic — never an exception."""
    r = flows.resolve
    assert r("GET", "/3,01abc", flows.tag("ec.gather")[
        flows.PURPOSE_HEADER]) == "ec.gather"
    assert r("POST", "/3,01abc",
             query_type="replicate") == "replicate.fanout"
    assert r("GET", "/dir/assign") == "control"
    assert r("POST", "/heartbeat") == "control"
    assert r("GET", "/3,01abc", low_priority=True) == "control"
    assert r("GET", "/3,01abc") == "user.read"
    assert r("POST", "/3,01abc") == "user.write"
    # A garbage header from a foreign client must degrade to the
    # heuristic, not 500 the request.
    assert r("GET", "/3,01abc", "not.a.purpose") == "user.read"


def test_rate_and_budget_grammar():
    assert flows.parse_rate("50MB/s") == 50 * 1024 * 1024
    assert flows.parse_rate("1.5GB/s") == 1.5 * 1024 ** 3
    assert flows.parse_rate("800KB/s") == 800 * 1024
    b = flows.parse_budgets("repair.fetch=50MB/s,tier.up=1GB/s")
    assert b == {"repair.fetch": 50 * 1024 * 1024,
                 "tier.up": float(1024 ** 3)}
    for bad in ("repair.fetch", "bogus.purpose=1MB/s",
                "repair.fetch=fast"):
        with pytest.raises(ValueError):
            flows.parse_budgets(bad)


def test_budget_breach_emits_event_and_status():
    """Over-budget traffic flips budget_status to breached and lands
    exactly one flows.budget event per dedup window (sustain=0 makes a
    single oversized note an immediate breach — the events driver
    path)."""
    led = flows.FlowLedger()
    led.set_budgets({"repair.fetch": 1024.0}, sustain=0.0)
    seq0 = JOURNAL._seq
    led.note("repair.fetch", "in", 1 << 20, peer="peer:1",
             peer_role="volume", local="bdg:0")
    st = led.budget_status(local="bdg:0")
    assert st["repair.fetch"]["breached"] is True
    assert st["repair.fetch"]["limit_bps"] == 1024.0
    assert st["repair.fetch"]["rate_bps"] > 1024.0
    evs = [e for e in JOURNAL.snapshot(type_="flows.budget")
           if e["seq"] > seq0]
    assert evs and evs[-1]["severity"] == "warn"
    assert evs[-1]["attrs"]["purpose"] == "repair.fetch"
    assert evs[-1]["attrs"]["rate_bps"] > evs[-1]["attrs"]["limit_bps"]
    # Within budget: status clean, no fresh event.
    led2 = flows.FlowLedger()
    led2.set_budgets({"repair.fetch": float(1 << 30)}, sustain=0.0)
    led2.note("repair.fetch", "in", 1024, peer="peer:1",
              peer_role="volume", local="bdg2:0")
    assert not led2.budget_status()["repair.fetch"]["breached"]


# -- live cluster ------------------------------------------------------------

@pytest.fixture
def cluster(tmp_path):
    master = MasterServer(volume_size_limit_mb=64,
                          meta_dir=str(tmp_path / "meta"),
                          pulse_seconds=60)
    master.start()
    servers = []
    for i in range(2):
        d = tmp_path / f"vs{i}"
        d.mkdir()
        vs = VolumeServer(master.url(), [str(d)],
                          max_volume_counts=[50], pulse_seconds=60)
        vs.start()
        servers.append(vs)
    # The in-process WeedClient's legs attribute to the thread-local
    # identity; under a full pytest run the process DEFAULT identity
    # belongs to whichever server started first in the process (an
    # earlier test's, long dead and never heartbeating), so bind this
    # thread to our master — its ledger self-merges into the matrix
    # and the client legs pair deterministically.
    flows.bind_thread(master.url().replace("http://", ""), "master")
    yield master, servers
    flows.clear_thread()
    fault.disarm_all()
    for vs in servers:
        vs.stop()
    master.stop()


def _freshen(servers):
    for vs in servers:
        vs._send_heartbeat(full=True)
        vs._ec_loc_cache.clear()


def _matrix(master, servers, q=""):
    """Heartbeat-merge the volume servers' ledgers and fetch the
    traffic matrix.  A forced beat can race the last post-sendfile
    ledger note by microseconds (the note runs after the syscall
    returns, on the server thread), so settle first."""
    time.sleep(0.3)
    _freshen(servers)
    time.sleep(0.1)
    return rpc.call(f"{master.url()}/cluster/flows{q}")


def test_conservation_live_multinode(cluster):
    """THE acceptance gate: every paired (link, purpose) cell of the
    live matrix conserves — sender's bytes == receiver's within 1% —
    across a workload covering replicated writes, zero-copy sendfile
    reads, and the request legs themselves."""
    master, servers = cluster
    client = WeedClient(master.url())
    payload = b"conserve me " * 25_000          # ~300KB > SENDFILE_MIN
    fid = client.upload(payload, replication="001")["fid"]
    assert client.download(fid) == payload      # sendfile leg, holder A
    assert client.download(fid) == payload      # sendfile leg, holder B

    doc = _matrix(master, servers)
    cons = doc["conservation"]
    assert cons["ok"], cons["violations"]
    assert cons["paired_cells"] >= 8, doc["cells"]
    by = {(c["src"], c["dst"], c["purpose"]): c for c in doc["cells"]}
    vs_urls = {vs.url() for vs in servers}

    # The replicated write fanned the full payload to exactly one
    # replica link, byte-conserved.
    fan = [c for c in doc["cells"] if c["purpose"] == "replicate.fanout"
           and (c["sent_bytes"] or 0) >= len(payload)]
    assert len(fan) == 1, doc["cells"]
    assert fan[0]["src"] in vs_urls and fan[0]["dst"] in vs_urls
    assert fan[0]["sent_bytes"] == fan[0]["recv_bytes"]

    # Both sendfile response legs show up as conserved user.read cells
    # whose bytes are the served body, not zero (the zero-copy path
    # must count syscall-returned totals).
    reads = [c for c in doc["cells"] if c["purpose"] == "user.read"
             and c["src"] in vs_urls
             and (c["sent_bytes"] or 0) >= len(payload)]
    assert {c["src"] for c in reads} == vs_urls, doc["cells"]
    for c in reads:
        assert c["sent_bytes"] == c["recv_bytes"] == len(payload)

    # Matrix trimmings: totals, ranking, and GB fields are coherent.
    assert doc["purposes"]["user.read"]["bytes"] >= 2 * len(payload)
    assert doc["top_talkers"] and "gb" in doc["top_talkers"][0]
    assert by, "matrix empty"


def test_conservation_covers_splice_proxy_leg(cluster, tmp_path):
    """Filer front door: a big single-chunk GET streams volume->client
    through ProxiedBody (the splice leg).  The filer->volume pull is
    attributed `proxy` and the volume server's side of that link
    conserves once merged."""
    import os
    master, servers = cluster
    filer = FilerServer(master.url(), chunk_size=1 << 20)
    filer.start()
    try:
        big = os.urandom(400 * 1024)
        rpc.call(filer.url() + "/flows.bin", "PUT", big)
        assert rpc.call(filer.url() + "/flows.bin") == big
        time.sleep(0.3)
        # The filer doesn't heartbeat rows into the master matrix —
        # its own ledger is the authority for its legs.
        proxy_in, _ops = flows.LEDGER.totals(
            purpose_="proxy", direction="in",
            local=filer.url().replace("http://", ""))
        assert proxy_in >= len(big), \
            "filer's proxied pull not attributed to `proxy`"
        doc = _matrix(master, servers)
        assert doc["conservation"]["ok"], \
            doc["conservation"]["violations"]
        # The volume side of the proxied pull is tagged by header.
        vs_proxy = [c for c in doc["cells"] if c["purpose"] == "proxy"
                    and (c["sent_bytes"] or 0) >= len(big)]
        assert vs_proxy, doc["cells"]
    finally:
        filer.stop()


def test_debug_flows_surface_and_matrix_filter(cluster):
    master, servers = cluster
    client = WeedClient(master.url())
    fid = client.upload(b"debug surface " * 2000)["fid"]
    client.download(fid)
    doc = rpc.call(f"http://{servers[0].url()}/debug/flows")
    assert doc["role"] == "volume" and doc["node"] == servers[0].url()
    assert set(doc["purposes"]) == set(flows.PURPOSES)
    assert isinstance(doc["rows"], list)
    # ?purpose= filters the matrix to one catalog entry; an unknown
    # purpose is refused, not silently empty.
    doc = _matrix(master, servers, "?purpose=user.write")
    assert doc["cells"] and all(c["purpose"] == "user.write"
                                for c in doc["cells"])
    with pytest.raises(rpc.RpcError):
        rpc.call(f"{master.url()}/cluster/flows?purpose=nonsense")


def test_budget_breach_healthz_warning_not_problem(cluster):
    """A sustained budget breach is a WARNING on /cluster/healthz —
    visibility, not an outage: the endpoint stays 200/healthy."""
    master, servers = cluster
    flows.LEDGER.set_budgets({"user.write": 1024.0}, sustain=0.0)
    try:
        client = WeedClient(master.url())
        client.upload(b"budget breaker " * 20_000)  # ~300KB >> 1KB/s
        _freshen(servers)
        status, doc = rpc.call_status(f"{master.url()}/cluster/healthz")
        assert status == 200 and doc["healthy"], doc
        warnings = doc["flows"]["warnings"]
        assert any("user.write" in w for w in warnings), doc["flows"]
        assert any(b["purpose"] == "user.write" and b["breached"]
                   for b in doc["flows"]["budgets"]), doc["flows"]
        # The breach also reaches the matrix's budget rollup.
        mdoc = _matrix(master, servers)
        assert any("user.write" in budgets
                   for budgets in mdoc["budgets"].values()), \
            mdoc["budgets"]
    finally:
        flows.LEDGER.set_budgets({})


# -- EC rebuild + repair attribution -----------------------------------------

def _make_ec_volume(master, servers):
    """One EC volume spread 5/5/4 across three holders (the
    test_batch_rebuild recipe, single volume)."""
    client = WeedClient(master.url())
    rpc.call_json(f"{master.url()}/vol/grow?count=1", "POST")
    fids = [client.upload_data(f"flows-ec-{i}".encode() * (i % 7 + 1))
            for i in range(8)]
    vid = int(fids[0].split(",")[0])
    spread = [(servers[0], [0, 1, 2, 3, 4]),
              (servers[1], [5, 6, 7, 8, 9]),
              (servers[2], [10, 11, 12, 13])]
    src = client.lookup(vid)[0]["url"]
    rpc.call_json(f"http://{src}/admin/ec/generate", "POST",
                  {"volume": vid})
    for vs, shards in spread:
        if vs.url() != src:
            rpc.call_json(f"http://{vs.url()}/admin/ec/copy_shard",
                          "POST", {"volume": vid, "source": src,
                                   "shards": shards,
                                   "copy_ecx": True})
    for vs, shards in spread:
        rpc.call_json(f"http://{vs.url()}/admin/ec/mount", "POST",
                      {"volume": vid})
        drop = [s for s in range(14) if s not in shards]
        rpc.call_json(f"http://{vs.url()}/admin/ec/delete_shards",
                      "POST", {"volume": vid, "shards": drop})
    rpc.call_json(f"http://{src}/admin/delete_volume", "POST",
                  {"volume": vid})
    _freshen(servers)
    return client, vid, fids


@pytest.fixture
def ec_cluster(tmp_path):
    master = MasterServer(volume_size_limit_mb=64,
                          meta_dir=str(tmp_path),
                          pulse_seconds=60)
    master.start()
    servers = []
    for i in range(3):
        d = tmp_path / f"vs{i}"
        d.mkdir()
        vs = VolumeServer(master.url(), [str(d)], pulse_seconds=60)
        vs.start()
        servers.append(vs)
    yield master, servers
    for vs in servers:
        vs.stop()
    master.stop()


def test_ec_rebuild_attributed_not_user_traffic(ec_cluster):
    """Acceptance: /cluster/flows attributes a rebuild's bytes to
    ec.gather (survivor fan-in) and ec.scatter (rebuilt fan-out), with
    NO user.* traffic — and the legacy ec_repair_read_bytes_total
    counter can never exceed the wire truth it is a view of."""
    master, servers = ec_cluster
    _client, vid, _fids = _make_ec_volume(master, servers)
    env = CommandEnv(master.url())
    holder = env.ec_shard_locations(vid)[1][0]
    rpc.call_json(f"http://{holder}/admin/ec/delete_shards", "POST",
                  {"volume": vid, "shards": [1]})
    _freshen(servers)

    flows.LEDGER.reset()
    legacy0 = ec_repair_read_bytes_total.value(codec="rs")
    run_command(env, "lock")
    out = run_command(env, "ec.rebuild -batch")
    assert f"volume {vid}: rebuilt shards" in out

    gather_in, gops = flows.LEDGER.totals(purpose_="ec.gather",
                                          direction="in")
    scatter_out, sops = flows.LEDGER.totals(purpose_="ec.scatter",
                                            direction="out")
    assert gather_in > 0 and gops >= 10, "survivor fan-in unattributed"
    assert scatter_out > 0 and sops >= 1, "rebuilt fan-out unattributed"
    # Legacy counter == payload bytes only; the ledger's ec.gather
    # additionally carries sidecars, so wire >= legacy always.
    legacy_read = ec_repair_read_bytes_total.value(codec="rs") - legacy0
    assert 0 < legacy_read <= gather_in

    doc = _matrix(master, servers)
    assert "ec.gather" in doc["purposes"], doc["purposes"]
    assert "ec.scatter" in doc["purposes"], doc["purposes"]
    assert "user.read" not in doc["purposes"], \
        "rebuild traffic leaked into user.read"
    assert "user.write" not in doc["purposes"], \
        "rebuild traffic leaked into user.write"
    assert doc["conservation"]["ok"], doc["conservation"]["violations"]
    env.close()


def test_degraded_read_attributes_repair_fetch(cluster):
    """An inline needle heal (CRC-failing GET repaired from the
    sibling replica) moves its bytes under repair.fetch."""
    master, servers = cluster
    col = "flowsheal"
    rpc.call(f"{master.url()}/vol/grow?count=1&collection={col}"
             f"&replication=001", "POST")
    a = rpc.call(f"{master.url()}/dir/assign?collection={col}"
                 f"&replication=001")
    payload = b"rot target " * 64
    fault.arm("volume.corrupt", "fail*1")
    try:
        rpc.call(f"http://{a['url']}/{a['fid']}", "POST", payload)
    finally:
        fault.disarm_all()
    flows.LEDGER.reset()
    assert bytes(rpc.call(f"http://{a['url']}/{a['fid']}")) == payload
    fetched, ops = flows.LEDGER.totals(purpose_="repair.fetch",
                                       direction="in")
    assert fetched >= len(payload) and ops >= 1, \
        "replica heal not attributed to repair.fetch"
    doc = _matrix(master, servers)
    assert "repair.fetch" in doc["purposes"], doc["purposes"]


# -- rlog shipping cross-assert ----------------------------------------------

def test_rlog_ship_cross_asserts_legacy_counter(tmp_path):
    """replication_shipped_bytes_total counts blob payload bytes; the
    ledger's rlog.ship leg counts the wire body (JSON envelope
    included).  wire >= legacy > 0, same traffic, two views."""
    from seaweedfs_tpu.stats.metrics import \
        replication_shipped_bytes_total
    sb_master = MasterServer(volume_size_limit_mb=16,
                             meta_dir=str(tmp_path / "sbmeta"),
                             pulse_seconds=60)
    sb_master.start()
    (tmp_path / "sb").mkdir()
    sb_vs = VolumeServer(sb_master.url(), [str(tmp_path / "sb")],
                         max_volume_counts=[200], pulse_seconds=60)
    sb_vs.start()
    pport = rpc.free_port()
    pr_master = MasterServer(port=pport, volume_size_limit_mb=16,
                             meta_dir=str(tmp_path / "prmeta"),
                             pulse_seconds=60,
                             peers=[f"http://127.0.0.1:{pport}"])
    pr_master.start()
    _wait(pr_master.is_leader, 15, "single-node raft never elected")
    (tmp_path / "pr").mkdir()
    pr_vs = VolumeServer(pr_master.url(), [str(tmp_path / "pr")],
                         max_volume_counts=[200], pulse_seconds=60,
                         replicate_peer=sb_master.url(),
                         replicate_interval=0.05)
    pr_vs.start()
    try:
        rpc.call(f"{pr_master.url()}/vol/grow?count=1&collection=fl",
                 "POST")
        a = rpc.call(f"{pr_master.url()}/dir/assign?collection=fl")
        vid = int(a["fid"].split(",")[0])
        v = pr_vs.store.find_volume(vid)
        if v.rlog is None:
            v.enable_rlog()
        legacy0 = replication_shipped_bytes_total.value()
        wire0, _ = flows.LEDGER.totals(purpose_="rlog.ship",
                                       direction="out")
        rpc.call(f"http://{a['url']}/{a['fid']}", "POST",
                 b"ship these bytes " * 64)

        def shipped():
            st = (rpc.call(f"http://{pr_vs.url()}/debug/replication")
                  .get("rlog") or {}).get(str(vid))
            return bool(st) and st["pending"] == 0 and \
                st["last_seq"] > 0
        _wait(shipped, 20, "change log never shipped")
        legacy = replication_shipped_bytes_total.value() - legacy0
        wire, ops = flows.LEDGER.totals(purpose_="rlog.ship",
                                        direction="out")
        wire -= wire0
        assert 0 < legacy <= wire, (legacy, wire)
        assert ops >= 1
    finally:
        pr_vs.stop()
        pr_master.stop()
        sb_vs.stop()
        sb_master.stop()


# -- promcheck: wire_bytes_total scrapes clean on every role -----------------

def test_promcheck_wire_bytes_all_roles(cluster):
    master, servers = cluster
    filer = FilerServer(master.url())
    filer.start()
    try:
        rpc.call(filer.url() + "/prom.bin", "PUT", b"w" * 8192)
        assert rpc.call(filer.url() + "/prom.bin") == b"w" * 8192
        mtext = bytes(rpc.call(f"{master.url()}/metrics")).decode()
        vtext = bytes(rpc.call(
            f"http://{servers[0].url()}/metrics")).decode()
        ftext = filer.metrics_registry.expose()
        for text, who in ((mtext, "master"), (vtext, "volume"),
                          (ftext, "filer")):
            assert validate_exposition(text) == [], \
                f"{who} scrape dirty"
            assert "SeaweedFS_wire_bytes_total" in text, who
        assert 'purpose="user.write"' in ftext
        assert 'direction="out"' in ftext
    finally:
        filer.stop()
