"""Reference-coder semantics tests: encode / reconstruct / verify.

Property style mirrors the reference's ec_test.go (encode, drop shards,
reconstruct from any >= k survivors, compare bytes)."""

import itertools

import numpy as np
import pytest

from seaweedfs_tpu.ops import rs_bitmatrix
from seaweedfs_tpu.ops.coder_numpy import NumpyCoder


@pytest.fixture(scope="module")
def coder():
    return NumpyCoder(10, 4)


def _rand_data(k, n, seed=0):
    return np.random.default_rng(seed).integers(0, 256, (k, n)).astype(np.uint8)


def test_encode_verify(coder):
    data = _rand_data(10, 1000)
    shards = coder.encode_all(data)
    assert shards.shape == (14, 1000)
    assert coder.verify(shards)
    # Corrupt one byte -> verify fails.
    bad = shards.copy()
    bad[12, 37] ^= 0x40
    assert not coder.verify(bad)


def test_zero_data_zero_parity(coder):
    data = np.zeros((10, 64), np.uint8)
    assert not coder.encode(data).any()


def test_linearity(coder):
    a, b = _rand_data(10, 128, 1), _rand_data(10, 128, 2)
    pa, pb = coder.encode(a), coder.encode(b)
    assert np.array_equal(coder.encode(a ^ b), pa ^ pb)


def test_reconstruct_all_4_loss_combinations(coder):
    data = _rand_data(10, 500, 3)
    shards = coder.encode_all(data)
    ids = list(range(14))
    rng = np.random.default_rng(4)
    combos = list(itertools.combinations(ids, 4))
    rng.shuffle(combos)
    for lost in combos[:60] + [(0, 1, 2, 3), (10, 11, 12, 13), (0, 5, 10, 13)]:
        have = {i: shards[i] for i in ids if i not in lost}
        rec = coder.reconstruct(have)
        assert set(rec) == set(lost)
        for i in lost:
            assert np.array_equal(rec[i], shards[i]), f"lost={lost} shard={i}"


def test_reconstruct_data_only(coder):
    data = _rand_data(10, 200, 5)
    shards = coder.encode_all(data)
    have = {i: shards[i] for i in range(14) if i not in (2, 7, 11)}
    rec = coder.reconstruct(have, wanted=[2, 7])
    assert set(rec) == {2, 7}
    assert np.array_equal(rec[2], shards[2])
    assert np.array_equal(rec[7], shards[7])


def test_too_few_shards_raises(coder):
    data = _rand_data(10, 50, 6)
    shards = coder.encode_all(data)
    have = {i: shards[i] for i in range(9)}  # only 9 < 10
    with pytest.raises(ValueError):
        coder.reconstruct(have)


def test_alt_schemes():
    for k, p in ((8, 3), (16, 4), (4, 2)):
        c = NumpyCoder(k, p)
        data = _rand_data(k, 100, k)
        shards = c.encode_all(data)
        lost = (0, k)  # one data, one parity
        have = {i: shards[i] for i in range(k + p) if i not in lost}
        rec = c.reconstruct(have)
        for i in lost:
            assert np.array_equal(rec[i], shards[i])


def test_cauchy_scheme_roundtrip():
    c = NumpyCoder(10, 4, matrix_kind="cauchy")
    data = _rand_data(10, 100, 9)
    shards = c.encode_all(data)
    have = {i: shards[i] for i in range(14) if i not in (1, 4, 12, 13)}
    rec = c.reconstruct(have)
    for i in (1, 4, 12, 13):
        assert np.array_equal(rec[i], shards[i])


def test_bitmatrix_encode_matches_gf_encode(coder):
    """The GF(2)-lowered matmul formulation == byte-domain GF math."""
    data = _rand_data(10, 777, 10)
    expect = coder.encode(data)
    got = rs_bitmatrix.encode_bits_numpy(data, 10, 14)
    assert np.array_equal(got, expect)


def test_bitmatrix_pack_unpack_roundtrip():
    data = _rand_data(5, 333, 11)
    bits = rs_bitmatrix.unpack_bits(data)
    assert bits.shape == (40, 333)
    assert np.array_equal(rs_bitmatrix.pack_bits(bits), data)


def test_bitmatrix_decode_matches(coder):
    data = _rand_data(10, 256, 12)
    shards = coder.encode_all(data)
    present = tuple(i for i in range(14) if i not in (3, 8, 10, 12))
    bmat, used = rs_bitmatrix.decode_bitmatrix(10, 14, present)
    stacked = np.stack([shards[i] for i in used])
    bits = rs_bitmatrix.unpack_bits(stacked)
    out_bits = (bmat.astype(np.int32) @ bits.astype(np.int32)) & 1
    rec = rs_bitmatrix.pack_bits(out_bits.astype(np.uint8))
    for row, i in enumerate((3, 8, 10, 12)):
        assert np.array_equal(rec[row], shards[i])
