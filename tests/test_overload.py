"""Overload protection & graceful lifecycle: admission-control lanes
(bounded queue, 429 + Retry-After sheds, internal-lane isolation), the
slow-loris idle-timeout reaper, disk-full safety (free-space reserve,
ENOSPC clean rollback, master steering), the drain lifecycle, and the
rolling-restart chaos acceptance test (SIGTERM-cycling subprocess
volume servers under sustained load with zero acknowledged-write loss
and zero client-visible errors)."""

import os
import signal
import subprocess
import sys
import threading
import time

import pytest

from seaweedfs_tpu import fault
from seaweedfs_tpu.cluster import resilience, rpc
from seaweedfs_tpu.cluster.client import WeedClient
from seaweedfs_tpu.cluster.master import MasterServer
from seaweedfs_tpu.cluster.volume_server import VolumeServer
from seaweedfs_tpu.core import types as t
from seaweedfs_tpu.events import JOURNAL
from seaweedfs_tpu.stats.promcheck import validate_exposition
from seaweedfs_tpu.storage.volume import DiskFullError, Volume

pytestmark = pytest.mark.overload


# -- admission control: bounded queue + shed ---------------------------------

def test_burst_sheds_with_429_and_every_rejection_is_counted():
    """Acceptance: with the concurrency cap set low, a 10x burst gets
    bounded-queue behavior — shed requests receive 429 + Retry-After,
    admitted requests all succeed, and the shed counter accounts for
    every rejection."""
    server = rpc.JsonHttpServer(
        admission=rpc.AdmissionControl(2, queue_depth=2,
                                       queue_timeout=5.0))
    server.route("GET", "/work",
                 lambda q, b: (time.sleep(0.15), {"ok": True})[1])
    server.start()
    base = f"http://127.0.0.1:{server.port}"
    results: list = []
    lock = threading.Lock()

    def one():
        try:
            out = rpc.call(f"{base}/work", timeout=30.0)
            with lock:
                results.append(("ok", out))
        except rpc.RpcError as e:
            with lock:
                results.append(("shed", e))

    shed_before = rpc.requests_shed_total.value(lane="read")
    try:
        threads = [threading.Thread(target=one) for _ in range(20)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
    finally:
        server.stop()
    oks = [r for kind, r in results if kind == "ok"]
    sheds = [e for kind, e in results if kind == "shed"]
    assert len(oks) + len(sheds) == 20
    # 2 executing + 2 queued admitted at minimum; the rest shed.
    assert len(sheds) >= 10, f"only {len(sheds)} shed"
    assert all(out == {"ok": True} for out in oks)
    for e in sheds:
        assert e.status == 429
        assert e.retry_after == 1.0  # Retry-After rode the answer
    shed_delta = rpc.requests_shed_total.value(lane="read") - shed_before
    assert shed_delta == len(sheds), \
        f"counter {shed_delta} != rejections {len(sheds)}"


def test_internal_lane_cannot_starve_user_reads():
    """Priority isolation: internal traffic (X-Weed-Priority: low —
    replication, scrub repair, EC rebuilds) runs in its own smaller
    lane, so a repair storm saturating it sheds REPAIR traffic while
    user reads keep flowing untouched."""
    server = rpc.JsonHttpServer(
        admission=rpc.AdmissionControl(4, queue_depth=0,
                                       queue_timeout=0.1))
    gate = threading.Event()
    server.route("GET", "/fetch",
                 lambda q, b: (gate.wait(5.0), {"ok": True})[1])
    server.start()
    base = f"http://127.0.0.1:{server.port}"
    internal_results: list = []

    def internal():
        try:
            rpc.call(f"{base}/fetch", timeout=30.0,
                     headers=rpc.PRIORITY_LOW)
            internal_results.append(200)
        except rpc.RpcError as e:
            internal_results.append(e.status)

    try:
        # Storm the internal lane (cap = max(1, 4//4) = 1, queue 0).
        threads = [threading.Thread(target=internal) for _ in range(6)]
        for th in threads:
            th.start()
        time.sleep(0.3)  # one holds the slot on gate.wait; rest shed
        # User reads are untouched: their lane has free slots.
        t0 = time.perf_counter()
        gate.set()
        assert rpc.call(f"{base}/fetch", timeout=5.0) == {"ok": True}
        assert time.perf_counter() - t0 < 2.0
        for th in threads:
            th.join()
    finally:
        server.stop()
    assert 429 in internal_results, internal_results
    assert internal_results.count(200) >= 1


def test_exempt_paths_never_shed():
    """Introspection stays reachable exactly when the server is
    overloaded: /metrics (and healthz/debug) bypass admission."""
    server = rpc.JsonHttpServer(
        admission=rpc.AdmissionControl(1, queue_depth=0,
                                       queue_timeout=0.1))
    reg = server.enable_metrics("overloadtest")
    gate = threading.Event()
    server.route("GET", "/work",
                 lambda q, b: (gate.wait(5.0), {"ok": True})[1])
    server.start()
    base = f"http://127.0.0.1:{server.port}"
    th = threading.Thread(
        target=lambda: rpc.call(f"{base}/work", timeout=30.0))
    try:
        th.start()
        time.sleep(0.2)  # the one slot is held
        # A second /work would shed — but /metrics must answer.
        text = bytes(rpc.call(f"{base}/metrics", timeout=5.0)).decode()
        assert "SeaweedFS_inflight_requests" in text
        assert not validate_exposition(text)
        row = next(ln for ln in text.splitlines()
                   if ln.startswith("SeaweedFS_inflight_requests")
                   and 'lane="read"' in ln)
        # The gated /work is visibly in flight.  The gauge is process-
        # global (it sums every live server's admission state), so
        # other suites' servers may contribute too: >= 1, not == 1.
        assert float(row.rsplit(" ", 1)[1]) >= 1
    finally:
        gate.set()
        th.join()
        server.stop()
    _ = reg


# -- slow-loris: idle timeout reaps stalled sockets --------------------------

def test_idle_timeout_reaps_slow_client_not_healthy_streams(
        monkeypatch):
    """Seeded net.slow_client fault: a client that stalls mid-request
    past the server's idle timeout is reaped (its socket dies), while
    a healthy request running concurrently on the same server is
    untouched."""
    monkeypatch.setenv("SEAWEEDFS_TPU_FAULTS_SEED", "7")
    server = rpc.JsonHttpServer(idle_timeout=1.0)
    server.route("GET", "/slowpath", lambda q, b: {"ok": True})
    server.route("GET", "/healthy", lambda q, b: {"ok": True})
    server.start()
    base = f"http://127.0.0.1:{server.port}"
    healthy: list = []

    def healthy_loop():
        for _ in range(8):
            healthy.append(rpc.call(f"{base}/healthy", timeout=5.0))
            time.sleep(0.25)

    th = threading.Thread(target=healthy_loop)
    fault.arm("net.slow_client", "delay:2.5~/slowpath")
    try:
        th.start()
        with pytest.raises((ConnectionError, OSError)):
            rpc.call(f"{base}/slowpath", timeout=10.0)
    finally:
        fault.disarm_all()
        th.join()
        server.stop()
    assert len(healthy) == 8 and all(h == {"ok": True} for h in healthy)


def test_aio_reaps_stalled_faster_than_keepalive_idle():
    """Event-loop reap policy distinguishes two idle shapes: a conn
    with request bytes buffered but no progress (slow loris) dies at
    the HARD stall timeout, while an empty-buffer keep-alive conn — a
    healthy pooled client between requests — survives until the full
    -idle.timeout.  One timer for both would either kill every pooled
    client early or give sloris attackers the long budget."""
    import socket as socketlib
    server = rpc.JsonHttpServer(idle_timeout=4.0, stall_timeout=0.5,
                                transport="aio")
    server.route("GET", "/ping", lambda q, b: {"ok": True})
    server.start()
    try:
        addr = ("127.0.0.1", server.port)
        # Stalled mid-request: half a request line, then silence.
        stalled = socketlib.create_connection(addr, timeout=5.0)
        stalled.sendall(b"GET /pi")
        # Keep-alive idle: one complete request, then silence.
        idle = socketlib.create_connection(addr, timeout=5.0)
        idle.sendall(b"GET /ping HTTP/1.1\r\nHost: x\r\n\r\n")
        assert b"200" in idle.recv(4096)
        deadline = time.time() + 3.0
        reaped = None
        while time.time() < deadline:
            stalled.settimeout(0.25)
            try:
                if stalled.recv(1) == b"":
                    reaped = time.time()
                    break
            except TimeoutError:
                continue
            except OSError:
                reaped = time.time()
                break
        assert reaped is not None, \
            "stalled conn survived well past stall_timeout"
        # The idle keep-alive conn must still be usable afterwards...
        idle.settimeout(5.0)
        idle.sendall(b"GET /ping HTTP/1.1\r\nHost: x\r\n\r\n")
        assert b"200" in idle.recv(4096)
        # ...and the registry recorded the reap with the right kind.
        snap = rpc.call(f"http://127.0.0.1:{server.port}/debug/conns")
        assert snap["transport"] == "aio"
        from seaweedfs_tpu.netcore.registry import conns_reaped_total
        assert conns_reaped_total.value(kind="stalled") >= 1
        idle.close()
        stalled.close()
    finally:
        server.stop()


# -- disk-full safety ---------------------------------------------------------

def test_enospc_rolls_back_cleanly_no_torn_tail(tmp_path):
    """Acceptance: an ENOSPC mid-append (injected: half the record
    lands) is rolled back in place — the .dat keeps no torn tail, the
    volume flips readonly, and a remount needs NO crash recovery and
    serves every previously-acked needle."""
    from seaweedfs_tpu.core.needle import Needle
    v = Volume(str(tmp_path), "", 7, use_worker=False)
    v.write_needle(Needle(cookie=1, id=1, data=b"first " * 64))
    size_before = v.dat_size()
    fault.arm("disk.full", "fail*1")
    try:
        with pytest.raises(DiskFullError):
            v.write_needle(Needle(cookie=1, id=2, data=b"boom " * 64))
    finally:
        fault.disarm_all()
    assert v.readonly
    assert v.dat_size() == size_before          # partial record gone
    assert os.path.getsize(v.file_name() + ".dat") == size_before
    assert v.dat_size() % t.NEEDLE_PADDING_SIZE == 0
    v.close()

    recovered_before = sum(
        1 for e in JOURNAL.snapshot(type_="volume.recovered"))
    v2 = Volume(str(tmp_path), "", 7, create=False, use_worker=False)
    # Remount: clean (no volume.recovered emitted — nothing to heal),
    # the acked needle is intact, and the volume writes again.
    recovered_after = sum(
        1 for e in JOURNAL.snapshot(type_="volume.recovered"))
    assert recovered_after == recovered_before, \
        "ENOSPC rollback left work for crash recovery"
    assert v2.read_needle(1).data == b"first " * 64
    v2.write_needle(Needle(cookie=1, id=3, data=b"after enospc"))
    assert v2.read_needle(3).data == b"after enospc"
    v2.close()


def test_disk_reserve_flips_readonly_and_master_steers(tmp_path):
    """Acceptance: a breached free-space reserve flips the node's
    volumes readonly BEFORE ENOSPC, the heartbeat carries the low-disk
    flag, /cluster/healthz reports it, the reserve-breached gauge
    scrapes, and the master's assignment steers to healthy nodes —
    recovering once the reserve is satisfied again."""
    master = MasterServer(pulse_seconds=60)
    master.start()
    servers = []
    try:
        for i in range(2):
            d = tmp_path / f"vs{i}"
            d.mkdir()
            vs = VolumeServer(master.url(), [str(d)],
                              max_volume_counts=[50], pulse_seconds=60)
            vs.start()
            servers.append(vs)
        client = WeedClient(master.url())
        fid = client.upload_data(b"pre-breach payload")
        low = servers[0]

        # Breach: an absurd reserve no disk satisfies.
        low.store.disk_reserve_bytes = 1 << 60
        low._send_heartbeat(full=True)
        assert low.store.low_disk_dirs
        assert all(v.readonly for loc in low.store.locations
                   for v in loc.volumes.values())
        status, doc = rpc.call_status(
            f"{master.url()}/cluster/healthz")
        assert status == 503
        assert any("disk reserve breached" in p
                   for p in doc["problems"]), doc["problems"]
        row = next(n for n in doc["nodes"] if n["node"] == low.url())
        assert row["low_disk"]
        scrape = bytes(rpc.call(f"http://{low.url()}/metrics")).decode()
        assert not validate_exposition(scrape)
        breached = [ln for ln in scrape.splitlines()
                    if ln.startswith("SeaweedFS_disk_reserve_breached")]
        assert breached and breached[0].endswith(" 1")

        # Steering: every new assignment lands on the healthy node.
        for _ in range(8):
            a = rpc.call(f"{master.url()}/dir/assign")
            assert a["url"] == servers[1].url(), a
        # Uploads still succeed (they ride the steering).
        assert client.upload_data(b"written during breach")
        # Reads of pre-breach data still serve (readonly, not gone).
        assert client.download(fid) == b"pre-breach payload"

        # Recovery: reserve satisfied again -> flips back, healthz 200.
        # The recovery itself must force a full heartbeat (the flip
        # list is non-empty in BOTH directions), or the master would
        # keep the recovered volumes out of its writable pool forever.
        low.store.disk_reserve_bytes = 1
        low._send_heartbeat()  # a DELTA beat: recovery must upgrade it
        assert not low.store.low_disk_dirs
        assert not any(v.readonly for loc in low.store.locations
                       for v in loc.volumes.values())
        status, doc = rpc.call_status(
            f"{master.url()}/cluster/healthz")
        assert status == 200, doc["problems"]
        # ...and the master assigns to the recovered node again.  The
        # pick among writable volumes is random, so sample until the
        # recovered node shows up (a fixed 20-draw sample can miss a
        # minority holder on a slow 1-core host).
        deadline = time.monotonic() + 10
        seen = set()
        while low.url() not in seen and time.monotonic() < deadline:
            seen.add(rpc.call(f"{master.url()}/dir/assign")["url"])
        assert low.url() in seen, seen
    finally:
        for vs in servers:
            vs.stop()
        master.stop()


def test_enospc_on_live_server_steers_and_client_recovers(tmp_path):
    """End-to-end ENOSPC: the write 500s (rolled back server-side),
    the client's re-assign machinery lands the retry on a healthy
    volume, and the poisoned volume never serves a torn byte."""
    master = MasterServer(pulse_seconds=60)
    master.start()
    servers = []
    try:
        for i in range(2):
            d = tmp_path / f"vs{i}"
            d.mkdir()
            vs = VolumeServer(master.url(), [str(d)],
                              max_volume_counts=[50], pulse_seconds=60)
            vs.start()
            servers.append(vs)
        client = WeedClient(master.url())
        client.upload_data(b"warmup")  # grows the layout
        fault.arm("disk.full", "fail*1")
        try:
            fid = client.upload_data(b"survives enospc " * 16)
        finally:
            fault.disarm_all()
        # The retry (fresh assign) succeeded and reads back intact.
        assert client.download(fid) == b"survives enospc " * 16
        assert any(e["type"] == "disk.full"
                   for e in JOURNAL.snapshot(type_="disk.full"))
    finally:
        for vs in servers:
            vs.stop()
        master.stop()


# -- graceful lifecycle -------------------------------------------------------

def test_drain_refuses_new_writes_finishes_inflight(tmp_path):
    """Draining: new writes get 503 + Retry-After while an in-flight
    request admitted BEFORE the drain completes normally; the goodbye
    unregisters the node with no dead-sweep window and the shell's
    cluster.drain drives the whole flow."""
    from seaweedfs_tpu.shell import CommandEnv, run_command
    master = MasterServer(pulse_seconds=60)
    master.start()
    vs = None
    slow_result: list = []
    try:
        d = tmp_path / "vs"
        d.mkdir()
        vs = VolumeServer(master.url(), [str(d)],
                          max_volume_counts=[50], pulse_seconds=60)
        vs.start()
        client = WeedClient(master.url())
        fid = client.upload_data(b"pre-drain")
        vid = t.parse_file_id(fid)[0]

        # An in-flight request admitted BEFORE the drain (a gated slow
        # handler on the real server) must complete: the drain waits
        # for the admission controller's in-flight count to hit zero.
        gate = threading.Event()
        entered = threading.Event()
        vs.server.route("GET", "/slowop", lambda q, b: (
            entered.set(), gate.wait(10.0), {"done": True})[2])

        def slow_call():
            try:
                slow_result.append(
                    rpc.call(f"http://{vs.url()}/slowop",
                             timeout=30.0))
            except Exception as e:  # noqa: BLE001
                slow_result.append(e)

        th = threading.Thread(target=slow_call)
        th.start()
        assert entered.wait(10.0)
        # Release the gate shortly after the drain begins waiting.
        threading.Timer(0.5, gate.set).start()

        env = CommandEnv(master.url())
        t0 = time.monotonic()
        try:
            out = run_command(env, f"cluster.drain -node {vs.url()} "
                                   f"-grace 15")
        finally:
            env.close()
        assert "drained" in out
        # The drain waited for the in-flight request (released at
        # ~0.5s) instead of cutting it off or burning the full grace.
        assert 0.3 <= time.monotonic() - t0 < 10.0
        th.join(timeout=10)
        assert slow_result == [{"done": True}], \
            f"in-flight request failed: {slow_result}"

        # New writes: 503 + Retry-After with a draining message.
        with pytest.raises(rpc.RpcError) as ei:
            rpc.call(f"http://{vs.url()}/{vid},1f00000001", "POST",
                     b"refused")
        assert ei.value.status == 503
        assert "draining" in ei.value.message
        assert ei.value.retry_after is not None

        # The master unregistered the node instantly — and healthz
        # never calls it heartbeat-lost.
        assert all(dn.url() != vs.url()
                   for dn in master.topo.leaves())
        status, doc = rpc.call_status(f"{master.url()}/cluster/healthz")
        assert not any("heartbeat stale" in p
                       for p in doc.get("problems", []))
        # Reads keep being served until the process actually exits.
        assert bytes(rpc.call(f"http://{vs.url()}/{fid}")) \
            == b"pre-drain"
        # Drain events are on the timeline.
        assert JOURNAL.snapshot(type_="node.draining")
        assert JOURNAL.snapshot(type_="node.drained")
    finally:
        if vs is not None:
            vs.stop()
        master.stop()


def _spawn_volume_subprocess(tmp_path, idx: int, port: int,
                             master_port: int):
    d = tmp_path / f"vsdata{idx}"
    d.mkdir(exist_ok=True)
    # Append (not truncate) the per-node log across restarts, and pin
    # the child to the CPU backend regardless of the parent's env — a
    # subprocess dialing real accelerator plumbing would hang past the
    # registration deadline.
    log = open(tmp_path / f"vs{idx}.log", "ab")
    return subprocess.Popen(
        [sys.executable, "-m", "seaweedfs_tpu", "volume",
         f"-port={port}", f"-dir={d}", "-max=50",
         f"-mserver=127.0.0.1:{master_port}",
         "-shutdown.grace=10"],
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
        stdout=log, stderr=subprocess.STDOUT)


def _dead_subprocess_report(tmp_path, procs) -> str | None:
    for i, proc in procs.items():
        if proc.poll() is not None:
            try:
                tail = (tmp_path / f"vs{i}.log").read_bytes()[-2000:]
            except OSError:
                tail = b""
            return (f"volume subprocess {i} died rc={proc.returncode}:"
                    f" {tail.decode(errors='replace')}")
    return None


def test_rolling_restart_zero_acked_loss_zero_client_errors(tmp_path):
    """Acceptance: SIGTERM-cycling every subprocess volume server in
    turn under a continuous upload/read burst yields zero
    acknowledged-write loss and zero client-visible errors (after
    RetryPolicy failover), with the drain visible in the event journal
    and /cluster/healthz never reporting a drained node as
    heartbeat-lost."""
    master = MasterServer(volume_size_limit_mb=64,
                          meta_dir=str(tmp_path / "meta"),
                          pulse_seconds=2)
    master.start()
    # free_port() can hand back duplicates (bind-close races): the
    # three servers need three DISTINCT ports or one dies at bind.
    ports: list[int] = []
    while len(ports) < 3:
        p = rpc.free_port()
        if p not in ports and p != master.server.port:
            ports.append(p)
    procs = {}
    client_errors: list = []
    healthz_violations: list = []
    acked: list[tuple[str, bytes]] = []
    lock = threading.Lock()
    stop = threading.Event()
    try:
        for i, port in enumerate(ports):
            procs[i] = _spawn_volume_subprocess(
                tmp_path, i, port, master.server.port)
        deadline = time.time() + 120
        while len(list(master.topo.leaves())) < 3:
            dead = _dead_subprocess_report(tmp_path, procs)
            if dead:
                raise RuntimeError(dead)
            if time.time() > deadline:
                raise TimeoutError("subprocess servers never registered")
            time.sleep(0.2)

        client = WeedClient(
            master.url(),
            retry_policy=resilience.RetryPolicy(
                max_attempts=8, base_delay=0.05, max_delay=0.5,
                per_attempt_timeout=10.0, total_deadline=30.0))

        def writer(k: int) -> None:
            i = 0
            while not stop.is_set():
                payload = f"rolling {k}-{i} ".encode() * 16
                try:
                    out = client.upload(payload, replication="001")
                except Exception as e:  # noqa: BLE001
                    with lock:
                        client_errors.append(f"upload: {e}")
                    continue
                with lock:
                    acked.append((out["fid"], payload))
                i += 1
                time.sleep(0.01)

        def reader() -> None:
            while not stop.is_set():
                with lock:
                    sample = acked[-20:]
                for fid, payload in sample:
                    try:
                        if client.download(fid) != payload:
                            with lock:
                                client_errors.append(
                                    f"read {fid}: bytes differ")
                    except Exception as e:  # noqa: BLE001
                        with lock:
                            client_errors.append(f"read {fid}: {e}")
                time.sleep(0.05)

        def healthz_watch() -> None:
            while not stop.is_set():
                try:
                    _st, doc = rpc.call_status(
                        f"{master.url()}/cluster/healthz", timeout=5.0)
                    for p in doc.get("problems", []):
                        if "heartbeat stale" in p:
                            healthz_violations.append(p)
                except Exception:  # noqa: BLE001
                    pass
                time.sleep(0.3)

        threads = [threading.Thread(target=writer, args=(k,))
                   for k in range(3)]
        threads.append(threading.Thread(target=reader))
        threads.append(threading.Thread(target=healthz_watch))
        for th in threads:
            th.start()

        # Let the burst get going.
        deadline = time.time() + 60
        while len(acked) < 30 and time.time() < deadline:
            time.sleep(0.1)
        assert len(acked) >= 30, "burst never got going"

        # Roll every server: SIGTERM (graceful drain) -> wait exit ->
        # restart -> wait re-register.
        for i, port in enumerate(ports):
            proc = procs[i]
            os.kill(proc.pid, signal.SIGTERM)
            proc.wait(timeout=60)
            procs[i] = _spawn_volume_subprocess(
                tmp_path, i, port, master.server.port)
            node = f"127.0.0.1:{port}"
            deadline = time.time() + 120
            while all(dn.url() != node
                      for dn in master.topo.leaves()):
                dead = _dead_subprocess_report(tmp_path, {i: procs[i]})
                if dead:
                    raise RuntimeError(dead)
                if time.time() > deadline:
                    raise TimeoutError(f"{node} never re-registered")
                time.sleep(0.2)
            # Keep load flowing a moment between cycles.
            time.sleep(0.5)

        stop.set()
        for th in threads:
            th.join(timeout=60)

        assert not client_errors, \
            f"{len(client_errors)} client-visible errors: " \
            f"{client_errors[:5]}"
        assert not healthz_violations, healthz_violations[:5]
        # Drain visible on the timeline: one node.drained per SIGTERM.
        assert len(JOURNAL.snapshot(type_="node.drained")) >= 3

        # Zero acknowledged-write loss: every acked fid reads back.
        lost = []
        for fid, payload in acked:
            try:
                if client.download(fid) != payload:
                    lost.append((fid, "bytes differ"))
            except Exception as e:  # noqa: BLE001
                lost.append((fid, str(e)))
        assert not lost, \
            f"{len(lost)}/{len(acked)} acked writes lost: {lost[:5]}"
    finally:
        stop.set()
        for proc in procs.values():
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=10)
        master.stop()


# -- live-scrape: the new instruments ----------------------------------------

def test_new_overload_gauges_scrape_clean(tmp_path, monkeypatch):
    """promcheck-gated live scrape: the shed counter, in-flight gauge,
    and reserve-breached gauge all expose on a real volume server and
    parse clean under the promtool-style validator; fault.ls lists the
    two new fault points."""
    monkeypatch.setenv("SEAWEEDFS_TPU_FAULTS_DEBUG", "1")
    master = MasterServer(pulse_seconds=60)
    master.start()
    vs = None
    try:
        d = tmp_path / "vs"
        d.mkdir()
        vs = VolumeServer(master.url(), [str(d)],
                          max_volume_counts=[10], pulse_seconds=60,
                          max_concurrent=1, queue_depth=0)
        vs.start()
        # Force one shed so the labeled counter has a sample.
        gate = threading.Event()
        held = threading.Thread(target=lambda: rpc.call(
            f"http://{vs.url()}/ui", timeout=30.0))
        vs.server.route("GET", "/ui", lambda q, b: (
            gate.wait(5.0), (200, b"", {}))[1])
        held.start()
        time.sleep(0.2)
        with pytest.raises(rpc.RpcError) as ei:
            rpc.call(f"http://{vs.url()}/ui", timeout=5.0)
        assert ei.value.status == 429
        gate.set()
        held.join()
        scrape = bytes(rpc.call(f"http://{vs.url()}/metrics")).decode()
        assert not validate_exposition(scrape), \
            validate_exposition(scrape)[:3]
        for name in ("SeaweedFS_requests_shed_total",
                     "SeaweedFS_inflight_requests",
                     "SeaweedFS_disk_reserve_breached"):
            assert name in scrape, f"{name} missing from scrape"
        # fault.ls lists the new points.
        from seaweedfs_tpu.shell import CommandEnv, run_command
        env = CommandEnv(master.url())
        try:
            out = run_command(env, "fault.ls")
        finally:
            env.close()
        assert "disk.full" in out and "net.slow_client" in out
    finally:
        if vs is not None:
            vs.stop()
        master.stop()
