"""Distributed EC reads: shards spread so no server holds a full set.

Exercises the remote-shard fetch and the on-the-fly reconstruction that
gathers intervals across servers (reference store_ec.go:221-376).
"""

import pytest

from seaweedfs_tpu.cluster import rpc
from seaweedfs_tpu.cluster.client import WeedClient
from seaweedfs_tpu.cluster.master import MasterServer
from seaweedfs_tpu.cluster.volume_server import VolumeServer


@pytest.fixture
def cluster(tmp_path):
    master = MasterServer(volume_size_limit_mb=64,
                          meta_dir=str(tmp_path),
                          # Volume servers here pulse every 60s:
                          # the master's dead-node threshold
                          # (2x its own pulse) must outlast a
                          # slow-machine encode, or the sweep
                          # empties the topology mid-test.
                          pulse_seconds=60)
    master.start()
    servers = []
    for i in range(3):
        d = tmp_path / f"vs{i}"
        d.mkdir()
        vs = VolumeServer(master.url(), [str(d)], pulse_seconds=60)
        vs.start()
        servers.append(vs)
    yield master, servers
    for vs in servers:
        vs.stop()
    master.stop()


def _spread(master, servers, client):
    """Upload objects, EC-encode, spread shards 5/5/4, drop originals."""
    fid0 = client.upload_data(b"payload-zero")
    vid = int(fid0.split(",")[0])
    fids = [fid0] + [client.upload_data(f"payload-{i}".encode())
                     for i in range(1, 25)]
    fids = [f for f in fids if int(f.split(",")[0]) == vid]
    src = client.lookup(vid)[0]["url"]
    rpc.call_json(f"http://{src}/admin/ec/generate", "POST", {"volume": vid})

    spread = {servers[0].url(): [0, 1, 2, 3, 4],
              servers[1].url(): [5, 6, 7, 8, 9],
              servers[2].url(): [10, 11, 12, 13]}
    # Copy everywhere first, then mount and trim — the source must keep its
    # full set until every target has pulled its shards.
    for url, shards in spread.items():
        if url != src:
            rpc.call_json(f"http://{url}/admin/ec/copy_shard", "POST",
                          {"volume": vid, "source": src, "shards": shards,
                           "copy_ecx": True})
    for url, shards in spread.items():
        rpc.call_json(f"http://{url}/admin/ec/mount", "POST",
                      {"volume": vid})
        drop = [s for s in range(14) if s not in shards]
        rpc.call_json(f"http://{url}/admin/ec/delete_shards", "POST",
                      {"volume": vid, "shards": drop})
    rpc.call_json(f"http://{src}/admin/delete_volume", "POST",
                  {"volume": vid})
    # Make sure the master knows every holder (heartbeats already sent on
    # mount/delete; force one more full round for determinism).
    for vs in servers:
        vs._send_heartbeat(full=True)
    return vid, fids


def test_remote_shard_reads(cluster):
    master, servers = cluster
    client = WeedClient(master.url())
    vid, fids = _spread(master, servers, client)
    # Every server can serve every object even though none holds all shards.
    for vs in servers:
        for fid in fids[:5]:
            data = rpc.call(f"http://{vs.url()}/{fid}")
            i = fids.index(fid)
            expect = b"payload-zero" if i == 0 else None
            if expect:
                assert bytes(data) == expect


def test_head_on_ec_volume_checks_existence(cluster):
    """HEAD on an EC volume must be a locate-only probe: 200 for live
    needles, 404 for absent keys — never a blind 200."""
    master, servers = cluster
    client = WeedClient(master.url())
    vid, fids = _spread(master, servers, client)
    url = servers[0].url()
    assert rpc.call(f"http://{url}/{fids[0]}", "HEAD") is not None
    cookie = fids[0].split(",")[1][-8:]
    with pytest.raises(rpc.RpcError) as ei:
        rpc.call(f"http://{url}/{vid},deadbeef{cookie}", "HEAD")
    assert ei.value.status == 404


def test_reconstruction_across_servers(cluster):
    master, servers = cluster
    client = WeedClient(master.url())
    vid, fids = _spread(master, servers, client)
    # Lose one whole server's shards (0-4): 9 shards survive in the
    # cluster... that's < 10, so instead lose only part: drop shards 0-3
    # from server 0, keeping 10 total reachable.
    rpc.call_json(f"http://{servers[0].url()}/admin/ec/delete_shards",
                  "POST", {"volume": vid, "shards": [0, 1, 2, 3]})
    for vs in servers:
        vs._send_heartbeat(full=True)
        vs._ec_loc_cache.clear()
    data = rpc.call(f"http://{servers[0].url()}/{fids[0]}")
    assert bytes(data) == b"payload-zero"
    # And through a server that never held data shards at all:
    data = rpc.call(f"http://{servers[2].url()}/{fids[0]}")
    assert bytes(data) == b"payload-zero"


def test_too_many_lost_cluster_wide(cluster):
    master, servers = cluster
    client = WeedClient(master.url())
    vid, fids = _spread(master, servers, client)
    # Drop 5 shards cluster-wide -> only 9 survive -> reads must fail.
    rpc.call_json(f"http://{servers[0].url()}/admin/ec/delete_shards",
                  "POST", {"volume": vid, "shards": [0, 1, 2, 3, 4]})
    for vs in servers:
        vs._send_heartbeat(full=True)
        vs._ec_loc_cache.clear()
    with pytest.raises(rpc.RpcError):
        rpc.call(f"http://{servers[1].url()}/{fids[0]}")


def test_gzip_needle_through_ec_path(cluster):
    """Needle flags survive EC: a gzip-stored needle read from shards
    decompresses for plain readers and passes through for
    gzip-accepting ones — storage layout never changes read behavior
    (_serve_needle is shared by the replicated and EC ladders)."""
    import gzip as _gzip

    from seaweedfs_tpu.cluster.client import WeedClient
    master, servers = cluster
    client = WeedClient(master.url())
    text = b"compress me through erasure coding\n" * 100
    r = client.upload(text, name="doc.txt")
    assert r["is_compressed"]
    vid = int(r["fid"].split(",")[0])
    src = client.lookup(vid)[0]["url"]
    rpc.call_json(f"http://{src}/admin/ec/generate", "POST",
                  {"volume": vid})
    rpc.call_json(f"http://{src}/admin/ec/mount", "POST",
                  {"volume": vid})
    rpc.call_json(f"http://{src}/admin/delete_volume", "POST",
                  {"volume": vid})
    for vs in servers:
        vs._send_heartbeat(full=True)
    # plain read through the EC ladder: decompressed
    assert rpc.call(f"http://{src}/{r['fid']}") == text
    # gzip-accepting read: stored bytes pass through
    resp, conn = rpc._request(f"http://{src}/{r['fid']}", "GET",
                              None, 10.0,
                              req_headers={"Accept-Encoding": "gzip"})
    raw = resp.read()
    rpc._finish(conn, resp)
    assert resp.getheader("content-encoding") == "gzip"
    assert _gzip.decompress(raw) == text
