"""Cloud replication queues/sinks without SDKs.

Reference: weed/notification/aws_sqs (SQS query API pub/sub),
weed/replication/sink/{gcssink,azuresink,b2sink}.  Fake local endpoints
stand in for the cloud; the SQS test VERIFIES the sig v4 signature
server-side with the same core the S3 gateway uses, so a signing
regression fails loudly rather than structurally.
"""

import base64
import hashlib
import hmac
import json
import threading

import pytest

from seaweedfs_tpu.cluster import rpc
from seaweedfs_tpu.replication.notification import (SqsQueue,
                                                    queue_for_spec)
from seaweedfs_tpu.replication.sink import (AzureSink, B2Sink, GcsSink,
                                            S3Sink, sink_for_spec)

AK, SK = "AKIDEXAMPLE", "wJalrXUtnFEMI/K7MDENG+bPxRfiCYEXAMPLEKEY"


def _verify_sigv4(query: dict, body: bytes, service: str) -> bool:
    """Recompute the signature from the received request exactly as an
    AWS endpoint would."""
    from seaweedfs_tpu.s3api.auth import compute_signature_v4
    h = query["_headers"]
    auth = h.get("authorization", "")
    if not auth.startswith("AWS4-HMAC-SHA256"):
        return False
    parts = dict(p.strip().split("=", 1)
                 for p in auth.split(" ", 1)[1].split(","))
    scope = parts["Credential"].split("/", 1)[1]
    if scope.split("/")[2] != service:
        return False
    signed = parts["SignedHeaders"].split(";")
    expect = compute_signature_v4(
        query["_method"], query["_path"], query.get("_raw_query", ""),
        h, signed, h.get("x-amz-content-sha256", ""),
        h.get("x-amz-date", ""), scope, SK)
    return hmac.compare_digest(expect, parts["Signature"])


@pytest.fixture
def endpoint():
    """Capture-everything fake cloud endpoint."""
    srv = rpc.JsonHttpServer("127.0.0.1", 0, pass_headers=True)
    seen = []
    canned = {"body": b"<ok/>"}

    def handler(path, query, body):
        query["_path"] = path
        seen.append((path, query, bytes(body or b"")))
        return (200, canned["body"],
                {"Content-Type": "application/xml"})

    for m in ("GET", "POST", "PUT", "DELETE"):
        srv.prefix_route(m, "/", handler)
    srv.start()
    yield srv, seen, canned
    srv.stop()


# -- SQS -------------------------------------------------------------------

def test_sqs_publish_signs_and_sends(endpoint):
    srv, seen, _ = endpoint
    q = SqsQueue(f"http://127.0.0.1:{srv.port}/12345/events",
                 access_key=AK, secret_key=SK, region="us-east-1")
    q.publish("/buckets/b/x.txt", {"op": "create"})
    q.flush(timeout=10.0)
    path, query, body = seen[0]
    assert path == "/12345/events"
    params = dict(p.split("=", 1) for p in
                  body.decode().replace("+", " ").split("&"))
    assert params["Action"] == "SendMessage"
    import urllib.parse
    doc = json.loads(urllib.parse.unquote(params["MessageBody"]))
    assert doc["key"] == "/buckets/b/x.txt"
    assert doc["message"] == {"op": "create"}
    assert _verify_sigv4(query, body, "sqs"), "sig v4 must verify"


def test_sqs_consume_delivers_then_deletes(endpoint):
    srv, seen, canned = endpoint
    msg = json.dumps({"key": "/k", "message": {"n": 1}})
    canned["body"] = f"""<ReceiveMessageResponse>
      <ReceiveMessageResult><Message>
        <MessageId>m1</MessageId>
        <ReceiptHandle>rh-42</ReceiptHandle>
        <Body>{msg.replace('"', '&quot;')}</Body>
      </Message></ReceiveMessageResult>
    </ReceiveMessageResponse>""".encode()
    q = SqsQueue(f"http://127.0.0.1:{srv.port}/12345/events",
                 access_key=AK, secret_key=SK)
    got = []

    def fn(key, message):
        # after the first delivery, make the queue read empty
        canned["body"] = b"<ReceiveMessageResponse/>"
        got.append((key, message))

    q.consume(fn)
    assert got == [("/k", {"n": 1})]
    actions = []
    for _p, _q, body in seen:
        params = dict(p.split("=", 1) for p in
                      body.decode().split("&") if "=" in p)
        actions.append((params.get("Action"),
                        params.get("ReceiptHandle")))
    assert ("DeleteMessage", "rh-42") in actions
    # delete came AFTER the delivery receive
    assert actions[0][0] == "ReceiveMessage"


def test_sqs_publish_never_blocks_caller():
    """The filer publishes under its meta-log lock: a dead/black-holed
    endpoint must not stall the caller — sends ride the async spool."""
    import time
    q = SqsQueue("http://10.255.255.1:9/1/q", access_key=AK,
                 secret_key=SK)
    t0 = time.perf_counter()
    for i in range(50):
        q.publish(f"/k{i}", {"n": i})
    assert time.perf_counter() - t0 < 0.5
    q.close()


def test_queue_spec_routing(tmp_path):
    q = queue_for_spec("sqs://h/1/q", access_key=AK, secret_key=SK,
                       http_endpoint=True)
    assert isinstance(q, SqsQueue) and q.queue_url == "http://h/1/q"
    with pytest.raises(NotImplementedError):
        queue_for_spec("gocdk://x")


# -- sinks -----------------------------------------------------------------

def test_gcs_b2_are_s3_compatible(endpoint):
    srv, seen, _ = endpoint
    for sink in (GcsSink("bkt", "/backup", AK, SK,
                         endpoint=f"http://127.0.0.1:{srv.port}"),
                 B2Sink("bkt", "/backup", AK, SK,
                        endpoint=f"http://127.0.0.1:{srv.port}")):
        seen.clear()
        sink.create_entry("a/b.txt", {"attributes": {"mime":
                                                     "text/plain"}},
                          b"hello")
        path, query, body = seen[0]
        assert path == "/bkt/backup/a/b.txt"
        assert body == b"hello"
        assert _verify_sigv4(query, body, "s3")
    # default endpoints point at the real services
    assert "storage.googleapis.com" in GcsSink("b").endpoint
    assert "backblazeb2.com" in B2Sink("b").endpoint


def test_azure_sharedkey_put_delete(endpoint):
    srv, seen, _ = endpoint
    key = base64.b64encode(b"0" * 64).decode()
    sink = AzureSink("myacct", "cont", "/backup", account_key=key,
                     endpoint=f"http://127.0.0.1:{srv.port}")
    sink.create_entry("a/b.txt",
                      {"attributes": {"mime": "text/plain"}}, b"data!")
    path, query, body = seen[0]
    assert path == "/cont/backup/a/b.txt" and body == b"data!"
    h = query["_headers"]
    assert h["x-ms-blob-type"] == "BlockBlob"
    assert h["x-ms-version"] == AzureSink.API_VERSION
    auth = h["authorization"]
    assert auth.startswith("SharedKey myacct:")
    # independent recompute from the Azure SharedKey spec
    canon = "\n".join([
        "PUT", "", "", "5", "", "text/plain", "",
        "", "", "", "", "",
        f"x-ms-blob-type:BlockBlob",
        f"x-ms-date:{h['x-ms-date']}",
        f"x-ms-version:{h['x-ms-version']}",
    ]) + "\n/myacct/cont/backup/a/b.txt"
    expect = base64.b64encode(
        hmac.new(base64.b64decode(key), canon.encode(),
                 hashlib.sha256).digest()).decode()
    assert auth == f"SharedKey myacct:{expect}"
    # delete
    seen.clear()
    sink.delete_entry("a/b.txt", False)
    path, query, body = seen[0]
    assert query["_method"] == "DELETE"
    assert path == "/cont/backup/a/b.txt"


def test_b2_signs_with_its_region(endpoint):
    """B2 validates the credential-scope region against the endpoint
    region — signing everything us-east-1 would 403 on a real bucket."""
    srv, seen, _ = endpoint
    sink = B2Sink("bkt", "/", AK, SK, region="eu-central-003",
                  endpoint=f"http://127.0.0.1:{srv.port}")
    sink.create_entry("x", {"attributes": {}}, b"1")
    _path, query, _body = seen[0]
    auth = query["_headers"]["authorization"]
    cred = auth.split("Credential=")[1].split(",")[0]
    assert cred.split("/")[2] == "eu-central-003"
    assert _verify_sigv4(query, b"1", "s3")


def test_azure_signs_encoded_path(endpoint):
    """The canonicalized resource must use the percent-encoded URI path
    (what the service receives); arbitrary filer names need encoding."""
    srv, seen, _ = endpoint
    key = base64.b64encode(b"0" * 64).decode()
    sink = AzureSink("acct", "cont", "/", account_key=key,
                     endpoint=f"http://127.0.0.1:{srv.port}")
    sink.create_entry("dir with space/café#1.txt",
                      {"attributes": {}}, b"z")
    path, query, _body = seen[0]
    h = query["_headers"]
    import urllib.parse
    encoded = urllib.parse.quote("dir with space/café#1.txt")
    assert path == "/cont/" + encoded
    canon = "\n".join([
        "PUT", "", "", "1", "", "application/octet-stream", "",
        "", "", "", "", "",
        "x-ms-blob-type:BlockBlob",
        f"x-ms-date:{h['x-ms-date']}",
        f"x-ms-version:{h['x-ms-version']}",
    ]) + f"\n/acct/cont/{encoded}"
    expect = base64.b64encode(
        hmac.new(base64.b64decode(key), canon.encode(),
                 hashlib.sha256).digest()).decode()
    assert h["authorization"] == f"SharedKey acct:{expect}"


def test_sqs_poison_message_deleted_not_looping(endpoint):
    """A well-formed-JSON body without the {key, message} envelope (a
    foreign publisher) must be deleted, not crash consume forever."""
    srv, seen, canned = endpoint
    canned["body"] = b"""<R><Message>
      <ReceiptHandle>poison-1</ReceiptHandle>
      <Body>"just a string"</Body></Message></R>"""
    q = SqsQueue(f"http://127.0.0.1:{srv.port}/1/q",
                 access_key=AK, secret_key=SK)
    got = []

    def spy(*a):
        got.append(a)

    # first receive returns the poison message; flip to empty after the
    # DeleteMessage so consume() terminates
    orig_call = q._call

    def call(params):
        if params["Action"] == "DeleteMessage":
            canned["body"] = b"<R/>"
        return orig_call(params)

    q._call = call
    q.consume(spy)  # must not raise
    assert got == []
    deletes = [p for _pa, _q, body in seen
               for p in [dict(x.split("=", 1)
                              for x in body.decode().split("&")
                              if "=" in x)]
               if p.get("Action") == "DeleteMessage"]
    assert deletes and deletes[0]["ReceiptHandle"] == "poison-1"


def test_sink_spec_routing():
    assert isinstance(sink_for_spec("gcs://bkt/d", access_key=AK,
                                    secret_key=SK), GcsSink)
    assert isinstance(sink_for_spec("b2://bkt/d"), B2Sink)
    s = sink_for_spec("azure://acct/cont/d")
    assert isinstance(s, AzureSink) and s.account == "acct" \
        and s.container == "cont"
    assert isinstance(sink_for_spec("s3://h:1/bkt/d"), S3Sink)


# -- full path: filer events -> SQS -> replicator -> local sink ------------

def test_replicate_through_sqs(endpoint, tmp_path):
    """The replicate worker is queue-agnostic: events published to a
    (fake) SQS queue drive a sink exactly like the in-process queues."""
    from seaweedfs_tpu.replication.sink import LocalSink
    srv, seen, canned = endpoint
    q = SqsQueue(f"http://127.0.0.1:{srv.port}/1/q",
                 access_key=AK, secret_key=SK)
    q.publish("/x.txt", {"event": "create"})
    q.flush(timeout=10.0)
    # replay what the fake captured as a ReceiveMessage response
    params = dict(p.split("=", 1) for p in
                  seen[0][2].decode().split("&") if "=" in p)
    import urllib.parse
    body_json = urllib.parse.unquote_plus(params["MessageBody"])
    canned["body"] = f"""<R><Message><ReceiptHandle>r1</ReceiptHandle>
      <Body>{body_json.replace('"', '&quot;')}</Body>
      </Message></R>""".encode()
    got = []

    def fn(key, message):
        canned["body"] = b"<R/>"
        got.append((key, message))

    q.consume(fn)
    assert got == [("/x.txt", {"event": "create"})]
