"""Tenancy & QoS plane: quota grammar, usage ledger, DRR fairness,
token buckets, per-tenant chunk-cache caps, noisy-neighbor chaos, and
the hard-quota end-to-end (403 at master assign AND the filer/S3 front
doors, usage surviving a master restart via the tenants.json snapshot,
delete-driven reclaim restoring writability).
"""

from __future__ import annotations

import http.client
import json
import time
import urllib.parse

import pytest

from seaweedfs_tpu.cluster import rpc
from seaweedfs_tpu.cluster.master import MasterServer
from seaweedfs_tpu.cluster.volume_server import VolumeServer
from seaweedfs_tpu.filer.server import FilerServer
from seaweedfs_tpu.stats.promcheck import validate_exposition
from seaweedfs_tpu.storage.chunk_cache import FilerChunkCache
from seaweedfs_tpu.tenancy import (DrrQueue, QuotaPolicy, TenantBuckets,
                                   TenantUsage, TokenBucket, UsageRollup,
                                   load_rules, parse_rules_text,
                                   parse_rules_toml, parse_size)

pytestmark = pytest.mark.tenancy


# -- quota rule grammar ------------------------------------------------------

def test_parse_size():
    assert parse_size("1024") == 1024
    assert parse_size("64MB") == 64 << 20
    assert parse_size("1.5KB") == 1536
    assert parse_size("2GiB") == 2 << 30
    with pytest.raises(ValueError):
        parse_size("twelve")


def test_rules_text_grammar():
    policy = parse_rules_text(
        "# comment\n"
        "alice max_bytes=1GB max_objects=100 weight=4\n"
        "bob   max_rps=10 max_mbps=8 soft=true\n"
        "*     max_bytes=10GB\n")
    assert len(policy) == 3
    r = policy.rule_for("alice")
    assert r.max_bytes == 1 << 30 and r.max_objects == 100
    assert policy.weight_for("alice") == 4.0
    assert policy.rule_for("bob").soft is True
    # wildcard catches everyone else; empty tenant never matches
    assert policy.rule_for("mallory").max_bytes == 10 << 30
    assert policy.rule_for("") is None


def test_rules_text_errors():
    with pytest.raises(ValueError, match="line 1"):
        parse_rules_text("alice max_bytes=nope\n")
    with pytest.raises(ValueError, match="unknown rule keys"):
        parse_rules_text("alice max_bananas=3\n")
    with pytest.raises(ValueError):
        parse_rules_text("alice\n")  # a rule needs at least one limit


def test_rules_toml(tmp_path):
    p = tmp_path / "tenants.toml"
    p.write_text('[[rule]]\ntenant = "alice"\nmax_bytes = "2MB"\n'
                 'weight = 2.0\n'
                 '[[rule]]\ntenant = "*"\nmax_rps = 5\n')
    policy = load_rules(str(p))
    assert policy.rule_for("alice").max_bytes == 2 << 20
    assert policy.rule_for("zoe").max_rps == 5.0
    assert parse_rules_toml(p.read_text()).weight_for("alice") == 2.0


# -- token buckets -----------------------------------------------------------

def test_token_bucket_admit_and_retry():
    b = TokenBucket(rate=10.0, burst=2.0)
    assert b.try_take() == 0.0
    assert b.try_take() == 0.0
    retry = b.try_take()  # bucket drained
    assert retry > 0.0
    time.sleep(retry + 0.02)
    assert b.try_take() == 0.0  # refilled


def test_tenant_buckets_scope():
    policy = parse_rules_text("flood max_rps=2\n")
    tb = TenantBuckets(policy)
    # ruleless tenants and untenanted traffic pass free, always
    for _ in range(50):
        assert tb.admit("calm") == 0.0
        assert tb.admit("") == 0.0
    verdicts = [tb.admit("flood") for _ in range(20)]
    assert any(v > 0.0 for v in verdicts)
    assert "flood" in tb.snapshot()["rps_tenants"]


# -- deficit round robin -----------------------------------------------------

def test_drr_weight_proportionality():
    weights = {"heavy": 3.0, "light": 1.0}
    q = DrrQueue(weight_for=lambda t: weights.get(t, 1.0))
    for _ in range(60):
        q.push("heavy")
        q.push("light")
    served: list[str] = []
    for _ in range(40):
        served.append(q.pop().tenant)
    heavy = served.count("heavy")
    light = served.count("light")
    # 3:1 weights -> ~30/10 of the first 40 serves; allow slack for
    # deficit carry at the window edge.
    assert heavy == pytest.approx(30, abs=3)
    assert light == pytest.approx(10, abs=3)
    assert heavy + light == 40


def test_drr_skips_cancelled_and_drains():
    q = DrrQueue()
    a = q.push("a")
    q.push("a")
    b = q.push("b")
    q.discard(a)
    got = [q.pop(), q.pop()]
    assert all(w is not None and not w.cancelled for w in got)
    assert b in got
    assert q.pop() is None
    assert len(q) == 0


# -- usage accounting --------------------------------------------------------

def test_tenant_usage_ledger():
    u = TenantUsage()
    u.add("alice", "pics", 1000, 2, vid=7)
    u.add("alice", "pics", 500, 1, vid=8)
    u.add("bob", "", 100, 1, vid=7)
    rows = {(r["tenant"], r["collection"]): r
            for r in u.heartbeat_view()}
    assert rows[("alice", "pics")]["bytes"] == 1500
    assert rows[("alice", "pics")]["objects"] == 3
    u.remove("alice", "pics", 500, 1, vid=8)
    assert u.stored_totals()["alice"]["bytes"] == 1000
    # dropping a volume sheds exactly that volume's contribution
    u.drop_volume(7)
    totals = u.stored_totals()
    assert "bob" not in totals
    assert totals.get("alice", {}).get("bytes", 0) == 0 or \
        "alice" not in totals
    # over-removal clamps at zero instead of going negative
    u.add("carol", "", 10, 1, vid=9)
    u.remove("carol", "", 9999, 99, vid=9)
    assert "carol" not in u.stored_totals()


def test_usage_rollup_snapshot_roundtrip(tmp_path):
    path = str(tmp_path / "tenants.json")
    r = UsageRollup(path)
    r.update_node("vs1", [{"tenant": "alice", "collection": "",
                           "bytes": 2048, "objects": 2}])
    r.update_node("vs2", [{"tenant": "alice", "collection": "",
                           "bytes": 1024, "objects": 1}])
    assert r.usage_for("alice") == (3072, 3)
    r.save(force=True)
    # a fresh rollup (master restart) restores the totals from disk
    r2 = UsageRollup(path)
    assert r2.usage_for("alice") == (3072, 3)
    assert r2.totals()["alice"]["objects"] == 3
    # absolute node reports REPLACE: a shrunken re-report shrinks usage
    r2.update_node("vs1", [{"tenant": "alice", "collection": "",
                            "bytes": 100, "objects": 1}])
    assert r2.usage_for("alice") == (1124, 2)


# -- per-tenant chunk-cache caps ---------------------------------------------

def test_chunk_cache_tenant_cap():
    c = FilerChunkCache(max_bytes=1 << 20)
    c.configure_tenant_cap(3000)
    blob = b"x" * 1000
    for i in range(5):
        c.get_or_fetch(f"scan,{i}", lambda: blob, tenant="scanner")
    # victim's chunks went in before the scanner blew its cap — they
    # must survive (the scanner evicts its OWN oldest, not the LRU)
    c2 = FilerChunkCache(max_bytes=1 << 20)
    c2.configure_tenant_cap(3000)
    c2.get_or_fetch("victim,1", lambda: blob, tenant="victim")
    for i in range(5):
        c2.get_or_fetch(f"scan,{i}", lambda: blob, tenant="scanner")
    stats = c2.stats()
    assert stats["tenants"]["scanner"] <= 3000
    assert stats["tenants"]["victim"] == 1000
    assert stats["tenant_evictions"] >= 2
    hits = c2.hit_bytes
    c2.get_or_fetch("victim,1", lambda: (_ for _ in ()).throw(
        AssertionError("victim chunk was evicted")), tenant="victim")
    assert c2.hit_bytes == hits + 1000
    # reset() clears the tenant plane too (conftest hermeticity)
    c2.reset()
    assert c2.stats()["tenants"] == {}
    assert c2.tenant_max_bytes == 0


# -- live-cluster helpers ----------------------------------------------------

def _http(url: str, method: str = "GET", body: bytes = b"",
          headers: dict | None = None):
    """Raw request so tests can inspect status + headers + body of
    error answers (rpc.call raises on non-2xx)."""
    u = urllib.parse.urlsplit(url)
    conn = http.client.HTTPConnection(u.hostname, u.port, timeout=10)
    try:
        conn.request(method, u.path + (f"?{u.query}" if u.query else ""),
                     body=body or None, headers=headers or {})
        resp = conn.getresponse()
        return resp.status, dict(resp.getheaders()), resp.read()
    finally:
        conn.close()


def _wait_until(fn, timeout=10.0, every=0.1, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return
        time.sleep(every)
    raise AssertionError(f"timed out waiting for {what}")


@pytest.fixture()
def tenant_cluster(tmp_path):
    rules = tmp_path / "tenants.txt"
    rules.write_text("alice max_bytes=1KB\n"
                     "flood max_rps=5 weight=1\n"
                     "victim weight=4 max_bytes=1GB\n")
    m = MasterServer(meta_dir=str(tmp_path / "m"),
                     tenant_rules=str(rules))
    m.start()
    vs = VolumeServer(m.url(), [str(tmp_path / "vs")], pulse_seconds=1,
                      tenant_rules=str(rules))
    vs.start()
    f = FilerServer(m.url(), store_path=str(tmp_path / "filer.db"),
                    tenant_rules=str(rules))
    f._quota_cache_ttl = 0.2  # keep the E2E fast
    f.start()
    try:
        yield m, vs, f, rules
    finally:
        import contextlib
        # the restart E2E stops the master itself; teardown tolerates
        # an already-stopped role
        for srv in (f, vs, m):
            with contextlib.suppress(Exception):
                srv.stop()


# -- hard-quota end-to-end ---------------------------------------------------

def test_hard_quota_e2e(tenant_cluster, tmp_path):
    m, vs, f, _rules = tenant_cluster
    hdr = {"X-Weed-Tenant": "alice"}
    vurl = f"http://{vs.url()}"

    # 1. fill past the 1KB quota (first write is under, so it lands)
    out = rpc.call(f.url() + "/a.bin", "POST", b"x" * 2048, headers=hdr)
    assert out["size"] == 2048
    _wait_until(
        lambda: rpc.call(m.url() + "/cluster/tenants")
        ["tenants"].get("alice", {}).get("bytes", 0) >= 2048,
        what="heartbeat usage rollup")

    # 2a. master assign rejects with 403 QuotaExceeded
    st, _h, body = _http(m.url() + "/dir/assign", headers=hdr)
    assert st == 403 and b"QuotaExceeded" in body
    # ...and emits the quota.exceeded event
    evs = rpc.call(m.url() + "/debug/events?type=quota.exceeded")
    assert any(e.get("attrs", {}).get("tenant") == "alice"
               for e in evs["events"])

    # 2b. the filer front door rejects before moving chunk bytes
    time.sleep(0.3)  # let the filer's quota cache expire
    st, _h, body = _http(f.url() + "/b.bin", "POST", b"y" * 10,
                         headers=hdr)
    assert st == 403 and b"QuotaExceeded" in body
    # other tenants are untouched
    assert rpc.call(f.url() + "/c.bin", "POST", b"z" * 10,
                    headers={"X-Weed-Tenant": "bob"})["size"] == 10

    # 3. delete reclaims; the next heartbeat drops usage and writes
    #    resume (vacuum-independent: deletes decrement the live ledger)
    rpc.call(f.url() + "/a.bin", "DELETE", headers=hdr)
    _wait_until(
        lambda: rpc.call(m.url() + "/cluster/tenants")
        ["tenants"].get("alice", {}).get("bytes", 1) < 1024,
        what="usage reclaim after delete")
    st, _h, _b = _http(m.url() + "/dir/assign", headers=hdr)
    assert st == 200
    assert rpc.call(f.url() + "/d.bin", "POST", b"w" * 100,
                    headers=hdr)["size"] == 100

    # 4. the volume-side ledger and /debug/tenants agree
    dt = rpc.call(vurl + "/debug/tenants")
    stored = {r["tenant"]: r["bytes"] for r in dt["stored"]}
    assert stored.get("alice", 0) == 100

    # 5. usage survives a master restart via <mdir>/tenants.json: a
    #    FRESH master on the same meta_dir — with no volume heartbeats
    #    arriving — serves the snapshotted rollup immediately
    _wait_until(
        lambda: rpc.call(m.url() + "/cluster/tenants")
        ["tenants"].get("alice", {}).get("bytes", 0) >= 100,
        what="rollup of the resumed write")
    m.stop()
    assert (tmp_path / "m" / "tenants.json").exists()
    m2 = MasterServer(meta_dir=str(tmp_path / "m"))
    m2.start()
    try:
        doc = rpc.call(m2.url() + "/cluster/tenants")
        assert doc["tenants"]["alice"]["bytes"] >= 100
    finally:
        m2.stop()


# -- noisy-neighbor chaos ----------------------------------------------------

def test_noisy_neighbor_throttle_and_victim_p99(tenant_cluster):
    m, vs, f, _rules = tenant_cluster
    vurl = f"http://{vs.url()}"
    # seed one object the victim will read
    fid = rpc.call(m.url() + "/dir/assign")
    loc, fidstr = fid["url"], fid["fid"]
    rpc.call(f"http://{loc}/{fidstr}", "POST", b"v" * 4096,
             headers={"X-Weed-Tenant": "victim"})

    before = rpc.tenant_throttled_total.value(tenant="flood")
    # flood: 10x its 5 req/s quota for ~1s
    flood_hdr = {"X-Weed-Tenant": "flood"}
    shed = ok = 0
    retry_after = None
    t_end = time.monotonic() + 1.0
    while time.monotonic() < t_end:
        st, h, _b = _http(f"http://{loc}/{fidstr}", headers=flood_hdr)
        if st == 429:
            shed += 1
            retry_after = h.get("Retry-After") or retry_after
        else:
            ok += 1
        time.sleep(0.02)  # ~50 req/s offered
    assert shed > 0, "flood was never throttled"
    assert retry_after is not None and float(retry_after) > 0.0
    # the flood's excess is counted, by tenant
    assert rpc.tenant_throttled_total.value(tenant="flood") \
        >= before + shed

    # victim p99 holds while the flood continues
    lat: list[float] = []
    victim_hdr = {"X-Weed-Tenant": "victim"}
    for _ in range(40):
        _http(f"http://{loc}/{fidstr}", headers=flood_hdr)
        t0 = time.perf_counter()
        st, _h, body = _http(f"http://{loc}/{fidstr}",
                             headers=victim_hdr)
        lat.append(time.perf_counter() - t0)
        assert st == 200 and len(body) == 4096
    lat.sort()
    p99 = lat[int(len(lat) * 0.99) - 1]
    assert p99 < 0.5, f"victim p99 {p99 * 1000:.1f}ms under flood"

    # the throttle episode is on the cluster timeline
    evs = rpc.call(vurl + "/debug/events?type=tenant.throttled")
    assert any(e.get("attrs", {}).get("tenant") == "flood"
               for e in evs["events"])


# -- attribution: the filer proxy leg names the real principal ---------------

def test_hotkey_tenant_attribution_via_filer(tenant_cluster):
    m, vs, f, _rules = tenant_cluster
    hdr = {"X-Weed-Tenant": "victim"}
    rpc.call(f.url() + "/hot.bin", "POST", b"h" * 512, headers=hdr)
    for _ in range(3):
        assert len(rpc.call(f.url() + "/hot.bin", headers=hdr)) == 512
    hot = rpc.call(f"http://{vs.url()}/debug/hot")
    reads = {r["key"] for r in
             hot["dimensions"]["tenant"]["read"]["top"]}
    writes = {r["key"] for r in
              hot["dimensions"]["tenant"]["write"]["top"]}
    # the proxy leg forwarded the ORIGINATING principal: the volume
    # server attributes to the tenant, not to "the filer"
    assert "victim" in reads and "victim" in writes
    clients = {r["key"] for r in
               hot["dimensions"]["client"]["read"]["top"]}
    assert clients, "client dimension lost on the proxy leg"


# -- S3 gateway error shape --------------------------------------------------

def test_s3_quota_and_slowdown_xml(tenant_cluster):
    m, vs, f, _rules = tenant_cluster
    from seaweedfs_tpu.s3api.server import S3ApiServer
    s3 = S3ApiServer(f.url())
    s3.start()
    try:
        _http(s3.url() + "/qbucket", "PUT")
        # drive alice over quota through the gateway, then PUT again
        st, _h, _b = _http(s3.url() + "/qbucket/big", "PUT", b"x" * 2048,
                           headers={"X-Weed-Tenant": "alice"})
        assert st == 200
        _wait_until(
            lambda: rpc.call(m.url() + "/cluster/tenants")
            ["tenants"].get("alice", {}).get("bytes", 0) >= 2048,
            what="rollup of the s3 upload")
        time.sleep(0.3)  # filer quota cache TTL
        st, h, body = _http(s3.url() + "/qbucket/more", "PUT", b"y",
                            headers={"X-Weed-Tenant": "alice"})
        assert st == 403
        assert b"<Code>QuotaExceeded</Code>" in body
        assert h.get("Content-Type") == "application/xml"
        # rate-limit throttle surfaces as AWS SlowDown with Retry-After
        got_slow = False
        for _ in range(40):
            st, h, body = _http(s3.url() + "/qbucket/f", "PUT", b"z",
                                headers={"X-Weed-Tenant": "flood"})
            if st == 503 and b"<Code>SlowDown</Code>" in body:
                assert float(h.get("Retry-After", "0")) > 0.0
                got_slow = True
                break
        assert got_slow, "flood was never told to SlowDown"
    finally:
        s3.stop()


# -- shell verbs -------------------------------------------------------------

def test_shell_tenant_verbs(tenant_cluster):
    m, vs, f, _rules = tenant_cluster
    import seaweedfs_tpu.shell  # noqa: F401 — registers verbs
    from seaweedfs_tpu.shell.command_tenant import (ClusterTenants,
                                                    TenantLs, TenantQuota)
    from seaweedfs_tpu.shell.env import CommandEnv
    rpc.call(f.url() + "/s.bin", "POST", b"s" * 700,
             headers={"X-Weed-Tenant": "alice"})
    _wait_until(
        lambda: rpc.call(m.url() + "/cluster/tenants")
        ["tenants"].get("alice", {}).get("bytes", 0) >= 700,
        what="rollup for shell verbs")
    env = CommandEnv(m.url(), filer_url=f.url())
    out = ClusterTenants().do([], env)
    assert "alice" in out and "RULE" in out
    out = TenantLs().do([], env)
    assert "alice" in out
    out = TenantQuota().do(["alice"], env)
    assert "alice" in out and "KB" in out


# -- promcheck: the new instruments scrape clean on every role ---------------

def test_promcheck_tenancy_instruments(tenant_cluster):
    m, vs, f, _rules = tenant_cluster
    rpc.call(f.url() + "/p.bin", "POST", b"p" * 300,
             headers={"X-Weed-Tenant": "alice"})
    _wait_until(
        lambda: rpc.call(m.url() + "/cluster/tenants")
        ["tenants"].get("alice", {}).get("bytes", 0) >= 300,
        what="rollup before the scrape")
    mtext = bytes(rpc.call(m.url() + "/metrics")).decode()
    vtext = bytes(rpc.call(f"http://{vs.url()}/metrics")).decode()
    ftext = f.metrics_registry.expose()
    for text, who in ((mtext, "master"), (vtext, "volume"),
                      (ftext, "filer")):
        assert validate_exposition(text) == [], f"{who} scrape dirty"
        assert "SeaweedFS_admission_queue_depth" in text, who
        assert "SeaweedFS_tenant_throttled_total" in text, who
    assert "SeaweedFS_master_tenant_bytes" in mtext
    assert 'tenant="alice"' in mtext
    assert "SeaweedFS_tenant_stored_bytes" in vtext
