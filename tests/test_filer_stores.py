"""Networked FilerStore backends: RESP redis wire client + the
abstract_sql dialect layer (VERDICT r4 missing #1).

Conformance coverage lives in test_filer.py (the `store` fixture runs
the full contract over redis + both SQL dialects); these tests pin the
wire/dialect details: RESP framing, AUTH/SELECT, TTL-backed expiry,
reconnect-once, the reference dirhash algorithm, and the verbatim
mysql/postgres SQL texts."""

import time

import pytest

from _mini_redis import MiniRedis
from seaweedfs_tpu.filer.abstract_sql import (MysqlDialect,
                                              PostgresDialect,
                                              hash_string_to_long,
                                              sqlite_validating_store)
from seaweedfs_tpu.filer.entry import Attributes, Entry
from seaweedfs_tpu.filer.filerstore import NotFound
from seaweedfs_tpu.filer.redis_store import (DIR_LIST_MARKER,
                                             RedisStore, RespClient,
                                             RespError)


@pytest.fixture
def mini():
    m = MiniRedis()
    yield m
    m.close()


def test_resp_wire_shapes(mini):
    """Entry insert produces the reference's exact key scheme: meta at
    the full path, name SADD'ed into `dir + \\x00`
    (universal_redis_store.go:36-60)."""
    s = RedisStore("127.0.0.1", mini.port)
    s.insert_entry(Entry(path="/d/file.txt"))
    cmds = [c for c in mini.commands_seen if c[0] in (b"SET", b"SADD")]
    assert cmds[0][:2] == [b"SET", b"/d/file.txt"]
    assert cmds[1] == [b"SADD", ("/d" + DIR_LIST_MARKER).encode(),
                       b"file.txt"]
    assert s.find_entry("/d/file.txt").path == "/d/file.txt"
    s.close()


def test_resp_auth_and_select():
    m = MiniRedis(password="hunter2")
    try:
        # wrong password -> RespError from AUTH
        bad = RespClient("127.0.0.1", m.port, password="nope")
        with pytest.raises(RespError):
            bad.call("PING")
        s = RedisStore("127.0.0.1", m.port, password="hunter2",
                       database=2)
        s.kv_put("k", b"v")
        assert s.kv_get("k") == b"v"
        assert m.dbs.get(2, {}).get(b"kv:k") == b"v"  # SELECT honored
        s.close()
    finally:
        m.close()


def test_resp_entry_ttl_expires(mini):
    """TtlSec rides `SET ... EX` (the reference passes the ttl duration
    to Client.Set) — an expired entry disappears server-side."""
    s = RedisStore("127.0.0.1", mini.port)
    e = Entry(path="/t/x", attributes=Attributes(ttl_sec=1))
    s.insert_entry(e)
    assert s.find_entry("/t/x").path == "/t/x"
    mini.expiry[(0, b"/t/x")] = time.time() - 1  # fast-forward
    with pytest.raises(NotFound):
        s.find_entry("/t/x")
    s.close()


def test_resp_reconnects_once(mini):
    s = RedisStore("127.0.0.1", mini.port)
    s.kv_put("a", b"1")
    # Kill the client's socket under it: next call must redial.
    s.client._sock.close()
    assert s.kv_get("a") == b"1"
    s.close()


def test_dirhash_matches_reference_algorithm():
    """util.HashStringToLong (bytes.go:73): md5 first 8 bytes as a
    signed big-endian int64 — checked against hand-computed values."""
    import hashlib
    for sample in ("/", "/topics", "/buckets/b1", "/etc/kv"):
        b = hashlib.md5(sample.encode()).digest()
        v = int.from_bytes(b[:8], "big", signed=True)
        assert hash_string_to_long(sample) == v
    # Must be able to go negative (signed int64, BIGINT column).
    assert any(hash_string_to_long(s) < 0
               for s in ("/", "/a", "/b", "/c", "/d", "/e", "/f"))


def test_sql_texts_are_reference_verbatim():
    """The dialect strings must stay byte-for-byte the reference's
    (mysql_store.go:45-51, postgres_store.go:44-50) — they ARE the
    compatibility surface."""
    my = MysqlDialect()
    assert my.insert == ("INSERT INTO filemeta (dirhash,name,directory,"
                         "meta) VALUES(?,?,?,?)")
    assert my.list_inclusive.endswith("ORDER BY NAME ASC LIMIT ?")
    pg = PostgresDialect()
    assert pg.insert == ("INSERT INTO filemeta (dirhash,name,directory,"
                         "meta) VALUES($1,$2,$3,$4)")
    assert pg.placeholders(pg.find) == (
        "SELECT meta FROM filemeta "
        "WHERE dirhash=?1 AND name=?2 AND directory=?3")


@pytest.mark.parametrize("dialect", [MysqlDialect(), PostgresDialect()])
def test_sql_insert_falls_back_to_update(dialect):
    """InsertEntry retries as update on duplicate key
    (abstract_sql_store.go InsertEntry / KvPut fallback)."""
    s = sqlite_validating_store(dialect)
    s.insert_entry(Entry(path="/a/f", attributes=Attributes(uid=1)))
    s.insert_entry(Entry(path="/a/f", attributes=Attributes(uid=2)))
    assert s.find_entry("/a/f").attributes.uid == 2
    rows = s._query(s.dialect.find,
                    (hash_string_to_long("/a"), "f", "/a"))
    assert len(rows) == 1  # updated in place, not duplicated
    s.close()


def test_sql_kv_rides_filemeta():
    """KV keys live in the filemeta table via genDirAndName
    (abstract_sql_store_kv.go) — no second table."""
    s = sqlite_validating_store(MysqlDialect())
    s.kv_put("checkpoint", b"\x01\x02")
    assert s.kv_get("checkpoint") == b"\x01\x02"
    tables = [r[0] for r in s.conn.execute(
        "SELECT name FROM sqlite_master WHERE type='table'")]
    assert tables == ["filemeta"]
    s.kv_delete("checkpoint")
    assert s.kv_get("checkpoint") is None
    s.close()


# -- etcd (v3 KV gRPC, no SDK) ----------------------------------------------

def test_etcd_wire_key_scheme():
    """Entry keys are dir + \\x00 + name (etcd_store.go
    DIR_FILE_SEPARATOR); subtree delete is one prefix DeleteRange."""
    from _mini_etcd import MiniEtcd
    from seaweedfs_tpu.filer.etcd_store import EtcdStore
    m = MiniEtcd()
    try:
        s = EtcdStore(f"127.0.0.1:{m.port}")
        s.insert_entry(Entry(path="/d/file.txt"))
        assert b"/d\x00file.txt" in m._m
        s.insert_entry(Entry(path="/d/sub", is_directory=True))
        s.insert_entry(Entry(path="/d/sub/leaf"))
        s.delete_folder_children("/d")
        assert [k for k in m._m if k.startswith(b"/d\x00")] == []
        assert [k for k in m._m if k.startswith(b"/d/sub\x00")] == []
        # kv keys carry no separator: no collision with entry keys
        s.kv_put("checkpoint", b"\x07")
        assert s.kv_get("checkpoint") == b"\x07"
        assert b"checkpoint" in m._m
        s.close()
    finally:
        m.close()


def test_etcd_range_pagination():
    from _mini_etcd import MiniEtcd
    from seaweedfs_tpu.filer.etcd_store import EtcdStore
    m = MiniEtcd()
    try:
        s = EtcdStore(f"127.0.0.1:{m.port}")
        for name in ("a", "b", "c", "d"):
            s.insert_entry(Entry(path=f"/dir/{name}"))
        page = s.list_directory_entries("/dir", "b", False, 2)
        assert [e.name for e in page] == ["c", "d"]
        page = s.list_directory_entries("/dir", "b", True, 2)
        assert [e.name for e in page] == ["b", "c"]
        s.close()
    finally:
        m.close()


# -- elastic (REST, no SDK) --------------------------------------------------

def test_elastic_wire_shapes():
    """One index per top-level component (.seaweedfs_<root>), doc id =
    md5(fullpath), {ParentId, Entry} doc shape, KV in
    .seaweedfs_kv_entries (elastic_store.go)."""
    import hashlib

    from _mini_es import MiniEs
    from seaweedfs_tpu.filer.elastic_store import ElasticStore
    m = MiniEs()
    try:
        s = ElasticStore(m.url())
        s.insert_entry(Entry(path="/buckets/b1/obj"))
        idx = m.indices[".seaweedfs_buckets"]
        doc_id = hashlib.md5(b"/buckets/b1/obj").hexdigest()
        assert doc_id in idx
        assert idx[doc_id]["ParentId"] == \
            hashlib.md5(b"/buckets/b1").hexdigest()
        s.kv_put("k", b"\x01\x02")
        assert ".seaweedfs_kv_entries" in m.indices
        assert s.kv_get("k") == b"\x01\x02"
        # root listing spans indexes
        s.insert_entry(Entry(path="/other", is_directory=True))
        names = {e.name
                 for e in s.list_directory_entries("/", "", True, 10)}
        assert "other" in names
        s.close()
    finally:
        m.close()


# -- mongodb (OP_MSG + BSON wire, no SDK) ------------------------------------

def test_bson_codec_roundtrip():
    from seaweedfs_tpu.filer.mongo_store import bson_decode, bson_encode
    doc = {"find": "filemeta", "$db": "seaweedfs",
           "filter": {"directory": "/d", "name": {"$gt": "a"}},
           "sort": {"name": 1}, "limit": 7,
           "blob": b"\x00\x01\xff", "ok": 1.0, "flag": True,
           "nothing": None, "big": 1 << 40,
           "arr": ["x", 2, {"y": b"z"}]}
    enc = bson_encode(doc)
    got, end = bson_decode(enc)
    assert end == len(enc)
    assert got["filter"] == {"directory": "/d", "name": {"$gt": "a"}}
    assert got["blob"] == b"\x00\x01\xff"
    assert got["big"] == 1 << 40 and got["limit"] == 7
    assert got["flag"] is True and got["nothing"] is None
    assert got["arr"] == ["x", 2, {"y": b"z"}]


def test_mongo_wire_commands():
    """The store issues the reference's exact command shapes: upsert
    update on (directory, name), find with $gt/$gte + name sort,
    deleteMany on directory, unique-index creation at startup
    (mongodb_store.go)."""
    from _mini_mongo import MiniMongo
    from seaweedfs_tpu.filer.mongo_store import MongoStore
    m = MiniMongo()
    try:
        s = MongoStore("127.0.0.1", m.port, database="weeddb")
        assert any("createIndexes" in c for c in m.commands_seen)
        s.insert_entry(Entry(path="/d/f1"))
        up = next(c for c in m.commands_seen if "update" in c)
        assert up["$db"] == "weeddb"
        assert up["updates"][0]["q"] == {"directory": "/d",
                                         "name": "f1"}
        assert up["updates"][0]["upsert"] is True
        # update-in-place, not duplicate
        s.insert_entry(Entry(path="/d/f1", is_directory=False))
        docs = m.collections[("weeddb", "filemeta")]
        assert len([d for d in docs if d["name"] == "f1"]) == 1
        # kv rides the same collection under /etc/kv
        s.kv_put("ck", b"\x09")
        assert s.kv_get("ck") == b"\x09"
        assert any(d["directory"] == "/etc/kv" and d["name"] == "ck"
                   for d in docs)
        s.close()
    finally:
        m.close()


def test_mongo_reconnects_once():
    from _mini_mongo import MiniMongo
    from seaweedfs_tpu.filer.mongo_store import MongoStore
    m = MiniMongo()
    try:
        s = MongoStore("127.0.0.1", m.port)
        s.kv_put("a", b"1")
        s.client._sock.close()
        assert s.kv_get("a") == b"1"
        s.close()
    finally:
        m.close()


# -- cassandra (CQL v4 wire, no SDK) -----------------------------------------

def test_cql_wire_statements_are_reference_verbatim():
    """The five CQL texts must stay byte-for-byte the reference's
    (cassandra_store.go:72-146) — they are the compatibility surface,
    and the mini server dispatches on them exactly."""
    from seaweedfs_tpu.filer.cassandra_store import CassandraStore as S
    assert S.SQL_INSERT == ("INSERT INTO filemeta (directory,name,meta)"
                            " VALUES(?,?,?) USING TTL ? ")
    assert S.SQL_FIND == ("SELECT meta FROM filemeta "
                          "WHERE directory=? AND name=?")
    assert S.SQL_LIST_EXCLUSIVE == (
        "SELECT NAME, meta FROM filemeta WHERE directory=? AND name>? "
        "ORDER BY NAME ASC LIMIT ?")


def test_cql_handshake_and_values():
    from _mini_cassandra import MiniCassandra
    from seaweedfs_tpu.filer.cassandra_store import CassandraStore
    m = MiniCassandra()
    try:
        s = CassandraStore("127.0.0.1", m.port)
        s.insert_entry(Entry(path="/d/f"))
        assert m.queries_seen[0].startswith("USE")
        assert ("/d", "f") in m.rows
        # pagination over the wire
        for name in ("a", "b", "c"):
            s.insert_entry(Entry(path=f"/p/{name}"))
        page = s.list_directory_entries("/p", "a", False, 2)
        assert [e.name for e in page] == ["b", "c"]
        # reconnect-once after a dead socket
        s.client._sock.close()
        assert s.find_entry("/d/f").path == "/d/f"
        s.close()
    finally:
        m.close()
