"""Networked FilerStore backends: RESP redis wire client + the
abstract_sql dialect layer (VERDICT r4 missing #1).

Conformance coverage lives in test_filer.py (the `store` fixture runs
the full contract over redis + both SQL dialects); these tests pin the
wire/dialect details: RESP framing, AUTH/SELECT, TTL-backed expiry,
reconnect-once, the reference dirhash algorithm, and the verbatim
mysql/postgres SQL texts."""

import time

import pytest

from _mini_redis import MiniRedis
from seaweedfs_tpu.filer.abstract_sql import (MysqlDialect,
                                              PostgresDialect,
                                              hash_string_to_long,
                                              sqlite_validating_store)
from seaweedfs_tpu.filer.entry import Attributes, Entry
from seaweedfs_tpu.filer.filerstore import NotFound
from seaweedfs_tpu.filer.redis_store import (DIR_LIST_MARKER,
                                             RedisStore, RespClient,
                                             RespError)


@pytest.fixture
def mini():
    m = MiniRedis()
    yield m
    m.close()


def test_resp_wire_shapes(mini):
    """Entry insert produces the reference's exact key scheme: meta at
    the full path, name SADD'ed into `dir + \\x00`
    (universal_redis_store.go:36-60)."""
    s = RedisStore("127.0.0.1", mini.port)
    s.insert_entry(Entry(path="/d/file.txt"))
    cmds = [c for c in mini.commands_seen if c[0] in (b"SET", b"SADD")]
    assert cmds[0][:2] == [b"SET", b"/d/file.txt"]
    assert cmds[1] == [b"SADD", ("/d" + DIR_LIST_MARKER).encode(),
                       b"file.txt"]
    assert s.find_entry("/d/file.txt").path == "/d/file.txt"
    s.close()


def test_resp_auth_and_select():
    m = MiniRedis(password="hunter2")
    try:
        # wrong password -> RespError from AUTH
        bad = RespClient("127.0.0.1", m.port, password="nope")
        with pytest.raises(RespError):
            bad.call("PING")
        s = RedisStore("127.0.0.1", m.port, password="hunter2",
                       database=2)
        s.kv_put("k", b"v")
        assert s.kv_get("k") == b"v"
        assert m.dbs.get(2, {}).get(b"kv:k") == b"v"  # SELECT honored
        s.close()
    finally:
        m.close()


def test_resp_entry_ttl_expires(mini):
    """TtlSec rides `SET ... EX` (the reference passes the ttl duration
    to Client.Set) — an expired entry disappears server-side."""
    s = RedisStore("127.0.0.1", mini.port)
    e = Entry(path="/t/x", attributes=Attributes(ttl_sec=1))
    s.insert_entry(e)
    assert s.find_entry("/t/x").path == "/t/x"
    mini.expiry[(0, b"/t/x")] = time.time() - 1  # fast-forward
    with pytest.raises(NotFound):
        s.find_entry("/t/x")
    s.close()


def test_resp_reconnects_once(mini):
    s = RedisStore("127.0.0.1", mini.port)
    s.kv_put("a", b"1")
    # Kill the client's socket under it: next call must redial.
    s.client._sock.close()
    assert s.kv_get("a") == b"1"
    s.close()


def test_dirhash_matches_reference_algorithm():
    """util.HashStringToLong (bytes.go:73): md5 first 8 bytes as a
    signed big-endian int64 — checked against hand-computed values."""
    import hashlib
    for sample in ("/", "/topics", "/buckets/b1", "/etc/kv"):
        b = hashlib.md5(sample.encode()).digest()
        v = int.from_bytes(b[:8], "big", signed=True)
        assert hash_string_to_long(sample) == v
    # Must be able to go negative (signed int64, BIGINT column).
    assert any(hash_string_to_long(s) < 0
               for s in ("/", "/a", "/b", "/c", "/d", "/e", "/f"))


def test_sql_texts_are_reference_verbatim():
    """The dialect strings must stay byte-for-byte the reference's
    (mysql_store.go:45-51, postgres_store.go:44-50) — they ARE the
    compatibility surface."""
    my = MysqlDialect()
    assert my.insert == ("INSERT INTO filemeta (dirhash,name,directory,"
                         "meta) VALUES(?,?,?,?)")
    assert my.list_inclusive.endswith("ORDER BY NAME ASC LIMIT ?")
    pg = PostgresDialect()
    assert pg.insert == ("INSERT INTO filemeta (dirhash,name,directory,"
                         "meta) VALUES($1,$2,$3,$4)")
    assert pg.placeholders(pg.find) == (
        "SELECT meta FROM filemeta "
        "WHERE dirhash=?1 AND name=?2 AND directory=?3")


@pytest.mark.parametrize("dialect", [MysqlDialect(), PostgresDialect()])
def test_sql_insert_falls_back_to_update(dialect):
    """InsertEntry retries as update on duplicate key
    (abstract_sql_store.go InsertEntry / KvPut fallback)."""
    s = sqlite_validating_store(dialect)
    s.insert_entry(Entry(path="/a/f", attributes=Attributes(uid=1)))
    s.insert_entry(Entry(path="/a/f", attributes=Attributes(uid=2)))
    assert s.find_entry("/a/f").attributes.uid == 2
    rows = s._query(s.dialect.find,
                    (hash_string_to_long("/a"), "f", "/a"))
    assert len(rows) == 1  # updated in place, not duplicated
    s.close()


def test_sql_kv_rides_filemeta():
    """KV keys live in the filemeta table via genDirAndName
    (abstract_sql_store_kv.go) — no second table."""
    s = sqlite_validating_store(MysqlDialect())
    s.kv_put("checkpoint", b"\x01\x02")
    assert s.kv_get("checkpoint") == b"\x01\x02"
    tables = [r[0] for r in s.conn.execute(
        "SELECT name FROM sqlite_master WHERE type='table'")]
    assert tables == ["filemeta"]
    s.kv_delete("checkpoint")
    assert s.kv_get("checkpoint") is None
    s.close()
