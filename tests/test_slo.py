"""Workload SLO plane: quantile sketch accuracy/merge/window-roll
(deterministic injected clock — no sleeps), space-saving heavy-hitter
properties, burn-rate engine, the rpc histogram's new status-class +
endpoint-family labels, /debug/slow exemplars linking to /debug/traces,
/debug/hot + cluster.hot, cross-process aggregation on
/cluster/healthz, the duplicate-registration regression, and live
promcheck-gated scrapes of every new instrument on all three roles."""

import os
import threading
import time

import numpy as np
import pytest

from seaweedfs_tpu import events, fault
from seaweedfs_tpu.cluster import rpc
from seaweedfs_tpu.cluster.client import WeedClient
from seaweedfs_tpu.cluster.master import MasterServer
from seaweedfs_tpu.cluster.volume_server import VolumeServer
from seaweedfs_tpu.stats.hotkeys import HotKeyTracker, SpaceSaving
from seaweedfs_tpu.stats.promcheck import validate_exposition
from seaweedfs_tpu.stats.sketch import QuantileSketch, WindowedSketch
from seaweedfs_tpu.stats.slo import (SloObjectives, SloTracker,
                                     merge_sketch_dicts)

pytestmark = pytest.mark.slo


# -- quantile sketch: documented accuracy bound ------------------------------

def _check_bound(values, alpha=0.01):
    """The sketch's documented guarantee: the reported q-quantile is
    within relative error alpha of the true (nearest-rank) q-quantile.
    A hair of slack covers the nearest-rank-vs-interpolation delta at
    rank boundaries."""
    sk = QuantileSketch(alpha=alpha)
    for v in values:
        sk.observe(v)
    arr = np.sort(np.asarray(values))
    for q in (0.01, 0.1, 0.5, 0.9, 0.95, 0.99, 0.999):
        est = sk.quantile(q)
        true = float(arr[max(0, int(np.ceil(q * len(arr))) - 1)])
        assert abs(est - true) <= alpha * true + 1e-12, \
            (q, est, true, abs(est - true) / true)


def test_sketch_accuracy_heavy_tail():
    rng = np.random.default_rng(7)
    _check_bound(rng.pareto(1.5, 50000) * 1e-3 + 1e-5)


def test_sketch_accuracy_bimodal():
    rng = np.random.default_rng(8)
    fast = rng.lognormal(-8.0, 0.3, 40000)    # ~0.3ms mode
    slow = rng.lognormal(-2.0, 0.4, 1000)     # ~135ms tail mode
    _check_bound(np.concatenate([fast, slow]))


def test_sketch_accuracy_lognormal_and_constant():
    rng = np.random.default_rng(9)
    _check_bound(rng.lognormal(-7.0, 1.5, 30000))
    _check_bound(np.full(1000, 0.0042))


def test_sketch_zero_and_empty():
    sk = QuantileSketch()
    assert sk.quantile(0.5) is None
    sk.observe(0.0)          # below min_value -> zero bucket
    sk.observe(1e-9)
    assert sk.quantile(0.5) == sk.min_value
    assert sk.count == 2


def test_sketch_merge_equals_concatenated_stream():
    rng = np.random.default_rng(10)
    a, b = rng.pareto(2.0, 5000) * 1e-3, rng.lognormal(-6, 1, 5000)
    whole = QuantileSketch()
    for v in np.concatenate([a, b]):
        whole.observe(v)
    left, right = QuantileSketch(), QuantileSketch()
    for v in a:
        left.observe(v)
    for v in b:
        right.observe(v)
    left.merge(right)
    assert left.count == whole.count
    for q in (0.05, 0.5, 0.95, 0.99):
        assert left.quantile(q) == whole.quantile(q)  # merge is exact


def test_sketch_merge_parameter_mismatch_raises():
    with pytest.raises(ValueError):
        QuantileSketch(alpha=0.01).merge(QuantileSketch(alpha=0.02))


def test_sketch_wire_roundtrip_and_dict_merge():
    rng = np.random.default_rng(11)
    sketches, dicts = [], []
    for _ in range(3):
        sk = QuantileSketch()
        for v in rng.lognormal(-6, 1, 2000):
            sk.observe(v)
        sketches.append(sk)
        dicts.append(sk.to_dict())
    # Roundtrip is lossless.
    rt = QuantileSketch.from_dict(dicts[0])
    assert rt.quantile(0.99) == sketches[0].quantile(0.99)
    assert rt.count == sketches[0].count
    # Cross-process aggregation: merging the wire dicts equals merging
    # the live sketches.
    merged = merge_sketch_dicts(dicts)
    live = QuantileSketch()
    for sk in sketches:
        live.merge(sk)
    assert merged.count == live.count
    assert merged.quantile(0.95) == live.quantile(0.95)
    # Mismatched/garbage entries are skipped, not fatal — including
    # structurally malformed payloads from buggy/mixed-version peers
    # (healthz must never 500 on a bad heartbeat).
    assert merge_sketch_dicts([{"junk": 1}, dicts[0]]).count == 2000
    assert merge_sketch_dicts(
        [{"buckets": [1, 2]}, {"buckets": "zzz", "alpha": 0.01},
         {"alpha": "NaN is fine", "buckets": {"1": "x"}},
         dicts[0]]).count == 2000
    assert merge_sketch_dicts([]) is None


def test_windowed_sketch_rolls_with_injected_clock():
    t = [0.0]
    w = WindowedSketch(window=60.0, slices=6, clock=lambda: t[0])
    for _ in range(100):
        w.observe(0.001)
    t[0] = 30.0
    for _ in range(100):
        w.observe(1.0)
    assert w.count() == 200           # both slices live
    assert w.quantile(0.25) < 0.01
    t[0] = 65.0                        # t=0 slice expired, t=30 lives
    assert w.count() == 100
    assert w.quantile(0.5) == pytest.approx(1.0, rel=0.02)
    t[0] = 200.0                       # everything expired
    assert w.count() == 0 and w.quantile(0.5) is None
    # Ring reuse after a long idle gap must not resurrect old epochs.
    w.observe(0.5)
    assert w.count() == 1


# -- space-saving heavy hitters ----------------------------------------------

def test_space_saving_exact_when_under_capacity():
    ss = SpaceSaving(capacity=64)
    rng = np.random.default_rng(12)
    truth: dict[int, int] = {}
    for k in rng.integers(0, 40, 5000):
        ss.offer(int(k))
        truth[int(k)] = truth.get(int(k), 0) + 1
    for row in ss.top(64):
        assert row["error"] == 0
        assert row["count"] == truth[row["key"]]


def test_space_saving_bounded_error_under_zipf():
    capacity, n = 64, 50000
    ss = SpaceSaving(capacity=capacity)
    rng = np.random.default_rng(13)
    ranks = np.arange(1, 5001)
    probs = 1.0 / ranks ** 1.2
    probs /= probs.sum()
    keys = rng.choice(ranks, size=n, p=probs)
    truth: dict[int, int] = {}
    for k in keys:
        ss.offer(int(k))
        truth[int(k)] = truth.get(int(k), 0) + 1
    top = ss.top(capacity)
    min_count = min(row["count"] for row in top)
    for row in top:
        true = truth.get(row["key"], 0)
        # count overestimates by at most the recorded error, which is
        # itself bounded by the evicted minimum <= N/capacity.
        assert true <= row["count"] <= true + row["error"]
        assert row["error"] <= min_count <= n / capacity + min_count
    # The true heavy hitters survive: every key with frequency above
    # N/capacity is guaranteed present.
    tracked = {row["key"] for row in top}
    for key, cnt in truth.items():
        if cnt > n / capacity:
            assert key in tracked, (key, cnt)


def test_hot_key_tracker_snapshot_shape():
    hk = HotKeyTracker(capacity=8)
    for _ in range(5):
        hk.read(3, 0x172, "10.0.0.1")
    hk.write(4, 0x9, "10.0.0.2")
    snap = hk.snapshot(k=4)
    assert snap["dimensions"]["volume"]["read"]["top"][0]["key"] == 3
    assert snap["dimensions"]["needle"]["read"]["top"][0]["key"] \
        == "3,172"
    assert snap["dimensions"]["client"]["write"]["top"][0]["key"] \
        == "10.0.0.2"
    hk.clear()
    assert hk.snapshot()["dimensions"]["volume"]["read"]["total"] == 0


# -- burn-rate engine (deterministic clock) ----------------------------------

def _tracker(clock, **obj):
    tr = SloTracker("t", node="t:1", clock=clock, short_window=60.0,
                    long_window=360.0)
    tr.set_objectives(**obj)
    return tr


def test_undeclared_objectives_never_burn():
    t = [100.0]
    tr = _tracker(lambda: t[0])
    for _ in range(50):
        tr.observe("/needle", "GET", 500, 2.0)
    state = tr.burn_state()
    assert not state["declared"] and not state["fast_burn"]


def test_availability_fast_burn_and_recovery():
    t = [100.0]
    tr = _tracker(lambda: t[0], availability=0.999)
    before = events.events_total.value(type="slo.burn")
    for i in range(40):
        tr.observe("/needle", "GET", 500 if i % 2 else 200, 0.001)
    state = tr.burn_state()
    # 50% errors / 0.1% budget = 500x burn in both windows.
    assert state["fast_burn"]
    assert state["availability"]["short"]["burn"] >= 14.4
    assert events.events_total.value(type="slo.burn") == before + 1
    # Episode semantics: still burning -> no second event.
    tr.burn_state()
    assert events.events_total.value(type="slo.burn") == before + 1
    # Errors stop; the short window expires -> burn clears (min of the
    # two windows gates the verdict).
    t[0] += 70.0
    for _ in range(20):
        tr.observe("/needle", "GET", 200, 0.001)
    state = tr.burn_state()
    assert not state["fast_burn"]
    # A fresh episode emits again.
    for _ in range(40):
        tr.observe("/needle", "GET", 500, 0.001)
    assert tr.burn_state()["fast_burn"]
    assert events.events_total.value(type="slo.burn") == before + 2


def test_latency_burn_counts_slow_reads_only():
    """The read-p99 burn divides by READS: a write-heavy workload
    (10 slow reads among 90 writes) must still fast-burn — writes in
    the denominator would dilute a total read collapse to 10x and
    never page."""
    t = [50.0]
    tr = _tracker(lambda: t[0], read_p99=0.010)
    for _ in range(10):
        tr.observe("/needle", "GET", 200, 0.050)   # all reads slow
    for _ in range(90):
        tr.observe("/needle", "POST", 200, 0.050)  # writes don't count
    state = tr.burn_state()
    assert state["fast_burn"]
    lat = state["latency"]
    assert lat["short"]["breaching"] == 10
    assert lat["short"]["total"] == 10  # denominator is reads, not ops


def test_sheds_do_not_pollute_latency_sketches():
    """A 429 shed is refused before execution: it must not enter the
    aggregate read/write tails (a shedding storm would fake a great
    p50) nor the error-rate denominator — only the shed column."""
    t = [20.0]
    tr = _tracker(lambda: t[0], availability=0.999)
    tr.observe("/needle", "GET", 200, 0.020)
    for _ in range(50):
        tr.observe("/needle", "GET", 429, 0.0)
    agg = tr.agg_quantiles("read")
    assert agg["count"] == 1
    assert agg["p50"] == pytest.approx(0.020, rel=0.03)
    st = tr.burn_state()["availability"]["short"]
    assert st["shed"] == 50
    assert st["total"] == 1 and st["breaching"] == 0


def test_burn_needs_minimum_traffic():
    t = [10.0]
    tr = _tracker(lambda: t[0], availability=0.999)
    for _ in range(SloTracker.MIN_WINDOW_REQUESTS - 1):
        tr.observe("/needle", "GET", 500, 0.001)
    assert not tr.burn_state()["fast_burn"]


def test_control_plane_excluded_from_burn_and_agg():
    t = [10.0]
    tr = _tracker(lambda: t[0], availability=0.999)
    for _ in range(50):
        tr.observe("/admin/scrub", "POST", 500, 0.001)
        tr.observe("/debug/*", "GET", 500, 0.001)
    state = tr.burn_state()
    assert not state["fast_burn"]
    assert state["availability"]["short"]["total"] == 0
    assert tr.agg_quantiles("read")["count"] == 0
    # ...but the per-family sketches still see them.
    assert "/admin/scrub 5xx" in tr.snapshot()["families"]


def test_objectives_validation():
    assert SloObjectives(availability=99.9).availability == \
        pytest.approx(0.999)
    with pytest.raises(ValueError):
        SloObjectives(read_p99=-1.0)
    assert not SloObjectives().declared


def test_exemplars_ring_is_bounded_newest_first():
    t = [5.0]
    tr = SloTracker("t", clock=lambda: t[0], exemplar_capacity=4)
    tr.set_objectives(read_p99=0.001)
    for i in range(10):
        tr.observe("/needle", "GET", 200, 0.5, trace_id=f"tid{i}")
    ex = tr.exemplars(10)
    assert len(ex) == 4 and tr.exemplars_recorded == 10
    assert [e["trace_id"] for e in ex] == \
        ["tid9", "tid8", "tid7", "tid6"]
    assert ex[0]["seconds"] == 0.5


# -- rpc middleware: labels, family normalization, sheds ---------------------

def test_endpoint_family_bounds_cardinality():
    assert rpc.endpoint_family("/dir/assign", literal=True) == \
        "/dir/assign"
    # Real admin endpoints are literal routes and keep their path;
    # an UNMOUNTED /admin/<x> is a client-chosen string (on gateways
    # the whole / namespace is) and must not mint a label.
    assert rpc.endpoint_family("/admin/ec/generate", literal=True) == \
        "/admin/ec/generate"
    assert rpc.endpoint_family("/admin/minted-by-client-7",
                               literal=False) == "/other"
    assert rpc.endpoint_family("/3,0172cb7d88", literal=False) == \
        "/needle"
    assert rpc.endpoint_family("/3,0172cb7d88/img.jpg",
                               literal=False) == "/needle"
    assert rpc.endpoint_family("/debug/whatever", literal=False) == \
        "/debug/*"
    assert rpc.endpoint_family("/any/user/path.txt", literal=False) == \
        "/other"


def test_request_histogram_status_and_family_labels():
    server = rpc.JsonHttpServer()
    server.route("GET", "/admin/thing", lambda q, b: {"ok": 1})

    def boom(q, b):
        raise RuntimeError("kaboom")
    server.route("GET", "/boom", boom)

    def missing(q, b):
        raise rpc.RpcError(404, "nope")
    server.route("GET", "/gone", missing)
    server.prefix_route("GET", "/", lambda p, q, b: {"path": p})
    reg = server.enable_metrics("labeltest")
    server.start()
    base = f"http://127.0.0.1:{server.port}"
    try:
        rpc.call(f"{base}/admin/thing")
        rpc.call(f"{base}/3,0172abcd")        # prefix -> /needle
        rpc.call(f"{base}/some/user/file")    # prefix -> /other
        with pytest.raises(rpc.RpcError):
            rpc.call(f"{base}/boom")
        with pytest.raises(rpc.RpcError):
            rpc.call(f"{base}/gone")
        text = reg.expose()
        assert ('SeaweedFS_labeltest_request_seconds_bucket{'
                'family="/admin/thing"') in text
        assert 'family="/needle"' in text
        assert 'family="/other"' in text
        assert 'family="/boom",le="+Inf",status="5xx"' in text
        assert 'family="/gone",le="+Inf",status="4xx"' in text
        # The counter keeps its reference shape (stats/metrics.go).
        assert 'SeaweedFS_labeltest_request_total{type="GET"} 5' in text
        assert validate_exposition(text) == []
        # The SLO tracker saw the same requests, split by status class.
        fams = server.slo.snapshot()["families"]
        assert "/boom 5xx" in fams and "/gone 4xx" in fams
        assert fams["/needle 2xx"]["count"] == 1
    finally:
        server.stop()


def test_admission_shed_lands_in_error_tail():
    """A shed 429 is part of the observable error tail: it shows up in
    the labeled histogram and the SLO shed column."""
    server = rpc.JsonHttpServer(
        admission=rpc.AdmissionControl(1, queue_depth=0,
                                       queue_timeout=0.05))
    server.route("GET", "/slow",
                 lambda q, b: (time.sleep(0.4), {"ok": True})[1])
    reg = server.enable_metrics("shedtest")
    server.slo.set_objectives(availability=0.999)
    server.start()
    statuses = []

    def call_slow():
        try:
            rpc.call(f"http://127.0.0.1:{server.port}/slow",
                     timeout=5.0)
            statuses.append(200)
        except rpc.RpcError as e:
            statuses.append(e.status)
    try:
        threads = [threading.Thread(target=call_slow)
                   for _ in range(3)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        assert 429 in statuses
        text = reg.expose()
        assert 'family="/slow",le="+Inf",status="4xx"' in text
        burn = server.slo.burn_state()
        assert burn["availability"]["short"]["shed"] >= 1
        # Sheds are reported but never counted as budget burn.
        assert burn["availability"]["short"]["breaching"] == 0
    finally:
        server.stop()


# -- duplicate-registration regression ---------------------------------------

def test_enable_metrics_idempotent_no_duplicate_families():
    """Re-initializing metrics on a live server (rolling-restart /
    re-init paths re-create registries) must not stack duplicate
    exposition families — promcheck treats a duplicate TYPE as a
    corrupt scrape."""
    server = rpc.JsonHttpServer()
    reg1 = server.enable_metrics("duptest")
    reg2 = server.enable_metrics("duptest")
    assert reg1 is reg2
    from seaweedfs_tpu.stats.metrics import (ec_stage_bytes,
                                             ec_stage_seconds)
    for _ in range(2):  # process-global singletons re-registered
        reg1.register_once(ec_stage_seconds)
        reg1.register_once(ec_stage_bytes)
    text = reg1.expose()
    assert text.count("# TYPE SeaweedFS_duptest_request_total") == 1
    assert text.count("# TYPE SeaweedFS_request_quantile_seconds") == 1
    assert text.count("# TYPE SeaweedFS_ec_stage_seconds") == 1
    assert validate_exposition(text) == []


def test_in_process_server_restart_scrape_stays_clean(tmp_path):
    """A volume server stopped and re-created in one process (the
    rolling-restart tests' pattern) re-registers every process-global
    instrument into a fresh registry; the new scrape must stay
    promcheck-clean with no duplicated families."""
    master = MasterServer(volume_size_limit_mb=16,
                          meta_dir=str(tmp_path / "meta"),
                          pulse_seconds=60)
    master.start()
    try:
        d = tmp_path / "vs"
        d.mkdir()
        vs1 = VolumeServer(master.url(), [str(d)], pulse_seconds=60)
        vs1.start()
        client = WeedClient(master.url())
        fid = client.upload_data(b"restart payload")
        client.download(fid)
        vs1.stop()
        vs2 = VolumeServer(master.url(), [str(d)], pulse_seconds=60)
        vs2.start()
        try:
            client2 = WeedClient(master.url())
            client2.download(fid)
            text = rpc.call(f"http://{vs2.url()}/metrics").decode()
            assert validate_exposition(text) == [], \
                validate_exposition(text)[:5]
            for fam in ("SeaweedFS_ec_stage_seconds",
                        "SeaweedFS_request_quantile_seconds",
                        "SeaweedFS_requests_shed_total"):
                assert text.count(f"# TYPE {fam}") == 1, fam
        finally:
            vs2.stop()
    finally:
        master.stop()


# -- mini-cluster: live scrapes, aggregation, hot keys, acceptance -----------

@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    """Master + two volume servers + filer in one process, tracing
    recording on (exemplars must carry resolvable trace ids)."""
    saved = {k: os.environ.get(k)
             for k in ("SEAWEEDFS_TPU_TRACES", "SEAWEEDFS_TPU_TRACE")}
    os.environ["SEAWEEDFS_TPU_TRACES"] = "1"
    os.environ.pop("SEAWEEDFS_TPU_TRACE", None)
    tmp = tmp_path_factory.mktemp("slo-cluster")
    master = MasterServer(volume_size_limit_mb=16,
                          meta_dir=str(tmp / "meta"), pulse_seconds=60)
    master.start()
    servers = []
    for i in range(2):
        d = tmp / f"vs{i}"
        d.mkdir()
        vs = VolumeServer(master.url(), [str(d)],
                          max_volume_counts=[100], pulse_seconds=60,
                          slo_read_p99=0.5, slo_availability=0.999)
        vs.start()
        servers.append(vs)
    from seaweedfs_tpu.filer.server import FilerServer
    filer = FilerServer(master.url(), metrics_port=0)
    filer.start()
    client = WeedClient(master.url())
    yield master, servers, filer, client
    filer.stop()
    for vs in servers:
        vs.stop()
    master.stop()
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v


def test_live_scrape_new_instruments_all_roles(cluster):
    """promcheck-gated live scrape of every new instrument —
    SeaweedFS_request_quantile_seconds, SeaweedFS_slo_burn_rate, and
    the labeled request histogram — on master, volume server, and the
    filer's metrics port."""
    master, servers, filer, client = cluster
    from seaweedfs_tpu.filer.client import FilerProxy
    fid = client.upload_data(b"slo scrape payload " * 8)
    for _ in range(3):
        client.download(fid)
    FilerProxy(filer.url()).put("/slo/f.txt", b"filer traffic")
    scrapes = {
        "master": rpc.call(f"{master.url()}/metrics").decode(),
        "volume": rpc.call(
            f"http://{servers[0].url()}/metrics").decode(),
        "filer": rpc.call(
            f"{filer.metrics_server.url()}/metrics").decode(),
    }
    for role, text in scrapes.items():
        assert validate_exposition(text) == [], \
            (role, validate_exposition(text)[:5])
        assert "SeaweedFS_request_quantile_seconds" in text, role
        assert "SeaweedFS_slo_burn_rate" in text, role
        assert 'status="2xx"' in text, role
    assert 'q="0.99"' in scrapes["volume"]
    # Burn gauge carries live values on the volume role (objectives
    # declared there).
    assert ('SeaweedFS_slo_burn_rate{role="volumeServer",'
            'slo="availability",window="short"}') in scrapes["volume"]


def test_healthz_aggregates_node_sketches(cluster):
    """Window-roll + cross-process aggregation: every node ships its
    mergeable read/write sketches in heartbeats; /cluster/healthz
    folds them (plus the master's own) into one cluster-wide tail."""
    master, servers, _filer, client = cluster
    fid = client.upload_data(b"aggregation payload")
    for _ in range(4):
        client.download(fid)
    for vs in servers:
        vs._send_heartbeat(full=True)
    status, doc = rpc.call_status(f"{master.url()}/cluster/healthz")
    assert status == 200, doc.get("problems")
    slo_doc = doc["slo"]
    # master + both volume servers contribute sketches.
    assert slo_doc["sources"] == 3
    assert slo_doc["read"]["count"] >= 4
    assert slo_doc["read"]["p99"] > 0
    # The merged count equals the sum of the contributors' live
    # aggregate counts at heartbeat time (merge is exact addition) —
    # node sketches are heartbeat snapshots, so recompute from them.
    node_counts = sum(
        getattr(dn, "slo_state", {}).get("read", {}).get("count", 0)
        for dn in master.topo.leaves())
    own = master.server.slo.agg_quantiles("read")["count"]
    assert slo_doc["read"]["count"] >= node_counts
    assert slo_doc["read"]["count"] <= node_counts + own
    # Node rows carry their burn verdict.
    assert all("slo" in n for n in doc["nodes"])


def test_dead_node_slo_state_excluded_from_rollup(cluster):
    """A dead node's final heartbeat verdict must not haunt the live
    rollup: its fast-burn problem and its last-window sketch drop out
    of /cluster/healthz once the heartbeat goes stale."""
    master, servers, _filer, _client = cluster
    dn = next(d for d in master.topo.leaves()
              if d.url() == servers[1].url())
    poisoned = {"declared": True, "fast_burn": True,
                "slow_burn": False,
                "read": {"alpha": 0.01, "min_value": 1e-6,
                         "count": 10 ** 9, "sum": 1.0, "zero": 0,
                         "buckets": {"600": 10 ** 9}}}
    saved_seen = dn.last_seen
    try:
        dn.slo_state = poisoned
        _st, doc = rpc.call_status(f"{master.url()}/cluster/healthz")
        assert any("SLO fast burn" in p for p in doc["problems"])
        assert doc["slo"]["read"]["count"] >= 10 ** 9
        dn.last_seen = 0.0  # node dies; verdict must die with it
        _st, doc = rpc.call_status(f"{master.url()}/cluster/healthz")
        assert not any("SLO fast burn" in p for p in doc["problems"])
        assert doc["slo"]["read"]["count"] < 10 ** 9
    finally:
        dn.last_seen = saved_seen
        servers[1]._send_heartbeat(full=True)  # restore real state


def test_debug_hot_and_cluster_hot_shell(cluster):
    """Skewed reads surface the hot needle/volume/client on /debug/hot
    and the merged shell view."""
    from seaweedfs_tpu.shell import CommandEnv, run_command
    master, servers, _filer, client = cluster
    hot_fid = client.upload_data(b"hot needle " * 4)
    cold_fid = client.upload_data(b"cold needle " * 4)
    for _ in range(12):
        client.download(hot_fid)
    client.download(cold_fid)
    hot_vid = int(hot_fid.split(",")[0])
    holder = next(vs for vs in servers
                  if vs.store.find_volume(hot_vid) is not None)
    out = rpc.call(f"http://{holder.url()}/debug/hot?k=4")
    top_needles = out["dimensions"]["needle"]["read"]["top"]
    # the tracker keys needles as "vid,hexkey" (no cookie)
    assert top_needles[0]["key"].startswith(f"{hot_vid},")
    assert top_needles[0]["count"] >= 12
    assert out["dimensions"]["volume"]["read"]["top"][0]["count"] >= 12
    assert out["dimensions"]["client"]["read"]["top"][0]["key"] == \
        "127.0.0.1"
    env = CommandEnv(master.url())
    try:
        text = run_command(env, "cluster.hot -k 5")
        assert "volume (read" in text and "needle (read" in text
        assert "127.0.0.1" in text
        text = run_command(env, "cluster.hot -k 3 -dimension client")
        assert "volume (read" not in text and "client (read" in text
    finally:
        env.close()
    # reset starts a fresh observation window
    out = rpc.call(f"http://{holder.url()}/debug/hot?reset=1")
    out = rpc.call(f"http://{holder.url()}/debug/hot")
    assert out["dimensions"]["needle"]["read"]["total"] == 0


def test_acceptance_slow_fault_exemplar_trace_burn_healthz(tmp_path):
    """The ISSUE acceptance flow end-to-end, in-process: an injected
    slow fault on the volume read path produces a /debug/slow exemplar
    whose trace id resolves in /debug/traces, flips /cluster/healthz
    to degraded via the latency burn rate, and emits slo.burn."""
    saved = {k: os.environ.get(k)
             for k in ("SEAWEEDFS_TPU_TRACES", "SEAWEEDFS_TPU_TRACE")}
    os.environ["SEAWEEDFS_TPU_TRACES"] = "1"
    os.environ.pop("SEAWEEDFS_TPU_TRACE", None)
    master = MasterServer(volume_size_limit_mb=16,
                          meta_dir=str(tmp_path / "meta"),
                          pulse_seconds=60)
    master.start()
    d = tmp_path / "vs"
    d.mkdir()
    vs = VolumeServer(master.url(), [str(d)], pulse_seconds=60,
                      slo_read_p99=0.010, slo_availability=0.99)
    vs.start()
    try:
        client = WeedClient(master.url())
        fid = client.upload_data(b"slow fault payload " * 8)
        burn_before = events.events_total.value(type="slo.burn")
        fault.arm("volume.read", "delay:0.05")
        try:
            for _ in range(15):
                client.download(fid)
        finally:
            fault.disarm_all()
        # 1) /debug/slow carries exemplars above the 10ms objective...
        slow = rpc.call(f"http://{vs.url()}/debug/slow")
        assert slow["threshold_seconds"] == 0.010
        exemplars = [e for e in slow["exemplars"]
                     if e["family"] == "/needle"]
        assert len(exemplars) >= 15
        assert all(e["seconds"] >= 0.05 for e in exemplars[:15])
        # 2) ...whose trace id resolves to real spans in /debug/traces.
        tid = exemplars[0]["trace_id"]
        assert tid
        trace = rpc.call(
            f"http://{vs.url()}/debug/traces?trace={tid}")
        assert trace["trace_id"] == tid and trace["spans"]
        assert any(s["service"] == "volumeServer"
                   for s in trace["spans"])
        # 3) the latency burn flips /cluster/healthz to degraded...
        vs._send_heartbeat(full=True)
        status, doc = rpc.call_status(
            f"{master.url()}/cluster/healthz")
        assert status == 503 and not doc["healthy"]
        assert any("SLO fast burn" in p for p in doc["problems"]), \
            doc["problems"]
        assert vs.url() in doc["slo"]["fast_burn"]
        # 4) ...and slo.burn landed in the journal with a trace id.
        assert events.events_total.value(type="slo.burn") > burn_before
        evs = events.JOURNAL.snapshot(type_="slo.burn")
        assert evs and evs[-1]["attrs"]["slo"] == "latency"
        assert evs[-1]["trace_id"]
    finally:
        vs.stop()
        master.stop()
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


# -- load-harness smoke (subprocess cluster; seconds, CPU-only) --------------

@pytest.mark.slow
def test_bench_load_quick_mode(tmp_path):
    """bench_load.py quick mode: a real subprocess cluster, a short
    open-loop mixed workload, client/server quantile cross-check and
    the fault-phase acceptance checks — the gating BENCH series'
    machinery, shrunk to seconds."""
    import json
    import subprocess
    import sys
    out_path = tmp_path / "BENCH_load_smoke.json"
    env = dict(os.environ, BENCH_LOAD_QUICK="1", JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, os.path.join(os.path.dirname(__file__), "..",
                                      "bench_load.py"),
         "-o", str(out_path)],
        env=env, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr[-4000:]
    doc = json.loads(out_path.read_text())
    assert doc["achieved_rps"] > 0
    assert doc["client"]["read"]["p99"] > 0
    assert doc["server"]["read"]["p99"] > 0
    assert doc["agreement"]["read"]["within_bound"], doc["agreement"]
    fc = doc["fault_checks"]
    assert fc["exemplar_recorded"] and fc["trace_resolved"]
    assert fc["healthz_degraded"] and fc["slo_burn_emitted"]
    # Round 2: the time-attribution acceptance rows.
    pb = doc["phase_budget"]
    assert pb["exemplars_with_phases"] > 0
    assert pb["budget_ok"], pb
    assert doc["cluster_profile"]["merged_ok"], doc["cluster_profile"]
    ov = doc["attribution_overhead"]
    assert ov["on"]["median_rps"] > 0 and ov["off"]["median_rps"] > 0
