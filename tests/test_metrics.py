"""Metrics: Prometheus exposition, request instrumentation, gauges,
push loop, sys stats.

Reference behaviors: weed/stats/metrics.go (request vectors, volume
gauges, LoopPushingMetric), disk.go, memory.go.
"""

import threading
import urllib.request

import pytest

from seaweedfs_tpu.stats import (MetricsPusher, Registry, disk_status,
                                 memory_status, validate_exposition)


# -- primitives ------------------------------------------------------------

def test_counter_exposition():
    reg = Registry()
    c = reg.counter("test_total", "a counter", ("op",))
    c.inc(op="read")
    c.inc(2, op="write")
    text = reg.expose()
    assert "# TYPE test_total counter" in text
    assert 'test_total{op="read"} 1' in text
    assert 'test_total{op="write"} 2' in text


def test_gauge_set_and_callback():
    reg = Registry()
    g = reg.gauge("depth", "queue depth")
    g.set(7)
    assert "depth 7" in reg.expose()
    reg2 = Registry()
    reg2.gauge("cb", "sampled", ("kind",),
               callback=lambda: {("a",): 1.5, ("b",): 2.0})
    text = reg2.expose()
    assert 'cb{kind="a"} 1.5' in text and 'cb{kind="b"} 2' in text


def test_histogram_buckets_and_sum():
    reg = Registry()
    h = reg.histogram("lat_seconds", "latency", ("op",),
                      buckets=(0.01, 0.1, 1.0))
    for v in (0.005, 0.05, 0.5, 5.0):
        h.observe(v, op="get")
    text = reg.expose()
    assert 'lat_seconds_bucket{le="0.01",op="get"} 1' in text
    assert 'lat_seconds_bucket{le="0.1",op="get"} 2' in text
    assert 'lat_seconds_bucket{le="1",op="get"} 3' in text
    assert 'lat_seconds_bucket{le="+Inf",op="get"} 4' in text
    assert 'lat_seconds_count{op="get"} 4' in text
    assert 'lat_seconds_sum{op="get"} 5.555' in text


def test_label_values_fully_escaped():
    """Backslash, quote AND newline must all be escaped — an unescaped
    \\n splits the sample line and corrupts the whole scrape."""
    reg = Registry()
    c = reg.counter("esc_total", "escapes", ("path",))
    c.inc(path='a\\b"c\nd')
    text = reg.expose()
    assert 'esc_total{path="a\\\\b\\"c\\nd"} 1' in text
    assert validate_exposition(text) == []


def test_histogram_time_returns_timer():
    reg = Registry()
    h = reg.histogram("t_seconds", "timer", buckets=(1.0,))
    with h.time() as timer:
        assert timer is not None  # nestable with other ctx managers
    assert "t_seconds_count 1" in reg.expose()


def test_concurrent_observe_while_exposing():
    """8 writer threads inc/observe while expose() runs in a loop —
    thread-safety regression test (the exposition must neither crash
    nor lose increments)."""
    reg = Registry()
    c = reg.counter("cc_total", "concurrent", ("t",))
    h = reg.histogram("ch_seconds", "concurrent", ("t",),
                      buckets=(0.001, 0.01, 0.1))
    stop = threading.Event()
    errors: list = []

    def writer(i: int) -> None:
        try:
            for n in range(500):
                c.inc(t=str(i))
                h.observe(0.001 * (n % 3), t=str(i))
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    def scraper() -> None:
        try:
            while not stop.is_set():
                assert validate_exposition(reg.expose()) == []
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    scrape_thread = threading.Thread(target=scraper)
    scrape_thread.start()
    writers = [threading.Thread(target=writer, args=(i,))
               for i in range(8)]
    for t in writers:
        t.start()
    for t in writers:
        t.join()
    stop.set()
    scrape_thread.join()
    assert not errors
    text = reg.expose()
    for i in range(8):
        assert f'cc_total{{t="{i}"}} 500' in text
        assert f'ch_seconds_count{{t="{i}"}} 500' in text
    assert validate_exposition(text) == []


# -- exposition-format validator (promtool-style) ---------------------------

def test_validator_accepts_all_primitive_expositions():
    reg = Registry()
    reg.counter("v_total", "c", ("op",)).inc(op="x")
    reg.gauge("v_depth", "g").set(3)
    h = reg.histogram("v_seconds", "h", ("op",), buckets=(0.1, 1.0))
    h.observe(0.05, op="x")
    h.observe(5.0, op="x")
    assert validate_exposition(reg.expose()) == []


@pytest.mark.parametrize("bad,needle", [
    ("m_total{l=\"a\nb\"} 1", "bad"),                     # raw newline
    ("m_total{l=\"a\\qb\"} 1", "escape"),                 # bad escape
    ("1bad_name 2", "name"),                              # bad name
    ("m_total{l=\"v\"} notanumber", "value"),             # bad value
    ("# TYPE m histogram\nm_bucket{le=\"1\"} 5\n"
     "m_bucket{le=\"0.5\"} 3\nm_bucket{le=\"+Inf\"} 6",
     "ascending"),                                        # le order
    ("# TYPE m histogram\nm_bucket{le=\"1\"} 5\n"
     "m_bucket{le=\"+Inf\"} 3", "cumulative"),            # non-cumulative
    ("# TYPE m histogram\nm_bucket{le=\"1\"} 5", "+Inf"),  # no +Inf
    ("a_total 1\nb_total 1\na_total 2", "interleaved"),   # family split
    ("# HELP m x\n# HELP m y\nm 1", "duplicate"),         # dup HELP
])
def test_validator_rejects_malformed(bad, needle):
    problems = validate_exposition(bad)
    assert problems and any(needle in p for p in problems), problems


def test_broken_callback_does_not_kill_scrape():
    reg = Registry()
    reg.gauge("bad", "boom", callback=lambda: 1 / 0)
    reg.counter("good_total", "fine").inc()
    assert "good_total 1" in reg.expose()


def test_sysstats(tmp_path):
    d = disk_status(str(tmp_path))
    assert d["all"] > 0 and 0 <= d["percent_used"] <= 100
    m = memory_status()
    assert m["rss"] > 0


# -- server integration ----------------------------------------------------

@pytest.fixture(scope="module")
def stack(tmp_path_factory):
    from seaweedfs_tpu.cluster.client import WeedClient
    from seaweedfs_tpu.cluster.master import MasterServer
    from seaweedfs_tpu.cluster.volume_server import VolumeServer
    from seaweedfs_tpu.filer.server import FilerServer
    tmp = tmp_path_factory.mktemp("metrics-stack")
    master = MasterServer(volume_size_limit_mb=64, meta_dir=str(tmp))
    master.start()
    vs = VolumeServer(master.url(), [str(tmp / "vs")], pulse_seconds=60)
    vs.start()
    # metrics_port=0 -> scrape rides its own free port (the filer's /
    # is user namespace, like the reference's -metricsPort).
    filer = FilerServer(master.url(), metrics_port=0)
    filer.start()
    yield master, vs, WeedClient(master.url()), filer
    filer.stop()
    vs.stop()
    master.stop()


def _scrape(url: str) -> str:
    with urllib.request.urlopen(url + "/metrics") as r:
        assert r.headers["Content-Type"].startswith("text/plain")
        return r.read().decode()


def test_master_and_volume_metrics_endpoints(stack):
    master, vs, client, _filer = stack
    fid = client.upload_data(b"metrics payload")
    client.download(fid)
    mtext = _scrape(master.url())
    assert "SeaweedFS_master_request_total" in mtext
    assert "SeaweedFS_master_volume_count" in mtext
    assert "SeaweedFS_master_is_leader 1" in mtext
    assert "SeaweedFS_master_data_node_count 1" in mtext
    vtext = _scrape(vs.server.url())
    assert 'SeaweedFS_volumeServer_request_total{type="POST"}' in vtext
    assert "SeaweedFS_volumeServer_request_seconds_bucket" in vtext
    assert 'SeaweedFS_volumeServer_volumes{collection="default",' \
           'type="volume"}' in vtext
    assert "SeaweedFS_disk_free_bytes" in vtext
    assert "SeaweedFS_memory_rss_bytes" in vtext


def test_metrics_pusher(stack):
    from seaweedfs_tpu.cluster import rpc
    # Fake push gateway capturing POSTs.
    received = []
    gw = rpc.JsonHttpServer()
    gw.prefix_route("POST", "/metrics/", lambda p, q, b: (
        received.append((p, b)), {"ok": True})[-1])
    gw.start()
    try:
        reg = Registry()
        reg.counter("pushed_total", "x").inc(5)
        pusher = MetricsPusher(reg, gw.url(), job="volumeServer",
                               instance="vs-1")
        pusher.push_once()
        assert received
        path, body = received[0]
        assert path == "/metrics/job/volumeServer/instance/vs-1"
        assert b"pushed_total 5" in body
    finally:
        gw.stop()


def test_live_scrapes_pass_promtool_validation(stack):
    """Every role's live exposition parses clean under the promtool-
    style validator: master, volume server, and the filer's dedicated
    metrics port."""
    master, vs, client, filer = stack
    from seaweedfs_tpu.filer.client import FilerProxy
    fid = client.upload_data(b"validate me")
    client.download(fid)
    FilerProxy(filer.url()).put("/scrape/f.txt", b"filer traffic")
    for url in (master.url(), vs.server.url(),
                filer.metrics_server.url()):
        text = _scrape(url)
        assert validate_exposition(text) == [], url


def test_pusher_stop_joins_and_flushes(stack):
    """stop() must join the push thread (bounded) and attempt one final
    push so a short-lived process doesn't lose its last interval."""
    from seaweedfs_tpu.cluster import rpc
    received = []
    gw = rpc.JsonHttpServer()
    gw.prefix_route("POST", "/metrics/", lambda p, q, b: (
        received.append(b), {"ok": True})[-1])
    gw.start()
    try:
        reg = Registry()
        counter = reg.counter("final_total", "x")
        # Interval far beyond the test: the loop never fires on its
        # own, so anything received comes from stop()'s final flush.
        pusher = MetricsPusher(reg, gw.url(), job="j", instance="i",
                               interval_seconds=3600.0)
        pusher.start()
        counter.inc(7)
        pusher.stop()
        assert not pusher._thread.is_alive()
        assert received and b"final_total 7" in received[-1]
    finally:
        gw.stop()


def test_pusher_stop_without_start():
    """stop() before start() must not raise (no thread to join) — it
    still attempts the final flush, which may fail harmlessly."""
    reg = Registry()
    pusher = MetricsPusher(reg, "http://127.0.0.1:9", job="j",
                           instance="i")
    pusher.stop()  # unreachable gateway: swallowed


def test_benchmark_command(stack):
    """weed benchmark against the live stack (command/benchmark.go)."""
    from seaweedfs_tpu.command import COMMANDS, _load_all, parse_flags
    master, _vs, _c, _f = stack
    _load_all()
    host = master.url().replace("http://", "")
    flags, rest = parse_flags(
        [f"-master={host}", "-n=32", "-size=256", "-c=4"])
    assert COMMANDS["benchmark"].run(flags, rest) == 0


def test_benchmark_cpu_accounting(stack):
    """-cpu=true (default) reports requests per core-second — the
    hardware-independent number BASELINE.md compares against the
    reference's multi-core req/s."""
    from seaweedfs_tpu.command.benchmark_cmd import run_benchmark
    from seaweedfs_tpu.command import parse_flags
    master, _vs, _c, _f = stack
    host = master.url().replace("http://", "")
    flags, rest = parse_flags(
        [f"-master={host}", "-n=24", "-size=256", "-c=4", "-procs=1"])
    reports: list = []
    assert run_benchmark(flags, rest, reports) == 0
    assert len(reports) == 2  # write + read
    for rep in reports:
        cpu = rep["cpu"]
        # In-process servers: all cost lands in client CPU (pid-deduped)
        assert cpu["total_s"] > 0
        assert cpu["req_per_core_sec"] > 0
        assert cpu["cpu_us_per_req"] > 0
    # -cpu=false suppresses the section
    flags, rest = parse_flags(
        [f"-master={host}", "-n=8", "-size=64", "-c=2", "-procs=1",
         "-cpu=false"])
    reports2: list = []
    assert run_benchmark(flags, rest, reports2) == 0
    assert all("cpu" not in r for r in reports2)
