"""Metrics: Prometheus exposition, request instrumentation, gauges,
push loop, sys stats.

Reference behaviors: weed/stats/metrics.go (request vectors, volume
gauges, LoopPushingMetric), disk.go, memory.go.
"""

import threading
import urllib.request

import pytest

from seaweedfs_tpu.stats import (MetricsPusher, Registry, disk_status,
                                 memory_status)


# -- primitives ------------------------------------------------------------

def test_counter_exposition():
    reg = Registry()
    c = reg.counter("test_total", "a counter", ("op",))
    c.inc(op="read")
    c.inc(2, op="write")
    text = reg.expose()
    assert "# TYPE test_total counter" in text
    assert 'test_total{op="read"} 1' in text
    assert 'test_total{op="write"} 2' in text


def test_gauge_set_and_callback():
    reg = Registry()
    g = reg.gauge("depth", "queue depth")
    g.set(7)
    assert "depth 7" in reg.expose()
    reg2 = Registry()
    reg2.gauge("cb", "sampled", ("kind",),
               callback=lambda: {("a",): 1.5, ("b",): 2.0})
    text = reg2.expose()
    assert 'cb{kind="a"} 1.5' in text and 'cb{kind="b"} 2' in text


def test_histogram_buckets_and_sum():
    reg = Registry()
    h = reg.histogram("lat_seconds", "latency", ("op",),
                      buckets=(0.01, 0.1, 1.0))
    for v in (0.005, 0.05, 0.5, 5.0):
        h.observe(v, op="get")
    text = reg.expose()
    assert 'lat_seconds_bucket{le="0.01",op="get"} 1' in text
    assert 'lat_seconds_bucket{le="0.1",op="get"} 2' in text
    assert 'lat_seconds_bucket{le="1",op="get"} 3' in text
    assert 'lat_seconds_bucket{le="+Inf",op="get"} 4' in text
    assert 'lat_seconds_count{op="get"} 4' in text
    assert 'lat_seconds_sum{op="get"} 5.555' in text


def test_broken_callback_does_not_kill_scrape():
    reg = Registry()
    reg.gauge("bad", "boom", callback=lambda: 1 / 0)
    reg.counter("good_total", "fine").inc()
    assert "good_total 1" in reg.expose()


def test_sysstats(tmp_path):
    d = disk_status(str(tmp_path))
    assert d["all"] > 0 and 0 <= d["percent_used"] <= 100
    m = memory_status()
    assert m["rss"] > 0


# -- server integration ----------------------------------------------------

@pytest.fixture(scope="module")
def stack(tmp_path_factory):
    from seaweedfs_tpu.cluster.client import WeedClient
    from seaweedfs_tpu.cluster.master import MasterServer
    from seaweedfs_tpu.cluster.volume_server import VolumeServer
    tmp = tmp_path_factory.mktemp("metrics-stack")
    master = MasterServer(volume_size_limit_mb=64, meta_dir=str(tmp))
    master.start()
    vs = VolumeServer(master.url(), [str(tmp / "vs")], pulse_seconds=60)
    vs.start()
    yield master, vs, WeedClient(master.url())
    vs.stop()
    master.stop()


def _scrape(url: str) -> str:
    with urllib.request.urlopen(url + "/metrics") as r:
        assert r.headers["Content-Type"].startswith("text/plain")
        return r.read().decode()


def test_master_and_volume_metrics_endpoints(stack):
    master, vs, client = stack
    fid = client.upload_data(b"metrics payload")
    client.download(fid)
    mtext = _scrape(master.url())
    assert "SeaweedFS_master_request_total" in mtext
    assert "SeaweedFS_master_volume_count" in mtext
    assert "SeaweedFS_master_is_leader 1" in mtext
    assert "SeaweedFS_master_data_node_count 1" in mtext
    vtext = _scrape(vs.server.url())
    assert 'SeaweedFS_volumeServer_request_total{type="POST"}' in vtext
    assert "SeaweedFS_volumeServer_request_seconds_bucket" in vtext
    assert 'SeaweedFS_volumeServer_volumes{collection="default",' \
           'type="volume"}' in vtext
    assert "SeaweedFS_disk_free_bytes" in vtext
    assert "SeaweedFS_memory_rss_bytes" in vtext


def test_metrics_pusher(stack):
    from seaweedfs_tpu.cluster import rpc
    # Fake push gateway capturing POSTs.
    received = []
    gw = rpc.JsonHttpServer()
    gw.prefix_route("POST", "/metrics/", lambda p, q, b: (
        received.append((p, b)), {"ok": True})[-1])
    gw.start()
    try:
        reg = Registry()
        reg.counter("pushed_total", "x").inc(5)
        pusher = MetricsPusher(reg, gw.url(), job="volumeServer",
                               instance="vs-1")
        pusher.push_once()
        assert received
        path, body = received[0]
        assert path == "/metrics/job/volumeServer/instance/vs-1"
        assert b"pushed_total 5" in body
    finally:
        gw.stop()


def test_benchmark_command(stack):
    """weed benchmark against the live stack (command/benchmark.go)."""
    from seaweedfs_tpu.command import COMMANDS, _load_all, parse_flags
    master, _vs, _c = stack
    _load_all()
    host = master.url().replace("http://", "")
    flags, rest = parse_flags(
        [f"-master={host}", "-n=32", "-size=256", "-c=4"])
    assert COMMANDS["benchmark"].run(flags, rest) == 0


def test_benchmark_cpu_accounting(stack):
    """-cpu=true (default) reports requests per core-second — the
    hardware-independent number BASELINE.md compares against the
    reference's multi-core req/s."""
    from seaweedfs_tpu.command.benchmark_cmd import run_benchmark
    from seaweedfs_tpu.command import parse_flags
    master, _vs, _c = stack
    host = master.url().replace("http://", "")
    flags, rest = parse_flags(
        [f"-master={host}", "-n=24", "-size=256", "-c=4", "-procs=1"])
    reports: list = []
    assert run_benchmark(flags, rest, reports) == 0
    assert len(reports) == 2  # write + read
    for rep in reports:
        cpu = rep["cpu"]
        # In-process servers: all cost lands in client CPU (pid-deduped)
        assert cpu["total_s"] > 0
        assert cpu["req_per_core_sec"] > 0
        assert cpu["cpu_us_per_req"] > 0
    # -cpu=false suppresses the section
    flags, rest = parse_flags(
        [f"-master={host}", "-n=8", "-size=64", "-c=2", "-procs=1",
         "-cpu=false"])
    reports2: list = []
    assert run_benchmark(flags, rest, reports2) == 0
    assert all("cpu" not in r for r in reports2)
