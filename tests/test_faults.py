"""Fault-injection framework: spec grammar, deterministic seeding,
zero-cost-when-disarmed, /debug/faults + shell commands, and the
smoke test proving EVERY registered fault point is reachable (arms
it, observes the induced failure, disarms) so dead points can't rot."""

import time

import pytest

from seaweedfs_tpu import fault
from seaweedfs_tpu.cluster import resilience, rpc
from seaweedfs_tpu.cluster.client import WeedClient
from seaweedfs_tpu.cluster.master import MasterServer
from seaweedfs_tpu.cluster.volume_server import VolumeServer
from seaweedfs_tpu.fault import registry
from seaweedfs_tpu.parallel import cluster_rebuild
from seaweedfs_tpu.replication import ReplicationShipper

pytestmark = pytest.mark.faults


@pytest.fixture(autouse=True)
def _clean():
    fault.disarm_all()
    resilience.reset_breakers()
    yield
    fault.disarm_all()
    resilience.reset_breakers()


def _flush_pool():
    """Close every idle client connection so 'fresh dial' vs 'pooled
    reuse' is deterministic per test."""
    rpc.set_client_ssl_context(None)


# -- spec grammar ------------------------------------------------------------

def test_spec_grammar_variants():
    s = registry.FaultSpec("rpc.connect", "fail")
    assert (s.kind, s.times, s.prob, s.match) == ("fail", -1, 1.0, "")
    s = registry.FaultSpec("rpc.connect", "fail*2")
    assert (s.kind, s.times) == ("fail", 2)
    s = registry.FaultSpec("rpc.connect", "delay:0.25")
    assert (s.kind, s.arg) == ("delay", 0.25)
    s = registry.FaultSpec("rpc.connect", "status:503*3@0.5~10.0.0.1")
    assert (s.kind, int(s.arg), s.times, s.prob, s.match) == \
        ("status", 503, 3, 0.5, "10.0.0.1")
    s = registry.FaultSpec("volume.read", "drop*1")
    assert s.kind == "drop"


@pytest.mark.parametrize("bad", [
    "explode", "fail*0", "fail*-1", "status:200", "status:700",
    "fail@0", "fail@1.5", "delay:abc",
])
def test_spec_grammar_rejects(bad):
    with pytest.raises(ValueError):
        registry.FaultSpec("rpc.connect", bad)


def test_arm_rejects_unknown_point():
    with pytest.raises(ValueError):
        fault.arm("no.such.point", "fail")


def test_env_grammar_arms_and_rejects(monkeypatch):
    armed = registry.arm_from_env(
        "rpc.connect=fail*1; volume.read=delay:0")
    assert armed == ["rpc.connect", "volume.read"]
    assert set(registry.ARMED) == {"rpc.connect", "volume.read"}
    fault.disarm_all()
    with pytest.raises(ValueError):
        registry.arm_from_env("rpc.connect")  # missing =spec
    with pytest.raises(ValueError):
        registry.arm_from_env("bogus.point=fail")


def test_times_auto_disarms():
    fault.arm("rpc.connect", "fail*2")
    for _ in range(2):
        with pytest.raises(ConnectionError):
            registry.hit("rpc.connect")
    assert "rpc.connect" not in registry.ARMED
    registry.hit("rpc.connect")  # disarmed: no-op


def test_match_filters_by_context():
    fault.arm("rpc.connect", "fail~10.9.9.9:1234")
    registry.hit("rpc.connect", host="127.0.0.1:80")  # no match: pass
    with pytest.raises(ConnectionError):
        registry.hit("rpc.connect", host="10.9.9.9:1234")


def test_prob_deterministic_from_seed(monkeypatch):
    def trigger_pattern():
        spec = registry.FaultSpec("rpc.connect", "fail@0.5")
        out = []
        for _ in range(32):
            try:
                spec.fire({})
                out.append(0)
            except ConnectionError:
                out.append(1)
        return out

    monkeypatch.setenv("SEAWEEDFS_TPU_FAULTS_SEED", "42")
    a = trigger_pattern()
    b = trigger_pattern()
    assert a == b                      # same seed -> same chaos run
    assert 0 < sum(a) < 32             # actually probabilistic
    monkeypatch.setenv("SEAWEEDFS_TPU_FAULTS_SEED", "43")
    c = trigger_pattern()
    assert a != c                      # different seed -> different run


# -- zero cost when disarmed -------------------------------------------------

def test_disarmed_hot_path_is_a_single_dict_check(monkeypatch):
    """The disarmed contract: call sites guard on `if ARMED:` (one
    dict truthiness check, no locks, no allocation) and never even
    call hit().  Proven by replacing hit with a bomb and running the
    full client/server hot path with nothing armed."""
    assert type(registry.ARMED) is dict and not registry.ARMED

    def bomb(point, **ctx):  # pragma: no cover — must never run
        raise AssertionError(f"hit({point}) called while disarmed")

    monkeypatch.setattr(registry, "hit", bomb)
    server = rpc.JsonHttpServer()
    server.route("GET", "/ok", lambda q, b: {"ok": True})
    server.start()
    try:
        for _ in range(3):
            assert rpc.call(f"http://127.0.0.1:{server.port}/ok") == \
                {"ok": True}
    finally:
        server.stop()


# -- /debug/faults + shell ---------------------------------------------------

def test_debug_faults_endpoint(monkeypatch):
    monkeypatch.setenv("SEAWEEDFS_TPU_FAULTS", "")
    server = rpc.JsonHttpServer()
    fault.setup_fault_routes(server)
    server.start()
    base = f"http://127.0.0.1:{server.port}/debug/faults"
    try:
        out = rpc.call(base)
        assert {p["point"] for p in out["points"]} == \
            set(registry.POINTS)
        assert not any(p["armed"] for p in out["points"])
        out = rpc.call(f"{base}?point=volume.read&spec=fail*1", "POST")
        assert out["armed"] is True
        assert registry.ARMED["volume.read"].times == 1
        with pytest.raises(rpc.RpcError) as ei:
            rpc.call(f"{base}?point=volume.read&spec=explode", "POST")
        assert ei.value.status == 400
        out = rpc.call(f"{base}?point=volume.read&spec=off", "POST")
        assert out["armed"] is False
        fault.arm("rpc.connect", "fail~nowhere")
        out = rpc.call(f"{base}?disarm=all", "POST")
        assert not registry.ARMED
    finally:
        server.stop()


def test_route_not_mounted_without_opt_in(monkeypatch):
    monkeypatch.delenv("SEAWEEDFS_TPU_FAULTS", raising=False)
    monkeypatch.delenv("SEAWEEDFS_TPU_FAULTS_DEBUG", raising=False)
    server = rpc.JsonHttpServer()
    fault.setup_fault_routes(server)
    server.start()
    try:
        with pytest.raises(rpc.RpcError) as ei:
            rpc.call(f"http://127.0.0.1:{server.port}/debug/faults")
        assert ei.value.status == 404
    finally:
        server.stop()


def test_shell_fault_ls_and_set(monkeypatch):
    from seaweedfs_tpu.shell import run_command
    from seaweedfs_tpu.shell.env import CommandEnv, ShellError
    monkeypatch.setenv("SEAWEEDFS_TPU_FAULTS", "")
    server = rpc.JsonHttpServer()
    fault.setup_fault_routes(server)
    server.start()
    try:
        env = CommandEnv(f"http://127.0.0.1:{server.port}")
        # Point the walk at our lone server (no real master topology).
        # Use a SERVER-side point: arming a client-plane point (rpc.*)
        # over HTTP in a single-process test would trip the arming
        # request's own response read.
        hostport = f"127.0.0.1:{server.port}"
        out = run_command(env, f"fault.set volume.read fail*1 "
                               f"-server {hostport}")
        assert "armed" in out and "volume.read" in out
        assert registry.ARMED["volume.read"].times == 1
        out = run_command(env, f"fault.ls -server {hostport}")
        assert "volume.read" in out and "fail*1" in out
        out = run_command(env, f"fault.set volume.read off "
                               f"-server {hostport}")
        assert "disarmed" in out
        assert "volume.read" not in registry.ARMED
        with pytest.raises(ShellError):
            run_command(env, f"fault.set bogus fail -server {hostport}")
        with pytest.raises(ShellError):
            run_command(env, f"fault.set volume.read explode "
                             f"-server {hostport}")
    finally:
        server.stop()


# -- every fault point is reachable (the anti-rot smoke test) ----------------

_APPLY_CALLS = [0]


def _stub_replication_apply(q, b):
    """Standby-shaped apply endpoint for the wan.* drivers: acks
    everything, counts deliveries (the wan.duplicate proof)."""
    _APPLY_CALLS[0] += 1
    return {"acked_seq": 0, "applied": 0, "skipped": 0}


@pytest.fixture(scope="module")
def smoke_cluster(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("faultsmoke")
    master = MasterServer(volume_size_limit_mb=16, meta_dir=str(tmp))
    master.start()
    servers = []
    for i in range(2):
        d = tmp / f"vs{i}"
        d.mkdir()
        vs = VolumeServer(master.url(), [str(d)], pulse_seconds=60)
        vs.start()
        servers.append(vs)
    # Stub peer for the EC fetch/scatter drivers: serves a shard file
    # and accepts shard pushes without a full EC volume on disk.
    stub = rpc.JsonHttpServer()
    stub.route("GET", "/admin/ec/shard_file",
               lambda q, b: b"\x07" * 64)
    stub.route("POST", "/admin/ec/receive_shard", lambda q, b: {})
    stub.route("POST", "/admin/replication/apply",
               _stub_replication_apply)
    stub.start()
    client = WeedClient(master.url())
    yield master, servers, stub, client
    stub.stop()
    for vs in servers:
        vs.stop()
    master.stop()


def _drive_rpc_connect(cl):
    _master, _servers, stub, _client = cl
    hostport = f"127.0.0.1:{stub.port}"
    fault.arm("rpc.connect", f"fail*1~{hostport}")
    with pytest.raises(ConnectionError):
        rpc.call(f"http://{hostport}/admin/ec/shard_file")


def _drive_rpc_send(cl):
    _master, _servers, stub, _client = cl
    hostport = f"127.0.0.1:{stub.port}"
    fault.arm("rpc.send", f"status:503*1~{hostport}")
    with pytest.raises(rpc.RpcError) as ei:
        rpc.call(f"http://{hostport}/admin/ec/shard_file")
    assert ei.value.status == 503


def _drive_rpc_recv(cl):
    _master, _servers, stub, _client = cl
    _flush_pool()  # fresh (non-reused) conn: no stale-keep-alive retry
    hostport = f"127.0.0.1:{stub.port}"
    fault.arm("rpc.recv", f"fail*1~{hostport}")
    with pytest.raises(ConnectionError):
        rpc.call(f"http://{hostport}/admin/ec/shard_file")


def _drive_volume_write(cl):
    _master, _servers, _stub, client = cl
    a = client.assign()
    fault.arm("volume.write", "status:500*1")
    with pytest.raises(rpc.RpcError) as ei:
        rpc.call(f"http://{a['url']}/{a['fid']}", "POST", b"x")
    assert ei.value.status == 500


def _drive_volume_read(cl):
    _master, _servers, _stub, client = cl
    fid = client.upload_data(b"drop me")
    url = client.lookup(int(fid.split(",")[0]))[0]["url"]
    _flush_pool()
    # drop: the server kills the connection with no response bytes.
    fault.arm("volume.read", "drop*1")
    with pytest.raises(ConnectionError):
        rpc.call(f"http://{url}/{fid}")
    assert client.download(fid) == b"drop me"  # disarmed: healthy


def _drive_volume_replicate(cl):
    _master, _servers, _stub, client = cl
    a = client.assign(replication="001")
    fault.arm("volume.replicate", "fail*1")
    with pytest.raises(rpc.RpcError) as ei:
        rpc.call(f"http://{a['url']}/{a['fid']}", "POST", b"x")
    assert ei.value.status == 500
    assert "replication failed" in ei.value.message


def _drive_ec_fetch_shard(cl):
    _master, _servers, stub, _client = cl
    hostport = f"127.0.0.1:{stub.port}"
    fault.arm("ec.fetch_shard", "fail*1")
    # First holder attempt fails (injected), the retry round recovers:
    # one flaky node must not fail the fetch.
    data = cluster_rebuild._fetch_shard(
        [hostport], 7, 0, attempt_timeout=5.0, total_deadline=10.0)
    assert data == b"\x07" * 64


def _drive_ec_scatter(cl):
    _master, _servers, stub, _client = cl
    hostport = f"127.0.0.1:{stub.port}"
    fault.arm("ec.scatter", "fail*1")
    with pytest.raises(rpc.RpcError) as ei:
        cluster_rebuild._push_shard(7, 0, b"\x07" * 64, hostport,
                                    [hostport])
    assert ei.value.status == 502
    fault.disarm_all()
    cluster_rebuild._push_shard(7, 0, b"\x07" * 64, hostport,
                                [hostport])


def _drive_master_heartbeat(cl):
    _master, servers, _stub, _client = cl
    fault.arm("master.heartbeat", "fail*1")
    servers[0]._send_heartbeat()  # injected failure -> rotate path


def _drive_volume_corrupt(cl):
    """Bit-rot injection: the write SUCCEEDS, the rot is caught by CRC
    on the read (which then 500s — single copy, nothing to heal from)."""
    _master, _servers, _stub, client = cl
    a = client.assign()
    fault.arm("volume.corrupt", "fail*1")
    rpc.call(f"http://{a['url']}/{a['fid']}", "POST", b"rot me " * 8)
    with pytest.raises(rpc.RpcError) as ei:
        rpc.call(f"http://{a['url']}/{a['fid']}")
    assert ei.value.status == 500


def _drive_disk_read(cl):
    """A one-shot injected sector failure 500s the read; the next read
    (fault exhausted, bytes were always fine) succeeds."""
    _master, _servers, _stub, client = cl
    fid = client.upload_data(b"sector bytes")
    url = client.lookup(int(fid.split(",")[0]))[0]["url"]
    fault.arm("disk.read", "fail*1")
    with pytest.raises(rpc.RpcError) as ei:
        rpc.call(f"http://{url}/{fid}")
    assert ei.value.status == 500
    assert client.download(fid) == b"sector bytes"


def _drive_disk_full(cl):
    """Injected ENOSPC mid-append: the write 500s, the partial record
    is rolled back (no torn tail) and the volume flips readonly."""
    _master, _servers, _stub, client = cl
    a = client.assign()
    fault.arm("disk.full", "fail*1")
    with pytest.raises(rpc.RpcError) as ei:
        rpc.call(f"http://{a['url']}/{a['fid']}", "POST",
                 b"no space left " * 8)
    assert ei.value.status == 500
    assert "disk full" in ei.value.message


def _drive_net_slow_client(cl):
    """A one-shot stall mid-request-send: with the fixture server's
    default (long) idle timeout the request still completes — the
    reaping behavior is proven in tests/test_overload.py."""
    _master, _servers, stub, _client = cl
    fault.arm("net.slow_client", "delay:0.05*1")
    rpc.call(f"http://127.0.0.1:{stub.port}/admin/ec/shard_file")


def _drive_wan_partition(cl):
    """The shipped batch never arrives (WAN partition): the first send
    dies at the wire, the retry policy re-sends — safe, because the
    receiver applies idempotently by seq — and the batch lands once."""
    _master, servers, stub, _client = cl
    sh = ReplicationShipper(servers[0].store, "127.0.0.1:1")
    n0 = _APPLY_CALLS[0]
    fault.arm("wan.partition", "fail*1")
    out = sh._post(f"127.0.0.1:{stub.port}", 1,
                   {"volume": 1, "records": []})
    assert out["acked_seq"] == 0
    assert _APPLY_CALLS[0] - n0 == 1  # failed send never reached the wire


def _drive_wan_delay(cl):
    """WAN latency shaping on the ship path: the send is delayed, not
    failed, and completes."""
    _master, servers, stub, _client = cl
    sh = ReplicationShipper(servers[0].store, "127.0.0.1:1")
    fault.arm("wan.delay", "delay:0.01*1")
    out = sh._post(f"127.0.0.1:{stub.port}", 1,
                   {"volume": 1, "records": []})
    assert out["acked_seq"] == 0


def _drive_wan_reorder(cl):
    """Out-of-order delivery on purpose: with batch n in hand and a
    batch n+1 pending behind it, the armed hook posts n+1 FIRST and
    counts the resend.  The receiver-side invariant — a gapped batch
    is refused 409 WITHOUT acking, so in-order re-delivery converges
    with nothing skipped — is proven end-to-end in test_geo.py."""
    from seaweedfs_tpu.core.needle import Needle
    from seaweedfs_tpu.stats.metrics import replication_resends_total
    _master, servers, stub, _client = cl
    # Any server with a spare volume slot: earlier drivers' assigns
    # grow 7 single-copy + paired 001 volumes with RANDOM node
    # placement, which can fill one (never both) of the two 7-slot
    # stores before this driver runs.
    vs = next(s for s in servers if s.store.free_location())
    vid = 7777
    v = vs.store.add_volume(vid, "reordercol", "000", "")
    v.enable_rlog()
    for key in (1, 2):  # two journaled writes -> two 1-record batches
        v.write_needle(Needle(cookie=0x7, id=key, data=b"reorder me"))
    sh = ReplicationShipper(vs.store, "127.0.0.1:1", batch_records=1)
    before = replication_resends_total.value(reason="reorder")
    n0 = _APPLY_CALLS[0]
    fault.arm("wan.reorder", "fail*1")
    recs = v.rlog.read_from(1, 1)  # batch n, about to be sent
    sh._maybe_reorder(v, v.rlog, recs, f"127.0.0.1:{stub.port}")
    assert _APPLY_CALLS[0] - n0 == 1, "batch n+1 must go out first"
    assert replication_resends_total.value(
        reason="reorder") - before == 1


def _drive_wan_duplicate(cl):
    """Duplicate delivery on purpose: the shipper sends the SAME batch
    twice and counts the resend — the receiver's applied watermark
    must make the replay a no-op (proven end-to-end in test_dr.py)."""
    from seaweedfs_tpu.stats.metrics import replication_resends_total
    _master, servers, stub, _client = cl
    sh = ReplicationShipper(servers[0].store, "127.0.0.1:1")
    before = replication_resends_total.value(reason="duplicate")
    n0 = _APPLY_CALLS[0]
    fault.arm("wan.duplicate", "fail*1")
    sh._post(f"127.0.0.1:{stub.port}", 1,
             {"volume": 1, "records": []})
    assert _APPLY_CALLS[0] - n0 == 2, "the same batch must land twice"
    assert replication_resends_total.value(
        reason="duplicate") == before + 1


def _drive_tier_read(cl):
    """A WAN-partitioned tier backend: the armed fetch makes the needle
    read answer a bounded 503 (+ Retry-After) — never a hang, never a
    degraded-read repair attempt — and the next read recovers."""
    import os
    _master, servers, _stub, client = cl
    fid = client.upload_data(b"tiered needle " * 8)
    vid = int(fid.split(",")[0])
    url = client.lookup(vid)[0]["url"]
    vs = next(s for s in servers
              if s.url().replace("http://", "") == url)
    dest = os.path.join(vs.store.locations[0].directory, "..",
                        "tierfault")
    rpc.call_json(f"http://{url}/admin/readonly", "POST",
                  {"volume": vid, "readonly": True})
    rpc.call_json(f"http://{url}/admin/tier_upload", "POST",
                  {"volume": vid, "dest": f"local://{dest}"})
    fault.arm("tier.read", "fail*1")
    t0 = time.monotonic()
    with pytest.raises(rpc.RpcError) as ei:
        rpc.call(f"http://{url}/{fid}")
    assert ei.value.status == 503
    assert ei.value.retry_after  # the 503 carries a pacing hint
    assert time.monotonic() - t0 < 10.0  # bounded, not a hang
    assert client.download(fid) == b"tiered needle " * 8
    rpc.call_json(f"http://{url}/admin/tier_download", "POST",
                  {"volume": vid})


DRIVERS = {
    "rpc.connect": _drive_rpc_connect,
    "rpc.send": _drive_rpc_send,
    "rpc.recv": _drive_rpc_recv,
    "volume.write": _drive_volume_write,
    "volume.read": _drive_volume_read,
    "volume.replicate": _drive_volume_replicate,
    "ec.fetch_shard": _drive_ec_fetch_shard,
    "ec.scatter": _drive_ec_scatter,
    "master.heartbeat": _drive_master_heartbeat,
    "volume.corrupt": _drive_volume_corrupt,
    "disk.read": _drive_disk_read,
    "disk.full": _drive_disk_full,
    "net.slow_client": _drive_net_slow_client,
    "wan.partition": _drive_wan_partition,
    "wan.delay": _drive_wan_delay,
    "wan.duplicate": _drive_wan_duplicate,
    "wan.reorder": _drive_wan_reorder,
    "tier.read": _drive_tier_read,
}


def test_driver_catalog_matches_registry():
    """Registering a fault point without a reachability driver (or
    vice versa) fails here: the catalog and the smoke suite move in
    lockstep."""
    assert set(DRIVERS) == set(registry.POINTS)


@pytest.mark.parametrize("point", sorted(registry.POINTS))
def test_every_fault_point_is_reachable(smoke_cluster, point):
    """Arm each point, drive the real code path that hosts its hook,
    observe the induced failure, disarm.  A hook that code motion
    orphaned shows up as triggered == 0."""
    before = registry.faults_injected_total.value(point=point)
    DRIVERS[point](smoke_cluster)
    after = registry.faults_injected_total.value(point=point)
    assert after > before, f"fault point {point} never triggered"
    fault.disarm_all()
