"""TLS plane end-to-end (reference weed/security/tls.go, guard.go:43-65).

security.toml's [grpc.*] sections drive mutual TLS for every
inter-server RPC: servers present their role cert and require CA-signed
client certs; the client context installs process-wide and upgrades all
http:// cluster URLs to TLS.  Covered here: a master+volume cluster
doing a full write/read cycle over mTLS, plaintext clients rejected,
and certless TLS clients rejected.
"""

import subprocess

import pytest

from seaweedfs_tpu.cluster import rpc
from seaweedfs_tpu.cluster.client import WeedClient
from seaweedfs_tpu.cluster.master import MasterServer
from seaweedfs_tpu.cluster.volume_server import VolumeServer
from seaweedfs_tpu.utils.config import load_configuration
from seaweedfs_tpu.utils.security import (
    install_cluster_tls,
    load_client_tls,
    load_server_tls,
    tls_client_context,
)


def _openssl(*args) -> None:
    subprocess.run(["openssl", *args], check=True, capture_output=True)


@pytest.fixture(scope="module")
def certs(tmp_path_factory):
    """A throwaway CA plus CA-signed server and client certs."""
    d = tmp_path_factory.mktemp("tls")
    _openssl("req", "-x509", "-newkey", "rsa:2048", "-nodes", "-days", "1",
             "-keyout", str(d / "ca.key"), "-out", str(d / "ca.crt"),
             "-subj", "/CN=weed-test-ca")
    for name in ("server", "client"):
        _openssl("req", "-newkey", "rsa:2048", "-nodes",
                 "-keyout", str(d / f"{name}.key"),
                 "-out", str(d / f"{name}.csr"),
                 "-subj", f"/CN=weed-{name}")
        _openssl("x509", "-req", "-days", "1",
                 "-in", str(d / f"{name}.csr"),
                 "-CA", str(d / "ca.crt"), "-CAkey", str(d / "ca.key"),
                 "-CAcreateserial", "-out", str(d / f"{name}.crt"))
    return d


@pytest.fixture
def security_cfg(certs, tmp_path):
    """A real security.toml on disk, loaded through the config search
    path — the same plumbing `weed master`/`weed volume` use."""
    (tmp_path / "security.toml").write_text(f'''
[grpc]
ca = "{certs / 'ca.crt'}"

[grpc.master]
cert = "{certs / 'server.crt'}"
key  = "{certs / 'server.key'}"
client_auth = "require"

[grpc.volume]
cert = "{certs / 'server.crt'}"
key  = "{certs / 'server.key'}"
client_auth = "require"

[grpc.client]
cert = "{certs / 'client.crt'}"
key  = "{certs / 'client.key'}"
''')
    return load_configuration("security", search_paths=[str(tmp_path)])


@pytest.fixture
def tls_cluster(security_cfg, tmp_path):
    """master + volume server, both serving mTLS, client plane installed."""
    assert install_cluster_tls(security_cfg) is True
    master = MasterServer(volume_size_limit_mb=64, meta_dir=str(tmp_path),
                          ssl_context=load_server_tls(security_cfg,
                                                      "master"))
    master.start()
    d = tmp_path / "vs0"
    d.mkdir()
    vs = VolumeServer(master.url(), [str(d)], pulse_seconds=60,
                      ssl_context=load_server_tls(security_cfg, "volume"))
    vs.start()
    try:
        yield security_cfg, master, vs
    finally:
        vs.stop()
        master.stop()
        rpc.set_client_ssl_context(None)


def test_full_cycle_over_mtls(tls_cluster):
    _cfg, master, vs = tls_cluster
    client = WeedClient(master.url())
    fid = client.upload_data(b"over-the-tls-wire")
    assert bytes(client.download(fid)) == b"over-the-tls-wire"
    # The status endpoint answers over TLS; volume locations the master
    # hands out are bare host:port upgraded by the transport.
    st = rpc.call(f"{master.url()}/dir/status")
    assert st["topology"]


def test_plaintext_client_rejected(tls_cluster):
    _cfg, master, _vs = tls_cluster
    rpc.set_client_ssl_context(None)  # back to plaintext http
    host_port = master.url().split("://", 1)[1]
    try:
        with pytest.raises(Exception):
            rpc.call(f"http://{host_port}/dir/status", timeout=5.0)
    finally:
        install_cluster_tls(_cfg)


def test_client_without_cert_rejected(tls_cluster):
    cfg, master, _vs = tls_cluster
    # A TLS client that trusts the CA but presents no certificate must
    # fail the handshake: the server runs RequireAndVerifyClientCert
    # semantics (tls.go:36-38).
    certless = tls_client_context(ca_file=cfg.get_string("grpc.ca"))
    rpc.set_client_ssl_context(certless, force_https=True)
    try:
        with pytest.raises(Exception):
            rpc.call(f"{master.url()}/dir/status", timeout=5.0)
    finally:
        install_cluster_tls(cfg)


def test_gateway_default_is_server_auth_only(security_cfg, certs):
    """Components without client_auth="require" (the gateways: s3,
    webdav, filer) serve plain TLS so cert-less standard clients — curl,
    aws-cli — can still connect; see load_server_tls's policy note."""
    import ssl
    cfg_text = f'''
[grpc]
ca = "{certs / 'ca.crt'}"
[grpc.s3]
cert = "{certs / 'server.crt'}"
key  = "{certs / 'server.key'}"
'''
    from seaweedfs_tpu.utils.config import (Configuration,
                                            tomllib)  # tomli fallback on 3.10
    cfg = Configuration(tomllib.loads(cfg_text))
    ctx = load_server_tls(cfg, "s3")
    assert ctx.verify_mode == ssl.CERT_NONE
    # and the mTLS components from the shared fixture do require certs:
    assert load_server_tls(security_cfg,
                           "master").verify_mode == ssl.CERT_REQUIRED


def test_client_auth_validation(certs):
    from seaweedfs_tpu.utils.config import (Configuration,
                                            tomllib)  # tomli fallback on 3.10
    bad = Configuration(tomllib.loads(f'''
[grpc.master]
cert = "{certs / 'server.crt'}"
key  = "{certs / 'server.key'}"
client_auth = "maybe"
'''))
    with pytest.raises(ValueError):
        load_server_tls(bad, "master")
    no_ca = Configuration(tomllib.loads(f'''
[grpc.master]
cert = "{certs / 'server.crt'}"
key  = "{certs / 'server.key'}"
client_auth = "require"
'''))
    with pytest.raises(ValueError):
        load_server_tls(no_ca, "master")


def test_load_client_tls_requires_all_three(tmp_path, certs):
    (tmp_path / "security.toml").write_text(f'''
[grpc.client]
cert = "{certs / 'client.crt'}"
key  = "{certs / 'client.key'}"
''')
    cfg = load_configuration("security", search_paths=[str(tmp_path)])
    # No CA -> insecure fallback, exactly like tls.go:48-51.
    assert load_client_tls(cfg) is None
    assert install_cluster_tls(cfg) is False
