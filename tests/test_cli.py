"""CLI dispatcher + offline commands + utils (glog/config/security).

Reference surfaces: weed/weed.go:38-80 (dispatch), weed/command/fix.go,
compact.go, export.go, scaffold.go, upload.go, download.go;
weed/util/config.go (TOML + WEED_ env); weed/security/jwt.go.
"""

from __future__ import annotations

import io
import json
import os
import tarfile

import pytest

from seaweedfs_tpu.command import main, parse_flags
from seaweedfs_tpu.core.needle import Needle
from seaweedfs_tpu.storage.volume import Volume
from seaweedfs_tpu.utils import security
from seaweedfs_tpu.utils.config import Configuration, load_configuration


# -- flag parsing ------------------------------------------------------------

def test_parse_flags_styles():
    flags, rest = parse_flags(["-port", "9333", "-dir=/d", "-quiet=true",
                               "file1", "file2"])
    assert flags.get_int("port") == 9333
    assert flags.get("dir") == "/d"
    assert flags.get_bool("quiet") is True
    assert rest == ["file1", "file2"]
    flags2, rest2 = parse_flags(["-force"])  # trailing bare boolean
    assert flags2.get_bool("force") is True and rest2 == []


def test_usage_and_unknown(capsys):
    assert main([]) == 0
    out = capsys.readouterr().out
    for name in ("master", "volume", "filer", "s3", "shell", "upload",
                 "download", "fix", "compact", "export", "scaffold",
                 "version", "server", "watch", "webdav"):
        assert name in out, f"command {name} not registered"
    assert main(["nonsense"]) == 2


def test_version(capsys):
    assert main(["version"]) == 0
    assert "version" in capsys.readouterr().out


def test_scaffold(capsys, tmp_path):
    assert main(["scaffold", "-config=security"]) == 0
    assert "[jwt.signing]" in capsys.readouterr().out
    assert main(["scaffold", "-config=filer",
                 f"-output={tmp_path}"]) == 0
    assert (tmp_path / "filer.toml").is_file()


# -- offline commands on a real volume --------------------------------------

@pytest.fixture
def volume_dir(tmp_path):
    vol = Volume(str(tmp_path), "", 7)
    for i in range(1, 21):
        n = Needle(id=i, cookie=0x1234, data=f"payload-{i}".encode())
        n.set_name(f"file-{i}.txt".encode())
        vol.write_needle(n)
    vol.delete_needle(3)
    vol.delete_needle(9)
    vol.close()
    return tmp_path


def test_fix_regenerates_idx(volume_dir, capsys):
    idx = volume_dir / "7.idx"
    original = idx.read_bytes()
    idx.unlink()
    assert main(["fix", f"-dir={volume_dir}", "-volumeId=7"]) == 0
    regenerated = idx.read_bytes()
    # Same live set: reload and compare the needle map contents.
    vol = Volume(str(volume_dir), "", 7)
    try:
        assert vol.file_count() == 18
        assert vol.read_needle(5).data == b"payload-5"
        with pytest.raises(Exception):
            vol.read_needle(3)
    finally:
        vol.close()
    assert len(regenerated) >= len(original) - 32


def test_compact_shrinks(volume_dir):
    before = (volume_dir / "7.dat").stat().st_size
    assert main(["compact", f"-dir={volume_dir}", "-volumeId=7"]) == 0
    after = (volume_dir / "7.dat").stat().st_size
    assert after < before
    vol = Volume(str(volume_dir), "", 7)
    try:
        assert vol.read_needle(5).data == b"payload-5"
        with pytest.raises(Exception):
            vol.read_needle(3)
    finally:
        vol.close()


def test_export_tar_and_listing(volume_dir, tmp_path, capsys):
    tar_path = tmp_path / "out.tar"
    assert main(["export", f"-dir={volume_dir}", "-volumeId=7",
                 f"-o={tar_path}"]) == 0
    with tarfile.open(tar_path) as tar:
        names = tar.getnames()
        assert "file-5.txt" in names and "file-3.txt" not in names
        data = tar.extractfile("file-5.txt").read()
        assert data == b"payload-5"
    # listing mode (no -o)
    assert main(["export", f"-dir={volume_dir}", "-volumeId=7"]) == 0
    out = capsys.readouterr().out
    assert "file-5.txt" in out and "file-9.txt" not in out


# -- config ------------------------------------------------------------------

def test_config_load_and_env_override(tmp_path, monkeypatch):
    (tmp_path / "security.toml").write_text(
        '[jwt.signing]\nkey = "abc"\nexpires_after_seconds = 10\n')
    cfg = load_configuration("security", search_paths=[str(tmp_path)])
    assert cfg.get_string("jwt.signing.key") == "abc"
    assert cfg.get_int("jwt.signing.expires_after_seconds") == 10
    monkeypatch.setenv("WEED_JWT_SIGNING_KEY", "override")
    assert cfg.get_string("jwt.signing.key") == "override"
    # missing optional config is empty, required raises
    assert load_configuration("nothere",
                              search_paths=[str(tmp_path)]).get("x") is None
    with pytest.raises(FileNotFoundError):
        load_configuration("nothere", required=True,
                           search_paths=[str(tmp_path)])


def test_config_sub_and_bool():
    cfg = Configuration({"sqlite": {"enabled": True, "file": "f.db"}})
    assert cfg.get_bool("sqlite.enabled") is True
    assert cfg.sub("sqlite") == {"enabled": True, "file": "f.db"}


# -- security / jwt ----------------------------------------------------------

def test_jwt_round_trip():
    tok = security.gen_jwt("secret", 60, "3,0144b2c8f1")
    claims = security.decode_jwt("secret", tok)
    assert claims["fid"] == "3,0144b2c8f1"


def test_jwt_bad_signature_and_expiry():
    tok = security.gen_jwt("secret", 60, "3,ab")
    with pytest.raises(security.JwtError):
        security.decode_jwt("wrong", tok)
    expired = security.gen_jwt("secret", -100, "3,ab")
    with pytest.raises(security.JwtError):
        security.decode_jwt("secret", expired)


def test_guard():
    g = security.Guard(signing_key="k", expires_seconds=60)
    assert g.is_active
    tok = security.gen_jwt("k", 60, "3,ab")
    g.check_jwt(tok, "3,ab")
    g.check_jwt(tok, "3,ab_1")  # chunk-suffix variants allowed
    with pytest.raises(security.JwtError):
        g.check_jwt(tok, "4,cd")
    with pytest.raises(security.JwtError):
        g.check_jwt("", "3,ab")
    inactive = security.Guard()
    assert not inactive.is_active
    inactive.check_jwt("", "3,ab")  # no-op when no key configured


def test_glog(capsys):
    from seaweedfs_tpu.utils import glog
    glog.setup(verbosity=1)
    glog.infof("hello %s", "world")
    glog.v(1).infof("visible")
    glog.v(5).infof("hidden")
    err = capsys.readouterr().err
    assert "hello world" in err and "visible" in err
    assert "hidden" not in err


# -- end-to-end: `weed server` subprocess + upload/download ------------------

def test_server_upload_download_roundtrip(tmp_path, capsys):
    import socket
    import subprocess
    import sys as _sys
    import time as _time
    import urllib.request

    def free_port():
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    mport, vport = free_port(), free_port()
    data_dir = tmp_path / "data"
    data_dir.mkdir()
    proc = subprocess.Popen(
        [_sys.executable, "-m", "seaweedfs_tpu", "server",
         f"-master.port={mport}", f"-volume.port={vport}",
         f"-dir={data_dir}", f"-mdir={tmp_path}"],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    try:
        deadline = _time.time() + 20
        while True:  # wait until the volume server has registered
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{mport}/dir/status",
                        timeout=1) as resp:
                    status = json.loads(resp.read())
                if status.get("topology", {}).get("children"):
                    break  # a data node has registered
            except Exception:
                pass
            if _time.time() > deadline:
                raise TimeoutError("cluster did not come up")
            _time.sleep(0.2)
        src = tmp_path / "hello.txt"
        src.write_bytes(b"hello from the cli")
        assert main(["upload", f"-master=127.0.0.1:{mport}",
                     str(src)]) == 0
        fid = json.loads(capsys.readouterr().out)[0]["fid"]
        out_dir = tmp_path / "dl"
        assert main(["download", f"-server=127.0.0.1:{mport}",
                     f"-dir={out_dir}", fid]) == 0
        name = fid.replace(",", "_")
        assert (out_dir / name).read_bytes() == b"hello from the cli"
    finally:
        proc.terminate()
        proc.wait(timeout=10)


@pytest.mark.parametrize("transport", ["json", "grpc"])
def test_server_full_stack_s3_webdav(tmp_path, transport):
    """Capstone: one `weed server -filer=true -s3=true -webdav=true`
    process; an object PUT through the S3 gateway reads back through
    S3, the filer HTTP API, and WebDAV.

    Parametrized over the filer's internal master transport: with
    WEED_INTERNAL_GRPC=1 the filer's assign/lookup traffic rides the
    wire-compatible master_pb.Seaweed gRPC plane instead of the JSON
    plane, so the gRPC facade is exercised by real cluster operation,
    not only its dedicated tests (round-4 facade-drift canary)."""
    import os as _os
    import socket
    import subprocess
    import sys as _sys
    import time as _time
    import urllib.request

    def free_port():
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    mport, vport, fport, s3port, davport = (free_port() for _ in range(5))
    data_dir = tmp_path / "data"
    data_dir.mkdir()
    env = dict(_os.environ)
    if transport == "grpc":
        env["WEED_INTERNAL_GRPC"] = "1"
    proc = subprocess.Popen(
        [_sys.executable, "-m", "seaweedfs_tpu", "server",
         f"-master.port={mport}", f"-volume.port={vport}",
         f"-dir={data_dir}", f"-mdir={tmp_path}",
         "-filer=true", f"-filer.port={fport}",
         "-s3=true", f"-s3.port={s3port}",
         "-webdav=true", f"-webdav.port={davport}"],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)

    def wait_http(url, deadline):
        while _time.time() < deadline:
            try:
                urllib.request.urlopen(url, timeout=1)
                return
            except urllib.error.HTTPError:
                return  # server answered (any status)
            except Exception:
                _time.sleep(0.2)
        raise TimeoutError(url)

    try:
        deadline = _time.time() + 30
        for port, path in ((mport, "/dir/status"), (fport, "/"),
                           (s3port, "/"), (davport, "/")):
            wait_http(f"http://127.0.0.1:{port}{path}", deadline)
        # wait for the volume server registration
        while _time.time() < deadline:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{mport}/dir/status",
                    timeout=2) as resp:
                if json.loads(resp.read()).get(
                        "topology", {}).get("children"):
                    break
            _time.sleep(0.2)
        s3 = f"http://127.0.0.1:{s3port}"
        body = b"through the S3 gateway" * 10
        # create bucket + put object (anonymous mode: no identities)
        urllib.request.urlopen(urllib.request.Request(
            f"{s3}/caps", method="PUT"), timeout=10)
        urllib.request.urlopen(urllib.request.Request(
            f"{s3}/caps/dir/obj.txt", data=body, method="PUT"),
            timeout=10)
        # read back through S3
        with urllib.request.urlopen(f"{s3}/caps/dir/obj.txt",
                                    timeout=10) as resp:
            assert resp.read() == body
        # the same object through the filer namespace
        with urllib.request.urlopen(
                f"http://127.0.0.1:{fport}/buckets/caps/dir/obj.txt",
                timeout=10) as resp:
            assert resp.read() == body
        # and through WebDAV
        with urllib.request.urlopen(
                f"http://127.0.0.1:{davport}/buckets/caps/dir/obj.txt",
                timeout=10) as resp:
            assert resp.read() == body
        # S3 list sees it
        with urllib.request.urlopen(
                f"{s3}/caps?list-type=2&prefix=dir/",
                timeout=10) as resp:
            listing = resp.read()
        assert b"dir/obj.txt" in listing
    finally:
        proc.terminate()
        proc.wait(timeout=10)
