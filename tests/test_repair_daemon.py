"""Durability autopilot chaos gates (cluster/repair_daemon.py).

The acceptance contract from the autopilot's introduction: permanent
loss of a replica holder and of an EC shard holder each converge back
to declared redundancy with zero operator commands, zero read
unavailability and healthz recovering 503 -> 200; a node resurrecting
mid-repair never yields duplicate or orphan replicas (checksum maps
across holders stay equal); a repair storm under an armed repair.fetch
budget keeps victim read p99 bounded while the queue drains in risk
order; and planned maintenance (drain/goodbye) never enqueues a single
repair.  Masters run with pulse_seconds=60 so nothing races the tests:
death is driven through the REAL sweep path (`dn.last_seen = 0` +
`_sweep_dead_nodes()`), repairs through the real tick/run_now paths.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.request

import pytest

from seaweedfs_tpu.cluster import rpc
from seaweedfs_tpu.cluster.client import WeedClient
from seaweedfs_tpu.cluster.master import MasterServer
from seaweedfs_tpu.cluster.volume_server import VolumeServer
from seaweedfs_tpu.events import JOURNAL
from seaweedfs_tpu.stats import flows
from seaweedfs_tpu.stats.promcheck import validate_exposition

pytestmark = pytest.mark.autorepair


# -- harness -----------------------------------------------------------------

def _mk_cluster(tmp_path, n_vs=3, **master_kw):
    master = MasterServer(volume_size_limit_mb=16,
                          meta_dir=str(tmp_path / "meta"),
                          pulse_seconds=60, **master_kw)
    master.start()
    servers = []
    for i in range(n_vs):
        d = tmp_path / f"vs{i}"
        d.mkdir()
        vs = VolumeServer(master.url(), [str(d)],
                          max_volume_counts=[200], pulse_seconds=60)
        vs.start()
        servers.append(vs)
    deadline = time.time() + 10
    while len(list(master.topo.leaves())) < n_vs:
        if time.time() > deadline:
            raise TimeoutError("volume servers never registered")
        time.sleep(0.05)
    return master, servers, WeedClient(master.url())


def _teardown(master, servers):
    for vs in servers:
        try:
            vs.stop()
        except Exception:  # noqa: BLE001
            pass
    master.stop()


def _kill(master, vs):
    """Permanent node loss through the real path: the process dies
    (stop() closes its sockets), its heartbeat goes stale, and the
    dead-node sweep unregisters it."""
    vs.stop()
    dn = next(n for n in master.topo.leaves() if n.url() == vs.url())
    dn.last_seen = 0.0
    master._sweep_dead_nodes()


def _holders(master, collection, vid):
    return sorted(dn.url() for dn in master.topo.lookup(collection, vid))


def _checksum_map(url, vid):
    return rpc.call(f"http://{url}/admin/volume/checksums?volume={vid}",
                    timeout=30.0)["checksums"]


def _events(t0, type_=""):
    return [e for e in JOURNAL.snapshot(type_=type_) if e["ts"] >= t0]


def _wait(pred, timeout=30.0, msg="condition"):
    deadline = time.time() + timeout
    while not pred():
        if time.time() > deadline:
            raise TimeoutError(f"timed out waiting for {msg}")
        time.sleep(0.05)


# -- chaos gate (a): permanent replica-holder loss ---------------------------

def test_kill_replica_holder_converges(tmp_path):
    """001 volume loses one of its two holders for good: the armed
    daemon re-replicates with ZERO operator commands, reads never
    fail, healthz goes 503 -> 200, and the new pair's checksum maps
    are equal.  Also exercises pause/resume gating and the
    /cluster/repair + /metrics surfaces."""
    t0 = time.time()
    master, servers, client = _mk_cluster(tmp_path)
    try:
        blob = os.urandom(1 << 16)
        a = client.assign(replication="001")
        fid, vid = a["fid"], int(a["fid"].split(",")[0])
        rpc.call(f"http://{a['url']}/{fid}", "POST", blob)
        assert len(_holders(master, "", vid)) == 2
        assert client.download(fid) == blob

        dead = next(vs for vs in servers
                    if vs.url() in _holders(master, "", vid))
        _kill(master, dead)
        live = [vs for vs in servers if vs is not dead]
        # Unhealthy while a registered node's heartbeat is stale:
        # re-register staleness by aging a probe BEFORE the sweep ran
        # is already consumed — the 503 leg is asserted on a fresh
        # staleness below; after sweep + repair it must be 200.
        assert len(_holders(master, "", vid)) == 1

        # Zero read unavailability mid-degradation.
        client.cache.forget(vid)
        assert client.download(fid) == blob

        # Armed daemon, paused: the deficit queues but never executes.
        master.repair.enabled = True
        master.repair.delay = 0.0
        master.repair.pause()
        master.repair.tick()
        assert any(t.vid == vid for t in master.repair._queue)
        time.sleep(0.3)
        assert len(_holders(master, "", vid)) == 1
        # Resume: the queue drains with no operator command.
        master.repair.resume()
        master.repair.tick()
        _wait(lambda: len(_holders(master, "", vid)) == 2,
              msg="re-replication")
        _wait(lambda: not master.repair._inflight, msg="executor exit")

        # Converged: reads still work from the fresh pair, healthz 200.
        client.cache.forget(vid)
        assert client.download(fid) == blob
        ok, doc = master.health_report()
        assert ok and doc["healthy"], doc["problems"]
        # The copy is verified: both holders' fsck maps are equal.
        ha, hb = _holders(master, "", vid)
        assert _checksum_map(ha, vid) == _checksum_map(hb, vid) != {}

        # The event spine: plan -> start -> finish for this volume.
        for etype in ("repair.plan", "repair.start", "repair.finish"):
            assert any(e["attrs"].get("volume") == vid
                       for e in _events(t0, etype)), etype

        # Surfaces: /cluster/repair reports the MTTR sample;
        # /metrics passes promcheck with the repair family present.
        doc = rpc.call(f"{master.url()}/cluster/repair", timeout=10.0)
        assert doc["mttr"]["count"] >= 1
        assert not doc["queue"] and not doc["inflight"]
        with urllib.request.urlopen(master.url() + "/metrics") as r:
            text = r.read().decode()
        assert validate_exposition(text) == []
        assert "SeaweedFS_repairs_total" in text
        assert "SeaweedFS_repair_seconds" in text
        assert "SeaweedFS_repair_queue_depth" in text
        assert live  # silence unused warning; survivors stay up
    finally:
        _teardown(master, servers)


def test_healthz_degrades_then_recovers(tmp_path):
    """The 503 leg of the gate: a stale registered node flips healthz
    unhealthy; after the sweep + automatic repair the report is
    healthy again."""
    master, servers, client = _mk_cluster(tmp_path)
    try:
        a = client.assign(replication="001")
        fid, vid = a["fid"], int(a["fid"].split(",")[0])
        rpc.call(f"http://{a['url']}/{fid}", "POST", b"x" * 1024)
        dead = next(vs for vs in servers
                    if vs.url() in _holders(master, "", vid))
        dead.stop()
        dn = next(n for n in master.topo.leaves()
                  if n.url() == dead.url())
        dn.last_seen = 0.0
        ok, doc = master.health_report()
        assert not ok and any("heartbeat" in p or "stale" in p
                              for p in doc["problems"]), doc["problems"]
        master._sweep_dead_nodes()
        master.repair.enabled = True
        master.repair.delay = 0.0
        master.repair.tick()
        _wait(lambda: len(_holders(master, "", vid)) == 2,
              msg="re-replication")
        ok, doc = master.health_report()
        assert ok, doc["problems"]
    finally:
        _teardown(master, servers)


# -- chaos gate (a): permanent EC shard-holder loss --------------------------

def _spread_ec(master, servers, client, collection):
    """Bench-round-2 recipe: encode one volume rs(10,4), spread shards
    5/5/4 across three servers, drop the original."""
    blob = os.urandom(1 << 18)
    fid = client.upload_data(blob, collection=collection)
    vid = int(fid.split(",")[0])
    src = client.lookup(vid)[0]["url"]
    rpc.call_json(f"http://{src}/admin/ec/generate", "POST",
                  {"volume": vid})
    spread = {servers[0].url(): [0, 1, 2, 3, 4],
              servers[1].url(): [5, 6, 7, 8, 9],
              servers[2].url(): [10, 11, 12, 13]}
    for url, shards in spread.items():
        if url != src:
            rpc.call_json(f"http://{url}/admin/ec/copy_shard", "POST",
                          {"volume": vid, "source": src,
                           "shards": shards, "copy_ecx": True})
    for url, shards in spread.items():
        rpc.call_json(f"http://{url}/admin/ec/mount", "POST",
                      {"volume": vid})
        drop = [s for s in range(14) if s not in shards]
        rpc.call_json(f"http://{url}/admin/ec/delete_shards", "POST",
                      {"volume": vid, "shards": drop})
    rpc.call_json(f"http://{src}/admin/delete_volume", "POST",
                  {"volume": vid})
    for vs in servers:
        vs._send_heartbeat(full=True)
        vs._ec_loc_cache.clear()
    return vid, fid, blob


def test_kill_ec_shard_holder_converges(tmp_path):
    """Losing the 4-shard holder leaves the stripe at its decode
    minimum (risk 0): the autopilot rebuilds the lost shards through
    the codec-aware batch planner and scatters them back — reads keep
    working throughout."""
    t0 = time.time()
    master, servers, client = _mk_cluster(tmp_path, n_vs=4)
    try:
        vid, fid, blob = _spread_ec(master, servers[:3], client, "ecrep")
        _kill(master, servers[2])  # shards 10-13 gone for good
        locs = master.topo.lookup_ec_shards(vid)
        present = {s for s, dns in locs.locations.items() if dns}
        assert present == set(range(10)), "decode-minimum setup"

        # Zero read unavailability at decode minimum.
        for vs in (servers[0], servers[1]):
            vs._ec_loc_cache.clear()
        assert bytes(rpc.call(
            f"http://{servers[0].url()}/{fid}")) == blob

        plan = master.repair.scan()
        ec_tasks = [t for t in plan if t.kind == "ec" and t.vid == vid]
        assert ec_tasks and ec_tasks[0].risk == 0
        assert set(ec_tasks[0].missing) == {10, 11, 12, 13}

        out = master.repair.run_now(kinds=["ec"])
        assert any(r["outcome"] == "ok" and r["kind"] == "ec"
                   for r in out["results"]), out

        locs = master.topo.lookup_ec_shards(vid)
        present = {s for s, dns in locs.locations.items() if dns}
        assert present == set(range(14)), "full stripe restored"
        assert not [t for t in master.repair.scan() if t.kind == "ec"]
        for vs in servers:
            if vs.url() != servers[2].url():
                vs._ec_loc_cache.clear()
        assert bytes(rpc.call(
            f"http://{servers[0].url()}/{fid}")) == blob
        assert any(e["attrs"].get("kind") == "ec"
                   for e in _events(t0, "repair.finish"))
    finally:
        _teardown(master, servers)


# -- chaos gate (b): resurrection mid-repair ---------------------------------

def test_resurrection_after_landed_repair_dedupes(tmp_path):
    """The repair lands on C, then the original holder B comes back:
    the volume is over-replicated for a moment, and the tick's dedupe
    pass trims the NEWEST placement (C) — never the original copies —
    leaving exactly the declared pair with equal checksum maps and no
    duplicate registrations."""
    master, servers, client = _mk_cluster(tmp_path)
    try:
        blob = os.urandom(1 << 15)
        a = client.assign(replication="001")
        fid, vid = a["fid"], int(a["fid"].split(",")[0])
        rpc.call(f"http://{a['url']}/{fid}", "POST", blob)
        holders0 = _holders(master, "", vid)
        dead = next(vs for vs in servers if vs.url() in holders0)
        dead_dir = dead.store.locations[0].directory
        dead_port = dead.server.port
        _kill(master, dead)

        master.repair.enabled = True
        master.repair.delay = 0.0
        master.repair.tick()
        _wait(lambda: len(_holders(master, "", vid)) == 2,
              msg="re-replication")
        _wait(lambda: not master.repair._inflight, msg="executor exit")
        landed = _holders(master, "", vid)

        # B resurrects on the same address with its old data.
        back = VolumeServer(master.url(), [dead_dir],
                            port=dead_port, max_volume_counts=[200],
                            pulse_seconds=60)
        back.start()
        servers.append(back)
        _wait(lambda: len(_holders(master, "", vid)) == 3,
              msg="resurrected holder re-registering")
        locs = _holders(master, "", vid)
        assert len(locs) == len(set(locs)), "duplicate registration"

        # The returning heartbeat scheduled the dedupe; the next tick
        # runs it and trims the newest placement.
        master.repair.tick()
        _wait(lambda: len(_holders(master, "", vid)) == 2,
              msg="dedupe trim")
        final = _holders(master, "", vid)
        assert back.url() in final, "the original copy must survive"
        trimmed_url = (set(landed) - set(final)).pop()
        trimmed_vs = next(vs for vs in servers
                          if vs.url() == trimmed_url)
        assert not trimmed_vs.store.has_volume(vid), "orphan replica"

        client.cache.forget(vid)
        assert client.download(fid) == blob
        ha, hb = final
        assert _checksum_map(ha, vid) == _checksum_map(hb, vid) != {}
    finally:
        _teardown(master, servers)


def test_returning_node_cancels_queued_repair():
    """Resurrection BEFORE the executor picks the task up: the healed
    deficit is dropped from the queue with a repair.cancel, and
    nothing executes."""
    t0 = time.time()
    m = MasterServer(port=0)
    vol = {"id": 4242, "collection": "rz", "size": 0, "file_count": 0,
           "replica_placement": 1}
    m._heartbeat({}, json.dumps(
        {"ip": "127.0.0.1", "port": 4101, "volumes": [vol]}).encode())
    m.repair._degraded_since[("replicate", 4242)] = 0.0
    m.repair.reconcile()
    assert [t.vid for t in m.repair._queue] == [4242]
    m._heartbeat({}, json.dumps(
        {"ip": "127.0.0.1", "port": 4102, "volumes": [vol]}).encode())
    m.repair.reconcile()
    assert not m.repair._queue
    cancels = [e for e in _events(t0, "repair.cancel")
               if e["attrs"].get("volume") == 4242]
    assert cancels and cancels[0]["attrs"]["reason"] == "healed"


# -- chaos gate (c): repair storm under an armed budget ----------------------

def _p99(samples):
    return sorted(samples)[max(0, int(len(samples) * 0.99) - 1)]


def test_repair_storm_budget_and_risk_order(tmp_path):
    """One node dies holding copies of ~20 mixed 001/002 volumes.
    With repair.fetch under an armed budget and one executor lane,
    the queue drains strictly in risk order (001 survivors at risk 0
    before 002 survivors at risk 1, pinned by the repair.start event
    sequence) while a victim reader's p99 stays within 3x baseline."""
    t0 = time.time()
    master, servers, client = _mk_cluster(tmp_path, n_vs=4)
    try:
        blob = os.urandom(1 << 16)
        fids = {}
        for i in range(14):
            f = client.upload_data(blob, collection=f"s1x{i}",
                                   replication="001")
            fids[int(f.split(",")[0])] = f
        for i in range(8):
            f = client.upload_data(blob, collection=f"s2x{i}",
                                   replication="002")
            fids[int(f.split(",")[0])] = f

        # Kill the node holding the most volumes (guarantees both risk
        # classes degrade).
        victim = max(servers,
                     key=lambda vs: len(next(
                         n for n in master.topo.leaves()
                         if n.url() == vs.url()).volumes))
        _kill(master, victim)
        plan = master.repair.scan()
        risks = {t.risk for t in plan}
        assert len(plan) >= 6 and 0 in risks and 1 in risks, \
            f"storm setup too small: {len(plan)} deficits, risks {risks}"

        # A healthy volume on surviving nodes is the victim reader.
        healthy_fid = None
        for vid, f in sorted(fids.items()):
            locs = client.lookup(vid)
            if locs and all(u["url"] != victim.url() for u in locs):
                healthy_fid = f
                break
        assert healthy_fid is not None
        client.cache.forget(int(healthy_fid.split(",")[0]))
        base = []
        for _ in range(30):
            s = time.perf_counter()
            assert client.download(healthy_fid) == blob
            base.append(time.perf_counter() - s)

        # Arm the repair.fetch budget (all in-process servers share the
        # ledger singleton) and drain with one executor lane.
        flows.LEDGER.reset()
        flows.LEDGER.set_budgets({"repair.fetch": 2_000_000.0},
                                 sustain=0.5)
        master.repair.concurrent = 1
        done = threading.Event()
        result = {}

        def drain():
            try:
                result["out"] = master.repair.run_now(
                    kinds=["replicate"], timeout=120.0)
            finally:
                done.set()

        threading.Thread(target=drain, daemon=True).start()
        during = []
        while not done.is_set():
            s = time.perf_counter()
            assert client.download(healthy_fid) == blob
            during.append(time.perf_counter() - s)
        assert during and "out" in result
        oks = [r for r in result["out"]["results"]
               if r["outcome"] == "ok"]
        assert len(oks) >= len(plan) - 1, result["out"]

        # User-read latency gate: p99 within 3x baseline (generous
        # floor absorbs scheduler noise on tiny absolute latencies).
        assert _p99(during) <= max(3 * _p99(base), 0.25), \
            f"p99 {_p99(during):.4f}s vs baseline {_p99(base):.4f}s"

        # Risk order pinned by the event sequence: with one lane, no
        # risk-1 repair may start before the last risk-0 start.
        starts = [e for e in _events(t0, "repair.start")
                  if e["attrs"]["kind"] == "replicate"]
        seq = [e["attrs"]["risk"] for e in starts]
        assert seq == sorted(seq), f"risk order violated: {seq}"

        # Everything is back at declared redundancy and readable.
        assert not [t for t in master.repair.scan()
                    if t.kind == "replicate"]
        for vid, f in list(fids.items())[:5]:
            client.cache.forget(vid)
            assert client.download(f) == blob
    finally:
        flows.LEDGER.reset()
        _teardown(master, servers)


# -- satellite: sweep snapshot-ordering regression ---------------------------

def test_sweep_snapshot_precedes_unregister():
    """heartbeat.lost must report the node's PRE-DEATH holdings even
    when the unregister mutates dn.volumes/dn.ec_shards under the
    sweep (a racing re-registration does exactly that): the snapshot
    is pinned BEFORE unregister_data_node."""
    t0 = time.time()
    m = MasterServer(port=0)
    vols = [{"id": 100 + i, "collection": "", "size": 0,
             "file_count": 0, "replica_placement": 0}
            for i in range(3)]
    shards = [{"id": 900, "shard_bits": 0b11, "collection": ""},
              {"id": 901, "shard_bits": 0b100, "collection": ""}]
    m._heartbeat({}, json.dumps(
        {"ip": "127.0.0.1", "port": 5101, "volumes": vols,
         "ec_shards": shards}).encode())
    dn = next(iter(m.topo.leaves()))
    assert len(dn.volumes) == 3 and len(dn.ec_shards) == 2
    real = m.topo.unregister_data_node

    def racing_unregister(node):
        # The interleaving under test: by the time unregister runs,
        # the node's live dicts have been drained by a racing sync.
        node.volumes.clear()
        node.ec_shards.clear()
        return real(node)

    m.topo.unregister_data_node = racing_unregister
    try:
        dn.last_seen = 0.0
        m._sweep_dead_nodes()
    finally:
        m.topo.unregister_data_node = real
    lost = [e for e in _events(t0, "heartbeat.lost")
            if e["node"] == "127.0.0.1:5101"]
    assert lost, "sweep never emitted heartbeat.lost"
    assert lost[-1]["attrs"]["volumes"] == 3
    assert lost[-1]["attrs"]["ec_shards"] == 2


# -- satellite: failure-domain audit ------------------------------------------

def test_placement_audit_warns_never_503():
    """Replicas all in one rack (against a 010 placement) and EC
    stripes concentrated on one node surface as healthz WARNINGS and
    in cluster.check — never as 503 problems."""
    m = MasterServer(port=0)
    vol = {"id": 7, "collection": "", "size": 0, "file_count": 0,
           "replica_placement": 10}  # 010: different rack demanded
    for port in (6101, 6102):
        m._heartbeat({}, json.dumps(
            {"ip": "127.0.0.1", "port": port, "rack": "rackA",
             "volumes": [vol]}).encode())
    # EC concentration: the FULL stripe on a single node — perfectly
    # healthy by redundancy-count rules, but one power cord from
    # gone (same_rack_count=0 for 000 -> limit 1 shard per node).
    m._heartbeat({}, json.dumps(
        {"ip": "127.0.0.1", "port": 6103, "rack": "rackB",
         "ec_shards": [{"id": 55, "shard_bits": (1 << 14) - 1,
                        "collection": ""}]}).encode())
    ok, doc = m.health_report()
    warnings = doc["placement"]["warnings"]
    assert any("volume 7" in w and "rack" in w for w in warnings), \
        warnings
    assert any("ec volume 55" in w and "14 shards" in w
               for w in warnings), warnings
    assert ok and doc["healthy"], \
        "placement violations must never 503"


def test_cluster_check_renders_placement_and_repair(tmp_path):
    from seaweedfs_tpu.shell import CommandEnv, run_command
    master, servers, client = _mk_cluster(tmp_path, n_vs=2)
    env = None
    try:
        # Both replicas of a 010 volume in the same rack (phantom
        # registrations — growth would rightly refuse this layout):
        # the audit must flag it in cluster.check.
        vol = {"id": 901, "collection": "mis", "size": 0,
               "file_count": 0, "replica_placement": 10}
        for port in (6201, 6202):
            master._heartbeat({}, json.dumps(
                {"ip": "127.0.0.1", "port": port, "rack": "rackZ",
                 "volumes": [vol]}).encode())
        env = CommandEnv(master.url())
        out = run_command(env, "cluster.check")
        assert "~ placement:" in out
        assert "repair autopilot: disarmed" in out
        out = run_command(env, "cluster.repair status")
        assert "durability autopilot: disarmed" in out
        out = run_command(env, "volume.fix.replication -n")
        assert "all volumes sufficiently replicated" in out
    finally:
        if env is not None:
            env.close()
        _teardown(master, servers)


# -- satellite: drained nodes never enqueue ----------------------------------

def test_rolling_restart_never_enqueues(tmp_path):
    """Planned maintenance across three subprocess volume servers with
    the daemon ARMED and zero hysteresis: every drain says goodbye, the
    drain fence suppresses the transient deficits, and the whole
    rolling restart produces ZERO repair.plan events and loses zero
    acked writes."""
    t0 = time.time()
    master = MasterServer(volume_size_limit_mb=16,
                          meta_dir=str(tmp_path / "meta"),
                          pulse_seconds=60, repair_enabled=True,
                          repair_delay=0.0)
    master.start()
    ports = [rpc.free_port() for _ in range(3)]
    dirs = []
    procs = {}

    def spawn(i):
        return subprocess.Popen(
            [sys.executable, "-m", "seaweedfs_tpu", "volume",
             f"-port={ports[i]}", f"-dir={dirs[i]}", "-max=50",
             f"-mserver=127.0.0.1:{master.server.port}"],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)

    try:
        for i in range(3):
            d = tmp_path / f"sub{i}"
            d.mkdir()
            dirs.append(str(d))
            procs[i] = spawn(i)
        _wait(lambda: len(list(master.topo.leaves())) == 3,
              timeout=20, msg="subprocess registration")

        client = WeedClient(master.url())
        blob = os.urandom(1 << 14)
        fids = [client.upload_data(blob, collection=f"roll{i}",
                                   replication="001")
                for i in range(6)]

        for i in range(3):
            url = f"127.0.0.1:{ports[i]}"
            procs[i].send_signal(signal.SIGTERM)  # drain -> goodbye
            procs[i].wait(timeout=30)
            _wait(lambda: url not in
                  {n.url() for n in master.topo.leaves()},
                  timeout=10, msg="goodbye unregistration")
            # The armed daemon ticks while the node is down: with
            # delay=0 any unfenced deficit would enqueue immediately.
            master.repair.tick()
            master.repair.tick()
            assert not master.repair._queue and \
                not master.repair._inflight
            procs[i] = spawn(i)
            _wait(lambda: url in
                  {n.url() for n in master.topo.leaves()},
                  timeout=20, msg="restart re-registration")
            # Wait for the full volume sync so the next round's scan
            # sees settled topology.
            _wait(lambda: not master.repair.scan(), timeout=20,
                  msg="post-restart convergence")
            master.repair.tick()

        assert _events(t0, "repair.plan") == [], \
            "planned maintenance enqueued repairs"
        assert len(_events(t0, "node.drained")) >= 3
        for f in fids:  # zero acked-write loss
            client.cache.forget(int(f.split(",")[0]))
            assert client.download(f) == blob
    finally:
        for p in procs.values():
            try:
                p.terminate()
                p.wait(timeout=10)
            except Exception:  # noqa: BLE001
                pass
        master.stop()


# -- crash safety: the receiver side -----------------------------------------

def test_receive_rejects_diverged_copy_and_reaps_tmps(
        tmp_path, monkeypatch):
    """A receive whose copied bytes don't match the source's fsck map
    refuses with 422 and leaves NO files behind; stale .part/.dl.tmp
    litter from a dead executor is reaped at startup."""
    master, servers, client = _mk_cluster(tmp_path, n_vs=2)
    try:
        blob = os.urandom(1 << 14)
        fid = client.upload_data(blob)
        vid = int(fid.split(",")[0])
        src = client.lookup(vid)[0]["url"]
        target = next(vs for vs in servers if vs.url() != src)
        tdir = target.store.locations[0].directory

        # Divergence: poison the source's checksum answer so the
        # copied bytes can never match — the receiver must 422 and
        # remove its partials without registering anything.
        real_call = rpc.call

        def poisoned(url, *a, **kw):
            out = real_call(url, *a, **kw)
            if "/admin/volume/checksums" in url:
                out["checksums"] = {"dead": "beefbeef"}
            return out

        monkeypatch.setattr(rpc, "call", poisoned)
        with pytest.raises(rpc.RpcError) as ei:
            rpc.call_json(
                f"http://{target.url()}/admin/volume/receive",
                payload={"volume": vid, "source": src})
        assert ei.value.status == 422
        monkeypatch.setattr(rpc, "call", real_call)
        assert not target.store.has_volume(vid)
        assert not [f for f in os.listdir(tdir) if ".part" in f], \
            "rejected receive left partial files"

        out = rpc.call_json(
            f"http://{target.url()}/admin/volume/receive",
            payload={"volume": vid, "source": src})
        assert out["needles"] >= 1
        assert target.store.has_volume(vid)
        assert not [f for f in os.listdir(tdir) if ".part" in f]

        # Already-present volume refuses 409.
        with pytest.raises(rpc.RpcError) as ei:
            rpc.call_json(
                f"http://{target.url()}/admin/volume/receive",
                payload={"volume": vid, "source": src})
        assert ei.value.status == 409

        # Startup reaping: litter the directory like a dead executor.
        litter = [os.path.join(tdir, "99.dat.part"),
                  os.path.join(tdir, "99.idx.part.dl.tmp")]
        for p in litter:
            with open(p, "wb") as f:
                f.write(b"junk")
        target.stop()
        d2 = tmp_path / "vs-reap"
        reborn = VolumeServer(master.url(), [tdir],
                              max_volume_counts=[200],
                              pulse_seconds=60)
        try:
            for p in litter:
                assert not os.path.exists(p), "tmp survived startup"
        finally:
            reborn.stop()
            assert d2 is not None
    finally:
        _teardown(master, servers)
