"""EC pipeline property tests — the port of the reference's ec_test.go.

Build a real volume, encode it with shrunken block sizes (large=10000,
small=100 — the reference test's constants), then assert:
- every needle read back through shard intervals equals the original;
- every needle reconstructs from shards even with 4 shard files deleted;
- rebuild regenerates missing shards byte-identically;
- decode (shards -> .dat) reproduces the original volume bytes;
- the deletion journal round-trips into idx tombstones.
"""

import os
import random

import numpy as np
import pytest

from seaweedfs_tpu.core import idx as idx_mod
from seaweedfs_tpu.core import types as t
from seaweedfs_tpu.core.needle import Needle
from seaweedfs_tpu.ec import (DATA_SHARDS, TOTAL_SHARDS, to_ext)
from seaweedfs_tpu.ec.decoder import (find_dat_file_size,
                                      write_dat_file,
                                      write_idx_file_from_ec_index)
from seaweedfs_tpu.ec.encoder import (rebuild_ec_files,
                                      write_ec_files,
                                      write_sorted_file_from_idx)
from seaweedfs_tpu.ec.locate import locate_data
from seaweedfs_tpu.ec.shard_bits import ShardBits
from seaweedfs_tpu.ec.volume import (EcVolume, NeedleNotFound,
                                     ShardsUnavailable)
from seaweedfs_tpu.ops.erasure import new_coder
from seaweedfs_tpu.storage.volume import Volume

LARGE, SMALL = 10000, 100  # the reference test's shrunken block sizes


@pytest.fixture(scope="module")
def ec_base(tmp_path_factory):
    """A volume with ~120 random needles, encoded to shards."""
    tmp = tmp_path_factory.mktemp("ecvol")
    v = Volume(str(tmp), "", 1)
    rng = random.Random(42)
    payloads = {}
    for i in range(1, 121):
        data = bytes(rng.randrange(256) for _ in range(rng.randrange(1, 800)))
        payloads[i] = data
        n = Needle(cookie=0x9999, id=i, data=data)
        n.append_at_ns = i  # deterministic
        v.write_needle(n)
    v.sync()
    base = v.file_name()
    v.close()
    write_sorted_file_from_idx(base)
    write_ec_files(base, coder=new_coder(backend="numpy"),
                   large_block_size=LARGE, small_block_size=SMALL,
                   chunk_size=SMALL)
    return base, payloads


def _open_ec(base, **kw):
    return EcVolume(base, coder=new_coder(backend="numpy"),
                    large_block_size=LARGE, small_block_size=SMALL, **kw)


def test_shard_files_created_and_sized(ec_base):
    base, _ = ec_base
    sizes = {os.path.getsize(base + to_ext(i)) for i in range(TOTAL_SHARDS)}
    assert len(sizes) == 1  # all equal
    size = sizes.pop()
    dat_size = os.path.getsize(base + ".dat")
    assert size * DATA_SHARDS >= dat_size
    assert size % SMALL == 0


def test_shard_rows_are_codewords(ec_base):
    """Every byte column across the 14 shard files is an RS codeword."""
    base, _ = ec_base
    shards = np.stack([
        np.frombuffer(open(base + to_ext(i), "rb").read(), dtype=np.uint8)
        for i in range(TOTAL_SHARDS)])
    assert new_coder(backend="numpy").verify(shards)


def test_every_needle_reads_back(ec_base):
    base, payloads = ec_base
    ev = _open_ec(base)
    try:
        for nid, data in payloads.items():
            n = ev.read_needle(nid)
            assert n.data == data, f"needle {nid}"
            assert n.cookie == 0x9999
    finally:
        ev.close()


def test_degraded_read_with_4_shards_lost(ec_base, tmp_path):
    """Copy shards, delete any 4, every needle must still read back
    (reconstruction from exactly 10 survivors) — readFromOtherEcFiles."""
    import shutil
    base, payloads = ec_base
    rng = random.Random(7)
    for trial in range(3):
        work = tmp_path / f"trial{trial}"
        work.mkdir()
        newbase = str(work / "1")
        for ext in [".ecx"] + [to_ext(i) for i in range(TOTAL_SHARDS)]:
            shutil.copyfile(base + ext, newbase + ext)
        # Trial 0 always loses shard 0: version detection must then
        # reconstruct the superblock from survivors instead of reading .ec00.
        lost = ([0] + rng.sample(range(1, TOTAL_SHARDS), 3)) if trial == 0 \
            else rng.sample(range(TOTAL_SHARDS), 4)
        for sid in lost:
            os.remove(newbase + to_ext(sid))
        ev = _open_ec(newbase)
        try:
            assert set(ev.shards) == set(range(TOTAL_SHARDS)) - set(lost)
            for nid, data in list(payloads.items())[::10]:
                assert ev.read_needle(nid).data == data, \
                    f"trial {trial} lost={lost} needle {nid}"
        finally:
            ev.close()


def test_rebuild_byte_identical(ec_base, tmp_path):
    import shutil
    base, _ = ec_base
    work = str(tmp_path / "1")
    originals = {}
    for i in range(TOTAL_SHARDS):
        shutil.copyfile(base + to_ext(i), work + to_ext(i))
        originals[i] = open(base + to_ext(i), "rb").read()
    lost = [0, 5, 11, 13]
    for sid in lost:
        os.remove(work + to_ext(sid))
    generated = rebuild_ec_files(work, coder=new_coder(backend="numpy"),
                                 chunk_size=1000)
    assert sorted(generated) == lost
    for sid in lost:
        assert open(work + to_ext(sid), "rb").read() == originals[sid], sid


def test_rebuild_too_few_shards(ec_base, tmp_path):
    import shutil
    base, _ = ec_base
    work = str(tmp_path / "1")
    for i in range(9):  # only 9 survivors
        shutil.copyfile(base + to_ext(i), work + to_ext(i))
    with pytest.raises(ValueError, match="too few"):
        rebuild_ec_files(work, coder=new_coder(backend="numpy"))


def test_decode_reproduces_dat(ec_base, tmp_path):
    import shutil
    base, _ = ec_base
    work = str(tmp_path / "1")
    for ext in [".ecx"] + [to_ext(i) for i in range(DATA_SHARDS)]:
        shutil.copyfile(base + ext, work + ext)
    write_idx_file_from_ec_index(work)
    dat_size = find_dat_file_size(work)
    orig = open(base + ".dat", "rb").read()
    assert dat_size == len(orig)  # last record ends the file
    write_dat_file(work, dat_size, large_block_size=LARGE,
                   small_block_size=SMALL)
    assert open(work + ".dat", "rb").read() == orig
    # idx must match the original volume's live entries
    with open(work + ".idx", "rb") as f:
        entries = {e.key: e for e in idx_mod.iter_index(f)}
    with open(base + ".idx", "rb") as f:
        orig_entries = {e.key: e for e in idx_mod.iter_index(f)}
    assert entries == orig_entries


def test_ec_delete_journal(ec_base, tmp_path):
    import shutil
    base, payloads = ec_base
    work = str(tmp_path / "1")
    for ext in [".ecx"] + [to_ext(i) for i in range(TOTAL_SHARDS)]:
        shutil.copyfile(base + ext, work + ext)
    ev = _open_ec(work)
    try:
        ev.delete_needle(50)
        with pytest.raises(NeedleNotFound):
            ev.read_needle(50)
        ev.read_needle(51)  # neighbors unaffected
    finally:
        ev.close()
    # .ecj recorded the id; idx regeneration adds a tombstone.
    assert os.path.getsize(work + ".ecj") == 8
    write_idx_file_from_ec_index(work)
    with open(work + ".idx", "rb") as f:
        entries = list(idx_mod.iter_index(f))
    assert entries[-1].key == 50
    assert entries[-1].size == t.TOMBSTONE_FILE_SIZE


def test_locate_data_boundaries():
    """Port of TestLocateData (ec_test.go:189-200)."""
    intervals = locate_data(LARGE, SMALL, DATA_SHARDS * LARGE + 1,
                            DATA_SHARDS * LARGE, 1)
    assert len(intervals) == 1
    iv = intervals[0]
    assert not iv.is_large_block
    assert iv.block_index == 0 and iv.inner_block_offset == 0 and iv.size == 1

    intervals = locate_data(LARGE, SMALL, DATA_SHARDS * LARGE + 1, 125, 200)
    assert len(intervals) == 1
    sid, off = intervals[0].to_shard_id_and_offset(LARGE, SMALL)
    assert sid == 0 and off == 125

    # Span across a large-block boundary.
    intervals = locate_data(LARGE, SMALL, DATA_SHARDS * LARGE + 1,
                            LARGE - 50, 100)
    assert len(intervals) == 2
    assert intervals[0].size == 50 and intervals[1].size == 50
    assert intervals[1].block_index == 1


def test_too_many_shards_missing_raises(ec_base, tmp_path):
    import shutil
    base, _ = ec_base
    work = str(tmp_path / "1")
    shutil.copyfile(base + ".ecx", work + ".ecx")
    for i in range(9):
        shutil.copyfile(base + to_ext(i), work + to_ext(i))
    ev = _open_ec(work)
    try:
        # Needles living wholly on present shards still read (O(1) local);
        # any needle with an interval on missing shard 9 must raise since
        # only 9 survivors remain (< data_shards).
        hit_missing = 0
        for nid in ec_base[1]:
            _, _, intervals = ev.locate_needle(nid)
            on_missing = any(
                iv.to_shard_id_and_offset(LARGE, SMALL)[0] == 9
                for iv in intervals)
            if on_missing:
                hit_missing += 1
                with pytest.raises(ShardsUnavailable):
                    ev.read_needle(nid)
            else:
                ev.read_needle(nid)
        assert hit_missing > 0
    finally:
        ev.close()


def test_shard_bits():
    b = ShardBits(0)
    b = b.add_shard_id(0).add_shard_id(5).add_shard_id(13)
    assert b.shard_ids() == [0, 5, 13]
    assert b.shard_id_count() == 3
    assert b.has_shard_id(5) and not b.has_shard_id(4)
    assert b.remove_shard_id(5).shard_ids() == [0, 13]
    assert b.minus_parity_shards().shard_ids() == [0, 5]
    other = ShardBits(0).add_shard_id(0).add_shard_id(1)
    assert b.plus(other).shard_ids() == [0, 1, 5, 13]
    assert b.minus(other).shard_ids() == [5, 13]


def test_cross_backend_shard_files_identical(ec_base, tmp_path):
    """jax-backend encode produces byte-identical shard files to numpy."""
    import shutil
    base, _ = ec_base
    work = str(tmp_path / "1")
    shutil.copyfile(base + ".dat", work + ".dat")
    shutil.copyfile(base + ".idx", work + ".idx")
    write_ec_files(work, coder=new_coder(backend="jax"),
                   large_block_size=LARGE, small_block_size=SMALL,
                   chunk_size=SMALL)
    for i in range(TOTAL_SHARDS):
        assert open(work + to_ext(i), "rb").read() == \
            open(base + to_ext(i), "rb").read(), f"shard {i}"


# -- golden byte-compatibility gate ------------------------------------------

REF_EC = "/root/reference/weed/storage/erasure_coding"
GOLDEN = os.path.join(os.path.dirname(__file__), "fixtures", "golden_ec")


@pytest.mark.skipif(not os.path.exists(os.path.join(REF_EC, "1.dat")),
                    reason="reference fixture not present")
def test_golden_manifest(tmp_path):
    """Regenerate .ec00-.ec13/.ecx from the reference's committed 1.dat
    at the reference test's block sizes and assert byte-for-byte
    equality with the pinned manifest — freezing the matrix
    construction, GF tables, stripe layout and .ecx sort (see
    fixtures/golden_ec/README.md for validating the same hashes
    against the Go reference)."""
    import hashlib
    import shutil
    shutil.copy(os.path.join(REF_EC, "1.dat"), tmp_path / "1.dat")
    shutil.copy(os.path.join(REF_EC, "1.idx"), tmp_path / "1.idx")
    write_ec_files(str(tmp_path / "1"), large_block_size=LARGE,
                   small_block_size=SMALL)
    write_sorted_file_from_idx(str(tmp_path / "1"))
    want = {}
    with open(os.path.join(GOLDEN, "MANIFEST.sha256")) as f:
        for line in f:
            digest, size, name = line.split()
            want[name] = (digest, int(size))
    assert len(want) == 15
    for name, (digest, size) in want.items():
        blob = (tmp_path / name).read_bytes()
        assert len(blob) == size, f"{name}: size {len(blob)} != {size}"
        got = hashlib.sha256(blob).hexdigest()
        assert got == digest, f"{name}: bytes drifted ({got[:16]}...)"


def test_parity_matrix_pinned_constants():
    """The RS(10,4) systematic matrix (klauspost buildMatrix: extended
    Vandermonde x inverse of its top square) — the full 4x10 parity
    coefficient block is frozen to the values this construction
    produced at pin time, so any drift in the GF tables or the matrix
    algebra fails loudly, independent of the file pipeline."""
    from seaweedfs_tpu.ops.gf256 import build_systematic_matrix
    m = build_systematic_matrix(10, 14)
    assert np.array_equal(m[:10], np.eye(10, dtype=np.uint8))
    assert m[10:].tolist() == [
        [129, 150, 175, 184, 210, 196, 254, 232, 3, 2],
        [150, 129, 184, 175, 196, 210, 232, 254, 2, 3],
        [191, 214, 98, 10, 6, 111, 223, 183, 5, 4],
        [214, 191, 10, 98, 111, 6, 183, 223, 4, 5],
    ]


def test_pipelined_encode_failure_propagates_promptly(tmp_path):
    """A coder failure mid-stream must raise out of write_ec_files —
    not deadlock the read-ahead thread on the full queue (review
    finding, reproduced as a hang before the fix)."""
    import threading
    import time as _t

    from seaweedfs_tpu.ops.coder_numpy import NumpyCoder

    blob = os.urandom(LARGE * DATA_SHARDS * 3)
    with open(tmp_path / "v.dat", "wb") as f:
        f.write(blob)

    class ExplodingCoder(NumpyCoder):
        calls = 0

        def encode(self, data):
            type(self).calls += 1
            if type(self).calls >= 2:
                raise RuntimeError("device fell over")
            return super().encode(data)

    result: list = []

    def run():
        try:
            write_ec_files(str(tmp_path / "v"),
                           coder=ExplodingCoder(10, 4),
                           large_block_size=LARGE, small_block_size=SMALL,
                           chunk_size=LARGE)
            result.append("no-error")
        except RuntimeError as e:
            result.append(str(e))

    th = threading.Thread(target=run, daemon=True)
    th.start()
    th.join(timeout=15)
    assert not th.is_alive(), "write_ec_files deadlocked on coder failure"
    assert result == ["device fell over"]
